"""``integrate.harmony`` — batch-effect correction in PCA space.

Reference parity: dpeerlab/sctools ships batch integration (source
unavailable — SURVEY.md §0; the algorithm is the published Harmony
method: alternate (a) diversity-penalised soft k-means clustering of
the cosine-normalised embedding with (b) a per-cluster ridge
mixture-of-experts regression that subtracts the batch component).

TPU design: harmonypy's reference loop updates soft assignments R in
sequential random row blocks (data-dependent, host-driven).  Here both
phases are fully synchronous linear algebra, jitted end to end:

* assignment: the reference's incremental block updates of R are kept
  (a fully synchronous R update turns out to equilibrate poorly — the
  diversity penalty must see its own block's effect), but as a
  ``lax.scan`` over ~20 *large* blocks: each block step is one MXU
  matmul ``Zn_blk @ Cᵀ`` plus K×B co-occurrence bookkeeping
  (O/E updated by two small matmuls), so the device stays busy while
  the penalty stays self-consistent;
* correction: the per-cluster design normal equations are accumulated
  with chunked einsums (no (n, K, P) tensor ever materialises), the
  K ridge systems solved batched with ``vmap(jnp.linalg.solve)`` on
  (B+1)×(B+1) matrices, intercept row zeroed, and the correction
  applied with one more chunked einsum.

Both phases run a fixed number of rounds under ``lax.scan`` (XLA needs
static trip counts; harmonypy's convergence test is an early-exit
optimisation, not a semantic difference).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..data.dataset import CellData
from ..registry import register

_ROW_CHUNK = 8192


def _l2norm(z, axis=1):
    return z / jnp.maximum(jnp.linalg.norm(z, axis=axis, keepdims=True),
                           1e-12)


def _batch_onehot(batch: np.ndarray):
    levels, codes = np.unique(np.asarray(batch), return_inverse=True)
    onehot = np.zeros((len(codes), len(levels)), np.float32)
    onehot[np.arange(len(codes)), codes] = 1.0
    return onehot, levels, codes.astype(np.int32)


@partial(jax.jit, static_argnames=("n_clusters", "n_rounds",
                                   "n_cluster_iter"))
def harmony_arrays(Z, phi, key, n_clusters: int, theta: float = 2.0,
                   sigma: float = 0.1, lamb: float = 1.0,
                   n_rounds: int = 10, n_cluster_iter: int = 10):
    """Run Harmony.  Z: (n, d) embedding; phi: (n, B) one-hot batch.
    Returns the corrected (n, d) embedding."""
    n, d = Z.shape
    B = phi.shape[1]
    Z = jnp.asarray(Z, jnp.float32)
    phi = jnp.asarray(phi, jnp.float32)
    G = jnp.concatenate([jnp.ones((n, 1), jnp.float32), phi], axis=1)
    P = B + 1
    # ridge penalises the batch coefficients, never the intercept
    ridge = lamb * jnp.diag(jnp.concatenate(
        [jnp.zeros((1,)), jnp.ones((B,))]))

    # block layout for the incremental R updates (~5% of cells per
    # block, the reference's granularity; static shapes via padding —
    # padded rows have phi == 0, so they never touch O/E)
    n_blocks = max(1, min(20, -(-n // 128)))
    bs = -(-n // n_blocks)
    pad_r = n_blocks * bs - n
    phi_p = (jnp.concatenate([phi, jnp.zeros((pad_r, B))]) if pad_r
             else phi)

    def cluster(Z_corr, R):
        Zn = _l2norm(Z_corr)
        Zn_p = (jnp.concatenate([Zn, jnp.zeros((pad_r, d))]) if pad_r
                else Zn)
        R_p = (jnp.concatenate([R, jnp.full((pad_r, R.shape[1]),
                                            1.0 / R.shape[1])])
               if pad_r else R)

        def it(R_p, _):
            C = _l2norm(R_p.T @ Zn_p)  # padded Zn rows are 0
            O0 = R_p.T @ phi_p  # (K, B)

            def block(O, inp):
                Rb, phib, Znb = inp
                O = O - Rb.T @ phib  # exclude this block
                m_k = jnp.sum(O, axis=1)  # included cluster mass
                n_b = jnp.sum(O, axis=0)  # included batch counts
                n_inc = jnp.maximum(jnp.sum(n_b), 1.0)
                E = m_k[:, None] * n_b[None, :] / n_inc
                pen = theta * jnp.log((E + 1.0) / (O + 1.0))
                dist = 2.0 * (1.0 - Znb @ C.T)
                logits = -dist / sigma + phib @ pen.T
                Rb = jax.nn.softmax(logits, axis=1)
                return O + Rb.T @ phib, Rb

            _, R_new = jax.lax.scan(
                block, O0,
                (R_p.reshape(n_blocks, bs, -1),
                 phi_p.reshape(n_blocks, bs, B),
                 Zn_p.reshape(n_blocks, bs, d)))
            return R_new.reshape(n_blocks * bs, -1), None

        R_p, _ = jax.lax.scan(it, R_p, None, length=n_cluster_iter)
        return R_p[:n]

    def correct(R):
        """Mixture-of-experts ridge correction from the ORIGINAL Z."""
        # normal equations per cluster, accumulated in row chunks
        nb = -(-n // _ROW_CHUNK)
        pad = nb * _ROW_CHUNK - n
        Rp = jnp.concatenate([R, jnp.zeros((pad, R.shape[1]))]) if pad else R
        Gp = jnp.concatenate([G, jnp.zeros((pad, P))]) if pad else G
        Zp = jnp.concatenate([Z, jnp.zeros((pad, d))]) if pad else Z

        def acc(carry, inp):
            A, rhs = carry
            r, g, z = inp
            rg = r[:, :, None] * g[:, None, :]  # (chunk, K, P)
            # cell-axis contractions feeding a linear SOLVE: the
            # numerics contract keeps solve inputs true f32 (TPU
            # DEFAULT would run bf16 MXU passes)
            hi = jax.lax.Precision.HIGHEST
            A = A + jnp.einsum("ckp,cq->kpq", rg, g, precision=hi)
            rhs = rhs + jnp.einsum("ckp,cd->kpd", rg, z, precision=hi)
            return (A, rhs), None

        K = R.shape[1]
        A0 = jnp.zeros((K, P, P))
        r0 = jnp.zeros((K, P, d))
        (A, rhs), _ = jax.lax.scan(
            acc, (A0, r0),
            (Rp.reshape(nb, _ROW_CHUNK, K), Gp.reshape(nb, _ROW_CHUNK, P),
             Zp.reshape(nb, _ROW_CHUNK, d)))
        W = jax.vmap(lambda a, r: jnp.linalg.solve(a + ridge, r))(A, rhs)
        W = W.at[:, 0, :].set(0.0)  # keep the intercept (cluster mean)

        def app(carry, inp):
            r, g = inp
            corr = jnp.einsum("ck,cp,kpd->cd", r, g, W,
                              precision=jax.lax.Precision.HIGHEST)
            return carry, corr

        _, corr = jax.lax.scan(
            app, None, (Rp.reshape(nb, _ROW_CHUNK, K),
                        Gp.reshape(nb, _ROW_CHUNK, P)))
        return Z - corr.reshape(-1, d)[:n]

    # init: soft assignment against k-means++-lite centroids
    from .cluster import kmeans_arrays

    Zn0 = _l2norm(Z)
    _, C0, _ = kmeans_arrays(Zn0, key, n_clusters=n_clusters, n_iter=10)
    R = jax.nn.softmax(-2.0 * (1.0 - Zn0 @ _l2norm(C0).T) / sigma, axis=1)

    def round_(carry, _):
        Z_corr, R = carry
        R = cluster(Z_corr, R)
        Z_new = correct(R)
        return (Z_new, R), None

    (Z_corr, _), _ = jax.lax.scan(round_, (Z, R), None, length=n_rounds)
    return Z_corr


def harmony_numpy(Z, phi, n_clusters: int, theta: float = 2.0,
                  sigma: float = 0.1, lamb: float = 1.0,
                  n_rounds: int = 10, n_cluster_iter: int = 10,
                  seed: int = 0):
    """Independent numpy oracle of the same synchronous scheme."""
    rng = np.random.default_rng(seed)
    Z = np.asarray(Z, np.float64)
    phi = np.asarray(phi, np.float64)
    n, d = Z.shape
    B = phi.shape[1]
    G = np.concatenate([np.ones((n, 1)), phi], axis=1)
    ridge = lamb * np.diag(np.concatenate([[0.0], np.ones(B)]))

    def norm(z):
        return z / np.maximum(np.linalg.norm(z, axis=1, keepdims=True),
                              1e-12)

    Zn0 = norm(Z)
    C = Zn0[rng.choice(n, n_clusters, replace=False)]
    logits = -2.0 * (1.0 - Zn0 @ norm(C).T) / sigma
    R = np.exp(logits - logits.max(1, keepdims=True))
    R /= R.sum(1, keepdims=True)
    n_blocks = max(1, min(20, -(-n // 128)))
    bounds = np.linspace(0, n, n_blocks + 1).astype(int)
    Z_corr = Z.copy()
    for _ in range(n_rounds):
        Zn = norm(Z_corr)
        for _ in range(n_cluster_iter):
            C = norm(R.T @ Zn)
            O = R.T @ phi
            for lo, hi in zip(bounds[:-1], bounds[1:]):
                O -= R[lo:hi].T @ phi[lo:hi]
                m_k = O.sum(1)
                n_b = O.sum(0)
                E = np.outer(m_k, n_b) / max(n_b.sum(), 1.0)
                pen = theta * np.log((E + 1.0) / (O + 1.0))
                dist = 2.0 * (1.0 - Zn[lo:hi] @ C.T)
                logits = -dist / sigma + phi[lo:hi] @ pen.T
                Rb = np.exp(logits - logits.max(1, keepdims=True))
                Rb /= Rb.sum(1, keepdims=True)
                R[lo:hi] = Rb
                O += Rb.T @ phi[lo:hi]
        corr = np.zeros_like(Z)
        for k in range(n_clusters):
            rg = G * R[:, k: k + 1]
            A = rg.T @ G + ridge
            W = np.linalg.solve(A, rg.T @ Z)
            W[0, :] = 0.0
            corr += rg @ W
        Z_corr = Z - corr
    return Z_corr.astype(np.float32)


def _resolve_harmony_inputs(data: CellData, batch_key: str, use_rep: str,
                            n_clusters):
    if batch_key not in data.obs:
        raise ValueError(f"batch_key={batch_key!r} not in obs "
                         f"({sorted(data.obs)})")
    if use_rep not in data.obsm:
        raise ValueError(f"use_rep={use_rep!r} not in obsm; run "
                         "pca.randomized first")
    n = data.n_cells
    Z = np.asarray(data.obsm[use_rep])[:n]
    onehot, levels, _ = _batch_onehot(np.asarray(data.obs[batch_key])[:n])
    if n_clusters is None:
        n_clusters = int(min(100, max(2, round(n / 30))))
    return Z, onehot, levels, n_clusters


@register("integrate.harmony", backend="tpu")
def harmony_tpu(data: CellData, batch_key: str = "batch",
                use_rep: str = "X_pca", theta: float = 2.0,
                sigma: float = 0.1, lamb: float = 1.0,
                n_clusters: int | None = None, n_rounds: int = 10,
                seed: int = 0) -> CellData:
    """Adds obsm["X_harmony"] — the batch-corrected embedding."""
    Z, onehot, levels, n_clusters = _resolve_harmony_inputs(
        data, batch_key, use_rep, n_clusters)
    out = harmony_arrays(
        jnp.asarray(Z), jnp.asarray(onehot), jax.random.PRNGKey(seed),
        n_clusters=n_clusters, theta=theta, sigma=sigma, lamb=lamb,
        n_rounds=n_rounds)
    return data.with_obsm(X_harmony=out).with_uns(
        harmony_batches=levels, harmony_n_clusters=n_clusters)


@register("integrate.harmony", backend="cpu")
def harmony_cpu(data: CellData, batch_key: str = "batch",
                use_rep: str = "X_pca", theta: float = 2.0,
                sigma: float = 0.1, lamb: float = 1.0,
                n_clusters: int | None = None, n_rounds: int = 10,
                seed: int = 0) -> CellData:
    Z, onehot, levels, n_clusters = _resolve_harmony_inputs(
        data, batch_key, use_rep, n_clusters)
    out = harmony_numpy(Z, onehot, n_clusters=n_clusters, theta=theta,
                        sigma=sigma, lamb=lamb, n_rounds=n_rounds,
                        seed=seed)
    return data.with_obsm(X_harmony=out).with_uns(
        harmony_batches=levels, harmony_n_clusters=n_clusters)


# ----------------------------------------------------------------------
# integrate.combat — parametric empirical-Bayes batch correction
# ----------------------------------------------------------------------


def _combat_hyperpriors(gamma_hat, delta_sq, xp):
    """Method-of-moments hyperpriors of the standard ComBat model
    (Johnson et al. 2007): normal prior on the per-batch gene shifts,
    inverse-gamma on the scales."""
    # ddof=1 throughout: scanpy's _combat computes these moments with
    # pandas sample variance — ddof=0 would shrink the priors by
    # (g-1)/g, a ~2% systematic divergence on a post-HVG gene count
    gamma_bar = xp.mean(gamma_hat, axis=1)           # (B,)
    t2 = xp.var(gamma_hat, axis=1, ddof=1)           # (B,)
    m = xp.mean(delta_sq, axis=1)
    s2 = xp.var(delta_sq, axis=1, ddof=1)
    a_prior = (2.0 * s2 + m * m) / xp.maximum(s2, 1e-12)
    b_prior = (m * s2 + m ** 3) / xp.maximum(s2, 1e-12)
    return gamma_bar, t2, a_prior, b_prior


@partial(jax.jit, static_argnames=("n_iter",))
def combat_arrays(X, codes, n_batches_arr, n_iter: int = 100):
    """ComBat on a dense (n, g) matrix.  codes: (n,) int32 batch ids;
    n_batches_arr: (B,) per-batch cell counts (float32).  Returns the
    adjusted (n, g) float32 matrix.

    TPU mapping: the whole algorithm reduces to per-batch segment sums
    into (B, g) matrices plus elementwise EB iterations on them — one
    ``lax.scan`` with a static trip count replaces the reference's
    convergence loop (early exit is an optimisation, not semantics;
    100 iterations is far past the default 1e-4 convergence on real
    data, and the oracle test asserts agreement with the converged
    numpy loop)."""
    X = jnp.asarray(X, jnp.float32)
    n, g = X.shape
    B = n_batches_arr.shape[0]
    nb = n_batches_arr.astype(jnp.float32)           # (B,)

    def bsum(M):  # per-batch column sums -> (B, g)
        return jax.ops.segment_sum(M, codes, num_segments=B)

    # per-batch means; pooled variance of the batch-mean-removed data
    batch_mean = bsum(X) / nb[:, None]               # (B, g)
    grand_mean = jnp.sum(batch_mean * (nb / n)[:, None], axis=0)  # (g,)
    resid = X - jnp.take(batch_mean, codes, axis=0)
    var_pooled = jnp.sum(resid * resid, axis=0) / n  # (g,)
    std = jnp.sqrt(jnp.maximum(var_pooled, 1e-12))
    Z = (X - grand_mean[None, :]) / std[None, :]

    gamma_hat = bsum(Z) / nb[:, None]                # (B, g)
    zc = Z - jnp.take(gamma_hat, codes, axis=0)
    delta_sq = bsum(zc * zc) / jnp.maximum(nb - 1.0, 1.0)[:, None]
    gamma_bar, t2, a_prior, b_prior = _combat_hyperpriors(
        gamma_hat, delta_sq, jnp)

    # EB shrinkage fixed point (per batch, per gene; all elementwise).
    # sum2[b, g] = Σ_i∈b (Z - γ*)² re-expands in closed form from the
    # per-batch sufficient statistics, so the scan never touches Z:
    #   Σ (Z - γ*)² = Σ Z² - 2 γ* Σ Z + n_b γ*²
    sZ = bsum(Z)
    sZZ = bsum(Z * Z)

    def step(carry, _):
        g_star, d_star = carry
        g_new = ((nb[:, None] * t2[:, None] * gamma_hat
                  + d_star * gamma_bar[:, None])
                 / (nb[:, None] * t2[:, None] + d_star))
        sum2 = sZZ - 2.0 * g_new * sZ + nb[:, None] * g_new * g_new
        d_new = ((b_prior[:, None] + 0.5 * sum2)
                 / (nb[:, None] / 2.0 + a_prior[:, None] - 1.0))
        d_new = jnp.maximum(d_new, 1e-12)
        return (g_new, d_new), None

    (gamma_star, delta_star), _ = jax.lax.scan(
        step, (gamma_hat, delta_sq), None, length=n_iter)

    adj = (Z - jnp.take(gamma_star, codes, axis=0)) / jnp.sqrt(
        jnp.take(delta_star, codes, axis=0))
    return adj * std[None, :] + grand_mean[None, :]


@register("integrate.combat", backend="tpu")
def combat_tpu(data: CellData, batch_key: str = "batch",
               n_iter: int = 100) -> CellData:
    """ComBat batch correction (scanpy ``pp.combat`` semantics, batch
    design only).  Operates on dense X — run after
    ``hvg.select(subset=True)`` / on log-normalised data.  Replaces X
    with the adjusted matrix."""
    from ..data.sparse import SparseCells

    if batch_key not in data.obs:
        raise KeyError(f"obs has no {batch_key!r}")
    X = data.X
    Xd = X.to_dense() if isinstance(X, SparseCells) else jnp.asarray(X)
    batch = np.asarray(data.obs[batch_key])[: data.n_cells]
    onehot, levels, codes_np = _batch_onehot(batch)
    if len(levels) < 2:
        raise ValueError("combat needs >= 2 batches")
    codes = jnp.asarray(codes_np)
    nb = jnp.asarray(onehot.sum(0))
    out = combat_arrays(Xd[: data.n_cells], codes, nb, n_iter=n_iter)
    return data.with_X(out).with_uns(combat_batches=levels)


@register("integrate.combat", backend="cpu")
def combat_cpu(data: CellData, batch_key: str = "batch",
               n_iter: int = 100) -> CellData:
    """float64 numpy oracle with a true convergence loop."""
    import scipy.sparse as sp

    if batch_key not in data.obs:
        raise KeyError(f"obs has no {batch_key!r}")
    X = data.X
    Xd = np.asarray(X.todense() if sp.issparse(X) else X, np.float64)
    batch = np.asarray(data.obs[batch_key])[: data.n_cells]
    onehot, levels, codes = _batch_onehot(batch)
    if len(levels) < 2:
        raise ValueError("combat needs >= 2 batches")
    n, g = Xd.shape
    nb = onehot.sum(0)                                # (B,)
    B = len(levels)
    batch_mean = (onehot.T @ Xd) / nb[:, None]
    grand_mean = (batch_mean * (nb / n)[:, None]).sum(0)
    resid = Xd - batch_mean[codes]
    var_pooled = (resid * resid).sum(0) / n
    std = np.sqrt(np.maximum(var_pooled, 1e-12))
    Z = (Xd - grand_mean) / std
    gamma_hat = (onehot.T @ Z) / nb[:, None]
    zc = Z - gamma_hat[codes]
    delta_sq = (onehot.T @ (zc * zc)) / np.maximum(nb - 1.0, 1.0)[:, None]
    gamma_bar, t2, a_prior, b_prior = _combat_hyperpriors(
        gamma_hat, delta_sq, np)
    sZ = onehot.T @ Z
    sZZ = onehot.T @ (Z * Z)
    g_star, d_star = gamma_hat.copy(), delta_sq.copy()
    for _ in range(max(n_iter, 1000)):
        g_new = ((nb[:, None] * t2[:, None] * gamma_hat
                  + d_star * gamma_bar[:, None])
                 / (nb[:, None] * t2[:, None] + d_star))
        sum2 = sZZ - 2.0 * g_new * sZ + nb[:, None] * g_new * g_new
        d_new = np.maximum((b_prior[:, None] + 0.5 * sum2)
                           / (nb[:, None] / 2.0 + a_prior[:, None] - 1.0),
                           1e-12)
        if (np.max(np.abs(g_new - g_star)) < 1e-8
                and np.max(np.abs(d_new - d_star)) < 1e-8):
            g_star, d_star = g_new, d_new
            break
        g_star, d_star = g_new, d_new
    adj = (Z - g_star[codes]) / np.sqrt(d_star[codes])
    out = adj * std + grand_mean
    return data.with_X(out.astype(np.float32)).with_uns(
        combat_batches=levels)
