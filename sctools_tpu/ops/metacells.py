"""``metacells.seacells`` — SEACells-style metacell identification.

Reference parity: dpeerlab/sctools descends from the Pe'er lab stack,
whose metacell tool is SEACells (source unavailable — SURVEY.md §0;
the published algorithm: kernel archetypal analysis — find archetypes
B (convex combinations of cells) and assignments A (convex
combinations of archetypes) minimising ‖K − K·B·A‖²_F, both updated by
Frank–Wolfe steps on the probability simplex).

TPU design: the n×n kernel K never materialises — it lives as the
symmetrised kNN edge list, and every kernel product is a k-sparse
``knn_matvec``/``knn_rmatvec``.  Per Frank–Wolfe round the gradients
reduce to

    ∇_A = 2·(CᵀC)·A − 2·CᵀK          with C = K·B   (n × m)
    ∇_B = 2·KᵀK·B·(AAᵀ) − 2·KᵀK·Aᵀ

where CᵀC and AAᵀ are tiny (m × m).  The simplex linear-minimisation
step is one argmin per column + a convex update — pure vectorised
VPU work, iterated under ``lax.fori_loop``.  Initialisation is
max–min (farthest-point) sampling in the embedding, the same seeding
SEACells uses.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..data.dataset import CellData
from ..data.sparse import SparseCells
from ..registry import register


def maxmin_sample(points: np.ndarray, m: int, seed: int = 0) -> np.ndarray:
    """Farthest-point sampling of ``m`` indices (host-side)."""
    rng = np.random.default_rng(seed)
    pts = np.asarray(points, np.float64)
    first = int(rng.integers(len(pts)))
    chosen = [first]
    dmin = np.linalg.norm(pts - pts[first], axis=1)
    for _ in range(m - 1):
        nxt = int(np.argmax(dmin))
        chosen.append(nxt)
        dmin = np.minimum(dmin, np.linalg.norm(pts - pts[nxt], axis=1))
    return np.asarray(chosen)


@partial(jax.jit, static_argnames=("n_iter", "graph_impl"))
def seacells_arrays(knn_idx, kernel_w, init_idx, n_iter: int = 50,
                    graph_impl: str | None = None):
    """Kernel archetypal analysis on the kNN kernel.

    knn_idx/kernel_w: (n, k) symmetric kernel edge list; init_idx:
    (m,) seed cells.  Returns (A (m, n) column-stochastic assignments,
    B (n, m) column-stochastic archetypes).  ``graph_impl`` (static)
    pins the tiled-family impl so config flips re-key this jit's
    cache (pallas_graph.matvec's contract for jitted callers).
    """
    n, k = knn_idx.shape
    m = init_idx.shape[0]

    from .graph import knn_matvec, knn_rmatvec

    def Kmat(V):  # K @ V — kernel is symmetric, edge list may not be;
        return knn_matvec(knn_idx, kernel_w, V, impl=graph_impl)

    def KTmat(V):
        return knn_rmatvec(knn_idx, kernel_w, V, n=n,
                           impl=graph_impl)

    B0 = jnp.zeros((n, m)).at[init_idx, jnp.arange(m)].set(1.0)
    # A0: assign each cell to its most similar archetype (one kernel hop)
    C0 = Kmat(B0)  # (n, m)
    A0 = jax.nn.one_hot(jnp.argmax(C0, axis=1), m).T  # (m, n)

    def body(t, carry):
        A, B = carry
        gamma = 2.0 / (t + 2.0)
        # --- update A (columns = cells, rows simplex over archetypes)
        C = Kmat(B)  # (n, m)
        CtC = C.T @ C  # (m, m)
        CtK = KTmat(C).T  # (m, n)  == Cᵀ K (K symmetric)
        gA = 2.0 * (CtC @ A) - 2.0 * CtK  # (m, n)
        eA = jax.nn.one_hot(jnp.argmin(gA, axis=0), m).T  # (m, n)
        A = (1.0 - gamma) * A + gamma * eA
        # --- update B (columns = archetypes, rows simplex over cells)
        KtKB = KTmat(Kmat(B))  # (n, m)
        KtKAt = KTmat(Kmat(A.T))  # (n, m)
        gB = 2.0 * (KtKB @ (A @ A.T)) - 2.0 * KtKAt  # (n, m)
        eB = jax.nn.one_hot(jnp.argmin(gB, axis=0), n).T  # (n, m)
        B = (1.0 - gamma) * B + gamma * eB
        return A, B

    A, B = jax.lax.fori_loop(0, n_iter, body, (A0, B0))
    return A, B


def _sym_kernel(data: CellData, backend: str):
    """Symmetrised connectivities as the kernel edge list."""
    from .graph import (_require_knn, _symmetrized_weights,
                        connectivities_cpu, connectivities_tpu)

    if "connectivities" not in data.obsp:
        data = (connectivities_tpu if backend == "tpu"
                else connectivities_cpu)(data)
    n = data.n_cells
    idx, _ = _require_knn(data)
    w = jnp.asarray(np.asarray(data.obsp["connectivities"],
                               np.float32)[:n])
    w = _symmetrized_weights(idx, w)  # averaged — near-symmetric
    return data, idx, w


def _attach_metacells(data: CellData, A, B, init_idx) -> CellData:
    labels = jnp.argmax(jnp.asarray(A), axis=0).astype(jnp.int32)
    return data.with_obs(metacell=labels).with_uns(
        seacells_A=A, seacells_B=B,
        seacells_seed_cells=np.asarray(init_idx))


@register("metacells.seacells", backend="tpu")
def seacells_tpu(data: CellData, n_metacells: int | None = None,
                 n_iter: int = 50, use_rep: str = "X_pca",
                 seed: int = 0) -> CellData:
    """Adds obs["metacell"] (hard assignment), uns["seacells_A"/"_B"].
    Requires neighbors.knn; default n_metacells ≈ n/75 (the SEACells
    rule of thumb)."""
    n = data.n_cells
    if n_metacells is None:
        n_metacells = max(2, int(round(n / 75)))
    data, idx, w = _sym_kernel(data, "tpu")
    emb = np.asarray(data.obsm[use_rep])[:n]
    init_idx = maxmin_sample(emb, n_metacells, seed=seed)
    from .pallas_graph import resolved_impl

    A, B = seacells_arrays(idx, w, jnp.asarray(init_idx),
                           n_iter=n_iter, graph_impl=resolved_impl())
    return _attach_metacells(data, A, B, init_idx)


@register("metacells.seacells", backend="cpu")
def seacells_cpu(data: CellData, n_metacells: int | None = None,
                 n_iter: int = 50, use_rep: str = "X_pca",
                 seed: int = 0) -> CellData:
    """Numpy oracle of the same Frank–Wolfe scheme (dense kernel —
    small inputs only)."""
    import scipy.sparse as sp

    n = data.n_cells
    if n_metacells is None:
        n_metacells = max(2, int(round(n / 75)))
    data, idx, w = _sym_kernel(data, "cpu")
    idx = np.asarray(idx)
    w = np.asarray(w, np.float64)
    k = idx.shape[1]
    rows = np.repeat(np.arange(n), k)
    cols = idx.reshape(-1)
    keep = cols >= 0
    K = sp.csr_matrix((w.reshape(-1)[keep], (rows[keep], cols[keep])),
                      shape=(n, n)).toarray()
    emb = np.asarray(data.obsm[use_rep])[:n]
    init_idx = maxmin_sample(emb, n_metacells, seed=seed)
    m = n_metacells
    B = np.zeros((n, m))
    B[init_idx, np.arange(m)] = 1.0
    C0 = K @ B
    A = np.eye(m)[np.argmax(C0, axis=1)].T
    for t in range(n_iter):
        gamma = 2.0 / (t + 2.0)
        C = K @ B
        gA = 2.0 * (C.T @ C) @ A - 2.0 * (C.T @ K)
        eA = np.eye(m)[np.argmin(gA, axis=0)].T
        A = (1 - gamma) * A + gamma * eA
        KtKB = K.T @ (K @ B)
        gB = 2.0 * KtKB @ (A @ A.T) - 2.0 * (K.T @ (K @ A.T))
        eB = np.eye(n)[np.argmin(gB, axis=0)].T
        B = (1 - gamma) * B + gamma * eB
    return _attach_metacells(data, A.astype(np.float32),
                             B.astype(np.float32), init_idx)


@register("metacells.aggregate", backend="tpu")
def aggregate_tpu(data: CellData, key: str = "metacell") -> CellData:
    """Sum raw counts per metacell → a new small CellData
    (n_metacells × n_genes, dense) carried in uns["metacell_counts"],
    plus obs sizes.  Works on SparseCells or dense X."""
    if key not in data.obs:
        raise ValueError(f"run metacells.seacells first ({key!r} missing)")
    n = data.n_cells
    labels = jnp.asarray(data.obs[key])[:n].astype(jnp.int32)
    m = int(jnp.max(labels)) + 1
    X = data.X
    if isinstance(X, SparseCells):
        from ..data.sparse import spmm_t

        onehot = jax.nn.one_hot(labels, m, dtype=jnp.float32)
        pad = X.rows_padded - n
        if pad:
            onehot = jnp.concatenate([onehot, jnp.zeros((pad, m))])
        counts = spmm_t(X, onehot).T  # (m, G)
    else:
        Xd = jnp.asarray(X)[:n]
        counts = jax.ops.segment_sum(Xd, labels, num_segments=m)
    sizes = jax.ops.segment_sum(jnp.ones((n,), jnp.float32), labels,
                                num_segments=m)
    return data.with_uns(metacell_counts=counts, metacell_sizes=sizes)


@register("metacells.aggregate", backend="cpu")
def aggregate_cpu(data: CellData, key: str = "metacell") -> CellData:
    import scipy.sparse as sp

    if key not in data.obs:
        raise ValueError(f"run metacells.seacells first ({key!r} missing)")
    n = data.n_cells
    labels = np.asarray(data.obs[key])[:n].astype(np.int64)
    m = int(labels.max()) + 1
    X = data.X
    onehot = sp.csr_matrix(
        (np.ones(n), (labels, np.arange(n))), shape=(m, n))
    if sp.issparse(X):
        counts = np.asarray((onehot @ X).todense())
    else:
        counts = onehot @ np.asarray(X)
    sizes = np.bincount(labels, minlength=m).astype(np.float32)
    return data.with_uns(metacell_counts=counts.astype(np.float32),
                         metacell_sizes=sizes)
