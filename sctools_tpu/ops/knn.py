"""k-nearest-neighbour graph construction: ``neighbors.knn``.

Reference parity: BASELINE.json configs[3] — "cosine kNN(k=15) on 1.3M
cells, single chip"; configs[4] extends to multi-chip
(``sctools_tpu.parallel``).

TPU design (single chip): brute-force blocked kNN.  The score tile
``Q_blk @ C_blkᵀ`` is an MXU matmul (optionally bfloat16 inputs with
float32 accumulation); the running top-k merge per candidate block is
``lax.top_k`` over ``k + col_block`` columns.  ``lax.map`` over query
blocks bounds live memory to one (row_block × col_block) tile, so the
full N×N distance matrix never exists in HBM.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..config import config, round_up
from ..data.dataset import CellData
from ..registry import register

from .. import buckets as _buckets


def _prep(points, metric, dtype):
    points = jnp.asarray(points)
    if metric == "cosine":
        norms = jnp.linalg.norm(points, axis=1, keepdims=True)
        points = points / jnp.maximum(norms, 1e-12)
    return points.astype(dtype)


def knn_arrays(
    query: jax.Array,
    cand: jax.Array,
    *,
    k: int = 15,
    metric: str = "cosine",
    n_query: int | None = None,
    n_cand: int | None = None,
    query_block: int | None = None,
    cand_block: int | None = None,
    exclude_self: bool = False,
    refine: int = 0,
    n_valid_cand=None,
):
    """Exact kNN of ``query`` rows against ``cand`` rows.

    ``n_valid_cand`` (optional, TRACED): only the first so-many
    candidate rows are real; the rest of ``cand``/``n_cand`` is shape
    padding.  Because it is dynamic, many calls with different valid
    counts but one bucketed ``n_cand`` share a single compiled program
    — what ``neighbors.bbknn`` relies on with dozens of distinct batch
    sizes (static ``n_cand`` alone would retrace per size).  XLA-path
    only; the pallas path ignores it (callers bucket only on xla).

    Returns (indices (n_query_padded, k) int32, distances (…, k)).
    Distances: cosine -> 1 - cos_sim, euclidean -> L2 distance; sorted
    ascending.  Padding queries return index -1 rows at the end.
    ``exclude_self`` drops matches where global ids coincide (use only
    when query is cand).

    Config (block sizes, matmul dtype) is resolved *here*, outside
    jit, and passed down as static arguments — so ``configure(...)``
    changes take effect instead of being baked into a cached trace.

    ``refine``: search ``refine`` candidates with the fast (bfloat16
    MXU) score path, then exactly re-rank them in float32
    (Precision.HIGHEST) and keep ``k``.  This recovers float64-oracle
    recall at bfloat16 search speed — the classic coarse-search +
    refine split.  0 disables refinement.

    Note TPU matmul precision: with float32 inputs XLA still runs the
    MXU in bfloat16 passes unless Precision.HIGHEST is requested, so
    ``matmul_dtype="float32"`` alone does NOT buy exact scores —
    we map it to HIGHEST explicitly.
    """
    if metric == "correlation":
        # Pearson-correlation distance == cosine distance of the
        # row-centered vectors (scanpy's metric="correlation"); fold
        # it into the cosine path so every backend/kernel shares one
        # implementation
        query = query - jnp.mean(query, axis=1, keepdims=True)
        cand = cand - jnp.mean(cand, axis=1, keepdims=True)
        metric = "cosine"
    if metric not in ("cosine", "euclidean"):
        raise ValueError(f"unknown metric {metric!r}")
    if config.knn_coarse not in ("topk", "approx"):
        raise ValueError(
            f"unknown knn_coarse {config.knn_coarse!r} "
            "(expected 'topk' or 'approx')")
    n_query = n_query or query.shape[0]
    n_cand = n_cand or cand.shape[0]
    k_search = max(k, refine) if refine else k
    impl = config.resolved_knn_impl()
    if impl in ("pallas", "pallas_binned") and n_valid_cand is not None:
        # the pallas kernels take exact candidate shapes and have no
        # valid-count mask; honouring the mask matters more than the
        # kernel win (only the bucketed bbknn path passes it today,
        # and that path already routes itself to xla)
        impl = "xla"
    if impl in ("pallas", "pallas_binned"):
        from .pallas_knn import pallas_knn_arrays

        idx, dist = pallas_knn_arrays(
            query, cand, k=k_search, metric=metric,
            n_query=n_query, n_cand=n_cand, query_block=query_block,
            cand_block=cand_block, exclude_self=exclude_self,
            merge="binned" if impl == "pallas_binned" else "select",
            n_bins=config.knn_bins,
        )
    else:
        nv = jnp.int32(n_cand if n_valid_cand is None else n_valid_cand)
        idx, dist = _knn_jit(
            query, cand, nv, k=k_search, metric=metric,
            n_query=n_query, n_cand=n_cand,
            qb=query_block or config.row_block,
            cb=cand_block or config.col_block,
            mm_dtype=str(jnp.dtype(config.matmul_dtype)),
            exclude_self=exclude_self,
            coarse=config.knn_coarse,
        )
    if refine:
        # Any refine > 0 runs the exact pass — even refine <= k still
        # re-scores the k candidates in f32 (caller asked for exact
        # distances, not just a wider search).
        mode = config.resolved_refine_mode(n_cand)
        if mode == "sorted":
            idx, dist = _refine_sorted_jit(query, cand, idx, k=k,
                                           metric=metric)
        else:
            idx, dist = _refine_jit(query, cand, idx, k=k,
                                    metric=metric,
                                    qb=query_block or config.row_block)
        qvalid = jnp.arange(idx.shape[0]) < n_query
        idx = jnp.where(qvalid[:, None], idx, -1)
    return idx, dist


def resolve_knn_chunk(chunk: int, n: int) -> int:
    """The actual query-chunk size ``iter_knn_chunks`` will use: a
    ``row_block`` multiple, so each compiled call returns exactly
    ``chunk`` rows (a non-multiple would leave -1 padding rows inside
    the concatenated result — silent corruption)."""
    from ..config import config, round_up

    return round_up(min(max(chunk, 1), n), config.row_block)


def iter_knn_chunks(scores, *, k: int, chunk: int, metric: str = "cosine",
                    refine: int = 0, n: int | None = None):
    """Query-chunked self-kNN: yields ``(offset, nq, idx, dist,
    wall_s)`` per chunk, with ``idx``/``dist`` TRIMMED to the ``nq``
    valid rows and each chunk hard-synced before the next dispatch.

    One compiled (chunk × n) program is reused for every chunk — the
    small-program discipline crash-prone backends need.  Both the
    bench's atlas path and ``stream_pipeline(knn_chunk=)`` drive this
    generator; the consumer decides about budgets, progress lines, and
    early stops (just stop iterating)."""
    import time as _time

    from ..utils.sync import hard_sync

    n = n or int(scores.shape[0])
    chunk = resolve_knn_chunk(chunk, n)
    from ..config import round_up

    n_pad = round_up(n, chunk)
    scores_pad = jnp.zeros((n_pad, scores.shape[1]), scores.dtype)
    scores_pad = scores_pad.at[:n].set(scores[:n])
    for off in range(0, n, chunk):
        q = jax.lax.dynamic_slice_in_dim(scores_pad, off, chunk, axis=0)
        nq = min(chunk, n - off)
        t0 = _time.time()
        idx_c, dist_c = knn_arrays(q, scores, k=k, metric=metric,
                                   n_query=chunk, n_cand=n,
                                   refine=refine)
        hard_sync(idx_c)
        yield off, nq, idx_c[:nq], dist_c[:nq], _time.time() - t0


@partial(
    jax.jit,
    static_argnames=("k", "metric", "qb", "cb", "n_query", "n_cand",
                     "mm_dtype", "exclude_self", "coarse"),
)
def _knn_jit(query, cand, n_valid, *, k, metric, n_query, n_cand, qb, cb,
             mm_dtype, exclude_self, coarse="topk"):
    mm_dtype = jnp.dtype(mm_dtype)
    # float32 inputs need HIGHEST or the MXU silently drops to bf16.
    precision = (jax.lax.Precision.HIGHEST if mm_dtype == jnp.float32
                 else jax.lax.Precision.DEFAULT)
    d = query.shape[1]
    nq_pad = round_up(n_query, qb)
    nc_pad = round_up(n_cand, cb)
    q = jnp.zeros((nq_pad, d), query.dtype).at[: query.shape[0]].set(query)
    c = jnp.zeros((nc_pad, d), cand.dtype).at[: cand.shape[0]].set(cand)
    q = _prep(q, metric, mm_dtype)
    c = _prep(c, metric, mm_dtype)

    c_blocks = c.reshape(nc_pad // cb, cb, d)
    if metric == "euclidean":
        cn2_blocks = jnp.sum(
            c_blocks.astype(jnp.float32) ** 2, axis=2
        )  # (ncb, cb)
    else:
        cn2_blocks = jnp.zeros((nc_pad // cb, cb), jnp.float32)
    offsets = jnp.arange(nc_pad // cb, dtype=jnp.int32) * cb
    col_iota = jnp.arange(cb, dtype=jnp.int32)

    def per_qblock(args):
        qblk, q_ids = args  # (qb, d), (qb,)
        if metric == "euclidean":
            qn2 = jnp.sum(qblk.astype(jnp.float32) ** 2, axis=1)

        def body(carry, inp):
            bvals, bidx = carry
            cblk, cn2, off = inp
            s = jnp.dot(
                qblk, cblk.T, preferred_element_type=jnp.float32,
                precision=precision,
            )  # (qb, cb) similarity-like
            if metric == "euclidean":
                s = -(qn2[:, None] - 2.0 * s + cn2[None, :])
            gcol = off + col_iota  # (cb,)
            invalid = gcol >= n_valid  # traced: bucketed shapes share
            s = jnp.where(invalid[None, :], -jnp.inf, s)
            if exclude_self:
                s = jnp.where(gcol[None, :] == q_ids[:, None], -jnp.inf, s)
            # approx_max_k reduces over the fresh tile's cb columns and
            # requires k <= cb; a narrower block silently gets the
            # exact branch (identical results, no crash)
            if coarse == "approx" and k <= cb:
                # TPU-native binned PartialReduce on the FRESH tile
                # only; the carry merge below stays exact, so a global
                # top-k item risks its one bin collision exactly once
                # (in its own block), never per subsequent block.
                fv, fsel = jax.lax.approx_max_k(s, k, recall_target=0.99)
                fi = off + fsel.astype(jnp.int32)
                allv = jnp.concatenate([bvals, fv], axis=1)  # (qb, 2k)
                alli = jnp.concatenate([bidx, fi], axis=1)
            else:
                allv = jnp.concatenate([bvals, s], axis=1)
                alli = jnp.concatenate(
                    [bidx, jnp.broadcast_to(gcol[None, :], s.shape)],
                    axis=1)
            v, sel = jax.lax.top_k(allv, k)
            i = jnp.take_along_axis(alli, sel, axis=1)
            return (v, i), None

        init = (
            jnp.full((qb, k), -jnp.inf, jnp.float32),
            jnp.full((qb, k), -1, jnp.int32),
        )
        (v, i), _ = jax.lax.scan(body, init, (c_blocks, cn2_blocks, offsets))
        return v, i

    q_ids_all = jnp.arange(nq_pad, dtype=jnp.int32)
    vals, idxs = jax.lax.map(
        per_qblock,
        (q.reshape(nq_pad // qb, qb, d), q_ids_all.reshape(nq_pad // qb, qb)),
    )
    vals = vals.reshape(nq_pad, k)
    idxs = idxs.reshape(nq_pad, k)
    if metric == "cosine":
        dists = 1.0 - vals
    else:
        dists = jnp.sqrt(jnp.maximum(-vals, 0.0))
    qvalid = jnp.arange(nq_pad) < n_query
    idxs = jnp.where(qvalid[:, None], idxs, -1)
    return idxs, dists


@partial(jax.jit, static_argnames=("k", "metric", "qb"))
def _refine_jit(query, cand, cand_idx, *, k, metric, qb):
    """Exact float32 re-rank of per-query candidate lists.

    query: (nq_pad, d); cand: (nc, d); cand_idx: (nq_pad, k') from the
    coarse search (may contain -1 padding).  Returns (idx, dist) of
    the top ``k`` by exact score.  Chunked over query blocks.
    """
    # the coarse search may have padded queries to a different block
    # multiple than qb (the pallas impl uses its own tile size) — pad
    # to the lcm-ish multiple here so the reshape below is exact
    nq_pad = round_up(cand_idx.shape[0], qb)
    if nq_pad > cand_idx.shape[0]:
        cand_idx = jnp.concatenate(
            [cand_idx,
             jnp.full((nq_pad - cand_idx.shape[0], cand_idx.shape[1]), -1,
                      cand_idx.dtype)])
    d = query.shape[1]
    kp = cand_idx.shape[1]
    q = jnp.zeros((nq_pad, d), jnp.float32).at[: query.shape[0]].set(
        query.astype(jnp.float32))
    c = cand.astype(jnp.float32)
    if metric == "cosine":
        q = q / jnp.maximum(jnp.linalg.norm(q, axis=1, keepdims=True), 1e-12)
        c = c / jnp.maximum(jnp.linalg.norm(c, axis=1, keepdims=True), 1e-12)
    # NOTE (r5 session-3 measurement): at 1.3M candidates the refine
    # pass costs ~13.9 s/chunk vs ~1.4 s at 131k.  The gather table
    # (260 MB f32) exceeds on-chip residency, so the random row
    # gather runs at HBM random-access rates; an explicit
    # optimization_barrier pinning the normalised table was tried and
    # measured to change NOTHING (19.45 s before and after), so the
    # cost is the gather itself, not re-fused normalisation.  Kept
    # barrier-free; a locality-aware gather is the known follow-up.

    def per_block(args):
        qblk, iblk = args  # (qb, d), (qb, kp); iblk may contain -1
        # jnp.take clips out-of-range under jit, and -1 rows are masked
        # to -inf below, so no explicit sanitising is needed.
        g = jnp.take(c, iblk, axis=0)  # (qb, kp, d)
        s = jnp.einsum("qd,qkd->qk", qblk, g,
                       precision=jax.lax.Precision.HIGHEST)
        if metric == "euclidean":
            qn2 = jnp.sum(qblk * qblk, axis=1)
            cn2 = jnp.sum(g * g, axis=2)
            s = -(qn2[:, None] - 2.0 * s + cn2)
        s = jnp.where(iblk < 0, -jnp.inf, s)
        v, sel = jax.lax.top_k(s, k)
        return v, jnp.take_along_axis(iblk, sel, axis=1)

    nqb = nq_pad // qb
    vals, idxs = jax.lax.map(
        per_block,
        (q.reshape(nqb, qb, d), cand_idx.reshape(nqb, qb, kp)),
    )
    vals = vals.reshape(nq_pad, k)
    idxs = idxs.reshape(nq_pad, k)  # -1 padding propagates via iblk
    dists = (1.0 - vals) if metric == "cosine" else jnp.sqrt(
        jnp.maximum(-vals, 0.0))
    return idxs, dists


@partial(jax.jit, static_argnames=("k", "metric"))
def _refine_sorted_jit(query, cand, cand_idx, *, k, metric):
    """Exact float32 re-rank with a LOCALITY-AWARE gather.

    Semantically identical to ``_refine_jit`` — the same candidate
    lists re-scored in f32 and the same top_k rule — with scores
    equal up to f32 reduction-order (ulp) differences: the blocked
    path reduces over d inside a batched einsum, this one in an
    elementwise dot, and the two may round differently (so a top-k
    selection can flip only between ulp-level ties).  Only the HBM
    access pattern is the point.  Motivation (r5 session-3 measurement on a
    v5e): at 1.3M candidates the blocked refine costs ~13.9 s/chunk —
    the (nc, d) f32 table is 260 MB, far beyond on-chip residency, so
    per-query-block random row gathers run at HBM random-access
    rates.  Here the flattened candidate ids are argsorted once
    (~4.2M int32), candidate rows are gathered in ASCENDING id order
    (streaming-friendly, duplicate-id reads coalesce), each element's
    score is computed against its owner query immediately (the query
    table is small enough to gather from freely), and only the f32
    SCORES (17 MB, not the 840 MB of gathered vectors) are scattered
    back through the inverse permutation.
    """
    nq, kp = cand_idx.shape
    d = query.shape[1]
    q = query.astype(jnp.float32)
    c = cand.astype(jnp.float32)
    if metric == "cosine":
        q = q / jnp.maximum(jnp.linalg.norm(q, axis=1, keepdims=True),
                            1e-12)
        c = c / jnp.maximum(jnp.linalg.norm(c, axis=1, keepdims=True),
                            1e-12)
    flat = cand_idx.reshape(-1)
    # -1 padding sorts to the FRONT as-is; remap to nc so the padding
    # gathers the (clipped) last row and sorts to the end instead —
    # the score is masked by the original -1 below either way
    flat_sane = jnp.where(flat < 0, c.shape[0], flat).astype(jnp.int32)
    order = jnp.argsort(flat_sane)
    owner = (order // kp).astype(jnp.int32)
    sorted_ids = jnp.take(flat_sane, order)

    def score_slice(args):
        ids, own = args  # (m,), (m,)
        g = jnp.take(c, ids, axis=0)          # ascending-id gather
        qg = jnp.take(q, own, axis=0)         # small-table gather
        s = jnp.einsum("md,md->m", qg, g,
                       precision=jax.lax.Precision.HIGHEST)
        if metric == "euclidean":
            qn2 = jnp.sum(qg * qg, axis=1)
            cn2 = jnp.sum(g * g, axis=1)
            s = -(qn2 - 2.0 * s + cn2)
        return s

    n_flat = nq * kp
    # bound the gathered-vector temp: slices of <=2^19 rows (~100 MB
    # of (m, d) f32 at d=50) pipelined by lax.map
    m = min(n_flat, 1 << 19)
    n_slices = -(-n_flat // m)
    pad = n_slices * m - n_flat
    ids_p = jnp.concatenate(
        [sorted_ids, jnp.zeros((pad,), jnp.int32)]) if pad else sorted_ids
    own_p = jnp.concatenate(
        [owner, jnp.zeros((pad,), jnp.int32)]) if pad else owner
    s_sorted = jax.lax.map(
        score_slice,
        (ids_p.reshape(n_slices, m), own_p.reshape(n_slices, m)),
    ).reshape(-1)[:n_flat]
    # inverse-permute ONLY the scores
    s = jnp.zeros((n_flat,), jnp.float32).at[order].set(s_sorted)
    s = s.reshape(nq, kp)
    s = jnp.where(cand_idx < 0, -jnp.inf, s)
    v, sel = jax.lax.top_k(s, k)
    idxs = jnp.take_along_axis(cand_idx, sel, axis=1)
    dists = (1.0 - v) if metric == "cosine" else jnp.sqrt(
        jnp.maximum(-v, 0.0))
    return idxs, dists


@register("neighbors.knn", backend="tpu", mask_aware=True)
def knn_tpu(data: CellData, k: int = 15, metric: str = "cosine",
            use_rep: str = "X_pca", exclude_self: bool = False,
            query_block: int | None = None,
            cand_block: int | None = None, refine: int = 0) -> CellData:
    """Adds obsp["knn_indices"], obsp["knn_distances"]; uns["knn_k"],
    uns["knn_metric"].

    Mask-aware: on bucket-padded data (buckets.py) the TRACED valid
    count feeds ``n_valid_cand`` — padded candidate columns score -inf
    before every top-k merge, so valid rows get bitwise the neighbours
    of the unpadded run (extra all--inf candidate blocks can never
    displace a real hit), while padded query rows are post-masked to
    index -1 / distance 0.  Passing ``n_valid_cand`` routes to the XLA
    impl, which is the point: one bucket shape = one compiled program.
    """
    rep = _get_rep(data, use_rep)
    masks = _buckets.masks_of(data)
    idx, dist = knn_arrays(
        rep, rep, k=k, metric=metric, n_query=data.n_cells,
        n_cand=data.n_cells, exclude_self=exclude_self,
        query_block=query_block, cand_block=cand_block, refine=refine,
        n_valid_cand=None if masks is None else masks.n_cells,
    )
    if masks is not None:
        # knn_arrays pads queries to a row_block multiple, which may
        # exceed the bucket row count — rebuild the validity test over
        # the returned rows from the traced count instead of the mask
        valid = jnp.arange(idx.shape[0]) < jnp.asarray(masks.n_cells)
        idx = jnp.where(valid[:, None], idx, -1)
        dist = jnp.where(valid[:, None], dist, 0.0)
    from .graph import invalidate_graph_layout_stats

    data = invalidate_graph_layout_stats(data)
    return data.with_obsp(knn_indices=idx, knn_distances=dist).with_uns(
        knn_k=k, knn_metric=metric
    )


def _get_rep(data: CellData, use_rep: str):
    if use_rep == "X":
        X = data.X
        from ..data.sparse import SparseCells

        if isinstance(X, SparseCells):
            raise ValueError(
                "neighbors.knn on raw sparse X is not supported; run "
                "pca.randomized first (use_rep='X_pca')"
            )
        return jnp.asarray(X) if not isinstance(X, np.ndarray) else X
    if use_rep not in data.obsm:
        raise ValueError(
            f"use_rep={use_rep!r} not in obsm ({sorted(data.obsm)}); "
            "run pca.randomized first"
        )
    return data.obsm[use_rep]


@register("neighbors.knn", backend="cpu")
def knn_cpu(data: CellData, k: int = 15, metric: str = "cosine",
            use_rep: str = "X_pca", exclude_self: bool = False,
            **_ignored) -> CellData:
    """Brute-force numpy oracle (chunked; exact)."""
    rep = np.asarray(_get_rep_cpu(data, use_rep), dtype=np.float64)
    idx, dist = knn_numpy(rep, rep, k=k, metric=metric,
                          exclude_self=exclude_self)
    from .graph import invalidate_graph_layout_stats

    data = invalidate_graph_layout_stats(data)
    return data.with_obsp(knn_indices=idx, knn_distances=dist).with_uns(
        knn_k=k, knn_metric=metric
    )


def _get_rep_cpu(data: CellData, use_rep: str):
    import scipy.sparse as sp

    if use_rep == "X":
        X = data.X
        return np.asarray(X.todense()) if sp.issparse(X) else np.asarray(X)
    return np.asarray(data.obsm[use_rep])


def knn_numpy(query, cand, k=15, metric="cosine", exclude_self=False,
              chunk=4096):
    """Exact brute-force kNN in numpy — the recall oracle."""
    query = np.asarray(query, np.float64)
    cand = np.asarray(cand, np.float64)
    if metric == "correlation":
        query = query - query.mean(axis=1, keepdims=True)
        cand = cand - cand.mean(axis=1, keepdims=True)
        metric = "cosine"
    if metric == "cosine":
        qn = query / np.maximum(np.linalg.norm(query, axis=1, keepdims=True), 1e-12)
        cn = cand / np.maximum(np.linalg.norm(cand, axis=1, keepdims=True), 1e-12)
    n = len(query)
    out_i = np.empty((n, k), np.int32)
    out_d = np.empty((n, k), np.float32)
    cn2 = (cand**2).sum(axis=1)
    for s in range(0, n, chunk):
        e = min(n, s + chunk)
        if metric == "cosine":
            score = qn[s:e] @ cn.T
        else:
            qn2 = (query[s:e] ** 2).sum(axis=1)
            score = -(qn2[:, None] - 2 * (query[s:e] @ cand.T) + cn2[None, :])
        if exclude_self:
            rows = np.arange(s, e)
            valid = rows < len(cand)
            score[np.arange(e - s)[valid], rows[valid]] = -np.inf
        part = np.argpartition(-score, k - 1, axis=1)[:, :k]
        ps = np.take_along_axis(score, part, axis=1)
        order = np.argsort(-ps, axis=1, kind="stable")
        idx = np.take_along_axis(part, order, axis=1)
        sc = np.take_along_axis(ps, order, axis=1)
        out_i[s:e] = idx
        out_d[s:e] = (1.0 - sc) if metric == "cosine" else np.sqrt(
            np.maximum(-sc, 0.0)
        )
    return out_i, out_d


def recall_at_k(pred_idx, true_idx, k: int | None = None) -> float:
    """Mean fraction of true k neighbours recovered (order-insensitive).

    Fully vectorised (broadcast membership test) so tens of thousands
    of query rows are cheap — the bench samples >=4096 queries.
    Assumes each row of ``true_idx`` has no duplicate ids (true for any
    exact-kNN oracle); ``-1`` padding in ``pred_idx`` never matches a
    valid oracle id.
    """
    pred_idx = np.asarray(pred_idx)
    true_idx = np.asarray(true_idx)
    n = min(len(pred_idx), len(true_idx))
    pred_idx = pred_idx[:n]
    true_idx = true_idx[:n]
    if k is not None:
        pred_idx = pred_idx[:, :k]
        true_idx = true_idx[:, :k]
    # (n, k_true, k_pred) membership; a true id is "hit" if it appears
    # anywhere in the predicted row.
    hits = (true_idx[:, :, None] == pred_idx[:, None, :]).any(axis=2)
    return float(hits.sum()) / (n * true_idx.shape[1])


# ----------------------------------------------------------------------
# neighbors.bbknn — batch-balanced kNN (BBKNN)
# ----------------------------------------------------------------------


def _bbknn_combine(parts):
    """Stack per-batch (idx, dist) results and sort each row by
    distance (missing slots ranked last)."""
    gi = np.concatenate([p[0] for p in parts], axis=1)   # (n, B*k)
    gd = np.concatenate([p[1] for p in parts], axis=1)
    gd = np.where(gi < 0, np.inf, gd)
    order = np.argsort(gd, axis=1, kind="stable")
    gi = np.take_along_axis(gi, order, axis=1)
    gd = np.take_along_axis(gd, order, axis=1)
    gd = np.where(gi < 0, np.inf, gd).astype(np.float32)
    return gi.astype(np.int32), np.where(np.isfinite(gd), gd, 0.0)


def _bbknn_driver(batch, n, k_within, search):
    """One BBKNN pass, parameterised by ``search(sel, k) -> (idx,
    dist)`` (per-batch local-index results) — the backends share every
    line of the mapping/self-drop/sort logic, so they cannot diverge.
    Every batch contributes EXACTLY ``k_within`` columns; batches with
    fewer cells pad with -1 (consistent shapes and uns["knn_k"] on
    both backends regardless of batch sizes)."""
    levels = np.unique(batch)
    if len(levels) < 2:
        raise ValueError("bbknn needs >= 2 batches")
    parts = []
    for lv in levels:
        sel = np.flatnonzero(batch == lv)
        # a query's own row is a candidate only within its own batch,
        # and global/local id mismatch makes exclude_self= unusable —
        # search one extra then drop selfs
        k_eff = min(k_within + 1, len(sel))
        idx, dist = search(sel, k_eff)
        idx = np.asarray(idx)[:n]
        dist = np.asarray(dist)[:n]
        gidx = np.where(idx >= 0, sel[np.clip(idx, 0, len(sel) - 1)], -1)
        self_hit = gidx == np.arange(n)[:, None]
        gidx = np.where(self_hit, -1, gidx)
        dist = np.where(self_hit, np.inf, dist)
        order = np.argsort(np.where(gidx < 0, np.inf, dist), axis=1,
                           kind="stable")[:, :k_within]
        gi = np.take_along_axis(gidx, order, axis=1)
        gd = np.take_along_axis(dist, order, axis=1)
        if gi.shape[1] < k_within:  # batch smaller than k_within
            pad = k_within - gi.shape[1]
            gi = np.pad(gi, ((0, 0), (0, pad)), constant_values=-1)
            gd = np.pad(gd, ((0, 0), (0, pad)), constant_values=np.inf)
        parts.append((gi, gd))
    return _bbknn_combine(parts), levels


_BBKNN_DOC = """Batch-balanced kNN (the BBKNN method): every cell takes
its ``k_within`` nearest neighbours FROM EACH BATCH, so no batch can
monopolise a neighbourhood — the lightweight graph-level integration.
Adds obsp["knn_indices"/"knn_distances"] with k = n_batches x
k_within (rows sorted by distance; self matches dropped; batches
smaller than k_within pad with -1) — feed graph.connectivities next,
as with neighbors.knn."""


@register("neighbors.bbknn", backend="tpu")
def bbknn_tpu(data: CellData, batch_key: str = "batch",
              k_within: int = 3, metric: str = "cosine",
              use_rep: str = "X_pca", refine: int = 0) -> CellData:
    if batch_key not in data.obs:
        raise KeyError(f"obs has no {batch_key!r}")
    rep = jnp.asarray(_get_rep(data, use_rep))
    n = data.n_cells
    rep = rep[:n]
    batch = np.asarray(data.obs[batch_key])[:n]

    def search(sel, k):
        cand = jnp.take(rep, jnp.asarray(sel), axis=0)
        # ALWAYS bucket the candidate count so dozens of batch sizes
        # share a handful of compiled programs; passing n_valid_cand
        # routes knn_arrays to the XLA path, which is deliberate —
        # exact-shape pallas here would retrace one kernel per batch
        # size (static n_cand), the program churn the tunneled worker
        # tolerates worst (n_valid_cand masks the pad)
        bucket = round_up(max(len(sel), 1), 1024)
        if bucket > len(sel):
            cand = jnp.concatenate(
                [cand, jnp.zeros((bucket - len(sel), cand.shape[1]),
                                 cand.dtype)])
        return knn_arrays(rep, cand, k=k, metric=metric,
                          n_query=n, n_cand=bucket,
                          n_valid_cand=len(sel), refine=refine)

    (gi, gd), levels = _bbknn_driver(batch, n, k_within, search)
    from .graph import invalidate_graph_layout_stats

    data = invalidate_graph_layout_stats(data)
    return data.with_obsp(knn_indices=gi, knn_distances=gd).with_uns(
        knn_k=gi.shape[1], knn_metric=metric,
        bbknn_batches=levels, bbknn_k_within=k_within)


bbknn_tpu.__doc__ = _BBKNN_DOC + """

TPU path: one blocked MXU search per batch over that batch's
candidate block."""


@register("neighbors.bbknn", backend="cpu")
def bbknn_cpu(data: CellData, batch_key: str = "batch",
              k_within: int = 3, metric: str = "cosine",
              use_rep: str = "X_pca", **_ignored) -> CellData:
    if batch_key not in data.obs:
        raise KeyError(f"obs has no {batch_key!r}")
    rep = np.asarray(_get_rep_cpu(data, use_rep), np.float64)[: data.n_cells]
    n = len(rep)
    batch = np.asarray(data.obs[batch_key])[:n]

    def search(sel, k):
        return knn_numpy(rep, rep[sel], k=k, metric=metric)

    (gi, gd), levels = _bbknn_driver(batch, n, k_within, search)
    from .graph import invalidate_graph_layout_stats

    data = invalidate_graph_layout_stats(data)
    return data.with_obsp(knn_indices=gi, knn_distances=gd).with_uns(
        knn_k=gi.shape[1], knn_metric=metric,
        bbknn_batches=levels, bbknn_k_within=k_within)


bbknn_cpu.__doc__ = _BBKNN_DOC + """

numpy oracle: identical per-batch brute-force searches."""
