"""``embed.phate`` — potential-distance embedding (PHATE).

Capability parity: PHATE (Moon et al. 2019), the
trajectory-preserving embedding in routine use alongside the Pe'er
trajectory stack.  The reference source was unavailable
(/root/reference empty — SURVEY.md §0); the published pipeline is the
contract:

1. adaptive-bandwidth kernel on the kNN graph (bandwidth = distance
   to the ``ka``-th neighbour), symmetrised, row-normalised to a
   diffusion operator P;
2. diffuse t steps; the **potential** U = −log(Pᵗ + eps) replaces
   raw diffusion probabilities (log-scale spreads the trajectory's
   low-probability tails instead of crushing them);
3. classical MDS on the pairwise potential distances.

TPU design: exact PHATE is O(n²) in memory by definition (the
potential matrix), so the device path leans into it — Pᵗ is a
``lax.scan`` of t dense (n, n) MXU matmuls, the potential Gram and
its centering are matmuls, and the MDS eigenvectors come from the
same subspace-iteration machinery PCA uses.  Run it on up to a few
tens of thousands of cells (post-metacell, post-subsample), the
regime the published method targets; the cpu backend mirrors the math
in numpy float64.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..data.dataset import CellData
from ..registry import register

_EPS = 1e-7


def _kernel(idx, dist, ka, xp, alpha: float = 2.0):
    """Adaptive-bandwidth decay kernel exp(−(d/σ)^α) on the edge
    list, symmetrised (average) and row-normalised.  α=2 (gaussian)
    default — on kNN-restricted graphs the sharp published α≈40
    disconnects noisy neighbourhoods (measured: trajectory ordering
    0.86 vs 0.94 spearman at the same t); pass ``alpha=40`` for the
    paper's decay.  Returns dense (n, n) P."""
    n, k = idx.shape
    ka = min(ka, k - 1)
    sigma = xp.maximum(dist[:, ka], 1e-12)  # per-cell bandwidth
    w = xp.exp(-((dist / sigma[:, None]) ** alpha))
    W = xp.zeros((n, n))
    rows = np.repeat(np.arange(n), k)
    if xp is np:
        cols = idx.reshape(-1)
        keep = cols >= 0
        W[rows[keep], cols[keep]] = w.reshape(-1)[keep]
    else:
        safe = jnp.where(idx < 0, 0, idx)
        # .add, not .set: padded -1 slots alias column 0, and duplicate
        # .set indices keep an arbitrary winner — a real edge to cell 0
        # could be clobbered by the padding's 0.0.  Adding a masked 0
        # is harmless.
        W = jnp.zeros((n, n)).at[
            jnp.asarray(rows), safe.reshape(-1)].add(
            jnp.where(idx < 0, 0.0, w).reshape(-1))
    W = 0.5 * (W + W.T)
    return W / xp.maximum(W.sum(axis=1, keepdims=True), 1e-12)


def _von_neumann_t(P, xp, max_t=100):
    """PHATE's automatic t: the KNEE of the von Neumann entropy curve
    of Pᵗ's spectrum — the t furthest from the chord joining the
    curve's endpoints (the published knee-point rule; a drop-threshold
    variant stopped ~5x too early on trajectory data)."""
    evals = xp.linalg.eigvalsh(0.5 * (P + P.T))
    lam = xp.clip(xp.abs(evals), 1e-12, 1.0)
    ts = np.arange(1, max_t + 1)
    ent = []
    for t in ts:
        p = lam ** t
        p = p / p.sum()
        # 0·log 0 = 0 — small eigenvalues underflow to exact zero at
        # large t and must not poison the entropy with log(0)
        plogp = np.where(np.asarray(p) > 0,
                         np.asarray(p) * np.log(np.maximum(p, 1e-300)),
                         0.0)
        ent.append(float(-plogp.sum()))
    ent = np.asarray(ent)
    # distance of each point to the line (t0, e0) -> (t1, e1), on
    # normalised coordinates so the two axes weigh equally
    x = (ts - ts[0]) / max(ts[-1] - ts[0], 1)
    y = (ent - ent[-1]) / max(ent[0] - ent[-1], 1e-12)
    dist_to_chord = np.abs(y - (1.0 - x))
    return max(int(ts[int(np.argmax(dist_to_chord))]), 2)


def _phate_host(idx, dist, n_components, t, ka, alpha=2.0):
    idx = np.asarray(idx)
    dist = np.asarray(dist, np.float64)
    P = _kernel(idx, dist, ka, np, alpha)
    if t is None:
        t = _von_neumann_t(P, np)
    Pt = np.linalg.matrix_power(P, t)
    U = -np.log(Pt + _EPS)
    # classical MDS on rows of U: double-centered Gram of the
    # euclidean potential distances == centered U Uᵀ
    Uc = U - U.mean(axis=0, keepdims=True)
    G = Uc @ Uc.T
    evals, evecs = np.linalg.eigh(G)
    order = np.argsort(-evals)[:n_components]
    emb = evecs[:, order] * np.sqrt(np.maximum(evals[order], 0.0))
    return emb.astype(np.float32), t


@partial(jax.jit, static_argnames=("t", "n_iter", "n_components",
                                   "ka", "alpha"))
def _phate_device(idx, dist, key, *, t: int, n_components: int,
                  ka: int, alpha: float = 2.0, n_iter: int = 4):
    from .pca import cholesky_qr

    n = idx.shape[0]
    P = _kernel(idx, dist.astype(jnp.float32), ka, jnp, alpha)

    def step(M, _):
        return P @ M, None

    Pt, _ = jax.lax.scan(step, jnp.eye(n, dtype=jnp.float32), None,
                         length=t)
    U = -jnp.log(Pt + _EPS)
    Uc = U - jnp.mean(U, axis=0, keepdims=True)
    # top eigenvectors of Uc Ucᵀ via subspace iteration (the PCA
    # machinery): G v = Uc (Ucᵀ v) keeps everything matmul-shaped
    L = n_components + 8
    Q = cholesky_qr(Uc @ (Uc.T @ jax.random.normal(key, (n, L))))
    for _ in range(n_iter):
        Q = cholesky_qr(Uc @ (Uc.T @ Q))
    B = Q.T @ Uc
    # one SVD: B's left singular vectors ARE the eigenvectors of B Bᵀ
    U_b, S, _ = jnp.linalg.svd(B, full_matrices=False)
    V = Q @ U_b
    emb = V[:, :n_components] * S[:n_components]
    return emb


def _require_graph(data):
    if "knn_indices" not in data.obsp:
        raise KeyError("embed.phate: run neighbors.knn first")
    n = data.n_cells
    return (np.asarray(data.obsp["knn_indices"])[:n],
            np.asarray(data.obsp["knn_distances"])[:n])


@register("embed.phate", backend="tpu")
def phate_tpu(data: CellData, n_components: int = 2,
              t: int | None = None, ka: int = 5,
              alpha: float = 2.0, seed: int = 0) -> CellData:
    """Adds obsm["X_phate"], uns["phate_t"].  ``t=None`` picks the
    diffusion time by the von Neumann entropy knee (host, on the
    kernel spectrum).  Exact PHATE is O(n²) — see module docstring."""
    idx, dist = _require_graph(data)
    if t is None:
        # the auto-t spectrum needs the dense host kernel — only pay
        # the O(n²) f64 build when t was not given
        P = _kernel(idx, dist.astype(np.float64), ka, np, alpha)
        t_used = _von_neumann_t(P, np)
    else:
        t_used = t
    emb = np.asarray(_phate_device(
        jnp.asarray(idx), jnp.asarray(dist), jax.random.PRNGKey(seed),
        t=int(t_used), n_components=n_components, ka=ka,
        alpha=float(alpha)))
    return data.with_obsm(X_phate=emb).with_uns(phate_t=int(t_used))


@register("embed.phate", backend="cpu")
def phate_cpu(data: CellData, n_components: int = 2,
              t: int | None = None, ka: int = 5,
              alpha: float = 2.0, seed: int = 0) -> CellData:
    idx, dist = _require_graph(data)
    emb, t_used = _phate_host(idx, dist, n_components, t, ka, alpha)
    return data.with_obsm(X_phate=emb).with_uns(phate_t=int(t_used))
