"""Operator implementations.  Importing this package registers every
transform with the registry (both cpu and tpu backends)."""

from . import (  # noqa: F401
    abundance, cluster, de, density, distance, doublet, graph, hvg, ingest, integrate,
    knn, metacells, metrics, mnn, normalize, palantir, pca, phate, qc,
    score, tsne, umap, velocity, wishbone,
)
