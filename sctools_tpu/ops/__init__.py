"""Operator implementations.  Importing this package registers every
transform with the registry (both cpu and tpu backends)."""

from . import distance, hvg, knn, normalize, pca, qc  # noqa: F401
