"""QC transforms: ``qc.per_cell_metrics``, ``qc.per_gene_metrics``,
``qc.filter_cells``, ``qc.filter_genes``.

Reference parity: BASELINE.json configs[1] — per-cell n_genes,
pct_mito, total_counts.  On TPU, per-cell metrics are row reductions
over the padded-ELL slots (VPU); the mito percentage gathers a boolean
gene mask by the slot indices — a (G+1,) table lookup, no scatter.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..config import config, round_up
from ..data.dataset import CellData
from ..data.sparse import SparseCells, gene_stats
from ..registry import register

from .. import buckets as _buckets


def _mito_mask(data: CellData):
    if "mito" in data.var:
        return data.var["mito"]
    if "gene_name" in data.var:
        names = np.asarray(data.var["gene_name"])
        return np.char.startswith(np.char.upper(names.astype(str)), "MT-")
    return None


@register("qc.per_cell_metrics", backend="tpu", fusable=True,
          mask_aware=True)
def per_cell_metrics_tpu(data: CellData, mito_mask=None,
                         percent_top: tuple = ()) -> CellData:
    """Adds obs: ``n_genes``, ``total_counts``, ``pct_counts_mt``;
    each N in ``percent_top`` adds ``pct_counts_in_top_N_genes``
    (scanpy ``calculate_qc_metrics`` semantics: share of a cell's
    counts captured by its N highest-count genes — opt-in, e.g.
    ``percent_top=(50, 100)``).  On the ELL layout the per-cell top-N
    is one ``lax.top_k`` over the capacity axis.

    Mask-aware for free: every metric is a per-row reduction, and
    bucket-padding rows/genes contribute only zeros (sentinel slots /
    zero columns), so padded rows read 0 and the valid region is
    untouched (buckets.py convention)."""
    X = data.X
    if mito_mask is None:
        mito_mask = _mito_mask(data)
    if isinstance(X, SparseCells):
        valid = X.valid_mask()
        n_genes = jnp.sum(valid, axis=1).astype(jnp.int32)
        total = jnp.sum(X.data, axis=1)
        if mito_mask is not None:
            table = jnp.concatenate(
                [jnp.asarray(mito_mask, X.data.dtype), jnp.zeros((1,), X.data.dtype)]
            )
            mito_per_slot = jnp.take(table, X.indices, axis=0)
            mito_counts = jnp.sum(X.data * mito_per_slot, axis=1)
        else:
            mito_counts = jnp.zeros_like(total)
    else:
        X = jnp.asarray(X)
        n_genes = jnp.sum(X > 0, axis=1).astype(jnp.int32)
        total = jnp.sum(X, axis=1)
        if mito_mask is not None:
            mito_counts = X @ jnp.asarray(mito_mask, X.dtype)
        else:
            mito_counts = jnp.zeros_like(total)
    pct_mt = 100.0 * mito_counts / jnp.maximum(total, 1e-12)
    extra = {}
    for N in percent_top:
        vals = X.data if isinstance(data.X, SparseCells) else X
        k_eff = min(int(N), vals.shape[1])
        top, _ = jax.lax.top_k(vals, k_eff)
        extra[f"pct_counts_in_top_{int(N)}_genes"] = (
            100.0 * jnp.sum(top, axis=1) / jnp.maximum(total, 1e-12))
    return data.with_obs(
        n_genes=n_genes, total_counts=total, pct_counts_mt=pct_mt,
        **extra,
    )


@register("qc.per_cell_metrics", backend="cpu")
def per_cell_metrics_cpu(data: CellData, mito_mask=None,
                         percent_top: tuple = ()) -> CellData:
    import scipy.sparse as sp

    X = data.X
    if mito_mask is None:
        mito_mask = _mito_mask(data)
    if sp.issparse(X):
        X = X.tocsr()
        n_genes = np.diff(X.indptr).astype(np.int32)
        total = np.asarray(X.sum(axis=1)).ravel().astype(np.float32)
        if mito_mask is not None:
            mito_counts = np.asarray(
                X[:, np.asarray(mito_mask, bool)].sum(axis=1)
            ).ravel()
        else:
            mito_counts = np.zeros_like(total)
    else:
        X = np.asarray(X)
        n_genes = (X > 0).sum(axis=1).astype(np.int32)
        total = X.sum(axis=1).astype(np.float32)
        mito_counts = (
            X[:, np.asarray(mito_mask, bool)].sum(axis=1)
            if mito_mask is not None else np.zeros_like(total)
        )
    pct_mt = 100.0 * mito_counts / np.maximum(total, 1e-12)
    extra = {}
    if percent_top:
        Xc = data.X.tocsr() if sp.issparse(data.X) else None
        for N in percent_top:
            N = int(N)
            tops = np.zeros(len(total))
            for i in range(len(total)):
                row = (Xc.data[Xc.indptr[i]:Xc.indptr[i + 1]]
                       if Xc is not None else X[i][X[i] > 0])
                if len(row) <= N:
                    tops[i] = row.sum()
                else:
                    tops[i] = np.partition(row, len(row) - N)[-N:].sum()
            extra[f"pct_counts_in_top_{N}_genes"] = (
                100.0 * tops / np.maximum(total, 1e-12)
            ).astype(np.float32)
    return data.with_obs(
        n_genes=n_genes, total_counts=total,
        pct_counts_mt=pct_mt.astype(np.float32),
        **extra,
    )


@register("qc.per_gene_metrics", backend="tpu", fusable=True,
          mask_aware=True)
def per_gene_metrics_tpu(data: CellData) -> CellData:
    """Adds var: ``n_cells``, ``total_counts``, ``mean_counts``.

    Mask-aware: on bucketized data the sums already exclude padding
    (sentinel slots / zero rows); only the mean's population count
    switches to the TRACED valid-cell count."""
    X = data.X
    masks = _buckets.masks_of(data)
    if isinstance(X, SparseCells):
        s, _, n = gene_stats(X)
        n_cells_by = n.astype(jnp.int32)
        total = s
        if masks is None:
            mean = s / X.n_cells
        else:
            mean = s / jnp.maximum(
                jnp.asarray(masks.n_cells, s.dtype), 1.0)
    else:
        X = jnp.asarray(X)
        n_cells_by = jnp.sum(X > 0, axis=0).astype(jnp.int32)
        total = jnp.sum(X, axis=0)
        if masks is None:
            mean = total / X.shape[0]
        else:
            mean = total / jnp.maximum(
                jnp.asarray(masks.n_cells, total.dtype), 1.0)
    return data.with_var(n_cells=n_cells_by, total_counts=total, mean_counts=mean)


@register("qc.per_gene_metrics", backend="cpu")
def per_gene_metrics_cpu(data: CellData) -> CellData:
    import scipy.sparse as sp

    X = data.X
    if sp.issparse(X):
        Xc = X.tocsc()
        n_cells_by = np.diff(Xc.indptr).astype(np.int32)
        total = np.asarray(X.sum(axis=0)).ravel().astype(np.float32)
    else:
        X = np.asarray(X)
        n_cells_by = (X > 0).sum(axis=0).astype(np.int32)
        total = X.sum(axis=0).astype(np.float32)
    mean = total / data.n_cells
    return data.with_var(n_cells=n_cells_by, total_counts=total, mean_counts=mean)


# ----------------------------------------------------------------------
# Filtering.  Subsetting changes shapes, so on the TPU backend this is
# a *materialisation point*: the keep-mask is computed on device, the
# row gather happens with a host-chosen new padded size.  Not jittable
# end-to-end by design (XLA needs static shapes); pipelines place
# filters between jitted segments, exactly like the reference places
# them between shard passes.
# ----------------------------------------------------------------------


def _cell_keep_mask(data: CellData, min_genes, min_counts, max_pct_mt,
                    xp, max_genes=None, max_counts=None):
    obs = data.obs
    need = [k for k in ("n_genes", "total_counts") if k not in obs]
    if need:
        raise ValueError(
            f"qc.filter_cells requires qc.per_cell_metrics first (missing {need})"
        )
    keep = xp.ones(obs["n_genes"].shape, bool)
    if min_genes is not None:
        keep &= obs["n_genes"] >= min_genes
    if max_genes is not None:
        keep &= obs["n_genes"] <= max_genes
    if min_counts is not None:
        keep &= obs["total_counts"] >= min_counts
    if max_counts is not None:
        keep &= obs["total_counts"] <= max_counts
    if max_pct_mt is not None and "pct_counts_mt" in obs:
        keep &= obs["pct_counts_mt"] <= max_pct_mt
    return keep


@register("qc.filter_cells", backend="tpu")
def filter_cells_tpu(
    data: CellData,
    min_genes: int | None = None,
    min_counts: float | None = None,
    max_pct_mt: float | None = None,
    max_genes: int | None = None,
    max_counts: float | None = None,
) -> CellData:
    """Drop cells outside the given QC bounds (scanpy
    ``pp.filter_cells`` semantics, all bounds inclusive; ``max_pct_mt``
    additionally caps mitochondrial fraction when
    ``obs["pct_counts_mt"]`` exists).  Requires ``qc.per_cell_metrics``
    first — raises if ``obs`` lacks n_genes/total_counts.  Subsetting
    changes shapes, so this is a materialisation point: the keep mask
    is computed on device, the row gather re-pads host-side; ``obsp``
    is dropped (pairwise graphs must be rebuilt)."""
    X = data.X
    keep = _cell_keep_mask(data, min_genes, min_counts, max_pct_mt, jnp,
                           max_genes, max_counts)
    if isinstance(X, SparseCells):
        if keep.shape[0] < X.rows_padded:
            # obs metrics computed on the cpu backend are n_cells long;
            # device-computed ones carry padded rows — align before
            # masking
            keep = jnp.concatenate([
                keep, jnp.zeros(X.rows_padded - keep.shape[0], bool)])
        keep = keep & X.row_mask()
    keep_host = np.asarray(keep)
    idx = np.nonzero(keep_host)[0]
    return select_cells_device(data, idx)


def _gather_rows_matrix(M, idx: np.ndarray):
    """Row-subset an X-shaped matrix (SparseCells / scipy / dense),
    device path — shared by X and every layer so they cannot drift."""
    import scipy.sparse as sp

    n_new = len(idx)
    if sp.issparse(M):
        return M.tocsr()[idx]
    if isinstance(M, SparseCells):
        rows_padded = round_up(max(n_new, 1), config.sublane)
        gidx = jnp.asarray(
            np.pad(idx, (0, rows_padded - n_new),
                   constant_values=M.rows_padded - 1)
        )
        ind = jnp.take(M.indices, gidx, axis=0)
        dat = jnp.take(M.data, gidx, axis=0)
        if rows_padded > n_new:  # ensure padding rows are empty
            pad_row = jnp.arange(rows_padded) >= n_new
            ind = jnp.where(pad_row[:, None], M.sentinel, ind)
            dat = jnp.where(pad_row[:, None], 0.0, dat)
        return SparseCells(ind, dat, n_new, M.n_genes)
    return jnp.take(jnp.asarray(M), jnp.asarray(idx), axis=0)


def select_cells_device(data: CellData, idx: np.ndarray) -> CellData:
    """Subset a CellData to the cells in ``idx`` (device row gather;
    shared by qc.filter_cells and qc.subsample).  X, obs, obsm, and
    every layer are sliced consistently; drops obsp — pairwise graphs
    refer to dropped rows and must be rebuilt."""
    X = data.X
    idx = np.asarray(idx)
    newX = _gather_rows_matrix(X, idx)
    num_idx = jnp.asarray(idx)

    def take(v):
        if isinstance(v, jax.Array) or np.asarray(v).dtype.kind in "biufc":
            return jnp.take(jnp.asarray(v), num_idx, axis=0)
        return np.asarray(v)[idx]  # strings/objects stay host-side
    obs = {k: take(v) for k, v in data.obs.items()}
    obsm = {k: take(v) for k, v in data.obsm.items()}
    layers = {k: _gather_rows_matrix(v, idx)
              for k, v in data.layers.items()}
    return data.replace(X=newX, obs=obs, obsm=obsm, obsp={},
                        layers=layers)


def _subsample_idx(n_cells: int, fraction: float | None, n_obs: int | None,
                   seed: int) -> np.ndarray:
    if (fraction is None) == (n_obs is None):
        raise ValueError("qc.subsample needs exactly one of "
                         "fraction= or n_obs=")
    if fraction is not None:
        if not 0.0 < fraction <= 1.0:
            raise ValueError(f"fraction must be in (0, 1], got {fraction}")
        n_obs = int(fraction * n_cells)  # floor — scanpy's convention
    if not 0 < n_obs <= n_cells:
        raise ValueError(
            f"n_obs={n_obs} out of range (need 1..{n_cells}); a "
            "fraction too small to keep one cell also lands here")
    rng = np.random.default_rng(seed)
    return np.sort(rng.choice(n_cells, size=n_obs, replace=False))


@register("qc.subsample", backend="tpu")
def subsample_tpu(data: CellData, fraction: float | None = None,
                  n_obs: int | None = None, seed: int = 0) -> CellData:
    """Random cell subset (scanpy ``pp.subsample`` semantics: exactly
    one of ``fraction`` / ``n_obs``; fraction FLOORS to a count),
    sampled without replacement with a seeded host RNG (identical
    cells on both backends), order preserved.  Divergence: a selection
    of zero cells raises instead of returning an empty dataset (every
    downstream per-cell op would divide by n_cells).  Device row
    gather; obsp dropped (rebuild the graph)."""
    idx = _subsample_idx(data.n_cells, fraction, n_obs, seed)
    return select_cells_device(data, idx)


@register("qc.subsample", backend="cpu")
def subsample_cpu(data: CellData, fraction: float | None = None,
                  n_obs: int | None = None, seed: int = 0) -> CellData:
    idx = _subsample_idx(data.n_cells, fraction, n_obs, seed)
    X = data.X[idx]
    obs = {k: np.asarray(v)[idx] for k, v in data.obs.items()}
    obsm = {k: np.asarray(v)[idx] for k, v in data.obsm.items()}
    layers = {k: v[idx] for k, v in data.layers.items()}
    return data.replace(X=X, obs=obs, obsm=obsm, obsp={}, layers=layers)


@register("qc.filter_cells", backend="cpu")
def filter_cells_cpu(
    data: CellData,
    min_genes: int | None = None,
    min_counts: float | None = None,
    max_pct_mt: float | None = None,
    max_genes: int | None = None,
    max_counts: float | None = None,
) -> CellData:
    keep = np.asarray(_cell_keep_mask(data, min_genes, min_counts,
                                      max_pct_mt, np, max_genes,
                                      max_counts))
    X = data.X[keep]
    obs = {k: np.asarray(v)[keep] for k, v in data.obs.items()}
    obsm = {k: np.asarray(v)[keep] for k, v in data.obsm.items()}
    layers = {k: v[keep] for k, v in data.layers.items()}
    return data.replace(X=X, obs=obs, obsm=obsm, obsp={}, layers=layers)


@register("qc.filter_genes", backend="tpu")
def filter_genes_tpu(data: CellData, min_cells: int | None = 3,
                     min_counts: float | None = None,
                     max_cells: int | None = None,
                     max_counts: float | None = None) -> CellData:
    """Drop genes outside the given QC bounds (scanpy
    ``pp.filter_genes`` semantics, bounds inclusive, on
    ``var["n_cells"]``/``var["total_counts"]`` — computed via
    ``qc.per_gene_metrics`` on demand).  A materialisation point like
    ``qc.filter_cells``: the column subset re-lays-out the ELL matrix
    at a new padded width."""
    from .hvg import select_genes_device  # shared gene-subset machinery

    if "n_cells" not in data.var:
        data = per_gene_metrics_tpu(data)
    keep = jnp.ones(data.n_genes, bool)
    if min_cells is not None:
        keep &= data.var["n_cells"] >= min_cells
    if max_cells is not None:
        keep &= data.var["n_cells"] <= max_cells
    if min_counts is not None:
        keep &= data.var["total_counts"] >= min_counts
    if max_counts is not None:
        keep &= data.var["total_counts"] <= max_counts
    idx = np.nonzero(np.asarray(keep))[0]
    return select_genes_device(data, idx)


@register("qc.filter_genes", backend="cpu")
def filter_genes_cpu(data: CellData, min_cells: int | None = 3,
                     min_counts: float | None = None,
                     max_cells: int | None = None,
                     max_counts: float | None = None) -> CellData:
    if "n_cells" not in data.var:
        data = per_gene_metrics_cpu(data)
    keep = np.ones(data.n_genes, bool)
    if min_cells is not None:
        keep &= np.asarray(data.var["n_cells"]) >= min_cells
    if max_cells is not None:
        keep &= np.asarray(data.var["n_cells"]) <= max_cells
    if min_counts is not None:
        keep &= np.asarray(data.var["total_counts"]) >= min_counts
    if max_counts is not None:
        keep &= np.asarray(data.var["total_counts"]) <= max_counts
    X = data.X[:, keep]
    var = {k: np.asarray(v)[keep] for k, v in data.var.items()}
    varm = {k: np.asarray(v)[keep] for k, v in data.varm.items()}
    layers = {k: v[:, keep] for k, v in data.layers.items()}
    return data.replace(X=X, var=var, varm=varm, layers=layers)


@register("util.snapshot_layer", backend="tpu", fusable=True,
          mask_aware=True)
@register("util.snapshot_layer", backend="cpu")
def snapshot_layer(data: CellData, layer: str = "counts") -> CellData:
    """Copy the CURRENT X into ``layers[layer]`` — the Pipeline-friendly
    form of the AnnData idiom ``adata.layers["counts"] = adata.X``
    (placed before normalisation to preserve raw counts).  X is
    functional/immutable here, so no copy is made — the layer shares
    the buffers.  (The kwarg is ``layer``, not ``name`` — ``name`` is
    the Transform's own first argument.)"""
    return data.with_layers(**{layer: data.X})
