"""``integrate.mnn`` — mutual-nearest-neighbour batch correction.

Capability parity: the MNN family (Haghverdi et al. 2018; the
``fastMNN``/``reducedMNN`` variant that operates in a reduced
embedding, and scanpy's ``external.pp.mnn_correct`` entry point).  The
reference source was unavailable (/root/reference empty — SURVEY.md
§0); the behavioral contract implemented here is the published
reducedMNN recipe:

1. order batches largest-first; the largest is the fixed reference;
2. for each further batch B: find k nearest reference cells of every
   B cell and k nearest B cells of every reference cell (euclidean, in
   the embedding); mutual pairs are edges present in both lists;
3. each pair votes a correction vector (ref endpoint − batch
   endpoint); per-endpoint votes are averaged, then smoothed over B by
   a Gaussian kernel on the distance to the nearest pair endpoints —
   so cells far from any anchor still move with their neighbourhood;
4. the corrected batch joins the reference set and the next batch is
   processed against the enlarged reference (the published "orthogonal
   merge" order).

TPU design: the two cross-batch kNN searches and the smoothing search
are the only heavy stages — all three ride the existing blocked-MXU
``knn_arrays`` (bucketed shapes, bf16 coarse + f32 refine).  Pair
bookkeeping is O(n·k) host numpy.  The CPU backend mirrors the same
steps with the numpy oracle, so both backends produce the same merge
up to f32-vs-f64 tie-breaks.
"""

from __future__ import annotations

import numpy as np

from ..data.dataset import CellData
from ..registry import register


def _mutual_pairs(idx_b2r, idx_r2b):
    """(b_cell, r_cell) pairs present in both neighbour lists.
    idx_b2r: (nB, k) reference ids per batch cell; idx_r2b: (nR, k)
    batch ids per reference cell."""
    nB, k = idx_b2r.shape
    nR = idx_r2b.shape[0]
    # vectorised edge-set intersection on packed int64 keys b*nR + r
    # (a python tuple-set would cost O(n*k) interpreter time and
    # hundreds of MB at atlas scale).  -1 padding slots (k larger than
    # the candidate set) must be dropped BEFORE packing: b*nR + (-1)
    # would alias (b-1)*nR + (nR-1) and fabricate pairs
    fwd = (np.repeat(np.arange(nB, dtype=np.int64), k) * nR
           + idx_b2r.ravel().astype(np.int64))
    fwd = fwd[idx_b2r.ravel() >= 0]
    rev = (idx_r2b.ravel().astype(np.int64) * nR
           + np.repeat(np.arange(nR, dtype=np.int64),
                       idx_r2b.shape[1]))
    rev = rev[idx_r2b.ravel() >= 0]
    mutual = np.intersect1d(fwd, rev, assume_unique=False)
    return mutual // nR, mutual % nR


def _correct_one(ref, bat, k, sigma, knn):
    """Correction matrix (nB, d) moving ``bat`` toward ``ref``."""
    k = min(k, len(ref), len(bat))  # tiny batches: no padded -1 ids
    idx_b2r, _ = knn(bat, ref, k)
    idx_r2b, _ = knn(ref, bat, k)
    bm, rm = _mutual_pairs(np.asarray(idx_b2r)[: len(bat)],
                           np.asarray(idx_r2b)[: len(ref)])
    if len(bm) == 0:
        raise ValueError(
            "integrate.mnn: no mutual pairs between batches — raise k "
            "or check that the batches share cell populations")
    # per unique batch endpoint: mean of its pair vectors
    vec = ref[rm] - bat[bm]
    uniq, inv = np.unique(bm, return_inverse=True)
    sums = np.zeros((len(uniq), bat.shape[1]), np.float64)
    np.add.at(sums, inv, vec)
    cnt = np.bincount(inv).astype(np.float64)
    anchor_vec = sums / cnt[:, None]
    anchors = bat[uniq]
    # smooth over B: Gaussian weights on distance to the nearest
    # anchors (ksm of them), bandwidth sigma * median anchor distance
    ksm = min(min(50, max(3 * k, 10)), len(uniq))
    a_idx, a_d = knn(bat, anchors, ksm)
    a_idx = np.asarray(a_idx)[: len(bat)]
    a_d = np.asarray(a_d, np.float64)[: len(bat)]
    med = np.median(a_d[:, 0]) + 1e-12
    h = sigma * med if sigma * med > 0 else 1.0
    w = np.exp(-0.5 * (a_d / h) ** 2) + 1e-12
    w /= w.sum(axis=1, keepdims=True)
    return np.einsum("ck,ckd->cd", w, anchor_vec[a_idx])


def _mnn(data: CellData, batch_key, use_rep, k, sigma, knn):
    if batch_key not in data.obs:
        raise KeyError(f"integrate.mnn: obs has no {batch_key!r}")
    n = data.n_cells
    labels = np.asarray(data.obs[batch_key])[:n]
    Z = np.asarray(data.obsm[use_rep], np.float64)[:n]
    levels, codes = np.unique(labels, return_inverse=True)
    if len(levels) < 2:
        raise ValueError("integrate.mnn: need at least 2 batches")
    order = np.argsort([-np.sum(codes == i) for i in range(len(levels))])
    out = Z.copy()
    ref_rows = np.where(codes == order[0])[0]
    for li in order[1:]:
        rows = np.where(codes == li)[0]
        corr = _correct_one(out[ref_rows], out[rows], k, sigma, knn)
        out[rows] += corr
        ref_rows = np.concatenate([ref_rows, rows])
    return data.with_obsm(X_mnn=out.astype(np.float32)).with_uns(
        mnn_merge_order=[str(levels[i]) for i in order])


@register("integrate.mnn", backend="tpu")
def mnn_tpu(data: CellData, batch_key: str = "batch",
            use_rep: str = "X_pca", k: int = 20,
            sigma: float = 1.0) -> CellData:
    """Adds obsm["X_mnn"] (corrected embedding) and
    uns["mnn_merge_order"].  The three kNN searches per merge run on
    the device; see module docstring for the algorithm contract."""
    import jax.numpy as jnp

    from .knn import knn_arrays

    def knn(q, c, kk):
        idx, d = knn_arrays(jnp.asarray(q, jnp.float32),
                            jnp.asarray(c, jnp.float32), k=kk,
                            metric="euclidean", n_query=len(q),
                            n_cand=len(c), refine=max(kk, 32))
        return np.asarray(idx), np.asarray(d)

    return _mnn(data, batch_key, use_rep, k, sigma, knn)


@register("integrate.mnn", backend="cpu")
def mnn_cpu(data: CellData, batch_key: str = "batch",
            use_rep: str = "X_pca", k: int = 20,
            sigma: float = 1.0) -> CellData:
    from .knn import knn_numpy

    def knn(q, c, kk):
        return knn_numpy(q, c, k=kk, metric="euclidean")

    return _mnn(data, batch_key, use_rep, k, sigma, knn)
