"""``embed.density`` + ``de.marker_gene_overlap`` — embedding-space
density scoring and marker-list comparison.

Capability parity: scanpy ``tl.embedding_density`` and
``tl.marker_gene_overlap`` (reference source unavailable —
SURVEY.md §0; public scanpy behavior is the contract).

``embed.density``: per-cell Gaussian KDE in a 2-D embedding, scaled to
[0, 1] within each group (scanpy's convention, so densities are
comparable across panels of a grouped plot).  Deviation from scanpy,
documented: scanpy delegates to ``scipy.stats.gaussian_kde`` (full
covariance); here both backends whiten the embedding per group and use
an isotropic kernel with Scott's-rule bandwidth — same asymptotics,
and the TPU path becomes a blocked MXU distance kernel (one
``(n, n)`` pass in row chunks) instead of a host-only estimator.  The
cpu backend implements the identical math so the oracle test is exact.

``de.marker_gene_overlap``: overlap between a ``de.rank_genes_groups``
result and user-supplied reference marker sets — pure host set
algebra, one implementation for both backends.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..data.dataset import CellData
from ..registry import register

_CHUNK = 4096


@partial(jax.jit, static_argnames=("chunk",))
def _kde_device(E, h2, n_valid, chunk: int = _CHUNK):
    """Mean isotropic Gaussian kernel to every valid row of E.
    E: (n_pad, d) whitened embedding, padding rows beyond n_valid."""
    n_pad = E.shape[0]
    valid = jnp.arange(n_pad) < n_valid

    def body(_, q):  # q: (chunk, d)
        d2 = (jnp.sum(q * q, axis=1)[:, None]
              - 2.0 * q @ E.T
              + jnp.sum(E * E, axis=1)[None, :])
        k = jnp.where(valid[None, :], jnp.exp(-0.5 * d2 / h2), 0.0)
        return _, jnp.sum(k, axis=1)

    qs = E.reshape(n_pad // chunk, chunk, E.shape[1])
    _, dens = jax.lax.scan(body, None, qs)
    return dens.reshape(-1) / jnp.maximum(n_valid, 1)


def _density_group(E, device: bool, pad_to: int | None = None):
    """[0,1]-scaled KDE of one group's embedding rows (n, d).
    ``pad_to``: shared padded size across groups, so one compiled
    shape serves every group."""
    n, d = E.shape
    mu = E.mean(axis=0)
    sd = E.std(axis=0) + 1e-12
    W = (E - mu) / sd  # whitened
    h = n ** (-1.0 / (d + 4))  # Scott's rule on unit-variance data
    if device and n >= 2:
        from ..config import round_up

        chunk = min(_CHUNK, round_up(pad_to or n, 8))
        n_pad = round_up(pad_to or n, chunk)
        Wp = jnp.zeros((n_pad, d), jnp.float32).at[:n].set(
            jnp.asarray(W, jnp.float32))
        dens = np.asarray(_kde_device(Wp, jnp.float32(h * h),
                                      jnp.int32(n), chunk=chunk))[:n]
    else:
        # row-chunked like the device path — a broadcast (n, n, d)
        # intermediate would be ~40 GB at 50k cells
        nrm = (W * W).sum(axis=1)
        dens = np.empty(n, np.float64)
        for lo in range(0, n, _CHUNK):
            q = W[lo: lo + _CHUNK]
            d2 = (nrm[lo: lo + _CHUNK, None] - 2.0 * q @ W.T
                  + nrm[None, :])
            dens[lo: lo + _CHUNK] = np.exp(
                -0.5 * np.maximum(d2, 0.0) / (h * h)).mean(axis=1)
    lo, hi = float(dens.min()), float(dens.max())
    return ((dens - lo) / (hi - lo) if hi > lo
            else np.zeros_like(dens))


def _embedding_density(data: CellData, basis, groupby, device):
    key = f"X_{basis}" if not basis.startswith("X_") else basis
    if key not in data.obsm:
        raise KeyError(f"embed.density: obsm has no {key!r}")
    n = data.n_cells
    E = np.asarray(data.obsm[key], np.float64)[:n]
    out_col = f"{basis.removeprefix('X_')}_density"
    dens = np.zeros(n, np.float32)
    if groupby is None:
        dens[:] = _density_group(E, device)
    else:
        if groupby not in data.obs:
            raise KeyError(f"embed.density: obs has no {groupby!r}")
        labels = np.asarray(data.obs[groupby])[:n]
        groups = np.unique(labels)
        # one shared padded shape for every group: chunk/n_pad are
        # STATIC to _kde_device, so per-group shapes would recompile
        # XLA once per distinct cluster size
        pad_to = max(int((labels == g).sum()) for g in groups)
        for g in groups:
            m = labels == g
            dens[m] = _density_group(E[m], device, pad_to=pad_to)
        out_col = f"{out_col}_{groupby}"
    return data.with_obs(**{out_col: dens})


@register("embed.density", backend="tpu")
def embedding_density_tpu(data: CellData, basis: str = "umap",
                          groupby: str | None = None) -> CellData:
    """Adds obs["<basis>_density[_<groupby>]"] in [0, 1] (scanpy
    tl.embedding_density semantics; kernel math in module docstring)."""
    return _embedding_density(data, basis, groupby, device=True)


@register("embed.density", backend="cpu")
def embedding_density_cpu(data: CellData, basis: str = "umap",
                          groupby: str | None = None) -> CellData:
    return _embedding_density(data, basis, groupby, device=False)


# ----------------------------------------------------------------------
# de.marker_gene_overlap
# ----------------------------------------------------------------------


def _overlap(found: set, ref: set, method: str):
    inter = len(found & ref)
    if method == "overlap_count":
        return float(inter)
    if method == "overlap_coef":
        return inter / max(min(len(found), len(ref)), 1)
    if method == "jaccard":
        return inter / max(len(found | ref), 1)
    raise ValueError(f"marker_gene_overlap: unknown method {method!r}")


@register("de.marker_gene_overlap", backend="tpu")
@register("de.marker_gene_overlap", backend="cpu")
def marker_gene_overlap(data: CellData, *, reference_markers: dict,
                        key: str = "rank_genes_groups",
                        method: str = "overlap_count",
                        top_n_markers: int = 100) -> CellData:
    """Compare each ranked group's top markers against reference
    marker sets (scanpy ``tl.marker_gene_overlap``).  Adds
    ``uns[key + '_overlap']``: {"groups", "reference", "matrix"
    (n_ref × n_groups)}.  Host set algebra — identical on both
    backends."""
    if key not in data.uns:
        raise KeyError(
            f"marker_gene_overlap: uns has no {key!r} — run "
            "de.rank_genes_groups first")
    if method not in ("overlap_count", "overlap_coef", "jaccard"):
        raise ValueError(f"marker_gene_overlap: unknown method {method!r}")
    res = data.uns[key]
    names = np.asarray(res["names"])
    groups = [str(g) for g in res["groups"]]
    tops = [set(map(str, names[i][:top_n_markers]))
            for i in range(len(groups))]
    refs = {str(r): set(map(str, v))
            for r, v in reference_markers.items()}
    mat = np.zeros((len(refs), len(tops)))
    for i, rv in enumerate(refs.values()):
        for j, t in enumerate(tops):
            mat[i, j] = _overlap(t, rv, method)
    return data.with_uns(**{f"{key}_overlap": {
        "groups": groups, "reference": list(refs), "matrix": mat,
        "method": method, "top_n_markers": top_n_markers}})
