"""``da.neighborhoods`` — Milo-style differential abundance.

Capability parity: the Milo recipe (Dann et al. 2022) for "where in
the manifold is condition A enriched over condition B", the standard
condition-comparison companion to integration.  The reference source
was unavailable (/root/reference empty — SURVEY.md §0); the published
recipe's core is the contract.

Two inference modes:

* ``sample_key=None`` (no replicates): binomial normal approximation
  of each neighbourhood's condition fraction against the global
  proportion, BH-corrected.  This matches the Milo GLM's calls on
  balanced designs but its FDRs are composition-shift calls, not
  replicate-backed inference — sample-level batch shifts inflate its
  call rate (pinned by a test).
* ``sample_key=`` (replicates): Milo's per-sample aggregation.
  Neighbourhood counts are aggregated per replicate, depth-normalised
  to per-sample neighbourhood frequencies, and tested with a Welch
  t-test ACROSS replicates within each condition — the
  quasi-likelihood analogue of Milo's edgeR NB GLM (between-replicate
  variance is estimated from the data, so an overdispersed replicated
  design widens the null instead of over-calling).  Requires >=2
  samples per condition; each sample must belong to exactly one
  condition.

TPU design: a neighbourhood is each index cell's kNN set (plus
itself) — per-neighbourhood per-sample counts are ONE one-hot
gather+sum over the edge list, the same k-sparse primitive every
graph op here uses.  The t/p/FDR bookkeeping is O(n·S) host math.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..data.dataset import CellData
from ..registry import register


def _nbhd_counts(idx, flags, device):
    """Per-index-cell count of flagged neighbours.  ``idx`` rows must
    already carry the index cell itself as their first column (the
    caller appends it — under ``prop=`` sampling the row position no
    longer equals the cell id, so an implicit self-add would index the
    wrong cells)."""
    if device:
        safe = jnp.where(idx < 0, 0, idx)
        f = jnp.asarray(flags, jnp.float32)
        gathered = jnp.where(idx >= 0, jnp.take(f, safe), 0.0)
        return np.asarray(jnp.sum(gathered, axis=1))
    f = np.asarray(flags, np.float64)
    safe = np.where(idx >= 0, idx, 0)
    gathered = np.where(idx >= 0, f[safe], 0.0)
    return gathered.sum(axis=1)


def _nbhd_sample_counts(idx, codes, S, device):
    """(n, S) count of each index cell's neighbours (self included)
    per sample code — one one-hot gather+sum over the edge list."""
    n, k = idx.shape
    if device:
        # one flag-gather pass per sample (S is small): keeps peak
        # device memory at the edge list's own O(n*k) instead of a
        # dense (n, k, S) one-hot gather — ~1.6 GB at 1.3M x 15 x 20
        cols = [_nbhd_counts(idx, np.asarray(codes) == s, device=True)
                for s in range(S)]
        return np.stack(cols, axis=1).astype(np.float64)
    codes = np.asarray(codes)
    valid = (idx >= 0).ravel()
    rows = np.repeat(np.arange(n), k)[valid]
    c = codes[idx.ravel()[valid]]
    counts = np.bincount(rows * S + c, minlength=n * S).reshape(n, S)
    return counts.astype(np.float64)  # self is idx's first column


def _expand(vals, index_cells, n):
    """Scatter per-index-cell results to (n,) float32, NaN elsewhere
    (Milo convention: non-index cells have no neighbourhood)."""
    if len(index_cells) == n:
        return np.asarray(vals, np.float32)
    out = np.full(n, np.nan, np.float32)
    out[index_cells] = vals
    return out


def _bh_fdr(pvals):
    order = np.argsort(pvals)
    q = pvals[order] * len(pvals) / np.arange(1, len(pvals) + 1)
    q = np.minimum.accumulate(q[::-1])[::-1]
    fdr = np.empty_like(q)
    fdr[order] = np.clip(q, 0, 1)
    return fdr


def _replicate_test(idx, cond, samples, a, b, device):
    """Welch t-test across per-sample neighbourhood frequencies —
    the replicate-aware path (see module docstring)."""
    from scipy import stats as sps

    slevels, scodes = np.unique(samples, return_inverse=True)
    S = len(slevels)
    samp_cond = np.empty(S, dtype=object)
    for si, s in enumerate(slevels):
        cs = set(cond[samples == s].tolist())
        if len(cs) != 1:
            raise ValueError(
                f"da.neighborhoods: sample {s!r} spans conditions "
                f"{sorted(cs)}; each sample must belong to exactly one")
        samp_cond[si] = cs.pop()
    in_a = samp_cond == a
    in_b = samp_cond == b
    if in_a.sum() < 2 or in_b.sum() < 2:
        raise ValueError(
            f"da.neighborhoods: replicate-aware test needs >=2 samples "
            f"per condition (got {int(in_a.sum())} {a!r} / "
            f"{int(in_b.sum())} {b!r}); omit sample_key= for the "
            f"closed-form composition test")
    C = _nbhd_sample_counts(idx, scodes, S, device)  # (n, S)
    # depth normalisation: per-sample neighbourhood frequency, so a
    # deeply-sampled replicate doesn't masquerade as enrichment
    Ns = np.bincount(scodes, minlength=S).astype(np.float64)
    R = C / np.maximum(Ns[None, :], 1.0)
    ra, rb = R[:, in_a], R[:, in_b]
    na_s, nb_s = int(in_a.sum()), int(in_b.sum())
    ma, mb = ra.mean(axis=1), rb.mean(axis=1)
    va = ra.var(axis=1, ddof=1) / na_s
    vb = rb.var(axis=1, ddof=1) / nb_s
    se = np.sqrt(np.maximum(va + vb, 1e-24))
    t = (ma - mb) / se
    # Welch–Satterthwaite df; zero-variance neighbourhoods fall back
    # to the pooled df
    denom = (va**2 / max(na_s - 1, 1) + vb**2 / max(nb_s - 1, 1))
    df = np.where(denom > 0, (va + vb) ** 2 / np.maximum(denom, 1e-300),
                  na_s + nb_s - 2)
    df = np.clip(df, 1.0, None)
    pvals = 2.0 * sps.t.sf(np.abs(t), df)
    eps = 0.5 / max(Ns.mean(), 1.0)  # half-cell pseudo-frequency
    lfc = np.log2((ma + eps) / (mb + eps))
    return t, pvals, lfc, slevels


def _differential_abundance(data: CellData, condition_key, groups,
                            sample_key, device, prop=1.0, seed=0):
    n = data.n_cells
    if "knn_indices" not in data.obsp:
        raise KeyError("da.neighborhoods: run neighbors.knn first")
    if condition_key not in data.obs:
        raise KeyError(f"da.neighborhoods: obs has no {condition_key!r}")
    cond = np.asarray(data.obs[condition_key]).astype(str)[:n]
    levels = sorted(set(cond.tolist())) if groups is None else list(groups)
    if len(levels) != 2:
        raise ValueError(
            f"da.neighborhoods compares exactly 2 condition levels, "
            f"got {levels}")
    a, b = levels
    idx = np.asarray(data.obsp["knn_indices"])[:n]

    # Milo's make_nhoods(prop=): sample a fraction of cells as
    # neighbourhood index cells — FDR correction then runs over the
    # sampled neighbourhoods only, and non-index cells carry NaN
    if not (0.0 < prop <= 1.0):
        raise ValueError(f"da.neighborhoods: prop={prop} not in (0, 1]")
    index_cells = np.arange(n)
    if prop < 1.0:
        rng = np.random.default_rng(seed)
        n_idx = max(int(round(prop * n)), 2)
        index_cells = np.sort(rng.choice(n, size=n_idx, replace=False))
        idx = idx[index_cells]
    # neighbourhood = index cell + its kNN set: make the self
    # membership an explicit first column (see _nbhd_counts)
    idx = np.concatenate([index_cells[:, None].astype(idx.dtype), idx],
                         axis=1)

    if sample_key is not None:
        if sample_key not in data.obs:
            raise KeyError(
                f"da.neighborhoods: obs has no {sample_key!r}")
        samples = np.asarray(data.obs[sample_key]).astype(str)[:n]
        score, pvals, lfc, slevels = _replicate_test(
            idx, cond, samples, a, b, device)
        return (data.with_obs(
            da_score=_expand(score, index_cells, n),
            da_fdr=_expand(_bh_fdr(pvals), index_cells, n),
            da_logfc=_expand(lfc, index_cells, n))
            .with_uns(da_conditions=[a, b],
                      da_method="replicate-welch",
                      da_index_cells=index_cells.astype(np.int64),
                      da_samples=[str(s) for s in slevels]))

    na = _nbhd_counts(idx, cond == a, device)
    nb = _nbhd_counts(idx, cond == b, device)
    tot = na + nb
    p0 = float((cond == a).sum()) / max(len(cond), 1)
    # binomial z of the neighbourhood's A-fraction vs the global
    # proportion (the no-replicates closed form)
    se = np.sqrt(np.maximum(tot * p0 * (1 - p0), 1e-12))
    z = (na - tot * p0) / se
    from scipy import stats as sps

    pvals = 2.0 * sps.norm.sf(np.abs(z))
    fdr = _bh_fdr(pvals)
    lfc = np.log2((na + 0.5) / (nb + 0.5)
                  / (p0 / max(1 - p0, 1e-12)))
    return (data.with_obs(
        da_score=_expand(z, index_cells, n),
        da_fdr=_expand(fdr, index_cells, n),
        da_logfc=_expand(lfc, index_cells, n))
        .with_uns(da_conditions=[a, b],
                  da_method="binomial-global",
                  da_index_cells=index_cells.astype(np.int64)))


@register("da.neighborhoods", backend="tpu")
def da_tpu(data: CellData, condition_key: str = "condition",
           groups=None, sample_key: str | None = None,
           prop: float = 1.0, seed: int = 0) -> CellData:
    """Adds obs["da_score"] (signed z or Welch t, + = enriched for the
    first level), obs["da_fdr"], obs["da_logfc"]; uns["da_conditions"],
    uns["da_method"].  Each cell's kNN neighbourhood is its Milo-style
    index set.  Pass ``sample_key=`` for replicate-aware inference
    (see module docstring)."""
    return _differential_abundance(data, condition_key, groups,
                                   sample_key, device=True, prop=prop,
                                   seed=seed)


@register("da.neighborhoods", backend="cpu")
def da_cpu(data: CellData, condition_key: str = "condition",
           groups=None, sample_key: str | None = None,
           prop: float = 1.0, seed: int = 0) -> CellData:
    return _differential_abundance(data, condition_key, groups,
                                   sample_key, device=False, prop=prop,
                                   seed=seed)
