"""``da.neighborhoods`` — Milo-style differential abundance.

Capability parity: the Milo recipe (Dann et al. 2022) for "where in
the manifold is condition A enriched over condition B", the standard
condition-comparison companion to integration.  The reference source
was unavailable (/root/reference empty — SURVEY.md §0); the published
recipe's core is the contract, with ONE documented simplification:
Milo fits an edgeR negative-binomial GLM per neighbourhood; this
implementation uses the binomial normal approximation against the
global condition proportion (with BH correction), which matches the
GLM's calls on balanced designs and keeps the op closed-form.
(Replicate-aware variance — Milo's per-sample aggregation — is NOT
implemented; treat the FDRs as composition-shift calls, not
replicate-backed inference.)

TPU design: a neighbourhood is each index cell's kNN set (plus
itself) — per-neighbourhood condition counts are ONE gather+sum over
the edge list per condition, the same k-sparse primitive every graph
op here uses.  The z/p/FDR bookkeeping is O(n) host math.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..data.dataset import CellData
from ..registry import register


def _nbhd_counts(idx, flags, device):
    """Per-index-cell count of flagged neighbours (self included)."""
    if device:
        safe = jnp.where(idx < 0, 0, idx)
        f = jnp.asarray(flags, jnp.float32)
        gathered = jnp.where(idx >= 0, jnp.take(f, safe), 0.0)
        return np.asarray(jnp.sum(gathered, axis=1) + f[: idx.shape[0]])
    f = np.asarray(flags, np.float64)
    safe = np.where(idx >= 0, idx, 0)
    gathered = np.where(idx >= 0, f[safe], 0.0)
    return gathered.sum(axis=1) + f[: idx.shape[0]]


def _differential_abundance(data: CellData, condition_key, groups,
                            device):
    n = data.n_cells
    if "knn_indices" not in data.obsp:
        raise KeyError("da.neighborhoods: run neighbors.knn first")
    if condition_key not in data.obs:
        raise KeyError(f"da.neighborhoods: obs has no {condition_key!r}")
    cond = np.asarray(data.obs[condition_key]).astype(str)[:n]
    levels = sorted(set(cond.tolist())) if groups is None else list(groups)
    if len(levels) != 2:
        raise ValueError(
            f"da.neighborhoods compares exactly 2 condition levels, "
            f"got {levels}")
    a, b = levels
    idx = np.asarray(data.obsp["knn_indices"])[:n]
    na = _nbhd_counts(idx, cond == a, device)
    nb = _nbhd_counts(idx, cond == b, device)
    tot = na + nb
    p0 = float((cond == a).sum()) / max(len(cond), 1)
    # binomial z of the neighbourhood's A-fraction vs the global
    # proportion (the documented Milo-GLM simplification)
    se = np.sqrt(np.maximum(tot * p0 * (1 - p0), 1e-12))
    z = (na - tot * p0) / se
    from scipy import stats as sps

    pvals = 2.0 * sps.norm.sf(np.abs(z))
    order = np.argsort(pvals)
    q = pvals[order] * len(pvals) / np.arange(1, len(pvals) + 1)
    q = np.minimum.accumulate(q[::-1])[::-1]
    fdr = np.empty_like(q)
    fdr[order] = np.clip(q, 0, 1)
    lfc = np.log2((na + 0.5) / (nb + 0.5)
                  / (p0 / max(1 - p0, 1e-12)))
    return (data.with_obs(
        da_score=z.astype(np.float32),
        da_fdr=fdr.astype(np.float32),
        da_logfc=lfc.astype(np.float32))
        .with_uns(da_conditions=[a, b]))


@register("da.neighborhoods", backend="tpu")
def da_tpu(data: CellData, condition_key: str = "condition",
           groups=None) -> CellData:
    """Adds obs["da_score"] (signed z, + = enriched for the first
    level), obs["da_fdr"], obs["da_logfc"]; uns["da_conditions"].
    Each cell's kNN neighbourhood is its Milo-style index set."""
    return _differential_abundance(data, condition_key, groups,
                                   device=True)


@register("da.neighborhoods", backend="cpu")
def da_cpu(data: CellData, condition_key: str = "condition",
           groups=None) -> CellData:
    return _differential_abundance(data, condition_key, groups,
                                   device=False)
