"""PCA transforms: ``pca.randomized`` (Halko randomized SVD) and
``pca.exact`` (small-data oracle).

Reference parity: BASELINE.json configs[3] — "50-PC randomized PCA".

TPU design: the only large ops are the two sparse matmul primitives
(``spmm``: gather+einsum, ``spmm_t``: segment-sum) plus small QR/SVD
factorizations of (n × L) / (L × G) matrices that XLA handles on-chip.
Mean-centering never densifies X — it is applied as a rank-1
correction inside the iteration:

    (X - 1 μᵀ) Ω      = X Ω - 1 (μᵀ Ω)
    (X - 1 μᵀ)ᵀ Q     = Xᵀ Q - μ (1ᵀ Q)
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..data.dataset import CellData
from ..data.sparse import SparseCells, gene_sum, row_sum, spmm, spmm_t
from ..registry import register


def _warn_if_narrowed(n_components: int, data) -> None:
    lim = min(data.n_cells, data.n_genes)
    if n_components > lim:
        import warnings

        warnings.warn(
            f"pca.randomized: n_components={n_components} exceeds "
            f"min(n_cells, n_genes)={lim}; returning {lim} components",
            stacklevel=3)


def _center_matvec(X, mu, V):
    """(X - 1 μᵀ) @ V with padded rows forced to zero."""
    if isinstance(X, SparseCells):
        out = spmm(X, V) - jnp.outer(jnp.ones(X.rows_padded, V.dtype), mu @ V)
        return jnp.where(X.row_mask()[:, None], out, 0.0)
    return X @ V - jnp.outer(jnp.ones(X.shape[0], V.dtype), mu @ V)


def _center_rmatvec(X, mu, Q):
    """(X - 1 μᵀ)ᵀ @ Q; assumes padded rows of Q are zero."""
    if isinstance(X, SparseCells):
        colsum = jnp.sum(jnp.where(X.row_mask()[:, None], Q, 0.0), axis=0)
        return spmm_t(X, Q) - jnp.outer(mu, colsum)
    return X.T @ Q - jnp.outer(mu, jnp.sum(Q, axis=0))


def _gene_mean(X) -> jax.Array:
    if isinstance(X, SparseCells):
        return gene_sum(X) / X.n_cells
    return jnp.mean(X, axis=0)


def cholesky_qr(Y: jax.Array, iters: int = 2) -> jax.Array:
    """Orthonormalise the columns of ``Y`` via CholeskyQR2.

    Distributed-friendly alternative to Householder QR: the only
    cross-row reduction is the (L, L) Gram matrix, which GSPMD turns
    into a single ``psum`` when Y is row-sharded over the mesh — no
    all-gather of the tall matrix.  Two iterations recover Householder-
    level orthogonality for the moderately conditioned iterates that
    arise inside randomized PCA.
    """
    for _ in range(iters):
        # HIGHEST: TPU would otherwise run the f32 Gram matmul in
        # bf16 passes; CholeskyQR error ~ κ(Y)²·ε, and bf16-level ε
        # drives the Gram matrix indefinite → NaN factorisation.
        G = jnp.dot(Y.T, Y, preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST)
        G = G + 1e-7 * jnp.trace(G) / G.shape[0] * jnp.eye(G.shape[0], dtype=G.dtype)
        R = jnp.linalg.cholesky(G, upper=True)
        Y = jax.lax.linalg.triangular_solve(
            R, Y, left_side=False, lower=False
        )
    return Y


def _orthonormalize(Y, method: str):
    if method == "cholesky":
        return cholesky_qr(Y)
    Q, _ = jnp.linalg.qr(Y)
    return Q


@partial(jax.jit, static_argnames=("n_components", "oversample", "n_iter",
                                   "center", "qr_method"))
def randomized_pca_arrays(X, key, n_components: int = 50, oversample: int = 10,
                          n_iter: int = 2, center: bool = True,
                          qr_method: str = "cholesky"):
    """Core randomized PCA.  X: SparseCells or dense (n, G).

    Returns (scores (rows, k), components (G, k), explained_var (k,),
    mean (G,)).  ``qr_method``: "cholesky" (CholeskyQR2; row-sharding
    friendly, default) or "householder" (jnp.linalg.qr).
    """
    G = X.n_genes if isinstance(X, SparseCells) else X.shape[1]
    n = X.n_cells if isinstance(X, SparseCells) else X.shape[0]
    # the sketch cannot be wider than the matrix: L > min(n, G) makes
    # the Gram matrix singular and CholeskyQR2 returns NaN scores
    # (found via a 14-gene velocity fixture whose NaNs silently
    # flipped a downstream terminal-state call)
    L = min(n_components + oversample, G, n)
    k = min(n_components, L)
    dtype = X.data.dtype if isinstance(X, SparseCells) else X.dtype
    mu = _gene_mean(X) if center else jnp.zeros((G,), dtype)

    omega = jax.random.normal(key, (G, L), dtype)
    Y = _center_matvec(X, mu, omega)  # (rows, L)
    Q = _orthonormalize(Y, qr_method)
    for _ in range(n_iter):
        Z = _center_rmatvec(X, mu, Q)  # (G, L)
        Qz = _orthonormalize(Z, qr_method)
        Y = _center_matvec(X, mu, Qz)
        Q = _orthonormalize(Y, qr_method)
    B = _center_rmatvec(X, mu, Q).T  # (L, G)
    U_b, S, Vt = jnp.linalg.svd(B, full_matrices=False)
    scores = (Q @ U_b[:, :k]) * S[:k]
    components = Vt[:k].T  # (G, k)
    explained = (S[:k] ** 2) / max(n - 1, 1)
    return scores, components, explained, mu


@register("pca.randomized", backend="tpu", fusable=True,
          mem_cost=4.0)
def pca_randomized_tpu(data: CellData, n_components: int = 50,
                       oversample: int = 10, n_iter: int = 2,
                       center: bool = True, seed: int = 0,
                       qr_method: str = "cholesky") -> CellData:
    """Adds obsm["X_pca"], varm["PCs"], uns["pca_explained_variance"].
    Requesting more components than min(n_cells, n_genes) returns the
    achievable width with a warning (the sketch clamp below)."""
    _warn_if_narrowed(n_components, data)
    key = jax.random.PRNGKey(seed)
    scores, comps, expl, mu = randomized_pca_arrays(
        data.X, key, n_components=n_components, oversample=oversample,
        n_iter=n_iter, center=center, qr_method=qr_method,
    )
    return data.with_obsm(X_pca=scores).with_varm(PCs=comps).with_uns(
        pca_explained_variance=expl, pca_mean=mu,
    )


@register("pca.randomized", backend="cpu")
def pca_randomized_cpu(data: CellData, n_components: int = 50,
                       oversample: int = 10, n_iter: int = 4,
                       center: bool = True, seed: int = 0) -> CellData:
    import scipy.sparse as sp

    _warn_if_narrowed(n_components, data)

    X = data.X
    rng = np.random.default_rng(seed)
    n, G = X.shape
    # same sketch-width clamp as the tpu path (L > min(n, G) is
    # rank-deficient; np.linalg.qr tolerates it but the trailing
    # components are garbage directions)
    L = min(n_components + oversample, G, n)
    k = min(n_components, L)
    if sp.issparse(X):
        mu = np.asarray(X.mean(axis=0)).ravel() if center else np.zeros(G)
        mv = lambda V: X @ V - np.outer(np.ones(n), mu @ V)
        rmv = lambda Q: X.T @ Q - np.outer(mu, Q.sum(axis=0))
    else:
        X = np.asarray(X, dtype=np.float64)
        mu = X.mean(axis=0) if center else np.zeros(G)
        mv = lambda V: (X - mu) @ V
        rmv = lambda Q: (X - mu).T @ Q
    omega = rng.standard_normal((G, L))
    Q, _ = np.linalg.qr(mv(omega))
    for _ in range(n_iter):
        Qz, _ = np.linalg.qr(rmv(Q))
        Q, _ = np.linalg.qr(mv(Qz))
    B = rmv(Q).T
    U_b, S, Vt = np.linalg.svd(B, full_matrices=False)
    scores = (Q @ U_b[:, :k]) * S[:k]
    comps = Vt[:k].T
    expl = (S[:k] ** 2) / max(n - 1, 1)
    return data.with_obsm(X_pca=scores.astype(np.float32)).with_varm(
        PCs=comps.astype(np.float32)
    ).with_uns(
        pca_explained_variance=expl.astype(np.float32),
        pca_mean=mu.astype(np.float32),
    )


@register("pca.exact", backend="cpu")
def pca_exact_cpu(data: CellData, n_components: int = 50,
                  center: bool = True) -> CellData:
    """Full-SVD oracle for tests (densifies; small data only)."""
    import scipy.sparse as sp

    X = data.X
    if sp.issparse(X):
        X = np.asarray(X.todense())
    X = np.asarray(X, dtype=np.float64)
    mu = X.mean(axis=0) if center else np.zeros(X.shape[1])
    U, S, Vt = np.linalg.svd(X - mu, full_matrices=False)
    k = n_components
    scores = U[:, :k] * S[:k]
    return data.with_obsm(X_pca=scores.astype(np.float32)).with_varm(
        PCs=Vt[:k].T.astype(np.float32)
    ).with_uns(
        pca_explained_variance=((S[:k] ** 2) / max(X.shape[0] - 1, 1)).astype(
            np.float32
        ),
        pca_mean=mu.astype(np.float32),
    )


@register("pca.exact", backend="tpu")
def pca_exact_tpu(data: CellData, n_components: int = 50,
                  center: bool = True) -> CellData:
    X = data.X
    if isinstance(X, SparseCells):
        Xd = X.to_dense()
    else:
        Xd = jnp.asarray(X)
    mu = jnp.mean(Xd, axis=0) if center else jnp.zeros(Xd.shape[1], Xd.dtype)
    U, S, Vt = jnp.linalg.svd(Xd - mu, full_matrices=False)
    k = n_components
    scores = U[:, :k] * S[:k]
    return data.with_obsm(X_pca=scores).with_varm(PCs=Vt[:k].T).with_uns(
        pca_explained_variance=(S[:k] ** 2) / max(data.n_cells - 1, 1),
        pca_mean=mu,
    )
