"""PCA transforms: ``pca.randomized`` (Halko randomized SVD) and
``pca.exact`` (small-data oracle).

Reference parity: BASELINE.json configs[3] — "50-PC randomized PCA".

TPU design: the only large ops are the two sparse matmul primitives
(``spmm``: gather+einsum, ``spmm_t``: segment-sum) plus small QR/SVD
factorizations of (n × L) / (L × G) matrices that XLA handles on-chip.
Mean-centering never densifies X — it is applied as a rank-1
correction inside the iteration:

    (X - 1 μᵀ) Ω      = X Ω - 1 (μᵀ Ω)
    (X - 1 μᵀ)ᵀ Q     = Xᵀ Q - μ (1ᵀ Q)
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..data.dataset import CellData
from ..data.sparse import SparseCells, gene_sum, row_sum, spmm, spmm_t
from ..registry import register

from .. import buckets as _buckets


def _warn_if_narrowed(n_components: int, data) -> None:
    lim = min(data.n_cells, data.n_genes)
    if n_components > lim:
        import warnings

        warnings.warn(
            f"pca.randomized: n_components={n_components} exceeds "
            f"min(n_cells, n_genes)={lim}; returning {lim} components",
            stacklevel=3)


def _center_matvec(X, mu, V, row_valid=None):
    """(X - 1 μᵀ) @ V with padded rows forced to zero.  ``row_valid``
    (TRACED bucket row mask, buckets.py) overrides the static row
    mask — centering writes ``-μᵀV`` into every padded row, which must
    not leak into the iteration's inner products."""
    if isinstance(X, SparseCells):
        rm = X.row_mask() if row_valid is None else row_valid
        out = spmm(X, V) - jnp.outer(jnp.ones(X.rows_padded, V.dtype), mu @ V)
        return jnp.where(rm[:, None], out, 0.0)
    out = X @ V - jnp.outer(jnp.ones(X.shape[0], V.dtype), mu @ V)
    if row_valid is not None:
        out = jnp.where(row_valid[:, None], out, 0.0)
    return out


def _center_rmatvec(X, mu, Q, row_valid=None):
    """(X - 1 μᵀ)ᵀ @ Q; assumes padded rows of Q are zero."""
    if isinstance(X, SparseCells):
        rm = X.row_mask() if row_valid is None else row_valid
        colsum = jnp.sum(jnp.where(rm[:, None], Q, 0.0), axis=0)
        return spmm_t(X, Q) - jnp.outer(mu, colsum)
    return X.T @ Q - jnp.outer(mu, jnp.sum(Q, axis=0))


def _gene_mean(X, n_valid=None) -> jax.Array:
    if isinstance(X, SparseCells):
        if n_valid is None:
            return gene_sum(X) / X.n_cells
        return gene_sum(X) / jnp.maximum(
            jnp.asarray(n_valid, X.data.dtype), 1.0)
    if n_valid is None:
        return jnp.mean(X, axis=0)
    # bucketized dense: padding rows are zero, only the count corrects
    return jnp.sum(X, axis=0) / jnp.maximum(
        jnp.asarray(n_valid, X.dtype), 1.0)


def cholesky_qr(Y: jax.Array, iters: int = 2) -> jax.Array:
    """Orthonormalise the columns of ``Y`` via CholeskyQR2.

    Distributed-friendly alternative to Householder QR: the only
    cross-row reduction is the (L, L) Gram matrix, which GSPMD turns
    into a single ``psum`` when Y is row-sharded over the mesh — no
    all-gather of the tall matrix.  Two iterations recover Householder-
    level orthogonality for the moderately conditioned iterates that
    arise inside randomized PCA.
    """
    for _ in range(iters):
        # HIGHEST: TPU would otherwise run the f32 Gram matmul in
        # bf16 passes; CholeskyQR error ~ κ(Y)²·ε, and bf16-level ε
        # drives the Gram matrix indefinite → NaN factorisation.
        G = jnp.dot(Y.T, Y, preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST)
        G = G + 1e-7 * jnp.trace(G) / G.shape[0] * jnp.eye(G.shape[0], dtype=G.dtype)
        R = jnp.linalg.cholesky(G, upper=True)
        Y = jax.lax.linalg.triangular_solve(
            R, Y, left_side=False, lower=False
        )
    return Y


def _orthonormalize(Y, method: str):
    if method == "cholesky":
        return cholesky_qr(Y)
    Q, _ = jnp.linalg.qr(Y)
    return Q


def _sketch_omega(key, G: int, L: int, dtype) -> jax.Array:
    """Random sketch matrix with PER-GENE streams: row g is drawn from
    ``fold_in(key, g)`` rather than slicing one (G, L) draw.  This makes
    omega's first G₀ rows independent of G, so a dataset padded from G₀
    to a gene bucket G sees bitwise the same sketch on its valid genes —
    padded gene rows multiply all-zero columns and contribute nothing.
    """
    keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(
        key, jnp.arange(G, dtype=jnp.uint32))
    return jax.vmap(lambda kg: jax.random.normal(kg, (L,), dtype))(keys)


@partial(jax.jit, static_argnames=("n_components", "oversample", "n_iter",
                                   "center", "qr_method"))
def randomized_pca_arrays(X, key, n_components: int = 50, oversample: int = 10,
                          n_iter: int = 2, center: bool = True,
                          qr_method: str = "cholesky",
                          row_valid=None, n_valid=None):
    """Core randomized PCA.  X: SparseCells or dense (n, G).

    Returns (scores (rows, k), components (G, k), explained_var (k,),
    mean (G,)).  ``qr_method``: "cholesky" (CholeskyQR2; row-sharding
    friendly, default) or "householder" (jnp.linalg.qr).

    ``row_valid``/``n_valid`` (traced bucket row mask + valid-row count,
    see buckets.py) restrict the factorization to the valid rows of a
    bucket-padded matrix.  Note the sketch width L clamps to the BUCKET
    dims, not the valid dims: when the valid region is narrower than
    ``n_components + oversample`` the trailing components are garbage
    directions, exactly like an unpadded matrix of bucket width.
    """
    G = X.n_genes if isinstance(X, SparseCells) else X.shape[1]
    n = X.n_cells if isinstance(X, SparseCells) else X.shape[0]
    # the sketch cannot be wider than the matrix: L > min(n, G) makes
    # the Gram matrix singular and CholeskyQR2 returns NaN scores
    # (found via a 14-gene velocity fixture whose NaNs silently
    # flipped a downstream terminal-state call)
    L = min(n_components + oversample, G, n)
    k = min(n_components, L)
    dtype = X.data.dtype if isinstance(X, SparseCells) else X.dtype
    mu = _gene_mean(X, n_valid) if center else jnp.zeros((G,), dtype)

    omega = _sketch_omega(key, G, L, dtype)
    Y = _center_matvec(X, mu, omega, row_valid)  # (rows, L)
    Q = _orthonormalize(Y, qr_method)
    for _ in range(n_iter):
        Z = _center_rmatvec(X, mu, Q, row_valid)  # (G, L)
        Qz = _orthonormalize(Z, qr_method)
        Y = _center_matvec(X, mu, Qz, row_valid)
        Q = _orthonormalize(Y, qr_method)
    B = _center_rmatvec(X, mu, Q, row_valid).T  # (L, G)
    U_b, S, Vt = jnp.linalg.svd(B, full_matrices=False)
    scores = (Q @ U_b[:, :k]) * S[:k]
    components = Vt[:k].T  # (G, k)
    if n_valid is None:
        explained = (S[:k] ** 2) / max(n - 1, 1)
    else:
        explained = (S[:k] ** 2) / jnp.maximum(
            jnp.asarray(n_valid, S.dtype) - 1.0, 1.0)
    return scores, components, explained, mu


@register("pca.randomized", backend="tpu", fusable=True,
          mem_cost=4.0, mask_aware=True)
def pca_randomized_tpu(data: CellData, n_components: int = 50,
                       oversample: int = 10, n_iter: int = 2,
                       center: bool = True, seed: int = 0,
                       qr_method: str = "cholesky") -> CellData:
    """Adds obsm["X_pca"], varm["PCs"], uns["pca_explained_variance"].
    Requesting more components than min(n_cells, n_genes) returns the
    achievable width with a warning (the sketch clamp below).

    Mask-aware: bucket-padded rows never enter the factorization (the
    centered matvec zeroes them, so the Q basis and scores are zero
    there) and the per-gene sketch streams make the valid-gene rows of
    omega independent of the gene bucket.  Padded results agree with
    unpadded up to iterative-solver tolerance (the L-row Gram/SVD
    reductions run over bucket-shaped operands whose padding is zero —
    same values, reassociated arithmetic).
    """
    _warn_if_narrowed(n_components, data)
    key = jax.random.PRNGKey(seed)
    masks = _buckets.masks_of(data)
    row_valid = None if masks is None else jnp.asarray(masks.row)
    n_valid = None if masks is None else masks.n_cells
    scores, comps, expl, mu = randomized_pca_arrays(
        data.X, key, n_components=n_components, oversample=oversample,
        n_iter=n_iter, center=center, qr_method=qr_method,
        row_valid=row_valid, n_valid=n_valid,
    )
    return data.with_obsm(X_pca=scores).with_varm(PCs=comps).with_uns(
        pca_explained_variance=expl, pca_mean=mu,
    )


@register("pca.randomized", backend="cpu")
def pca_randomized_cpu(data: CellData, n_components: int = 50,
                       oversample: int = 10, n_iter: int = 4,
                       center: bool = True, seed: int = 0) -> CellData:
    import scipy.sparse as sp

    _warn_if_narrowed(n_components, data)

    X = data.X
    rng = np.random.default_rng(seed)
    n, G = X.shape
    # same sketch-width clamp as the tpu path (L > min(n, G) is
    # rank-deficient; np.linalg.qr tolerates it but the trailing
    # components are garbage directions)
    L = min(n_components + oversample, G, n)
    k = min(n_components, L)
    if sp.issparse(X):
        mu = np.asarray(X.mean(axis=0)).ravel() if center else np.zeros(G)
        mv = lambda V: X @ V - np.outer(np.ones(n), mu @ V)
        rmv = lambda Q: X.T @ Q - np.outer(mu, Q.sum(axis=0))
    else:
        X = np.asarray(X, dtype=np.float64)
        mu = X.mean(axis=0) if center else np.zeros(G)
        mv = lambda V: (X - mu) @ V
        rmv = lambda Q: (X - mu).T @ Q
    omega = rng.standard_normal((G, L))
    Q, _ = np.linalg.qr(mv(omega))
    for _ in range(n_iter):
        Qz, _ = np.linalg.qr(rmv(Q))
        Q, _ = np.linalg.qr(mv(Qz))
    B = rmv(Q).T
    U_b, S, Vt = np.linalg.svd(B, full_matrices=False)
    scores = (Q @ U_b[:, :k]) * S[:k]
    comps = Vt[:k].T
    expl = (S[:k] ** 2) / max(n - 1, 1)
    return data.with_obsm(X_pca=scores.astype(np.float32)).with_varm(
        PCs=comps.astype(np.float32)
    ).with_uns(
        pca_explained_variance=expl.astype(np.float32),
        pca_mean=mu.astype(np.float32),
    )


@register("pca.exact", backend="cpu")
def pca_exact_cpu(data: CellData, n_components: int = 50,
                  center: bool = True) -> CellData:
    """Full-SVD oracle for tests (densifies; small data only)."""
    import scipy.sparse as sp

    X = data.X
    if sp.issparse(X):
        X = np.asarray(X.todense())
    X = np.asarray(X, dtype=np.float64)
    mu = X.mean(axis=0) if center else np.zeros(X.shape[1])
    U, S, Vt = np.linalg.svd(X - mu, full_matrices=False)
    k = n_components
    scores = U[:, :k] * S[:k]
    return data.with_obsm(X_pca=scores.astype(np.float32)).with_varm(
        PCs=Vt[:k].T.astype(np.float32)
    ).with_uns(
        pca_explained_variance=((S[:k] ** 2) / max(X.shape[0] - 1, 1)).astype(
            np.float32
        ),
        pca_mean=mu.astype(np.float32),
    )


@register("pca.exact", backend="tpu")
def pca_exact_tpu(data: CellData, n_components: int = 50,
                  center: bool = True) -> CellData:
    X = data.X
    if isinstance(X, SparseCells):
        Xd = X.to_dense()
    else:
        Xd = jnp.asarray(X)
    mu = jnp.mean(Xd, axis=0) if center else jnp.zeros(Xd.shape[1], Xd.dtype)
    U, S, Vt = jnp.linalg.svd(Xd - mu, full_matrices=False)
    k = n_components
    scores = U[:, :k] * S[:k]
    return data.with_obsm(X_pca=scores).with_varm(PCs=Vt[:k].T).with_uns(
        pca_explained_variance=(S[:k] ** 2) / max(data.n_cells - 1, 1),
        pca_mean=mu,
    )
