"""``wishbone.run`` — bifurcating-trajectory detection.

Capability parity: Wishbone (Setty et al. 2016), the Pe'er-lab
trajectory tool that preceded Palantir — orders cells along a
differentiation axis from a chosen start cell and splits post-branch
cells into two arms.  The reference source was unavailable
(/root/reference empty — SURVEY.md §0); the published algorithm is the
contract:

1. sample ``n_waypoints`` by greedy max-min farthest-point traversal
   of the embedding (deterministic given ``seed`` for the first pick);
2. shortest-path graph distances from the start cell and every
   waypoint over the symmetrised kNN graph (edge weights = embedding
   distances);
3. initial trajectory = distance from start; iterate: each waypoint w
   re-times every cell from its own perspective,
   ``V_w(i) = τ(w) ± d_w(i)`` (sign: whether i lies before or after w
   on the current trajectory), and the trajectory is the
   Gaussian-weighted average of perspectives; repeat until stable;
4. branch detection: the disagreement ``Q_w(i) = V_w(i) − τ(i)``
   splits waypoints into two post-branch arms via the sign structure
   of the waypoint-waypoint disagreement correlation (second
   eigenvector); cells inherit the branch of their nearest waypoints;
   cells before the detected branch point stay on the trunk.

TPU design: the one heavy stage is multi-source shortest paths.
Dijkstra's priority queue is hostile to SIMD; instead the device runs
**min-plus Bellman–Ford relaxation over the padded kNN edge list** —
``D ← min(D, min_j D[nbr_j] + w_j)`` — a gather+min per sweep,
vectorised over all waypoints at once (chunked so the (n, K, W) gather
stays in VMEM-friendly tiles), inside ``lax.while_loop`` with an
on-device convergence test.  On a kNN graph the number of sweeps is
the graph's hop-diameter (tens, not thousands).  The CPU backend runs
``scipy.sparse.csgraph.dijkstra`` on the identical symmetrised graph —
the two backends converge to the same distances, so the downstream
(host) trajectory/branch logic is shared verbatim.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..data.dataset import CellData
from ..registry import register

_WCHUNK = 32


def _sym_edges(idx, dist):
    """Undirected edge list: every directed kNN edge plus its reverse,
    per-row padded with -1.  Returns (idx2 (n, K2), w2 (n, K2))."""
    n, k = idx.shape
    rows = np.repeat(np.arange(n), k)
    cols = idx.reshape(-1)
    vals = dist.reshape(-1)
    keep = cols >= 0
    rows, cols, vals = rows[keep], cols[keep], vals[keep]
    # both directions, deduplicated by (min, max) pair keeping min w
    a = np.concatenate([rows, cols])
    b = np.concatenate([cols, rows])
    w = np.concatenate([vals, vals])
    order = np.lexsort((b, a))
    a, b, w = a[order], b[order], w[order]
    first = np.ones(len(a), bool)
    first[1:] = (a[1:] != a[:-1]) | (b[1:] != b[:-1])
    a, b, w = a[first], b[first], w[first]
    counts = np.bincount(a, minlength=n)
    K2 = int(counts.max())
    idx2 = np.full((n, K2), -1, np.int32)
    w2 = np.zeros((n, K2), np.float32)
    starts = np.zeros(n + 1, np.int64)
    np.cumsum(counts, out=starts[1:])
    slot = np.arange(len(a)) - starts[a]
    idx2[a, slot] = b.astype(np.int32)
    w2[a, slot] = w.astype(np.float32)
    return idx2, w2


@partial(jax.jit, static_argnames=("max_sweeps",))
def _minplus_round(idx2, w2, D0, max_sweeps: int = 128):
    """Up to ``max_sweeps`` min-plus relaxation sweeps starting from
    distance state D0.  Each sweep advances the frontier ONE hop, so a
    single round bounds progress at max_sweeps hops — the host loop in
    _distances_tpu re-invokes until converged.  Returns (D, changed):
    changed=True means the last sweep still relaxed something."""
    INF = jnp.float32(3e38)
    safe = jnp.where(idx2 < 0, 0, idx2)
    wpad = jnp.where(idx2 < 0, INF, w2)

    def cond(state):
        it, _, changed = state
        return jnp.logical_and(it < max_sweeps, changed)

    def body(state):
        it, D, _ = state
        nbr = jnp.take(D, safe, axis=0)              # (n, K2, n_src)
        relax = jnp.min(nbr + wpad[:, :, None], axis=1)
        Dn = jnp.minimum(D, relax)
        return it + 1, Dn, jnp.any(Dn < D)

    _, D, changed = jax.lax.while_loop(cond, body, (0, D0, True))
    return D, changed


def _distances_tpu(idx2, w2, sources):
    n = idx2.shape[0]
    out = []
    src = jnp.asarray(sources, jnp.int32)
    idx2_d, w2_d = jnp.asarray(idx2), jnp.asarray(w2)
    INF = jnp.float32(3e38)
    for lo in range(0, len(sources), _WCHUNK):
        pad = min(_WCHUNK, len(sources) - lo)
        chunk = jnp.full((_WCHUNK,), int(sources[0]), jnp.int32
                         ).at[:pad].set(src[lo: lo + pad])
        D = jnp.full((n, _WCHUNK), INF).at[chunk,
                                           jnp.arange(_WCHUNK)].set(0.0)
        # host loop of device rounds: one round advances <=128 hops,
        # so graphs whose hop-diameter exceeds any fixed cap still
        # converge (n-1 hops is the true upper bound; a graph that
        # needs them all is a path, 8 rounds per 1k cells)
        for _ in range(-(-max(n - 1, 1) // 128)):
            D, changed = _minplus_round(idx2_d, w2_d, D)
            if not bool(changed):
                break
        out.append(np.asarray(D[:, :pad], np.float64))
    return np.concatenate(out, axis=1)  # (n, n_src)


def _distances_cpu(idx2, w2, sources):
    import scipy.sparse as sp
    from scipy.sparse.csgraph import dijkstra

    n, K2 = idx2.shape
    rows = np.repeat(np.arange(n), K2)
    cols = idx2.reshape(-1)
    vals = w2.reshape(-1)
    keep = cols >= 0
    G = sp.csr_matrix((vals[keep], (rows[keep], cols[keep])),
                      shape=(n, n))
    return dijkstra(G, directed=False, indices=np.asarray(sources)).T


def _maxmin_waypoints(E, n_waypoints, start, rng):
    """Greedy farthest-point sampling in the embedding (the paper's
    coverage goal) seeded at the start cell."""
    n = len(E)
    n_waypoints = min(n_waypoints, n)
    chosen = [int(start)]
    d = np.linalg.norm(E - E[start], axis=1)
    while len(chosen) < n_waypoints:
        nxt = int(np.argmax(d))
        if d[nxt] <= 0:
            nxt = int(rng.integers(0, n))
        chosen.append(nxt)
        d = np.minimum(d, np.linalg.norm(E - E[nxt], axis=1))
    return np.array(chosen, np.int64)


def _wishbone_host(D, waypoints, branch, n_iter, sigma_scale):
    """Shared trajectory + branch logic on fetched distances.
    D: (n, n_way) distances from each waypoint; waypoints[0] == start.
    """
    n, n_way = D.shape
    tau = D[:, 0].copy()  # distance from start
    sigma = sigma_scale * np.mean(D[waypoints, 0]) + 1e-12
    Wgt = np.exp(-0.5 * (D / sigma) ** 2) + 1e-30
    Wgt /= Wgt.sum(axis=1, keepdims=True)
    V = np.zeros_like(D)
    for _ in range(n_iter):
        tau_w = tau[waypoints]  # (n_way,)
        before = tau[:, None] < tau_w[None, :]
        V = np.where(before, tau_w[None, :] - D, tau_w[None, :] + D)
        V[:, 0] = D[:, 0]  # the start's perspective is the raw distance
        tau_new = (Wgt * V).sum(axis=1)
        if np.max(np.abs(tau_new - tau)) < 1e-6 * max(tau.max(), 1e-12):
            tau = tau_new
            break
        tau = tau_new
    tau = tau - tau.min()
    if not branch:
        return tau, None, None
    # disagreement structure across waypoints: row w of M = Q_w(·)
    # restricted to waypoints is ~zero within w's own arm and on the
    # trunk, and large exactly at the OTHER arm's columns.  The two
    # arms' rows therefore have (nearly) DISJOINT supports — cosine
    # ~1 within an arm, ~0 across — so a cosine 2-means on the
    # row-normalised disagreement vectors separates them cleanly
    # (a single correlation eigenvector cannot: disjoint positive
    # blocks are orthogonal, not anti-correlated).  Trunk rows have
    # small norm and are gated out before clustering.
    Q = V - tau[:, None]                      # (n, n_way)
    Qw = np.abs(Q[waypoints].T)               # rows: waypoint views
    rn = np.linalg.norm(Qw, axis=1)
    confident = rn > 0.3 * rn.max()
    R = Qw / np.maximum(rn, 1e-12)[:, None]
    seed1 = int(np.argmax(rn))
    cos_to_1 = R @ R[seed1]
    cand = np.where(confident)[0]
    seed2 = int(cand[np.argmin(np.abs(cos_to_1[cand]))])
    c1, c2 = R[seed1].copy(), R[seed2].copy()
    lab = np.zeros(n_way, np.int32)
    for _ in range(10):
        s1, s2 = R @ c1, R @ c2
        lab = np.where(s1 >= s2, 1, 2).astype(np.int32)
        for b, c in ((1, c1), (2, c2)):
            m = confident & (lab == b)
            if m.any():
                v = R[m].mean(axis=0)
                c[:] = v / max(np.linalg.norm(v), 1e-12)
    tau_w = tau[waypoints]
    m1 = confident & (lab == 1)
    m2 = confident & (lab == 2)
    if not m1.any() or not m2.any():
        return tau, np.zeros(n, np.int32), float(tau.max())
    # branch point from the disagreement geometry: for a cross-arm
    # pair (w, u) the perspectives disagree by |Q_w(u)| ≈
    # 2·(min(τ_w, τ_u) − bt) — each pair hands back an estimate of bt,
    # and the median over confident cross-arm pairs is robust to the
    # noisy near-branch pairs
    iw, iu = np.where(m1)[0], np.where(m2)[0]
    tmin = np.minimum(tau_w[iw][:, None], tau_w[iu][None, :])
    bt_est = tmin - 0.5 * Qw[iw][:, iu]
    branch_time = float(np.median(bt_est))
    # waypoint labels: trunk before the branch point, arm label after.
    # The cutoff sits at 92% of the estimated branch time (the
    # pair-median estimator biases bt slightly late).  Past the
    # cutoff, CONFIDENT waypoints take their own cluster label; weak
    # ones (just past the branch, where 2-means is noise) inherit the
    # label of their nearest confident waypoint — their own label
    # would bleed cross-arm errors into the cells around them
    Dw = D[waypoints]                         # waypoint x waypoint
    conf_idx = np.where(confident)[0]
    nearest_conf = conf_idx[np.argmin(Dw[:, conf_idx], axis=1)]
    lab_f = np.where(confident, lab, lab[nearest_conf])
    way_branch = np.where(tau_w <= 0.92 * branch_time, 0,
                          lab_f).astype(np.int32)
    way_branch[0] = 0
    # cells: label of the nearest waypoint (graph distance) — a broad
    # Gaussian vote lets the trunk's many waypoints outvote a young
    # arm near the branch point; nearest-waypoint keeps the error
    # zone to one waypoint spacing
    cell_branch = way_branch[np.argmin(D, axis=1)].astype(np.int32)
    return tau, cell_branch, branch_time


def _run(data: CellData, start_cell, use_rep, n_waypoints, branch,
         n_iter, sigma_scale, seed, device):
    if "knn_indices" not in data.obsp:
        raise KeyError("wishbone.run: run neighbors.knn first")
    n = data.n_cells
    idx = np.asarray(data.obsp["knn_indices"])[:n]
    dist = np.asarray(data.obsp["knn_distances"], np.float64)[:n]
    rep = ("X_diffmap" if use_rep == "auto"
           and "X_diffmap" in data.obsm else
           "X_pca" if use_rep == "auto" else use_rep)
    E = np.asarray(data.obsm[rep], np.float64)[:n]
    if not 0 <= int(start_cell) < n:
        raise ValueError(f"wishbone.run: start_cell {start_cell} out of "
                         f"range [0, {n})")
    rng = np.random.default_rng(seed)
    waypoints = _maxmin_waypoints(E, n_waypoints, int(start_cell), rng)
    idx2, w2 = _sym_edges(idx, dist)
    D = (_distances_tpu if device else _distances_cpu)(idx2, w2,
                                                       waypoints)
    unreach = ~np.isfinite(D) | (D > 1e37)
    if unreach.any():
        # disconnected components sit at 2x the max finite distance —
        # far, but finite, so the weighting stays well-defined
        far = 2.0 * D[~unreach].max()
        D = np.where(unreach, far, D)
    tau, cell_branch, branch_time = _wishbone_host(
        D, waypoints, branch, n_iter, sigma_scale)
    out = data.with_obs(wishbone_trajectory=tau.astype(np.float32))
    uns = {"wishbone_waypoints": waypoints,
           "wishbone_start_cell": int(start_cell)}
    if branch:
        out = out.with_obs(wishbone_branch=cell_branch)
        uns["wishbone_branch_time"] = branch_time
    return out.with_uns(**uns)


@register("wishbone.run", backend="tpu")
def wishbone_tpu(data: CellData, start_cell: int, *,
                 use_rep: str = "auto", n_waypoints: int = 150,
                 branch: bool = True, n_iter: int = 25,
                 sigma_scale: float = 0.5, seed: int = 0) -> CellData:
    """Adds obs["wishbone_trajectory"] (pseudotime from start_cell),
    obs["wishbone_branch"] (0 = trunk, 1/2 = the two arms) and
    uns["wishbone_waypoints"/"wishbone_branch_time"].  Shortest paths
    run on device (min-plus edge-list relaxation); see module
    docstring."""
    return _run(data, start_cell, use_rep, n_waypoints, branch, n_iter,
                sigma_scale, seed, device=True)


@register("wishbone.run", backend="cpu")
def wishbone_cpu(data: CellData, start_cell: int, *,
                 use_rep: str = "auto", n_waypoints: int = 150,
                 branch: bool = True, n_iter: int = 25,
                 sigma_scale: float = 0.5, seed: int = 0) -> CellData:
    return _run(data, start_cell, use_rep, n_waypoints, branch, n_iter,
                sigma_scale, seed, device=False)
