"""``embed.tsne`` — t-SNE embedding, TPU-first.

Reference parity: the Pe'er-lab toolchain ships t-SNE as a standard
embedding step (dpeerlab/sctools source unavailable — SURVEY.md §0;
the algorithm is the published t-SNE method with the modern
kNN-sparse input affinities used by scanpy/FIt-SNE).

TPU design: CPU implementations avoid the O(n²) repulsion with
Barnes-Hut trees or FFT interpolation — data-dependent structures
that cannot map to XLA.  On a TPU the O(n²) term IS the fast path:
for every query block the pairwise ``1/(1+d²)`` kernel against all n
points is one MXU matmul (``d² = q² + c² − 2qc``), and the
force ``Σ_j w²(y_i−y_j)`` factors into ``y_i·Σw² − w²·Y`` — a second
matmul.  At 100k cells an iteration is ~2·n²·(dim+2) flops ≈ 4e10,
well under a second per iteration on one chip; no tree, no
approximation, exact gradients.

* input affinities: perplexity-calibrated Gaussian kernels on the
  kNN distances (vectorised bisection over all rows at once),
  symmetrised — the scanpy/FIt-SNE sparse-P formulation;
* attraction: gather + segment-sum over the directed kNN edges
  (same pattern as embed.umap);
* repulsion: blocked exact Q over all pairs via ``lax.map`` tiles;
* optimisation: classic momentum + per-coordinate gains schedule with
  early exaggeration, all inside one ``lax.scan``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..data.dataset import CellData
from ..registry import register


def _calibrate_p(dist2, perplexity, n_iter: int = 40, xp=np):
    """Per-row Gaussian bandwidths by bisection so the conditional
    distribution over the k neighbours has entropy log(perplexity).
    dist2: (n, k) squared distances, inf = missing.  Returns (n, k)
    conditional probabilities (rows sum to 1 over present entries)."""
    finite = xp.isfinite(dist2)
    d2 = xp.where(finite, dist2, 0.0)
    # shift per row so the smallest distance has weight 1 (numerics)
    d2 = d2 - xp.min(xp.where(finite, d2, xp.inf), axis=1, keepdims=True)
    target = np.log(perplexity)
    lo = xp.full(d2.shape[:1], 1e-8)
    hi = xp.full(d2.shape[:1], 1e8)
    for _ in range(n_iter):
        beta = xp.sqrt(lo * hi)  # geometric bisection over scales
        w = xp.where(finite, xp.exp(-d2 * beta[:, None]), 0.0)
        s = xp.maximum(w.sum(axis=1), 1e-30)
        p = w / s[:, None]
        h = -xp.sum(xp.where(p > 0, p * xp.log(xp.maximum(p, 1e-30)), 0.0),
                    axis=1)
        # entropy decreases in beta: too much entropy => raise beta
        hi_next = xp.where(h > target, hi, beta)
        lo_next = xp.where(h > target, beta, lo)
        lo, hi = lo_next, hi_next
    beta = xp.sqrt(lo * hi)
    w = xp.where(finite, xp.exp(-d2 * beta[:, None]), 0.0)
    return w / xp.maximum(w.sum(axis=1), 1e-30)[:, None]


@partial(jax.jit, static_argnames=("n_iter", "exaggeration_iter",
                                   "block", "graph_impl"))
def tsne_layout_arrays(knn_idx, P, init, n_iter: int = 500,
                       exaggeration: float = 12.0,
                       exaggeration_iter: int = 100,
                       learning_rate: float = 200.0,
                       block: int = 2048,
                       graph_impl: str | None = None):
    """Optimise the t-SNE layout.

    knn_idx: (n, k) neighbour ids (-1 padding); P: (n, k) symmetrised
    input affinities aligned with knn_idx (Σ P = 1 over all stored
    entries); init: (n, d) layout.  Returns the final (n, d) float32
    embedding.
    """
    n, k = knn_idx.shape
    dim = init.shape[1]
    dead = knn_idx < 0
    safe = jnp.where(dead, 0, knn_idx)
    p = jnp.where(dead, 0.0, P.astype(jnp.float32))

    nb = -(-n // block)
    pad = nb * block - n
    valid = jnp.arange(nb * block) < n

    def pad_rows(x):
        if pad == 0:
            return x
        return jnp.concatenate(
            [x, jnp.zeros((pad,) + x.shape[1:], x.dtype)])

    def repulsion(y):
        """Exact Σ_j q² Z (y_i − y_j) for all i, plus Z itself.

        Per tile: W = 1/(1+d²) against ALL points (one MXU matmul for
        the cross term), then the force factors as
        y_i·(Σ_j W²) − W²·Y (second matmul).  Returns ((n, d), Z).
        On a real TPU backend the whole sweep runs as ONE fused
        Pallas kernel (ops/pallas_graph.tsne_repulsion — the score
        tile never leaves VMEM); this blocked ``lax.map`` two-matmul
        form is its XLA twin and the off-TPU path."""
        from .pallas_graph import tsne_repulsion

        fused = tsne_repulsion(y, n, impl=graph_impl)
        if fused is not None:
            return fused
        yn2 = jnp.sum(y * y, axis=1)

        def per_block(args):
            yb, vb = args  # (block, d), (block,)
            s = jnp.dot(yb, y.T, preferred_element_type=jnp.float32,
                        precision=jax.lax.Precision.HIGHEST)
            d2 = jnp.maximum(
                jnp.sum(yb * yb, axis=1)[:, None] - 2.0 * s + yn2[None, :],
                0.0)
            w = 1.0 / (1.0 + d2)          # (block, n)
            w = jnp.where(vb[:, None], w, 0.0)
            # remove self-interaction: its w is 1 at d²=0
            w2 = w * w
            zrow = jnp.sum(w, axis=1) - 1.0
            f = yb * jnp.sum(w2, axis=1)[:, None] - jnp.dot(
                w2, y, preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST)
            # the self term of w² cancels in f (diff is zero) — only Z
            # needed the correction
            return f, zrow

        f, zrow = jax.lax.map(
            per_block,
            (pad_rows(y).reshape(nb, block, dim),
             valid.reshape(nb, block)))
        z = jnp.maximum(jnp.sum(jnp.where(valid.reshape(nb, block),
                                          zrow, 0.0)), 1e-12)
        return f.reshape(-1, dim)[:n], z

    def attraction(y):
        """Σ_j p_ij w_ij (y_i − y_j) over the sparse kNN edges, plus
        the symmetric reaction (edges are stored directed).  The edge
        gather is row-block tiled (pallas_graph.gather_rows)."""
        from .pallas_graph import gather_rows

        yj = gather_rows(y, safe)                 # (n, k, d)
        diff = y[:, None, :] - yj
        d2 = jnp.sum(diff * diff, axis=2)
        coef = p / (1.0 + d2)                     # (n, k)
        att = coef[:, :, None] * diff
        g = jnp.sum(att, axis=1)
        g = g + jax.ops.segment_sum(
            (-att).reshape(-1, dim), safe.reshape(-1), num_segments=n)
        return g

    y0 = jnp.asarray(init, jnp.float32)
    gains0 = jnp.ones_like(y0)
    vel0 = jnp.zeros_like(y0)

    def step(carry, it):
        y, vel, gains = carry
        exag = jnp.where(it < exaggeration_iter, exaggeration, 1.0)
        momentum = jnp.where(it < exaggeration_iter, 0.5, 0.8)
        f_rep, z = repulsion(y)
        grad = 4.0 * (exag * attraction(y) - f_rep / z)
        same_sign = (grad * vel) > 0
        gains = jnp.clip(jnp.where(same_sign, gains * 0.8, gains + 0.2),
                         0.01, 1e3)
        vel = momentum * vel - learning_rate * gains * grad
        y = y + vel
        y = y - jnp.mean(y, axis=0, keepdims=True)  # keep centred
        return (y, vel, gains), None

    (y, _, _), _ = jax.lax.scan(
        step, (y0, vel0, gains0), jnp.arange(n_iter, dtype=jnp.float32))
    return y


def _prep_p(idx, dist, perplexity, xp=np):
    """kNN distances → symmetrised sparse affinities aligned to the
    DIRECTED edge list (each undirected p_ij split across the one or
    two directed slots that carry it, so the segment-sum reaction in
    the attractive term reconstitutes the full symmetric force).

    Returns (P, effective_perplexity).  Perplexity is capped at k/3
    (the k ≈ 3·perplexity rule every kNN t-SNE uses): with only k
    stored neighbours, an entropy target at or above log(k) pins the
    bandwidth bisection at its lower bound and the affinities
    degenerate to exactly uniform — the parameter would silently do
    nothing."""
    n, k = idx.shape
    eff = min(float(perplexity), max(2.0, k / 3.0))
    if eff < perplexity:
        import warnings

        warnings.warn(
            f"embed.tsne: perplexity={perplexity} needs ≥3x as many "
            f"kNN neighbours, but the graph has k={k}; using "
            f"perplexity={eff:.1f} (rebuild neighbors.knn with "
            f"k≈{int(3 * perplexity)} for the requested value)",
            stacklevel=3)
    perplexity = eff
    is_self = idx == np.arange(n)[:, None]
    d2 = np.where((idx < 0) | is_self, np.inf,
                  np.asarray(dist, np.float64) ** 2)
    pc = _calibrate_p(d2, perplexity, xp=np)  # conditional p_{j|i}
    # symmetrise: p_ij = (p_{j|i} + p_{i|j}) / 2n over the UNION of
    # directed edges.  Edges present in both directions carry half of
    # p_ij in each slot (the attractive pass adds the reaction term,
    # so each undirected pair must sum to p_ij across its slots).
    import scipy.sparse as sp

    rows = np.repeat(np.arange(n), k)
    cols = idx.reshape(-1)
    keep = (cols >= 0) & ~is_self.reshape(-1)
    A = sp.coo_matrix((pc.reshape(-1)[keep],
                       (rows[keep], cols[keep])), shape=(n, n)).tocsr()
    S = (A + A.T).tocsr()  # p_{j|i} + p_{i|j} at every stored slot
    S.data /= 2.0 * n
    total = S.sum()
    if total > 0:
        S.data /= total  # exact Σ p_ij = 1 (kNN truncation drops mass)
    # back to the (n, k) directed slots; a slot carries p_ij/2 when
    # the reverse edge also exists (the reaction adds the other half),
    # or the full p_ij when it does not.
    # mutual-edge mask from the INDEX STRUCTURE, not stored values — a
    # conditional affinity that underflowed to exactly 0.0 is still a
    # stored edge, and treating it as absent would double-count its
    # pair's affinity below
    B = sp.coo_matrix((np.ones(int(keep.sum())),
                       (rows[keep], cols[keep])), shape=(n, n)).tocsr()
    both = B.multiply(B.T).tocsr()
    Sd = np.asarray(S[rows, cols.clip(0)]).reshape(n, k)
    both_d = np.asarray(both[rows, cols.clip(0)]).reshape(n, k)
    P = np.where(both_d > 0, Sd / 2.0, Sd).astype(np.float32)
    P[(idx < 0) | is_self] = 0.0
    return P, perplexity


def _exag_iters(n_iter: int, nominal: int = 100) -> int:
    """Early-exaggeration phase length: the standard ~100 iterations,
    but never more than a quarter of the run — an unclamped 100 would
    make a short n_iter<=100 call return the compressed exaggeration-
    phase layout instead of a t-SNE embedding."""
    return min(nominal, max(1, n_iter // 4))


@register("embed.tsne", backend="tpu")
def tsne_tpu(data: CellData, n_components: int = 2,
             perplexity: float = 30.0, n_iter: int = 500,
             learning_rate: float = 200.0, seed: int = 0) -> CellData:
    """t-SNE of the kNN graph (requires neighbors.knn).  Adds
    obsm["X_tsne"].  Exact blocked repulsion on the MXU — no
    Barnes-Hut approximation."""
    if "knn_indices" not in data.obsp:
        raise ValueError("run neighbors.knn first")
    n = data.n_cells
    idx = np.asarray(data.obsp["knn_indices"])[:n]
    dist = np.asarray(data.obsp["knn_distances"])[:n]
    P, eff = _prep_p(idx, dist, perplexity)
    rng = np.random.default_rng(seed)
    init = (rng.standard_normal((n, n_components)) * 1e-4).astype(
        np.float32)
    from .pallas_graph import resolved_impl

    y = tsne_layout_arrays(jnp.asarray(idx), jnp.asarray(P),
                           jnp.asarray(init), n_iter=n_iter,
                           exaggeration_iter=_exag_iters(n_iter),
                           learning_rate=learning_rate,
                           graph_impl=resolved_impl())
    return data.with_obsm(X_tsne=y).with_uns(tsne_perplexity=eff)


@register("embed.tsne", backend="cpu")
def tsne_cpu(data: CellData, n_components: int = 2,
             perplexity: float = 30.0, n_iter: int = 500,
             learning_rate: float = 200.0, seed: int = 0) -> CellData:
    """numpy oracle: identical math, plain loops (small n only)."""
    if "knn_indices" not in data.obsp:
        raise ValueError("run neighbors.knn first")
    n = data.n_cells
    idx = np.asarray(data.obsp["knn_indices"])[:n]
    dist = np.asarray(data.obsp["knn_distances"])[:n]
    P, eff = _prep_p(idx, dist, perplexity)
    P = np.asarray(P, np.float64)
    rng = np.random.default_rng(seed)
    y = rng.standard_normal((n, n_components)) * 1e-4
    vel = np.zeros_like(y)
    gains = np.ones_like(y)
    safe = np.where(idx < 0, 0, idx)
    ex_it = _exag_iters(n_iter)
    for it in range(n_iter):
        exag = 12.0 if it < ex_it else 1.0
        momentum = 0.5 if it < ex_it else 0.8
        d2 = ((y[:, None, :] - y[None, :, :]) ** 2).sum(-1)
        w = 1.0 / (1.0 + d2)
        np.fill_diagonal(w, 0.0)
        z = max(w.sum(), 1e-12)
        # attraction over sparse edges (+ reaction)
        diff = y[:, None, :] - y[safe]
        dd2 = (diff ** 2).sum(-1)
        coef = P / (1.0 + dd2)
        att = coef[:, :, None] * diff
        g_att = att.sum(1)
        np.add.at(g_att, safe.reshape(-1),
                  -att.reshape(-1, n_components))
        w2 = w * w
        f_rep = y * w2.sum(1)[:, None] - w2 @ y
        grad = 4.0 * (exag * g_att - f_rep / z)
        same = (grad * vel) > 0
        gains = np.clip(np.where(same, gains * 0.8, gains + 0.2),
                        0.01, 1e3)
        vel = momentum * vel - learning_rate * gains * grad
        y = y + vel
        y -= y.mean(0, keepdims=True)
    return data.with_obsm(X_tsne=y.astype(np.float32)).with_uns(
        tsne_perplexity=eff)
