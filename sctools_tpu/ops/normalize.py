"""Normalisation transforms: ``normalize.library_size``,
``normalize.log1p``, ``normalize.scale``.

Reference parity: these are the per-cell preprocessing ops named in
BASELINE.json configs[0] ("library-size normalize + log1p").  The CPU
backend (scipy/numpy) is the correctness oracle; the TPU backend is
pure JAX over the padded-ELL layout — per-row rescaling is a dense
VPU-vectorised op, no scatter/gather at all.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..data.dataset import CellData
from ..data.sparse import SparseCells, row_sum
from ..registry import register

from .. import buckets as _buckets

# ----------------------------------------------------------------------
# normalize.library_size
# ----------------------------------------------------------------------


def _library_size_sparse(x: SparseCells, target_sum, row_valid=None):
    totals = row_sum(x)
    if target_sum is None:
        valid = x.row_mask() if row_valid is None else row_valid
        target = jnp.nanmedian(jnp.where(valid, totals, jnp.nan))
    else:
        target = jnp.asarray(target_sum, x.data.dtype)
    scale = jnp.where(totals > 0, target / jnp.maximum(totals, 1e-12), 0.0)
    return x.with_data(x.data * scale[:, None]), totals


def _library_size_dense(x: jax.Array, target_sum, row_valid=None):
    totals = jnp.sum(x, axis=1)
    if target_sum is None:
        if row_valid is None:
            target = jnp.median(totals)
        else:
            # bucket-mask path: padding rows (totals == 0) must not
            # drag the median down
            target = jnp.nanmedian(
                jnp.where(row_valid, totals, jnp.nan))
    else:
        target = jnp.asarray(target_sum, x.dtype)
    scale = jnp.where(totals > 0, target / jnp.maximum(totals, 1e-12), 0.0)
    return x * scale[:, None], totals


def _he_gene_flag_device(x: SparseCells, totals, max_fraction):
    """Genes taking > max_fraction of ANY cell's counts (scanpy's
    exclude_highly_expressed rule).  Indicator slots -> one segment
    sum; no scatter-max needed."""
    from ..data.sparse import segment_reduce

    n_cells = x.n_cells
    sentinel = x.sentinel
    inv_tot = jnp.where(totals > 0, 1.0 / jnp.maximum(totals, 1e-12),
                        0.0)

    def slot_vals(ind, dat, row_offset):
        rows = row_offset + jnp.arange(ind.shape[0])
        valid = (ind != sentinel) & (rows < n_cells)[:, None]
        frac = dat * jnp.take(inv_tot, jnp.minimum(
            rows, len(totals) - 1))[:, None]
        return (valid & (frac > max_fraction)).astype(dat.dtype)[
            :, :, None]

    return segment_reduce(x, slot_vals, 1)[:, 0] > 0


@register("normalize.library_size", backend="tpu", fusable=True,
          mem_cost=2.5, mask_aware=True)
def library_size_tpu(data: CellData, target_sum: float | None = 1e4,
                     exclude_highly_expressed: bool = False,
                     max_fraction: float = 0.05) -> CellData:
    """Scale every cell to ``target_sum`` total counts (median of
    totals when ``target_sum=None``).  ``exclude_highly_expressed``
    (scanpy ``normalize_total`` parity): genes taking more than
    ``max_fraction`` of ANY cell's counts are left out of the size
    computation — so one hyper-abundant transcript cannot deflate
    every other gene of its cell — but are still scaled.

    Mask-aware: per-row rescaling leaves zero padding rows zero
    (``scale == 0`` at ``totals == 0``); the one cross-row statistic,
    the ``target_sum=None`` median, restricts to the bucket row
    mask."""
    X = data.X
    masks = _buckets.masks_of(data)
    row_valid = None if masks is None else jnp.asarray(masks.row)
    if isinstance(X, SparseCells):
        if exclude_highly_expressed:
            totals_all = row_sum(X)
            he = _he_gene_flag_device(X, totals_all, max_fraction)
            table = jnp.concatenate([
                he.astype(X.data.dtype), jnp.zeros((1,), X.data.dtype)])
            he_counts = jnp.sum(
                X.data * jnp.take(table, X.indices), axis=1)
            totals = totals_all - he_counts
            if target_sum is None:
                valid = (X.row_mask() if row_valid is None
                         else row_valid)
                target = jnp.nanmedian(
                    jnp.where(valid, totals, jnp.nan))
            else:
                target = jnp.asarray(target_sum, X.data.dtype)
            scale = jnp.where(totals > 0,
                              target / jnp.maximum(totals, 1e-12), 0.0)
            Xs = X.with_data(X.data * scale[:, None])
            return (data.with_X(Xs).with_obs(library_size=totals)
                    .with_var(highly_expressed=he))
        Xs, totals = _library_size_sparse(X, target_sum,
                                          row_valid=row_valid)
    else:
        Xd = jnp.asarray(X)
        if exclude_highly_expressed:
            totals_all = jnp.sum(Xd, axis=1)
            frac = Xd / jnp.maximum(totals_all[:, None], 1e-12)
            he = jnp.any(frac > max_fraction, axis=0)
            totals = jnp.sum(jnp.where(he[None, :], 0.0, Xd), axis=1)
            if target_sum is not None:
                target = jnp.asarray(target_sum, Xd.dtype)
            elif row_valid is None:
                target = jnp.median(totals)
            else:
                target = jnp.nanmedian(
                    jnp.where(row_valid, totals, jnp.nan))
            scale = jnp.where(totals > 0,
                              target / jnp.maximum(totals, 1e-12), 0.0)
            return (data.with_X(Xd * scale[:, None])
                    .with_obs(library_size=totals)
                    .with_var(highly_expressed=he))
        Xs, totals = _library_size_dense(Xd, target_sum,
                                         row_valid=row_valid)
    return data.with_X(Xs).with_obs(library_size=totals)


@register("normalize.library_size", backend="cpu")
def library_size_cpu(data: CellData, target_sum: float | None = 1e4,
                     exclude_highly_expressed: bool = False,
                     max_fraction: float = 0.05) -> CellData:
    import scipy.sparse as sp

    X = data.X
    he = None
    if sp.issparse(X):
        X = X.tocsr().astype(np.float64).astype(np.float32)
        totals = np.asarray(X.sum(axis=1)).ravel()
        if exclude_highly_expressed:
            inv = np.divide(1.0, totals, out=np.zeros_like(totals),
                            where=totals > 0)
            frac = sp.diags(inv) @ X
            he = np.asarray(
                (frac > max_fraction).max(axis=0).todense()).ravel()
            totals = totals - np.asarray(
                X[:, he].sum(axis=1)).ravel()
        target = np.median(totals) if target_sum is None else target_sum
        scale = np.divide(target, totals, out=np.zeros_like(totals),
                          where=totals > 0)
        X = sp.diags(scale.astype(np.float32)) @ X
    else:
        X = np.asarray(X, dtype=np.float32)
        totals = X.sum(axis=1)
        if exclude_highly_expressed:
            frac = X / np.maximum(totals[:, None], 1e-12)
            he = (frac > max_fraction).any(axis=0)
            totals = X[:, ~he].sum(axis=1)
        target = np.median(totals) if target_sum is None else target_sum
        scale = np.divide(target, totals, out=np.zeros_like(totals),
                          where=totals > 0)
        X = X * scale[:, None]
    out = data.with_X(X).with_obs(library_size=totals.astype(np.float32))
    if he is not None:
        out = out.with_var(highly_expressed=np.asarray(he, bool))
    return out


# ----------------------------------------------------------------------
# normalize.log1p
# ----------------------------------------------------------------------


@register("normalize.log1p", backend="tpu", fusable=True,
          mask_aware=True)
def log1p_tpu(data: CellData) -> CellData:
    """``x -> log(1 + x)`` elementwise.  On the sparse layout this maps
    only stored values (log1p(0) == 0, so sparsity is preserved).
    Mask-aware for free: elementwise with a zero fixed point, so
    bucket padding stays zero."""
    X = data.X
    if isinstance(X, SparseCells):
        X = X.with_data(jnp.log1p(X.data))
    else:
        X = jnp.log1p(jnp.asarray(X))
    return data.with_X(X)


@register("normalize.log1p", backend="cpu")
def log1p_cpu(data: CellData) -> CellData:
    import scipy.sparse as sp

    X = data.X
    if sp.issparse(X):
        X = X.copy()
        X.data = np.log1p(X.data)
    else:
        X = np.log1p(np.asarray(X))
    return data.with_X(X)


# ----------------------------------------------------------------------
# normalize.scale  (standardise genes; dense output)
# ----------------------------------------------------------------------


@register("normalize.scale", backend="tpu", fusable=True,
          mem_cost=3.0, mask_aware=True)
def scale_tpu(data: CellData, max_value: float | None = 10.0,
              zero_center: bool = True) -> CellData:
    """Per-gene standardisation (unit variance, optionally zero mean).

    Densifies: meant for the post-HVG matrix (n_cells × ~2k genes).

    Mask-aware: on bucketized data the moments are count-corrected
    (divide by the TRACED valid count, padding rows contribute zero
    sums) and the standardised padding rows are re-zeroed —
    ``(0 - mean)/std`` would otherwise turn inert padding into dense
    junk that downstream reductions would fold in.
    """
    X = data.X
    masks = _buckets.masks_of(data)
    if isinstance(X, SparseCells):
        X = X.to_dense()
    X = jnp.asarray(X)
    if masks is None:
        mean = jnp.mean(X, axis=0)
        var = jnp.var(X, axis=0)
    else:
        n = jnp.maximum(jnp.asarray(masks.n_cells, X.dtype), 1.0)
        mean = jnp.sum(X, axis=0) / n  # padding rows are zero
        rm = jnp.asarray(masks.row)[:, None]
        d = jnp.where(rm, X - mean[None, :], 0.0)
        var = jnp.sum(d * d, axis=0) / n
    std = jnp.sqrt(jnp.maximum(var, 1e-12))
    Xs = (X - mean) / std if zero_center else X / std
    if max_value is not None:
        Xs = jnp.clip(Xs, -max_value, max_value)
    if masks is not None:
        Xs = jnp.where(jnp.asarray(masks.row)[:, None], Xs, 0.0)
        Xs = jnp.where(jnp.asarray(masks.col)[None, :], Xs, 0.0)
    return data.with_X(Xs).with_var(scale_mean=mean, scale_std=std)


@register("normalize.scale", backend="cpu")
def scale_cpu(data: CellData, max_value: float | None = 10.0,
              zero_center: bool = True) -> CellData:
    import scipy.sparse as sp

    X = data.X
    if sp.issparse(X):
        X = np.asarray(X.todense())
    X = np.asarray(X, dtype=np.float32)
    mean = X.mean(axis=0)
    var = X.var(axis=0)
    std = np.sqrt(np.maximum(var, 1e-12))
    Xs = (X - mean) / std if zero_center else X / std
    if max_value is not None:
        Xs = np.clip(Xs, -max_value, max_value)
    return data.with_X(Xs).with_var(scale_mean=mean, scale_std=std)


# ----------------------------------------------------------------------
# normalize.pearson_residuals  (analytic Pearson residuals)
# ----------------------------------------------------------------------


def _pearson_residuals_math(X_dense, totals, gene_sums, grand, theta,
                            clip, n_cells, xp):
    """Shared residual math for both backends.

    ``Z_ij = (x_ij - mu_ij) / sqrt(mu_ij + mu_ij^2 / theta)`` with
    ``mu_ij = t_i * g_j / T`` (the NB offset model of Lause et al.
    2021), clipped to ``±clip`` (default ``sqrt(n_cells)``).
    """
    mu = (totals[:, None] * gene_sums[None, :]) / xp.maximum(grand, 1e-12)
    denom = xp.sqrt(mu + mu * mu / theta)
    Z = (X_dense - mu) / xp.maximum(denom, 1e-12)
    if clip is not None:
        c = float(clip)
    elif hasattr(n_cells, "dtype") or not isinstance(n_cells, (int, float)):
        # bucket-mask path: the valid count is a TRACED scalar — keep
        # the sqrt on device so the clip bound never bakes into the
        # compiled program
        c = xp.sqrt(xp.asarray(n_cells, X_dense.dtype))
    else:
        c = float(np.sqrt(n_cells))
    return xp.clip(Z, -c, c)


@register("normalize.pearson_residuals", backend="tpu",
          fusable=True, mem_cost=4.0, mask_aware=True)
def pearson_residuals_tpu(data: CellData, theta: float = 100.0,
                          clip: float | None = None) -> CellData:
    """Analytic Pearson residuals of an NB offset model (Lause et al.
    2021; scanpy's ``experimental.pp.normalize_pearson_residuals``).

    Densifies the output — run after ``hvg.select(subset=True)`` (or
    accept an (n_cells × n_genes) dense result).  Margins
    (``totals``/``gene_sums``) are computed sparsely; only the residual
    matrix itself is dense, which it must be (residuals of zeros are
    nonzero).  Pure VPU work: one rank-1 outer product + elementwise.

    Mask-aware: padding margins are zero so padded residuals read 0
    (``mu = 0`` and the denominator floor keeps 0/0 at 0); the default
    ``sqrt(n)`` clip switches to the TRACED valid count, and padding
    rows are explicitly re-zeroed as belt-and-braces.
    """
    X = data.X
    masks = _buckets.masks_of(data)
    Xd = X.to_dense() if isinstance(X, SparseCells) else jnp.asarray(X)
    totals = jnp.sum(Xd, axis=1)
    gene_sums = jnp.sum(Xd, axis=0)
    n = Xd.shape[0] if masks is None else masks.n_cells
    Z = _pearson_residuals_math(Xd, totals, gene_sums, jnp.sum(totals),
                                theta, clip, n, jnp)
    if masks is not None:
        Z = jnp.where(jnp.asarray(masks.row)[:, None], Z, 0.0)
        Z = jnp.where(jnp.asarray(masks.col)[None, :], Z, 0.0)
    return data.with_X(Z).with_uns(pearson_theta=theta)


@register("normalize.pearson_residuals", backend="cpu")
def pearson_residuals_cpu(data: CellData, theta: float = 100.0,
                          clip: float | None = None) -> CellData:
    import scipy.sparse as sp

    X = data.X
    if sp.issparse(X):
        Xd = np.asarray(X.todense(), dtype=np.float64)
    else:
        Xd = np.asarray(X, dtype=np.float64)
    totals = Xd.sum(axis=1)
    gene_sums = Xd.sum(axis=0)
    Z = _pearson_residuals_math(Xd, totals, gene_sums, totals.sum(),
                                theta, clip, Xd.shape[0], np)
    return data.with_X(Z.astype(np.float32)).with_uns(pearson_theta=theta)


# ----------------------------------------------------------------------
# normalize.regress_out  (residualise X against obs covariates)
# ----------------------------------------------------------------------


def _design_matrix(data: CellData, keys, n_rows, xp):
    """Intercept + one column per numeric covariate; categorical
    (string/object) covariates are one-hot encoded host-side with the
    first level dropped (absorbed by the intercept).

    TPU per-cell ops (``qc.per_cell_metrics`` &c.) emit obs arrays at
    the ELL padded row count, which may exceed ``n_rows`` after X has
    been trimmed/densified — covariates *longer* than ``n_rows`` are
    therefore trimmed (trailing entries are row padding by contract,
    see ``CellData.to_host``); *shorter* ones raise.
    """

    def fit(kname, v):
        if v.shape[0] >= n_rows:
            return v[:n_rows]
        raise ValueError(
            f"regress_out: obs[{kname!r}] has {v.shape[0]} entries, "
            f"X has {n_rows} rows")

    cols = [xp.ones((n_rows,), dtype=xp.float32)]
    for kname in keys:
        if kname not in data.obs:
            raise KeyError(f"regress_out: obs has no key {kname!r}; "
                           f"available: {sorted(data.obs)}")
        v = data.obs[kname]
        kind = getattr(np.asarray(v) if not hasattr(v, "dtype") else v,
                       "dtype", np.dtype(object)).kind
        if kind in "OUS":  # categorical: one-hot, drop first level
            host = fit(kname, np.asarray(v).reshape(-1))
            levels, codes = np.unique(host, return_inverse=True)
            onehot = np.eye(len(levels), dtype=np.float32)[codes][:, 1:]
            cols.extend(xp.asarray(onehot[:, j])
                        for j in range(onehot.shape[1]))
            continue
        cols.append(fit(kname, xp.asarray(v, dtype=xp.float32).reshape(-1)))
    return xp.stack(cols, axis=1)  # (n_rows, p)


@register("normalize.regress_out", backend="tpu")
def regress_out_tpu(data: CellData, keys: list | tuple = (),
                    ridge: float = 1e-6) -> CellData:
    """Remove linear effects of ``obs[keys]`` covariates per gene
    (scanpy ``pp.regress_out``), via one normal-equations solve.

    ``beta = (CᵀC + λI)⁻¹ CᵀX``; ``X ← X − C·beta``.  CᵀC is (p×p)
    (tiny), CᵀX is a single (p × n_genes) MXU matmul — no per-gene
    loop.  Densifies (run post-HVG; ``to_dense`` already trims padding
    rows, so C and X are both exactly n_cells tall).  Categorical
    covariates are one-hot encoded.
    """
    if not keys:
        raise ValueError("regress_out needs at least one obs key")
    X = data.X
    X = X.to_dense() if isinstance(X, SparseCells) else jnp.asarray(X)
    C = _design_matrix(data, keys, X.shape[0], jnp)
    ctc = C.T @ C + ridge * jnp.eye(C.shape[1], dtype=X.dtype)
    ctx = C.T @ X
    beta = jax.scipy.linalg.solve(ctc, ctx, assume_a="pos")
    return data.with_X(X - C @ beta)


@register("normalize.regress_out", backend="cpu")
def regress_out_cpu(data: CellData, keys: list | tuple = (),
                    ridge: float = 1e-6) -> CellData:
    import scipy.sparse as sp

    if not keys:
        raise ValueError("regress_out needs at least one obs key")
    X = data.X
    if sp.issparse(X):
        X = np.asarray(X.todense())
    X = np.asarray(X, dtype=np.float64)
    C = _design_matrix(data, keys, X.shape[0], np).astype(np.float64)
    ctc = C.T @ C + ridge * np.eye(C.shape[1])
    beta = np.linalg.solve(ctc, C.T @ X)
    return data.with_X((X - C @ beta).astype(np.float32))


# ----------------------------------------------------------------------
# normalize.downsample_counts  (binomial thinning to a target total)
# ----------------------------------------------------------------------


@register("normalize.downsample_counts", backend="tpu", fusable=True)
def downsample_counts_tpu(data: CellData, target_total: float = 1e3,
                          seed: int = 0) -> CellData:
    """Binomially thin each cell's counts to ~``target_total``
    (scanpy ``pp.downsample_counts`` semantics, per-cell).

    On the ELL layout this is elementwise ``Binomial(n=x_ij, p_i)``
    over the value plane — sparsity pattern only shrinks, the layout
    is reused as-is.  Cells already at/below target are untouched.

    Thinning is only defined for integer counts: non-integer values
    (e.g. after ``normalize.library_size``) are floored first, on both
    backends, so the CPU oracle and TPU path agree.
    """
    X = data.X
    key = jax.random.PRNGKey(seed)
    if isinstance(X, SparseCells):
        counts = jnp.floor(X.data.astype(jnp.float32))
        totals = jnp.sum(counts, axis=1)
        p = jnp.minimum(1.0, target_total / jnp.maximum(totals, 1e-12))
        newdata = jax.random.binomial(
            key, counts, p[:, None]).astype(X.data.dtype)
        # Entries thinned to zero leave the sparsity pattern: mark
        # their slots as padding (sentinel index) so nnz-based stats
        # (qc n_genes, hvg dropout) match the CPU oracle's
        # eliminate_zeros().  The pattern only ever shrinks, so
        # rewriting indices in place is legal in the ELL layout.
        newidx = jnp.where(newdata == 0, X.sentinel, X.indices)
        return data.with_X(SparseCells(newidx.astype(X.indices.dtype),
                                       newdata, X.n_cells, X.n_genes))
    Xd = jnp.floor(jnp.asarray(X).astype(jnp.float32))
    totals = jnp.sum(Xd, axis=1)
    p = jnp.minimum(1.0, target_total / jnp.maximum(totals, 1e-12))
    out = jax.random.binomial(key, Xd, p[:, None])
    return data.with_X(out.astype(jnp.asarray(X).dtype))


@register("normalize.downsample_counts", backend="cpu")
def downsample_counts_cpu(data: CellData, target_total: float = 1e3,
                          seed: int = 0) -> CellData:
    import scipy.sparse as sp

    rng = np.random.default_rng(seed)
    X = data.X
    if sp.issparse(X):
        X = X.tocsr().copy()
        counts = np.floor(X.data).astype(np.int64)  # match TPU floor
        totals = np.bincount(
            np.repeat(np.arange(X.shape[0]), np.diff(X.indptr)),
            weights=counts, minlength=X.shape[0])
        p = np.minimum(1.0, target_total / np.maximum(totals, 1e-12))
        per_nz = np.repeat(p, np.diff(X.indptr))
        X.data = rng.binomial(counts, per_nz).astype(X.data.dtype)
        X.eliminate_zeros()
        return data.with_X(X)
    X = np.asarray(X)
    counts = np.floor(X).astype(np.int64)
    totals = counts.sum(axis=1)
    p = np.minimum(1.0, target_total / np.maximum(totals, 1e-12))
    return data.with_X(rng.binomial(counts, p[:, None]).astype(X.dtype))


# ----------------------------------------------------------------------
# normalize.clr  (centered log-ratio — CITE-seq ADT normalisation)
# ----------------------------------------------------------------------


@register("normalize.clr", backend="tpu", fusable=True)
def clr_tpu(data: CellData, axis: str = "cell") -> CellData:
    """Centered log-ratio transform (Seurat ``NormalizeData(method=
    "CLR")`` / muon ``prot.pp.clr``): the standard normalisation for
    CITE-seq antibody (ADT) counts, where library-size normalisation
    is confounded by the composition of the panel.

    ``y = log1p(x / exp(mean(log1p(x))))`` with the mean over the
    chosen margin — ``axis="cell"`` (each cell's features, Seurat
    margin 1 on a features×cells matrix) or ``axis="gene"`` (each
    feature across cells).  Zeros stay zero only for the transform's
    stored entries on the sparse layout (log1p(0)=0 both sides), so
    sparsity is preserved.
    """
    if axis not in ("cell", "gene"):
        raise ValueError(f"normalize.clr: axis must be 'cell' or "
                         f"'gene', got {axis!r}")
    X = data.X
    if isinstance(X, SparseCells):
        lg = jnp.log1p(X.data)
        if axis == "cell":
            m = jnp.sum(lg, axis=1) / data.n_genes  # zeros add 0
            scale = jnp.exp(-m)[:, None]
            Xn = X.with_data(jnp.log1p(X.data * scale))
        else:
            from ..data.sparse import gene_sum

            gsum = gene_sum(X.with_data(lg))
            m = gsum / data.n_cells
            scale_pad = jnp.concatenate(
                [jnp.exp(-m), jnp.ones((1,), lg.dtype)])
            Xn = X.with_data(jnp.log1p(
                X.data * jnp.take(scale_pad, X.indices)))
        return data.with_X(Xn)
    Xd = jnp.asarray(X)
    lg = jnp.log1p(Xd)
    ax = 1 if axis == "cell" else 0
    m = jnp.mean(lg, axis=ax, keepdims=True)
    return data.with_X(jnp.log1p(Xd * jnp.exp(-m)))


@register("normalize.clr", backend="cpu")
def clr_cpu(data: CellData, axis: str = "cell") -> CellData:
    import scipy.sparse as sp

    if axis not in ("cell", "gene"):
        raise ValueError(f"normalize.clr: axis must be 'cell' or "
                         f"'gene', got {axis!r}")
    X = data.X
    if sp.issparse(X):
        X = X.tocsr().astype(np.float64)
        lg = X.copy()
        lg.data = np.log1p(lg.data)
        if axis == "cell":
            m = np.asarray(lg.sum(axis=1)).ravel() / data.n_genes
            scale = sp.diags(np.exp(-m))
            Xn = (scale @ X).tocsr()
        else:
            m = np.asarray(lg.sum(axis=0)).ravel() / data.n_cells
            Xn = (X @ sp.diags(np.exp(-m))).tocsr()
        Xn.data = np.log1p(Xn.data)
        return data.with_X(Xn.astype(np.float32))
    Xd = np.asarray(X, np.float64)
    lg = np.log1p(Xd)
    ax = 1 if axis == "cell" else 0
    m = lg.mean(axis=ax, keepdims=True)
    return data.with_X(np.log1p(Xd * np.exp(-m)).astype(np.float32))
