"""Normalisation transforms: ``normalize.library_size``,
``normalize.log1p``, ``normalize.scale``.

Reference parity: these are the per-cell preprocessing ops named in
BASELINE.json configs[0] ("library-size normalize + log1p").  The CPU
backend (scipy/numpy) is the correctness oracle; the TPU backend is
pure JAX over the padded-ELL layout — per-row rescaling is a dense
VPU-vectorised op, no scatter/gather at all.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..data.dataset import CellData
from ..data.sparse import SparseCells, row_sum
from ..registry import register

# ----------------------------------------------------------------------
# normalize.library_size
# ----------------------------------------------------------------------


def _library_size_sparse(x: SparseCells, target_sum):
    totals = row_sum(x)
    if target_sum is None:
        valid = x.row_mask()
        target = jnp.nanmedian(jnp.where(valid, totals, jnp.nan))
    else:
        target = jnp.asarray(target_sum, x.data.dtype)
    scale = jnp.where(totals > 0, target / jnp.maximum(totals, 1e-12), 0.0)
    return x.with_data(x.data * scale[:, None]), totals


def _library_size_dense(x: jax.Array, target_sum):
    totals = jnp.sum(x, axis=1)
    if target_sum is None:
        target = jnp.median(totals)
    else:
        target = jnp.asarray(target_sum, x.dtype)
    scale = jnp.where(totals > 0, target / jnp.maximum(totals, 1e-12), 0.0)
    return x * scale[:, None], totals


@register("normalize.library_size", backend="tpu")
def library_size_tpu(data: CellData, target_sum: float | None = 1e4) -> CellData:
    """Scale every cell to ``target_sum`` total counts (median of
    totals when ``target_sum=None``)."""
    if isinstance(data.X, SparseCells):
        X, totals = _library_size_sparse(data.X, target_sum)
    else:
        X, totals = _library_size_dense(jnp.asarray(data.X), target_sum)
    return data.with_X(X).with_obs(library_size=totals)


@register("normalize.library_size", backend="cpu")
def library_size_cpu(data: CellData, target_sum: float | None = 1e4) -> CellData:
    import scipy.sparse as sp

    X = data.X
    if sp.issparse(X):
        X = X.tocsr().astype(np.float64).astype(np.float32)
        totals = np.asarray(X.sum(axis=1)).ravel()
        target = np.median(totals) if target_sum is None else target_sum
        scale = np.divide(target, totals, out=np.zeros_like(totals),
                          where=totals > 0)
        X = sp.diags(scale.astype(np.float32)) @ X
    else:
        X = np.asarray(X, dtype=np.float32)
        totals = X.sum(axis=1)
        target = np.median(totals) if target_sum is None else target_sum
        scale = np.divide(target, totals, out=np.zeros_like(totals),
                          where=totals > 0)
        X = X * scale[:, None]
    return data.with_X(X).with_obs(library_size=totals.astype(np.float32))


# ----------------------------------------------------------------------
# normalize.log1p
# ----------------------------------------------------------------------


@register("normalize.log1p", backend="tpu")
def log1p_tpu(data: CellData) -> CellData:
    """``x -> log(1 + x)`` elementwise.  On the sparse layout this maps
    only stored values (log1p(0) == 0, so sparsity is preserved)."""
    X = data.X
    if isinstance(X, SparseCells):
        X = X.with_data(jnp.log1p(X.data))
    else:
        X = jnp.log1p(jnp.asarray(X))
    return data.with_X(X)


@register("normalize.log1p", backend="cpu")
def log1p_cpu(data: CellData) -> CellData:
    import scipy.sparse as sp

    X = data.X
    if sp.issparse(X):
        X = X.copy()
        X.data = np.log1p(X.data)
    else:
        X = np.log1p(np.asarray(X))
    return data.with_X(X)


# ----------------------------------------------------------------------
# normalize.scale  (standardise genes; dense output)
# ----------------------------------------------------------------------


@register("normalize.scale", backend="tpu")
def scale_tpu(data: CellData, max_value: float | None = 10.0,
              zero_center: bool = True) -> CellData:
    """Per-gene standardisation (unit variance, optionally zero mean).

    Densifies: meant for the post-HVG matrix (n_cells × ~2k genes).
    """
    X = data.X
    if isinstance(X, SparseCells):
        X = X.to_dense()
    X = jnp.asarray(X)
    mean = jnp.mean(X, axis=0)
    var = jnp.var(X, axis=0)
    std = jnp.sqrt(jnp.maximum(var, 1e-12))
    Xs = (X - mean) / std if zero_center else X / std
    if max_value is not None:
        Xs = jnp.clip(Xs, -max_value, max_value)
    return data.with_X(Xs).with_var(scale_mean=mean, scale_std=std)


@register("normalize.scale", backend="cpu")
def scale_cpu(data: CellData, max_value: float | None = 10.0,
              zero_center: bool = True) -> CellData:
    import scipy.sparse as sp

    X = data.X
    if sp.issparse(X):
        X = np.asarray(X.todense())
    X = np.asarray(X, dtype=np.float32)
    mean = X.mean(axis=0)
    var = X.var(axis=0)
    std = np.sqrt(np.maximum(var, 1e-12))
    Xs = (X - mean) / std if zero_center else X / std
    if max_value is not None:
        Xs = np.clip(Xs, -max_value, max_value)
    return data.with_X(Xs).with_var(scale_mean=mean, scale_std=std)
