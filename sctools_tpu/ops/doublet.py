"""``qc.doublet_score`` — scrublet-style doublet detection.

Reference parity: dpeerlab/sctools ships doublet QC in its
preprocessing suite (source unavailable — SURVEY.md §0; algorithm is
the published Scrublet method: simulate doublets by summing random
pairs of observed cells, embed them with the observed cells, and score
each observed cell by how enriched its neighbourhood is in simulated
doublets).

TPU design: the expensive stage — normalising and projecting the
simulated doublets into PCA space — is a **fused blocked kernel**
(``lax.map`` over pair blocks) that never materialises the simulated
count matrix:

* per block, gather the two parent rows' padded-ELL slots and
  concatenate → ``(block, 2·capacity)``;
* merge duplicate gene ids (a gene present in both parents) exactly
  with a sort + cumsum-difference trick — counts are non-negative, so
  the cumulative sum at run boundaries recovers every run total
  regardless of run length, with no scatter;
* library-normalise + log1p the merged counts and contract against
  the PCA loadings gathered per slot (zero-padded table row kills
  sentinel/merged slots) — one VPU-friendly einsum per block.

The kNN over the combined (observed + simulated) embedding reuses the
blocked MXU top-k from ``neighbors.knn``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..data.dataset import CellData
from ..data.sparse import SparseCells
from ..registry import register


def _default_k(n_cells: int) -> int:
    return max(10, int(round(0.5 * np.sqrt(n_cells))))


def _resolve_params(n: int, sim_ratio: float, k: int | None):
    """(n_sim, k, k_adj) shared by both backends — n_sim depends only
    on the statistics, never on tiling, so cpu and tpu simulate the
    same doublets for a seed."""
    n_sim = max(1, int(round(sim_ratio * n)))
    k = k or _default_k(n)
    k_adj = int(round(k * (1.0 + n_sim / n)))
    return n_sim, k, k_adj


def _attach_outputs(data: CellData, obs_s, sim_s, expected_rate,
                    threshold) -> CellData:
    out = data.with_obs(doublet_score=obs_s).with_uns(
        doublet_sim_scores=sim_s, doublet_expected_rate=expected_rate)
    if threshold is not None:
        out = out.with_obs(predicted_doublet=obs_s > threshold).with_uns(
            doublet_threshold=threshold)
    return out


def _doublet_likelihood(q, r, rho):
    """Scrublet's posterior doublet likelihood from the simulated-
    neighbour fraction ``q``, simulation ratio ``r = n_sim/n_obs`` and
    expected doublet rate ``rho``.  q == r/(1+r) (no enrichment) maps
    to rho; q -> 1 maps to 1."""
    return q * rho / r / (1.0 - rho - q * (1.0 - rho - rho / r))


def _sample_pairs(n_cells: int, n_sim: int, seed: int) -> np.ndarray:
    """(n_sim, 2) parent indices, i != j.  Host-side numpy rng so the
    cpu and tpu backends simulate the *same* doublets for a seed."""
    rng = np.random.default_rng(seed)
    i = rng.integers(0, n_cells, size=n_sim)
    j = (i + 1 + rng.integers(0, n_cells - 1, size=n_sim)) % n_cells
    return np.stack([i, j], axis=1).astype(np.int32)


@partial(jax.jit, static_argnames=("target_sum", "block"))
def _project_doublets(ind, dat, pairs, comps, mu, target_sum: float,
                      block: int = 1024):
    """Project simulated doublets into PCA space without materialising
    their count matrix.

    ind/dat: padded-ELL arrays of the *raw counts*; pairs: (n_sim, 2);
    comps: (G, d) loadings; mu: (G,) gene means of the normalised
    observed matrix.  Returns (n_sim, d) centred scores.  Pairs are
    padded internally to a ``block`` multiple (pair (0, 0) — harmless)
    and the padding sliced off the result.
    """
    d = comps.shape[1]
    comps_pad = jnp.concatenate(
        [comps, jnp.zeros((1, d), comps.dtype)], axis=0)
    mu_proj = mu @ comps  # (d,)
    n_sim = pairs.shape[0]
    pad = (-n_sim) % block
    if pad:
        pairs = jnp.concatenate(
            [pairs, jnp.zeros((pad, 2), pairs.dtype)], axis=0)

    def per_block(pblk):  # (block, 2)
        ind2 = jnp.concatenate(
            [jnp.take(ind, pblk[:, 0], axis=0),
             jnp.take(ind, pblk[:, 1], axis=0)], axis=1)
        dat2 = jnp.concatenate(
            [jnp.take(dat, pblk[:, 0], axis=0),
             jnp.take(dat, pblk[:, 1], axis=0)], axis=1)
        order = jnp.argsort(ind2, axis=1)
        ind_s = jnp.take_along_axis(ind2, order, axis=1)
        dat_s = jnp.take_along_axis(dat2, order, axis=1)
        # Exact duplicate merge: counts are >= 0, so the cumsum is
        # non-decreasing and the cumulative max of run-boundary cumsums
        # is the cumsum at the *previous* boundary — run total =
        # cs[last] - cs[previous last], any run length, no scatter.
        cs = jnp.cumsum(dat_s.astype(jnp.float32), axis=1)
        is_last = jnp.concatenate(
            [ind_s[:, :-1] != ind_s[:, 1:],
             jnp.ones((ind_s.shape[0], 1), bool)], axis=1)
        boundary_cs = jnp.where(is_last, cs, 0.0)
        prev_cs = jnp.concatenate(
            [jnp.zeros((ind_s.shape[0], 1), jnp.float32),
             jax.lax.cummax(boundary_cs, axis=1)[:, :-1]], axis=1)
        val = jnp.where(is_last, cs - prev_cs, 0.0)
        # library-size normalise + log1p the merged doublet counts
        totals = cs[:, -1]
        scale = jnp.where(totals > 0, target_sum / jnp.maximum(totals, 1e-12),
                          0.0)
        v = jnp.log1p(val * scale[:, None])
        # project: zero rows of comps_pad kill sentinel slots; merged
        # (zero-valued) slots contribute 0 regardless of their index
        g = jnp.take(comps_pad, jnp.minimum(ind_s, comps.shape[0]), axis=0)
        return jnp.einsum("bc,bcd->bd", v, g,
                          precision=jax.lax.Precision.HIGHEST
                          ) - mu_proj[None, :]

    out = jax.lax.map(
        per_block, pairs.reshape((n_sim + pad) // block, block, 2))
    return out.reshape(n_sim + pad, d)[:n_sim]


def _neighbor_scores(emb_obs, emb_sim, n_obs, n_sim, k_adj, metric,
                     expected_rate, backend):
    """kNN over the combined embedding; per-row simulated-neighbour
    fraction → doublet likelihood.  Returns (obs_scores, sim_scores)."""
    r = n_sim / n_obs
    if backend == "tpu":
        from .knn import knn_arrays

        combined = jnp.concatenate(
            [jnp.asarray(emb_obs), jnp.asarray(emb_sim)], axis=0)
        idx, _ = knn_arrays(combined, combined, k=k_adj, metric=metric,
                            n_query=n_obs + n_sim, n_cand=n_obs + n_sim,
                            exclude_self=True)
        idx = idx[: n_obs + n_sim]
        n_sim_nb = jnp.sum(idx >= n_obs, axis=1)
        n_valid = jnp.sum(idx >= 0, axis=1)
        q = (n_sim_nb + 1.0) / (n_valid + 2.0)
        scores = np.asarray(_doublet_likelihood(q, r, expected_rate))
    else:
        from .knn import knn_numpy

        combined = np.concatenate(
            [np.asarray(emb_obs, np.float64), np.asarray(emb_sim, np.float64)])
        idx, _ = knn_numpy(combined, combined, k=k_adj, metric=metric,
                           exclude_self=True)
        n_sim_nb = (idx >= n_obs).sum(axis=1)
        n_valid = (idx >= 0).sum(axis=1)
        q = (n_sim_nb + 1.0) / (n_valid + 2.0)
        scores = _doublet_likelihood(q, r, expected_rate)
    return (scores[:n_obs].astype(np.float32),
            scores[n_obs:].astype(np.float32))


@register("qc.doublet_score", backend="tpu")
def doublet_score_tpu(data: CellData, expected_rate: float = 0.06,
                      sim_ratio: float = 2.0, n_components: int = 30,
                      k: int | None = None, metric: str = "euclidean",
                      target_sum: float = 1e4, seed: int = 0,
                      threshold: float | None = None,
                      block: int = 1024) -> CellData:
    """Scrublet-style doublet scoring.  ``data.X`` must hold **raw
    counts** (run before normalisation).  Adds obs["doublet_score"],
    uns["doublet_sim_scores"]; with ``threshold`` also
    obs["predicted_doublet"]."""
    from .pca import randomized_pca_arrays

    X = data.X
    if not isinstance(X, SparseCells):
        raise TypeError("qc.doublet_score(tpu) expects SparseCells raw "
                        "counts; device_put the data first")
    n = data.n_cells
    n_sim, k, k_adj = _resolve_params(n, sim_ratio, k)

    # normalised log1p view of the observed counts (functional copy)
    from .normalize import _library_size_sparse

    x_scaled, _ = _library_size_sparse(X, target_sum)
    x_norm = x_scaled.with_data(jnp.log1p(x_scaled.data))
    obs_scores, comps, _, mu = randomized_pca_arrays(
        x_norm, jax.random.PRNGKey(seed), n_components=n_components)
    obs_scores = obs_scores[:n]

    pairs = jnp.asarray(_sample_pairs(n, n_sim, seed))
    sim_scores_emb = _project_doublets(
        X.indices, X.data, pairs, comps, mu, target_sum, block=block)

    obs_s, sim_s = _neighbor_scores(
        obs_scores, sim_scores_emb, n, n_sim, k_adj, metric,
        expected_rate, backend="tpu")
    return _attach_outputs(data, obs_s, sim_s, expected_rate, threshold)


@register("qc.doublet_score", backend="cpu")
def doublet_score_cpu(data: CellData, expected_rate: float = 0.06,
                      sim_ratio: float = 2.0, n_components: int = 30,
                      k: int | None = None, metric: str = "euclidean",
                      target_sum: float = 1e4, seed: int = 0,
                      threshold: float | None = None,
                      **_ignored) -> CellData:
    """Numpy/scipy oracle: same simulation (same host rng), exact CSR
    doublet sums, dense PCA projection."""
    import scipy.sparse as sp

    X = data.X
    if not sp.issparse(X):
        X = sp.csr_matrix(np.asarray(X))
    X = X.tocsr()
    n = data.n_cells
    n_sim, k, k_adj = _resolve_params(n, sim_ratio, k)

    totals = np.asarray(X.sum(axis=1)).ravel()
    scale = np.where(totals > 0, target_sum / np.maximum(totals, 1e-12), 0.0)
    x_norm = sp.diags(scale) @ X
    x_norm.data = np.log1p(x_norm.data)

    from .pca import pca_randomized_cpu

    pcad = pca_randomized_cpu(CellData(x_norm), n_components=n_components,
                              seed=seed)
    obs_scores = np.asarray(pcad.obsm["X_pca"], np.float64)
    comps = np.asarray(pcad.varm["PCs"], np.float64)
    mu = np.asarray(pcad.uns["pca_mean"], np.float64)

    pairs = _sample_pairs(n, n_sim, seed)
    dbl = X[pairs[:, 0]] + X[pairs[:, 1]]  # exact CSR duplicate handling
    dtot = np.asarray(dbl.sum(axis=1)).ravel()
    dbl = sp.diags(np.where(dtot > 0, target_sum / np.maximum(dtot, 1e-12),
                            0.0)) @ dbl
    dbl.data = np.log1p(dbl.data)
    sim_scores_emb = dbl @ comps - mu @ comps

    obs_s, sim_s = _neighbor_scores(
        obs_scores, sim_scores_emb, n, n_sim, k_adj, metric,
        expected_rate, backend="cpu")
    return _attach_outputs(data, obs_s, sim_s, expected_rate, threshold)
