"""Highly-variable-gene selection: ``hvg.select``.

Reference parity: BASELINE.json configs[2] — Seurat-v3 flavor on raw
counts.  Flavors:

* ``"seurat_v3"`` — variance-stabilising: per-gene mean/variance of raw
  counts, a quadratic fit of log10(var) vs log10(mean) replaces the
  reference loess (documented divergence: loess is not expressible as a
  fixed-shape XLA program; the quadratic fit tracks it closely on
  log-log scale and both backends implement the *same* math so parity
  is exact between cpu and tpu), then clipped standardised variance
  ranks genes.
* ``"dispersion"`` (Seurat v1) — on log-normalised data: dispersion =
  var/mean, z-scored within 20 mean-bins.

On TPU the per-gene moments come from one fused ``segment_sum`` pass
over the ELL slots (``gene_stats``); the clipped second pass is a
second segment-sum.  Everything else is O(G) work.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..config import config, round_up
from ..data.dataset import CellData
from ..data.sparse import SparseCells
from ..registry import register

from .. import buckets as _buckets


# ----------------------------------------------------------------------
# Gene subsetting (shared with qc.filter_genes).
# ----------------------------------------------------------------------


def subset_genes_sparse(x: SparseCells, gene_idx: np.ndarray,
                        capacity: int | None = None) -> SparseCells:
    """Device-side gene subset of a padded-ELL matrix.

    Builds an old→new gene-id map (dropped genes → sentinel) and
    remaps the slot indices.  The sparsity pattern only loses entries,
    so existing capacity always suffices; pass ``capacity`` to re-pack
    tighter (host round-trip is avoided by keeping slots in place and
    relying on sentinel annihilation).
    """
    gene_idx = np.asarray(gene_idx)
    g_new = len(gene_idx)
    mapping = np.full(x.n_genes + 1, g_new, dtype=np.int32)  # new sentinel
    mapping[gene_idx] = np.arange(g_new, dtype=np.int32)
    mapping = jnp.asarray(mapping)
    new_ind = jnp.take(mapping, x.indices, axis=0)
    new_dat = jnp.where(new_ind == g_new, 0.0, x.data)
    out = SparseCells(new_ind, new_dat, x.n_cells, g_new)
    if capacity is not None and capacity < x.capacity:
        out = _compact_capacity(out, capacity)
    return out


def _compact_capacity(x: SparseCells, capacity: int) -> SparseCells:
    """Shift valid slots left (stable) and truncate to ``capacity``.

    Jittable: an argsort on the "is padding" flag per row is a stable
    left-compaction.
    """
    capacity = round_up(capacity, config.capacity_multiple)
    is_pad = (x.indices == x.sentinel).astype(jnp.int32)
    order = jnp.argsort(is_pad, axis=1, stable=True)
    ind = jnp.take_along_axis(x.indices, order, axis=1)[:, :capacity]
    dat = jnp.take_along_axis(x.data, order, axis=1)[:, :capacity]
    return SparseCells(ind, dat, x.n_cells, x.n_genes)


def _subset_genes_matrix(M, gene_idx: np.ndarray, compact: bool):
    """Gene-subset an X-shaped matrix (SparseCells / scipy / dense) —
    shared by X and every layer so they cannot drift."""
    import scipy.sparse as sp

    if sp.issparse(M):
        return M.tocsc()[:, gene_idx].tocsr()
    if isinstance(M, SparseCells):
        cap = None
        if compact:
            # safe upper bound on new nnz/row: min(old capacity, g_new)
            cap = min(M.capacity, round_up(max(len(gene_idx), 1),
                                           config.capacity_multiple))
        return subset_genes_sparse(M, gene_idx, capacity=cap)
    return jnp.take(jnp.asarray(M), jnp.asarray(gene_idx), axis=1)


def select_genes_device(data: CellData, gene_idx: np.ndarray,
                        compact: bool = False) -> CellData:
    """Subset a CellData to ``gene_idx`` (device path).  X, var, varm,
    and every layer are sliced consistently."""
    gene_idx = np.asarray(gene_idx)
    newX = _subset_genes_matrix(data.X, gene_idx, compact)

    def take(v):
        if isinstance(v, jax.Array) or np.asarray(v).dtype.kind in "biufc":
            return jnp.take(jnp.asarray(v), jnp.asarray(gene_idx), axis=0)
        return np.asarray(v)[gene_idx]  # strings/objects stay host-side
    var = {k: take(v) for k, v in data.var.items()}
    varm = {k: take(v) for k, v in data.varm.items()}
    layers = {k: _subset_genes_matrix(v, gene_idx, compact)
              for k, v in data.layers.items()}
    return data.replace(X=newX, var=var, varm=varm, layers=layers)


# ----------------------------------------------------------------------
# Moments
# ----------------------------------------------------------------------


def _gene_moments_tpu(X, n_valid=None, row_valid=None):
    """Per-gene mean, (ddof=1) variance, and nnz over cells;
    sparse-aware.  The sparse path uses the cancellation-free centered
    two-pass (``gene_moments``) — ``ss − n·μ²`` in f32 loses all
    precision for genes with μ² ≫ var, which on raw counts is most
    housekeeping genes (round-4 fix, mirrors the streaming stats).

    ``n_valid``/``row_valid`` (TRACED count / bucket row mask) switch
    to count-corrected moments on bucketized data (buckets.py):
    padding rows contribute zero sums but must not inflate the
    population count or the dense centered squares."""
    if isinstance(X, SparseCells):
        from ..data.sparse import gene_moments

        mean, m2, nnz = gene_moments(X, n_valid=n_valid)
        if n_valid is None:
            var = m2 / max(X.n_cells - 1, 1)
        else:
            var = m2 / jnp.maximum(
                jnp.asarray(n_valid, m2.dtype) - 1.0, 1.0)
    else:
        X = jnp.asarray(X)
        if n_valid is None:
            mean = jnp.mean(X, axis=0)
            var = jnp.var(X, axis=0, ddof=1)
        else:
            nv = jnp.asarray(n_valid, X.dtype)
            mean = jnp.sum(X, axis=0) / jnp.maximum(nv, 1.0)
            d = jnp.where(jnp.asarray(row_valid)[:, None],
                          X - mean[None, :], 0.0)
            var = jnp.sum(d * d, axis=0) / jnp.maximum(nv - 1.0, 1.0)
        nnz = jnp.sum(X != 0, axis=0).astype(mean.dtype)
    return mean, jnp.maximum(var, 0.0), nnz


def _pearson_residual_var_sparse_tpu(X: SparseCells, theta: float,
                                     gchunk: int = 256):
    """Per-gene variance of clipped Pearson residuals of RAW counts
    (scanpy experimental flavor='pearson_residuals', Lause 2021):
    ``r = clip((x - mu) / sqrt(mu + mu^2/theta), ±sqrt(n))`` with
    ``mu = total_i * gene_sum_j / grand_total``.

    The zeros' residual depends on the CELL total, so there is no
    per-gene closed form — the zero baseline is computed densely per
    gene chunk (an outer product, MXU-shaped), then the stored entries
    are corrected in one k-sparse segment pass (r - r0, r² - r0²)."""
    from ..data.sparse import segment_reduce

    n = X.n_cells
    totals = jnp.sum(X.data, axis=1)[:n]
    gsum = n * _gene_moments_tpu(X)[0]
    p = gsum / jnp.maximum(jnp.sum(totals), 1e-12)
    clip = float(np.sqrt(n))

    @partial(jax.jit, static_argnames=())
    def chunk_baseline(p_chunk):
        mu = totals[:, None] * p_chunk[None, :]
        denom = jnp.sqrt(mu + mu * mu / theta)
        r0 = jnp.clip(-mu / jnp.maximum(denom, 1e-12), -clip, clip)
        return jnp.sum(r0, axis=0), jnp.sum(r0 * r0, axis=0)

    G = int(p.shape[0])
    S = np.zeros(G, np.float64)
    Q = np.zeros(G, np.float64)
    p_chunkpad = jnp.pad(p, (0, (-G) % gchunk))
    for lo in range(0, G, gchunk):
        s0, q0 = chunk_baseline(jax.lax.dynamic_slice_in_dim(
            p_chunkpad, lo, gchunk))
        hi = min(G, lo + gchunk)
        S[lo:hi] = np.asarray(s0)[: hi - lo]
        Q[lo:hi] = np.asarray(q0)[: hi - lo]

    totals_pad = jnp.concatenate([totals, jnp.zeros(
        (X.rows_padded - n,), totals.dtype)])
    p_pad = jnp.concatenate([p, jnp.zeros((1,))])
    sentinel = X.sentinel

    def slot_vals(ind, dat, row_offset):
        rows = row_offset + jnp.arange(ind.shape[0])
        t = jnp.take(totals_pad, jnp.minimum(rows, X.rows_padded - 1))
        mu = t[:, None] * jnp.take(p_pad, ind)
        denom = jnp.maximum(jnp.sqrt(mu + mu * mu / theta), 1e-12)
        r = jnp.clip((dat - mu) / denom, -clip, clip)
        r0 = jnp.clip(-mu / denom, -clip, clip)
        ok = (ind != sentinel) & (rows < n)[:, None]
        dS = jnp.where(ok, r - r0, 0.0)
        dQ = jnp.where(ok, r * r - r0 * r0, 0.0)
        return jnp.stack([dS, dQ], axis=2)

    corr = np.asarray(segment_reduce(X, slot_vals, 2), np.float64)
    S += corr[:, 0]
    Q += corr[:, 1]
    return ((Q - S * S / n) / max(n - 1, 1)).astype(np.float32)


def _pearson_residual_var_dense(Xd, theta: float, xp):
    """Dense counterpart (numpy oracle and small device-dense X)."""
    n = Xd.shape[0]
    Xd = xp.asarray(Xd, jnp.float32 if xp is jnp else np.float64)
    totals = Xd.sum(axis=1, keepdims=True)
    p = Xd.sum(axis=0) / xp.maximum(totals.sum(), 1e-12)
    mu = totals * p[None, :]
    denom = xp.maximum(xp.sqrt(mu + mu * mu / theta), 1e-12)
    clip = float(np.sqrt(n))
    r = xp.clip((Xd - mu) / denom, -clip, clip)
    return r.var(axis=0, ddof=1)


def _gene_moments_cpu(X) -> tuple[np.ndarray, np.ndarray]:
    import scipy.sparse as sp

    # all sums in float64: ss − n·μ² in the input's float32 cancels
    # catastrophically for genes with μ² ≫ var (the same defect the
    # TPU path fixes with the centered two-pass gene_moments)
    if sp.issparse(X):
        X = X.tocsr().astype(np.float64)
        n = X.shape[0]
        s = np.asarray(X.sum(axis=0)).ravel()
        ss = np.asarray(X.multiply(X).sum(axis=0)).ravel()
        mean = s / n
        var = (ss - n * mean**2) / max(n - 1, 1)
    else:
        X = np.asarray(X, dtype=np.float64)
        mean = X.mean(axis=0)
        var = X.var(axis=0, ddof=1)
    return mean, np.maximum(var, 0.0)


# ----------------------------------------------------------------------
# seurat_v3 standardised variance (shared math, two array namespaces)
# ----------------------------------------------------------------------


def _fit_mean_var_trend(mean, var, xp):
    """Quadratic fit of log10(var) ~ log10(mean) over expressed genes.

    Returns predicted variance per gene (clipped positive).
    """
    expressed = (mean > 0) & (var > 0)
    lm = xp.log10(xp.where(mean > 0, mean, 1.0))
    lv = xp.log10(xp.where(var > 0, var, 1.0))
    w = expressed.astype(lm.dtype)
    # Standardise the regressor first: the raw [1, lm, lm²] normal
    # equations are too ill-conditioned for float32 (TPU) to match the
    # float64 oracle.
    wsum = xp.maximum(xp.sum(w), 1.0)
    m0 = xp.sum(lm * w) / wsum
    s0 = xp.sqrt(xp.maximum(xp.sum(w * (lm - m0) ** 2) / wsum, 1e-12))
    t = (lm - m0) / s0
    A = xp.stack([xp.ones_like(t), t, t * t], axis=1)
    Aw = A * w[:, None]
    G = Aw.T @ A
    b = Aw.T @ lv
    coef = xp.linalg.solve(G + 1e-6 * xp.eye(3, dtype=lm.dtype), b)
    pred = A @ coef
    return xp.power(10.0, pred)


def _seurat_v3_scores_from_stats(mean, var, clipped_ssq, n, xp):
    """Standardised variance given the clipped second moment.
    ``n`` may be a TRACED scalar (bucket-mask path)."""
    if hasattr(n, "dtype"):
        std_var = clipped_ssq / xp.maximum(
            xp.asarray(n, clipped_ssq.dtype) - 1.0, 1.0)
    else:
        std_var = clipped_ssq / max(n - 1, 1)
    return xp.where((mean > 0) & (var > 0), std_var, 0.0)




def _hvg_batched(data: CellData, n_top, flavor, subset, compact,
                 batch_key, single, subset_fn):
    """scanpy batch_key semantics: score each batch separately
    (per-batch cell subsets via CellData.__getitem__), then combine —
    genes flagged in MORE batches win, median per-batch rank breaks
    ties.  Adds var["highly_variable_nbatches"]."""
    n = data.n_cells
    if batch_key not in data.obs:
        raise KeyError(f"hvg.select: obs has no {batch_key!r}")
    labels = np.asarray(data.obs[batch_key])[:n]
    ranks, flags = [], []
    for b in np.unique(labels):
        scored = single(data[labels == b])
        ranks.append(np.asarray(scored.var["hvg_rank"]))
        flags.append(np.asarray(scored.var["highly_variable"]))
    nb = np.sum(np.stack(flags), axis=0).astype(np.int32)
    med = np.median(np.stack(ranks), axis=0)
    order = np.lexsort((med, -nb))
    G = data.n_genes
    rank = np.empty(G, np.int64)
    rank[order] = np.arange(G)
    highly = rank < n_top
    out = data.with_var(
        highly_variable=highly, hvg_rank=rank.astype(np.int32),
        highly_variable_nbatches=nb,
        hvg_score=(-med).astype(np.float32))
    if subset:
        out = subset_fn(out, np.sort(order[:n_top]), compact=compact)
    return out

def _hvg_fusable(params: dict) -> bool:
    """hvg.select traces end-to-end only without its host-side paths:
    ``subset=True`` is a data-dependent-shape materialisation point,
    ``batch_key`` subsets per batch on host, and the cell_ranger /
    pearson_residuals flavors do host-side per-bin / chunked work."""
    return (not params.get("subset", False)
            and params.get("batch_key") is None
            and params.get("flavor", "seurat_v3")
            in ("seurat_v3", "dispersion", "seurat"))


@register("hvg.select", backend="tpu", fusable=_hvg_fusable,
          mem_cost=2.5, mask_aware=_hvg_fusable)
def hvg_select_tpu(data: CellData, n_top: int = 2000,
                   flavor: str = "seurat_v3", subset: bool = False,
                   compact: bool = True,
                   batch_key: str | None = None,
                   theta: float = 100.0) -> CellData:
    """Rank genes by variability; adds var: ``highly_variable``,
    ``hvg_rank``, ``hvg_score`` (+ ``means``/``variances``).  With
    ``subset=True`` returns the gene-subset CellData (materialisation
    point, like the reference's shard repack).  ``batch_key`` scores
    each batch separately and rank-combines (scanpy semantics: genes
    variable in MORE batches win, median per-batch rank breaks ties;
    adds ``highly_variable_nbatches``).

    Mask-aware for the fusable flavors (same predicate as fusability:
    no subset, no batch_key, moment-based scoring): moments are
    count-corrected with the TRACED valid count, the seurat_v3 clip
    and zeros term use it too, and padding genes score ``-inf`` so
    they can never displace a real gene from the top-``n_top`` set."""
    if batch_key is not None:
        return _hvg_batched(
            data, n_top, flavor, subset, compact, batch_key,
            lambda d: hvg_select_tpu(d, n_top=n_top, flavor=flavor),
            select_genes_device)
    X = data.X
    masks = _buckets.masks_of(data)
    n_valid = None if masks is None else masks.n_cells
    row_valid = None if masks is None else masks.row
    if flavor == "seurat_v3":
        mean, var, nnz = _gene_moments_tpu(X, n_valid=n_valid,
                                           row_valid=row_valid)
        n = data.n_cells if masks is None else n_valid
        reg_var = _fit_mean_var_trend(mean, var, jnp)
        reg_std = jnp.sqrt(reg_var)
        clip = (jnp.sqrt(jnp.asarray(float(n))) if masks is None
                else jnp.sqrt(jnp.asarray(n, jnp.float32)))
        if isinstance(X, SparseCells):
            # clipped standardised second moment via one chunked
            # segment pass: sum_c min(clip, (x - mu)/sigma)^2 =
            #   [nnz terms] + (n - nnz) * (mu/sigma)^2   (zeros clip
            #   too, their term is (0-mu)/sigma).
            from ..data.sparse import segment_reduce

            std = jnp.maximum(reg_std, 1e-12)
            table_mu = jnp.concatenate([mean / std, jnp.zeros((1,))])
            table_inv = jnp.concatenate([1.0 / std, jnp.zeros((1,))])
            n_cells = X.n_cells
            sentinel = X.sentinel

            def slot_vals(ind, dat, row_offset):
                zval = jnp.take(table_inv, ind, axis=0) * dat - jnp.take(
                    table_mu, ind, axis=0)
                zval = jnp.clip(zval, -clip, clip)
                rows = row_offset + jnp.arange(ind.shape[0])
                ok = (ind != sentinel) & (rows < n_cells)[:, None]
                return jnp.where(ok, zval * zval, 0.0)[:, :, None]

            ssq_nnz = segment_reduce(X, slot_vals, 1)[:, 0]
            zero_term = jnp.clip(-mean / std, -clip, clip) ** 2
            ssq = ssq_nnz + (n - nnz) * zero_term
        else:
            Xd = jnp.asarray(X)
            z = (Xd - mean) / jnp.maximum(reg_std, 1e-12)
            z = jnp.clip(z, -clip, clip)
            ssq = jnp.sum(z * z, axis=0)
        score = _seurat_v3_scores_from_stats(mean, var, ssq, n, jnp)
    elif flavor in ("dispersion", "seurat"):
        # "seurat" is scanpy's name for exactly this ranking
        mean, var, _ = _gene_moments_tpu(X, n_valid=n_valid,
                                         row_valid=row_valid)
        score = _dispersion_scores(mean, var, jnp)
    elif flavor == "cell_ranger":
        mean, var, _ = _gene_moments_tpu(X)
        score = jnp.asarray(_cell_ranger_scores(
            np.asarray(mean), np.asarray(var)), jnp.float32)
    elif flavor == "pearson_residuals":
        # expects RAW counts (like seurat_v3); scanpy experimental
        # flavor (Lause 2021) — rank by clipped-residual variance
        mean, var, _ = _gene_moments_tpu(X)
        if isinstance(X, SparseCells):
            score = jnp.asarray(
                _pearson_residual_var_sparse_tpu(X, theta))
        else:
            score = _pearson_residual_var_dense(jnp.asarray(X), theta,
                                                jnp)
    else:
        raise ValueError(f"unknown hvg flavor {flavor!r}")

    if masks is not None:
        # padding genes sort LAST: a zero score ties real unexpressed
        # genes and could steal a top-n_top slot from them
        score = jnp.where(jnp.asarray(masks.col), score, -jnp.inf)
    order = jnp.argsort(-score, stable=True)
    rank = jnp.empty_like(order).at[order].set(jnp.arange(data.n_genes))
    highly = rank < n_top
    out = data.with_var(
        highly_variable=highly, hvg_rank=rank.astype(jnp.int32),
        hvg_score=score, means=mean, variances=var,
    )
    if subset:
        top_idx = np.sort(np.asarray(order[:n_top]))
        out = select_genes_device(out, top_idx, compact=compact)
    return out


@register("hvg.select", backend="cpu")
def hvg_select_cpu(data: CellData, n_top: int = 2000,
                   flavor: str = "seurat_v3", subset: bool = False,
                   compact: bool = True,
                   batch_key: str | None = None,
                   theta: float = 100.0) -> CellData:
    import scipy.sparse as sp

    if batch_key is not None:
        return _hvg_batched(
            data, n_top, flavor, subset, compact, batch_key,
            lambda d: hvg_select_cpu(d, n_top=n_top, flavor=flavor),
            select_genes_device)
    X = data.X
    mean, var = _gene_moments_cpu(X)
    n = data.n_cells
    if flavor == "seurat_v3":
        reg_var = _fit_mean_var_trend(mean, var, np)
        reg_std = np.sqrt(reg_var)
        clip = np.sqrt(float(n))
        std = np.maximum(reg_std, 1e-12)
        if sp.issparse(X):
            Xc = X.tocsc()
            nnz = np.diff(Xc.indptr)
            zero_term = np.clip(-mean / std, -clip, clip) ** 2
            z = (Xc.data - np.repeat(mean, nnz)) / np.repeat(std, nnz)
            z = np.clip(z, -clip, clip)
            ssq = np.zeros(data.n_genes)
            np.add.at(ssq, np.repeat(np.arange(data.n_genes), nnz), z * z)
            ssq += (n - nnz) * zero_term
        else:
            Xd = np.asarray(X)
            z = np.clip((Xd - mean) / std, -clip, clip)
            ssq = (z * z).sum(axis=0)
        score = _seurat_v3_scores_from_stats(mean, var, ssq, n, np)
    elif flavor in ("dispersion", "seurat"):
        score = _dispersion_scores(mean, var, np)
    elif flavor == "cell_ranger":
        score = _cell_ranger_scores(mean, var)
    elif flavor == "pearson_residuals":
        Xd = X.toarray() if sp.issparse(X) else np.asarray(X)
        score = _pearson_residual_var_dense(Xd, theta, np)
    else:
        raise ValueError(f"unknown hvg flavor {flavor!r}")

    order = np.argsort(-score, kind="stable")
    rank = np.empty_like(order)
    rank[order] = np.arange(data.n_genes)
    highly = rank < n_top
    out = data.with_var(
        highly_variable=highly, hvg_rank=rank.astype(np.int32),
        hvg_score=score.astype(np.float32),
        means=mean.astype(np.float32), variances=var.astype(np.float32),
    )
    if subset:
        idx = np.sort(order[:n_top])
        Xs = X[:, idx] if not sp.issparse(X) else X.tocsc()[:, idx].tocsr()
        var_d = {k: np.asarray(v)[idx] for k, v in out.var.items()}
        varm = {k: np.asarray(v)[idx] for k, v in out.varm.items()}
        layers = {k: (v.tocsc()[:, idx].tocsr() if sp.issparse(v)
                      else np.asarray(v)[:, idx])
                  for k, v in out.layers.items()}
        out = out.replace(X=Xs, var=var_d, varm=varm, layers=layers)
    return out


def _dispersion_scores(mean, var, xp, n_bins: int = 20):
    """Seurat-v1 dispersion: var/mean, z-scored within mean bins."""
    disp = xp.where(mean > 0, var / xp.maximum(mean, 1e-12), 0.0)
    logm = xp.log1p(mean)
    lo = xp.min(logm)
    hi = xp.max(logm) + 1e-6
    bins = xp.clip(((logm - lo) / (hi - lo) * n_bins).astype(xp.int32), 0, n_bins - 1)
    if xp is np:
        m = np.zeros(n_bins)
        s = np.zeros(n_bins)
        cnt = np.zeros(n_bins)
        np.add.at(cnt, bins, 1.0)
        np.add.at(m, bins, disp)
        np.add.at(s, bins, disp * disp)
    else:
        one = xp.ones_like(disp)
        cnt = jax.ops.segment_sum(one, bins, num_segments=n_bins)
        m = jax.ops.segment_sum(disp, bins, num_segments=n_bins)
        s = jax.ops.segment_sum(disp * disp, bins, num_segments=n_bins)
    cnt = xp.maximum(cnt, 1.0)
    bmean = m / cnt
    bvar = xp.maximum(s / cnt - bmean**2, 1e-12)
    bstd = xp.sqrt(bvar)
    return (disp - bmean[bins]) / bstd[bins]


def _cell_ranger_scores(mean, var, min_bins: int = 3):
    """scanpy flavor="cell_ranger": dispersion normalised by the
    MEDIAN and median-absolute-deviation within mean-PERCENTILE bins
    (vs the seurat flavor's equal-width log-mean bins and mean/std).
    Host numpy on fetched (G,) moments — medians need per-bin sorts,
    O(G log G) host work vs the O(n·G) device pass that produced the
    moments."""
    mean = np.asarray(mean, np.float64)
    var = np.asarray(var, np.float64)
    disp = np.where(mean > 0, var / np.maximum(mean, 1e-12), 0.0)
    edges = np.percentile(mean[mean > 0], np.arange(10, 105, 5))
    bins = np.digitize(mean, np.unique(edges))
    score = np.zeros_like(disp)
    for b in np.unique(bins):
        m = bins == b
        if m.sum() < min_bins:
            # scanpy parity: genes in tiny bins keep raw dispersion
            # (their MAD is meaningless)
            score[m] = disp[m]
            continue
        med = np.median(disp[m])
        mad = np.median(np.abs(disp[m] - med)) + 1e-12
        # Signed, as in scanpy: low-dispersion genes must rank LAST,
        # not alias with high-dispersion ones via an abs().
        score[m] = (disp[m] - med) / mad
    return score
