"""Pairwise distance matrices: ``distance.pairwise``.

Reference parity: the ``distance.pairwise`` (cosine/Euclidean) op named
in BASELINE.json's north star.  Materialises the full (n_query ×
n_cand) matrix, so it is meant for small/medium n; the kNN path
(``neighbors.knn``) never materialises it.  The compute is one blocked
MXU matmul either way.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..config import config
from ..data.dataset import CellData
from ..registry import register
from .knn import _get_rep, _get_rep_cpu


def pairwise_arrays(query, cand, metric: str = "cosine"):
    """Full distance matrix (n_query, n_cand), float32.  Resolves the
    matmul dtype from config outside jit (see knn_arrays)."""
    return _pairwise_jit(query, cand, metric=metric,
                         mm_dtype=str(jnp.dtype(config.matmul_dtype)))


@partial(jax.jit, static_argnames=("metric", "mm_dtype"))
def _pairwise_jit(query, cand, *, metric, mm_dtype):
    mm_dtype = jnp.dtype(mm_dtype)
    # the numerics contract (config.py): f32 policy means TRUE f32 —
    # on TPU, f32 inputs at DEFAULT precision silently run bf16 MXU
    # passes, so request HIGHEST explicitly (same as knn/spmm)
    precision = (jax.lax.Precision.HIGHEST if mm_dtype == jnp.float32
                 else jax.lax.Precision.DEFAULT)
    q = jnp.asarray(query, mm_dtype)
    c = jnp.asarray(cand, mm_dtype)
    if metric == "cosine":
        q = q / jnp.maximum(jnp.linalg.norm(q, axis=1, keepdims=True), 1e-12)
        c = c / jnp.maximum(jnp.linalg.norm(c, axis=1, keepdims=True), 1e-12)
        return 1.0 - jnp.dot(q, c.T, preferred_element_type=jnp.float32,
                             precision=precision)
    if metric == "euclidean":
        qn2 = jnp.sum(q.astype(jnp.float32) ** 2, axis=1)
        cn2 = jnp.sum(c.astype(jnp.float32) ** 2, axis=1)
        d2 = qn2[:, None] - 2.0 * jnp.dot(
            q, c.T, preferred_element_type=jnp.float32,
            precision=precision
        ) + cn2[None, :]
        return jnp.sqrt(jnp.maximum(d2, 0.0))
    raise ValueError(f"unknown metric {metric!r}")


@register("distance.pairwise", backend="tpu")
def pairwise_tpu(data: CellData, metric: str = "cosine",
                 use_rep: str = "X_pca") -> CellData:
    """Adds obsp["pairwise_distances"]."""
    rep = _get_rep(data, use_rep)
    D = pairwise_arrays(rep, rep, metric=metric)
    D = D[: data.n_cells, : data.n_cells]
    return data.with_obsp(pairwise_distances=D).with_uns(
        pairwise_metric=metric
    )


@register("distance.pairwise", backend="cpu")
def pairwise_cpu(data: CellData, metric: str = "cosine",
                 use_rep: str = "X_pca") -> CellData:
    rep = np.asarray(_get_rep_cpu(data, use_rep), np.float64)
    if metric == "cosine":
        rn = rep / np.maximum(np.linalg.norm(rep, axis=1, keepdims=True), 1e-12)
        D = 1.0 - rn @ rn.T
    elif metric == "euclidean":
        n2 = (rep**2).sum(axis=1)
        D = np.sqrt(np.maximum(n2[:, None] - 2 * rep @ rep.T + n2[None, :], 0.0))
    else:
        raise ValueError(f"unknown metric {metric!r}")
    return data.with_obsp(pairwise_distances=D.astype(np.float32)).with_uns(
        pairwise_metric=metric
    )
