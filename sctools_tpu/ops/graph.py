"""Neighbour-graph ops: ``graph.connectivities`` (UMAP-style fuzzy
weights / gaussian kernel), ``graph.diffusion_operator`` (row-
normalised transition matrix), ``impute.magic`` (diffusion
imputation), ``embed.spectral`` (diffusion-map embedding),
``dpt.pseudotime`` (diffusion pseudotime from a root cell).

TPU design: the kNN graph is kept in its padded (n, k) edge-list form
— exactly the shape ``neighbors.knn`` produces — and every graph
operation is either per-edge VPU work or a gather+reduce along the k
axis.  ``P @ X`` (diffusion steps) is a k-sparse matvec: gather k
rows of X, weight, sum — O(n·k·d), chunked over rows.  The symmetric
normalised operator uses the edge-reversed weights via one
segment-sum.  Spectral embedding reuses the randomized eigensolver
machinery from PCA (subspace iteration with CholeskyQR2) on the
diffusion operator — matrix-free, multi-chip-sharding friendly.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..data.dataset import CellData
from ..data.sparse import SparseCells
from ..registry import register


def _require_knn(data: CellData):
    if "knn_indices" not in data.obsp:
        raise ValueError("run neighbors.knn (or knn_multichip) first")
    n = data.n_cells
    idx = jnp.asarray(data.obsp["knn_indices"])[:n]
    dist = jnp.asarray(data.obsp["knn_distances"])[:n]
    return idx, dist


# ----------------------------------------------------------------------
# graph.connectivities
# ----------------------------------------------------------------------


@partial(jax.jit, static_argnames=("mode",))
def connectivities_arrays(knn_idx, knn_dist, mode: str = "umap"):
    """Edge weights from distances.

    "umap": the fuzzy-simplicial-set weights exp(-(d - rho)/sigma)
    with rho = distance to nearest neighbour and sigma calibrated so
    the weights sum to log2(k) per row (binary search, fixed 20
    iterations — the smooth-kNN calibration of UMAP).
    "gaussian": exp(-d² / (2 σ²)) with σ = mean kNN distance per row.

    Self-edges (``neighbors.knn`` includes self at distance 0 by
    default) are excluded: they get weight 0 and do not enter rho/σ —
    otherwise rho would always be 0 and the self-weight 1.0 would eat
    part of the log2(k) calibration budget.
    """
    n = knn_idx.shape[0]
    is_self = knn_idx == jnp.arange(n, dtype=knn_idx.dtype)[:, None]
    d = jnp.where((knn_idx < 0) | is_self, jnp.inf,
                  knn_dist.astype(jnp.float32))
    if mode == "gaussian":
        finite = jnp.isfinite(d)
        sigma = jnp.sum(jnp.where(finite, d, 0.0), axis=1) / jnp.maximum(
            jnp.sum(finite, axis=1), 1)
        w = jnp.exp(-(d**2) / jnp.maximum(2.0 * sigma[:, None] ** 2, 1e-12))
        return jnp.where(finite, w, 0.0)
    if mode != "umap":
        raise ValueError(f"unknown connectivity mode {mode!r}")
    k = knn_idx.shape[1]
    target = jnp.log2(jnp.float32(max(k, 2)))
    rho = jnp.min(jnp.where(jnp.isfinite(d), d, jnp.inf), axis=1)
    shifted = jnp.maximum(d - rho[:, None], 0.0)

    def weight_sum(sigma):
        w = jnp.exp(-shifted / jnp.maximum(sigma[:, None], 1e-12))
        return jnp.sum(jnp.where(jnp.isfinite(d), w, 0.0), axis=1)

    lo = jnp.full(d.shape[0], 1e-6)
    hi = jnp.full(d.shape[0], 1e3)

    def bisect(_, carry):
        lo, hi = carry
        mid = 0.5 * (lo + hi)
        too_small = weight_sum(mid) < target  # need larger sigma
        lo = jnp.where(too_small, mid, lo)
        hi = jnp.where(too_small, hi, mid)
        return lo, hi

    lo, hi = jax.lax.fori_loop(0, 20, bisect, (lo, hi))
    sigma = 0.5 * (lo + hi)
    w = jnp.exp(-shifted / jnp.maximum(sigma[:, None], 1e-12))
    return jnp.where(jnp.isfinite(d), w, 0.0)


@register("graph.connectivities", backend="tpu")
def connectivities_tpu(data: CellData, mode: str = "umap") -> CellData:
    """Adds obsp["connectivities"] (aligned with knn_indices)."""
    idx, dist = _require_knn(data)
    w = connectivities_arrays(idx, dist, mode=mode)
    return data.with_obsp(connectivities=w).with_uns(connectivity_mode=mode)


@register("graph.connectivities", backend="cpu")
def connectivities_cpu(data: CellData, mode: str = "umap") -> CellData:
    idx = np.asarray(data.obsp["knn_indices"])[: data.n_cells]
    dist = np.asarray(data.obsp["knn_distances"], np.float64)[: data.n_cells]
    is_self = idx == np.arange(len(idx))[:, None]
    d = np.where((idx < 0) | is_self, np.inf, dist)
    if mode == "gaussian":
        finite = np.isfinite(d)
        sigma = np.where(finite, d, 0.0).sum(1) / np.maximum(finite.sum(1), 1)
        w = np.exp(-(d**2) / np.maximum(2 * sigma[:, None] ** 2, 1e-12))
        w = np.where(finite, w, 0.0)
    elif mode == "umap":
        k = idx.shape[1]
        target = np.log2(max(k, 2))
        rho = np.min(np.where(np.isfinite(d), d, np.inf), axis=1)
        shifted = np.maximum(d - rho[:, None], 0.0)
        lo = np.full(len(d), 1e-6)
        hi = np.full(len(d), 1e3)
        for _ in range(20):
            mid = 0.5 * (lo + hi)
            w = np.exp(-shifted / np.maximum(mid[:, None], 1e-12))
            s = np.where(np.isfinite(d), w, 0.0).sum(1)
            small = s < target
            lo = np.where(small, mid, lo)
            hi = np.where(small, hi, mid)
        sigma = 0.5 * (lo + hi)
        w = np.exp(-shifted / np.maximum(sigma[:, None], 1e-12))
        w = np.where(np.isfinite(d), w, 0.0)
    else:
        raise ValueError(f"unknown connectivity mode {mode!r}")
    return data.with_obsp(connectivities=w.astype(np.float32)).with_uns(
        connectivity_mode=mode)


# ----------------------------------------------------------------------
# graph.jaccard — neighbour-set Jaccard weights (PhenoGraph's kernel)
# ----------------------------------------------------------------------


@partial(jax.jit, static_argnames=("block",))
def jaccard_arrays(knn_idx, block: int = 1024):
    """Per-edge Jaccard similarity of neighbour sets:
    ``J(i→j) = |N(i) ∩ N(j)| / |N(i) ∪ N(j)|``.

    TPU mapping: per row block, gather each neighbour's neighbour list
    (``(block, k, k)``) and count matches against the row's own list
    with a broadcast equality mask (``(block, k, k, k)`` bools —
    k ≤ ~60 keeps this in VMEM-scale tiles) — pure VPU reductions, no
    scatter.  -1 slots are excluded from both sets; the result is 0 on
    missing edges.
    """
    n, k = knn_idx.shape
    # row n of the lookup table = all -2: a -1 neighbour maps there and
    # can never match a real id (own list uses -3 for its padding)
    tab = jnp.concatenate(
        [jnp.where(knn_idx < 0, -2, knn_idx),
         jnp.full((1, k), -2, knn_idx.dtype)])
    nb = -(-n // block)
    pad = nb * block - n
    idx_p = (jnp.concatenate([knn_idx, jnp.full((pad, k), -1, knn_idx.dtype)])
             if pad else knn_idx)

    def per_block(iblk):  # (block, k)
        own = jnp.where(iblk < 0, -3, iblk)
        safe = jnp.where(iblk < 0, n, iblk)
        nbr = jnp.take(tab, safe, axis=0)  # (block, k, k)
        eq = nbr[:, :, :, None] == own[:, None, None, :]
        inter = jnp.sum(eq, axis=(2, 3)).astype(jnp.float32)  # (block, k)
        vi = jnp.sum(iblk >= 0, axis=1).astype(jnp.float32)  # (block,)
        vj = jnp.sum(nbr >= 0, axis=2).astype(jnp.float32)  # (block, k)
        union = vi[:, None] + vj - inter
        return jnp.where(iblk < 0, 0.0, inter / jnp.maximum(union, 1.0))

    out = jax.lax.map(per_block, idx_p.reshape(nb, block, k))
    return out.reshape(-1, k)[:n]


@register("graph.jaccard", backend="tpu")
def jaccard_tpu(data: CellData, block: int = 1024) -> CellData:
    """Adds obsp["jaccard"] (aligned with knn_indices)."""
    idx, _ = _require_knn(data)
    return data.with_obsp(jaccard=jaccard_arrays(idx, block=block))


@register("graph.jaccard", backend="cpu")
def jaccard_cpu(data: CellData, **_ignored) -> CellData:
    idx = np.asarray(data.obsp["knn_indices"])[: data.n_cells]
    n, k = idx.shape
    out = np.zeros((n, k), np.float32)
    sets = [set(r[r >= 0].tolist()) for r in idx]
    for i in range(n):
        si = sets[i]
        for e, j in enumerate(idx[i]):
            if j < 0:
                continue
            sj = sets[j]
            inter = len(si & sj)
            union = len(si) + len(sj) - inter
            out[i, e] = inter / max(union, 1)
    return data.with_obsp(jaccard=out)


# ----------------------------------------------------------------------
# Diffusion operator + sparse matvec on the kNN edge list
# ----------------------------------------------------------------------


@jax.jit
def knn_matvec(knn_idx, weights, x):
    """``P @ x`` where P is the (n, k)-edge-list sparse matrix.

    x: (n, d).  Gather-weight-sum along k; O(n·k·d).
    """
    safe = jnp.where(knn_idx < 0, 0, knn_idx)
    w = jnp.where(knn_idx < 0, 0.0, weights)
    gathered = jnp.take(x, safe, axis=0)  # (n, k, d)
    return jnp.einsum("nk,nkd->nd", w, gathered,
                      precision=jax.lax.Precision.HIGHEST)


@partial(jax.jit, static_argnames=("n",))
def knn_rmatvec(knn_idx, weights, x, n: int | None = None):
    """``Pᵀ @ x`` via segment-sum over edges (adjoint of knn_matvec;
    used for reverse-mode flows and left-eigenvector iterations)."""
    n = n if n is not None else x.shape[0]
    safe = jnp.where(knn_idx < 0, n, knn_idx)  # dropped bin
    w = jnp.where(knn_idx < 0, 0.0, weights)
    contrib = w[:, :, None] * x[:, None, :]  # (n, k, d)
    flat = contrib.reshape(-1, x.shape[-1])
    out = jax.ops.segment_sum(flat, safe.reshape(-1), num_segments=n + 1)
    return out[:n]


@partial(jax.jit, static_argnames=("mode",))
def _symmetrized_weights(idx, w, block: int = 8192, mode: str = "average"):
    """Symmetrise edge weights on the kNN edge list.

    "average": w_sym(i→j) = (w_ij + w_ji)/2 when the reverse edge
    exists, else w_ij (keeps all edges; operator only approximately
    symmetric — fine for diffusion smoothing).
    "mutual": same average but one-sided edges are dropped — the
    resulting kernel is *exactly* symmetric, which the spectral path
    requires.
    "union": the probabilistic t-conorm ``w + w' - w·w'`` (UMAP's
    fuzzy-set union; one-sided edges keep their weight).
    "union_norm": the t-conorm divided by the edge's directed
    multiplicity (1 + has-reverse-edge) — one pass of the reverse
    lookup instead of two for layouts that apply a symmetric
    reaction per directed entry (embed.umap).
    The reverse-edge lookup is an (block, k, k) equality
    mask, chunked over rows so the full (n, k, k) never materialises."""
    n, k = idx.shape
    # Lookup tables padded with a sentinel row of -2s: a -1 neighbour
    # slot maps to row n, whose "neighbours" (-2) can never equal a
    # real row id — otherwise -1 slots would alias row 0 and fabricate
    # reverse edges for it, breaking the mutual mode's exact symmetry.
    safe_tab = jnp.concatenate(
        [jnp.where(idx < 0, -2, idx), jnp.full((1, k), -2, idx.dtype)])
    w_tab = jnp.concatenate([w, jnp.zeros((1, k), w.dtype)])
    nb = -(-n // block)
    pad = nb * block - n
    idx_p = jnp.concatenate([idx, jnp.full((pad, k), -1, idx.dtype)]) if pad else idx
    w_p = jnp.concatenate([w, jnp.zeros((pad, k), w.dtype)]) if pad else w
    rows = jnp.arange(nb * block, dtype=idx.dtype)

    def per_block(args):
        iblk, wblk, rblk = args
        sblk = jnp.where(iblk < 0, n, iblk)
        non = jnp.take(safe_tab, sblk, axis=0)   # (block, k, k)
        nw = jnp.take(w_tab, sblk, axis=0)       # (block, k, k)
        hit = non == rblk[:, None, None]
        w_rev = jnp.sum(jnp.where(hit, nw, 0.0), axis=2)
        has_rev = jnp.any(hit, axis=2)
        if mode == "mutual":
            return jnp.where(has_rev, 0.5 * (wblk + w_rev), 0.0)
        if mode == "union":
            return wblk + w_rev - wblk * w_rev
        if mode == "union_norm":
            return (wblk + w_rev - wblk * w_rev) / (1.0 + has_rev)
        return jnp.where(has_rev, 0.5 * (wblk + w_rev), wblk)

    out = jax.lax.map(per_block, (idx_p.reshape(nb, block, k),
                                  w_p.reshape(nb, block, k),
                                  rows.reshape(nb, block)))
    return out.reshape(-1, k)[:n]


@register("graph.diffusion_operator", backend="tpu")
def diffusion_operator_tpu(data: CellData, symmetrize: bool = True) -> CellData:
    """Row-normalised diffusion weights from connectivities.

    With ``symmetrize`` the kernel is (W + Wᵀ)/2 restricted to the
    existing edge pattern (the reverse-edge weight is looked up via a
    segment-mean approximation: w_sym(i→j) = (w_ij + w_ji)/2 where
    w_ji is taken as w_ij when the reverse edge is absent).
    Adds obsp["diffusion_weights"] (row-stochastic, aligned with
    knn_indices).
    """
    if "connectivities" not in data.obsp:
        data = connectivities_tpu(data)
    idx, _ = _require_knn(data)
    w = jnp.asarray(data.obsp["connectivities"])[: data.n_cells]
    if symmetrize:
        w = _symmetrized_weights(idx, w)
    row = jnp.sum(jnp.where(idx < 0, 0.0, w), axis=1, keepdims=True)
    p = jnp.where(idx < 0, 0.0, w) / jnp.maximum(row, 1e-12)
    return data.with_obsp(diffusion_weights=p)


@register("graph.diffusion_operator", backend="cpu")
def diffusion_operator_cpu(data: CellData, symmetrize: bool = True) -> CellData:
    import scipy.sparse as sp

    if "connectivities" not in data.obsp:
        data = connectivities_cpu(data)
    n = data.n_cells
    idx = np.asarray(data.obsp["knn_indices"])[:n]
    w = np.asarray(data.obsp["connectivities"], np.float64)[:n]
    k = idx.shape[1]
    rows = np.repeat(np.arange(n), k)
    cols = idx.reshape(-1)
    vals = w.reshape(-1)
    keep = cols >= 0
    W = sp.csr_matrix((vals[keep], (rows[keep], cols[keep])), shape=(n, n))
    if symmetrize:
        Wt = W.T.tocsr()
        # restrict to existing edge pattern: (w_ij + w_ji)/2 where both
        # exist, else w_ij  (matches the TPU edge-list semantics)
        both = W.multiply(Wt.astype(bool).astype(np.float64))
        W = W - 0.5 * both + 0.5 * Wt.multiply(W.astype(bool).astype(np.float64))
    # read back into edge-list aligned with knn_indices
    p = np.zeros_like(w)
    Wc = W.tocsr()
    for i in range(n):
        row = {c: v for c, v in zip(Wc.indices[Wc.indptr[i]:Wc.indptr[i+1]],
                                    Wc.data[Wc.indptr[i]:Wc.indptr[i+1]])}
        for j in range(k):
            if idx[i, j] >= 0:
                p[i, j] = row.get(idx[i, j], 0.0)
    rs = p.sum(1, keepdims=True)
    p = p / np.maximum(rs, 1e-12)
    return data.with_obsp(diffusion_weights=p.astype(np.float32))


# ----------------------------------------------------------------------
# impute.magic — diffusion imputation (X_imputed = Pᵗ X)
# ----------------------------------------------------------------------


@register("impute.magic", backend="tpu", sharding="cells",
          collective=True)
def magic_tpu(data: CellData, t: int = 3, use_rep: str = "X",
              n_genes_out: int | None = None, mesh=None,
              strategy: str = "all_gather") -> CellData:
    """MAGIC-style imputation: t diffusion steps of the expression
    matrix along the cell graph.  Adds obsm["X_magic"] (dense
    (n, n_genes_out or n_genes)).  Densifies gene space — subset genes
    first (hvg.select(subset=True)) for large panels.  ``mesh=`` runs
    the diffusion cells-sharded as one mesh program (t steps inside
    the program — ``parallel.diffuse_sharded``); ``strategy="ring"``
    bounds per-device memory at one chunk for wide gene panels."""
    if "diffusion_weights" not in data.obsp:
        data = diffusion_operator_tpu(data)
    idx, _ = _require_knn(data)
    n = data.n_cells
    p = jnp.asarray(data.obsp["diffusion_weights"])[:n]
    if use_rep == "X":
        X = data.X
        Xd = X.to_dense() if isinstance(X, SparseCells) else (
            jnp.asarray(X)[:n])
    else:
        Xd = jnp.asarray(data.obsm[use_rep])[:n]
    if n_genes_out is not None:
        Xd = Xd[:, :n_genes_out]
    Xd = Xd.astype(jnp.float32)

    if mesh is not None:
        from ..parallel.graph_multichip import (diffuse_sharded,
                                                pad_rows_for_mesh)

        idx_p, p_p, X_p, _ = pad_rows_for_mesh(
            mesh, idx=idx[:n], weights=p, x=Xd, who="impute.magic")
        out = diffuse_sharded(idx_p, p_p, X_p, mesh, t,
                              strategy=strategy)[:n]
        return data.with_obsm(X_magic=out).with_uns(magic_t=t)

    def step(x, _):
        return knn_matvec(idx, p, x), None

    out, _ = jax.lax.scan(step, Xd, None, length=t)
    return data.with_obsm(X_magic=out).with_uns(magic_t=t)


@register("impute.magic", backend="cpu")
def magic_cpu(data: CellData, t: int = 3, use_rep: str = "X",
              n_genes_out: int | None = None) -> CellData:
    import scipy.sparse as sp

    if "diffusion_weights" not in data.obsp:
        data = diffusion_operator_cpu(data)
    n = data.n_cells
    idx = np.asarray(data.obsp["knn_indices"])[:n]
    p = np.asarray(data.obsp["diffusion_weights"], np.float64)[:n]
    if use_rep == "X":
        X = data.X
        Xd = np.asarray(X.todense()) if sp.issparse(X) else np.asarray(X)[:n]
    else:
        Xd = np.asarray(data.obsm[use_rep])[:n]
    if n_genes_out is not None:
        Xd = Xd[:, :n_genes_out]
    out = Xd.astype(np.float64)
    safe = np.where(idx < 0, 0, idx)
    w = np.where(idx < 0, 0.0, p)
    for _ in range(t):
        out = np.einsum("nk,nkd->nd", w, out[safe])
    return data.with_obsm(X_magic=out.astype(np.float32)).with_uns(magic_t=t)


# ----------------------------------------------------------------------
# embed.spectral — diffusion-map embedding (top eigenvectors of P)
# ----------------------------------------------------------------------


def _sym_normalized_edges(idx, w):
    """Edge weights of S = D^-1/2 W_mutual D^-1/2 plus the degree
    vector.  W_mutual is exactly symmetric (one-sided edges dropped),
    so S is symmetric and its spectrum is real in [-1, 1]."""
    wm = _symmetrized_weights(idx, w, mode="mutual")
    wm = jnp.where(idx < 0, 0.0, wm)
    deg = jnp.sum(wm, axis=1)
    inv_sqrt = jnp.where(deg > 0, 1.0 / jnp.sqrt(jnp.maximum(deg, 1e-12)), 0.0)
    safe = jnp.where(idx < 0, 0, idx)
    s = wm * inv_sqrt[:, None] * jnp.take(inv_sqrt, safe, axis=0)
    return s, deg, inv_sqrt


@partial(jax.jit, static_argnames=("n_comps", "n_iter"))
def diffusion_eigs(knn_idx, s_edges, key, n_comps: int = 15,
                   n_iter: int = 60):
    """Leading eigenpairs of the symmetric normalised operator S via
    subspace iteration with CholeskyQR2 + Rayleigh–Ritz (matrix-free:
    only knn_matvec).  Ordered by descending eigenvalue."""
    from .pca import cholesky_qr

    n = knn_idx.shape[0]
    V = jax.random.normal(key, (n, n_comps + 5), jnp.float32)
    V = cholesky_qr(V)

    def step(V, _):
        # shift: (S + I)/2 maps spectrum to [0, 1] so the largest
        # *algebraic* eigenvalues dominate the iteration, not the
        # largest-magnitude (possibly negative) ones
        V = 0.5 * (knn_matvec(knn_idx, s_edges, V) + V)
        return cholesky_qr(V), None

    V, _ = jax.lax.scan(step, V, None, length=n_iter)
    SV = knn_matvec(knn_idx, s_edges, V)
    H = jnp.dot(V.T, SV, preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST)
    evals, W = jnp.linalg.eigh(0.5 * (H + H.T))
    order = jnp.argsort(-evals)[: n_comps]
    rot = jnp.dot(V, W, preferred_element_type=jnp.float32,
                  precision=jax.lax.Precision.HIGHEST)  # Ritz rotation
    return evals[order], rot[:, order]


@register("embed.spectral", backend="tpu")
def spectral_tpu(data: CellData, n_comps: int = 15, seed: int = 0,
                 drop_first: bool = True) -> CellData:
    """Diffusion-map embedding from the symmetric normalised kernel;
    eigenvectors are mapped back to the random-walk convention
    (ψ = D^-1/2 φ, unit-normalised).  Adds obsm["X_diffmap"],
    uns["diffmap_evals"].  The trivial top eigenvector is dropped by
    default."""
    if "connectivities" not in data.obsp:
        data = connectivities_tpu(data)
    idx, _ = _require_knn(data)
    w = jnp.asarray(data.obsp["connectivities"])[: data.n_cells]
    s, deg, inv_sqrt = _sym_normalized_edges(idx, w)
    extra = 1 if drop_first else 0
    evals, phi = diffusion_eigs(idx, s, jax.random.PRNGKey(seed),
                                n_comps=n_comps + extra)
    psi = phi * inv_sqrt[:, None]
    psi = psi / jnp.maximum(jnp.linalg.norm(psi, axis=0, keepdims=True), 1e-12)
    if drop_first:
        evals, psi = evals[1:], psi[:, 1:]
    return data.with_obsm(X_diffmap=psi).with_uns(diffmap_evals=evals)


@register("embed.spectral", backend="cpu")
def spectral_cpu(data: CellData, n_comps: int = 15, seed: int = 0,
                 drop_first: bool = True) -> CellData:
    import scipy.sparse as sp

    if "connectivities" not in data.obsp:
        data = connectivities_cpu(data)
    n = data.n_cells
    idx = np.asarray(data.obsp["knn_indices"])[:n]
    w = np.asarray(data.obsp["connectivities"], np.float64)[:n]
    k = idx.shape[1]
    rows = np.repeat(np.arange(n), k)
    cols = idx.reshape(-1)
    keep = cols >= 0
    W = sp.csr_matrix((w.reshape(-1)[keep], (rows[keep], cols[keep])),
                      shape=(n, n))
    # mutual symmetrisation: average where both directions exist
    maskT = W.T.astype(bool)
    Wm = 0.5 * (W.multiply(maskT) + W.T.multiply(W.astype(bool)))
    deg = np.asarray(Wm.sum(axis=1)).ravel()
    inv_sqrt = np.where(deg > 0, 1.0 / np.sqrt(np.maximum(deg, 1e-12)), 0.0)
    Di = sp.diags(inv_sqrt)
    S = Di @ Wm @ Di
    extra = 1 if drop_first else 0
    # Multi-vector subspace iteration (same scheme as the TPU path):
    # single-vector Lanczos (eigsh) under-resolves the degenerate
    # unit eigenspace of graphs with weakly/mutually-disconnected
    # components — verified against dense eigvalsh.
    rng = np.random.default_rng(seed)
    m = n_comps + extra + 5
    V = np.linalg.qr(rng.standard_normal((n, m)))[0]
    for _ in range(60):
        V = np.linalg.qr(0.5 * (S @ V + V))[0]
    H = V.T @ (S @ V)
    evals, W_ = np.linalg.eigh(0.5 * (H + H.T))
    order = np.argsort(-evals)[: n_comps + extra]
    evals = evals[order]
    phi = V @ W_[:, order]
    psi = phi * inv_sqrt[:, None]
    psi = psi / np.maximum(np.linalg.norm(psi, axis=0, keepdims=True), 1e-12)
    if drop_first:
        evals, psi = evals[1:], psi[:, 1:]
    return data.with_obsm(X_diffmap=psi.astype(np.float32)).with_uns(
        diffmap_evals=evals.astype(np.float32))


# ----------------------------------------------------------------------
# dpt.pseudotime — diffusion pseudotime from a root cell
# ----------------------------------------------------------------------


@register("dpt.pseudotime", backend="tpu")
def dpt_tpu(data: CellData, root: int = 0) -> CellData:
    """Diffusion-distance pseudotime: Euclidean distance to the root
    in eigenvalue-rescaled diffusion-map space (DPT's closed form).
    Requires embed.spectral.  Adds obs["dpt_pseudotime"]."""
    if "X_diffmap" not in data.obsm:
        data = spectral_tpu(data)
    V = jnp.asarray(data.obsm["X_diffmap"])
    ev = jnp.asarray(data.uns["diffmap_evals"])
    scale = ev / jnp.maximum(1.0 - ev, 1e-6)
    Z = V * scale[None, :]
    d = jnp.linalg.norm(Z - Z[root], axis=1)
    d = d / jnp.maximum(jnp.max(d), 1e-12)
    return data.with_obs(dpt_pseudotime=d).with_uns(dpt_root=root)


@register("dpt.pseudotime", backend="cpu")
def dpt_cpu(data: CellData, root: int = 0) -> CellData:
    if "X_diffmap" not in data.obsm:
        data = spectral_cpu(data)
    V = np.asarray(data.obsm["X_diffmap"], np.float64)
    ev = np.asarray(data.uns["diffmap_evals"], np.float64)
    scale = ev / np.maximum(1.0 - ev, 1e-6)
    Z = V * scale[None, :]
    d = np.linalg.norm(Z - Z[root], axis=1)
    d = d / max(d.max(), 1e-12)
    return data.with_obs(dpt_pseudotime=d.astype(np.float32)).with_uns(
        dpt_root=root)


# ----------------------------------------------------------------------
# graph.paga — partition-based graph abstraction
# ----------------------------------------------------------------------


def _paga_stats(idx, w, labels, n_groups):
    """Inter-group connectivity statistics on the weighted kNN edge
    list (host numpy — the group graph is tiny; the per-cell work
    upstream was the device's job).

    theta follows the scanpy ``tl.paga`` v1.2 convention: the
    symmetrised inter-group edge WEIGHT divided by its random-wiring
    expectation ``(es_i·n_j + es_j·n_i)/(n−1)`` — where ``es_g`` is
    the total edge weight incident to group g and ``n_g`` its size —
    clipped to [0, 1].  No global re-normalisation: absolute
    thresholds carried over from scanpy keep their meaning.
    """
    n, k = idx.shape
    rows = np.repeat(labels, k)
    cols = idx.reshape(-1)
    wf = np.asarray(w, np.float64).reshape(-1)
    # self-edges carry no inter-group information and would inflate es
    keep = (cols >= 0) & (wf > 0) & (cols != np.repeat(np.arange(n), k))
    lj = labels[np.clip(cols, 0, n - 1)]
    import scipy.sparse as sp

    W = sp.coo_matrix((wf[keep], (rows[keep], lj[keep])),
                      shape=(n_groups, n_groups)).toarray()
    C = W + W.T  # symmetrised inter-group weight (each edge ≤ twice)
    np.fill_diagonal(C, 0.0)
    sizes = np.bincount(labels, minlength=n_groups).astype(np.float64)
    es = W.sum(axis=1) + W.sum(axis=0)  # total incident weight per group
    expected = (np.outer(es, sizes) + np.outer(sizes, es)) / max(n - 1, 1)
    np.fill_diagonal(expected, 1.0)
    theta = np.clip(C / np.maximum(expected, 1e-12), 0.0, 1.0)
    np.fill_diagonal(theta, 0.0)
    return C, expected, theta.astype(np.float32)


def _paga_impl(data: CellData, groups: str) -> CellData:
    if groups not in data.obs:
        raise KeyError(
            f"obs has no {groups!r} — run cluster.leiden (or another "
            "clustering) first")
    idx, _ = _require_knn(data)
    n = data.n_cells
    idx = np.asarray(idx)[:n]
    w = None
    if "connectivities" in data.obsp:
        cand = np.asarray(data.obsp["connectivities"], np.float64)[:n]
        if cand.shape == idx.shape:
            w = cand
        else:
            import warnings

            warnings.warn(
                "graph.paga: obsp['connectivities'] shape "
                f"{cand.shape} does not match the current kNN graph "
                f"{idx.shape} (stale after a kNN rebuild?) — using "
                "unit edge weights", stacklevel=3)
    if w is None:
        w = np.ones_like(idx, np.float64)
    labels = np.asarray(data.obs[groups])[:n]
    uniq, codes = np.unique(labels, return_inverse=True)
    C, exp, theta = _paga_stats(idx, w, codes.astype(np.int64), len(uniq))
    return data.with_uns(
        paga_connectivities=theta,
        paga_edge_weights=C.astype(np.float32),
        paga_groups=uniq,
        # the obs column the abstraction was computed over (scanpy
        # stores uns['paga']['groups']); pl.paga must not have to
        # guess it by level-matching across obs columns
        paga_groups_key=groups)


@register("graph.paga", backend="tpu")
def paga_tpu(data: CellData, groups: str = "leiden") -> CellData:
    """PAGA (partition-based graph abstraction): the cluster-level
    connectivity map — symmetrised inter-group edge weight over the
    degree-based random-wiring expectation, clipped to [0, 1] (the
    scanpy ``tl.paga`` v1.2 formula — see _paga_stats).  Requires
    neighbors.knn + a clustering in ``obs[groups]``; uses
    obsp["connectivities"] weights when they match the current graph.
    Adds uns["paga_connectivities"] (G × G),
    uns["paga_edge_weights"], uns["paga_groups"].

    The group graph is a few thousand entries at most — this is host
    bookkeeping over the device-built kNN graph, identical on both
    backends by construction."""
    return _paga_impl(data, groups)


@register("graph.paga", backend="cpu")
def paga_cpu(data: CellData, groups: str = "leiden") -> CellData:
    return _paga_impl(data, groups)


# ----------------------------------------------------------------------
# embed.diffmap — scanpy's name for the diffusion-map embedding
# ----------------------------------------------------------------------


@register("embed.diffmap", backend="tpu")
def diffmap_tpu(data: CellData, n_comps: int = 15, seed: int = 0,
                drop_first: bool = True) -> CellData:
    """scanpy ``tl.diffmap`` naming for ``embed.spectral`` — identical
    computation (the two public APIs describe the same diffusion-map
    eigendecomposition); registered separately so reference users find
    it under the name they know."""
    return spectral_tpu(data, n_comps=n_comps, seed=seed,
                        drop_first=drop_first)


@register("embed.diffmap", backend="cpu")
def diffmap_cpu(data: CellData, n_comps: int = 15, seed: int = 0,
                drop_first: bool = True) -> CellData:
    return spectral_cpu(data, n_comps=n_comps, seed=seed,
                        drop_first=drop_first)
