"""Neighbour-graph ops: ``graph.connectivities`` (UMAP-style fuzzy
weights / gaussian kernel), ``graph.diffusion_operator`` (row-
normalised transition matrix), ``impute.magic`` (diffusion
imputation), ``embed.spectral`` (diffusion-map embedding),
``dpt.pseudotime`` (diffusion pseudotime from a root cell).

TPU design: the kNN graph is kept in its padded (n, k) edge-list form
— exactly the shape ``neighbors.knn`` produces — and every graph
operation is either per-edge VPU work or a gather+reduce along the k
axis.  ``P @ X`` (diffusion steps) is a k-sparse matvec: gather k
rows of X, weight, sum — O(n·k·d), chunked over rows.  The symmetric
normalised operator uses the edge-reversed weights via one
segment-sum.  Spectral embedding reuses the randomized eigensolver
machinery from PCA (subspace iteration with CholeskyQR2) on the
diffusion operator — matrix-free, multi-chip-sharding friendly.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..data.dataset import CellData
from ..data.sparse import SparseCells
from ..registry import register


def _require_knn(data: CellData):
    if "knn_indices" not in data.obsp:
        raise ValueError("run neighbors.knn (or knn_multichip) first")
    n = data.n_cells
    idx = jnp.asarray(data.obsp["knn_indices"])[:n]
    dist = jnp.asarray(data.obsp["knn_distances"])[:n]
    return idx, dist


# ----------------------------------------------------------------------
# graph.connectivities
# ----------------------------------------------------------------------


@partial(jax.jit, static_argnames=("mode",))
def connectivities_arrays(knn_idx, knn_dist, mode: str = "umap"):
    """Edge weights from distances.

    "umap": the fuzzy-simplicial-set weights exp(-(d - rho)/sigma)
    with rho = distance to nearest neighbour and sigma calibrated so
    the weights sum to log2(k) per row (binary search, fixed 20
    iterations — the smooth-kNN calibration of UMAP).
    "gaussian": exp(-d² / (2 σ²)) with σ = mean kNN distance per row.

    Self-edges (``neighbors.knn`` includes self at distance 0 by
    default) are excluded: they get weight 0 and do not enter rho/σ —
    otherwise rho would always be 0 and the self-weight 1.0 would eat
    part of the log2(k) calibration budget.
    """
    n = knn_idx.shape[0]
    is_self = knn_idx == jnp.arange(n, dtype=knn_idx.dtype)[:, None]
    d = jnp.where((knn_idx < 0) | is_self, jnp.inf,
                  knn_dist.astype(jnp.float32))
    if mode == "gaussian":
        finite = jnp.isfinite(d)
        sigma = jnp.sum(jnp.where(finite, d, 0.0), axis=1) / jnp.maximum(
            jnp.sum(finite, axis=1), 1)
        w = jnp.exp(-(d**2) / jnp.maximum(2.0 * sigma[:, None] ** 2, 1e-12))
        return jnp.where(finite, w, 0.0)
    if mode != "umap":
        raise ValueError(f"unknown connectivity mode {mode!r}")
    k = knn_idx.shape[1]
    target = jnp.log2(jnp.float32(max(k, 2)))
    rho = jnp.min(jnp.where(jnp.isfinite(d), d, jnp.inf), axis=1)
    shifted = jnp.maximum(d - rho[:, None], 0.0)

    def weight_sum(sigma):
        w = jnp.exp(-shifted / jnp.maximum(sigma[:, None], 1e-12))
        return jnp.sum(jnp.where(jnp.isfinite(d), w, 0.0), axis=1)

    lo = jnp.full(d.shape[0], 1e-6)
    hi = jnp.full(d.shape[0], 1e3)

    def bisect(_, carry):
        lo, hi = carry
        mid = 0.5 * (lo + hi)
        too_small = weight_sum(mid) < target  # need larger sigma
        lo = jnp.where(too_small, mid, lo)
        hi = jnp.where(too_small, hi, mid)
        return lo, hi

    lo, hi = jax.lax.fori_loop(0, 20, bisect, (lo, hi))
    sigma = 0.5 * (lo + hi)
    w = jnp.exp(-shifted / jnp.maximum(sigma[:, None], 1e-12))
    return jnp.where(jnp.isfinite(d), w, 0.0)


@register("graph.connectivities", backend="tpu", fusable=True,
          sharding="cells")
def connectivities_tpu(data: CellData, mode: str = "umap") -> CellData:
    """Adds obsp["connectivities"] (aligned with knn_indices)."""
    idx, dist = _require_knn(data)
    w = connectivities_arrays(idx, dist, mode=mode)
    return data.with_obsp(connectivities=w).with_uns(connectivity_mode=mode)


@register("graph.connectivities", backend="cpu")
def connectivities_cpu(data: CellData, mode: str = "umap") -> CellData:
    idx = np.asarray(data.obsp["knn_indices"])[: data.n_cells]
    dist = np.asarray(data.obsp["knn_distances"], np.float64)[: data.n_cells]
    is_self = idx == np.arange(len(idx))[:, None]
    d = np.where((idx < 0) | is_self, np.inf, dist)
    if mode == "gaussian":
        finite = np.isfinite(d)
        sigma = np.where(finite, d, 0.0).sum(1) / np.maximum(finite.sum(1), 1)
        w = np.exp(-(d**2) / np.maximum(2 * sigma[:, None] ** 2, 1e-12))
        w = np.where(finite, w, 0.0)
    elif mode == "umap":
        k = idx.shape[1]
        target = np.log2(max(k, 2))
        rho = np.min(np.where(np.isfinite(d), d, np.inf), axis=1)
        shifted = np.maximum(d - rho[:, None], 0.0)
        lo = np.full(len(d), 1e-6)
        hi = np.full(len(d), 1e3)
        for _ in range(20):
            mid = 0.5 * (lo + hi)
            w = np.exp(-shifted / np.maximum(mid[:, None], 1e-12))
            s = np.where(np.isfinite(d), w, 0.0).sum(1)
            small = s < target
            lo = np.where(small, mid, lo)
            hi = np.where(small, hi, mid)
        sigma = 0.5 * (lo + hi)
        w = np.exp(-shifted / np.maximum(sigma[:, None], 1e-12))
        w = np.where(np.isfinite(d), w, 0.0)
    else:
        raise ValueError(f"unknown connectivity mode {mode!r}")
    return data.with_obsp(connectivities=w.astype(np.float32)).with_uns(
        connectivity_mode=mode)


# ----------------------------------------------------------------------
# graph.jaccard — neighbour-set Jaccard weights (PhenoGraph's kernel)
# ----------------------------------------------------------------------


@partial(jax.jit, static_argnames=("block",))
def jaccard_arrays(knn_idx, block: int = 1024):
    """Per-edge Jaccard similarity of neighbour sets:
    ``J(i→j) = |N(i) ∩ N(j)| / |N(i) ∪ N(j)|``.

    TPU mapping: per row block, gather each neighbour's neighbour list
    (``(block, k, k)``) and count matches against the row's own list
    with a broadcast equality mask (``(block, k, k, k)`` bools —
    k ≤ ~60 keeps this in VMEM-scale tiles) — pure VPU reductions, no
    scatter.  -1 slots are excluded from both sets; the result is 0 on
    missing edges.
    """
    n, k = knn_idx.shape
    # row n of the lookup table = all -2: a -1 neighbour maps there and
    # can never match a real id (own list uses -3 for its padding)
    tab = jnp.concatenate(
        [jnp.where(knn_idx < 0, -2, knn_idx),
         jnp.full((1, k), -2, knn_idx.dtype)])
    nb = -(-n // block)
    pad = nb * block - n
    idx_p = (jnp.concatenate([knn_idx, jnp.full((pad, k), -1, knn_idx.dtype)])
             if pad else knn_idx)

    def per_block(iblk):  # (block, k)
        own = jnp.where(iblk < 0, -3, iblk)
        safe = jnp.where(iblk < 0, n, iblk)
        nbr = jnp.take(tab, safe, axis=0)  # (block, k, k)
        eq = nbr[:, :, :, None] == own[:, None, None, :]
        inter = jnp.sum(eq, axis=(2, 3)).astype(jnp.float32)  # (block, k)
        vi = jnp.sum(iblk >= 0, axis=1).astype(jnp.float32)  # (block,)
        vj = jnp.sum(nbr >= 0, axis=2).astype(jnp.float32)  # (block, k)
        union = vi[:, None] + vj - inter
        return jnp.where(iblk < 0, 0.0, inter / jnp.maximum(union, 1.0))

    out = jax.lax.map(per_block, idx_p.reshape(nb, block, k))
    return out.reshape(-1, k)[:n]


def _fusable_unless_pallas(_params: dict) -> bool:
    """Fusability predicate for ops whose tpu body dispatches into
    the tiled graph-kernel family: when the resolved impl is the
    Pallas kernels (real TPU), the op must stay an EAGER step — a
    ``pl.pallas_call`` cannot be traced inside a mesh-sharded
    (GSPMD ``in_shardings``) fused stage, and the kernel dominates
    the op's wall anyway, so fusion loses little.  Off-TPU (the
    blocked-XLA twins) the op fuses as usual.  Evaluated at plan
    build time, like every fusability predicate."""
    from .pallas_graph import resolved_impl

    return resolved_impl() != "pallas"


def _jaccard_mem_shrink(params: dict) -> dict | None:
    """OOM-ladder middle rung (``registry mem_shrink=``): halve the
    row-tile size — the device path's per-tile working set
    (``(block, k, k)`` gathers and match masks) halves with it while
    the result is bitwise unchanged (``block`` only tiles the rows).
    Floor 64: below that the tile no longer dominates the live set."""
    b = int(params.get("block", 1024))
    if b <= 64:
        return None
    params["block"] = b // 2
    return params


@register("graph.jaccard", backend="tpu",
          fusable=_fusable_unless_pallas, sharding="cells",
          mem_cost=3.0, mem_shrink=_jaccard_mem_shrink)
def jaccard_tpu(data: CellData, block: int = 1024) -> CellData:
    """Adds obsp["jaccard"] (aligned with knn_indices).  Runs through
    the tiled graph-kernel family (ops/pallas_graph.py): the banded
    Pallas kernel on TPU, the legacy blocked equality-mask pass
    elsewhere — counts are exact integers, so results are identical
    on every impl.  ``block`` is the row-tile size."""
    from .pallas_graph import jaccard as _jaccard_tiled

    idx, _ = _require_knn(data)
    band = data.uns.get("graph_bandwidth")
    return data.with_obsp(jaccard=_jaccard_tiled(
        idx, block=block,
        band_rows=int(band) if band is not None else None))


@register("graph.jaccard", backend="cpu")
def jaccard_cpu(data: CellData, block: int = 1024) -> CellData:
    """Numpy set oracle.  ``block`` is accepted for signature parity
    with the tpu backend — it is the device path's row-tile size and
    has no effect on the sequential oracle (results are identical for
    every value); it used to be swallowed by ``**_ignored``, which
    silently accepted typos too."""
    del block  # tiling knob; the oracle is row-sequential
    idx = np.asarray(data.obsp["knn_indices"])[: data.n_cells]
    n, k = idx.shape
    out = np.zeros((n, k), np.float32)
    sets = [set(r[r >= 0].tolist()) for r in idx]
    for i in range(n):
        si = sets[i]
        for e, j in enumerate(idx[i]):
            if j < 0:
                continue
            sj = sets[j]
            inter = len(si & sj)
            union = len(si) + len(sj) - inter
            out[i, e] = inter / max(union, 1)
    return data.with_obsp(jaccard=out)


# ----------------------------------------------------------------------
# Diffusion operator + sparse matvec on the kNN edge list
# ----------------------------------------------------------------------


@jax.jit
def _knn_matvec_gather(knn_idx, weights, x):
    """The legacy whole-graph gather path of ``knn_matvec`` — kept
    registered as the correctness fallback the
    ``SCTOOLS_PALLAS_GRAPH=0`` escape hatch restores (the tiled
    family in ops/pallas_graph.py is the hot path)."""
    safe = jnp.where(knn_idx < 0, 0, knn_idx)
    w = jnp.where(knn_idx < 0, 0.0, weights)
    gathered = jnp.take(x, safe, axis=0)  # (n, k, d)
    return jnp.einsum("nk,nkd->nd", w, gathered,
                      precision=jax.lax.Precision.HIGHEST)


def knn_matvec(knn_idx, weights, x, band_rows: int | None = None,
               impl: str | None = None):
    """``P @ x`` where P is the (n, k)-edge-list sparse matrix.

    x: (n, d).  Gather-weight-sum along k; O(n·k·d).  Dispatches to
    the tiled graph-kernel family (ops/pallas_graph.py —
    ``config.graph_impl``): the blocked-XLA twin is bitwise identical
    to the legacy gather; the Pallas banded kernel agrees to f32
    reduction-order ulps.  ``band_rows`` (from
    ``uns['graph_bandwidth']`` after ``graph.reorder``) bounds the
    Pallas banded sweep; pass it STATICALLY when calling from inside
    an enclosing ``jax.jit``; so must ``impl`` (see
    ``pallas_graph.matvec`` — jitted callers thread the resolved
    impl statically or their cached traces ignore config flips)."""
    from .pallas_graph import matvec

    return matvec(knn_idx, weights, x, band_rows=band_rows, impl=impl)


@partial(jax.jit, static_argnames=("n",))
def _knn_rmatvec_segsum(knn_idx, weights, x, n: int | None = None):
    """Legacy segment-sum path of ``knn_rmatvec`` (the xla/gather
    impls of the tiled family share it — its (n, k, d) intermediate
    is small for the d=1..T callers)."""
    n = n if n is not None else x.shape[0]
    safe = jnp.where(knn_idx < 0, n, knn_idx)  # dropped bin
    w = jnp.where(knn_idx < 0, 0.0, weights)
    contrib = w[:, :, None] * x[:, None, :]  # (n, k, d)
    flat = contrib.reshape(-1, x.shape[-1])
    out = jax.ops.segment_sum(flat, safe.reshape(-1), num_segments=n + 1)
    return out[:n]


def knn_rmatvec(knn_idx, weights, x, n: int | None = None,
                band_rows: int | None = None,
                impl: str | None = None):
    """``Pᵀ @ x`` via segment-sum over edges (adjoint of knn_matvec;
    used for reverse-mode flows and left-eigenvector iterations).
    Dispatches like :func:`knn_matvec` — the Pallas path runs the
    transposed banded kernel."""
    from .pallas_graph import rmatvec

    return rmatvec(knn_idx, weights, x, n=n, band_rows=band_rows,
                   impl=impl)


@partial(jax.jit, static_argnames=("mode",))
def _symmetrized_weights(idx, w, block: int = 8192, mode: str = "average"):
    """Symmetrise edge weights on the kNN edge list.

    "average": w_sym(i→j) = (w_ij + w_ji)/2 when the reverse edge
    exists, else w_ij (keeps all edges; operator only approximately
    symmetric — fine for diffusion smoothing).
    "mutual": same average but one-sided edges are dropped — the
    resulting kernel is *exactly* symmetric, which the spectral path
    requires.
    "union": the probabilistic t-conorm ``w + w' - w·w'`` (UMAP's
    fuzzy-set union; one-sided edges keep their weight).
    "union_norm": the t-conorm divided by the edge's directed
    multiplicity (1 + has-reverse-edge) — one pass of the reverse
    lookup instead of two for layouts that apply a symmetric
    reaction per directed entry (embed.umap).
    The reverse-edge lookup is an (block, k, k) equality
    mask, chunked over rows so the full (n, k, k) never materialises."""
    n, k = idx.shape
    # Lookup tables padded with a sentinel row of -2s: a -1 neighbour
    # slot maps to row n, whose "neighbours" (-2) can never equal a
    # real row id — otherwise -1 slots would alias row 0 and fabricate
    # reverse edges for it, breaking the mutual mode's exact symmetry.
    safe_tab = jnp.concatenate(
        [jnp.where(idx < 0, -2, idx), jnp.full((1, k), -2, idx.dtype)])
    w_tab = jnp.concatenate([w, jnp.zeros((1, k), w.dtype)])
    nb = -(-n // block)
    pad = nb * block - n
    idx_p = jnp.concatenate([idx, jnp.full((pad, k), -1, idx.dtype)]) if pad else idx
    w_p = jnp.concatenate([w, jnp.zeros((pad, k), w.dtype)]) if pad else w
    rows = jnp.arange(nb * block, dtype=idx.dtype)

    def per_block(args):
        iblk, wblk, rblk = args
        sblk = jnp.where(iblk < 0, n, iblk)
        non = jnp.take(safe_tab, sblk, axis=0)   # (block, k, k)
        nw = jnp.take(w_tab, sblk, axis=0)       # (block, k, k)
        hit = non == rblk[:, None, None]
        w_rev = jnp.sum(jnp.where(hit, nw, 0.0), axis=2)
        has_rev = jnp.any(hit, axis=2)
        if mode == "mutual":
            return jnp.where(has_rev, 0.5 * (wblk + w_rev), 0.0)
        if mode == "union":
            return wblk + w_rev - wblk * w_rev
        if mode == "union_norm":
            return (wblk + w_rev - wblk * w_rev) / (1.0 + has_rev)
        return jnp.where(has_rev, 0.5 * (wblk + w_rev), wblk)

    out = jax.lax.map(per_block, (idx_p.reshape(nb, block, k),
                                  w_p.reshape(nb, block, k),
                                  rows.reshape(nb, block)))
    return out.reshape(-1, k)[:n]


@register("graph.diffusion_operator", backend="tpu", fusable=True,
          sharding="cells")
def diffusion_operator_tpu(data: CellData, symmetrize: bool = True) -> CellData:
    """Row-normalised diffusion weights from connectivities.

    With ``symmetrize`` the kernel is (W + Wᵀ)/2 restricted to the
    existing edge pattern (the reverse-edge weight is looked up via a
    segment-mean approximation: w_sym(i→j) = (w_ij + w_ji)/2 where
    w_ji is taken as w_ij when the reverse edge is absent).
    Adds obsp["diffusion_weights"] (row-stochastic, aligned with
    knn_indices).
    """
    if "connectivities" not in data.obsp:
        data = connectivities_tpu(data)
    idx, _ = _require_knn(data)
    w = jnp.asarray(data.obsp["connectivities"])[: data.n_cells]
    if symmetrize:
        w = _symmetrized_weights(idx, w)
    row = jnp.sum(jnp.where(idx < 0, 0.0, w), axis=1, keepdims=True)
    p = jnp.where(idx < 0, 0.0, w) / jnp.maximum(row, 1e-12)
    return data.with_obsp(diffusion_weights=p)


@register("graph.diffusion_operator", backend="cpu")
def diffusion_operator_cpu(data: CellData, symmetrize: bool = True) -> CellData:
    import scipy.sparse as sp

    if "connectivities" not in data.obsp:
        data = connectivities_cpu(data)
    n = data.n_cells
    idx = np.asarray(data.obsp["knn_indices"])[:n]
    w = np.asarray(data.obsp["connectivities"], np.float64)[:n]
    k = idx.shape[1]
    rows = np.repeat(np.arange(n), k)
    cols = idx.reshape(-1)
    vals = w.reshape(-1)
    keep = cols >= 0
    W = sp.csr_matrix((vals[keep], (rows[keep], cols[keep])), shape=(n, n))
    if symmetrize:
        Wt = W.T.tocsr()
        # restrict to existing edge pattern: (w_ij + w_ji)/2 where both
        # exist, else w_ij  (matches the TPU edge-list semantics)
        both = W.multiply(Wt.astype(bool).astype(np.float64))
        W = W - 0.5 * both + 0.5 * Wt.multiply(W.astype(bool).astype(np.float64))
    # read back into edge-list aligned with knn_indices
    p = np.zeros_like(w)
    Wc = W.tocsr()
    for i in range(n):
        row = {c: v for c, v in zip(Wc.indices[Wc.indptr[i]:Wc.indptr[i+1]],
                                    Wc.data[Wc.indptr[i]:Wc.indptr[i+1]])}
        for j in range(k):
            if idx[i, j] >= 0:
                p[i, j] = row.get(idx[i, j], 0.0)
    rs = p.sum(1, keepdims=True)
    p = p / np.maximum(rs, 1e-12)
    return data.with_obsp(diffusion_weights=p.astype(np.float32))


# ----------------------------------------------------------------------
# impute.magic — diffusion imputation (X_imputed = Pᵗ X)
# ----------------------------------------------------------------------


@register("impute.magic", backend="tpu", sharding="cells",
          collective=True,
          fusable=lambda p: (not p.get("mesh")
                             and _fusable_unless_pallas(p)))
def magic_tpu(data: CellData, t: int = 3, use_rep: str = "X",
              n_genes_out: int | None = None, mesh=None,
              strategy: str = "all_gather") -> CellData:
    """MAGIC-style imputation: t diffusion steps of the expression
    matrix along the cell graph.  Adds obsm["X_magic"] (dense
    (n, n_genes_out or n_genes)).  Densifies gene space — subset genes
    first (hvg.select(subset=True)) for large panels.  ``mesh=`` runs
    the diffusion cells-sharded as one mesh program (t steps inside
    the program — ``parallel.diffuse_sharded``); ``strategy="ring"``
    bounds per-device memory at one chunk for wide gene panels."""
    if "diffusion_weights" not in data.obsp:
        data = diffusion_operator_tpu(data)
    idx, _ = _require_knn(data)
    n = data.n_cells
    p = jnp.asarray(data.obsp["diffusion_weights"])[:n]
    if use_rep == "X":
        X = data.X
        Xd = X.to_dense() if isinstance(X, SparseCells) else (
            jnp.asarray(X)[:n])
    else:
        Xd = jnp.asarray(data.obsm[use_rep])[:n]
    if n_genes_out is not None:
        Xd = Xd[:, :n_genes_out]
    Xd = Xd.astype(jnp.float32)

    if mesh is not None:
        from ..parallel.graph_multichip import (diffuse_sharded,
                                                pad_rows_for_mesh)

        idx_p, p_p, X_p, _ = pad_rows_for_mesh(
            mesh, idx=idx[:n], weights=p, x=Xd, who="impute.magic")
        out = diffuse_sharded(idx_p, p_p, X_p, mesh, t,
                              strategy=strategy)[:n]
        return data.with_obsm(X_magic=out).with_uns(magic_t=t)

    band = data.uns.get("graph_bandwidth")
    band = int(band) if band is not None else None

    def step(x, _):
        return knn_matvec(idx, p, x, band_rows=band), None

    out, _ = jax.lax.scan(step, Xd, None, length=t)
    return data.with_obsm(X_magic=out).with_uns(magic_t=t)


@register("impute.magic", backend="cpu")
def magic_cpu(data: CellData, t: int = 3, use_rep: str = "X",
              n_genes_out: int | None = None) -> CellData:
    import scipy.sparse as sp

    if "diffusion_weights" not in data.obsp:
        data = diffusion_operator_cpu(data)
    n = data.n_cells
    idx = np.asarray(data.obsp["knn_indices"])[:n]
    p = np.asarray(data.obsp["diffusion_weights"], np.float64)[:n]
    if use_rep == "X":
        X = data.X
        Xd = np.asarray(X.todense()) if sp.issparse(X) else np.asarray(X)[:n]
    else:
        Xd = np.asarray(data.obsm[use_rep])[:n]
    if n_genes_out is not None:
        Xd = Xd[:, :n_genes_out]
    out = Xd.astype(np.float64)
    safe = np.where(idx < 0, 0, idx)
    w = np.where(idx < 0, 0.0, p)
    for _ in range(t):
        out = np.einsum("nk,nkd->nd", w, out[safe])
    return data.with_obsm(X_magic=out.astype(np.float32)).with_uns(magic_t=t)


# ----------------------------------------------------------------------
# embed.spectral — diffusion-map embedding (top eigenvectors of P)
# ----------------------------------------------------------------------


def _sym_normalized_edges(idx, w):
    """Edge weights of S = D^-1/2 W_mutual D^-1/2 plus the degree
    vector.  W_mutual is exactly symmetric (one-sided edges dropped),
    so S is symmetric and its spectrum is real in [-1, 1]."""
    wm = _symmetrized_weights(idx, w, mode="mutual")
    wm = jnp.where(idx < 0, 0.0, wm)
    deg = jnp.sum(wm, axis=1)
    inv_sqrt = jnp.where(deg > 0, 1.0 / jnp.sqrt(jnp.maximum(deg, 1e-12)), 0.0)
    safe = jnp.where(idx < 0, 0, idx)
    s = wm * inv_sqrt[:, None] * jnp.take(inv_sqrt, safe, axis=0)
    return s, deg, inv_sqrt


@partial(jax.jit, static_argnames=("n_comps", "n_iter", "band_rows",
                                   "graph_impl"))
def diffusion_eigs(knn_idx, s_edges, key, n_comps: int = 15,
                   n_iter: int = 60, band_rows: int | None = None,
                   graph_impl: str | None = None):
    """Leading eigenpairs of the symmetric normalised operator S via
    subspace iteration with CholeskyQR2 + Rayleigh–Ritz (matrix-free:
    only knn_matvec).  Ordered by descending eigenvalue.
    ``band_rows`` (static — the reordered graph's bandwidth from
    ``graph.reorder``) bounds the banded matvec sweep on the Pallas
    path; ``graph_impl`` (static) pins the tiled-family impl so a
    ``configure(graph_impl=)`` flip re-keys this jit's cache instead
    of being ignored by an earlier trace."""
    from .pca import cholesky_qr

    n = knn_idx.shape[0]
    V = jax.random.normal(key, (n, n_comps + 5), jnp.float32)
    V = cholesky_qr(V)

    def step(V, _):
        # shift: (S + I)/2 maps spectrum to [0, 1] so the largest
        # *algebraic* eigenvalues dominate the iteration, not the
        # largest-magnitude (possibly negative) ones
        V = 0.5 * (knn_matvec(knn_idx, s_edges, V,
                              band_rows=band_rows,
                              impl=graph_impl) + V)
        return cholesky_qr(V), None

    V, _ = jax.lax.scan(step, V, None, length=n_iter)
    SV = knn_matvec(knn_idx, s_edges, V, band_rows=band_rows,
                    impl=graph_impl)
    H = jnp.dot(V.T, SV, preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST)
    evals, W = jnp.linalg.eigh(0.5 * (H + H.T))
    order = jnp.argsort(-evals)[: n_comps]
    rot = jnp.dot(V, W, preferred_element_type=jnp.float32,
                  precision=jax.lax.Precision.HIGHEST)  # Ritz rotation
    return evals[order], rot[:, order]


@register("embed.spectral", backend="tpu")
def spectral_tpu(data: CellData, n_comps: int = 15, seed: int = 0,
                 drop_first: bool = True) -> CellData:
    """Diffusion-map embedding from the symmetric normalised kernel;
    eigenvectors are mapped back to the random-walk convention
    (ψ = D^-1/2 φ, unit-normalised).  Adds obsm["X_diffmap"],
    uns["diffmap_evals"].  The trivial top eigenvector is dropped by
    default."""
    if "connectivities" not in data.obsp:
        data = connectivities_tpu(data)
    idx, _ = _require_knn(data)
    w = jnp.asarray(data.obsp["connectivities"])[: data.n_cells]
    from .pallas_graph import resolved_impl

    s, deg, inv_sqrt = _sym_normalized_edges(idx, w)
    extra = 1 if drop_first else 0
    band = data.uns.get("graph_bandwidth")
    evals, phi = diffusion_eigs(idx, s, jax.random.PRNGKey(seed),
                                n_comps=n_comps + extra,
                                band_rows=(int(band) if band is not None
                                           else None),
                                graph_impl=resolved_impl())
    psi = phi * inv_sqrt[:, None]
    psi = psi / jnp.maximum(jnp.linalg.norm(psi, axis=0, keepdims=True), 1e-12)
    if drop_first:
        evals, psi = evals[1:], psi[:, 1:]
    return data.with_obsm(X_diffmap=psi).with_uns(diffmap_evals=evals)


@register("embed.spectral", backend="cpu")
def spectral_cpu(data: CellData, n_comps: int = 15, seed: int = 0,
                 drop_first: bool = True) -> CellData:
    import scipy.sparse as sp

    if "connectivities" not in data.obsp:
        data = connectivities_cpu(data)
    n = data.n_cells
    idx = np.asarray(data.obsp["knn_indices"])[:n]
    w = np.asarray(data.obsp["connectivities"], np.float64)[:n]
    k = idx.shape[1]
    rows = np.repeat(np.arange(n), k)
    cols = idx.reshape(-1)
    keep = cols >= 0
    W = sp.csr_matrix((w.reshape(-1)[keep], (rows[keep], cols[keep])),
                      shape=(n, n))
    # mutual symmetrisation: average where both directions exist
    maskT = W.T.astype(bool)
    Wm = 0.5 * (W.multiply(maskT) + W.T.multiply(W.astype(bool)))
    deg = np.asarray(Wm.sum(axis=1)).ravel()
    inv_sqrt = np.where(deg > 0, 1.0 / np.sqrt(np.maximum(deg, 1e-12)), 0.0)
    Di = sp.diags(inv_sqrt)
    S = Di @ Wm @ Di
    extra = 1 if drop_first else 0
    # Multi-vector subspace iteration (same scheme as the TPU path):
    # single-vector Lanczos (eigsh) under-resolves the degenerate
    # unit eigenspace of graphs with weakly/mutually-disconnected
    # components — verified against dense eigvalsh.
    rng = np.random.default_rng(seed)
    m = n_comps + extra + 5
    V = np.linalg.qr(rng.standard_normal((n, m)))[0]
    for _ in range(60):
        V = np.linalg.qr(0.5 * (S @ V + V))[0]
    H = V.T @ (S @ V)
    evals, W_ = np.linalg.eigh(0.5 * (H + H.T))
    order = np.argsort(-evals)[: n_comps + extra]
    evals = evals[order]
    phi = V @ W_[:, order]
    psi = phi * inv_sqrt[:, None]
    psi = psi / np.maximum(np.linalg.norm(psi, axis=0, keepdims=True), 1e-12)
    if drop_first:
        evals, psi = evals[1:], psi[:, 1:]
    return data.with_obsm(X_diffmap=psi.astype(np.float32)).with_uns(
        diffmap_evals=evals.astype(np.float32))


# ----------------------------------------------------------------------
# dpt.pseudotime — diffusion pseudotime from a root cell
# ----------------------------------------------------------------------


@register("dpt.pseudotime", backend="tpu")
def dpt_tpu(data: CellData, root: int = 0) -> CellData:
    """Diffusion-distance pseudotime: Euclidean distance to the root
    in eigenvalue-rescaled diffusion-map space (DPT's closed form).
    Requires embed.spectral.  Adds obs["dpt_pseudotime"]."""
    if "X_diffmap" not in data.obsm:
        data = spectral_tpu(data)
    V = jnp.asarray(data.obsm["X_diffmap"])
    ev = jnp.asarray(data.uns["diffmap_evals"])
    scale = ev / jnp.maximum(1.0 - ev, 1e-6)
    Z = V * scale[None, :]
    d = jnp.linalg.norm(Z - Z[root], axis=1)
    d = d / jnp.maximum(jnp.max(d), 1e-12)
    return data.with_obs(dpt_pseudotime=d).with_uns(dpt_root=root)


@register("dpt.pseudotime", backend="cpu")
def dpt_cpu(data: CellData, root: int = 0) -> CellData:
    if "X_diffmap" not in data.obsm:
        data = spectral_cpu(data)
    V = np.asarray(data.obsm["X_diffmap"], np.float64)
    ev = np.asarray(data.uns["diffmap_evals"], np.float64)
    scale = ev / np.maximum(1.0 - ev, 1e-6)
    Z = V * scale[None, :]
    d = np.linalg.norm(Z - Z[root], axis=1)
    d = d / max(d.max(), 1e-12)
    return data.with_obs(dpt_pseudotime=d.astype(np.float32)).with_uns(
        dpt_root=root)


# ----------------------------------------------------------------------
# graph.paga — partition-based graph abstraction
# ----------------------------------------------------------------------


def _paga_stats(idx, w, labels, n_groups):
    """Inter-group connectivity statistics on the weighted kNN edge
    list (host numpy — the group graph is tiny; the per-cell work
    upstream was the device's job).

    theta follows the scanpy ``tl.paga`` v1.2 convention: the
    symmetrised inter-group edge WEIGHT divided by its random-wiring
    expectation ``(es_i·n_j + es_j·n_i)/(n−1)`` — where ``es_g`` is
    the total edge weight incident to group g and ``n_g`` its size —
    clipped to [0, 1].  No global re-normalisation: absolute
    thresholds carried over from scanpy keep their meaning.
    """
    n, k = idx.shape
    rows = np.repeat(labels, k)
    cols = idx.reshape(-1)
    wf = np.asarray(w, np.float64).reshape(-1)
    # self-edges carry no inter-group information and would inflate es
    keep = (cols >= 0) & (wf > 0) & (cols != np.repeat(np.arange(n), k))
    lj = labels[np.clip(cols, 0, n - 1)]
    import scipy.sparse as sp

    W = sp.coo_matrix((wf[keep], (rows[keep], lj[keep])),
                      shape=(n_groups, n_groups)).toarray()
    C = W + W.T  # symmetrised inter-group weight (each edge ≤ twice)
    np.fill_diagonal(C, 0.0)
    sizes = np.bincount(labels, minlength=n_groups).astype(np.float64)
    es = W.sum(axis=1) + W.sum(axis=0)  # total incident weight per group
    expected = (np.outer(es, sizes) + np.outer(sizes, es)) / max(n - 1, 1)
    np.fill_diagonal(expected, 1.0)
    theta = np.clip(C / np.maximum(expected, 1e-12), 0.0, 1.0)
    np.fill_diagonal(theta, 0.0)
    return C, expected, theta.astype(np.float32)


def _paga_impl(data: CellData, groups: str) -> CellData:
    if groups not in data.obs:
        raise KeyError(
            f"obs has no {groups!r} — run cluster.leiden (or another "
            "clustering) first")
    idx, _ = _require_knn(data)
    n = data.n_cells
    idx = np.asarray(idx)[:n]
    w = None
    if "connectivities" in data.obsp:
        cand = np.asarray(data.obsp["connectivities"], np.float64)[:n]
        if cand.shape == idx.shape:
            w = cand
        else:
            import warnings

            warnings.warn(
                "graph.paga: obsp['connectivities'] shape "
                f"{cand.shape} does not match the current kNN graph "
                f"{idx.shape} (stale after a kNN rebuild?) — using "
                "unit edge weights", stacklevel=3)
    if w is None:
        w = np.ones_like(idx, np.float64)
    labels = np.asarray(data.obs[groups])[:n]
    uniq, codes = np.unique(labels, return_inverse=True)
    C, exp, theta = _paga_stats(idx, w, codes.astype(np.int64), len(uniq))
    return data.with_uns(
        paga_connectivities=theta,
        paga_edge_weights=C.astype(np.float32),
        paga_groups=uniq,
        # the obs column the abstraction was computed over (scanpy
        # stores uns['paga']['groups']); pl.paga must not have to
        # guess it by level-matching across obs columns
        paga_groups_key=groups)


@register("graph.paga", backend="tpu")
def paga_tpu(data: CellData, groups: str = "leiden") -> CellData:
    """PAGA (partition-based graph abstraction): the cluster-level
    connectivity map — symmetrised inter-group edge weight over the
    degree-based random-wiring expectation, clipped to [0, 1] (the
    scanpy ``tl.paga`` v1.2 formula — see _paga_stats).  Requires
    neighbors.knn + a clustering in ``obs[groups]``; uses
    obsp["connectivities"] weights when they match the current graph.
    Adds uns["paga_connectivities"] (G × G),
    uns["paga_edge_weights"], uns["paga_groups"].

    The group graph is a few thousand entries at most — this is host
    bookkeeping over the device-built kNN graph, identical on both
    backends by construction."""
    return _paga_impl(data, groups)


@register("graph.paga", backend="cpu")
def paga_cpu(data: CellData, groups: str = "leiden") -> CellData:
    return _paga_impl(data, groups)


# ----------------------------------------------------------------------
# graph.reorder — one-shot locality pass (RCM over the kNN graph)
# ----------------------------------------------------------------------


def reorder_permutation(knn_idx, method: str = "rcm") -> np.ndarray:
    """Row permutation (new → old) that clusters the kNN graph's
    edges around the diagonal.  ``"rcm"`` is reverse Cuthill–McKee on
    the symmetrised edge pattern (scipy's bandwidth-minimising
    ordering — the AutoGNN-style hardware preprocessing step);
    ``"natural"`` is the identity (tests / A-B baselines)."""
    idx = np.asarray(knn_idx)
    n, k = idx.shape
    if method == "natural":
        return np.arange(n, dtype=np.int64)
    if method != "rcm":
        raise ValueError(f"unknown reorder method {method!r}; "
                         "use 'rcm' or 'natural'")
    import scipy.sparse as sp
    from scipy.sparse.csgraph import reverse_cuthill_mckee

    rows = np.repeat(np.arange(n), k)
    cols = idx.reshape(-1)
    keep = cols >= 0
    W = sp.csr_matrix(
        (np.ones(int(keep.sum()), np.float32),
         (rows[keep], cols[keep])), shape=(n, n))
    W = (W + W.T).tocsr()
    perm = np.asarray(reverse_cuthill_mckee(W, symmetric_mode=True))
    return perm.astype(np.int64)


def graph_bandwidth(knn_idx) -> int:
    """Max |i − j| over the stored edges — the banded Pallas sweep's
    window bound (0 for an edgeless graph)."""
    idx = np.asarray(knn_idx)
    n = idx.shape[0]
    rows = np.repeat(np.arange(n), idx.shape[1]).reshape(idx.shape)
    d = np.abs(idx - rows)[idx >= 0]
    return int(d.max()) if d.size else 0


def tile_density(knn_idx, block: int = 256) -> float:
    """Fraction of stored edges within one ``block``-row band of the
    diagonal — the locality the tiled kernels exploit (gauge
    ``graph.tile_density``).  1.0 = every gather hits the diagonal
    tile neighbourhood."""
    idx = np.asarray(knn_idx)
    n = idx.shape[0]
    rows = np.repeat(np.arange(n), idx.shape[1]).reshape(idx.shape)
    valid = idx >= 0
    if not valid.any():
        return 1.0
    close = (np.abs(idx - rows) < block) & valid
    return float(close.sum() / valid.sum())


def invalidate_graph_layout_stats(data: CellData) -> CellData:
    """Drop the graph-layout STATISTICS (``graph_bandwidth`` /
    ``graph_tile_density``) from uns.  Every op that REPLACES
    ``obsp['knn_indices']`` (neighbors.knn / bbknn / knn_multichip)
    must call this: the band was measured on the old graph, and a
    stale band would make the Pallas banded sweep silently skip any
    new edge outside the old window — wrong results, invisible to
    the CPU parity suite (the xla/gather impls ignore the band).
    The permutation itself stays: it describes the ROW layout, which
    a kNN rebuild does not change (``graph.restore_order`` can still
    undo it); the rebuilt graph simply runs full-sweep until the
    next ``graph.reorder``."""
    if ("graph_bandwidth" not in data.uns
            and "graph_tile_density" not in data.uns):
        return data
    uns = {k: v for k, v in data.uns.items()
           if k not in ("graph_bandwidth", "graph_tile_density")}
    return data.replace(uns=uns)


def _remap_edge_values(arr: np.ndarray, inv: np.ndarray) -> np.ndarray:
    """Old row ids → new row ids inside an index-valued obsp array
    (-1 padding preserved)."""
    safe = np.where(arr < 0, 0, arr)
    return np.where(arr < 0, arr, inv[safe]).astype(arr.dtype)


def _apply_permutation(data: CellData, perm: np.ndarray) -> CellData:
    """Row-permute every per-cell field of ``data`` (new row i = old
    row ``perm[i]``), remapping index-valued obsp arrays (names
    ending ``indices``) into the new row space.  obsp is stashed and
    re-attached around the ``data[perm]`` subset (which by design
    drops pairwise graphs on cell subsets — a permutation is the one
    subset that keeps them valid)."""
    n = data.n_cells
    perm = np.asarray(perm, np.int64)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(len(perm), dtype=np.int64)
    obsp = data.obsp
    base = data.replace(obsp={})[perm]
    new_obsp = {}
    for key, v in obsp.items():
        a = np.asarray(v)[:n]
        if a.dtype.kind in "iu" and key.endswith("indices"):
            a = _remap_edge_values(a, inv)
        new_obsp[key] = a[perm]
    return base.replace(obsp=new_obsp)


def _reorder_impl(data: CellData, method: str,
                  block: int = 256) -> CellData:
    import time

    from ..utils import telemetry

    if "graph_perm" in data.uns:
        import warnings

        warnings.warn(
            "graph.reorder: data already carries a layout permutation "
            "(uns['graph_perm']) — run graph.restore_order first; "
            "returning the input unchanged", stacklevel=3)
        return data
    idx, _ = _require_knn(data)
    idx_h = np.asarray(idx)
    m = telemetry.default_registry()
    t0 = time.perf_counter()
    m.gauge("graph.tile_density", layout="natural").set(
        tile_density(idx_h, block=block))
    perm = reorder_permutation(idx_h, method=method)
    out = _apply_permutation(data, perm)
    new_idx = np.asarray(out.obsp["knn_indices"])
    bw = graph_bandwidth(new_idx)
    density = tile_density(new_idx, block=block)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(len(perm), dtype=np.int64)
    out = out.with_uns(
        graph_perm=perm.astype(np.int32),
        graph_perm_inv=inv.astype(np.int32),
        # plain python scalars ON PURPOSE: they ride plan-cache keys
        # as opaque content (the band is baked statically into the
        # compiled kernels, so a bandwidth change MUST be a cache
        # miss), while the perm arrays stay traced leaves (layout-
        # agnostic programs rightly hit across different perms)
        graph_bandwidth=int(bw),
        graph_tile_density=float(density),
        graph_reorder_method=str(method))
    m.gauge("graph.tile_density", layout="reordered").set(density)
    m.counter("graph.reorder_s").inc(time.perf_counter() - t0)
    return out


@register("graph.reorder", backend="tpu")
def reorder_tpu(data: CellData, method: str = "rcm",
                block: int = 256) -> CellData:
    """One-shot locality pass: permute rows (cells) so kNN
    neighbours sit near the diagonal, making every downstream
    iterative graph kernel sweep dense tiles instead of the whole
    table (docs/ARCHITECTURE.md "Graph kernels & layout").  Computes
    an RCM ordering from ``obsp['knn_indices']``, permutes
    X/obs/obsm/layers/obsp (index-valued arrays remapped), and
    records ``uns['graph_perm'/'graph_perm_inv'/'graph_bandwidth'/
    'graph_tile_density']`` so kernels pick up the band, checkpoints
    fingerprint the layout, and ``graph.restore_order`` can undo it
    at the recipe boundary.  Host pass, identical on both backends;
    ``block`` is the tile size the density gauge is scored against."""
    return _reorder_impl(data, method, block)


@register("graph.reorder", backend="cpu")
def reorder_cpu(data: CellData, method: str = "rcm",
                block: int = 256) -> CellData:
    return _reorder_impl(data, method, block)


def _restore_impl(data: CellData) -> CellData:
    import time

    from ..utils import telemetry

    if "graph_perm" not in data.uns:
        return data  # natural layout already — the boundary is a no-op
    t0 = time.perf_counter()
    inv = np.asarray(data.uns["graph_perm_inv"], np.int64)
    out = _apply_permutation(data, inv)
    uns = {k: v for k, v in out.uns.items()
           if k not in ("graph_perm", "graph_perm_inv",
                        "graph_bandwidth", "graph_tile_density",
                        "graph_reorder_method")}
    telemetry.default_registry().counter("graph.reorder_s").inc(
        time.perf_counter() - t0)
    return out.replace(uns=uns)


@register("graph.restore_order", backend="tpu")
def restore_order_tpu(data: CellData) -> CellData:
    """Undo ``graph.reorder``: inverse-permute every per-cell field
    back to the natural row order and drop the layout keys from uns —
    the recipe-boundary step, so results leave the pipeline in the
    caller's row order (bitwise round-trip, tests/
    test_graph_reorder.py).  A no-op on natural-layout data."""
    return _restore_impl(data)


@register("graph.restore_order", backend="cpu")
def restore_order_cpu(data: CellData) -> CellData:
    return _restore_impl(data)


# ----------------------------------------------------------------------
# embed.diffmap — scanpy's name for the diffusion-map embedding
# ----------------------------------------------------------------------


@register("embed.diffmap", backend="tpu")
def diffmap_tpu(data: CellData, n_comps: int = 15, seed: int = 0,
                drop_first: bool = True) -> CellData:
    """scanpy ``tl.diffmap`` naming for ``embed.spectral`` — identical
    computation (the two public APIs describe the same diffusion-map
    eigendecomposition); registered separately so reference users find
    it under the name they know."""
    return spectral_tpu(data, n_comps=n_comps, seed=seed,
                        drop_first=drop_first)


@register("embed.diffmap", backend="cpu")
def diffmap_cpu(data: CellData, n_comps: int = 15, seed: int = 0,
                drop_first: bool = True) -> CellData:
    return spectral_cpu(data, n_comps=n_comps, seed=seed,
                        drop_first=drop_first)
