"""Gene-set scoring: ``score.genes`` and ``score.cell_cycle``.

Scanpy-parity (``tl.score_genes`` / ``tl.score_genes_cell_cycle``):
a cell's score is its mean expression over the gene set minus its mean
over a control set sampled from expression-matched bins (Satija et al.
2015).  TPU-first shape: both means are one ``X @ w`` sparse matvec
(``spmm`` with a (n_genes, 2) weight table), so the whole op is a
single fused pass over the ELL data regardless of set size.

Control sampling (binning genes by mean expression, drawing
``ctrl_size`` per occupied bin) is host-side numpy on (n_genes,)
vectors — data-dependent sizes don't belong under jit.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..data.dataset import CellData
from ..data.sparse import SparseCells, spmm
from ..registry import register


def _resolve_gene_indices(data: CellData, genes) -> np.ndarray:
    """Gene list -> integer indices; names resolved via var['gene_name']."""
    genes = np.asarray(genes)
    if genes.dtype.kind in "iu":
        return genes.astype(np.int64)
    if "gene_name" not in data.var:
        raise KeyError("score.genes: gene names given but var has no "
                       "'gene_name' column")
    names = np.asarray(data.var["gene_name"]).astype(str)
    lut = {n: i for i, n in enumerate(names)}
    wanted = genes.astype(str)
    idx = [lut[g] for g in wanted if g in lut]
    missing = [g for g in wanted if g not in lut]
    if not idx:
        raise ValueError("score.genes: none of the given genes found in "
                         "var['gene_name']")
    if missing:
        import warnings

        warnings.warn(
            f"score.genes: {len(missing)}/{len(wanted)} genes not in "
            f"var['gene_name'] and ignored (e.g. {missing[:5]})",
            stacklevel=3)
    return np.asarray(idx, np.int64)


def _gene_means_host(data: CellData) -> np.ndarray:
    """Per-gene mean expression on the host (for control binning)."""
    X = data.X
    if isinstance(X, SparseCells):
        from ..data.sparse import gene_stats

        s, _, _ = gene_stats(X)
        return np.asarray(s) / X.n_cells
    import scipy.sparse as sp

    if sp.issparse(X):
        return np.asarray(X.mean(axis=0)).ravel()
    return np.asarray(X).mean(axis=0)


def _control_indices(gene_means, target_idx, ctrl_size, n_bins, seed):
    """Expression-matched control genes: bin all genes by mean
    expression rank, then for each bin containing a target gene draw
    ``ctrl_size`` genes from it (excluding targets)."""
    rng = np.random.default_rng(seed)
    n_genes = gene_means.shape[0]
    order = np.argsort(gene_means)
    bin_of = np.empty(n_genes, np.int64)
    bin_of[order] = np.arange(n_genes) * n_bins // n_genes
    target_set = np.zeros(n_genes, bool)
    target_set[target_idx] = True
    ctrl = []
    for b in np.unique(bin_of[target_idx]):
        pool = np.where((bin_of == b) & ~target_set)[0]
        if len(pool) == 0:
            continue
        take = min(ctrl_size, len(pool))
        ctrl.append(rng.choice(pool, size=take, replace=False))
    if not ctrl:
        raise ValueError("score.genes: control pool is empty")
    return np.unique(np.concatenate(ctrl))


def _score_weights(n_genes, target_idx, ctrl_idx):
    """(n_genes, 2) weight table: col0 averages the target set, col1
    the control set — score = X@w[:,0] - X@w[:,1]."""
    w = np.zeros((n_genes, 2), np.float32)
    w[target_idx, 0] = 1.0 / len(target_idx)
    w[ctrl_idx, 1] = 1.0 / len(ctrl_idx)
    return w


@register("score.genes", backend="tpu")
def score_genes_tpu(data: CellData, genes=None, score_name: str = "score",
                    ctrl_size: int = 50, n_bins: int = 25,
                    seed: int = 0) -> CellData:
    """Per-cell gene-set score: mean(set) - mean(expression-matched
    control), stored in ``obs[score_name]``."""
    if genes is None:
        raise ValueError("score.genes needs a gene list")
    target_idx = _resolve_gene_indices(data, genes)
    gm = _gene_means_host(data)
    ctrl_idx = _control_indices(gm, target_idx, ctrl_size, n_bins, seed)
    w = jnp.asarray(_score_weights(data.n_genes, target_idx, ctrl_idx))
    X = data.X
    if isinstance(X, SparseCells):
        both = spmm(X, w)  # (rows_padded, 2)
    else:
        both = jnp.asarray(X) @ w
    score = both[:, 0] - both[:, 1]
    return data.with_obs(**{score_name: score})


@register("score.genes", backend="cpu")
def score_genes_cpu(data: CellData, genes=None, score_name: str = "score",
                    ctrl_size: int = 50, n_bins: int = 25,
                    seed: int = 0) -> CellData:
    import scipy.sparse as sp

    if genes is None:
        raise ValueError("score.genes needs a gene list")
    target_idx = _resolve_gene_indices(data, genes)
    gm = _gene_means_host(data)
    ctrl_idx = _control_indices(gm, target_idx, ctrl_size, n_bins, seed)
    w = _score_weights(data.n_genes, target_idx, ctrl_idx)
    X = data.X
    both = (X @ w if sp.issparse(X) else np.asarray(X) @ w)
    both = np.asarray(both)
    return data.with_obs(**{score_name: both[:, 0] - both[:, 1]})


def _cell_cycle(data: CellData, s_genes, g2m_genes, backend, seed):
    from ..registry import apply

    data = apply("score.genes", data, backend=backend, genes=s_genes,
                 score_name="S_score", seed=seed)
    data = apply("score.genes", data, backend=backend, genes=g2m_genes,
                 score_name="G2M_score", seed=seed + 1)
    # keep obs columns uniform length: phase matches the (possibly
    # padded) score arrays; padding rows get "" and are trimmed by
    # to_host like any other per-cell array
    s = np.asarray(data.obs["S_score"])
    g2m = np.asarray(data.obs["G2M_score"])
    phase = np.where((s <= 0) & (g2m <= 0), "G1",
                     np.where(s > g2m, "S", "G2M"))
    phase[data.n_cells:] = ""
    return data.with_obs(phase=phase)


@register("score.cell_cycle", backend="tpu")
def cell_cycle_tpu(data: CellData, s_genes=None, g2m_genes=None,
                   seed: int = 0) -> CellData:
    """S/G2M phase scores + phase call (scanpy
    ``score_genes_cell_cycle``): ``obs["S_score"]``,
    ``obs["G2M_score"]``, ``obs["phase"]`` in {G1, S, G2M}."""
    if s_genes is None or g2m_genes is None:
        raise ValueError("score.cell_cycle needs s_genes and g2m_genes")
    return _cell_cycle(data, s_genes, g2m_genes, "tpu", seed)


@register("score.cell_cycle", backend="cpu")
def cell_cycle_cpu(data: CellData, s_genes=None, g2m_genes=None,
                   seed: int = 0) -> CellData:
    if s_genes is None or g2m_genes is None:
        raise ValueError("score.cell_cycle needs s_genes and g2m_genes")
    return _cell_cycle(data, s_genes, g2m_genes, "cpu", seed)
