"""``palantir.run`` — trajectory fate mapping (Palantir).

Reference parity: dpeerlab/sctools descends from the Pe'er lab stack,
whose trajectory tool is Palantir (source unavailable — SURVEY.md §0;
the published algorithm: multiscale diffusion space → pseudotime from
a root cell → pseudotime-directed Markov chain → terminal states →
absorbing-chain fate probabilities + differentiation entropy).

TPU design: every stage is a fixed-shape operation on the (n, k) kNN
edge list:

* **pseudotime** — single-source shortest path by min-plus relaxation
  (Bellman–Ford): each round combines a pull (gather neighbours'
  distances + edge length, min over k) and a push (``segment_min``
  along reversed edges), under ``lax.scan`` with a static round count
  — the graph diameter, not n, bounds convergence.  Palantir's
  waypoint refinement is a sampling device for CPUs; the full
  relaxation IS the exact limit it approximates (documented
  divergence).
* **directed chain** — anisotropic gaussian kernel in multiscale
  space, gated by a logistic in the pseudotime increment (soft
  forward drift; see ``directed_chain_arrays`` for why the hard
  backward cut is not used), rows renormalised.
* **terminal states** — stationary mass by power iteration of ``Pᵀ``
  (``knn_rmatvec``); late-pseudotime local maxima of the stationary
  mass, graph-deduplicated (host-side on k-wide arrays).
* **fate probabilities** — absorbing-chain absorption probabilities by
  fixed-point iteration ``B ← P·B`` with terminal rows pinned to
  one-hot (k-sparse matvecs only); entropy of B is the
  differentiation potential.

CPU oracle: scipy ``dijkstra`` + a direct sparse solve of
``(I - Q) B = R`` — an independent formulation of both hard stages.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..data.dataset import CellData
from ..registry import register


# ----------------------------------------------------------------------
# multiscale space
# ----------------------------------------------------------------------


def multiscale_space(evals, evecs, n_eigs: int | None = None):
    """Palantir's multiscale data space: eigenvectors scaled by
    λ/(1-λ), using the eigengap to pick how many (host-side)."""
    evals = np.asarray(evals, np.float64)
    evecs = np.asarray(evecs, np.float64)
    if n_eigs is None:
        gaps = evals[:-1] - evals[1:]
        n_eigs = int(np.argmax(gaps) + 1)
        n_eigs = max(n_eigs, 2)
    use = slice(0, n_eigs)
    scale = evals[use] / (1.0 - np.minimum(evals[use], 1.0 - 1e-6))
    return (evecs[:, use] * scale[None, :]).astype(np.float32)


# ----------------------------------------------------------------------
# pseudotime: single-source shortest path on the kNN graph
# ----------------------------------------------------------------------


@partial(jax.jit, static_argnames=("n_rounds",))
def shortest_path_arrays(knn_idx, edge_len, root, n_rounds: int = 64):
    """Min-plus Bellman–Ford from ``root``.  knn_idx: (n, k);
    edge_len: (n, k) non-negative lengths (-1 slots ignored).
    Returns (n,) distances (inf where unreachable)."""
    n, k = knn_idx.shape
    safe = jnp.where(knn_idx < 0, 0, knn_idx)
    wlen = jnp.where(knn_idx < 0, jnp.inf, edge_len.astype(jnp.float32))
    d0 = jnp.full((n,), jnp.inf, jnp.float32).at[root].set(0.0)

    def relax(d, _):
        # pull: via my out-edges, d_i ← min(d_i, d_j + len_ij)
        pull = jnp.min(jnp.take(d, safe) + wlen, axis=1)
        d = jnp.minimum(d, pull)
        # push: via reversed edges, d_j ← min(d_j, d_i + len_ij)
        cand = (d[:, None] + wlen).reshape(-1)
        seg = jnp.where(knn_idx < 0, n, knn_idx).reshape(-1)
        push = jax.ops.segment_min(cand, seg, num_segments=n + 1)[:n]
        return jnp.minimum(d, push), None

    d, _ = jax.lax.scan(relax, d0, None, length=n_rounds)
    return d


# ----------------------------------------------------------------------
# directed transition matrix
# ----------------------------------------------------------------------


@jax.jit
def directed_chain_arrays(knn_idx, ms_emb, pseudotime, beta: float = 4.0):
    """Pseudotime-directed row-stochastic transition weights on the
    kNN edge list.  Anisotropic kernel σ_i = median neighbour distance,
    gated by a **logistic** in the pseudotime increment:
    ``w ← w · sigmoid(β·Δpt/s_i)`` with s_i the local scale of
    neighbour Δpt.

    Documented divergence from Palantir's hard backward-edge cut: when
    the two branches of a fork advance pseudotime at different rates
    (sparser sampling stretches diffusion distances), a hard tolerance
    turns the faster branch into a one-way trapdoor — walks that enter
    it can never re-emerge, and absorption ratios collapse to ~0/1
    regardless of branch size (reproduced on synthetic forks,
    tests/test_palantir.py).  The smooth gate keeps the same forward
    drift while leaving every move reversible at reduced probability,
    which removes the trapdoor artifact and also guarantees the
    absorbing solve is nonsingular."""
    from .pallas_graph import gather_rows

    n, k = knn_idx.shape
    safe = jnp.where(knn_idx < 0, 0, knn_idx)
    emb = jnp.asarray(ms_emb, jnp.float32)
    diff = emb[:, None, :] - gather_rows(emb, safe)
    d = jnp.sqrt(jnp.maximum(jnp.sum(diff * diff, axis=2), 0.0))
    d = jnp.where(knn_idx < 0, jnp.inf, d)
    finite = jnp.isfinite(d)
    sigma = jnp.nanmedian(jnp.where(finite, d, jnp.nan), axis=1)
    sigma = jnp.maximum(sigma, 1e-12)
    w = jnp.exp(-(d * d) / (sigma[:, None] * jnp.take(sigma, safe)))
    pt = jnp.asarray(pseudotime, jnp.float32)
    dpt = jnp.take(pt, safe) - pt[:, None]  # >0 = forward
    s = jnp.nanstd(jnp.where(finite, dpt, jnp.nan), axis=1)
    s = jnp.maximum(jnp.where(jnp.isfinite(s), s, 0.0), 1e-9)
    w = jnp.where(finite,
                  w * jax.nn.sigmoid(beta * dpt / s[:, None]), 0.0)
    row = jnp.sum(w, axis=1, keepdims=True)
    return jnp.where(row > 0, w / jnp.maximum(row, 1e-12), 0.0)


@partial(jax.jit, static_argnames=("n_iter", "band_rows",
                                   "graph_impl"))
def stationary_arrays(knn_idx, p_edges, n_iter: int = 100,
                      band_rows: int | None = None,
                      graph_impl: str | None = None):
    """Stationary mass of the directed chain by power iteration of
    Pᵀ (zero rows treated as self-loops).  ``band_rows`` (static)
    bounds the banded rmatvec sweep after ``graph.reorder``;
    ``graph_impl`` (static) pins the tiled-family impl so config
    flips re-key the jit cache."""
    from .graph import knn_rmatvec

    n = knn_idx.shape[0]
    x = jnp.full((n, 1), 1.0 / n, jnp.float32)
    self_mass = 1.0 - jnp.sum(jnp.where(knn_idx < 0, 0.0, p_edges), axis=1)

    def step(x, _):
        x_new = (knn_rmatvec(knn_idx, p_edges, x, n=n,
                             band_rows=band_rows, impl=graph_impl)
                 + self_mass[:, None] * x)
        return x_new / jnp.maximum(jnp.sum(x_new), 1e-12), None

    x, _ = jax.lax.scan(step, x, None, length=n_iter)
    return x[:, 0]


@partial(jax.jit, static_argnames=("n_iter", "band_rows",
                                   "graph_impl"))
def fate_probs_arrays(knn_idx, p_edges, terminal_onehot, is_terminal,
                      n_iter: int = 5000, tol: float = 1e-6,
                      band_rows: int | None = None,
                      graph_impl: str | None = None):
    """Absorption probabilities of the pseudotime-directed chain.

    terminal_onehot: (n, T) — rows of terminal cells are one-hot over
    fates, others zero; is_terminal: (n,) bool.  Fixed-point
    ``B ← P·B`` with terminal rows pinned (the Neumann series of
    (I-Q)⁻¹R), run under ``lax.while_loop`` until ``max|ΔB| < tol``
    or ``n_iter`` sweeps — convergence takes on the order of the
    chain's absorption time, far past any fixed small count (an
    unconverged B silently mis-splits the early fates).
    """
    from .graph import knn_matvec

    n, k = knn_idx.shape
    self_mass = 1.0 - jnp.sum(jnp.where(knn_idx < 0, 0.0, p_edges), axis=1)
    B0 = terminal_onehot.astype(jnp.float32)

    def cond(carry):
        _, i, delta = carry
        return (i < n_iter) & (delta > tol)

    def step(carry):
        B, i, _ = carry
        Bn = (knn_matvec(knn_idx, p_edges, B, band_rows=band_rows,
                         impl=graph_impl)
              + self_mass[:, None] * B)
        Bn = jnp.where(is_terminal[:, None], terminal_onehot, Bn)
        return Bn, i + 1, jnp.max(jnp.abs(Bn - B))

    B, _, _ = jax.lax.while_loop(cond, step, (B0, jnp.int32(0),
                                              jnp.float32(jnp.inf)))
    return B


def _find_terminal_states(knn_idx, stationary, pseudotime,
                          max_terminal: int = 10,
                          pt_quantile: float = 0.7,
                          reachable=None):
    """Late-pseudotime local maxima of stationary mass, deduplicated
    through the graph (host-side).

    ``reachable``: bool mask of cells reachable from the root.  The
    callers clamp unreachable cells' pseudotime to the max *before*
    this runs, which would otherwise park every disconnected component
    in the late-pseudotime quantile where its stationary-mass maximum
    can be picked as a spurious terminal state — so unreachable cells
    are excluded from candidacy here.
    """
    idx = np.asarray(knn_idx)
    pi = np.asarray(stationary, np.float64)
    pt = np.asarray(pseudotime, np.float64)
    n, k = idx.shape
    if reachable is None:
        reachable = np.isfinite(pt)
    reachable = np.asarray(reachable, bool)
    safe = np.where(idx < 0, 0, idx)
    nb_pi = np.where(idx < 0, -np.inf, pi[safe])
    is_max = pi >= nb_pi.max(axis=1)
    finite_pt = pt[np.isfinite(pt) & reachable]
    late = pt >= np.quantile(finite_pt, pt_quantile)
    cand = np.flatnonzero(is_max & late & np.isfinite(pt) & reachable)
    cand = cand[np.argsort(-pi[cand])]
    chosen: list[int] = []
    taken = np.zeros(n, bool)
    for c in cand:
        if taken[c]:
            continue
        chosen.append(int(c))
        taken[c] = True
        taken[safe[c][idx[c] >= 0]] = True  # block its neighbourhood
        if len(chosen) >= max_terminal:
            break
    return np.asarray(chosen, np.int64)


# ----------------------------------------------------------------------
# registry ops
# ----------------------------------------------------------------------


def _prep_palantir(data: CellData, backend: str, n_eigs):
    from .graph import spectral_cpu, spectral_tpu

    if "X_diffmap" not in data.obsm:
        data = (spectral_tpu if backend == "tpu" else spectral_cpu)(data)
    if "knn_indices" not in data.obsp:
        raise ValueError("run neighbors.knn first")
    n = data.n_cells
    idx = np.asarray(data.obsp["knn_indices"])[:n]
    ms = multiscale_space(np.asarray(data.uns["diffmap_evals"]),
                          np.asarray(data.obsm["X_diffmap"])[:n],
                          n_eigs=n_eigs)
    return data, idx, ms


def _edge_lengths(idx, ms):
    safe = np.where(idx < 0, 0, idx)
    d = np.linalg.norm(ms[:, None, :] - ms[safe], axis=2)
    return np.where(idx < 0, np.inf, d).astype(np.float32)


def _attach(data, pt, fate, entropy, terminals, levels):
    return data.with_obs(
        palantir_pseudotime=pt, palantir_entropy=entropy,
    ).with_obsm(palantir_fate_probs=fate).with_uns(
        palantir_terminal_states=np.asarray(terminals),
        palantir_fate_labels=np.asarray(levels),
    )


@register("palantir.run", backend="tpu")
def palantir_tpu(data: CellData, root: int = 0, terminal_states=None,
                 n_eigs: int | None = None, max_terminal: int = 10,
                 sp_rounds: int = 64, fate_iter: int = 5000) -> CellData:
    """Adds obs["palantir_pseudotime"], obs["palantir_entropy"],
    obsm["palantir_fate_probs"], uns["palantir_terminal_states"].
    Requires neighbors.knn (embed.spectral runs if missing)."""
    from .pallas_graph import resolved_impl

    data, idx, ms = _prep_palantir(data, "tpu", n_eigs)
    n = data.n_cells
    band = data.uns.get("graph_bandwidth")
    band = int(band) if band is not None else None
    gimpl = resolved_impl()
    idx_j = jnp.asarray(idx)
    elen = jnp.asarray(_edge_lengths(idx, ms))
    d = shortest_path_arrays(idx_j, elen, root, n_rounds=sp_rounds)
    # silent non-convergence check: cells beyond the relaxation horizon
    # keep d=inf, clamp to pt=1.0 and would masquerade as terminal
    # states — retry with a deeper sweep, then warn about genuinely
    # unreachable (disconnected) cells
    if not bool(jnp.all(jnp.isfinite(d))):
        d = shortest_path_arrays(idx_j, elen, root, n_rounds=4 * sp_rounds)
        n_inf = int(jnp.sum(~jnp.isfinite(d)))
        if n_inf:
            import warnings

            warnings.warn(
                f"palantir: {n_inf} cells unreachable from root {root} "
                f"after {4 * sp_rounds} relaxation rounds (disconnected "
                "graph or raise sp_rounds); their pseudotime is clamped "
                "to the max", stacklevel=2)
    reach = np.isfinite(np.asarray(d))
    pt_max = jnp.max(jnp.where(jnp.isfinite(d), d, 0.0))
    pt = jnp.where(jnp.isfinite(d), d, pt_max) / jnp.maximum(pt_max, 1e-12)

    p = directed_chain_arrays(idx_j, jnp.asarray(ms), pt)
    if terminal_states is None:
        pi = stationary_arrays(idx_j, p, band_rows=band,
                               graph_impl=gimpl)
        terminal_states = _find_terminal_states(
            idx, pi, np.asarray(pt), max_terminal=max_terminal,
            reachable=reach)
    terminal_states = np.asarray(terminal_states, np.int64)
    T = len(terminal_states)
    if T == 0:
        raise ValueError("no terminal states found; pass terminal_states")
    onehot = np.zeros((n, T), np.float32)
    onehot[terminal_states, np.arange(T)] = 1.0
    is_term = np.zeros(n, bool)
    is_term[terminal_states] = True
    B = fate_probs_arrays(idx_j, p, jnp.asarray(onehot),
                          jnp.asarray(is_term), n_iter=fate_iter,
                          band_rows=band, graph_impl=gimpl)
    rowsum = jnp.sum(B, axis=1, keepdims=True)
    Bn = jnp.where(rowsum > 1e-6, B / jnp.maximum(rowsum, 1e-12), 1.0 / T)
    ent = -jnp.sum(jnp.where(Bn > 0, Bn * jnp.log(Bn), 0.0), axis=1)
    return _attach(data, pt, Bn, ent, terminal_states,
                   terminal_states)


@partial(jax.jit, static_argnames=("n_grid",))
def gene_trends_arrays(pseudotime, weights_mask, X_dense, n_grid: int = 100,
                       bandwidth: float | None = None):
    """Kernel regression of expression against pseudotime.

    pseudotime: (n,) in [0, 1]; weights_mask: (n,) 0/1 cell weights
    (e.g. a fate-probability column — Palantir weighs each lineage's
    trend by its fate probabilities); X_dense: (n, g).  Returns
    (grid (n_grid,), trends (n_grid, g), std (n_grid, g)).

    TPU mapping: the Gaussian kernel over (grid, n) pseudotime
    distances and both weighted moments are three matmuls — no
    per-gene loop (the reference's per-gene GAM fit is a scalar CPU
    loop; a shared-kernel Nadaraya–Watson regression computes every
    gene's trend at once and matches GAM fits closely for the smooth
    trends this is used for — documented divergence)."""
    pt = jnp.asarray(pseudotime, jnp.float32)
    w = jnp.asarray(weights_mask, jnp.float32)
    X = jnp.asarray(X_dense, jnp.float32)
    grid = jnp.linspace(0.0, 1.0, n_grid)
    if bandwidth is None:
        bandwidth = 0.75 * (jnp.max(pt) - jnp.min(pt) + 1e-12) / (
            n_grid ** 0.4)
    K = jnp.exp(-0.5 * ((grid[:, None] - pt[None, :]) / bandwidth) ** 2)
    K = K * w[None, :]
    norm = jnp.maximum(jnp.sum(K, axis=1, keepdims=True), 1e-12)
    trends = (K @ X) / norm
    second = (K @ (X * X)) / norm
    std = jnp.sqrt(jnp.maximum(second - trends**2, 0.0))
    return grid, trends, std


@register("palantir.gene_trends", backend="tpu")
def gene_trends_tpu(data: CellData, genes=None, lineage: int | None = None,
                    n_grid: int = 100, bandwidth: float | None = None,
                    use_rep: str = "X") -> CellData:
    """Expression trends along Palantir pseudotime, optionally
    weighted by one lineage's fate probabilities.  Adds
    uns["gene_trends"] = {"grid", "trends", "std", "gene_idx"}."""
    from ..data.sparse import SparseCells
    from .score import _resolve_gene_indices

    if "palantir_pseudotime" not in data.obs:
        raise ValueError("run palantir.run first")
    n = data.n_cells
    pt = jnp.asarray(data.obs["palantir_pseudotime"])[:n]
    if lineage is not None:
        w = jnp.asarray(data.obsm["palantir_fate_probs"])[:n, lineage]
    else:
        w = jnp.ones((n,), jnp.float32)
    if use_rep == "X":
        X = data.X
        Xd = X.to_dense() if isinstance(X, SparseCells) else (
            jnp.asarray(X)[:n])
    else:
        Xd = jnp.asarray(data.obsm[use_rep])[:n]
    if genes is not None:
        gene_idx = _resolve_gene_indices(data, genes)
        Xd = Xd[:, jnp.asarray(gene_idx)]
    else:
        gene_idx = np.arange(Xd.shape[1])
    grid, trends, std = gene_trends_arrays(pt, w, Xd[:n], n_grid=n_grid,
                                           bandwidth=bandwidth)
    return data.with_uns(gene_trends={
        "grid": grid, "trends": trends, "std": std,
        "gene_idx": np.asarray(gene_idx), "lineage": lineage,
    })


@register("palantir.gene_trends", backend="cpu")
def gene_trends_cpu(data: CellData, genes=None, lineage: int | None = None,
                    n_grid: int = 100, bandwidth: float | None = None,
                    use_rep: str = "X") -> CellData:
    """Numpy oracle of the same Nadaraya–Watson regression."""
    import scipy.sparse as sp

    from .score import _resolve_gene_indices

    if "palantir_pseudotime" not in data.obs:
        raise ValueError("run palantir.run first")
    n = data.n_cells
    pt = np.asarray(data.obs["palantir_pseudotime"], np.float64)[:n]
    w = (np.asarray(data.obsm["palantir_fate_probs"], np.float64)[:n, lineage]
         if lineage is not None else np.ones(n))
    if use_rep == "X":
        X = data.X
        Xd = np.asarray(X.todense()) if sp.issparse(X) else np.asarray(X)[:n]
    else:
        Xd = np.asarray(data.obsm[use_rep])[:n]
    if genes is not None:
        gene_idx = _resolve_gene_indices(data, genes)
        Xd = Xd[:, gene_idx]
    else:
        gene_idx = np.arange(Xd.shape[1])
    grid = np.linspace(0.0, 1.0, n_grid)
    if bandwidth is None:
        bandwidth = 0.75 * (pt.max() - pt.min() + 1e-12) / (n_grid ** 0.4)
    K = np.exp(-0.5 * ((grid[:, None] - pt[None, :]) / bandwidth) ** 2)
    K = K * w[None, :]
    norm = np.maximum(K.sum(axis=1, keepdims=True), 1e-12)
    trends = (K @ Xd) / norm
    second = (K @ (Xd * Xd)) / norm
    std = np.sqrt(np.maximum(second - trends**2, 0.0))
    return data.with_uns(gene_trends={
        "grid": grid.astype(np.float32),
        "trends": trends.astype(np.float32),
        "std": std.astype(np.float32),
        "gene_idx": np.asarray(gene_idx), "lineage": lineage,
    })


@register("palantir.run", backend="cpu")
def palantir_cpu(data: CellData, root: int = 0, terminal_states=None,
                 n_eigs: int | None = None, max_terminal: int = 10,
                 **_ignored) -> CellData:
    """scipy oracle: dijkstra pseudotime + direct sparse absorbing-
    chain solve."""
    import scipy.sparse as sp
    from scipy.sparse.csgraph import dijkstra

    data, idx, ms = _prep_palantir(data, "cpu", n_eigs)
    n = data.n_cells
    k = idx.shape[1]
    elen = _edge_lengths(idx, ms)
    rows = np.repeat(np.arange(n), k)
    cols = idx.reshape(-1)
    keep = cols >= 0
    Wlen = sp.csr_matrix(
        (elen.reshape(-1)[keep], (rows[keep], cols[keep])), shape=(n, n))
    d = dijkstra(Wlen, directed=False, indices=root)
    reach = np.isfinite(d)
    pt_max = np.nanmax(np.where(np.isfinite(d), d, np.nan))
    pt = np.where(np.isfinite(d), d, pt_max) / max(pt_max, 1e-12)

    # directed chain — same math as the TPU kernel, scipy container
    p = np.asarray(directed_chain_arrays(jnp.asarray(idx),
                                         jnp.asarray(ms),
                                         jnp.asarray(pt)))
    if terminal_states is None:
        pi = np.asarray(stationary_arrays(jnp.asarray(idx),
                                          jnp.asarray(p)))
        terminal_states = _find_terminal_states(idx, pi, pt,
                                                max_terminal=max_terminal,
                                                reachable=reach)
    terminal_states = np.asarray(terminal_states, np.int64)
    T = len(terminal_states)
    if T == 0:
        raise ValueError("no terminal states found; pass terminal_states")
    # absorbing-chain direct solve:  (I - Q) B_trans = R
    self_mass = 1.0 - np.where(idx < 0, 0.0, p).sum(axis=1)
    P = sp.csr_matrix((p.reshape(-1)[keep], (rows[keep], cols[keep])),
                      shape=(n, n)) + sp.diags(self_mass)
    is_term = np.zeros(n, bool)
    is_term[terminal_states] = True
    trans = ~is_term
    Q = P[trans][:, trans]
    R = P[trans][:, terminal_states]
    from scipy.sparse.linalg import spsolve

    # ε-damping: closed transient cycles (mutually-late cell pairs
    # that drain into each other) make I - Q exactly singular; the
    # damped chain leaks ε of their mass per step instead, and the
    # final row renormalisation (or the uniform fallback for fully
    # trapped rows) absorbs the O(ε) error for everyone else.
    eps = 1e-6
    I = sp.identity(Q.shape[0], format="csc")
    B_trans = spsolve(I - (1.0 - eps) * Q.tocsc(), R.tocsc())
    B_trans = np.asarray(B_trans.todense() if sp.issparse(B_trans)
                         else B_trans).reshape(Q.shape[0], T)
    B = np.zeros((n, T), np.float64)
    B[trans] = B_trans
    B[terminal_states, np.arange(T)] = 1.0
    B[~np.isfinite(B).all(axis=1)] = 1.0 / T  # singular-row fallback
    rowsum = B.sum(axis=1, keepdims=True)
    Bn = np.where(rowsum > 1e-6, B / np.maximum(rowsum, 1e-12), 1.0 / T)
    ent = -np.sum(np.where(Bn > 0, Bn * np.log(np.maximum(Bn, 1e-30)), 0.0),
                  axis=1)
    return _attach(data, pt.astype(np.float32), Bn.astype(np.float32),
                   ent.astype(np.float32), terminal_states,
                   terminal_states)
