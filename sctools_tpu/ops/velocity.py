"""``velocity.*`` — RNA velocity: steady-state, stochastic, and
dynamical models, plus the CellRank-style fate-mapping family.

Capability parity: the scVelo/velocyto steady-state workflow (the
reference source was unavailable — /root/reference empty, SURVEY.md
§0; the published model is the contract):

* ``velocity.moments`` — kNN-smoothed first moments of the spliced /
  unspliced layers (scVelo ``pp.moments``): ``Ms = D⁻¹(W + I) S``.
* ``velocity.estimate`` — per-gene degradation rate γ by regression
  through the origin over the extreme-quantile cells (the presumed
  steady-state population), velocity ``v = Mu − γ·Ms``, per-gene fit
  r² and a ``velocity_genes`` mask (scVelo ``tl.velocity`` with
  ``mode="steady_state"``); ``mode="stochastic"`` adds the stacked
  second-moment GLS system (scVelo's default mode).
* ``velocity.recover_dynamics`` / ``velocity.latent_time`` — the
  dynamical splicing-ODE model (per-gene EM, vmapped) and the
  gene-shared latent time.
* ``velocity.terminal_states`` / ``fate_probabilities`` /
  ``lineage_drivers`` — CellRank-style fate mapping on the
  velocity-directed chain.
* ``velocity.graph`` — cosine similarity between each cell's velocity
  vector and the displacement to each kNN neighbour (scVelo
  ``tl.velocity_graph``, restricted to the kNN edge pattern).
* ``velocity.embedding`` — project velocities into a 2-D embedding via
  the softmax transition weights of those cosines (scVelo
  ``tl.velocity_embedding``).

Input convention: ``layers["spliced"]`` and ``layers["unspliced"]``
(set them via ``CellData.with_layers`` or read from a loom-style
h5ad).  Subset to HVGs first — moments densify gene space.

TPU design: every stage is either a k-sparse gather-matvec on the
existing kNN edge list (moments, graph, embedding — ``knn_matvec`` /
per-chunk gathers, VPU-bound) or a per-gene masked reduction
(γ fit — one pass, MXU-free but fused).  Nothing materialises an
(n, n) object; the velocity graph lives in the same padded (n, k)
edge-list form as every other graph in this framework.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..data.dataset import CellData
from ..data.sparse import SparseCells
from ..registry import register

_CHUNK = 2048


def _dense_layer(data: CellData, name: str, xp):
    if name not in data.layers:
        hint = ("run velocity.moments first" if name in ("Ms", "Mu")
                else "set layers['spliced']/layers['unspliced'] first")
        raise KeyError(f"velocity: layers has no {name!r} — {hint}")
    L = data.layers[name]
    n = data.n_cells
    if isinstance(L, SparseCells):
        return L.to_dense()[:n]
    try:
        import scipy.sparse as sp

        if sp.issparse(L):
            return xp.asarray(L.todense(), dtype=xp.float32)
    except ImportError:  # pragma: no cover
        pass
    return xp.asarray(L, dtype=xp.float32)[:n]


# ----------------------------------------------------------------------
# velocity.moments
# ----------------------------------------------------------------------


def _moments(data: CellData, device: bool, second: bool = False,
             mesh=None, strategy: str = "all_gather"):
    n = data.n_cells
    if device:
        from .graph import (_require_knn, _symmetrized_weights,
                            connectivities_tpu, knn_matvec)

        # validate the cheap preconditions BEFORE building
        # connectivities (a missing layer must not cost a full kNN
        # smooth-calibration first)
        S = _dense_layer(data, "spliced", jnp)
        U = _dense_layer(data, "unspliced", jnp)
        if "connectivities" not in data.obsp:
            data = connectivities_tpu(data)
        idx, _ = _require_knn(data)
        w = jnp.asarray(data.obsp["connectivities"])[:n]
        # scVelo parity: moments smooth over the SYMMETRIC fuzzy-union
        # connectivities (scanpy's neighbors output), not the directed
        # kNN weights — one-sided edges at cluster boundaries matter
        w = _symmetrized_weights(idx, w, mode="union")
        w = jnp.where(idx < 0, 0.0, w)
        if mesh is not None:
            # heavy (n, g) smoothing cells-sharded over the mesh —
            # the symmetrised (n, k) weight prep above stays
            # single-program (it is k-sparse and tiny next to X)
            from ..parallel.graph_multichip import (pad_rows_for_mesh,
                                                    smooth_layers_sharded)

            mats = [S, U] + ([S * S, U * S] if second else [])
            # ONE mesh program over the gene-concatenated matrix —
            # the smoothing is per-gene independent, so four separate
            # shard_map dispatches (one per layer) would run four
            # collective chains for identical idx/weights
            idx_p, w_p, big, _ = pad_rows_for_mesh(
                mesh, idx=idx[:n], weights=w[:n],
                x=jnp.concatenate(mats, axis=1),
                who="velocity.moments")
            sm = smooth_layers_sharded(idx_p, w_p, [big], mesh,
                                       strategy=strategy)[0][:n]
            g = S.shape[1]
            out = {"Ms": sm[:, :g], "Mu": sm[:, g:2 * g]}
            if second:
                out["Mss"] = sm[:, 2 * g:3 * g]
                out["Mus"] = sm[:, 3 * g:]
            return data.with_layers(**out)
        denom = 1.0 + jnp.sum(w, axis=1, keepdims=True)
        band = data.uns.get("graph_bandwidth")
        band = int(band) if band is not None else None

        def smooth(X):
            return (X + knn_matvec(idx, w, X, band_rows=band)) / denom

        out = {"Ms": smooth(S), "Mu": smooth(U)}
        if second:
            # second moments for the stochastic model: smoothed
            # elementwise squares/cross-products (scVelo pp.moments'
            # get_moments(second_order=True) analogue)
            out["Mss"] = smooth(S * S)
            out["Mus"] = smooth(U * S)
        return data.with_layers(**out)
    import scipy.sparse as sp

    from .graph import connectivities_cpu

    S = _dense_layer(data, "spliced", np)
    U = _dense_layer(data, "unspliced", np)
    if "connectivities" not in data.obsp:
        data = connectivities_cpu(data)
    idx = np.asarray(data.obsp["knn_indices"])[:n]
    w = np.asarray(data.obsp["connectivities"], np.float64)[:n]
    k = idx.shape[1]
    # same union symmetrisation, restricted to the edge list (matches
    # the TPU _symmetrized_weights(mode="union") semantics)
    rows = np.repeat(np.arange(n), k)
    cols = idx.reshape(-1)
    keep = cols >= 0
    W = sp.csr_matrix((w.reshape(-1)[keep], (rows[keep], cols[keep])),
                      shape=(n, n)).tocsr()
    # reverse edge weights w_{j -> i} via one vectorised CSR fancy
    # lookup (a python n*k loop here took minutes at 100k cells)
    w_rev = np.zeros_like(w)
    qi, qj = rows[keep], cols[keep]
    w_rev.reshape(-1)[keep] = np.asarray(W[qj, qi]).ravel()
    w_sym = np.where(idx >= 0, w + w_rev - w * w_rev, 0.0)
    denom = 1.0 + w_sym.sum(axis=1, keepdims=True)
    safe = np.where(idx < 0, 0, idx)

    def smooth(X):
        return np.asarray(
            (X + np.einsum("ck,ckg->cg", w_sym, X[safe])) / denom,
            np.float32)

    out = {"Ms": smooth(S), "Mu": smooth(U)}
    if second:
        out["Mss"] = smooth(S * S)
        out["Mus"] = smooth(U * S)
    return data.with_layers(**out)


@register("velocity.moments", backend="tpu", sharding="cells",
          collective=True)
def moments_tpu(data: CellData, second: bool = False,
                mesh=None, strategy: str = "all_gather") -> CellData:
    """Adds layers["Ms"]/["Mu"] (kNN-smoothed spliced/unspliced);
    ``second=True`` also adds ["Mss"]/["Mus"] for the stochastic
    model.  ``mesh=`` (a ``parallel.make_mesh`` cell mesh) runs the
    heavy (n, g) smoothing cells-sharded over the devices;
    ``strategy="ring"`` keeps per-device memory at one chunk for
    operands too wide to all_gather (parallel/graph_multichip.py)."""
    return _moments(data, device=True, second=second, mesh=mesh,
                    strategy=strategy)


@register("velocity.moments", backend="cpu")
def moments_cpu(data: CellData, second: bool = False) -> CellData:
    return _moments(data, device=False, second=second)


# ----------------------------------------------------------------------
# velocity.estimate — steady-state γ fit + velocities
# ----------------------------------------------------------------------


@partial(jax.jit, static_argnames=())
def _steady_state_fit(Ms, Mu, q):
    """Per-gene γ through the origin over extreme-quantile cells.
    Extremes: cells whose Ms+Mu lies above the (1−q) quantile or at
    zero-expression bottom (the two presumed steady states)."""
    t = Ms + Mu
    hi = jnp.quantile(t, 1.0 - q, axis=0, keepdims=True)
    mask = (t >= hi) | (t <= 0.0)
    wm = mask.astype(jnp.float32)
    sxy = jnp.sum(wm * Ms * Mu, axis=0)
    sxx = jnp.sum(wm * Ms * Ms, axis=0)
    gamma = sxy / jnp.maximum(sxx, 1e-12)
    resid = Mu - gamma[None, :] * Ms
    # r² of the through-origin fit on the extreme set
    ss_res = jnp.sum(wm * resid * resid, axis=0)
    mu_mean = (jnp.sum(wm * Mu, axis=0)
               / jnp.maximum(jnp.sum(wm, axis=0), 1.0))
    ss_tot = jnp.sum(wm * (Mu - mu_mean[None, :]) ** 2, axis=0)
    r2 = 1.0 - ss_res / jnp.maximum(ss_tot, 1e-12)
    return gamma, r2, resid


def _stochastic_core(Ms, Mu, Mss, Mus, q, xp):
    """scVelo's default 'stochastic' mode (Bergen 2020): the
    stationary SECOND moments of the splicing birth-death process
    obey 2·E[us] + E[u] = γ/β · (2·E[s²] − E[s]), so γ solves the
    STACKED system [Mu; 2·Mus + Mu] = γ·[Ms; 2·Mss − Ms] over the
    extreme cells, as weighted least squares with per-equation
    inverse residual-variance weights seeded by a deterministic
    pre-fit (the second-moment residuals carry fourth-moment noise —
    equal weights let them DEGRADE the fit, measured on
    stationary-Poisson synthetic data).  Measured behaviour stated
    honestly (tests): on iid-pooled synthetic steady states the
    deterministic estimator is already efficient and this mode
    matches it to within ~1.5x error; the mode exists for
    scVelo-default parity and for data whose second moments carry
    structure the first don't.  Shared by the jitted device wrapper
    and the float64 numpy wrapper below."""
    t = Ms + Mu
    hi = xp.quantile(t, 1.0 - q, axis=0, keepdims=True)
    wm = ((t >= hi) | (t <= 0.0)).astype(Ms.dtype)
    x2 = 2.0 * Mss - Ms
    y2 = 2.0 * Mus + Mu
    cnt = xp.maximum(wm.sum(axis=0), 1.0)
    g0 = ((wm * Ms * Mu).sum(axis=0)
          / xp.maximum((wm * Ms * Ms).sum(axis=0), 1e-12))
    r1 = wm * (Mu - g0[None, :] * Ms)
    r2_ = wm * (y2 - g0[None, :] * x2)
    v1 = xp.maximum((r1 * r1).sum(axis=0) / cnt, 1e-12)
    v2 = xp.maximum((r2_ * r2_).sum(axis=0) / cnt, 1e-12)
    sxy = ((wm * Ms * Mu).sum(axis=0) / v1
           + (wm * x2 * y2).sum(axis=0) / v2)
    sxx = ((wm * Ms * Ms).sum(axis=0) / v1
           + (wm * x2 * x2).sum(axis=0) / v2)
    gamma = sxy / xp.maximum(sxx, 1e-12)
    vel = Mu - gamma[None, :] * Ms
    resid2 = y2 - gamma[None, :] * x2
    ss_res = (wm * (vel * vel / v1[None, :]
                    + resid2 * resid2 / v2[None, :])).sum(axis=0)
    mu_m = (wm * Mu).sum(axis=0) / cnt
    y2_m = (wm * y2).sum(axis=0) / cnt
    ss_tot = (wm * ((Mu - mu_m[None, :]) ** 2 / v1[None, :]
                    + (y2 - y2_m[None, :]) ** 2
                    / v2[None, :])).sum(axis=0)
    r2 = 1.0 - ss_res / xp.maximum(ss_tot, 1e-12)
    return gamma, r2, vel


@jax.jit
def _stochastic_fit(Ms, Mu, Mss, Mus, q):
    return _stochastic_core(Ms, Mu, Mss, Mus, q, jnp)


def _stochastic_fit_np(Ms, Mu, Mss, Mus, q):
    return _stochastic_core(Ms, Mu, Mss, Mus, q, np)


def _estimate(data: CellData, quantile, min_r2, device,
              mode: str = "deterministic"):
    xp = jnp if device else np
    if mode == "stochastic" and "Mss" not in data.layers:
        data = _moments(data, device, second=True)
    if "Ms" not in data.layers:
        data = _moments(data, device)
    Ms = xp.asarray(data.layers["Ms"], xp.float32)
    Mu = xp.asarray(data.layers["Mu"], xp.float32)
    if mode == "stochastic":
        Mss = xp.asarray(data.layers["Mss"], xp.float32)
        Mus = xp.asarray(data.layers["Mus"], xp.float32)
        if device:
            gamma, r2, vel = _stochastic_fit(Ms, Mu, Mss, Mus, quantile)
        else:
            # float64 on CPU, like the deterministic branch — the
            # stochastic sums hold FOURTH moments (x2² ~ counts⁴), so
            # f32's 7 digits drop the small-cell contributions at
            # high-expression genes
            gamma, r2, vel = _stochastic_fit_np(
                Ms.astype(np.float64), Mu.astype(np.float64),
                Mss.astype(np.float64), Mus.astype(np.float64),
                quantile)
        gamma = np.asarray(gamma, np.float32)
        r2 = np.asarray(r2, np.float32)
        vel = np.asarray(vel, np.float32)
    elif device:
        gamma, r2, vel = _steady_state_fit(Ms, Mu, quantile)
    else:
        Ms64, Mu64 = Ms.astype(np.float64), Mu.astype(np.float64)
        t = Ms64 + Mu64
        hi = np.quantile(t, 1.0 - quantile, axis=0, keepdims=True)
        wm = ((t >= hi) | (t <= 0.0)).astype(np.float64)
        sxy = (wm * Ms64 * Mu64).sum(axis=0)
        sxx = (wm * Ms64 * Ms64).sum(axis=0)
        gamma = sxy / np.maximum(sxx, 1e-12)
        vel = Mu64 - gamma[None, :] * Ms64
        ss_res = (wm * vel * vel).sum(axis=0)
        mu_mean = (wm * Mu64).sum(axis=0) / np.maximum(wm.sum(axis=0), 1.0)
        ss_tot = (wm * (Mu64 - mu_mean[None, :]) ** 2).sum(axis=0)
        r2 = 1.0 - ss_res / np.maximum(ss_tot, 1e-12)
        gamma, r2, vel = (gamma.astype(np.float32), r2.astype(np.float32),
                          vel.astype(np.float32))
    genes_mask = np.asarray(r2) > min_r2
    return (data.with_layers(velocity=vel)
            .with_var(velocity_gamma=np.asarray(gamma),
                      velocity_r2=np.asarray(r2),
                      velocity_genes=genes_mask))


@register("velocity.estimate", backend="tpu")
def estimate_tpu(data: CellData, quantile: float = 0.05,
                 min_r2: float = 0.01,
                 mode: str = "deterministic") -> CellData:
    """Adds layers["velocity"] (= Mu − γ·Ms), var["velocity_gamma"],
    var["velocity_r2"], var["velocity_genes"].  ``mode="stochastic"``
    fits γ on the stacked first+second-moment system (scVelo's
    default mode; computes Mss/Mus if missing)."""
    return _estimate(data, quantile, min_r2, device=True, mode=mode)


@register("velocity.estimate", backend="cpu")
def estimate_cpu(data: CellData, quantile: float = 0.05,
                 min_r2: float = 0.01,
                 mode: str = "deterministic") -> CellData:
    return _estimate(data, quantile, min_r2, device=False, mode=mode)


# ----------------------------------------------------------------------
# velocity.graph — cosine(velocity_i, Ms_j − Ms_i) over kNN edges
# ----------------------------------------------------------------------


@partial(jax.jit, static_argnames=("chunk",))
def _velocity_cosines(Ms, V, idx, chunk: int = _CHUNK):
    n_pad = Ms.shape[0]

    def body(_, inp):
        ms_c, v_c, idx_c = inp  # (chunk, g), (chunk, g), (chunk, k)
        safe = jnp.where(idx_c < 0, 0, idx_c)
        nbr = jnp.take(Ms, safe, axis=0)          # (chunk, k, g)
        delta = nbr - ms_c[:, None, :]
        num = jnp.einsum("ckg,cg->ck", delta, v_c)
        dn = jnp.linalg.norm(delta, axis=2) * jnp.maximum(
            jnp.linalg.norm(v_c, axis=1)[:, None], 1e-12)
        cos = jnp.where(idx_c < 0, 0.0, num / jnp.maximum(dn, 1e-12))
        return _, cos

    k = idx.shape[1]
    nb = n_pad // chunk
    _, cos = jax.lax.scan(
        body, None,
        (Ms.reshape(nb, chunk, -1),
         V.reshape(nb, chunk, -1),
         idx.reshape(nb, chunk, k)))
    return cos.reshape(n_pad, k)


def _vgraph(data: CellData, device):
    n = data.n_cells
    if "velocity" not in data.layers:
        raise KeyError("velocity.graph: run velocity.estimate first")
    idx_np = np.asarray(data.obsp["knn_indices"])[:n]
    genes = np.asarray(data.var.get(
        "velocity_genes", np.ones(data.n_genes, bool)))
    Ms = np.asarray(data.layers["Ms"], np.float32)[:n][:, genes]
    V = np.asarray(data.layers["velocity"], np.float32)[:n][:, genes]
    if device:
        from ..config import round_up

        chunk = min(_CHUNK, round_up(n, 8))
        n_pad = round_up(n, chunk)
        pad = lambda M: jnp.zeros((n_pad, M.shape[1]), jnp.float32
                                  ).at[:n].set(jnp.asarray(M))
        idx_pad = jnp.full((n_pad, idx_np.shape[1]), -1, jnp.int32
                           ).at[:n].set(jnp.asarray(idx_np))
        cos = np.asarray(_velocity_cosines(
            pad(Ms), pad(V), idx_pad, chunk=chunk))[:n]
    else:
        vn = np.linalg.norm(V, axis=1)
        cos = np.zeros_like(idx_np, np.float64)
        for lo in range(0, n, _CHUNK):
            sl = slice(lo, min(lo + _CHUNK, n))
            safe = np.where(idx_np[sl] < 0, 0, idx_np[sl])
            delta = Ms[safe] - Ms[sl][:, None, :]
            num = np.einsum("ckg,cg->ck", delta, V[sl])
            dn = (np.linalg.norm(delta, axis=2)
                  * np.maximum(vn[sl][:, None], 1e-12))
            cos[sl] = np.where(idx_np[sl] < 0, 0.0,
                               num / np.maximum(dn, 1e-12))
    return data.with_obsp(velocity_graph=np.asarray(cos, np.float32))


@register("velocity.graph", backend="tpu")
def vgraph_tpu(data: CellData) -> CellData:
    """Adds obsp["velocity_graph"]: cosine(velocity_i, Ms_j − Ms_i)
    aligned with obsp["knn_indices"] (padded (n, k) edge-list form,
    like every graph here — never an (n, n) matrix)."""
    return _vgraph(data, device=True)


@register("velocity.graph", backend="cpu")
def vgraph_cpu(data: CellData) -> CellData:
    return _vgraph(data, device=False)


# ----------------------------------------------------------------------
# velocity.embedding — arrows in a 2-D basis
# ----------------------------------------------------------------------


def _vembed(data: CellData, basis, scale):
    key = f"X_{basis}" if not basis.startswith("X_") else basis
    if key not in data.obsm:
        raise KeyError(f"velocity.embedding: obsm has no {key!r}")
    if "velocity_graph" not in data.obsp:
        raise KeyError("velocity.embedding: run velocity.graph first")
    n = data.n_cells
    E = np.asarray(data.obsm[key], np.float64)[:n]
    idx = np.asarray(data.obsp["knn_indices"])[:n]
    cos = np.asarray(data.obsp["velocity_graph"], np.float64)[:n]
    # softmax transition weights over each cell's edges; subtracting
    # the uniform expectation keeps a zero-velocity cell's arrow ~0
    # (scVelo's convention)
    z = np.where(idx < 0, -np.inf, cos / scale)
    z = z - z.max(axis=1, keepdims=True)
    T = np.exp(z)
    T /= np.maximum(T.sum(axis=1, keepdims=True), 1e-12)
    k_eff = np.maximum((idx >= 0).sum(axis=1, keepdims=True), 1)
    uniform = np.where(idx >= 0, 1.0 / k_eff, 0.0)
    safe = np.where(idx < 0, 0, idx)
    delta = E[safe] - E[:, None, :]
    arrows = np.einsum("ck,ckd->cd", T - uniform, delta)
    col = f"velocity_{basis.removeprefix('X_')}"
    return data.with_obsm(**{col: arrows.astype(np.float32)})


@register("velocity.embedding", backend="tpu")
@register("velocity.embedding", backend="cpu")
def vembed(data: CellData, basis: str = "umap",
           scale: float = 0.1) -> CellData:
    """Adds obsm["velocity_<basis>"]: per-cell arrows = Σ_j (T_ij −
    uniform)(e_j − e_i) with T the softmax of the velocity-graph
    cosines.  O(n·k·2) host math on fetched edge data — identical on
    both backends."""
    return _vembed(data, basis, scale)


# ----------------------------------------------------------------------
# velocity.terminal_states / velocity.fate_probabilities — CellRank-
# style fate mapping on the velocity-directed kNN chain
# ----------------------------------------------------------------------


def _velocity_transition(data: CellData, scale: float,
                         lambda_conn: float = 0.2, device=False):
    """Row-stochastic T over the UNDIRECTED-UNION kNN edge list: a
    (1−λ)/λ blend of the velocity kernel exp(cosine/scale) with a
    uniform diffusive walk — CellRank's kernel-combination recipe.

    Two details are load-bearing, both measured on the Y-fixture:

    * the support is the union of each directed edge and its reverse
      (wishbone's ``_sym_edges``).  Out-edge-only support broke
      reachability: the overlap zone at a branch had no OUT-edge onto
      one arm's continuation even though the reverse edge existed, so
      that arm's absorption probability was exactly 0 for every
      upstream cell;
    * the diffusive component: a pure velocity kernel is near-
      deterministic and funnels all mass through whichever branch
      wins the first tie-break in the noise.

    Cosines for added reverse edges are recomputed with the same
    kernel ``velocity.graph`` uses (the jitted device path on the tpu
    backend; chunked numpy on cpu)."""
    n = data.n_cells
    if "velocity" not in data.layers or "Ms" not in data.layers:
        raise KeyError("velocity fate mapping: run velocity.estimate "
                       "(and velocity.graph) first")
    if "knn_indices" not in data.obsp:
        raise KeyError("velocity fate mapping: run neighbors.knn first")
    from .wishbone import _sym_edges

    idx = np.asarray(data.obsp["knn_indices"])[:n]
    dist = np.asarray(data.obsp.get(
        "knn_distances", np.ones_like(idx, np.float32)), np.float64)[:n]
    idx2, _ = _sym_edges(idx, dist)
    genes = np.asarray(data.var.get(
        "velocity_genes", np.ones(data.n_genes, bool)))
    Ms = np.asarray(data.layers["Ms"], np.float32)[:n][:, genes]
    V = np.asarray(data.layers["velocity"], np.float32)[:n][:, genes]
    if device:
        from ..config import round_up

        K2 = idx2.shape[1]
        chunk = min(_CHUNK, round_up(n, 8))
        n_pad = round_up(n, chunk)
        pad = lambda M: jnp.zeros((n_pad, M.shape[1]), jnp.float32
                                  ).at[:n].set(jnp.asarray(M))
        idx_pad = jnp.full((n_pad, K2), -1, jnp.int32
                           ).at[:n].set(jnp.asarray(idx2))
        cos = np.asarray(_velocity_cosines(
            pad(Ms), pad(V), idx_pad, chunk=chunk), np.float64)[:n]
    else:
        vn = np.linalg.norm(V, axis=1)
        cos = np.zeros_like(idx2, np.float64)
        for lo in range(0, n, _CHUNK):
            sl = slice(lo, min(lo + _CHUNK, n))
            safe = np.where(idx2[sl] < 0, 0, idx2[sl])
            delta = Ms[safe] - Ms[sl][:, None, :]
            num = np.einsum("ckg,cg->ck", delta, V[sl])
            dn = (np.linalg.norm(delta, axis=2)
                  * np.maximum(vn[sl][:, None], 1e-12))
            cos[sl] = np.where(idx2[sl] < 0, 0.0,
                               num / np.maximum(dn, 1e-12))
    ok = (idx2 >= 0).astype(np.float64)
    Tv = ok * np.exp(cos / scale)
    Tv /= np.maximum(Tv.sum(axis=1, keepdims=True), 1e-12)
    Tc = ok / np.maximum(ok.sum(axis=1, keepdims=True), 1e-12)
    T = (1.0 - lambda_conn) * Tv + lambda_conn * Tc
    T /= np.maximum(T.sum(axis=1, keepdims=True), 1e-12)
    return idx2, T


@register("velocity.terminal_states", backend="cpu")
def terminal_states(data: CellData, scale: float = 0.25,
                    quantile: float = 0.95, min_cells: int = 5,
                    n_iter: int = 300, device: bool = False) -> CellData:
    """Find absorbing regions of the velocity-directed chain: the
    stationary distribution (power iteration of Tᵀ over the edge
    list) concentrates on cells flow converges INTO; the top-quantile
    cells are grouped into connected components and small groups are
    dropped.  Adds obs["terminal_states"] (-1 = not terminal, else
    group id) and uns["terminal_stationary"].  Host numpy — the chain
    bookkeeping is O(n·k) and shared verbatim by both backends (the
    heavy inputs, velocity graph and connectivities, were computed on
    device upstream)."""
    n = data.n_cells
    idx, T = _velocity_transition(data, scale, device=device)
    k = idx.shape[1]
    # stationary distribution: pi <- pi T via edge scatter
    pi = np.full(n, 1.0 / n)
    rows = np.repeat(np.arange(n), k)
    cols = np.where(idx >= 0, idx, 0).ravel()
    vals = T.ravel()
    for _ in range(n_iter):
        nxt = np.zeros(n)
        np.add.at(nxt, cols, vals * pi[rows])
        s = nxt.sum()
        if s <= 0:
            break
        nxt /= s
        if np.abs(nxt - pi).max() < 1e-12:
            pi = nxt
            break
        pi = nxt
    thresh = np.quantile(pi, quantile)
    top = np.where(pi >= thresh)[0]
    # connected components among top cells (undirected kNN edges)
    top_set = set(top.tolist())
    label = {c: -1 for c in top.tolist()}
    gid = 0
    for c in top.tolist():
        if label[c] != -1:
            continue
        stack = [c]
        label[c] = gid
        while stack:
            u = stack.pop()
            for v in idx[u]:
                v = int(v)
                if v in top_set and label[v] == -1:
                    label[v] = gid
                    stack.append(v)
        gid += 1
    counts = np.bincount([label[c] for c in top.tolist()],
                         minlength=gid)
    keep = {g for g in range(gid) if counts[g] >= min_cells}
    remap = {g: i for i, g in enumerate(sorted(keep))}
    out = np.full(n, -1, np.int32)
    for c in top.tolist():
        if label[c] in keep:
            out[c] = remap[label[c]]
    return (data.with_obs(terminal_states=out)
            .with_uns(terminal_stationary=pi.astype(np.float32)))


@register("velocity.fate_probabilities", backend="cpu")
def fate_probabilities(data: CellData,
                       terminal_key: str = "terminal_states",
                       scale: float = 0.25, n_iter: int = 2000,
                       device: bool = False) -> CellData:
    """Absorption probabilities of the velocity-directed chain into
    each terminal group: iterate F <- Q F + R (Jacobi on the linear
    system (I − Q) F = R — Q is substochastic on transient cells, so
    the iteration contracts).  Adds obsm["fate_probs"]
    (n x n_terminal; terminal rows are one-hot on their own group)."""
    n = data.n_cells
    if terminal_key not in data.obs:
        raise KeyError("velocity.fate_probabilities: run "
                       "velocity.terminal_states first")
    term = np.asarray(data.obs[terminal_key])[:n].astype(int)
    n_groups = int(term.max()) + 1
    if n_groups < 1:
        raise ValueError("velocity.fate_probabilities: no terminal "
                         "states found")
    idx, T = _velocity_transition(data, scale, device=device)
    k = idx.shape[1]
    absorbed = term >= 0
    F = np.zeros((n, n_groups))
    F[absorbed, term[absorbed]] = 1.0
    safe = np.where(idx >= 0, idx, 0)
    Tm = np.where(idx >= 0, T, 0.0)
    transient = ~absorbed
    for _ in range(n_iter):
        nxt = np.einsum("ck,ckg->cg", Tm, F[safe])
        nxt[absorbed] = F[absorbed]
        if np.abs(nxt - F).max() < 1e-10:
            F = nxt
            break
        F = nxt
    # rows that never reach any terminal state stay ~0 — normalise
    # only where mass arrived, leave true orphans at zero
    s = F.sum(axis=1, keepdims=True)
    F = np.where(s > 1e-8, F / np.maximum(s, 1e-12), 0.0)
    F[absorbed] = 0.0
    F[absorbed, term[absorbed]] = 1.0
    return data.with_obsm(fate_probs=F.astype(np.float32))


@register("velocity.terminal_states", backend="tpu")
def terminal_states_tpu(data: CellData, scale: float = 0.25,
                        quantile: float = 0.95, min_cells: int = 5,
                        n_iter: int = 300) -> CellData:
    """tpu backend: union-edge cosine recomputation runs through the
    jitted _velocity_cosines kernel; the O(n·k) chain bookkeeping
    stays host numpy (see terminal_states)."""
    return terminal_states(data, scale, quantile, min_cells, n_iter,
                           device=True)


@register("velocity.fate_probabilities", backend="tpu")
def fate_probabilities_tpu(data: CellData,
                           terminal_key: str = "terminal_states",
                           scale: float = 0.25,
                           n_iter: int = 2000) -> CellData:
    """tpu backend of :func:`fate_probabilities` (device cosines)."""
    return fate_probabilities(data, terminal_key, scale, n_iter,
                              device=True)


# ----------------------------------------------------------------------
# velocity.lineage_drivers
# ----------------------------------------------------------------------


def _lineage_drivers(data: CellData, layer: str, device: bool):
    """Per-gene Pearson correlation with each lineage's fate
    probability across TRANSIENT cells (CellRank ``lineage_drivers``:
    a gene whose expression tracks commitment toward a fate is a
    candidate driver of it).  Terminal cells are excluded — their
    one-hot fate rows would let any marker of the terminal cluster
    masquerade as a driver of the journey there.

    One centered cross-product matmul per call: corr = (Xc^T Fc)
    / (||Xc_g|| * ||Fc_l||) — (n_genes x n_lineages) on the MXU for
    the device path, numpy otherwise.  Adds varm["lineage_drivers"].
    """
    if "fate_probs" not in data.obsm:
        raise KeyError("velocity.lineage_drivers: run "
                       "velocity.fate_probabilities first")
    n = data.n_cells
    F = np.asarray(data.obsm["fate_probs"])[:n].astype(np.float32)
    term = np.asarray(data.obs["terminal_states"])[:n].astype(int)
    mask = term < 0  # transient cells only
    if mask.sum() < 3:
        raise ValueError("velocity.lineage_drivers: fewer than 3 "
                         "transient cells")
    if device:
        X = _dense_layer(data, layer, jnp)
        Xm = jnp.asarray(X)[jnp.asarray(mask)]
        Fm = jnp.asarray(F)[jnp.asarray(mask)]
        Xc = Xm - Xm.mean(axis=0)
        Fc = Fm - Fm.mean(axis=0)
        num = Xc.T @ Fc  # (g, l) — the MXU cross-product
        den = (jnp.linalg.norm(Xc, axis=0)[:, None]
               * jnp.linalg.norm(Fc, axis=0)[None, :])
        corr = np.asarray(num / jnp.maximum(den, 1e-12))
    else:
        X = _dense_layer(data, layer, np)
        Xm, Fm = X[mask], F[mask]
        Xc = Xm - Xm.mean(axis=0)
        Fc = Fm - Fm.mean(axis=0)
        den = (np.linalg.norm(Xc, axis=0)[:, None]
               * np.linalg.norm(Fc, axis=0)[None, :])
        corr = (Xc.T @ Fc) / np.maximum(den, 1e-12)
    # zero-variance genes (or a zero-variance lineage) carry no signal
    corr = np.where(np.isfinite(corr), corr, 0.0).astype(np.float32)
    return data.with_varm(lineage_drivers=corr)


@register("velocity.lineage_drivers", backend="tpu")
def lineage_drivers_tpu(data: CellData,
                        layer: str = "Ms") -> CellData:
    """CellRank-style driver-gene correlations (device matmul)."""
    return _lineage_drivers(data, layer, device=True)


@register("velocity.lineage_drivers", backend="cpu")
def lineage_drivers_cpu(data: CellData,
                        layer: str = "Ms") -> CellData:
    return _lineage_drivers(data, layer, device=False)


# ----------------------------------------------------------------------
# velocity.recover_dynamics / velocity.latent_time (scVelo dynamical)
# ----------------------------------------------------------------------


def _dyn_traj(la, lb, lg, ts, tgrid):
    """(u(t), s(t)) of the splicing ODE on a time grid, one gene.

    du/dt = α·[t<ts] − β·u ; ds/dt = β·u − γ·s, from (0,0): closed
    forms for the induction branch and, after the switch at ts, the
    repression branch from the switch-point state.  Rates are carried
    in log space (positivity); γ is nudged off β to avoid the
    removable singularity in the (γ−β) denominators.
    """
    a, b = jnp.exp(la), jnp.exp(lb)
    g = jnp.exp(lg)
    g = jnp.where(jnp.abs(g - b) < 1e-3 * b, b * 1.001, g)

    def state_on(t):
        u = a / b * (1.0 - jnp.exp(-b * t))
        s = (a / g * (1.0 - jnp.exp(-g * t))
             + a / (g - b) * (jnp.exp(-g * t) - jnp.exp(-b * t)))
        return u, s

    u_sw, s_sw = state_on(ts)
    tau = jnp.maximum(tgrid - ts, 0.0)
    u_off = u_sw * jnp.exp(-b * tau)
    # s(τ) = s_sw·e^{−γτ} + β·u_sw·∫₀^τ e^{−γ(τ−x)} e^{−βx} dx and the
    # integral is (e^{−βτ} − e^{−γτ})/(γ−β) — review caught the
    # flipped difference here (verified against numeric integration;
    # the flipped form even goes negative), and the test fixture now
    # integrates the ODE numerically so the two cannot share a bug
    s_off = (s_sw * jnp.exp(-g * tau)
             + b * u_sw / (g - b) * (jnp.exp(-b * tau)
                                     - jnp.exp(-g * tau)))
    u_on, s_on = state_on(jnp.minimum(tgrid, ts))
    on = tgrid <= ts
    return jnp.where(on, u_on, u_off), jnp.where(on, s_on, s_off)


def _dyn_fit_gene(u, s, slope, n_outer=40, n_inner=5, n_grid=64,
                  lr=0.05):
    """EM-style dynamical fit for ONE gene (vmapped across genes).

    E-step: assign each cell the nearest grid time on the current
    trajectory (normalised (u,s) space).  M-step: ``n_inner`` Adam
    steps on (log α, log β, log γ, switch logit) against the squared
    distance at the assigned times.  Everything is fixed-iteration
    ``lax.scan`` — no data-dependent control flow.

    Returns (params, t_cells, r2): params = (α, β, γ, t_switch_ecdf,
    fit_scaling, t_switch_geometric) — SIX entries — in NORMALISED
    units (u, s scaled to
    ~unit 99th percentile, t in [0, 1] — absolute time is not
    identifiable from one snapshot, so the latent-time scale is fixed
    instead of the rates; fit_scaling is the u measurement scale,
    optimised as its log alongside the log-rates and switch logit).
    """
    half = jnp.linspace(0.0, 1.0, n_grid // 2)

    # Measurement-scale parameter (scVelo's fit_scaling): u and s are
    # normalised by DIFFERENT per-gene scales, and u itself is
    # captured with different efficiency — so the observed u is
    # c·u_ode with c free.  Without it, one shared β must serve two
    # incompatibly-scaled equations and the fitted γ/β ratio (hence
    # every velocity SIGN) comes out wrong — the exact-ODE test
    # caught repression-phase cells with uniformly positive ds/dt.

    def assign(params):
        la, lb, lg, ta, lc = params
        ts = jax.nn.sigmoid(ta)
        # branch-balanced grid: half the points on EACH side of the
        # switch, however compressed either branch's time span is — a
        # uniform [0,1] grid starves a short induction segment of
        # points and biases assignment (hence the reported switch
        # fraction) toward the other branch
        tgrid = jnp.concatenate([ts * half, ts + (1.0 - ts) * half])
        ut, st = _dyn_traj(la, lb, lg, ts, tgrid)
        d2 = (u[:, None] - jnp.exp(lc) * ut[None, :]) ** 2 \
            + (s[:, None] - st[None, :]) ** 2
        return tgrid[jnp.argmin(d2, axis=1)]

    def loss_fn(params, t_cells):
        la, lb, lg, ta, lc = params
        ts = jax.nn.sigmoid(ta)
        ut, st = _dyn_traj(la, lb, lg, ts, t_cells)
        return jnp.mean((u - jnp.exp(lc) * ut) ** 2 + (s - st) ** 2)

    beta0 = 4.0
    gamma0 = jnp.clip(slope, 1e-2, 1e2) * beta0
    params0 = jnp.stack([jnp.log(beta0 * jnp.maximum(u.max(), 1e-3)),
                         jnp.log(beta0), jnp.log(gamma0), 0.0, 0.0])
    m0 = jnp.zeros(5)
    v0 = jnp.zeros(5)
    grad = jax.grad(loss_fn)

    def outer(carry, i):
        params, m, v = carry
        t_cells = assign(params)

        def inner(c, j):
            p, m, v = c
            gr = grad(p, t_cells)
            m = 0.9 * m + 0.1 * gr
            v = 0.999 * v + 0.001 * gr * gr
            step = i * n_inner + j + 1.0
            mh = m / (1.0 - 0.9 ** step)
            vh = v / (1.0 - 0.999 ** step)
            p = p - lr * mh / (jnp.sqrt(vh) + 1e-8)
            return (p, m, v), None

        (params, m, v), _ = jax.lax.scan(
            inner, (params, m, v), jnp.arange(n_inner, dtype=jnp.float32))
        return (params, m, v), None

    (params, _, _), _ = jax.lax.scan(
        outer, (params0, m0, v0),
        jnp.arange(n_outer, dtype=jnp.float32))
    t_cells = assign(params)
    la, lb, lg, ta, lc = params
    ts = jax.nn.sigmoid(ta)
    ut, st = _dyn_traj(la, lb, lg, ts, t_cells)
    ss_res = jnp.sum((u - jnp.exp(lc) * ut) ** 2 + (s - st) ** 2)
    ss_tot = jnp.sum((u - u.mean()) ** 2 + (s - s.mean()) ** 2)
    r2 = 1.0 - ss_res / jnp.maximum(ss_tot, 1e-12)
    # uniform-latent-time prior, applied as a POST-HOC monotone warp:
    # the geometric fit fixes the curve and the cell ORDER along it,
    # but traversal speed is free (a tiny ts with fast rates draws the
    # same shape), which left the reported switch time unidentifiable
    # — measured ANTI-correlated with truth on exact-ODE data, and an
    # in-loss density anchor degraded the geometry fit instead.  ECDF
    # warping the assigned times to uniform (ties preserved) and
    # mapping ts through the same warp reports both on the scale a
    # uniform prior over latent time implies.
    t_sorted = jnp.sort(t_cells)
    n_c = t_cells.shape[0]
    t_ecdf = (jnp.searchsorted(t_sorted, t_cells, side="right")
              .astype(jnp.float32)) / n_c
    ts_ecdf = (jnp.searchsorted(t_sorted, ts, side="right")
               .astype(jnp.float32)) / n_c
    # keep BOTH switch times: the ECDF-warped one lives on the same
    # scale as the reported cell times; the GEOMETRIC one is what
    # _dyn_traj needs to reconstruct the fitted curve (pl.velocity) —
    # review caught the warped value being fed back into the ODE
    return (jnp.stack([jnp.exp(la), jnp.exp(lb), jnp.exp(lg), ts_ecdf,
                       jnp.exp(lc), ts]),
            t_ecdf, r2)


@partial(jax.jit, static_argnames=("n_outer",))
def _dyn_fit_all(un, sn, slope, n_outer):
    """Module-scope jit of the vmapped per-gene fit — a fresh lambda
    per call would recompile the 40x5 scan on every invocation."""
    return jax.vmap(
        lambda u, s, sl: _dyn_fit_gene(u, s, sl, n_outer=n_outer),
        in_axes=(1, 1, 0), out_axes=(0, 0, 0))(un, sn, slope)


@register("velocity.recover_dynamics", backend="tpu")
@register("velocity.recover_dynamics", backend="cpu")
def recover_dynamics(data: CellData, min_r2: float = 0.3,
                     n_outer: int = 40) -> CellData:
    """scVelo-style DYNAMICAL velocity model (Bergen 2020): per-gene
    splicing-ODE fit (α, β, γ, switch time) with per-cell latent
    times, replacing the steady-state γ-only model.

    Capability parity: the published model EM-alternates per-cell time
    assignment with rate updates; this implementation keeps exactly
    that structure as fixed-iteration jitted loops, vmapped across
    genes (the per-gene problems are independent — embarrassingly
    parallel on the VPU).  Documented simplifications, validated on
    synthetic ODE data in tests/test_velocity.py: (a) time assignment
    is a 64-point grid projection, not a continuous root-solve; (b)
    the latent-time scale is fixed to [0,1] per gene (absolute time is
    unidentifiable from one snapshot — scVelo fixes rates instead);
    (c) no per-cell likelihood variances (scVelo's fit_std_u/s).

    Needs layers["Ms"]/["Mu"] (run velocity.moments first).  Adds
    var["fit_alpha"/"fit_beta"/"fit_gamma"/"fit_t_switch" (ECDF
    scale) / "fit_t_switch_geo" (ODE scale, for curve
    reconstruction) / "fit_scaling"/"fit_r2"],
    layers["fit_t"] (per-cell per-gene latent time),
    layers["velocity"] = β·u − γ·s in NORMALISED units (feeds
    velocity.graph unchanged), var["velocity_genes"] = fit_r2 gate,
    var["velocity_gamma"] = fitted γ.
    """
    n = data.n_cells
    # _dense_layer: names the velocity.moments prerequisite on a
    # missing layer and densifies sparse-resident layers
    Ms = np.asarray(_dense_layer(data, "Ms", np), np.float32)[:n]
    Mu = np.asarray(_dense_layer(data, "Mu", np), np.float32)[:n]
    # normalise per gene: unit ~99th percentile, like scVelo's
    # std-ratio scaling — conditions the shared-lr Adam fit
    su = np.maximum(np.percentile(Mu, 99, axis=0), 1e-6)
    ss = np.maximum(np.percentile(Ms, 99, axis=0), 1e-6)
    un = jnp.asarray(Mu / su[None, :])
    sn = jnp.asarray(Ms / ss[None, :])
    slope, _, _ = _steady_state_fit(sn, un, 0.05)
    params, t_cells, r2 = _dyn_fit_all(un, sn, slope, n_outer)
    params = np.asarray(params)
    t_cells = np.asarray(t_cells).T  # (n, g)
    r2 = np.asarray(r2)
    alpha, beta, gamma, t_sw, scal, t_sw_geo = params.T
    # ds/dt in RAW Ms units (velocity.graph cosines mix this with raw
    # Ms displacements — per-gene-normalised units would silently
    # reweight every gene by 1/ss in the graph): the normalised-space
    # rate expression, times ss
    vel = np.asarray(beta[None, :] * np.asarray(un)
                     / np.maximum(scal[None, :], 1e-6)
                     - gamma[None, :] * np.asarray(sn)) * ss[None, :]
    # velocity_gamma in velocity.estimate's convention (the raw-unit
    # Mu-vs-Ms steady-state slope): slope = (γ/β)·(su·scaling/ss)
    gamma_slope = (gamma / np.maximum(beta, 1e-12)
                   * su * scal / ss).astype(np.float32)
    out = data.with_var(
        fit_alpha=alpha.astype(np.float32),
        fit_beta=beta.astype(np.float32),
        fit_gamma=gamma.astype(np.float32),
        fit_t_switch=t_sw.astype(np.float32),
        fit_t_switch_geo=t_sw_geo.astype(np.float32),
        fit_scaling=scal.astype(np.float32),
        fit_r2=r2.astype(np.float32),
        velocity_gamma=gamma_slope,
        velocity_r2=r2.astype(np.float32),
        velocity_genes=(r2 > min_r2),
    )
    return out.with_layers(fit_t=t_cells.astype(np.float32),
                           velocity=vel.astype(np.float32))


@register("velocity.latent_time", backend="tpu")
@register("velocity.latent_time", backend="cpu")
def latent_time(data: CellData, min_r2: float = 0.3) -> CellData:
    """Gene-shared latent time: fit-quality-weighted mean of the
    per-gene dynamical times, refined by CONSENSUS reweighting — two
    further rounds in which each gene's weight is multiplied by its
    positive correlation with the current shared time (scVelo's
    iterative refinement in spirit; its root-cell anchoring pass is
    the documented omission).  The reweighting downweights genes whose
    assignment confused the self-intersecting ends of the (u, s) loop.
    Needs velocity.recover_dynamics.  Adds obs["latent_time"]."""
    if "fit_t" not in data.layers:
        raise KeyError("velocity.latent_time: run "
                       "velocity.recover_dynamics first")
    n = data.n_cells
    T = np.asarray(data.layers["fit_t"], np.float32)[:n]
    r2 = np.asarray(data.var["fit_r2"], np.float32)
    w0 = np.clip(r2, 0.0, None) * (r2 > min_r2)
    if w0.sum() <= 0:
        raise ValueError("velocity.latent_time: no gene passes the "
                         f"fit_r2 > {min_r2} gate")
    w = w0
    lt = T @ w / w.sum()
    for _ in range(2):
        Tc = T - T.mean(axis=0, keepdims=True)
        lc = lt - lt.mean()
        corr = (Tc * lc[:, None]).sum(axis=0) / np.maximum(
            np.linalg.norm(Tc, axis=0) * np.linalg.norm(lc), 1e-12)
        w = w0 * np.clip(corr, 0.0, None)
        if w.sum() <= 0:  # degenerate consensus: keep round-0 answer
            w = w0
            break
        lt = T @ w / w.sum()
    lo, hi = lt.min(), lt.max()
    lt = (lt - lo) / max(hi - lo, 1e-12)
    return data.with_obs(latent_time=lt.astype(np.float32))
