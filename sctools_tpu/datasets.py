"""``sct.datasets`` — the offline subset of scanpy's ``sc.datasets``.

Capability parity: scanpy ships dataset helpers; the network-fetched
ones (pbmc3k, pbmc68k_reduced, ...) cannot exist in an offline
environment and are NOT faked here — asking for them raises with the
honest reason.  The procedurally GENERATED ones (``blobs``; plus this
framework's synthetic single-cell generators under their own names)
work anywhere.
"""

from __future__ import annotations

import numpy as np

from .data.dataset import CellData


def blobs(n_variables: int = 11, n_centers: int = 5,
          cluster_std: float = 1.0, n_observations: int = 640,
          random_state: int = 0) -> CellData:
    """Gaussian blobs (scanpy ``sc.datasets.blobs``): dense X with a
    ground-truth ``obs['blobs']`` cluster label."""
    rng = np.random.default_rng(random_state)
    centers = rng.normal(0.0, 5.0, (n_centers, n_variables))
    # guaranteed coverage: every center gets ~n/k members (sampling
    # labels independently can leave centers empty at small n), and
    # labels are STRINGS like scanpy's blobs — ported code compares
    # against '0'/'1'/... and int labels would silently match nothing
    labels = rng.permutation(np.arange(n_observations) % n_centers)
    X = (centers[labels]
         + rng.normal(0.0, cluster_std,
                      (n_observations, n_variables)))
    return CellData(X.astype(np.float32),
                    obs={"blobs": labels.astype(str)})


def synthetic_counts(n_cells: int = 2700, n_genes: int = 3000,
                     **kwargs) -> CellData:
    """Clustered sparse count matrix — a pure re-export of
    ``data.synthetic.synthetic_counts`` (same defaults; re-stating
    them here once silently diverged from the source of truth)."""
    from .data.synthetic import synthetic_counts as _sc

    return _sc(n_cells, n_genes, **kwargs)


def pbmc3k_like(seed: int = 0) -> CellData:
    """A pbmc3k-SHAPED synthetic dataset (2700 × 32738, ~8 clusters)
    for offline tutorials.  This is NOT the real 10x pbmc3k — no
    network exists here to fetch it, and shipping synthetic counts
    under the real name would be worse than saying so."""
    return synthetic_counts(2700, 32738, density=0.02, n_clusters=8,
                            seed=seed)


def _network_required(name: str):
    def f(*a, **kw):
        raise RuntimeError(
            f"sct.datasets.{name}: scanpy fetches this dataset from "
            f"the network, which this environment does not have; use "
            f"datasets.pbmc3k_like()/synthetic_counts()/blobs() for "
            f"offline stand-ins, or read your own file with sct.read")
    f.__name__ = name
    return f


pbmc3k = _network_required("pbmc3k")
pbmc3k_processed = _network_required("pbmc3k_processed")
pbmc68k_reduced = _network_required("pbmc68k_reduced")
paul15 = _network_required("paul15")
moignard15 = _network_required("moignard15")
