"""Resilient pipeline execution: retry, backoff, containment, resume.

``Pipeline.run()`` is a bare loop — it dies on the first transient
error and restarts from scratch.  On the hardware this framework
targets that is the WRONG default: rounds 1–5 of the bench established
empirically (bench.py, VERDICT.md) that the tunneled TPU backend
crashes (every later call raises ``UNAVAILABLE``) and wedges (calls
block forever), and at atlas scale preemption is the common case, not
the exception.  The survival primitives already exist —
``utils/failsafe.py`` (probes, watched subprocesses, the retryable-
error taxonomy), ``utils/checkpoint.py`` (step-fingerprinted
checkpoints), ``utils/trace.py`` (spans) — this module composes them
into one execution layer:

* **Per-step retry with exponential backoff + jitter** — transient
  device errors (``UNAVAILABLE``, timeouts; ``failsafe.classify_error``)
  are retried up to ``RetryPolicy.max_attempts``; deterministic
  program errors (ValueError, shape errors) FAIL FAST on the first
  attempt — retrying them only burns the budget.
* **Health checks + degrade-to-CPU** — before the run (``preflight=``)
  and after a step exhausts its retries, ``failsafe.probe_device``
  rules on the accelerator from a throwaway subprocess; ruled
  unhealthy, the run degrades every remaining step to the
  ``fallback_backend`` with a loud warning rather than dying.
* **Subprocess containment** — steps named in ``isolate=`` run under
  ``failsafe.run_isolated``: a crash or wedge kills the CHILD, the
  runner's process (and its jax runtime) stays clean, and the death
  is classified transient (retried, possibly degraded).
* **Checkpointed resume with integrity** — with ``checkpoint_dir=``,
  every completed step is checkpointed under its content fingerprint
  (``checkpoint.step_filename``) with an embedded digest; a killed
  run re-invoked with ``resume=True`` restarts at the failed step.
  Resume VERIFIES every candidate file (``verify_checkpoint``) before
  trusting it: corrupt or mismatched files are QUARANTINED (moved to
  ``quarantine/``, never deleted, reason journaled) and resume falls
  back past them deterministically.  The input data's content digest
  is part of every fingerprint, so a resume against different data
  recomputes instead of returning the previous run's result.
  Filenames are shared with ``PipelineCheckpointer``, so the two
  interoperate.
* **Per-step deadlines** — ``step_deadline_s=`` gives every step
  ATTEMPT a wall-clock budget (a fresh token per retry — a retried
  attempt must be allowed the time a wedge stole; worst-case step
  wall is therefore budget × attempts + backoff): a cooperative
  ``DeadlineToken`` is threaded through the registry call-wrapper
  hooks (checked before and after every transform invocation),
  isolated steps inherit the remaining budget as their watchdog
  timeout, and an overrun raises ``StepDeadlineExceeded`` —
  classified transient, so it is journaled and retried/degraded like
  any other device error.
* **Circuit breaker** — after K classified-transient accelerator
  failures in a sliding window (``failsafe.CircuitBreaker``) the
  breaker OPENS and further accelerator attempts short-circuit
  straight to the degrade ruling — no retry storm, no 90 s probe
  storm.  After the cooldown it HALF-OPENS; one successful probe
  closes it and un-degrades the run.
* **Structured run journal** — one JSONL record per event (attempt,
  backoff, deadline, breaker transition, fallback, quarantine,
  resume, completion) with the classified error, backend, wall time
  and the ``trace.span`` id it links to; the in-memory
  :class:`RunReport` mirrors it.
* **Telemetry** — every recovery ruling also increments a metric in
  the (injectable) ``utils/telemetry.py`` registry — retries,
  degrades, breaker transitions, quarantines, deadline overruns,
  checkpoint bytes — and every transform call is auto-instrumented
  (per-op call count + duration, labelled cpu/tpu/degraded) through
  the registry call-wrapper hook.  At run end the runner writes
  ``metrics.json`` and a Perfetto-loadable ``trace.json`` next to
  the journal; ``python -m tools.sctreport <checkpoint_dir>`` merges
  the three into one run report.  Isolated steps hand their span
  TREE back through the handoff file and it is grafted under the
  parent's step span (their in-child op metrics are not merged — the
  parent's attempt record and spans carry the containment story).

All time sources are injectable (``sleep=``, ``probe=``, ``clock=`` —
see ``utils/vclock.py``), so recovery behaviour — backoff schedules,
deadline overruns, breaker cooldowns — is testable in tier-1 with
zero real sleeps (tests/test_runner.py, tests/test_integrity.py),
with faults injected deterministically by ``utils/chaos.py``.

>>> from sctools_tpu.runner import ResilientRunner
>>> runner = ResilientRunner(seurat_pipeline(), checkpoint_dir="ck/")
>>> out = runner.run(data, backend="tpu")     # survives; resumes
>>> runner.report.summary()
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import tempfile
import threading as _threading
import time
import warnings

from . import memory as _memory
from . import registry as _registry
from .registry import Pipeline, Transform
from .utils import telemetry, trace
from .utils.checkpoint import (CheckpointCorruptError, data_digest,
                               load_celldata, quarantine_checkpoint,
                               save_celldata, step_filename,
                               step_fingerprint, latest_step)
from .utils.failsafe import (DETERMINISTIC, FATAL, RESOURCE, TRANSIENT,
                             CircuitBreaker, DeadlineToken,
                             JobPreempted, StepDeadlineExceeded,
                             check_deadline, classify_child_result,
                             classify_error, current_deadline,
                             deadline_scope, default_breaker_registry,
                             probe_device, run_isolated)
from .utils.vclock import SYSTEM_CLOCK

#: the backend runs degrade to when the accelerator is ruled
#: unhealthy.  ONE definition: ResilientRunner's ``fallback_backend=``
#: default and the scheduler's breaker-signature resolution both read
#: it — if they disagreed, pool runs would silently stop sharing
#: breaker state.
DEFAULT_FALLBACK_BACKEND = "cpu"


@dataclasses.dataclass
class RetryPolicy:
    """Exponential backoff with deterministic seeded jitter.

    Attempt ``n`` (1-based) that fails transiently waits
    ``min(base_delay_s * multiplier**(n-1), max_delay_s)`` scaled by a
    jitter factor uniform in ``[1-jitter, 1+jitter)`` drawn from a
    ``random.Random(seed)`` stream — same seed, same schedule, which
    is what lets tier-1 pin the exact delays against a fake sleeper.
    """

    max_attempts: int = 3
    base_delay_s: float = 0.5
    multiplier: float = 2.0
    max_delay_s: float = 30.0
    jitter: float = 0.5
    seed: int = 0

    def delay_s(self, attempt: int, rng) -> float:
        d = min(self.base_delay_s * self.multiplier ** max(attempt - 1, 0),
                self.max_delay_s)
        if self.jitter > 0:
            d *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return d


@dataclasses.dataclass
class StepAttempt:
    attempt: int
    backend: str
    status: str                      # "ok" | "error"
    wall_s: float
    span_id: int
    error: str | None = None
    classified: str | None = None    # transient | deterministic | fatal


@dataclasses.dataclass
class StepReport:
    index: int
    name: str
    fingerprint: str
    status: str = "pending"   # pending|completed|resumed|failed|aborted
    backend: str | None = None
    isolated: bool = False
    attempts: list = dataclasses.field(default_factory=list)

    @property
    def wall_s(self) -> float:
        return round(sum(a.wall_s for a in self.attempts), 4)


@dataclasses.dataclass
class RunReport:
    status: str = "pending"   # pending|completed|failed|aborted
    #                           |preempted (cooperative yield — the
    #                           run is NOT terminal; it resumes from
    #                           its cursor on the next dispatch)
    backend: str | None = None
    degraded: bool = False
    resumed_from: int | None = None
    journal_path: str | None = None
    input_digest: str | None = None
    breaker: dict | None = None   # CircuitBreaker.snapshot(), live
    steps: list = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def summary(self) -> str:
        lines = [f"run: {self.status}"
                 + (f" (degraded to {self.backend})" if self.degraded
                    else "")]
        for s in self.steps:
            lines.append(
                f"  [{s.index:02d}] {s.name:<28s} {s.status:<10s} "
                f"attempts={len(s.attempts)} backend={s.backend or '-'} "
                f"wall={s.wall_s:.3f}s")
        return "\n".join(lines)


class ResilientRunError(RuntimeError):
    """A step exhausted its retry budget (and any fallback).  Carries
    the :class:`RunReport` in ``.report``; the last device error is
    chained as ``__cause__``."""

    def __init__(self, msg: str, report: RunReport):
        super().__init__(msg)
        self.report = report


def _exec_step(in_path: str, name: str, backend: str, params: dict,
               out_path: str, chaos_spec: dict | None = None) -> dict:
    """Containment target for ``failsafe.run_isolated``: load → apply
    one transform → save.  Module-level because the payload pickles it
    by reference; data crosses the process boundary as checkpoint
    files, not pickles.  A forwarded chaos spec re-arms fault
    injection inside the child (how tier-1 exercises the kill/wedge
    containment paths for real).

    Returns the child's SPAN TREE (``trace.serialize_spans``) so the
    parent can graft it under its step span — without this handoff,
    isolated steps simply vanish from the run's trace."""
    trace.reset()  # a fresh child, but cheap insurance on reuse
    with trace.span(f"isolated:{name}", meta={"backend": backend}):
        with trace.span("load"):
            data = load_celldata(in_path)
        t = Transform(name, backend=backend, **params)
        with trace.span(name):
            if chaos_spec is not None:
                from .utils.chaos import ChaosMonkey

                with ChaosMonkey.from_spec(chaos_spec).activate():
                    out = t(data)
            else:
                out = t(data)
        # digest=False: a same-process transfer file, never resumed
        # from — hashing multi-GB payloads twice per attempt buys
        # nothing here
        with trace.span("save"):
            save_celldata(out, out_path, digest=False)
    return {"ok": True, "spans": trace.serialize_spans()}


def run_backend_signature(pipeline: Pipeline, backend: str | None,
                          fallback_backend: str | None = None) -> str:
    """The backend signature a run's shared circuit breaker is keyed
    by in ``failsafe.BreakerRegistry``: the run-level ``backend=``
    override when given, else the pipeline's ACCELERATOR backend —
    the first step backend that differs from ``fallback_backend``,
    because that is the backend whose failures feed the breaker (a
    mixed cpu+tpu pipeline must key "tpu", not whatever step 0 happens
    to be).  One string per BACKEND, not per run — that is what lets
    the first run to trip the tpu breaker short-circuit every other
    run."""
    if backend is not None:
        return backend
    steps = list(pipeline.steps)
    for t in steps:
        if fallback_backend is None or t.backend != fallback_backend:
            return t.backend
    return steps[0].backend if steps else _registry.DEFAULT_BACKEND


def _deadline_wrap(name, backend, fn):
    """Registry call-wrapper: check the current cooperative deadline
    token before AND after every transform invocation.  Installed for
    the whole run, so composite steps that dispatch nested ``apply``
    calls hit the check at every boundary; outside a
    ``deadline_scope`` the check is a no-op."""
    def checked(data, *args, **kw):
        check_deadline()
        out = fn(data, *args, **kw)
        check_deadline()
        return out

    return checked


class _Journal:
    """Append-only JSONL event log.  One ``open/write/close`` per
    record: a killed run keeps every line written before the kill,
    which is the whole point of a crash journal.  Writes serialize on
    an internal lock — the scheduler's workers share one journal and
    write terminal events from their own threads."""

    def __init__(self, path: str | None, bound: dict | None = None):
        self.path = path
        #: fields stamped onto EVERY record (the runner binds
        #: ``trace_id=`` here, so a run's whole journal joins the
        #: fleet trace without each write site repeating it)
        self.bound = dict(bound) if bound else {}
        self._lock = _threading.Lock()
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)

    def write(self, event: str, **fields) -> None:
        if not self.path:
            return
        rec = {"event": event, "ts": round(time.time(), 3),
               **self.bound, **fields}
        with self._lock:
            # the one sanctioned write-under-lock: THIS lock exists
            # solely to serialize this append (concurrent workers
            # share one journal file); it guards nothing else, so
            # nothing can starve behind it but another append
            with open(self.path, "a") as f:  # sctlint: disable=SCT011
                f.write(json.dumps(rec) + "\n")  # sctlint: disable=SCT011


class ResilientRunner:
    """Execute a :class:`Pipeline` step-by-step with retry/backoff,
    health-checked backend fallback, optional subprocess containment,
    checkpointed resume and a structured run journal (module docstring
    has the full contract).

    Parameters
    ----------
    pipeline : Pipeline
    checkpoint_dir : str | None
        Enables per-step checkpoints + resume; also the default home
        of ``journal.jsonl`` and the isolation handoff files.
    policy : RetryPolicy
    probe : callable | None
        Zero-arg health check returning ``{"ok": bool, ...}``;
        defaults to ``failsafe.probe_device``.  Injectable for tests.
    preflight : bool
        Probe before the first step; degrade immediately if unhealthy.
    fallback_backend : str | None
        Backend remaining steps degrade to when the accelerator is
        ruled unhealthy (``None`` disables fallback).
    isolate : collection of str
        Transform names to contain in a watched subprocess
        (known-wedging stages); the child's death is CLASSIFIED from
        its stderr tail (``failsafe.classify_child_result``) — a
        deterministic traceback fails fast, only device/timeout
        signatures retry.
    validate : callable | None
        ``validate(index, name, data)`` after each successful step;
        a raise is treated as that attempt's failure (a ``ValueError``
        therefore fails fast — how silent corruption gets caught).
    chaos : ChaosMonkey | None
        Fault-injection harness active for the whole run and
        forwarded into isolated children.
    step_deadline_s : float | None
        Wall-clock budget per step ATTEMPT (each retry gets a fresh
        token; a step's worst-case wall is budget × max_attempts plus
        backoff).  In-process steps carry a cooperative
        ``DeadlineToken`` checked at every registry call boundary;
        isolated steps inherit the remaining budget as their watchdog
        timeout.  Overrun → ``StepDeadlineExceeded`` (transient:
        journaled, retried, degradable).
    breaker : failsafe.CircuitBreaker | None
        Accelerator circuit breaker.  ``None`` (the default) resolves
        the run's backend signature in the PROCESS-SHARED
        ``failsafe.default_breaker_registry()`` at ``run()`` time —
        breaker state is per BACKEND, not per run, so two sequential
        (or concurrent) runs against the same backend share trip
        state: the first to trip it sends every other run straight to
        the degrade ruling without a fresh retry storm, and every
        breaker journal event names the registry ``signature`` that
        ruled.  Pass ``CircuitBreaker(...)`` explicitly for the old
        run-local isolation.  OPEN short-circuits accelerator
        attempts (checked BEFORE the first attempt of every step)
        straight to the degrade ruling; HALF_OPEN allows one
        EXCLUSIVE probe across all sharers — a successful probe (or
        probe-claimed accelerator attempt) closes the breaker and
        un-degrades the run.
    clock : vclock.Clock
        Time source for backoff, deadlines and the breaker window
        (default: the system clock).  Tests share one
        ``VirtualClock`` between runner, breaker and ChaosMonkey.
    sleep : callable
        Backoff sleeper (default ``clock.sleep``); tests inject a
        fake.
    fuse : bool
        Compile the pipeline into fused execution stages first
        (``plan.fused_pipeline``): maximal runs of consecutive
        jit-traceable device transforms execute as ONE cached
        compiled program and ONE retryable step.  Deadline tokens are
        checked at stage boundaries, chaos faults inside a fused
        stage still classify on their member op's name, a degrade
        ruling unfuses the stage onto the fallback backend, and
        checkpoints land at stage granularity (different step
        fingerprints than the unfused pipeline — a fuse toggle across
        a resume recomputes).  Names in ``isolate`` are never fused.
    mesh : jax.sharding.Mesh | None
        With ``fuse=True``, compile MESH-SHARDED stages over this
        device mesh (``plan.fused_pipeline(mesh=)``).  A sharded
        stage is one retryable step whose degrade ruling is RE-PLAN
        ON FEWER DEVICES: when a stage exhausts its retry budget the
        runner shrinks the mesh (halving the device count), then
        drops to the single-device fused form, and only then rules on
        the backend fallback — two extra rungs in the retry →
        breaker → degrade ladder that keep the run on the
        accelerator.  Each shrink is journaled as a ``degrade`` event
        with ``reason="mesh_shrink"`` and refreshes the step
        fingerprints from the re-planned steps (they embed the mesh
        signature, so checkpoints written before and after the shrink
        never mix and a resume across the mesh change recomputes).
    metrics : telemetry.MetricsRegistry | None
        Where recovery counters (retries, degrades, breaker
        transitions, quarantines, checkpoint bytes, …) and the
        auto-instrumented per-op call metrics are recorded; defaults
        to the process-wide ``telemetry.default_registry()``.  With
        ``checkpoint_dir=`` the snapshot is written to
        ``metrics.json`` (and the run's spans to ``trace.json``) at
        run end — the inputs ``tools/sctreport.py`` merges with the
        journal.
    """

    def __init__(self, pipeline: Pipeline, *,
                 checkpoint_dir: str | None = None,
                 journal_path: str | None = None,
                 policy: RetryPolicy | None = None,
                 probe=None, preflight: bool = False,
                 probe_timeout_s: float = 90.0,
                 fallback_backend: str | None = DEFAULT_FALLBACK_BACKEND,
                 isolate=(), isolate_timeout_s: float = 600.0,
                 isolate_stall_s: float = 240.0,
                 validate=None, chaos=None,
                 step_deadline_s: float | None = None,
                 breaker: CircuitBreaker | None = None,
                 clock=None, sleep=None, metrics=None,
                 fuse: bool = False, mesh=None,
                 trace_id: str | None = None):
        if mesh is not None and not fuse:
            raise ValueError(
                "ResilientRunner(mesh=...) shards fused execution "
                "stages — pass fuse=True as well (an eager "
                "step-by-step run ignores the mesh, silently "
                "dropping the parallelism you asked for)")
        if fuse:
            # compile the pipeline into fused execution stages
            # (plan.fused_pipeline): each fused stage is ONE retryable
            # step — retried/deadlined/checkpointed as a unit, with
            # chaos faults inside it still firing (and classifying) on
            # member-op names.  Isolated steps are fusion breaks: a
            # contained subprocess must dispatch exactly one named op.
            # The runner path never donates stage inputs — a retried
            # attempt must be able to replay them.
            from .plan import fused_pipeline

            pipeline = fused_pipeline(pipeline, no_fuse=isolate,
                                      donate=False, metrics=metrics,
                                      mesh=mesh)
        self.pipeline = pipeline
        self.checkpoint_dir = checkpoint_dir
        if checkpoint_dir:
            os.makedirs(checkpoint_dir, exist_ok=True)
            if journal_path is None:
                journal_path = os.path.join(checkpoint_dir,
                                            "journal.jsonl")
        self.policy = policy or RetryPolicy()
        self.probe = probe if probe is not None else (
            lambda: probe_device(timeout_s=probe_timeout_s))
        self.preflight = preflight
        self.fallback_backend = fallback_backend
        self.isolate = frozenset(isolate)
        self.isolate_timeout_s = isolate_timeout_s
        self.isolate_stall_s = isolate_stall_s
        self.validate = validate
        self.chaos = chaos
        self.step_deadline_s = step_deadline_s
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        # None → resolved per-run from the process-shared
        # BreakerRegistry (keyed by the run's backend signature);
        # an explicit CircuitBreaker keeps the old run-local state
        self.breaker = breaker
        self._breaker_explicit = breaker is not None
        self.sleep = sleep if sleep is not None else self.clock.sleep
        self.metrics = metrics if metrics is not None \
            else telemetry.default_registry()
        # one instrumentor per runner: its backend_override scopes a
        # degrade ruling's "degraded" label to THIS run, even when the
        # metrics registry is the process-shared default
        self._inst = telemetry.CallInstrumentor(self.metrics)
        # the admission-stamped causal id: bound onto every journal
        # record of this run and into every attempt span's meta, the
        # end-to-end join key of the fleet observability plane
        self.trace_id = trace_id
        self.journal = _Journal(
            journal_path,
            bound={"trace_id": trace_id} if trace_id else None)
        self.report = RunReport(journal_path=journal_path)
        self._input_digest: str | None = None
        self._mem_input_bytes: int = 1
        self._breaker_degraded = False
        self._spans: list = []  # this run's attempt spans, for export

    # ------------------------------------------------------------------
    def run(self, data, backend: str | None = None, resume: bool = True):
        import random

        steps = list(self.pipeline.steps)
        rng = random.Random(self.policy.seed)
        dig = self._input_digest = data_digest(data)
        # the memory model's input-size term, measured ONCE at run
        # start: every step's estimate key uses it, matching what the
        # scheduler's admission estimate computed for the same data —
        # an OOM correction recorded here is the one admission reads
        self._mem_input_bytes = max(_memory.data_nbytes(data), 1)
        self._breaker_degraded = False
        self._spans = []
        self._inst.backend_override = None
        if not self._breaker_explicit:
            # per-BACKEND shared breaker: resolved lazily because the
            # signature depends on the run's backend override.  The
            # clock kwarg applies only if THIS run creates the
            # breaker — later sharers inherit the first creator's.
            self.breaker = default_breaker_registry().get(
                run_backend_signature(self.pipeline, backend,
                                      self.fallback_backend),
                clock=self.clock)
        report = self.report = RunReport(
            status="pending", backend=backend,
            journal_path=self.journal.path, input_digest=dig,
            breaker=self.breaker.snapshot(),
            steps=[StepReport(i, t.name,
                              step_fingerprint(steps, i,
                                               input_digest=dig),
                              isolated=t.name in self.isolate)
                   for i, t in enumerate(steps)])
        self.journal.write(
            "run_start", n_steps=len(steps), backend=backend,
            resume=bool(resume and self.checkpoint_dir),
            input_digest=dig,
            steps=[{"index": s.index, "name": s.name,
                    "fingerprint": s.fingerprint}
                   for s in report.steps])
        if dig is None:
            # data_digest already warned; the journal must say so too —
            # resume cannot prove the checkpoints belong to THIS input
            self.journal.write("resume_unverified_input")

        degraded = False
        if self.preflight:
            degraded = self._rule_unhealthy(where="preflight")
        start = 0
        if resume and self.checkpoint_dir:
            # host-side load only — device placement happens per-step
            # inside the attempt try-block (_match_residency), where a
            # dead device is classified and degraded like any other
            # failure.  Every candidate is digest-verified first; a
            # corrupt, mismatched or unreadable file is QUARANTINED
            # (moved aside, never deleted, reason journaled) and the
            # resume falls back to the next-newest intact one; only
            # when none survive does the run restart from scratch.
            i = latest_step(self.checkpoint_dir, steps,
                            input_digest=dig)
            while i is not None:
                path = self._ckpt_path(steps, i)
                try:
                    # verify + load from ONE read of the file
                    data_ck = load_celldata(
                        path, verify=True,
                        expect_fingerprint=report.steps[i].fingerprint)
                except CheckpointCorruptError as e:
                    self._quarantine(i, path, e.reason)
                    i = latest_step(self.checkpoint_dir, steps,
                                    upto=i - 1, input_digest=dig)
                    continue
                except Exception as e:  # noqa: BLE001 — verified yet
                    # not reconstructable (malformed payload keys):
                    # same ruling as corrupt — quarantine, fall back
                    self._quarantine(
                        i, path,
                        f"unreadable ({type(e).__name__}: {e})")
                    i = latest_step(self.checkpoint_dir, steps,
                                    upto=i - 1, input_digest=dig)
                    continue
                data = data_ck
                start = i + 1
                report.resumed_from = i
                for s in report.steps[: i + 1]:
                    s.status = "resumed"
                # the passed `data` argument is superseded by the
                # checkpoint from here on — safe (the input digest is
                # part of the fingerprint) but worth a journal line
                self.journal.write(
                    "resume", from_step=i,
                    fingerprint=report.steps[i].fingerprint,
                    input_digest=dig,
                    note="checkpoint supersedes the passed data "
                         "argument")
                self.metrics.counter("runner.resumes").inc()
                break

        chaos_ctx = (self.chaos.activate() if self.chaos is not None
                     else contextlib.nullcontext())
        # wrapper order (innermost → outermost): chaos, then the
        # deadline check (a chaos wedge that burns the clock is caught
        # by the token check on the way out of the op), then telemetry
        # outermost — so an op's recorded duration includes the wedge
        # and its raise is counted as that op's error
        # deadline + telemetry wrappers install THREAD-LOCAL: under
        # the scheduler's worker pool, concurrent runs must not wrap
        # (or double-count) each other's op calls.  Chaos stays
        # global — injected faults fire on every thread by design.
        try:
            with chaos_ctx, \
                    _registry.call_wrapper(_deadline_wrap,
                                           thread_local=True), \
                    _registry.call_wrapper(self._inst.wrap,
                                           thread_local=True):
                for i in range(start, len(steps)):
                    data, degraded = self._run_step(
                        steps, i, data, backend, degraded, rng)
        except BaseException:
            # a FAILED run still gets metrics.json/trace.json — the
            # post-mortem needs them most — but WITHOUT journal
            # records: run_failed has already been written and must
            # stay the file's final line (the journal's last line is
            # the run verdict, for every outcome).  An ABORTED run
            # (fatal, process-death class) gets neither: real death
            # writes nothing either.
            if self.report.status == "failed":
                self._write_run_artifacts(journal_events=False)
            raise
        finally:
            self._inst.backend_override = None

        if start == len(steps) and steps:
            # fully-resumed: no step ran to re-place the loaded data —
            # return the residency a fresh run would (matches
            # PipelineCheckpointer's device_put-on-resume; unlike the
            # per-step adapter this places DENSE host X too, since the
            # contract here is output parity, not op-input minimum).
            # Best effort: a dead device must not fail a run whose
            # every step is already done — hand back host data instead.
            try:
                b = self._target_backend(steps[-1], backend, degraded)
                if b != "cpu" and hasattr(data, "device_put"):
                    data = data.device_put()
            except Exception as e:  # noqa: BLE001
                warnings.warn(
                    "ResilientRunner: device placement of the fully-"
                    f"resumed result failed ({type(e).__name__}: {e})"
                    " — returning host-resident data.",
                    RuntimeWarning, stacklevel=2)
                self.journal.write(
                    "resume_place_failed",
                    error=f"{type(e).__name__}: {e}")
        report.status = "completed"
        report.degraded = degraded
        report.breaker = self.breaker.snapshot()
        if degraded:
            report.backend = self.fallback_backend
        # artifacts BEFORE the run_completed record: the journal's
        # final line stays the run verdict (tests and tail -1 rely on
        # it), and the snapshot already holds every counter
        self._write_run_artifacts()
        self.journal.write("run_completed", degraded=degraded,
                           breaker=report.breaker)
        return data

    def _write_run_artifacts(self, journal_events: bool = True) -> None:
        """End-of-run telemetry: the metrics snapshot as
        ``metrics.json`` and this run's span trees as a
        Perfetto-loadable ``trace.json``, both next to the journal —
        the three files ``tools/sctreport.py`` merges.  Best effort:
        a full disk must not turn a completed run into a failure.
        ``journal_events=False`` (the failed-run path) writes the
        files but no journal records, so the terminal verdict stays
        the journal's final line."""
        if not self.checkpoint_dir:
            return
        mpath = os.path.join(self.checkpoint_dir, "metrics.json")
        try:
            self.metrics.write(mpath)
            if journal_events:
                self.journal.write("metrics_written", path=mpath)
        except OSError as e:
            warnings.warn(
                f"ResilientRunner: could not write {mpath} "
                f"({type(e).__name__}: {e})", RuntimeWarning,
                stacklevel=3)
        tpath = os.path.join(self.checkpoint_dir, "trace.json")
        try:
            # append: a crash → resume sequence shares the journal
            # file, so it must share the trace too — the old spans'
            # ids keep resolving
            trace.export_trace(tpath, self._spans, append=True)
            if journal_events:
                self.journal.write("trace_exported", path=tpath,
                                   n_spans=len(self._spans))
        except OSError as e:
            warnings.warn(
                f"ResilientRunner: could not write {tpath} "
                f"({type(e).__name__}: {e})", RuntimeWarning,
                stacklevel=3)

    # ------------------------------------------------------------------
    def _target_backend(self, t: Transform, backend: str | None,
                        degraded: bool) -> str:
        b = backend if backend is not None else t.backend
        if degraded and self.fallback_backend:
            b = self.fallback_backend
        return b

    def _ckpt_path(self, steps, i: int) -> str:
        return os.path.join(
            self.checkpoint_dir,
            step_filename(steps, i, input_digest=self._input_digest))

    def _quarantine(self, i: int, path: str, reason: str) -> None:
        """Move a failed-verification checkpoint aside (never delete),
        warn loudly, and journal the ruling."""
        qpath = quarantine_checkpoint(path, reason)
        warnings.warn(
            f"ResilientRunner: checkpoint for step {i} failed "
            f"verification ({reason}) — QUARANTINED to {qpath}; "
            "falling back to the previous checkpoint",
            RuntimeWarning, stacklevel=3)
        self.journal.write("quarantine", step=i, reason=reason,
                           path=qpath)
        self.metrics.counter("runner.quarantines").inc()

    def _rule_unhealthy(self, where: str) -> bool:
        """Probe the device; on an unhealthy verdict warn LOUDLY and
        rule the run degraded.  Returns the new degraded flag."""
        rec = self.probe()
        self.journal.write("health_check", where=where, result=rec)
        if rec.get("ok"):
            return False
        if not self.fallback_backend:
            # the caller asked for the check — an unhealthy verdict
            # must not pass silently just because degrading is off
            warnings.warn(
                "ResilientRunner: accelerator ruled UNHEALTHY "
                f"({rec.get('reason', 'probe failed')!r} at {where}) "
                "and no fallback_backend is configured — continuing "
                "on the unhealthy device.", RuntimeWarning,
                stacklevel=3)
            return False
        warnings.warn(
            "ResilientRunner: accelerator ruled UNHEALTHY "
            f"({rec.get('reason', 'probe failed')!r} at {where}) — "
            f"DEGRADING remaining steps to backend="
            f"{self.fallback_backend!r}.  Results stay correct but "
            "slow; fix the device and re-run to get it back.",
            RuntimeWarning, stacklevel=3)
        self.journal.write("fallback", where=where,
                           backend=self.fallback_backend)
        self.metrics.counter("runner.degrades", reason="probe").inc()
        self._inst.backend_override = "degraded"
        # recorded immediately, not at run end: the report attached to
        # a later failure must already say what the run degraded to
        self.report.degraded = True
        self.report.backend = self.fallback_backend
        return True

    def _run_step(self, steps, i: int, data, backend, degraded: bool,
                  rng):
        policy = self.policy
        t = steps[i]
        sr = self.report.steps[i]
        attempt = 0        # monotonic across a fallback — the journal
        budget_used = 0    # join key must never repeat within a step
        probing = False    # this attempt holds the half-open probe slot
        replanned = False  # last iteration re-planned on fewer devices
        try:
            while True:
                # the SHARED breaker closed while this run was degraded
                # (another sharer's probe succeeded): rejoin the
                # accelerator — the pool-wide un-degrade contract.  With
                # a run-local breaker this state is unreachable (only the
                # run itself can close it), so the legacy path below is
                # unchanged.
                if (degraded and self._breaker_degraded
                        and self.breaker.state == CircuitBreaker.CLOSED):
                    degraded = False
                    self._note_breaker_close(i, backend, observed=True)
                    budget_used = 0
                # breaker half-open (cooldown elapsed): ONE probe decides —
                # success closes the breaker and un-degrades the run,
                # failure re-opens it for another cooldown.  The probe
                # slot is EXCLUSIVE (try_acquire_probe): with the breaker
                # shared per backend, contending runs must not probe-storm
                # a recovering device — losers stay degraded until the
                # winner's verdict lands.
                if (degraded and self._breaker_degraded
                        and self.breaker.state == CircuitBreaker.HALF_OPEN
                        and self.breaker.try_acquire_probe()):
                    probe_resolved = False
                    try:
                        rec = self.probe()
                        self.journal.write("health_check",
                                           where=f"step {i} half-open",
                                           result=rec)
                        if rec.get("ok"):
                            self.breaker.record_success()
                            probe_resolved = True
                            degraded = False
                            self._note_breaker_close(i, backend)
                        else:
                            self.breaker.record_failure()  # → open again
                            probe_resolved = True
                            self.report.breaker = self.breaker.snapshot()
                            self.journal.write(
                                "breaker_reopen", step=i,
                                reason=rec.get("reason"),
                                signature=self.breaker.signature)
                            self.metrics.counter(
                                "runner.breaker_transitions",
                                to="reopen").inc()
                    finally:
                        # a probe (or journal write) that RAISED before
                        # a verdict must not leave the shared breaker's
                        # exclusive probe slot claimed forever — that
                        # would wedge every sharer on the fallback
                        # until process restart.  Conditional on
                        # purpose: after a verdict the slot may already
                        # belong to ANOTHER run, and an unconditional
                        # release would wipe that claim.
                        if not probe_resolved:
                            self.breaker.release_probe()
                # pre-attempt gate on the SHARED breaker: a breaker opened
                # by another run (or another step) rules this run degraded
                # BEFORE it burns a single accelerator attempt — that is
                # the whole point of per-backend breaker state.  While
                # HALF_OPEN, one run's attempt IS the probe (exclusive
                # claim); everyone else keeps treating the breaker as
                # open until the verdict lands.  A step that just
                # RE-PLANNED on fewer devices bypasses the gate once: the
                # mesh-shrink rung is that iteration's degrade ruling and
                # the shrunk stage must actually be attempted (the breaker
                # is usually still open at that moment — it is what
                # triggered the shrink).
                if replanned:
                    replanned = False
                elif not degraded and not probing:
                    b_next = self._target_backend(t, backend, degraded)
                    on_accel_next = (self.fallback_backend is not None
                                     and b_next != self.fallback_backend)
                    if on_accel_next:
                        # state read + probe acquire under ONE lock
                        # hold: another sharer's record_success
                        # between the two would otherwise rule this
                        # run degraded off a stale HALF_OPEN read and
                        # journal a fallback whose breaker snapshot
                        # contradicts it
                        with self.breaker.lock:
                            st = self.breaker.state
                            if st == CircuitBreaker.HALF_OPEN:
                                probing = \
                                    self.breaker.try_acquire_probe()
                        if st == CircuitBreaker.OPEN or (
                                st == CircuitBreaker.HALF_OPEN
                                and not probing):
                            degraded = self._degrade_breaker_open(
                                i, short_circuit=True)
                            budget_used = 0
                            continue
                attempt += 1
                budget_used += 1
                b = self._target_backend(t, backend, degraded)
                sr.backend = b
                tok = (DeadlineToken(self.step_deadline_s, clock=self.clock,
                                     label=f"step {i} ({t.name})")
                       if self.step_deadline_s is not None else None)
                err = None
                meta = {"step": i, "attempt": attempt, "backend": b}
                if self.trace_id:
                    meta["trace_id"] = self.trace_id
                with trace.span(f"runner:{t.name}", meta=meta) as sp:
                    try:
                        scope = (deadline_scope(tok) if tok is not None
                                 else contextlib.nullcontext())
                        with scope:
                            out = self._execute(t, data, b, i, steps)
                            if tok is not None:
                                tok.check()  # isolated steps bypass the
                                # registry wrapper in THIS process
                        if self.validate is not None:
                            self.validate(i, t.name, out)
                        if self.checkpoint_dir:
                            # inside the classified block on purpose: the
                            # save fetches device results to host, and a
                            # device that died between compute and save
                            # must be retried/degraded like any other
                            # step failure — not leak a raw raise
                            save_celldata(out, self._ckpt_path(steps, i),
                                          fingerprint=sr.fingerprint)
                            if self.chaos is not None:
                                # silent on-disk corruption, injected after
                                # a good save — only the next resume's
                                # digest verify can catch it
                                self.chaos.on_checkpoint(
                                    t.name, self._ckpt_path(steps, i), b)
                    except BaseException as e:  # noqa: BLE001 — reported,
                        err = e                 # classified, re-raised below
                self._spans.append(sp)
                if isinstance(err, JobPreempted):
                    # cooperative checkpoint-then-yield, NOT a failure:
                    # the step saved its cursor before raising, so the
                    # ruling is neither retry nor degrade — journal the
                    # yield and hand the raise to the caller (the
                    # scheduler requeues the ticket with its cursor;
                    # reason="cancelled" terminals it as shed).  No
                    # terminal run event: like a real preemption, the
                    # journal's next line is the resumed run's
                    # run_start.
                    sr.status = "pending"
                    self.report.status = "preempted"
                    self.journal.write("preempted", step=i,
                                       name=t.name, reason=err.reason,
                                       cursor=err.cursor)
                    raise err
                status = "ok" if err is None else "error"
                self.metrics.counter("runner.attempts", status=status,
                                     backend=b).inc()
                self.metrics.histogram("runner.step_wall_s",
                                       status=status).observe(sp.duration)
                if err is None:
                    if probing:
                        # the probe-claimed accelerator attempt succeeded —
                        # the device is back: close the SHARED breaker so
                        # the whole pool returns to the accelerator
                        self.breaker.record_success()
                        self._note_breaker_close(i, backend,
                                                 undegrade=False)
                        probing = False
                    sr.attempts.append(StepAttempt(
                        attempt, b, "ok", round(sp.duration, 4), sp.id))
                    sr.status = "completed"
                    self.journal.write(
                        "attempt", step=i, name=t.name, attempt=attempt,
                        backend=b, status="ok",
                        wall_s=round(sp.duration, 4), span_id=sp.id)
                    if self.checkpoint_dir:
                        self.journal.write("checkpoint", step=i,
                                           fingerprint=sr.fingerprint)
                        self.metrics.counter("runner.checkpoint_writes") \
                            .inc()
                        try:
                            self.metrics.counter("runner.checkpoint_bytes") \
                                .inc(os.path.getsize(
                                    self._ckpt_path(steps, i)))
                        except OSError:
                            pass  # stat raced a cleanup; the write event
                            # above already proves the save happened
                    return out, degraded

                cls = classify_error(err)
                sr.attempts.append(StepAttempt(
                    attempt, b, "error", round(sp.duration, 4), sp.id,
                    error=f"{type(err).__name__}: {err}", classified=cls))
                self.journal.write(
                    "attempt", step=i, name=t.name, attempt=attempt,
                    backend=b, status="error", classified=cls,
                    error=f"{type(err).__name__}: {err}",
                    wall_s=round(sp.duration, 4), span_id=sp.id)
                if isinstance(err, StepDeadlineExceeded):
                    # its own journal event: the acceptance contract is
                    # that a wedged step leaves a "deadline" record before
                    # any breaker/fallback ruling it feeds into
                    self.journal.write(
                        "deadline", step=i, name=t.name, attempt=attempt,
                        budget_s=self.step_deadline_s)
                    self.metrics.counter("runner.deadline_overruns").inc()
                if cls == RESOURCE:
                    # device memory exhausted: neither retry (the
                    # live set recurs at the same shapes) nor breaker
                    # (the device is healthy, just full) — the OOM
                    # CONTAINMENT LADDER rules: unfuse the stage
                    # (smaller live set) → re-plan at a smaller
                    # batch/tile (registered mem_shrink) → cpu
                    # fallback; recurrence at the bottom rung is
                    # ruled deterministic.  Every rung inflates the
                    # stored peak estimate first (the self-correcting
                    # model admission reads).
                    if probing:
                        # an OOM says nothing about the outage the
                        # half-open probe was judging: release the
                        # exclusive slot without a verdict so another
                        # sharer can probe
                        self.breaker.release_probe()
                        probing = False
                    rung, new_t = self._rule_oom(steps, i, t, b,
                                                 degraded)
                    if rung in ("unfuse", "replan"):
                        t = new_t
                        budget_used = 0
                        replanned = True  # the re-planned form must
                        # actually be attempted — bypass the breaker
                        # gate once, like a mesh shrink
                        continue
                    if rung == "cpu":
                        degraded = True
                        budget_used = 0
                        continue
                    # bottom rung: OOM on the fallback backend (or no
                    # fallback configured) — recurs identically, fail
                    # fast with the real error
                    sr.status = "failed"
                    self.report.status = "failed"
                    self.journal.write("run_failed", step=i,
                                       classified=cls)
                    raise err
                # FATAL / DETERMINISTIC while holding the probe slot:
                # no device verdict — the slot is released by the
                # enclosing finally (the ONE release point; releasing
                # here too could, after another run re-claimed the
                # freed slot, wipe THAT claim and let two probes run)
                if cls == FATAL:
                    sr.status = "aborted"
                    self.report.status = "aborted"
                    self.journal.write("run_aborted", step=i,
                                       error=type(err).__name__)
                    raise err
                if cls == DETERMINISTIC:
                    # retrying replays the same raise — fail fast, and
                    # hand the caller the REAL exception, not a wrapper
                    sr.status = "failed"
                    self.report.status = "failed"
                    self.journal.write("run_failed", step=i,
                                       classified=cls)
                    raise err
                # transient: feed the breaker (accelerator attempts only —
                # there is nothing to trip when already on the fallback).
                # prev→now read-modify under breaker.lock: with the
                # breaker shared across runs, two concurrent failures must
                # produce exactly ONE breaker_open journal event, on the
                # run whose failure actually tripped it.
                on_accel = (self.fallback_backend is not None
                            and b != self.fallback_backend)
                if on_accel:
                    # probe=probing: only the half-open probe HOLDER's
                    # failure re-opens the breaker (and resolves the
                    # slot); a non-holder's failure — an attempt that
                    # started before the cooldown elapsed — counts
                    # into the window without wiping another run's
                    # in-flight probe claim
                    with self.breaker.lock:
                        prev = self.breaker.state
                        now_state = self.breaker.record_failure(
                            probe=probing)
                    probing = False  # record_failure resolved the probe
                    self.report.breaker = self.breaker.snapshot()
                    if (now_state == CircuitBreaker.OPEN
                            and prev != CircuitBreaker.OPEN):
                        to = ("reopen" if prev == CircuitBreaker.HALF_OPEN
                              else "open")
                        if to == "reopen":
                            # a probe-claimed attempt lied: half_open → open
                            self.journal.write(
                                "breaker_reopen", step=i,
                                signature=self.breaker.signature)
                        else:
                            self.journal.write("breaker_open", step=i,
                                               **self.breaker.snapshot())
                        self.metrics.counter("runner.breaker_transitions",
                                             to=to).inc()
                if on_accel and not degraded and not self.breaker.allow():
                    # breaker OPEN: skip the remaining retries AND the
                    # probe — straight to the degrade ruling.  For a
                    # mesh-sharded stage the ruling is RE-PLAN ON FEWER
                    # DEVICES first (shrink, then single-device); only
                    # when those rungs are spent does the run leave the
                    # accelerator for the fallback backend.
                    shrunk = self._replan_fewer_devices(steps, i, t)
                    if shrunk is not None:
                        t = shrunk
                        budget_used = 0
                        replanned = True
                        continue
                    degraded = self._degrade_breaker_open(i)
                    budget_used = 0  # fresh budget on the fallback
                    continue
                # retry with backoff until the budget is spent, then let
                # the health probe rule on a backend fallback
                if budget_used < policy.max_attempts:
                    d = policy.delay_s(budget_used, rng)
                    self.journal.write("backoff", step=i, attempt=attempt,
                                       delay_s=round(d, 4))
                    self.metrics.counter("runner.retries").inc()
                    self.sleep(d)
                    continue
                if not degraded:
                    # mesh-sharded stage out of budget: before ruling the
                    # whole backend unhealthy, RE-PLAN ON FEWER DEVICES —
                    # shrink the mesh (half the devices), then the
                    # single-device fused form; only when those rungs are
                    # spent does the run fall through to the cpu fallback
                    shrunk = self._replan_fewer_devices(steps, i, t)
                    if shrunk is not None:
                        t = shrunk
                        budget_used = 0  # fresh budget on the smaller mesh
                        replanned = True
                        continue
                if (not degraded and self.fallback_backend
                        and b != self.fallback_backend):
                    if self._rule_unhealthy(where=f"step {i}"):
                        degraded = True  # report fields set by the ruling
                        budget_used = 0  # fresh budget on the healthy backend
                        continue
                sr.status = "failed"
                self.report.status = "failed"
                self.journal.write("run_failed", step=i, classified=cls)
                raise ResilientRunError(
                    f"step {i} ({t.name!r}) failed {attempt} times on "
                    f"backend {b!r}; last error: "
                    f"{type(err).__name__}: {err}", self.report) from err
        finally:
            # resolve-or-release invariant for the SHARED breaker's
            # exclusive half-open probe slot: every verdict path
            # (record_success / record_failure / explicit release)
            # clears `probing`, so this fires only when an exception
            # escaped BETWEEN claim and verdict (journal write,
            # metrics, validate ...).  A leaked claim would wedge
            # every sharer on the fallback until process restart.
            if probing:
                self.breaker.release_probe()

    def _note_breaker_close(self, i: int, backend,
                            undegrade: bool = True,
                            observed: bool = False) -> None:
        """Bookkeeping for a breaker CLOSE this run ruled or observed
        (the symmetric twin of ``_degrade_breaker_open`` — one place
        for the journal/report/metrics close sequence).
        ``observed=True`` means another sharer's probe closed it: the
        close is journaled for THIS run's story but the transition
        counter is not incremented (the closer already counted it).
        ``undegrade=False`` is the probe-claimed-attempt path, where
        the run was never degraded to begin with."""
        self.report.breaker = self.breaker.snapshot()
        self.journal.write("breaker_close", step=i, observed=observed,
                           signature=self.breaker.signature)
        if not observed:
            self.metrics.counter("runner.breaker_transitions",
                                 to="close").inc()
        if undegrade:
            self._breaker_degraded = False
            self.report.degraded = False
            self.report.backend = backend
            self._inst.backend_override = None

    def _degrade_breaker_open(self, i: int,
                              short_circuit: bool = False) -> bool:
        """The breaker-open degrade ruling: warn loudly, journal the
        fallback (naming the registry breaker that ruled), flip the
        run onto the fallback backend.  ``short_circuit=True`` marks
        the pre-attempt path — a breaker opened by ANOTHER run ruled
        this one degraded before it burned a single accelerator
        attempt.  Returns the new degraded flag (always True)."""
        warnings.warn(
            "ResilientRunner: circuit breaker OPEN "
            f"({self.breaker.failure_threshold} transient "
            f"failures within {self.breaker.window_s:g}s"
            + (f" on backend {self.breaker.signature!r}"
               if self.breaker.signature else "")
            + ") — DEGRADING remaining steps to backend="
            f"{self.fallback_backend!r} without probing.  A "
            "successful probe after the cooldown closes the "
            "breaker and returns to the accelerator.",
            RuntimeWarning, stacklevel=3)
        self.journal.write("fallback", where=f"step {i}",
                           backend=self.fallback_backend,
                           reason="breaker_open",
                           signature=self.breaker.signature,
                           short_circuit=short_circuit)
        self.metrics.counter("runner.degrades",
                             reason="breaker_open").inc()
        self._inst.backend_override = "degraded"
        self.report.degraded = True
        self.report.backend = self.fallback_backend
        self.report.breaker = self.breaker.snapshot()
        self._breaker_degraded = True
        return True

    def _rule_oom(self, steps, i: int, t, b: str, degraded: bool):
        """One RESOURCE-classified failure's containment ruling.

        Always inflates the step's stored peak estimate first
        (``memory.MemoryEstimates.inflate`` ×2 — the self-correcting
        model: the next admission of this chain at this input bucket
        believes the device, not the old estimate), then picks the
        rung:

        * ``unfuse`` — a fused stage with >1 member becomes the
          step-by-step chain on the SAME backend (member
          intermediates free between dispatches instead of sharing
          one program's live set);
        * ``replan`` — re-plan at a smaller live set via registered
          ``mem_shrink`` metadata (halve a batch/tile param);
          fingerprints ``i..`` refresh (the params changed, so
          checkpoints from the larger plan never mix);
        * ``cpu`` — remaining steps degrade to the fallback backend
          (host memory is a different, bigger pool);
        * ``fail`` — already on the fallback (or no fallback):
          recurrence at the bottom rung replays identically, the
          caller fails fast.

        Journals ``degrade reason=oom rung=<rung>`` with the
        before/after estimates; returns ``(rung, new_step | None)``.
        """
        input_bytes = self._mem_input_bytes
        est = _memory.default_estimates()
        before = _memory.step_estimate(t, input_bytes)["bytes"]
        corrected = est.inflate(_memory.step_sig(t, input_bytes),
                                before)
        self.metrics.counter("mem.estimate_corrections").inc()
        # unfuse/replan are SAME-BACKEND rungs — available whenever
        # the step is not already on the fallback, even with
        # fallback_backend=None (forbidding the cpu degrade must not
        # degenerate the whole ladder to fail-fast); only the cpu
        # rung needs a configured fallback
        on_fallback = (self.fallback_backend is not None
                       and b == self.fallback_backend)
        rung, new_t = "fail", None
        if not degraded and not on_fallback:
            unfuse = getattr(t, "unfuse", None)
            members = getattr(t, "members", None)
            # a MESH-SHARDED stage never unfuses: the unfused chain
            # runs single-device, CONCENTRATING the whole sharded
            # input onto one device — a guaranteed re-OOM, the
            # opposite of a smaller live set.  Sharded stages go
            # straight to the replan rung (mesh-preserving) and the
            # backend fallback.
            if unfuse is not None and members is not None \
                    and len(members) > 1 \
                    and getattr(t, "mesh", None) is None:
                rung, new_t = "unfuse", unfuse()
            else:
                new_t = self._shrink_step(t)
                if new_t is not None:
                    rung = "replan"
                elif self.fallback_backend is not None:
                    rung = "cpu"
        self.metrics.counter("mem.oom_events", rung=rung).inc()
        if rung == "fail":
            warnings.warn(
                f"ResilientRunner: step {i} ({t.name!r}) exhausted "
                f"device memory on the BOTTOM ladder rung (backend "
                f"{b!r}) — no rung left, failing fast (estimate "
                f"corrected to {corrected} bytes).",
                RuntimeWarning, stacklevel=3)
            return rung, None
        if new_t is not None:
            steps[i] = new_t
            for j in range(i, len(steps)):
                # a shrink changes step i's params — every downstream
                # fingerprint embeds them (unfuse keeps params: the
                # recompute is then a no-op)
                self.report.steps[j].fingerprint = step_fingerprint(
                    steps, j, input_digest=self._input_digest)
        after = (_memory.step_estimate(new_t, input_bytes)["bytes"]
                 if new_t is not None else corrected)
        warnings.warn(
            f"ResilientRunner: step {i} ({t.name!r}) exhausted device "
            f"memory — OOM ladder rung {rung!r} (estimate {before} "
            f"-> {after} bytes, stored estimate corrected to "
            f"{corrected}).",
            RuntimeWarning, stacklevel=3)
        self.journal.write(
            "degrade", step=i, reason="oom", rung=rung,
            from_bytes=int(before), to_bytes=int(after),
            corrected_bytes=int(corrected),
            fingerprint=self.report.steps[i].fingerprint)
        self.metrics.counter("runner.degrades", reason="oom").inc()
        if rung == "cpu":
            # the backend-fallback bookkeeping the probe/breaker
            # degrades share — minus the breaker (an OOM is not an
            # outage; a sharer's probe must not un-degrade this run
            # back into the same full device mid-run, so
            # _breaker_degraded stays False)
            self._inst.backend_override = "degraded"
            self.report.degraded = True
            self.report.backend = self.fallback_backend
        return rung, new_t

    @staticmethod
    def _shrink_step(t):
        """The OOM ladder's middle rung: the same step re-planned at
        a smaller live set via registered ``mem_shrink`` metadata
        (``registry.mem_shrink_of`` — halve a batch/tile/block
        param).  For a chain, every member that declares a shrink
        shrinks; returns ``None`` when nothing can (no metadata, or
        every member at its floor)."""
        from .plan import FusedTransform, ShardedCollective, \
            _UnfusedChain

        members = getattr(t, "members", None)
        if members is None:
            p2 = _registry.mem_shrink_of(t.name, t.backend, t.params)
            if p2 is None:
                return None
            return Transform(t.name, backend=t.backend, **p2)
        shrunk, any_shrunk = [], False
        for m in members:
            p2 = _registry.mem_shrink_of(m.name, m.backend, m.params)
            if p2 is None:
                shrunk.append(m)
            else:
                any_shrunk = True
                shrunk.append(Transform(m.name, backend=m.backend,
                                        **p2))
        if not any_shrunk:
            return None
        if isinstance(t, ShardedCollective):
            return ShardedCollective(shrunk[0], t.mesh,
                                     metrics=t.metrics)
        if isinstance(t, FusedTransform):
            return FusedTransform(shrunk, t.backend, metrics=t.metrics,
                                  donate=False, mesh=t.mesh)
        if isinstance(t, _UnfusedChain):
            # rebuilt params so checkpoint fingerprints track the
            # shrunk member chain
            return _UnfusedChain(
                shrunk, t.backend, t.name,
                {"ops": [(m.name, dict(m.params)) for m in shrunk]})
        return None

    def _replan_fewer_devices(self, steps, i: int, t):
        """The sharded-stage degrade rungs.  A mesh spanning MULTIPLE
        hosts first drops a whole host's device group and re-plans on
        the survivors (``reason="host_lost"`` — on a pod, a device
        failure usually means the HOST behind it is gone, and the
        surviving processes' devices are the ones still answering);
        a single-host mesh halves its devices (``reason=
        "mesh_shrink"``, → single-device fused when it bottoms out).
        Returns the re-planned step (already swapped into ``steps``,
        fingerprints for ``i..`` refreshed — they embed the mesh
        signature, so checkpoints from the larger mesh never match
        again) or ``None`` when the step is not sharded / already
        single-device."""
        mesh = getattr(t, "mesh", None)
        replan = getattr(t, "replan", None)
        if mesh is None or replan is None:
            return None
        n_dev = int(mesh.devices.size)
        if n_dev <= 1:
            return None
        reason, kw = "mesh_shrink", {}
        from_hosts = to_hosts = None
        from .parallel.mesh import mesh_host_groups

        groups = mesh_host_groups(mesh)
        if len(groups) > 1:
            survivors = self._surviving_host_devices(groups)
            reason = "host_lost"
            from_hosts, to_hosts = len(groups), len(groups) - 1
            new_t = replan(None, devices=survivors)
            kw = {"from_hosts": from_hosts, "to_hosts": to_hosts}
        else:
            target = n_dev // 2 if n_dev // 2 > 1 else None
            new_t = replan(target)
        steps[i] = new_t
        for j in range(i, len(steps)):
            # the prefix chain embeds step i's mesh signature — every
            # downstream fingerprint moves with it
            self.report.steps[j].fingerprint = step_fingerprint(
                steps, j, input_digest=self._input_digest)
        new_mesh = getattr(new_t, "mesh", None)
        to_dev = 1 if new_mesh is None else int(new_mesh.devices.size)
        warnings.warn(
            f"ResilientRunner: sharded step {i} ({t.name!r}) exhausted "
            f"its retry budget on {n_dev} devices — RE-PLANNING on "
            f"{to_dev} device(s)"
            + (f" across {to_hosts} surviving host(s)"
               if reason == "host_lost" else "")
            + " before ruling on a backend fallback.",
            RuntimeWarning, stacklevel=3)
        self.journal.write(
            "degrade", step=i, reason=reason,
            from_devices=n_dev, to_devices=to_dev,
            fingerprint=self.report.steps[i].fingerprint, **kw)
        self.metrics.counter("runner.degrades", reason=reason).inc()
        return new_t

    @staticmethod
    def _surviving_host_devices(groups) -> list:
        """Which devices survive a lost-host ruling: drop the LAST
        host group that holds no local-process device — the local
        host is provably alive (this code is executing on it), and
        without failure attribution the far end of the mesh is the
        best guess for the lost one.  When every group is local (the
        single-process harness's fake grouping) the last group drops."""
        import jax

        local_pi = jax.process_index()
        drop = None
        for g in reversed(groups):
            if all(int(getattr(d, "process_index", 0)) != local_pi
                   for d in g):
                drop = g
                break
        if drop is None:
            drop = groups[-1]
        return [d for g in groups if g is not drop for d in g]

    # ------------------------------------------------------------------
    @staticmethod
    def _match_residency(data, backend: str):
        """cpu ops consume host numpy/scipy; tpu ops consume device
        arrays.  A mid-run backend change — the degrade-to-cpu
        fallback, or a host-resident input to a tpu run — hands the
        next op the previous op's output in the WRONG residency;
        convert at the boundary.  Runs inside the attempt try-block,
        so a fetch from a dead device is classified and retried like
        any other step failure."""
        if not (hasattr(data, "to_host") and hasattr(data, "device_put")):
            return data
        import numpy as np
        import scipy.sparse as sp

        X = getattr(data, "X", None)
        on_host = isinstance(X, np.ndarray) or sp.issparse(X)
        if backend == "cpu" and not on_host:
            return data.to_host()
        if backend != "cpu" and sp.issparse(X):
            # dense numpy feeds jnp ops directly; packed sparse does
            # not — only the scipy format needs the device packing
            return data.device_put()
        return data

    def _execute(self, t: Transform, data, backend: str, i: int, steps):
        if backend != t.backend:
            t = t.with_backend(backend)
        if t.name not in self.isolate:
            return t(self._match_residency(data, backend))
        # isolated steps hand data over as a host-side checkpoint file
        # anyway — a device round-trip here would be pure waste
        return self._execute_isolated(t, data, backend, i)

    def _execute_isolated(self, t: Transform, data, backend: str,
                          i: int):
        """Run one step under ``failsafe.run_isolated``: the data
        crosses into the watched child as a checkpoint file and comes
        back the same way, so a crashed/wedged child can never poison
        this process's jax runtime.  The child's death is CLASSIFIED
        (``failsafe.classify_child_result``): a deterministic
        traceback in the stderr tail fails fast instead of burning
        the retry budget; watchdog kills and tracebackless process
        death stay transient.  A per-step deadline caps the child's
        watchdog timeout to the budget that remains."""
        workdir = self.checkpoint_dir or tempfile.mkdtemp(
            prefix="sctools_runner_")
        in_path = os.path.join(workdir, f"isolate_in_{i:03d}.npz")
        out_path = os.path.join(workdir, f"isolate_out_{i:03d}.npz")
        save_celldata(data, in_path, digest=False)  # transfer file
        kwargs = {"chaos_spec": self.chaos.spec()} if self.chaos else {}
        timeout_s = self.isolate_timeout_s
        tok = current_deadline()
        if tok is not None:
            # the deadline rules the child too; floor keeps a nearly-
            # spent budget from passing a zero/negative watchdog
            timeout_s = max(0.1, min(timeout_s, tok.remaining()))
        try:
            res = run_isolated(
                _exec_step, in_path, t.name, t.backend, dict(t.params),
                out_path, timeout_s=timeout_s,
                stall_timeout_s=self.isolate_stall_s, **kwargs)
            if self.chaos is not None:
                self.chaos.note_external_call(t.name)
            if res["status"] != "completed":
                raise classify_child_result(res, t.name)
            payload = res.get("result")
            if isinstance(payload, dict) and payload.get("spans"):
                # graft the child's span tree under the current step
                # span (we are inside _run_step's `runner:<name>`
                # span here) — isolated steps must not vanish from
                # the trace
                trace.graft(payload["spans"])
            out = load_celldata(out_path)
            if backend == "tpu":
                out = out.device_put()
            return out
        finally:
            for p in (in_path, out_path):
                try:
                    os.remove(p)
                except OSError:
                    pass
            if workdir is not self.checkpoint_dir:
                try:
                    os.rmdir(workdir)  # only the throwaway mkdtemp
                except OSError:
                    pass
