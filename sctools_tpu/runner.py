"""Resilient pipeline execution: retry, backoff, containment, resume.

``Pipeline.run()`` is a bare loop — it dies on the first transient
error and restarts from scratch.  On the hardware this framework
targets that is the WRONG default: rounds 1–5 of the bench established
empirically (bench.py, VERDICT.md) that the tunneled TPU backend
crashes (every later call raises ``UNAVAILABLE``) and wedges (calls
block forever), and at atlas scale preemption is the common case, not
the exception.  The survival primitives already exist —
``utils/failsafe.py`` (probes, watched subprocesses, the retryable-
error taxonomy), ``utils/checkpoint.py`` (step-fingerprinted
checkpoints), ``utils/trace.py`` (spans) — this module composes them
into one execution layer:

* **Per-step retry with exponential backoff + jitter** — transient
  device errors (``UNAVAILABLE``, timeouts; ``failsafe.classify_error``)
  are retried up to ``RetryPolicy.max_attempts``; deterministic
  program errors (ValueError, shape errors) FAIL FAST on the first
  attempt — retrying them only burns the budget.
* **Health checks + degrade-to-CPU** — before the run (``preflight=``)
  and after a step exhausts its retries, ``failsafe.probe_device``
  rules on the accelerator from a throwaway subprocess; ruled
  unhealthy, the run degrades every remaining step to the
  ``fallback_backend`` with a loud warning rather than dying.
* **Subprocess containment** — steps named in ``isolate=`` run under
  ``failsafe.run_isolated``: a crash or wedge kills the CHILD, the
  runner's process (and its jax runtime) stays clean, and the death
  is classified transient (retried, possibly degraded).
* **Checkpointed resume** — with ``checkpoint_dir=``, every completed
  step is checkpointed under its content fingerprint
  (``checkpoint.step_filename``); a killed run re-invoked with
  ``resume=True`` restarts at the failed step.  Filenames are shared
  with ``PipelineCheckpointer``, so the two interoperate.
* **Structured run journal** — one JSONL record per event (attempt,
  backoff, fallback, resume, completion) with the classified error,
  backend, wall time and the ``trace.span`` id it links to; the
  in-memory :class:`RunReport` mirrors it.

All time sources are injectable (``sleep=``, ``probe=``), so recovery
behaviour — including the backoff schedule — is testable in tier-1
with zero real sleeps (tests/test_runner.py), with faults injected
deterministically by ``utils/chaos.py``.

>>> from sctools_tpu.runner import ResilientRunner
>>> runner = ResilientRunner(seurat_pipeline(), checkpoint_dir="ck/")
>>> out = runner.run(data, backend="tpu")     # survives; resumes
>>> runner.report.summary()
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import tempfile
import time
import warnings

from .registry import Pipeline, Transform
from .utils import trace
from .utils.checkpoint import (load_celldata, save_celldata,
                               step_filename, step_fingerprint,
                               latest_step)
from .utils.failsafe import (DETERMINISTIC, FATAL, TRANSIENT,
                             TransientDeviceError, classify_error,
                             probe_device, run_isolated)


@dataclasses.dataclass
class RetryPolicy:
    """Exponential backoff with deterministic seeded jitter.

    Attempt ``n`` (1-based) that fails transiently waits
    ``min(base_delay_s * multiplier**(n-1), max_delay_s)`` scaled by a
    jitter factor uniform in ``[1-jitter, 1+jitter)`` drawn from a
    ``random.Random(seed)`` stream — same seed, same schedule, which
    is what lets tier-1 pin the exact delays against a fake sleeper.
    """

    max_attempts: int = 3
    base_delay_s: float = 0.5
    multiplier: float = 2.0
    max_delay_s: float = 30.0
    jitter: float = 0.5
    seed: int = 0

    def delay_s(self, attempt: int, rng) -> float:
        d = min(self.base_delay_s * self.multiplier ** max(attempt - 1, 0),
                self.max_delay_s)
        if self.jitter > 0:
            d *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return d


@dataclasses.dataclass
class StepAttempt:
    attempt: int
    backend: str
    status: str                      # "ok" | "error"
    wall_s: float
    span_id: int
    error: str | None = None
    classified: str | None = None    # transient | deterministic | fatal


@dataclasses.dataclass
class StepReport:
    index: int
    name: str
    fingerprint: str
    status: str = "pending"   # pending|completed|resumed|failed|aborted
    backend: str | None = None
    isolated: bool = False
    attempts: list = dataclasses.field(default_factory=list)

    @property
    def wall_s(self) -> float:
        return round(sum(a.wall_s for a in self.attempts), 4)


@dataclasses.dataclass
class RunReport:
    status: str = "pending"   # pending|completed|failed|aborted
    backend: str | None = None
    degraded: bool = False
    resumed_from: int | None = None
    journal_path: str | None = None
    steps: list = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def summary(self) -> str:
        lines = [f"run: {self.status}"
                 + (f" (degraded to {self.backend})" if self.degraded
                    else "")]
        for s in self.steps:
            lines.append(
                f"  [{s.index:02d}] {s.name:<28s} {s.status:<10s} "
                f"attempts={len(s.attempts)} backend={s.backend or '-'} "
                f"wall={s.wall_s:.3f}s")
        return "\n".join(lines)


class ResilientRunError(RuntimeError):
    """A step exhausted its retry budget (and any fallback).  Carries
    the :class:`RunReport` in ``.report``; the last device error is
    chained as ``__cause__``."""

    def __init__(self, msg: str, report: RunReport):
        super().__init__(msg)
        self.report = report


def _exec_step(in_path: str, name: str, backend: str, params: dict,
               out_path: str, chaos_spec: dict | None = None) -> bool:
    """Containment target for ``failsafe.run_isolated``: load → apply
    one transform → save.  Module-level because the payload pickles it
    by reference; data crosses the process boundary as checkpoint
    files, not pickles.  A forwarded chaos spec re-arms fault
    injection inside the child (how tier-1 exercises the kill/wedge
    containment paths for real)."""
    data = load_celldata(in_path)
    t = Transform(name, backend=backend, **params)
    if chaos_spec is not None:
        from .utils.chaos import ChaosMonkey

        with ChaosMonkey.from_spec(chaos_spec).activate():
            out = t(data)
    else:
        out = t(data)
    save_celldata(out, out_path)
    return True


class _Journal:
    """Append-only JSONL event log.  One ``open/write/close`` per
    record: a killed run keeps every line written before the kill,
    which is the whole point of a crash journal."""

    def __init__(self, path: str | None):
        self.path = path
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)

    def write(self, event: str, **fields) -> None:
        if not self.path:
            return
        rec = {"event": event, "ts": round(time.time(), 3), **fields}
        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")


class ResilientRunner:
    """Execute a :class:`Pipeline` step-by-step with retry/backoff,
    health-checked backend fallback, optional subprocess containment,
    checkpointed resume and a structured run journal (module docstring
    has the full contract).

    Parameters
    ----------
    pipeline : Pipeline
    checkpoint_dir : str | None
        Enables per-step checkpoints + resume; also the default home
        of ``journal.jsonl`` and the isolation handoff files.
    policy : RetryPolicy
    probe : callable | None
        Zero-arg health check returning ``{"ok": bool, ...}``;
        defaults to ``failsafe.probe_device``.  Injectable for tests.
    preflight : bool
        Probe before the first step; degrade immediately if unhealthy.
    fallback_backend : str | None
        Backend remaining steps degrade to when the accelerator is
        ruled unhealthy (``None`` disables fallback).
    isolate : collection of str
        Transform names to contain in a watched subprocess
        (known-wedging stages); a killed child is a TRANSIENT failure.
    validate : callable | None
        ``validate(index, name, data)`` after each successful step;
        a raise is treated as that attempt's failure (a ``ValueError``
        therefore fails fast — how silent corruption gets caught).
    chaos : ChaosMonkey | None
        Fault-injection harness active for the whole run and
        forwarded into isolated children.
    sleep : callable
        Backoff sleeper (``time.sleep``); tests inject a fake.
    """

    def __init__(self, pipeline: Pipeline, *,
                 checkpoint_dir: str | None = None,
                 journal_path: str | None = None,
                 policy: RetryPolicy | None = None,
                 probe=None, preflight: bool = False,
                 probe_timeout_s: float = 90.0,
                 fallback_backend: str | None = "cpu",
                 isolate=(), isolate_timeout_s: float = 600.0,
                 isolate_stall_s: float = 240.0,
                 validate=None, chaos=None, sleep=time.sleep):
        self.pipeline = pipeline
        self.checkpoint_dir = checkpoint_dir
        if checkpoint_dir:
            os.makedirs(checkpoint_dir, exist_ok=True)
            if journal_path is None:
                journal_path = os.path.join(checkpoint_dir,
                                            "journal.jsonl")
        self.policy = policy or RetryPolicy()
        self.probe = probe if probe is not None else (
            lambda: probe_device(timeout_s=probe_timeout_s))
        self.preflight = preflight
        self.fallback_backend = fallback_backend
        self.isolate = frozenset(isolate)
        self.isolate_timeout_s = isolate_timeout_s
        self.isolate_stall_s = isolate_stall_s
        self.validate = validate
        self.chaos = chaos
        self.sleep = sleep
        self.journal = _Journal(journal_path)
        self.report = RunReport(journal_path=journal_path)

    # ------------------------------------------------------------------
    def run(self, data, backend: str | None = None, resume: bool = True):
        import random

        steps = list(self.pipeline.steps)
        rng = random.Random(self.policy.seed)
        report = self.report = RunReport(
            status="pending", backend=backend,
            journal_path=self.journal.path,
            steps=[StepReport(i, t.name, step_fingerprint(steps, i),
                              isolated=t.name in self.isolate)
                   for i, t in enumerate(steps)])
        self.journal.write(
            "run_start", n_steps=len(steps), backend=backend,
            resume=bool(resume and self.checkpoint_dir),
            steps=[{"index": s.index, "name": s.name,
                    "fingerprint": s.fingerprint}
                   for s in report.steps])

        degraded = False
        if self.preflight:
            degraded = self._rule_unhealthy(where="preflight")
        start = 0
        if resume and self.checkpoint_dir:
            # host-side load only — device placement happens per-step
            # inside the attempt try-block (_match_residency), where a
            # dead device is classified and degraded like any other
            # failure.  An unreadable checkpoint (disk error, external
            # truncation) falls back to the next-newest intact one;
            # only when none survive does the run restart from scratch.
            i = latest_step(self.checkpoint_dir, steps)
            while i is not None:
                try:
                    data_ck = load_celldata(self._ckpt_path(steps, i))
                except Exception as e:  # noqa: BLE001 — a corrupt
                    # checkpoint must not kill the run; an earlier
                    # one (or scratch) always can
                    warnings.warn(
                        f"ResilientRunner: checkpoint for step {i} "
                        f"unreadable ({type(e).__name__}: {e}) — "
                        "falling back to the previous checkpoint",
                        RuntimeWarning, stacklevel=2)
                    self.journal.write(
                        "resume_load_failed", from_step=i,
                        error=f"{type(e).__name__}: {e}")
                    i = latest_step(self.checkpoint_dir, steps,
                                    upto=i - 1)
                    continue
                data = data_ck
                start = i + 1
                report.resumed_from = i
                for s in report.steps[: i + 1]:
                    s.status = "resumed"
                self.journal.write(
                    "resume", from_step=i,
                    fingerprint=report.steps[i].fingerprint)
                break

        chaos_ctx = (self.chaos.activate() if self.chaos is not None
                     else contextlib.nullcontext())
        with chaos_ctx:
            for i in range(start, len(steps)):
                data, degraded = self._run_step(
                    steps, i, data, backend, degraded, rng)

        if start == len(steps) and steps:
            # fully-resumed: no step ran to re-place the loaded data —
            # return the residency a fresh run would (matches
            # PipelineCheckpointer's device_put-on-resume; unlike the
            # per-step adapter this places DENSE host X too, since the
            # contract here is output parity, not op-input minimum).
            # Best effort: a dead device must not fail a run whose
            # every step is already done — hand back host data instead.
            try:
                b = self._target_backend(steps[-1], backend, degraded)
                if b != "cpu" and hasattr(data, "device_put"):
                    data = data.device_put()
            except Exception as e:  # noqa: BLE001
                warnings.warn(
                    "ResilientRunner: device placement of the fully-"
                    f"resumed result failed ({type(e).__name__}: {e})"
                    " — returning host-resident data.",
                    RuntimeWarning, stacklevel=2)
                self.journal.write(
                    "resume_place_failed",
                    error=f"{type(e).__name__}: {e}")
        report.status = "completed"
        report.degraded = degraded
        if degraded:
            report.backend = self.fallback_backend
        self.journal.write("run_completed", degraded=degraded)
        return data

    # ------------------------------------------------------------------
    def _target_backend(self, t: Transform, backend: str | None,
                        degraded: bool) -> str:
        b = backend if backend is not None else t.backend
        if degraded and self.fallback_backend:
            b = self.fallback_backend
        return b

    def _ckpt_path(self, steps, i: int) -> str:
        return os.path.join(self.checkpoint_dir, step_filename(steps, i))

    def _rule_unhealthy(self, where: str) -> bool:
        """Probe the device; on an unhealthy verdict warn LOUDLY and
        rule the run degraded.  Returns the new degraded flag."""
        rec = self.probe()
        self.journal.write("health_check", where=where, result=rec)
        if rec.get("ok"):
            return False
        if not self.fallback_backend:
            # the caller asked for the check — an unhealthy verdict
            # must not pass silently just because degrading is off
            warnings.warn(
                "ResilientRunner: accelerator ruled UNHEALTHY "
                f"({rec.get('reason', 'probe failed')!r} at {where}) "
                "and no fallback_backend is configured — continuing "
                "on the unhealthy device.", RuntimeWarning,
                stacklevel=3)
            return False
        warnings.warn(
            "ResilientRunner: accelerator ruled UNHEALTHY "
            f"({rec.get('reason', 'probe failed')!r} at {where}) — "
            f"DEGRADING remaining steps to backend="
            f"{self.fallback_backend!r}.  Results stay correct but "
            "slow; fix the device and re-run to get it back.",
            RuntimeWarning, stacklevel=3)
        self.journal.write("fallback", where=where,
                           backend=self.fallback_backend)
        # recorded immediately, not at run end: the report attached to
        # a later failure must already say what the run degraded to
        self.report.degraded = True
        self.report.backend = self.fallback_backend
        return True

    def _run_step(self, steps, i: int, data, backend, degraded: bool,
                  rng):
        policy = self.policy
        t = steps[i]
        sr = self.report.steps[i]
        attempt = 0        # monotonic across a fallback — the journal
        budget_used = 0    # join key must never repeat within a step
        while True:
            attempt += 1
            budget_used += 1
            b = self._target_backend(t, backend, degraded)
            sr.backend = b
            err = None
            with trace.span(f"runner:{t.name}",
                            meta={"step": i, "attempt": attempt,
                                  "backend": b}) as sp:
                try:
                    out = self._execute(t, data, b, i, steps)
                    if self.validate is not None:
                        self.validate(i, t.name, out)
                    if self.checkpoint_dir:
                        # inside the classified block on purpose: the
                        # save fetches device results to host, and a
                        # device that died between compute and save
                        # must be retried/degraded like any other
                        # step failure — not leak a raw raise
                        save_celldata(out, self._ckpt_path(steps, i))
                except BaseException as e:  # noqa: BLE001 — reported,
                    err = e                 # classified, re-raised below
            if err is None:
                sr.attempts.append(StepAttempt(
                    attempt, b, "ok", round(sp.duration, 4), sp.id))
                sr.status = "completed"
                self.journal.write(
                    "attempt", step=i, name=t.name, attempt=attempt,
                    backend=b, status="ok",
                    wall_s=round(sp.duration, 4), span_id=sp.id)
                if self.checkpoint_dir:
                    self.journal.write("checkpoint", step=i,
                                       fingerprint=sr.fingerprint)
                return out, degraded

            cls = classify_error(err)
            sr.attempts.append(StepAttempt(
                attempt, b, "error", round(sp.duration, 4), sp.id,
                error=f"{type(err).__name__}: {err}", classified=cls))
            self.journal.write(
                "attempt", step=i, name=t.name, attempt=attempt,
                backend=b, status="error", classified=cls,
                error=f"{type(err).__name__}: {err}",
                wall_s=round(sp.duration, 4), span_id=sp.id)
            if cls == FATAL:
                sr.status = "aborted"
                self.report.status = "aborted"
                self.journal.write("run_aborted", step=i,
                                   error=type(err).__name__)
                raise err
            if cls == DETERMINISTIC:
                # retrying replays the same raise — fail fast, and
                # hand the caller the REAL exception, not a wrapper
                sr.status = "failed"
                self.report.status = "failed"
                self.journal.write("run_failed", step=i,
                                   classified=cls)
                raise err
            # transient: retry with backoff until the budget is spent,
            # then let the health probe rule on a backend fallback
            if budget_used < policy.max_attempts:
                d = policy.delay_s(budget_used, rng)
                self.journal.write("backoff", step=i, attempt=attempt,
                                   delay_s=round(d, 4))
                self.sleep(d)
                continue
            if (not degraded and self.fallback_backend
                    and b != self.fallback_backend):
                if self._rule_unhealthy(where=f"step {i}"):
                    degraded = True  # report fields set by the ruling
                    budget_used = 0  # fresh budget on the healthy backend
                    continue
            sr.status = "failed"
            self.report.status = "failed"
            self.journal.write("run_failed", step=i, classified=cls)
            raise ResilientRunError(
                f"step {i} ({t.name!r}) failed {attempt} times on "
                f"backend {b!r}; last error: "
                f"{type(err).__name__}: {err}", self.report) from err

    # ------------------------------------------------------------------
    @staticmethod
    def _match_residency(data, backend: str):
        """cpu ops consume host numpy/scipy; tpu ops consume device
        arrays.  A mid-run backend change — the degrade-to-cpu
        fallback, or a host-resident input to a tpu run — hands the
        next op the previous op's output in the WRONG residency;
        convert at the boundary.  Runs inside the attempt try-block,
        so a fetch from a dead device is classified and retried like
        any other step failure."""
        if not (hasattr(data, "to_host") and hasattr(data, "device_put")):
            return data
        import numpy as np
        import scipy.sparse as sp

        X = getattr(data, "X", None)
        on_host = isinstance(X, np.ndarray) or sp.issparse(X)
        if backend == "cpu" and not on_host:
            return data.to_host()
        if backend != "cpu" and sp.issparse(X):
            # dense numpy feeds jnp ops directly; packed sparse does
            # not — only the scipy format needs the device packing
            return data.device_put()
        return data

    def _execute(self, t: Transform, data, backend: str, i: int, steps):
        if backend != t.backend:
            t = t.with_backend(backend)
        if t.name not in self.isolate:
            return t(self._match_residency(data, backend))
        # isolated steps hand data over as a host-side checkpoint file
        # anyway — a device round-trip here would be pure waste
        return self._execute_isolated(t, data, backend, i)

    def _execute_isolated(self, t: Transform, data, backend: str,
                          i: int):
        """Run one step under ``failsafe.run_isolated``: the data
        crosses into the watched child as a checkpoint file and comes
        back the same way, so a crashed/wedged child can never poison
        this process's jax runtime."""
        workdir = self.checkpoint_dir or tempfile.mkdtemp(
            prefix="sctools_runner_")
        in_path = os.path.join(workdir, f"isolate_in_{i:03d}.npz")
        out_path = os.path.join(workdir, f"isolate_out_{i:03d}.npz")
        save_celldata(data, in_path)
        kwargs = {"chaos_spec": self.chaos.spec()} if self.chaos else {}
        try:
            res = run_isolated(
                _exec_step, in_path, t.name, t.backend, dict(t.params),
                out_path, timeout_s=self.isolate_timeout_s,
                stall_timeout_s=self.isolate_stall_s, **kwargs)
            if self.chaos is not None:
                self.chaos.note_external_call(t.name)
            if res["status"] != "completed":
                raise TransientDeviceError(
                    f"isolated step {t.name!r} {res['status']} "
                    f"(rc={res.get('rc')}, wall={res.get('wall_s')}s); "
                    f"stderr tail: {res.get('stderr_tail', '')[-300:]}")
            out = load_celldata(out_path)
            if backend == "tpu":
                out = out.device_put()
            return out
        finally:
            for p in (in_path, out_path):
                try:
                    os.remove(p)
                except OSError:
                    pass
            if workdir is not self.checkpoint_dir:
                try:
                    os.rmdir(workdir)  # only the throwaway mkdtemp
                except OSError:
                    pass
