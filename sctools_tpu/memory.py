"""Device memory as a first-class fault domain: learned peak
estimates, a budgeted reservation ledger, and the scopes that thread
them through the stack.

Every fault domain built so far — device crashes (runner), host RAM
(shard store), processes (federation), resident state (serving) —
managed a resource the process could observe failing.  Device memory
was the blind spot: ``RunScheduler`` admission checked quotas and
deadlines but two large admitted runs would happily co-schedule into
one HBM and OOM, and ``failsafe.classify_error`` deliberately left
``RESOURCE_EXHAUSTED`` out of the transient set so the only ruling
for the canonical TPU production failure was fail-fast.  This module
is the missing substrate, in three pieces:

* :class:`MemoryEstimates` — the process-wide peak-memory model.
  Every compiled plan-cache entry records the peak its XLA executable
  actually declared (``compiled.memory_analysis()``, recorded by
  ``plan.FusedTransform`` on the cache-miss path); everything else —
  eager ops, host ops, stages not yet compiled — is estimated from
  registry ``mem_cost=`` metadata applied to the input size
  (:func:`step_estimate`).  The model is SELF-CORRECTING: an OOM
  observed at runtime inflates the stored estimate
  (:meth:`MemoryEstimates.inflate`, ×2 per observation), and the
  correction outlives the pipeline object — a rebuilt identical
  pipeline sees the inflated number, so the admission layer stops
  believing an estimate the device already refuted.
* :class:`MemoryBudget` — a per-backend reservation ledger.  Capacity
  comes from the device's own ``memory_stats()['bytes_limit']`` when
  the platform reports one, or the ``SCTOOLS_MEM_BUDGET_BYTES`` env
  cap (how CI fakes an HBM on a CPU box).  Submissions RESERVE their
  estimated peak at dispatch and release on terminal; residents hold
  NAMED reservations so query traffic and training jobs contend for
  what is actually left, not for the nameplate capacity —
  service-lifetime residents (the serving tier's reference model) as
  STANDING holds that also shrink what admission may ever promise,
  run-scoped residents (the streaming trainer's feed buffers) as
  dynamic holds that tighten dispatch fitting only.  ``set_pressure``
  models a shrunken apparent budget (chaos ``mem_pressure``) without
  touching the ledger.
* :func:`budget_scope` / :func:`current_budget` — the thread-local
  handoff (same shape as ``failsafe.deadline_scope``): the scheduler
  worker installs its pool's budget around each dispatched run, so
  code deep inside an op (``models/train_stream.py``'s device feed)
  can take a named reservation against the pool's budget without
  any parameter plumbing.

Estimate keys deliberately bucket the input size to the next power of
two: a rebuilt pipeline over the same data, or a same-bucket query
batch, lands on the same key — which is what lets a compiled
estimate (or an OOM correction) recorded under one run serve the
admission ruling of the next.  Stages deep inside a long pipeline
whose intermediate sizes diverge from the run input simply fall back
to the ``mem_cost`` heuristic; the model is a budget guide, not an
allocator, and the OOM containment ladder (``runner.py``) backstops
every estimate it gets wrong.

This module is importable without jax (capacity detection imports it
lazily) and never sleeps or journals — callers own clocks and
journals.
"""

from __future__ import annotations

import os
import threading

from . import registry as _registry
from .utils import telemetry

#: peak multiplier assumed for an op with no ``mem_cost=`` metadata:
#: inputs resident + an output of the same size (the shape of most
#: elementwise/normalise ops).  Registered metadata overrides it.
DEFAULT_STEP_MULTIPLIER = 2.0

#: multiplicative inflation applied to a stored estimate per observed
#: OOM — the self-correction step.  Doubling converges in
#: log2(true/estimated) observations and never oscillates (estimates
#: only ever grow; a compiled re-record cannot deflate a correction).
OOM_INFLATE_FACTOR = 2.0

#: documented accuracy contract for the heuristic estimator: for the
#: canned fused plans tier-1 pins, the ``mem_cost`` heuristic must be
#: within this factor of ``compiled.memory_analysis()`` actuals
#: (either direction).  Deliberately loose — the heuristic exists to
#: rank runs for admission, the compiled record replaces it after
#: first execution, and the OOM ladder backstops underestimates.
HEURISTIC_ACCURACY_FACTOR = 16.0


def size_bucket(nbytes: int) -> int:
    """Input sizes bucket to the next power of two for estimate keys:
    exact-byte keys would fragment the model across trivially
    different inputs, while a 2× bucket still separates workloads
    whose peaks meaningfully differ."""
    n = max(int(nbytes), 1)
    return 1 << (n - 1).bit_length()


def data_nbytes(data) -> int:
    """Total array bytes of a pytree (CellData, dict, array): the
    input-size term every heuristic estimate scales from.  Opaque
    leaves (strings, scalars) count nothing — they never land on
    device."""
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(data)
    except Exception:  # pragma: no cover - jax-free caller
        leaves = [data]
    total = 0
    for v in leaves:
        n = getattr(v, "nbytes", None)
        if isinstance(n, (int, float)):
            total += int(n)
            continue
        # scipy sparse leaves carry no .nbytes of their own — count
        # their buffer triplet (a host CSR about to be densified or
        # packed is exactly the input the estimate scales from)
        for attr in ("data", "indices", "indptr"):
            b = getattr(getattr(v, attr, None), "nbytes", None)
            if isinstance(b, (int, float)):
                total += int(b)
    return total


def _tok(v):
    """Stable hashable token for a bound param value (estimate keys
    must not retain arrays; mirrors plan._freeze without importing
    jax at module load)."""
    if isinstance(v, dict):
        return ("d",) + tuple(sorted((k, _tok(x)) for k, x in v.items()))
    if isinstance(v, (list, tuple, set, frozenset)):
        items = sorted(v, key=repr) if isinstance(v, (set, frozenset)) \
            else v
        return (type(v).__name__,) + tuple(_tok(x) for x in items)
    nb = getattr(v, "nbytes", None)
    if nb is not None and hasattr(v, "shape"):
        return ("nd", str(getattr(v, "dtype", "?")),
                tuple(getattr(v, "shape", ())))
    if isinstance(v, (bool, int, float, complex, str, bytes,
                      type(None))):
        return v
    return ("r", type(v).__name__, repr(v))


def _step_members(step):
    """The member transforms of a step: a fused stage / unfused chain
    exposes ``.members``, a plain Transform is its own single
    member."""
    members = getattr(step, "members", None)
    if members:
        return list(members)
    return [step]


def _step_kind(step) -> str:
    """How the step holds its live set — the part of the estimate key
    that distinguishes one compiled program (``fused``: every member
    intermediate may be live at once) from an eager chain (``chain``/
    ``eager``: intermediates free between members)."""
    if getattr(step, "members", None):
        if getattr(step, "mesh", None) is not None:
            return "sharded"
        cls = type(step).__name__
        return "chain" if cls == "_UnfusedChain" else "fused"
    return "eager"


def step_sig(step, input_bytes: int) -> tuple:
    """The estimate-store key for one pipeline step at one input-size
    bucket: step kind + the (name, backend, params) member chain +
    the bucketed input bytes.  Pure function of the step OBJECT's
    declaration, so a rebuilt pipeline lands on the same key."""
    members = tuple((m.name, m.backend, _tok(dict(m.params)))
                    for m in _step_members(step))
    return (_step_kind(step), members, size_bucket(input_bytes))


def heuristic_estimate(step, input_bytes: int) -> int:
    """Registry-metadata peak estimate for one step on
    ``input_bytes`` of input.

    * eager / collective step: ``input × mem_cost`` (a callable
      ``mem_cost`` returns bytes outright, converted to an effective
      multiplier here);
    * fused stage: ``input × (1 + Σ (mᵢ − 1))`` — one compiled
      program may hold every member's intermediates live at once;
    * unfused chain: ``input × max(mᵢ)`` — intermediates free
      between member dispatches, which is exactly why unfusing is
      the OOM ladder's first rung.
    """
    input_bytes = max(int(input_bytes), 1)
    members = _step_members(step)
    mults = []
    for m in members:
        c = _registry.mem_cost_of(m.name, m.backend, m.params,
                                  input_bytes=input_bytes)
        if c is None:
            mults.append(DEFAULT_STEP_MULTIPLIER)
        elif c[0] == "bytes":
            mults.append(max(float(c[1]) / input_bytes, 1.0))
        else:
            mults.append(float(c[1]))
    kind = _step_kind(step)
    if kind in ("fused", "sharded") and len(mults) > 1:
        mult = 1.0 + sum(m - 1.0 for m in mults)
    else:
        mult = max(mults)
    return int(input_bytes * max(mult, 1.0))


class MemoryEstimates:
    """The process-wide learned peak-memory model (module docstring).
    Thread-safe; entries are ``{"bytes", "source", "corrections"}``
    with ``source`` one of ``compiled`` (recorded from
    ``memory_analysis()``), ``heuristic`` (never stored — computed on
    demand) or ``corrected`` (inflated by an observed OOM; can only
    grow)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._store: dict[tuple, dict] = {}

    def record(self, sig: tuple, nbytes: int,
               source: str = "compiled") -> int:
        """Record a measured estimate.  A correction already in the
        store is never DEFLATED by a later compiled record — the
        device's refusal outranks the compiler's declaration."""
        nbytes = int(nbytes)
        with self._lock:
            cur = self._store.get(sig)
            if cur is not None and cur["corrections"] > 0:
                if nbytes > cur["bytes"]:
                    cur["bytes"] = nbytes
                return cur["bytes"]
            self._store[sig] = {"bytes": nbytes, "source": source,
                                "corrections":
                                    cur["corrections"] if cur else 0}
            return nbytes

    def get(self, sig: tuple) -> dict | None:
        with self._lock:
            e = self._store.get(sig)
            return dict(e) if e is not None else None

    def inflate(self, sig: tuple, base_bytes: int) -> int:
        """The OOM self-correction: the stored estimate (or
        ``base_bytes`` on first sight) inflates ×2 and is marked
        corrected.  Returns the new estimate."""
        with self._lock:
            cur = self._store.get(sig)
            base = max(int(base_bytes),
                       cur["bytes"] if cur is not None else 0, 1)
            new = int(base * OOM_INFLATE_FACTOR)
            self._store[sig] = {
                "bytes": new, "source": "corrected",
                "corrections": (cur["corrections"] + 1
                                if cur is not None else 1)}
            return new

    def snapshot(self) -> dict:
        with self._lock:
            return {repr(k): dict(v) for k, v in self._store.items()}

    def reset(self) -> None:
        with self._lock:
            self._store.clear()


_DEFAULT_ESTIMATES = MemoryEstimates()


def default_estimates() -> MemoryEstimates:
    """The process-wide estimate store — 'process-wide' is the
    contract that lets a compiled record (or OOM correction) from one
    run serve the admission ruling of the next."""
    return _DEFAULT_ESTIMATES


def step_estimate(step, input_bytes: int,
                  estimates: MemoryEstimates | None = None) -> dict:
    """Best available peak estimate for one step:
    ``{"bytes", "source"}`` — the learned store first (compiled /
    corrected), the ``mem_cost`` heuristic otherwise."""
    est = estimates if estimates is not None else _DEFAULT_ESTIMATES
    rec = est.get(step_sig(step, input_bytes))
    if rec is not None:
        return {"bytes": rec["bytes"], "source": rec["source"]}
    return {"bytes": heuristic_estimate(step, input_bytes),
            "source": "heuristic"}


def estimate_run_peak(pipeline, data=None, *, input_bytes: int | None
                      = None, estimates: MemoryEstimates | None
                      = None) -> dict:
    """Peak-memory estimate for one run at admission time: the max
    over its steps' estimates (steps execute sequentially — their
    peaks never stack), floored at the input's own resident bytes.
    Returns ``{"bytes", "per_step": [{name, bytes, source}]}``."""
    if input_bytes is None:
        input_bytes = data_nbytes(data) if data is not None else 1
    input_bytes = max(int(input_bytes), 1)
    per_step = []
    peak = input_bytes
    for t in getattr(pipeline, "steps", []):
        e = step_estimate(t, input_bytes, estimates)
        per_step.append({"name": getattr(t, "name", "?"), **e})
        peak = max(peak, e["bytes"])
    return {"bytes": int(peak), "per_step": per_step}


# ---------------------------------------------------------------------------
# The budget
# ---------------------------------------------------------------------------


def detect_budget_bytes() -> int | None:
    """Device-memory capacity for this process: the
    ``SCTOOLS_MEM_BUDGET_BYTES`` env cap when set (CI's fake HBM),
    else the first local device's reported ``bytes_limit`` (real TPU
    platforms report one; CPU reports nothing → ``None``, and a
    budget cannot be constructed without an explicit capacity)."""
    env = os.environ.get("SCTOOLS_MEM_BUDGET_BYTES")
    if env:
        try:
            return int(env)
        except ValueError:
            raise ValueError(
                f"SCTOOLS_MEM_BUDGET_BYTES={env!r} is not an integer "
                f"byte count") from None
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
    except Exception:  # pragma: no cover - backend without stats
        return None
    if isinstance(stats, dict) and stats.get("bytes_limit"):
        return int(stats["bytes_limit"])
    return None


class MemoryBudget:
    """A per-backend device-memory reservation ledger (module
    docstring).

    Thread-safe.  Two reservation classes share one ledger:

    * DYNAMIC — one per dispatched run (reserved by the scheduler
      at dispatch, released at terminal or a preemption yield) or
      per run-scoped resident (the trainer's feed window);
    * STANDING (``standing=True``) — SERVICE-LIFETIME residents (the
      serving model).  Standing bytes are additionally
      subtracted from the capacity an ADMISSION ruling may promise
      (:meth:`admissible_bytes`): a run whose estimate cannot fit
      beside the residents at ZERO concurrency can never run here and
      is refused ``over_memory`` at the door.

    ``set_pressure(frac)`` shrinks the APPARENT capacity (chaos
    ``mem_pressure``, or an operator modelling fragmentation) for
    :meth:`fits` only — admission feasibility ignores pressure on
    purpose (pressure is transient; refusing admission over it would
    turn a soak blip into a hard reject).

    Reserving the same name again REPLACES the previous amount (how
    the serving tier tracks a model swap without a release window).
    """

    def __init__(self, capacity_bytes: int | None = None, *,
                 name: str = "device", metrics=None):
        if capacity_bytes is None:
            capacity_bytes = detect_budget_bytes()
        if capacity_bytes is None:
            raise ValueError(
                "MemoryBudget: no capacity — pass capacity_bytes=, "
                "set SCTOOLS_MEM_BUDGET_BYTES, or run on a platform "
                "whose devices report memory_stats()['bytes_limit']")
        if capacity_bytes < 1:
            raise ValueError("MemoryBudget: capacity must be >= 1 byte")
        self.name = str(name)
        self.capacity_bytes = int(capacity_bytes)
        self.metrics = (metrics if metrics is not None
                        else telemetry.default_registry())
        self._lock = threading.RLock()
        self._held: dict[str, dict] = {}   # name -> {bytes, tenant, standing}
        self._pressure = 1.0
        self.peak_reserved_bytes = 0
        self.metrics.gauge("mem.budget_bytes").set(self.capacity_bytes)
        self.metrics.gauge("mem.reserved_bytes").set(0)

    # -- pressure ------------------------------------------------------
    def set_pressure(self, frac: float) -> None:
        """Shrink the apparent capacity to ``frac`` of nameplate for
        dispatch-time :meth:`fits` rulings (chaos ``mem_pressure``).
        Reservations already held are untouched."""
        with self._lock:
            self._pressure = min(max(float(frac), 0.0), 1.0)

    def clear_pressure(self) -> None:
        with self._lock:
            self._pressure = 1.0

    @property
    def pressure(self) -> float:
        with self._lock:
            return self._pressure

    # -- ledger --------------------------------------------------------
    def _reserved_locked(self, standing_only: bool = False) -> int:
        return sum(r["bytes"] for r in self._held.values()
                   if r["standing"] or not standing_only)

    def reserved_bytes(self) -> int:
        with self._lock:
            return self._reserved_locked()

    def standing_bytes(self) -> int:
        with self._lock:
            return self._reserved_locked(standing_only=True)

    def available_bytes(self) -> int:
        """What a dispatch may still reserve right now — apparent
        (pressure-scaled) capacity minus everything held."""
        with self._lock:
            return int(self.capacity_bytes * self._pressure) \
                - self._reserved_locked()

    def admissible_bytes(self) -> int:
        """The largest estimate admission may promise to EVER run:
        nameplate capacity minus the standing residents.  Pressure is
        deliberately excluded (transient; see class docstring)."""
        with self._lock:
            return self.capacity_bytes \
                - self._reserved_locked(standing_only=True)

    def fits(self, nbytes: int) -> bool:
        return int(nbytes) <= self.available_bytes()

    def reserve(self, name: str, nbytes: int, *,
                tenant: str | None = None,
                standing: bool = False) -> int:
        """Hold ``nbytes`` under ``name`` (replacing any previous
        hold of that name).  Returns total reserved bytes after."""
        nbytes = max(int(nbytes), 0)
        with self._lock:
            self._held[str(name)] = {"bytes": nbytes, "tenant": tenant,
                                     "standing": bool(standing)}
            total = self._reserved_locked()
            if total > self.peak_reserved_bytes:
                self.peak_reserved_bytes = total
            # gauge set INSIDE the lock: two racing mutations setting
            # it after release would leave the last writer's stale
            # total standing until the next mutation
            self.metrics.gauge("mem.reserved_bytes").set(total)
        return total

    def release(self, name: str) -> int:
        """Drop the hold under ``name`` (idempotent).  Returns total
        reserved bytes after."""
        with self._lock:
            self._held.pop(str(name), None)
            total = self._reserved_locked()
            self.metrics.gauge("mem.reserved_bytes").set(total)
        return total

    def holders(self) -> dict:
        """Report-ready ledger view: ``{name: {bytes, tenant,
        standing}}``."""
        with self._lock:
            return {k: dict(v) for k, v in self._held.items()}

    def snapshot(self) -> dict:
        with self._lock:
            return {"name": self.name,
                    "capacity_bytes": self.capacity_bytes,
                    "reserved_bytes": self._reserved_locked(),
                    "standing_bytes":
                        self._reserved_locked(standing_only=True),
                    "peak_reserved_bytes": self.peak_reserved_bytes,
                    "pressure": self._pressure,
                    "holders": {k: dict(v)
                                for k, v in self._held.items()}}

    def __repr__(self):
        s = self.snapshot()
        return (f"MemoryBudget({self.name!r}, "
                f"{s['reserved_bytes']}/{s['capacity_bytes']} bytes "
                f"reserved, pressure={s['pressure']:g})")


# ---------------------------------------------------------------------------
# Thread-local budget handoff (the scheduler-worker → op seam)
# ---------------------------------------------------------------------------

_BUDGETS = threading.local()


def _budget_stack() -> list:
    stack = getattr(_BUDGETS, "stack", None)
    if stack is None:
        stack = _BUDGETS.stack = []
    return stack


class budget_scope:
    """Make ``budget`` the current memory budget for the enclosed
    block ON THIS THREAD (the scheduler worker installs its pool's
    budget around each dispatched run; ``current_budget()`` deep
    inside an op — the streaming trainer's feed — finds it without
    parameter plumbing)."""

    def __init__(self, budget: MemoryBudget | None):
        self.budget = budget

    def __enter__(self):
        _budget_stack().append(self.budget)
        return self.budget

    def __exit__(self, *exc):
        _budget_stack().remove(self.budget)
        return False


def current_budget() -> MemoryBudget | None:
    stack = _budget_stack()
    return stack[-1] if stack else None
