"""``sct.settings`` / ``sct.logging`` — the scanpy session-config
surface, so a switched script's first lines keep working
(``sc.settings.verbosity = 3``, ``sc.settings.set_figure_params(...)``,
``sc.logging.print_header()``).

Capability parity: scanpy ships a module-level settings object
consulted by its plotting and logging; the reference source was
unavailable (/root/reference empty — SURVEY.md §0), so the public
scanpy attribute names are the contract.  Only the attributes that
change observable behavior HERE are live: ``figdir`` + ``dpi_save``
feed ``sct.pl``'s save path/resolution, ``set_figure_params`` applies
matplotlib rcParams, ``verbosity`` gates the ``info``/``hint``
helpers.  The rest (``n_jobs``, ``autoshow``, ...) are accepted and
stored — harness knobs other libraries read from scanpy don't apply
to a jit-compiled TPU pipeline, and silently dropping an assignment
would be worse than holding the value.
"""

from __future__ import annotations

import sys


class _Settings:
    def __init__(self):
        self.verbosity: int = 1
        self.figdir: str = "./figures/"
        self.file_format_figs: str = "pdf"
        self.autoshow: bool = True
        self.autosave: bool = False
        self.n_jobs: int = 1
        self.dpi: int = 80
        self.dpi_save: int = 150

    def set_figure_params(self, dpi: int = 80, dpi_save: int = 150,
                          figsize=None, facecolor=None,
                          frameon: bool = True, fontsize: int = 14,
                          color_map: str | None = None,
                          format: str = "pdf",
                          transparent: bool = False, **_ignored):
        """Apply scanpy's figure defaults to matplotlib rcParams (the
        subset that exists in matplotlib; unknown scanpy-only kwargs
        are accepted and ignored, stated here rather than hidden)."""
        self.dpi, self.dpi_save = int(dpi), int(dpi_save)
        self.file_format_figs = format
        try:
            import matplotlib as mpl
        except ImportError:  # plotting remains optional
            return
        rc = {"figure.dpi": dpi, "savefig.dpi": dpi_save,
              "savefig.transparent": transparent,
              "font.size": fontsize, "axes.spines.top": frameon,
              "axes.spines.right": frameon}
        if figsize is not None:
            rc["figure.figsize"] = figsize
        if facecolor is not None:
            rc["figure.facecolor"] = facecolor
            rc["axes.facecolor"] = facecolor
        if color_map is not None:
            rc["image.cmap"] = color_map
        mpl.rcParams.update(rc)


settings = _Settings()


def _versions() -> dict:
    import importlib.metadata as md

    out = {"python": sys.version.split()[0]}
    for pkg in ("jax", "jaxlib", "numpy", "scipy", "h5py"):
        try:
            out[pkg] = md.version(pkg)
        except md.PackageNotFoundError:
            pass
    return out


def print_header(*, file=None) -> None:
    """scanpy ``sc.logging.print_header`` analogue: one line of
    dependency versions."""
    vs = _versions()
    print(" ".join(f"{k}=={v}" for k, v in vs.items()),
          file=file or sys.stdout)


def print_versions(*, file=None) -> None:
    print_header(file=file)


def info(*msg) -> None:
    if settings.verbosity >= 2:
        print(*msg, file=sys.stderr)


def hint(*msg) -> None:
    if settings.verbosity >= 3:
        print(*msg, file=sys.stderr)
