"""``sct.queries`` — the offline-answerable subset of scanpy's
``sc.queries``.

scanpy's queries hit Ensembl BioMart over the network; this
environment has none.  What CAN be answered offline is the question
people actually ask these helpers: "which genes are mitochondrial" —
the 13 protein-coding mtDNA genes are a fixed, organism-stable list,
and the standard nomenclature prefix ("MT-" human / "mt-" mouse)
covers the full mitochondrial transcript set in CellRanger
references.  Anything genuinely requiring BioMart raises with the
honest reason.
"""

from __future__ import annotations

import numpy as np

# The 13 protein-coding genes of the human mitochondrial genome
# (HGNC symbols).  Mouse uses the same set, lowercase-prefixed.
_MT_PROTEIN_CODING = (
    "ND1", "ND2", "ND3", "ND4", "ND4L", "ND5", "ND6",
    "CO1", "CO2", "CO3", "ATP6", "ATP8", "CYB",
)


def mitochondrial_genes(org: str = "hsapiens") -> list[str]:
    """The 13 protein-coding mitochondrial gene symbols for human
    (``MT-*``) or mouse (``mt-*``).  For masking rRNA/tRNA transcripts
    too, prefer :func:`mitochondrial_mask` — the name PREFIX covers
    the whole mt chromosome in CellRanger references."""
    if org in ("hsapiens", "human", "hg38", "hg19"):
        return [f"MT-{g}" for g in _MT_PROTEIN_CODING]
    if org in ("mmusculus", "mouse", "mm10", "mm39"):
        return [f"mt-{g.capitalize()}" for g in _MT_PROTEIN_CODING]
    raise ValueError(
        f"mitochondrial_genes: unknown organism {org!r} (offline "
        f"support: hsapiens/human, mmusculus/mouse; other organisms "
        f"need scanpy's BioMart query, which requires network)")


def mitochondrial_mask(data, org: str = "hsapiens") -> np.ndarray:
    """Boolean per-gene mask of mitochondrial genes — the SAME
    implementation ``qc.per_cell_metrics`` uses (case-insensitive
    ``MT-`` prefix, honouring a curated ``var['mito']`` column), so
    the two can never disagree on one dataset.  ``org`` is validated
    for API parity but doesn't change the mask: the prefix rule is
    case-insensitive, covering human ``MT-`` and mouse ``mt-``."""
    if org not in ("hsapiens", "human", "hg38", "hg19",
                   "mmusculus", "mouse", "mm10", "mm39"):
        raise ValueError(
            f"mitochondrial_mask: unknown organism {org!r} (offline "
            f"support: hsapiens/human, mmusculus/mouse)")
    from .ops.qc import _mito_mask

    mask = _mito_mask(data)
    if mask is None:
        raise KeyError("mitochondrial_mask: data has neither "
                       "var['gene_name'] nor var['mito']")
    return np.asarray(mask, bool)


def _network_required(name: str):
    def f(*a, **kw):
        raise RuntimeError(
            f"sct.queries.{name}: scanpy answers this via an Ensembl "
            f"BioMart query, which needs network access this "
            f"environment does not have")
    f.__name__ = name
    return f


biomart_annotations = _network_required("biomart_annotations")
gene_coordinates = _network_required("gene_coordinates")
enrich = _network_required("enrich")
