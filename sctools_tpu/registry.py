"""The ``Transform`` operator registry.

The reference framework (dpeerlab/sctools — source unavailable, see
SURVEY.md) organises all per-cell/per-gene operations as named
transforms in a registry, selected at call time with a ``backend=``
kwarg (BASELINE.json ``north_star``).  This module provides that
surface, TPU-first:

* ops register under dotted names (``"normalize.log1p"``) per backend
  (``"cpu"`` = numpy/scipy oracle, ``"tpu"`` = JAX/XLA/Pallas);
* ``apply(name, data, backend=...)`` dispatches a single op;
* ``Transform(name, backend=..., **params)`` is a bound, reusable op;
* ``Pipeline([...])`` composes transforms sequentially; each TPU op is
  itself jit-compiled, and device arrays flow between ops without
  host round-trips (materialisation points like ``subset=True``
  filters excepted).

The ``"tpu"`` backend is pure JAX: it runs on whatever
``jax.default_backend()`` is (real TPU chips in production, the CPU
emulator in tests) — semantics are identical, the name records the
design target.
"""

from __future__ import annotations

import contextlib
import functools
import threading
from typing import Any, Callable

_REGISTRY: dict[str, dict[str, Callable]] = {}
_DOCS: dict[str, str] = {}

# fusability metadata: name -> backend -> True | predicate(params)->bool.
# A FUSABLE implementation is traceable end-to-end by jax.jit on a
# device-resident CellData — no host syncs (np.asarray on results, data
# -dependent output shapes, host-side loops).  The plan layer
# (plan.py) compiles maximal runs of consecutive fusable transforms
# into ONE program; everything else stays an eager dispatch and forms
# a fusion break.  Param-dependent cases (hvg.select's subset=True
# materialisation) register a predicate instead of True.
_FUSABLE: dict[str, dict[str, object]] = {}

# mesh-execution metadata (plan.py's sharded stages):
#
# _SHARDING: name -> backend -> "cells" | "replicated" |
#   predicate(params)->str.  Declares how the op's OUTPUT leaves
#   should be partitioned when the op runs inside a mesh-sharded
#   fused stage ("cells" = leading axis sharded over the cell mesh
#   axis where divisible, the default heuristic; "replicated" = every
#   output leaf replicated).  Consecutive fused stages apply the same
#   rule to their in_shardings, which is what keeps stage boundaries
#   reshard-free (SNIPPETS pjit contract: outputs of one compiled
#   stage arrive pre-partitioned to match the next's in_shardings).
#
# _COLLECTIVE: name -> backend -> True | predicate(params)->bool.
# Declares that the implementation carries its OWN collective body
# (shard_map / ppermute ring — e.g. neighbors.knn_multichip) instead
# of relying on GSPMD sharding propagation: the plan layer must not
# trace it into a pjit stage but wrap it as a single sharded stage
# that threads the plan's mesh into the call (plan.ShardedCollective).
_SHARDING: dict[str, dict[str, object]] = {}
_COLLECTIVE: dict[str, dict[str, object]] = {}

# memory-domain metadata (sctools_tpu/memory.py's estimate model and
# the runner's OOM containment ladder):
#
# _MEM_COST: name -> backend -> number | callable(params, input_bytes)
#   -> bytes.  Declares the op's PEAK device-memory footprint for the
#   admission estimator: a number is a multiplier over the input's
#   array bytes (2.0 = inputs resident + a same-sized output, the
#   default for unregistered ops), a callable computes peak BYTES
#   from the bound params and the input size.  Estimates learned from
#   compiled programs (``memory_analysis()``) and OOM corrections
#   override the heuristic once observed.
#
# _MEM_SHRINK: name -> backend -> callable(params) -> params | None.
#   Declares how to RE-PLAN the op at a smaller live set — the OOM
#   ladder's middle rung (halve a batch/tile/block param; return None
#   when already at the floor).  Must preserve results: shrinking may
#   only change HOW the op tiles its work, never what it computes.
_MEM_COST: dict[str, dict[str, object]] = {}
_MEM_SHRINK: dict[str, dict[str, object]] = {}

# _MASK_AWARE: name -> backend -> True | predicate(params)->bool.
# Declares the implementation honours the bucket-validity convention
# (sctools_tpu/buckets.py): when the data carries bucket masks the op
# restricts every reduction to valid rows/genes (masked medians,
# count-corrected moments, neighbor candidates clipped to valid rows)
# so padded results equal unpadded results on the valid region.  A
# predicate gates parameterisations that change shapes (e.g.
# hvg.select's subset=True materialisation) out of the bucketized
# path.  recipes.run_recipe(bucketize=True) refuses pipelines with a
# non-mask-aware step.
_MASK_AWARE: dict[str, dict[str, object]] = {}

DEFAULT_BACKEND = "tpu"

# ---------------------------------------------------------------------------
# Call wrappers — the registry's run hooks.
#
# A wrapper is ``wrapper(name, backend, fn) -> fn`` applied around every
# transform invocation (``apply()``, ``Transform.__call__``, and
# therefore every ``Pipeline``/recipe step) while it is installed.
# This is the ONE interception point every cross-cutting layer shares:
# the chaos fault-injection harness (utils/chaos.py), the runner's
# cooperative deadline check (runner._deadline_wrap), and the
# telemetry auto-instrumentor (utils/telemetry.py CallInstrumentor —
# per-op call/error/duration metrics).  Installation is dynamic, so
# already-constructed Transforms/Pipelines are covered — the wrap
# happens at call time, not at bind time.  Wrappers stack; the most
# recently pushed runs outermost (the runner pushes chaos, then the
# deadline check, then telemetry, so an op's recorded duration
# includes an injected wedge and its deadline raise counts as that
# op's error).
#
# Two scopes: GLOBAL wrappers (the default — chaos faults must fire
# on every thread's calls) and THREAD-LOCAL wrappers
# (``thread_local=True``).  The scheduler's worker pool runs several
# ResilientRunners concurrently; each run's deadline check and
# telemetry instrumentor install thread-locally so run A's wrappers
# never wrap (or double-count) run B's op calls.  Thread-local
# wrappers run OUTERMOST relative to globals — the same composition
# a single-threaded runner always had (chaos innermost, telemetry
# outermost).
# ---------------------------------------------------------------------------

_CALL_WRAPPERS: list[Callable[[str, str, Callable], Callable]] = []
_TLS_WRAPPERS = threading.local()


def _thread_wrappers() -> list:
    ws = getattr(_TLS_WRAPPERS, "stack", None)
    if ws is None:
        ws = _TLS_WRAPPERS.stack = []
    return ws


def push_call_wrapper(wrapper: Callable[[str, str, Callable], Callable],
                      thread_local: bool = False) -> None:
    (_thread_wrappers() if thread_local else _CALL_WRAPPERS) \
        .append(wrapper)


def pop_call_wrapper(wrapper: Callable[[str, str, Callable], Callable],
                     thread_local: bool = False) -> None:
    (_thread_wrappers() if thread_local else _CALL_WRAPPERS) \
        .remove(wrapper)


@contextlib.contextmanager
def call_wrapper(wrapper: Callable[[str, str, Callable], Callable],
                 thread_local: bool = False):
    """Scoped installation: ``with call_wrapper(w): pipeline.run(...)``.
    ``thread_local=True`` scopes the wrapper to the calling thread —
    concurrent runs on other threads are not wrapped by it."""
    push_call_wrapper(wrapper, thread_local=thread_local)
    try:
        yield
    finally:
        pop_call_wrapper(wrapper, thread_local=thread_local)


def _active_wrappers() -> list:
    tls = getattr(_TLS_WRAPPERS, "stack", None)
    if tls:
        return _CALL_WRAPPERS + tls
    return _CALL_WRAPPERS


def _wrap_call(name: str, backend: str, fn: Callable) -> Callable:
    for w in _active_wrappers():
        fn = w(name, backend, fn)
    return fn


class UnknownTransformError(KeyError):
    pass


class UnknownBackendError(KeyError):
    pass


def register(name: str, backend: str = "tpu",
             fusable=False, sharding=None, collective=False,
             mem_cost=None,
             mem_shrink=None,
             mask_aware=False) -> Callable[[Callable], Callable]:
    """Decorator: register ``fn`` as the implementation of ``name`` for
    ``backend``.

    ``fusable`` declares the implementation traceable end-to-end by
    ``jax.jit`` on device-resident data (no host syncs, no
    data-dependent output shapes) — the opt-in that lets ``plan.py``
    compile it into a fused multi-op program.  Pass ``True``, or a
    ``predicate(params) -> bool`` when fusability depends on the bound
    parameters (e.g. ``hvg.select``'s ``subset=True`` materialisation
    point).

    ``sharding`` ("cells" | "replicated" | predicate(params)->str)
    declares how the op's output leaves partition over a cell mesh
    when it runs inside a mesh-sharded fused stage; unset means the
    plan layer's default heuristic (leading axis cells-sharded where
    it divides the mesh).  ``collective`` (True | predicate) declares
    the implementation carries its own collective body (shard_map /
    ppermute) — the plan layer then wraps it as a single sharded
    stage, threading the plan's mesh into the call, instead of
    tracing it under GSPMD.

    ``mem_cost`` (number | ``callable(params, input_bytes) -> bytes``)
    declares the op's peak device-memory footprint for the memory
    fault domain's admission estimator (``sctools_tpu/memory.py``): a
    number is a multiplier over the input's array bytes, a callable
    computes peak bytes outright.  ``mem_shrink``
    (``callable(params) -> params | None``) declares how to re-plan
    the op at a smaller live set — the OOM containment ladder's
    middle rung (halve a batch/tile param; ``None`` = at the floor).
    A shrink must preserve results: it may change how the op tiles
    its work, never what it computes.

    ``mask_aware`` (True | ``predicate(params) -> bool``) declares the
    implementation honours the bucket-validity convention
    (``sctools_tpu/buckets.py``): on data carrying bucket masks it
    restricts reductions to valid rows/genes so padded results equal
    unpadded results on the valid region.  The gate
    ``recipes.run_recipe(bucketize=True)`` checks before padding.

    >>> @register("normalize.log1p", backend="tpu", fusable=True)
    ... def log1p_tpu(data, **kw): ...
    """

    def deco(fn: Callable) -> Callable:
        _REGISTRY.setdefault(name, {})[backend] = fn
        if fusable:
            _FUSABLE.setdefault(name, {})[backend] = fusable
        if sharding is not None:
            _SHARDING.setdefault(name, {})[backend] = sharding
        if collective:
            _COLLECTIVE.setdefault(name, {})[backend] = collective
        if mem_cost is not None:
            _MEM_COST.setdefault(name, {})[backend] = mem_cost
        if mem_shrink is not None:
            _MEM_SHRINK.setdefault(name, {})[backend] = mem_shrink
        if mask_aware:
            _MASK_AWARE.setdefault(name, {})[backend] = mask_aware
        if fn.__doc__ and name not in _DOCS:
            _DOCS[name] = fn.__doc__
        return fn

    return deco


def is_fusable(name: str, backend: str, params: dict | None = None) -> bool:
    """True when the ``(name, backend)`` implementation declared itself
    jit-traceable (``register(..., fusable=...)``) for these bound
    parameters — the plan layer's fusion eligibility test."""
    f = _FUSABLE.get(name, {}).get(backend, False)
    if callable(f):
        return bool(f(dict(params or {})))
    return bool(f)


def is_mask_aware(name: str, backend: str,
                  params: dict | None = None) -> bool:
    """True when the ``(name, backend)`` implementation declared it
    honours the bucket-validity mask convention
    (``register(..., mask_aware=...)``) for these bound parameters —
    the bucketized-recipe eligibility test."""
    a = _MASK_AWARE.get(name, {}).get(backend, False)
    if callable(a):
        return bool(a(dict(params or {})))
    return bool(a)


def is_collective(name: str, backend: str,
                  params: dict | None = None) -> bool:
    """True when the ``(name, backend)`` implementation declared a
    collective body (``register(..., collective=...)``): the plan
    layer runs it as its own sharded stage (mesh threaded into the
    call) rather than tracing it into a pjit program."""
    c = _COLLECTIVE.get(name, {}).get(backend, False)
    if callable(c):
        return bool(c(dict(params or {})))
    return bool(c)


def sharding_of(name: str, backend: str,
                params: dict | None = None) -> str | None:
    """The op's declared output-partitioning rule over a cell mesh
    (``"cells"`` / ``"replicated"``), or ``None`` when the op left it
    to the plan layer's default heuristic."""
    s = _SHARDING.get(name, {}).get(backend)
    if callable(s):
        s = s(dict(params or {}))
    if s is not None and s not in ("cells", "replicated"):
        raise ValueError(
            f"transform {name!r} declared sharding={s!r}; "
            f"use 'cells' or 'replicated'")
    return s


def mem_cost_of(name: str, backend: str, params: dict | None = None,
                input_bytes: int | None = None):
    """The op's declared peak-memory cost, or ``None`` when
    unregistered.  Returns a tagged tuple: ``("mult", m)`` for a
    numeric multiplier over input bytes, ``("bytes", n)`` for a
    callable evaluated against the bound params and ``input_bytes``.
    A callable with no ``input_bytes`` to evaluate against returns
    ``None`` — the caller falls back to the default multiplier."""
    c = _MEM_COST.get(name, {}).get(backend)
    if c is None:
        return None
    if callable(c):
        if input_bytes is None:
            return None
        return ("bytes", int(c(dict(params or {}), int(input_bytes))))
    return ("mult", float(c))


def mem_shrink_of(name: str, backend: str,
                  params: dict | None = None) -> dict | None:
    """Re-planned params for the op at a smaller live set (the OOM
    ladder's middle rung), or ``None`` when the op registered no
    ``mem_shrink`` or is already at its floor.  Identical returned
    params also count as the floor — a 'shrink' that changes nothing
    would loop the ladder forever."""
    s = _MEM_SHRINK.get(name, {}).get(backend)
    if s is None:
        return None
    old = dict(params or {})
    new = s(dict(old))
    if new is None or dict(new) == old:
        return None
    return dict(new)


def get(name: str, backend: str = DEFAULT_BACKEND) -> Callable:
    try:
        impls = _REGISTRY[name]
    except KeyError:
        raise UnknownTransformError(
            f"no transform named {name!r}; known: {sorted(_REGISTRY)}"
        ) from None
    try:
        return impls[backend]
    except KeyError:
        raise UnknownBackendError(
            f"transform {name!r} has no {backend!r} backend; "
            f"available: {sorted(impls)}"
        ) from None


def names(backend: str | None = None) -> list[str]:
    if backend is None:
        return sorted(_REGISTRY)
    return sorted(n for n, impls in _REGISTRY.items() if backend in impls)


def backends(name: str) -> list[str]:
    return sorted(_REGISTRY.get(name, {}))


def describe(name: str) -> str:
    return _DOCS.get(name, "")


def apply(name: str, data, *args, backend: str = DEFAULT_BACKEND, **kw):
    """Apply a registered transform to ``data`` and return the result."""
    fn = get(name, backend)
    if _active_wrappers():
        fn = _wrap_call(name, backend, fn)
    return fn(data, *args, **kw)


class Transform:
    """A named operator bound to a backend and fixed parameters.

    Mirrors the reference's ``Transform`` objects: construct once,
    apply to many datasets.

    >>> t = Transform("normalize.library_size", backend="tpu", target_sum=1e4)
    >>> out = t(celldata)
    """

    def __init__(self, name: str, backend: str = DEFAULT_BACKEND, **params):
        self.name = name
        self.backend = backend
        self.params = params
        self._fn = get(name, backend)  # fail fast on unknown name/backend

    def __call__(self, data, **overrides):
        kw = {**self.params, **overrides}
        fn = self._fn
        if _active_wrappers():
            fn = _wrap_call(self.name, self.backend, fn)
        return fn(data, **kw)

    def with_backend(self, backend: str) -> "Transform":
        return Transform(self.name, backend=backend, **self.params)

    def __repr__(self):
        ps = ", ".join(f"{k}={v!r}" for k, v in self.params.items())
        return f"Transform({self.name!r}, backend={self.backend!r}{', ' + ps if ps else ''})"


class Pipeline:
    """An ordered chain of transforms applied to a dataset.

    Steps are ``(name, params)`` tuples or ``Transform`` objects.  The
    same pipeline runs on any backend: ``backend=`` at ``run()`` time
    overrides per-step backends, which is how the CPU oracle validates
    the TPU path in tests.
    """

    def __init__(self, steps, backend: str | None = None):
        self.steps: list[Transform] = []
        for step in steps:
            if isinstance(step, Transform) or (
                    callable(step) and hasattr(step, "name")
                    and hasattr(step, "backend")
                    and hasattr(step, "params")):
                # Transform, or a Transform-alike (plan.FusedTransform)
                self.steps.append(step)
            elif isinstance(step, str):
                self.steps.append(Transform(step, backend=backend or DEFAULT_BACKEND))
            else:
                name, params = step
                self.steps.append(
                    Transform(name, backend=backend or DEFAULT_BACKEND, **params)
                )

    def run(self, data, backend: str | None = None, fuse: bool = False):
        """Run all steps.  ``fuse=True`` first compiles the chain into
        fused execution stages (``plan.fused_pipeline``): maximal runs
        of consecutive jit-traceable device transforms execute as ONE
        cached compiled program — repeated invocations with the same
        op chain, params and shapes skip retrace entirely.  For retry,
        fault containment and resume, run the pipeline under
        ``sctools_tpu.runner.ResilientRunner`` instead — this loop
        dies on the first error by design."""
        if fuse:
            from .plan import fused_pipeline

            return fused_pipeline(self, backend=backend).run(data)
        for t in self.steps:
            if backend is not None and backend != t.backend:
                t = t.with_backend(backend)
            data = t(data)
        return data

    def __iter__(self):
        return iter(self.steps)

    def __repr__(self):
        return "Pipeline([\n  " + ",\n  ".join(map(repr, self.steps)) + "\n])"
