"""Pod-scale fault domains: process supervision, federated admission,
lost-worker degrade.

Everything below PR 9 survives failures INSIDE one process — retry,
breaker, degrade, quarantine, resume all assume the Python process
hosting the run stays alive.  A worker process that dies takes its
runs, its breaker observations and its journal with it; at pod scale
(and at "serve millions of users" scale) process death is the common
case, not the exception.  This module extends the fault-containment
ladder ACROSS process boundaries:

* **Process supervision** — :class:`FederationSupervisor` spawns N
  worker subprocesses (:func:`worker_main`), each running a
  ``RunScheduler`` worker loop and holding a LEASE: a heartbeat
  stream whose age is measured on the supervisor's injectable clock
  (``utils/vclock.py``).  A missed lease — or a reaped exit —
  classifies the worker :data:`PROCESS_LOST`; the supervisor FENCES
  it (epoch bump + fence file + SIGKILL), requeues its in-flight
  tickets, journals ``worker_lost`` with the dead worker's journal
  tail grafted in, and respawns a replacement (``worker_respawned``).
* **At-most-once requeue** — a requeued ticket keeps its checkpoint
  directory, so the new owner's ``ResilientRunner`` RESUMES from the
  checkpoint fingerprint instead of replaying completed stages
  (non-idempotent work runs at most once); acceptance is guarded by
  the ticket EPOCH — only the current epoch's result commits, so a
  fenced worker that comes back from a partition can never
  double-commit (``commit_refused``).
* **Federated admission** — tenant queue quotas, pool-wide in-flight
  quotas and the queue high-water mark are enforced at the
  federation tier (same admission funnel and journal shape as
  ``scheduler.RunScheduler``: every ticket is terminal in exactly one
  of ``completed | failed | rejected | shed`` even when its worker
  died mid-run), and per-backend circuit-breaker state crosses
  processes through :class:`FederatedBreakerRegistry` — file-plane
  state files and/or ``breaker`` messages on a
  :class:`~sctools_tpu.transport.SocketTransport`, same
  ``BreakerRegistry`` API either way — so one worker's breaker trip
  short-circuits every OTHER worker's admission to the accelerator
  (the PR-8 pre-attempt gate, now pool-wide).
* **Transports** — every worker↔supervisor message (heartbeat, done
  doorbell, refusal, breaker transition) rides the
  ``sctools_tpu.transport`` seam: the stderr line protocol
  (``FileTransport``) by default, length-prefixed TCP frames
  (``SocketTransport``, ``transport="socket"``) where workers span
  hosts without a shared stderr — with graceful degradation (a lost
  doorbell falls back to the result-file probe, a partitioned
  worker's breakers go LOCAL-ONLY, leases ride out delay up to
  ``lease_timeout_s``) and epoch-fenced reconciliation on heal.
* **Chaos** — ``kill_worker`` (SIGKILL at the Nth heartbeat) and
  ``lease_wedge`` (worker alive, heartbeats withheld: the split-brain
  partition) fire through ``ChaosMonkey.on_worker``, so the whole
  reap → fence → requeue → respawn ladder is tier-1 testable.

Clock discipline matches ``data/shardstore.py``: every lease/age
SCHEDULE is arithmetic on the injectable clock (tests drive a
``VirtualClock`` and never really sleep), while waits on REAL
subprocesses are event-driven (pipe pumps, process reaps, completion
events) so virtual time never races real work.  Wall-clock
``time.time()`` appears only in journal facts, as everywhere else.

>>> from sctools_tpu.federation import FederationSupervisor
>>> with FederationSupervisor(fed_dir, n_workers=2) as sup:
...     h = sup.submit(pipeline, data, tenant="lab-a")
...     out = h.result()          # survives a SIGKILLed worker
"""

from __future__ import annotations

import contextlib
import fnmatch
import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
import warnings

from .registry import Pipeline, Transform
from .runner import DEFAULT_FALLBACK_BACKEND, _Journal
from .scheduler import (RunRejected, RunShed,  # noqa: F401
                        TERMINAL_STATES, new_trace_id)
from .transport import (FileTransport, SocketTransport,
                        LINE_RE, LOSSY_KINDS, parse_fields)
from .utils import telemetry, trace
from .utils.checkpoint import load_celldata, save_celldata
from .utils.failsafe import BreakerRegistry, CircuitBreaker
from .utils.vclock import SYSTEM_CLOCK

#: the new failure kind this tier introduces: the WORKER PROCESS is
#: gone (reaped exit or expired lease) — not any single step.  Runs
#: in flight on a lost worker are requeued, not failed: from the
#: ticket's point of view process death is transient.
PROCESS_LOST = "process_lost"

#: worker → supervisor protocol: one stderr line per event, pumped by
#: a per-worker thread.  The codec lives in ``sctools_tpu.transport``
#: (the FileTransport wire format); anything not matching is worker
#: noise (jax logging etc.) and deliberately does NOT refresh the
#: lease — only explicit beats prove the worker LOOP is alive, not
#: just the process.
_LINE_RE = LINE_RE
_parse_fields = parse_fields

#: the worker's default message plane: one protocol line per message
#: on stderr (read by the supervisor's per-worker pump thread), with
#: emission serialized across the heartbeat thread and the main loop
#: by the transport's internal lock.
_SAY_TRANSPORT = FileTransport("worker")


def _say(kind: str, **fields) -> None:
    """Worker-side: emit one protocol message on the default
    (stderr-line) transport."""
    if kind == "done" and os.environ.get("SCT_FED_TEST_MUTE_DONE"):
        # test hook: simulate the lost-commit-message transport fault
        # (the worker still commits the result file and keeps
        # beating) — exercises the supervisor's result-file recovery
        return
    _SAY_TRANSPORT.send("supervisor", kind, **fields)


# ---------------------------------------------------------------------------
# Federated circuit breakers: the cross-process transport
# ---------------------------------------------------------------------------

def _safe_name(sig: str) -> str:
    return re.sub(r"[^A-Za-z0-9._-]", "_", str(sig)) or "_"


class FederatedBreaker(CircuitBreaker):
    """A :class:`~sctools_tpu.utils.failsafe.CircuitBreaker` whose
    OPEN/CLOSED transitions replicate across processes through a
    shared state file.

    The file carries ``{epoch, state, owner, ts}``; ``epoch`` is a
    monotonic transition counter.  Every state read first applies any
    UNSEEN remote transition (``open`` → force the local breaker open
    with a fresh cooldown on the LOCAL clock; ``closed`` → close and
    clear the window), and every local transition publishes
    ``epoch+1`` under a lock directory.  Cooldowns therefore run on
    each process's own clock from the moment IT observed the open —
    cross-process monotonic timestamps are never compared (their
    bases differ, and tests drive one side with a ``VirtualClock``).

    The half-open probe slot is exclusive ACROSS processes too: a
    ``.probe`` claim file (O_EXCL) backs the local claim, released by
    the verdict paths; a claim older than ``probe_stale_s``
    (wall-clock fact) is broken — its owner died without a verdict.

    ``store_dir=None`` drops the file plane entirely (no shared
    filesystem): transitions then replicate only through the
    registry's transport (``on_transition``) and inbound
    :meth:`apply_remote` messages, and the probe slot is exclusive
    within this process only.
    """

    def __init__(self, *args, store_dir: str | None, owner: str = "",
                 metrics=None, journal=None,
                 probe_stale_s: float = 600.0, on_transition=None,
                 **kw):
        super().__init__(*args, **kw)
        self._dir = store_dir
        self._owner = owner
        self._metrics = metrics
        self._journal = journal
        self._probe_stale_s = float(probe_stale_s)
        if store_dir is None:
            self._file = None
            self._probe_file = None
        else:
            base = _safe_name(self.signature)
            self._file = os.path.join(store_dir, base + ".json")
            self._probe_file = os.path.join(store_dir, base + ".probe")
        self._holds_probe_file = False
        self._seen_epoch = 0
        #: ``on_transition(signature, state, epoch)`` — the registry's
        #: transport broadcast.  NEVER called under the breaker lock:
        #: _publish RECORDS the transition in _pending_remote and the
        #: verdict paths flush after release (a transport send retries
        #: and backs off — that latency must not serialize every
        #: sharer of this breaker)
        self._on_transition = on_transition
        self._pending_remote: list[tuple[str, int]] = []

    # -- remote sync ---------------------------------------------------
    def _refresh(self) -> None:
        """Apply any unseen remote transition (caller holds the
        lock — a contract the call graph now PROVES, so no
        locked-by-caller annotation is needed)."""
        # sctlint: io-under-lock — reading the shared state file IS
        # the sync step: it must happen inside the same lock hold as
        # the ruling that consumes it, or a remote `open` could land
        # between the read and the local decision
        if self._file is None:
            return  # no file plane: apply_remote is the only inbound
        try:
            with open(self._file) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            return  # no remote state yet / torn read: next ruling wins
        ep = int(rec.get("epoch", 0))
        if ep <= self._seen_epoch:
            return
        self._seen_epoch = ep
        st = rec.get("state")
        if st == "open":
            # force open with a FRESH local cooldown — a re-published
            # open (another process's probe failed) restarts it too
            self._state = self.OPEN
            self._opened_at = self.clock.monotonic()
            self._probe_claimed = False
            self.opened_count += 1
        elif st == "closed" and self._state != self.CLOSED:
            self._failures.clear()
            self._state = self.CLOSED
            self._opened_at = None
            self._probe_claimed = False
        else:
            return
        if self._metrics is not None:
            self._metrics.counter("fed.breaker_syncs",
                                  signature=self.signature,
                                  to=st).inc()

    def _publish(self, state: str) -> None:
        """Write a new transition epoch (caller holds the lock).
        Serialized across processes by a lock directory; a contended
        lock is retried briefly, then the write proceeds anyway —
        last-writer-wins on a torn race beats wedging the breaker's
        caller on a dead locker."""
        # sctlint: io-under-lock — the publish must be atomic with
        # the local transition it mirrors: dropping the breaker lock
        # between deciding `open` and writing it would let a sharer
        # read the stale state and re-close a breaker we just tripped
        if self._file is None:
            # no file plane: the epoch still advances (the transport
            # peers fence on it) and the transition queues for the
            # out-of-lock broadcast
            # deliberately NOT fence-checked: same advance-the-epoch
            # semantics as the file path below
            self._seen_epoch += 1  # sctlint: disable=SCT016
            self._pending_remote.append((state, self._seen_epoch))
            return
        lockdir = self._file + ".lock"
        held = False
        for _ in range(50):
            try:
                os.mkdir(lockdir)
                held = True
                break
            except FileExistsError:
                self.clock.sleep(0.01)
            except OSError:
                break  # store dir gone (teardown): nothing to publish
        try:
            ep = self._seen_epoch
            try:
                with open(self._file) as f:
                    ep = max(ep, int(json.load(f).get("epoch", 0)))
            except (OSError, ValueError):
                ep = max(ep, 0)
            rec = {"epoch": ep + 1, "state": state,
                   "owner": self._owner, "ts": round(time.time(), 3)}
            tmp = self._file + f".tmp.{os.getpid()}"
            try:
                with open(tmp, "w") as f:
                    json.dump(rec, f)
                os.replace(tmp, self._file)
                # deliberately NOT fence-checked: _publish ADVANCES
                # the epoch (fetch-max-increment under the lockdir,
                # last-writer-wins on a torn race per the docstring)
                # rather than committing under an existing one
                self._seen_epoch = ep + 1  # sctlint: disable=SCT016
                self._pending_remote.append((state, ep + 1))
            except OSError as e:
                warnings.warn(
                    f"FederatedBreaker: could not publish {state!r} "
                    f"for {self.signature!r} ({type(e).__name__}: "
                    f"{e}) — remote sharers will not see this "
                    "transition", RuntimeWarning, stacklevel=3)
        finally:
            if held:
                try:
                    os.rmdir(lockdir)
                except OSError:
                    pass  # already cleaned up: the lock was ours alone

    # -- CircuitBreaker overrides --------------------------------------
    @property
    def state(self) -> str:
        with self.lock:
            self._refresh()
            return CircuitBreaker.state.fget(self)

    def record_failure(self, probe: bool = True) -> str:
        with self.lock:
            prev = self.state  # includes the remote refresh
            st = super().record_failure(probe=probe)
            if st == self.OPEN and prev != self.OPEN:
                self._publish("open")
            if probe and self._holds_probe_file:
                self._drop_probe_file()
        self._notify_remote()
        return st

    def record_success(self) -> str:
        with self.lock:
            prev = self.state
            st = super().record_success()
            if prev != self.CLOSED:
                self._publish("closed")
            if self._holds_probe_file:
                self._drop_probe_file()
        self._notify_remote()
        return st

    def _notify_remote(self) -> None:
        """Broadcast transitions queued by ``_publish`` — AFTER the
        breaker lock is released: a transport send retries with
        backoff, and that latency must never serialize the sharers."""
        with self.lock:
            pending, self._pending_remote = self._pending_remote, []
        cb = self._on_transition
        if cb is None:
            return
        for state, epoch in pending:
            cb(self.signature, state, epoch)

    def apply_remote(self, state: str, epoch: int,
                     owner: str = "") -> bool:
        """Apply a transition delivered over a TRANSPORT — the
        socket-plane twin of ``_refresh``.  Epoch-fenced: a
        transition at or below the last seen epoch is REFUSED
        (returns False) — how a claimant that kept publishing behind
        a partition loses on heal instead of double-committing its
        stale verdict — and an accepted one replays the file plane's
        open/closed semantics (fresh LOCAL cooldown on ``open``)."""
        if state not in ("open", "closed"):
            return False  # unknown state word: refuse, don't guess
        ep = int(epoch)
        with self.lock:
            if ep <= self._seen_epoch:
                return False  # at/behind the fence: refused on arrival
            self._seen_epoch = ep
            if state == "open":
                self._state = self.OPEN
                self._opened_at = self.clock.monotonic()
                self._probe_claimed = False
                self.opened_count += 1
            elif self._state != self.CLOSED:
                self._failures.clear()
                self._state = self.CLOSED
                self._opened_at = None
                self._probe_claimed = False
        if self._metrics is not None:
            self._metrics.counter("fed.breaker_syncs",
                                  signature=self.signature,
                                  to=state).inc()
        return True

    def try_acquire_probe(self) -> bool:
        with self.lock:
            # ownership transfer by design: a claimed slot OUTLIVES
            # this method — the verdict paths (record_success /
            # record_failure / release_probe) are its release
            if not super().try_acquire_probe():  # sctlint: disable=SCT010
                return False
            if self._claim_probe_file():
                return True
            # another PROCESS holds the probe: give the local slot
            # back and treat the breaker as still open
            self._probe_claimed = False
            return False

    def release_probe(self) -> None:
        with self.lock:
            super().release_probe()
            if self._holds_probe_file:
                self._drop_probe_file()

    def snapshot(self) -> dict:
        with self.lock:
            self._refresh()
            snap = super().snapshot()
            snap["fed_epoch"] = self._seen_epoch
            return snap

    # -- probe claim file ----------------------------------------------
    def _claim_probe_file(self) -> bool:
        # sctlint: io-under-lock — the O_EXCL-style link IS the
        # cross-process probe claim; it must be decided in the same
        # lock hold that claimed the local slot, or two threads of
        # one process could both believe they hold the probe
        # the claim is made by LINKING a fully-written private record
        # into place: the shared path either carries a complete owner
        # record or does not exist, so a disk-full failure happens on
        # the private temp and never leaves (or requires cleaning up)
        # a half-written claim another process could misjudge
        if self._probe_file is None:
            # no file plane: the local slot (already claimed by the
            # caller) is the only probe exclusivity there is
            return True
        tmp = f"{self._probe_file}.{self._owner or os.getpid()}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({"owner": self._owner,
                           "ts": round(time.time(), 3)}, f)
        except OSError:
            with contextlib.suppress(OSError):
                os.unlink(tmp)
            return False
        try:
            for attempt in (1, 2):
                try:
                    # ownership transfer on success: the claim file
                    # outlives this method (released by
                    # _drop_probe_file on the verdict paths, or
                    # broken by the stale TTL)
                    os.link(tmp, self._probe_file)
                    self._holds_probe_file = True
                    return True
                except FileExistsError:
                    if attempt == 2:
                        return False
                    # stale-claim break: the holder died without a
                    # verdict.  Wall-clock ages are FACTS about the
                    # file, not schedules — legal outside the
                    # injectable clock.
                    try:
                        with open(self._probe_file) as f:
                            stale_rec = json.load(f)
                        ts = float(stale_rec.get("ts", 0.0))
                    except (OSError, ValueError):
                        stale_rec, ts = {}, 0.0
                    if time.time() - ts < self._probe_stale_s:
                        return False
                    # exactly ONE contender wins the break: rename is
                    # the atomic claim on the break itself, so a
                    # rival that also ruled the claim stale cannot
                    # unlink the fresh claim we are about to make
                    bpath = self._probe_file + ".break"
                    try:
                        os.rename(self._probe_file, bpath)
                    except OSError:
                        return False  # another contender broke it
                    with contextlib.suppress(OSError):
                        os.unlink(bpath)
                    # the audit line the crash-between-claim-and-
                    # verdict window used to lack: WHO held the slot,
                    # for how long, and who swept it
                    if self._journal is not None:
                        self._journal.write(
                            "probe_reclaimed",
                            signature=self.signature, reason="stale",
                            prev_owner=str(stale_rec.get("owner", "")),
                            by=self._owner,
                            age_s=round(time.time() - ts, 3))
                except OSError:
                    return False  # store dir gone: claim locally only
            return False
        finally:
            with contextlib.suppress(OSError):
                os.unlink(tmp)

    def _drop_probe_file(self) -> None:
        # sctlint: io-under-lock — releasing the claim file must be
        # atomic with clearing the local flag: a gap would let a
        # sharer win the claim while this process still thinks it
        # holds the slot
        self._holds_probe_file = False
        if self._probe_file is None:
            return
        try:
            os.unlink(self._probe_file)
        except OSError:
            pass  # already released/broken: the claim is gone either way


class FederatedBreakerRegistry(BreakerRegistry):
    """A :class:`~sctools_tpu.utils.failsafe.BreakerRegistry` whose
    breakers replicate per-backend state across processes through
    ``store_dir`` (same ``get``/``snapshot``/``reset`` API — the run
    scheduler and every worker accept it unchanged).  ``owner`` names
    this process in published transitions and probe claims, so the
    supervisor can clear a dead worker's claims
    (:meth:`clear_probe_claims`).

    Two replication planes compose (either may be absent):

    * the FILE plane — ``store_dir`` state files, exactly as before;
      ``store_dir=None`` turns it off (no shared filesystem).
    * the TRANSPORT plane — give it a ``transport`` and ``peers``,
      and every local transition is broadcast as a ``breaker``
      message after the verdict; inbound messages land through
      :meth:`apply_remote`, epoch-fenced per breaker so a stale
      claimant's verdict published behind a partition is refused on
      heal.  The transport's ``on_rejoin`` hook is wired to
      :meth:`sync_peer`: the first delivery after a partition
      re-offers the full state, epoch-max wins — the no-split-brain
      reconciliation step.
    """

    def __init__(self, store_dir: str | None, clock=None,
                 owner: str = "", metrics=None, journal=None,
                 transport=None, peers=(), **breaker_defaults):
        super().__init__(clock=clock, **breaker_defaults)
        self.store_dir = None if store_dir is None else str(store_dir)
        if self.store_dir is not None:
            os.makedirs(self.store_dir, exist_ok=True)
        self.owner = owner
        self.metrics = metrics
        self.journal = journal
        self.transport = transport
        self.peers = tuple(peers or ())
        if transport is not None and transport.on_rejoin is None:
            transport.on_rejoin = self.sync_peer

    def get(self, signature: str, **kw) -> CircuitBreaker:
        signature = str(signature)
        with self._lock:
            b = self._breakers.get(signature)
            if b is None:
                merged = {**self._defaults, **kw}
                merged.setdefault("clock", self.clock)
                b = self._breakers[signature] = FederatedBreaker(
                    signature=signature, store_dir=self.store_dir,
                    owner=self.owner, metrics=self.metrics,
                    journal=self.journal,
                    on_transition=(self._broadcast if self.transport
                                   is not None else None), **merged)
            return b

    # -- the transport plane -------------------------------------------
    def _broadcast(self, signature: str, state: str,
                   epoch: int) -> None:
        """Send one local transition to every peer (called by the
        breaker's verdict paths AFTER its lock is released).  A send
        that gives up is fine: the peer is partitioned, keeps making
        LOCAL-ONLY decisions, and :meth:`sync_peer` re-offers
        everything on heal."""
        for peer in self.peers:
            self.transport.send(peer, "breaker", sig=signature,
                                state=state, epoch=epoch,
                                owner=self.owner)

    def apply_remote(self, signature: str, state: str, epoch: int,
                     owner: str = "") -> bool:
        """Inbound transport plane: route a peer's transition to its
        breaker, which fences it by epoch (True = applied)."""
        return self.get(str(signature)).apply_remote(
            str(state), epoch, owner=owner)

    def sync_peer(self, peer: str) -> None:
        """Re-offer every known breaker's state at its current epoch
        to ``peer`` — the receiver's epoch fence accepts what is news
        and refuses what is stale, so sending is always safe.  Wired
        as the transport's ``on_rejoin`` hook: healing a partition
        IS a sync."""
        if self.transport is None:
            return
        for sig in self.signatures():
            b = self.get(sig)
            with b.lock:
                ep = b._seen_epoch
                state = "open" if b._state != b.CLOSED else "closed"
            if ep > 0:
                self.transport.send(peer, "breaker", sig=sig,
                                    state=state, epoch=ep,
                                    owner=self.owner)

    def signatures(self) -> list[str]:
        """Every signature this registry has seen — locally OR
        published to the store by another process."""
        local = set(super().signatures())
        if self.store_dir is not None:
            try:
                for fn in os.listdir(self.store_dir):
                    if fn.endswith(".json") and not fn.endswith(".tmp"):
                        local.add(fn[:-5])
            except OSError:
                pass  # store dir gone: local view is all there is
        return sorted(local)

    def snapshot(self) -> dict:
        # materialize store-only signatures first so the snapshot
        # covers breakers other PROCESSES tripped
        for sig in self.signatures():
            self.get(sig)
        return super().snapshot()

    def clear_probe_claims(self, owner: str) -> int:
        """Remove probe-claim files held by ``owner`` (a fenced/dead
        worker cannot deliver a verdict; leaving its claim would
        wedge every sharer on the fallback until the stale TTL)."""
        # sctlint: io-under-lock — runs inside the lost-worker ruling
        # (supervisor lock held): the claims must be gone before the
        # ruling completes, or a respawned worker could collide with
        # its predecessor's stale probe slot
        if self.store_dir is None:
            return 0  # no file plane: no claim files to sweep
        cleared = 0
        try:
            names = os.listdir(self.store_dir)
        except OSError:
            return 0
        for fn in names:
            if not fn.endswith(".probe"):
                continue
            path = os.path.join(self.store_dir, fn)
            try:
                with open(path) as f:
                    rec = json.load(f)
                if rec.get("owner") == owner:
                    os.unlink(path)
                    cleared += 1
                    if self.journal is not None:
                        self.journal.write(
                            "probe_reclaimed", signature=fn[:-6],
                            reason="owner_lost", prev_owner=owner,
                            by=self.owner,
                            age_s=round(time.time()
                                        - float(rec.get("ts", 0.0)),
                                        3))
            except (OSError, ValueError):
                continue  # racing claim churn: nothing of ours here
        return cleared


# ---------------------------------------------------------------------------
# Tickets and handles
# ---------------------------------------------------------------------------

class TicketHandle:
    """The caller's view of one federated submission.  ``status``
    moves ``queued`` → ``running`` → ``completed`` | ``failed``, or
    ``queued``/``running`` → ``shed`` (a requeue moves it back to
    ``queued`` — that is the process-death-is-transient contract).
    ``result()`` blocks until terminal and LOADS the committed result
    from the ticket directory; ``failed`` re-raises a
    :class:`FederatedRunError` carrying the worker-side error text,
    ``shed`` raises :class:`~sctools_tpu.scheduler.RunShed`."""

    def __init__(self, ticket: str, tenant: str, priority: int):
        self.ticket = ticket
        self.tenant = tenant
        self.priority = priority
        self.reason: str | None = None
        self.worker: str | None = None
        self.epoch = 0
        self._status = "queued"
        self._result_path: str | None = None
        self._error: BaseException | None = None
        self._terminal = threading.Event()

    @property
    def status(self) -> str:
        return self._status

    def done(self) -> bool:
        return self._terminal.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        return self._terminal.wait(timeout)

    def result(self, timeout: float | None = None):
        if not self._terminal.wait(timeout):
            raise TimeoutError(
                f"ticket {self.ticket} (tenant {self.tenant!r}) not "
                f"terminal after {timeout}s (status {self._status!r})")
        if self._status == "completed":
            return load_celldata(self._result_path)
        raise self._error

    def _finish(self, status: str, result_path: str | None = None,
                error: BaseException | None = None,
                reason: str | None = None) -> None:
        self._result_path = result_path
        self._error = error
        self.reason = reason
        self._status = status
        self._terminal.set()

    def __repr__(self):
        return (f"TicketHandle({self.ticket!r}, tenant={self.tenant!r}"
                f", status={self._status!r}, epoch={self.epoch})")


class FederatedRunError(RuntimeError):
    """A federated run FAILED on its worker (deterministic error,
    exhausted ladder).  Carries the worker-reported error text; the
    worker's journal under ``workers/<name>/journal.jsonl`` has the
    full attempt-by-attempt story."""


class FederationFencedError(RuntimeError):
    """A ticket was requeued while its previous incarnation could
    still commit — the caller skipped the fence step (fence the
    worker, record the refusal, or know the assignment never reached
    an inbox) before bumping the epoch.  Raised by the supervisor's
    own invariant check, never expected in normal operation."""


class _Ticket:
    __slots__ = ("id", "seq", "tenant", "priority", "backend",
                 "steps", "runner_kw", "dir", "epoch", "handle",
                 "worker", "submitted_at", "ready", "committing",
                 "accepted", "trace_id")

    def __init__(self, seq: int, tenant: str, priority: int,
                 backend, steps, runner_kw, tdir, handle, now,
                 trace_id: str = ""):
        self.id = f"t{seq:06d}"
        self.seq = seq
        self.tenant = tenant
        self.priority = int(priority)
        self.backend = backend
        self.steps = steps
        self.runner_kw = runner_kw
        self.dir = tdir
        self.epoch = 0
        self.handle = handle
        self.worker = None          # _Worker currently assigned, or None
        self.submitted_at = now
        self.ready = False          # data.npz + ticket.json on disk
        #: a pump thread accepted this ticket's commit under the lock
        #: and is finishing it OUTSIDE the lock — terminal belongs to
        #: that thread alone (shed paths must keep their hands off)
        self.committing = False
        #: (worker_name, epoch) of the ACCEPTED commit — lets a
        #: duplicate delivery of the same commit (result-file probe
        #: vs the real `done` line) dedupe silently instead of being
        #: journalled as a fencing refusal
        self.accepted = None
        #: the admission-stamped trace context: every supervisor
        #: journal record about this ticket carries it, the spec
        #: ships it to whichever worker owns the epoch, and the
        #: worker's spans come back keyed on it
        self.trace_id = trace_id

    def sort_key(self):
        return (-self.priority, self.seq)


class _Worker:
    """Supervisor-side record of one worker incarnation."""

    __slots__ = ("name", "gen", "dir", "proc", "pid", "last_beat",
                 "beats", "served", "wedged", "lost", "stopping",
                 "in_flight", "pump")

    def __init__(self, name: str, gen: int, wdir: str):
        self.name = name
        self.gen = gen
        self.dir = wdir
        self.proc = None
        self.pid = None
        self.last_beat = 0.0
        self.beats = 0
        self.served = 0
        self.wedged = False   # chaos partition: drop all its messages
        self.lost = False
        self.stopping = False
        self.in_flight: list[_Ticket] = []
        self.pump = None

    @property
    def chaos_name(self) -> str:
        """The name chaos patterns match: the bare logical name for
        the FIRST incarnation only — a respawned worker is a fresh
        process and must not re-arm its predecessor's faults."""
        return self.name if self.gen == 0 else f"{self.name}#{self.gen}"

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


# ---------------------------------------------------------------------------
# The supervisor
# ---------------------------------------------------------------------------

class FederationSupervisor:
    """Admission-controlled ticket queue + supervised worker-process
    pool (module docstring has the full contract).

    Parameters
    ----------
    fed_dir : str
        The federation's on-disk home: tickets, worker dirs, the
        breaker transport and the supervisor journal all live here.
    n_workers, worker_capacity : int
        Pool size and per-worker concurrent-assignment bound.
    transport : str
        ``"file"`` (default): worker messages ride the stderr line
        protocol, parsed by the per-worker pump thread.
        ``"socket"``: the supervisor listens on a
        :class:`~sctools_tpu.transport.SocketTransport`; workers
        connect to the address in ``config.json`` and push the same
        protocol messages as length-prefixed frames (tagged with
        their ``gen`` so a fenced predecessor behind a healed
        partition is refused on the record), and their breaker
        transitions ride the same socket, epoch-fenced by
        :meth:`FederatedBreakerRegistry.apply_remote`.  The stderr
        pipe stays attached for noise draining and exit detection.
    lease_timeout_s : float
        Lease age (on ``clock``) past which a worker with no credited
        heartbeat is ruled :data:`PROCESS_LOST`.  Must comfortably
        exceed worker startup (a fresh interpreter imports jax).
    heartbeat_s, poll_s : float
        Worker-side cadences (written into ``config.json``): beat
        interval and inbox scan interval.
    tenant_max_queued, tenant_max_in_flight, queue_high_water : int
        The federation-tier admission quotas (same semantics as
        ``RunScheduler``: queue quota at admission, in-flight quota
        at dispatch, high-water shedding of the lowest-priority
        victim).
    max_respawns : int
        Replacement incarnations per logical worker name.
    monitor_interval_s : float | None
        When set, a monitor thread calls :meth:`check_leases` every
        interval (REAL event-wait, like ``failsafe.watch_process`` —
        it supervises real subprocesses).  Tests leave it ``None``
        and drive :meth:`check_leases` explicitly on a VirtualClock.
    clock, metrics, chaos
        The injectable clock (lease arithmetic), the ``fed.*``/
        ``sched.*`` metrics home, and the chaos monkey consulted at
        admission (``reject_storm``) and per heartbeat
        (``kill_worker``/``lease_wedge``).
    breaker_defaults : dict | None
        Construction defaults for the federated breaker transport
        (``failure_threshold=``, ``cooldown_s=`` …), written into
        ``config.json`` so every WORKER builds its registry the same
        way.
    runner_config : dict | None
        Worker-side runner defaults, JSON-serializable: ``policy``
        (RetryPolicy fields), ``step_deadline_s``,
        ``fallback_backend``, ``fuse``, ``assume_healthy`` (replace
        the subprocess device probe with an always-ok verdict — the
        supervisor already owns process-level health).
    init_module : str | None
        Imported by every worker before serving (register custom
        ops there; tests point it at a fixture module).
    chaos_specs : dict | None
        ``{worker-name-pattern: ChaosMonkey.spec()}`` — each FIRST
        incarnation whose name matches re-arms the spec in-process
        (kill/unavailable faults inside the worker); respawned
        incarnations never inherit.
    """

    #: the result-file recovery probe runs on every Nth supervision
    #: tick (check_leases call) instead of every one — lease ruling
    #: stays per-tick, the ENOENT-churning file probes do not
    RECOVERY_EVERY_TICKS = 5

    def __init__(self, fed_dir: str, *, n_workers: int = 2,
                 worker_capacity: int = 1,
                 transport: str = "file",
                 lease_timeout_s: float = 60.0,
                 heartbeat_s: float = 1.0, poll_s: float = 0.25,
                 tenant_max_queued: int = 16,
                 tenant_max_in_flight: int = 8,
                 queue_high_water: int = 64,
                 max_respawns: int = 1,
                 monitor_interval_s: float | None = None,
                 clock=None, metrics=None, chaos=None,
                 breaker_defaults: dict | None = None,
                 runner_config: dict | None = None,
                 init_module: str | None = None,
                 chaos_specs: dict | None = None,
                 env: dict | None = None):
        if n_workers < 1:
            raise ValueError("n_workers must be >= 1")
        if worker_capacity < 1:
            raise ValueError("worker_capacity must be >= 1")
        self.fed_dir = str(fed_dir)
        os.makedirs(os.path.join(self.fed_dir, "tickets"),
                    exist_ok=True)
        os.makedirs(os.path.join(self.fed_dir, "workers"),
                    exist_ok=True)
        os.makedirs(os.path.join(self.fed_dir, "obs"),
                    exist_ok=True)
        self.n_workers = int(n_workers)
        self.worker_capacity = int(worker_capacity)
        self.lease_timeout_s = float(lease_timeout_s)
        self.heartbeat_s = float(heartbeat_s)
        self.poll_s = float(poll_s)
        self.tenant_max_queued = int(tenant_max_queued)
        self.tenant_max_in_flight = int(tenant_max_in_flight)
        self.queue_high_water = int(queue_high_water)
        self.max_respawns = int(max_respawns)
        self.monitor_interval_s = monitor_interval_s
        self.clock = clock if clock is not None else SYSTEM_CLOCK
        self.metrics = (metrics if metrics is not None
                        else telemetry.default_registry())
        self.chaos = chaos
        self.env = env
        self.journal = _Journal(os.path.join(self.fed_dir,
                                             "journal.jsonl"))
        #: the FLEET registry: every worker's lossy obs deltas merge
        #: here keyed ``worker=``, ticked on the supervisor's
        #: injectable clock and flushed tick-stamped under ``obs/`` —
        #: a worker SIGKILLed mid-run leaves its series up to its
        #: last delivered frame (the trail the post-mortem reads)
        self.fleet = telemetry.MetricsRegistry(clock=self.clock)
        if transport not in ("file", "socket"):
            raise ValueError(f"unknown transport {transport!r} "
                             f"(file | socket)")
        self.transport_kind = transport
        #: socket mode: the supervisor listens, workers connect from
        #: config.json's address and push the same protocol messages
        #: the stderr pump would have parsed.  The stderr pipe stays
        #: attached either way — it drains worker noise and its EOF
        #: is still how the reap path notices an exit.
        self.transport = None
        if transport == "socket":
            self.transport = SocketTransport(
                "supervisor", clock=self.clock, journal=self.journal,
                metrics=self.metrics,
                on_message=self._on_net_message)
        self.breakers = FederatedBreakerRegistry(
            os.path.join(self.fed_dir, "breakers"), clock=self.clock,
            owner="supervisor", metrics=self.metrics,
            journal=self.journal, **(breaker_defaults or {}))
        self._config = {
            "heartbeat_s": self.heartbeat_s, "poll_s": self.poll_s,
            "breaker": dict(breaker_defaults or {}),
            "runner": dict(runner_config or {}),
            "init_module": init_module,
            "chaos_specs": dict(chaos_specs or {}),
            "transport": ({"kind": "socket",
                           "host": self.transport.host,
                           "port": self.transport.port}
                          if self.transport is not None
                          else {"kind": "file"}),
        }
        self._lock = threading.RLock()
        self._queue: list[_Ticket] = []
        self._tickets: dict[str, _Ticket] = {}
        self._seq = 0
        self._closed = False
        self._started = False
        self._committing = 0  # tickets accepted, terminal pending
        self._recovery_tick = 0  # supervision ticks since start
        self._workers: dict[str, _Worker] = {}
        self._monitor_stop = threading.Event()
        self._monitor = None
        self._all_idle = threading.Event()
        self._all_idle.set()
        #: set when a lease_wedge chaos ruling partitions a worker —
        #: the event-driven signal tests wait on before advancing a
        #: VirtualClock past the lease timeout (no polling sleeps)
        self.wedge_observed = threading.Event()

    @property
    def journal_path(self) -> str:
        """Path of the supervisor's merged journal
        (``fed_dir/journal.jsonl``) — the one file
        ``check_journal_coherent`` and ``sctreport`` read; dead
        workers' journal tails are grafted in as ``journal_tail``
        fields on their ``worker_lost`` records."""
        return self.journal.path

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "FederationSupervisor":
        with self._lock:
            if self._started:
                return self
            self._started = True
        # config write OUTSIDE the lock (SCT011: no file IO under the
        # dispatch lock).  Safe: _started already claimed the one
        # start, and the workers that read this file are only spawned
        # below, after the rename lands
        cpath = os.path.join(self.fed_dir, "config.json")
        with open(cpath + ".tmp", "w") as f:
            json.dump(self._config, f, indent=1)
        os.replace(cpath + ".tmp", cpath)
        with self._lock:
            if self._closed:
                # a concurrent shutdown() landed in the gap between
                # claiming _started and this block: it saw an empty
                # worker dict, so spawning now would leak processes
                # nothing will ever stop
                return self
            for i in range(self.n_workers):
                self._spawn_locked(f"w{i}", gen=0)
        if self.monitor_interval_s is not None:
            self._monitor = threading.Thread(
                target=self._monitor_loop, daemon=True,
                name="sct-fed-monitor")
            self._monitor.start()
        return self

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown(shed_queued=exc[0] is not None)
        return False

    def _monitor_loop(self) -> None:
        # REAL event-wait on purpose (cf. failsafe.watch_process):
        # this thread supervises real subprocesses; a virtual clock
        # here would hot-spin and rule healthy workers lost.  Tests
        # leave monitor_interval_s=None and drive check_leases().
        while not self._monitor_stop.wait(self.monitor_interval_s):
            self.check_leases()

    def _spawn_locked(self, name: str, gen: int) -> _Worker:
        # sctlint: io-under-lock — preparing the worker dir (fence
        # and stop markers REMOVED, inbox created) and registering
        # the process must be one atomic step under the dispatch
        # lock: a dispatch between the spawn and the bookkeeping
        # would assign to a worker whose inbox does not exist yet
        wdir = os.path.join(self.fed_dir, "workers", name)
        os.makedirs(os.path.join(wdir, "inbox"), exist_ok=True)
        for stale in ("fence.json", "stop"):
            with contextlib.suppress(OSError):
                os.unlink(os.path.join(wdir, stale))
        w = _Worker(name, gen, wdir)
        code = ("import sys\n"
                "from sctools_tpu.federation import worker_main\n"
                "sys.exit(worker_main(sys.argv[1], sys.argv[2], "
                "gen=int(sys.argv[3])))\n")
        child_env = dict(os.environ if self.env is None else self.env)
        paths = [p for p in sys.path if p] + [
            p for p in child_env.get("PYTHONPATH", "").split(os.pathsep)
            if p]
        child_env["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(paths))
        w.proc = subprocess.Popen(
            [sys.executable, "-c", code, self.fed_dir, name, str(gen)],
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
            text=True, env=child_env)
        w.pid = w.proc.pid
        w.last_beat = self.clock.monotonic()  # startup grace
        self._workers[name] = w
        self.journal.write("worker_spawned", worker=name, gen=gen,
                           pid=w.pid)
        w.pump = threading.Thread(target=self._pump, args=(w,),
                                  daemon=True,
                                  name=f"sct-fed-pump-{name}")
        w.pump.start()
        return w

    # -- worker message pump -------------------------------------------
    def _pump(self, w: _Worker) -> None:
        try:
            for line in w.proc.stderr:
                m = _LINE_RE.match(line.strip())
                if m is None:
                    continue  # worker noise never refreshes the lease
                kind, fields = m.group(1), _parse_fields(m.group(2))
                if kind == "beat" or kind == "hello":
                    self._on_beat(w)
                elif kind == "done":
                    self._on_done(w, fields)
                elif kind == "refused":
                    self._on_refused(w, fields)
                elif kind == "obs":
                    self._on_obs(w, fields)
        finally:
            with contextlib.suppress(subprocess.TimeoutExpired,
                                     OSError):
                w.proc.wait(timeout=30)
            self._on_exit(w)

    def _on_net_message(self, frm: str, kind: str,
                        fields: dict) -> None:
        """Socket-mode twin of the pump parse: runs on a transport
        receiver thread.  Messages carry ``gen`` — the socket plane's
        fencing evidence: a predecessor incarnation still talking
        through a healed partition must not refresh the CURRENT
        incarnation's lease, and its commit is refused on the
        record (the same at-most-once story the epoch guard tells,
        one layer earlier)."""
        if kind == "breaker":
            # breaker transitions self-fence by EPOCH inside
            # apply_remote, so they are deliberately gen-independent:
            # a true state transition is news no matter which
            # incarnation reports it
            self.breakers.apply_remote(
                fields.get("sig", ""), fields.get("state", ""),
                int(fields.get("epoch", 0)),
                owner=str(fields.get("owner", frm)))
            return
        with self._lock:
            w = self._workers.get(frm)
            stale = (w is None
                     or int(fields.get("gen", w.gen)) != w.gen)
        if stale:
            if kind == "obs":
                # a fenced predecessor's telemetry must not pollute
                # the fleet trail under the CURRENT incarnation's
                # worker= label — dropped on the record, never merged
                self.metrics.counter("obs.dropped",
                                     reason="stale_gen").inc()
            elif kind == "done" and w is not None:
                self.journal.write(
                    "commit_refused",
                    ticket=str(fields.get("ticket", "")), worker=frm,
                    epoch=int(fields.get("epoch", -1)),
                    by="supervisor", reason="stale_gen")
                self.metrics.counter("fed.fenced_commits").inc()
            return
        if kind in ("beat", "hello"):
            self._on_beat(w)
        elif kind == "done":
            self._on_done(w, fields)
        elif kind == "refused":
            self._on_refused(w, fields)
        elif kind == "obs":
            self._on_obs(w, fields)

    def _on_beat(self, w: _Worker) -> None:
        with self._lock:
            if w.lost or (self._closed and w.stopping):
                return
            if self.chaos is not None and not w.wedged:
                ruling = self.chaos.on_worker(w.chaos_name)
                if ruling is not None:
                    if ruling["mode"] == "kill_worker":
                        # hard process death mid-run — the reap path
                        # (pipe EOF -> _on_exit) runs the lost-worker
                        # ladder; nothing more to do here
                        with contextlib.suppress(OSError):
                            os.kill(w.pid, signal.SIGKILL)
                        return
                    if ruling["mode"] == "lease_wedge":
                        # partition: the worker stays alive but none
                        # of its messages arrive from here on — its
                        # lease goes stale and only check_leases()
                        # can rule on it
                        w.wedged = True
                        self.wedge_observed.set()
                        return
            if w.wedged:
                return
            w.last_beat = self.clock.monotonic()
            w.beats += 1
            self.metrics.counter("fed.heartbeats", worker=w.name).inc()
            self._dispatch_locked()
        self.check_leases()

    def _on_done(self, w: _Worker, fields: dict,
                 recovered: bool = False) -> None:
        tid = fields.get("ticket", "")
        epoch = int(fields.get("epoch", -1))
        status = fields.get("status", "failed")
        with self._lock:
            if w.wedged and not w.lost:
                return  # partitioned: its messages never arrive
            if w.lost:
                if recovered:
                    return  # ruling raced the probe: requeue won
                # a FENCED worker's commit DID arrive (the fence
                # raced the run's tail) — refuse it on the record:
                # this is the at-most-once evidence the docs promise
                self.journal.write(
                    "commit_refused", ticket=tid, worker=w.name,
                    epoch=epoch, by="supervisor", reason="fenced")
                self.metrics.counter("fed.fenced_commits").inc()
                return
            t = self._tickets.get(tid)
            if t is None:
                return
            if t.handle.done() or t.committing or epoch != t.epoch \
                    or t.worker is not w:
                if recovered or t.accepted == (w.name, epoch):
                    # duplicate delivery of an ALREADY-ACCEPTED
                    # commit (the result-file probe and the real
                    # `done` line race each other): dedupe silently —
                    # this is not fencing evidence
                    return
                # stale epoch / foreign worker: the fencing guard —
                # this commit is REFUSED, the current owner's is the
                # one that counts
                self.journal.write(
                    "commit_refused", ticket=tid, worker=w.name,
                    epoch=epoch, current_epoch=t.epoch, by="supervisor")
                self.metrics.counter("fed.fenced_commits").inc()
                return
            # ACCEPT the commit under the lock (epoch checked, slot
            # freed, terminal claimed via `committing` so no shed
            # path touches the handle) ...
            w.in_flight.remove(t)
            w.served += 1
            t.worker = None
            t.committing = True
            t.accepted = (w.name, epoch)
            self._committing += 1
            rpath = os.path.join(t.dir, f"result-{epoch:03d}")
        # ... but resolve it OUTSIDE: the terminal journal append and
        # the error-detail read are disk work, and disk latency under
        # the dispatch lock stalls heartbeat crediting and every
        # other tenant's dispatch (SCT011 — the same rule the
        # in-process scheduler's worker follows).  Ordering is safe:
        # this ticket's admitted/assigned lines were flushed before
        # the worker ever saw it, and _Journal serializes appends.
        # The handle RESOLVES in the finally: once accepted, nothing
        # that can raise out here — a journal append on a full disk,
        # a caller-injected metrics registry, the error-detail read —
        # may strand the ticket non-terminal, so the try starts
        # IMMEDIATELY after the accept and the verdict has a pure
        # (no-IO) default before anything fallible runs.
        extra = {"recovered": True} if recovered else {}
        err = "worker-side failure"
        if status == "completed":
            verdict = ("completed", dict(result_path=rpath + ".npz"))
        else:
            verdict = ("failed", dict(
                error=FederatedRunError(
                    f"ticket {tid} failed on worker {w.name}: {err}"),
                reason="run_failed"))
        try:
            if recovered:
                self.metrics.counter("fed.recovered_commits").inc()
            if status == "completed":
                self.journal.write("run_completed", ticket=tid,
                                   tenant=t.tenant, worker=w.name,
                                   epoch=epoch, trace_id=t.trace_id,
                                   **extra)
            else:
                with contextlib.suppress(OSError, ValueError):
                    # terse fallback; the worker journal has it all
                    with open(rpath + ".json") as f:
                        err = json.load(f).get("error", err)
                verdict = ("failed", dict(
                    error=FederatedRunError(
                        f"ticket {tid} failed on worker {w.name}: "
                        f"{err}"), reason="run_failed"))
                self.journal.write("run_failed", ticket=tid,
                                   tenant=t.tenant, worker=w.name,
                                   epoch=epoch, error=err,
                                   trace_id=t.trace_id, **extra)
        finally:
            t.handle.worker = w.name
            t.handle._finish(verdict[0], **verdict[1])
            with self._lock:
                t.committing = False
                self._committing -= 1
                self._note_idle_locked()
                self._dispatch_locked()

    def _on_refused(self, w: _Worker, fields: dict) -> None:
        with self._lock:
            if w.wedged and not w.lost:
                return  # partitioned: the refusal never arrives either
            self.journal.write(
                "commit_refused", ticket=fields.get("ticket", ""),
                worker=w.name, epoch=int(fields.get("epoch", -1)),
                by="worker")
            self.metrics.counter("fed.fenced_commits").inc()
            if w.lost:
                return  # already fenced+requeued by the lose path
            # the assignment is dead on that worker either way
            t = self._tickets.get(fields.get("ticket", ""))
            if t is not None and t.worker is w:
                w.in_flight.remove(t)
                t.worker = None
                self._requeue_locked(t, from_worker=w)
                self._dispatch_locked()

    def _on_obs(self, w: _Worker, fields: dict) -> None:
        """One LOSSY obs frame: merge the worker's metric delta into
        the fleet registry.  Never refreshes the lease (only explicit
        beats prove the worker LOOP is alive), never raises back into
        the pump/receiver thread, and a malformed or stale frame is
        dropped on the record (``obs.dropped``) — the cost of any
        loss is exactly that frame's window of samples, which the
        worker's cursor already gave up at export time."""
        with self._lock:
            if w.lost or w.wedged:
                # a partitioned/fenced worker's telemetry is dropped
                # like every other message of its incarnation
                self.metrics.counter("obs.dropped",
                                     reason="partitioned").inc()
                return
        try:
            delta = json.loads(str(fields.get("delta", "")))
        except ValueError:
            self.metrics.counter("obs.dropped", reason="decode").inc()
            return
        try:
            self.fleet.merge_delta(delta, worker=w.name)
        except (TypeError, ValueError, KeyError, AttributeError):
            # boundary mismatch / wrong shape: refuse the frame, keep
            # the trail — obs must degrade, never propagate
            self.metrics.counter("obs.dropped", reason="merge").inc()
            return
        self.metrics.counter("obs.frames", worker=w.name).inc()

    def _on_exit(self, w: _Worker) -> None:
        with self._lock:
            rc = w.proc.returncode
            if w.lost or (w.stopping and rc == 0):
                self._note_idle_locked()
                return
            self._lose_worker_locked(w, reason="exited", rc=rc)

    # -- the lost-worker ladder ----------------------------------------
    def check_leases(self) -> None:
        """Rule on every live worker's lease age, then recover any
        commit whose ``done`` line was lost in transit (the
        supervision tick).  Called from every credited heartbeat,
        from worker exits, from the optional monitor thread — and
        directly by tests after advancing a VirtualClock."""
        with self._lock:
            now = self.clock.monotonic()
            for w in list(self._workers.values()):
                if w.lost or w.stopping:
                    continue
                age = now - w.last_beat
                self.metrics.histogram("fed.lease_age_s",
                                       worker=w.name).observe(age)
                if age > self.lease_timeout_s:
                    self._lose_worker_locked(w, reason="lease_expired")
            # decimated by a TICK COUNTER, not a clock grace: a
            # clock-based threshold would never elapse on a
            # VirtualClock that stops advancing — exactly the regime
            # the chaos soaks run in — and the probe exists to heal
            # without any further clock movement
            self._recovery_tick += 1
            if self._recovery_tick % self.RECOVERY_EVERY_TICKS:
                return
            # stopping workers stay INCLUDED: a done line lost during
            # shutdown would otherwise turn committed work into a
            # teardown shed (only wedged/lost workers' commits must
            # wait for the lease ruling)
            pending = [(w, t, t.epoch)
                       for w in self._workers.values()
                       if not (w.lost or w.wedged)
                       for t in list(w.in_flight)]
        # the fleet trail flush rides the same decimated tick as the
        # recovery probe (file IO — outside the lock, SCT011): one
        # tick-stamped snapshot under obs/ per Nth supervision tick
        self._flush_obs()
        # RESULT-FILE RECOVERY, outside the lock (file IO — SCT011):
        # the atomic rename on the shared fed dir is the durable
        # commit; the worker's stderr ``done`` line is only the
        # doorbell.  A line lost in transit (mangled by interleaved
        # worker output, a full pipe) used to wedge the ticket
        # in_flight forever — the worker stays healthy, so no lease
        # ever expires and nothing requeues.  Probing the result file
        # of the ticket's CURRENT epoch heals any lost doorbell;
        # ``_on_done`` re-checks every guard under the lock, so a
        # probe that races the real line, a requeue or a fence is
        # silently deduplicated (``recovered=True``).  Wedged workers
        # are excluded: a partitioned worker's commit must wait for
        # the lease ruling (its epoch is about to be superseded).
        for w, t, epoch in pending:
            rpath = os.path.join(t.dir, f"result-{epoch:03d}.json")
            try:
                with open(rpath) as f:
                    status = json.load(f).get("status", "failed")
            except (OSError, ValueError):
                continue  # not committed (or mid-write): next tick
            self._on_done(w, {"ticket": t.id, "epoch": epoch,
                              "status": status}, recovered=True)

    def _flush_obs(self) -> None:
        """Tick the fleet registry and land the trail as a durable
        tick-stamped snapshot (``obs/fleet-<tick>.json``, atomic
        rename).  A worker already ruled lost keeps its merged series
        in every later flush — death truncates a trail, it never
        erases one."""
        rec = self.fleet.tick()
        path = os.path.join(self.fed_dir, "obs",
                            f"fleet-{int(rec['tick']):06d}.json")
        try:
            self.fleet.write(path, series=True)
        except OSError as e:
            warnings.warn(
                f"FederationSupervisor: could not flush {path} "
                f"({type(e).__name__}: {e}) — the in-memory trail "
                "still has the series", RuntimeWarning, stacklevel=2)
            return
        self.metrics.counter("obs.flushes").inc()

    def _journal_tail(self, w: _Worker, n: int = 8) -> list:
        """The dead worker's last journal records, grafted into its
        ``worker_lost`` event — the post-mortem a vanished process
        cannot give any other way."""
        # sctlint: io-under-lock — the tail is read as part of the
        # lost-worker ruling so the worker_lost record carries the
        # evidence as of the ruling, not of some later state; the
        # file is small (last n lines of a dead worker's journal)
        path = os.path.join(w.dir, "journal.jsonl")
        try:
            with open(path) as f:
                lines = f.readlines()[-n:]
        except OSError:
            return []
        tail = []
        for line in lines:
            try:
                tail.append(json.loads(line))
            except ValueError:
                tail.append({"raw": line.strip()[:200]})
        return tail

    def _lose_worker_locked(self, w: _Worker, reason: str,
                            rc=None) -> None:
        # sctlint: io-under-lock — the fence write is the POINT of
        # this function and must precede, in the same lock hold, the
        # requeue it licenses (see FENCE FIRST below); the inbox
        # purge likewise must be atomic with the respawn decision
        if w.lost:
            return
        w.lost = True
        # FENCE FIRST: after this write the worker refuses to commit,
        # and the epoch bump below refuses anything it already sent —
        # requeue without the fence would be the double-commit race
        fpath = os.path.join(w.dir, "fence.json")
        try:
            with open(fpath + ".tmp", "w") as f:
                json.dump({"reason": reason,
                           "ts": round(time.time(), 3)}, f)
            os.replace(fpath + ".tmp", fpath)
        except OSError as e:
            warnings.warn(
                f"FederationSupervisor: could not write fence for "
                f"{w.name} ({type(e).__name__}: {e}) — the epoch "
                "guard still refuses its commits", RuntimeWarning,
                stacklevel=2)
        self.journal.write(
            "worker_lost", worker=w.name, gen=w.gen, reason=reason,
            rc=rc, classified=PROCESS_LOST,
            in_flight=[t.id for t in w.in_flight],
            lease_age_s=round(self.clock.monotonic() - w.last_beat, 3),
            journal_tail=self._journal_tail(w))
        self.metrics.counter("fed.workers_lost", reason=reason).inc()
        if w.alive():
            with contextlib.suppress(OSError):
                os.kill(w.pid, signal.SIGKILL)
        # a dead worker can never deliver a probe verdict
        self.breakers.clear_probe_claims(w.name)
        # clear its inbox so a respawn never runs a stale epoch
        inbox = os.path.join(w.dir, "inbox")
        try:
            for fn in os.listdir(inbox):
                with contextlib.suppress(OSError):
                    os.unlink(os.path.join(inbox, fn))
        except OSError:
            pass  # inbox gone with the worker dir: nothing stale left
        for t in list(w.in_flight):
            w.in_flight.remove(t)
            t.worker = None
            self._requeue_locked(t, from_worker=w)
        warnings.warn(
            f"FederationSupervisor: worker {w.name} (gen {w.gen}) "
            f"ruled {PROCESS_LOST} ({reason}) — fenced, reaped, "
            f"in-flight tickets requeued.", RuntimeWarning,
            stacklevel=2)
        if not self._closed and w.gen < self.max_respawns:
            nw = self._spawn_locked(w.name, gen=w.gen + 1)
            self.journal.write("worker_respawned", worker=w.name,
                               gen=nw.gen, pid=nw.pid)
        elif not any(x.alive() and not x.lost
                     for x in self._workers.values()):
            # no capacity left and none coming back: queued work can
            # never run — shed it rather than hang every caller
            for t in list(self._queue):
                self._shed_locked(t, "no_workers")
        self._note_idle_locked()
        self._dispatch_locked()

    def _requeue_locked(self, t: _Ticket, from_worker: _Worker) -> None:
        # the fence-before-requeue invariant, enforced: every caller
        # must have detached the old incarnation (fence file written,
        # worker-side refusal recorded, or the assignment never
        # reached an inbox) before the epoch may move — a requeue
        # with the old worker still attached is the double-commit
        # race the epoch exists to prevent
        if t.worker is not None:
            raise FederationFencedError(
                f"requeue of {t.id} while still assigned to "
                f"{t.worker.name} — fence the worker first")
        t.epoch += 1
        t.handle.epoch = t.epoch
        t.handle._status = "queued"
        self._queue.append(t)
        self._queue.sort(key=_Ticket.sort_key)
        self.journal.write("requeued", ticket=t.id, tenant=t.tenant,
                           from_worker=from_worker.name, epoch=t.epoch,
                           trace_id=t.trace_id)
        self.metrics.counter("fed.requeues").inc()

    # -- admission ------------------------------------------------------
    def submit(self, pipeline: Pipeline, data, *,
               tenant: str = "default", priority: int = 0,
               backend: str | None = None,
               runner_kw: dict | None = None,
               trace_id: str | None = None) -> TicketHandle:
        """Admit one federated run (or refuse it: ``RunRejected``).
        Funnel: open → chaos ``reject_storm`` → tenant queue quota →
        high-water (shed a lower-priority victim or reject the
        arrival) → admit.  Same journal shape as the in-process
        scheduler: ``submitted`` → ``admitted`` | ``rejected``, then
        exactly one of ``shed`` | ``run_completed`` | ``run_failed``.

        String step params may carry the ``{ticket_dir}`` placeholder
        (expanded worker-side to the per-ticket directory in the
        shared fed dir) — how a long-running training step
        (``model.scvi_stream``) gets a cursor-checkpoint path that a
        REQUEUED epoch finds again, so a worker lost mid-epoch costs
        at most ``checkpoint_every`` shards of training, never the
        epoch."""
        if not self._started:
            raise RuntimeError("FederationSupervisor.submit before "
                               "start() — use it as a context manager")
        steps = [(t.name, t.backend, dict(t.params))
                 for t in pipeline.steps]
        # the trace context is minted HERE, at federated admission —
        # the id every record about this ticket joins on, across the
        # supervisor journal, the owning worker's journal, the inner
        # runner's records and the returned span tree
        if not trace_id:
            trace_id = new_trace_id()
        with self._lock:
            seq = self._seq
            self._seq += 1
            tid = f"t{seq:06d}"
            self.journal.write("submitted", ticket=tid, tenant=tenant,
                               priority=priority,
                               queue_depth=len(self._queue),
                               trace_id=trace_id)
            if self._closed:
                self._reject(tid, tenant, "scheduler_closed",
                             trace_id=trace_id)
            if self.chaos is not None and \
                    self.chaos.on_admission(tenant, backend=backend):
                self._reject(tid, tenant, "reject_storm",
                             trace_id=trace_id)
            queued = sum(1 for q in self._queue if q.tenant == tenant)
            if queued >= self.tenant_max_queued:
                self._reject(tid, tenant, "tenant_queue_quota",
                             trace_id=trace_id)
            if len(self._queue) >= self.queue_high_water:
                victim = self._pick_victim_locked(priority)
                if victim is None:
                    self._reject(tid, tenant, "queue_full",
                                 trace_id=trace_id)
                self._shed_locked(victim, "queue_high_water")
            tdir = os.path.join(self.fed_dir, "tickets", tid)
            handle = TicketHandle(tid, tenant, int(priority))
            handle.trace_id = trace_id
            t = _Ticket(seq, tenant, priority, backend, steps,
                        dict(runner_kw or {}), tdir, handle,
                        self.clock.monotonic(), trace_id=trace_id)
            self._tickets[tid] = t
            # queued immediately (not-yet-ready: dispatch skips it)
            # so quota/high-water accounting stays exact while the
            # DATA WRITE below runs OUTSIDE the lock — serializing a
            # large dataset under it would starve heartbeat
            # crediting and could rule a healthy worker process_lost
            self._queue.append(t)
            self._queue.sort(key=_Ticket.sort_key)
            self._all_idle.clear()
            self.journal.write("admitted", ticket=tid, tenant=tenant,
                               priority=priority,
                               queue_depth=len(self._queue),
                               trace_id=trace_id)
            self.metrics.counter("sched.admitted", tenant=tenant).inc()
            self.metrics.gauge("sched.queue_depth").set(
                len(self._queue))
        try:
            os.makedirs(tdir, exist_ok=True)
            save_celldata(data, os.path.join(tdir, "data.npz"))
            spec = {"ticket": tid, "tenant": tenant,
                    "priority": int(priority), "backend": backend,
                    "steps": steps, "runner_kw": dict(runner_kw or {}),
                    "trace_id": trace_id}
            with open(os.path.join(tdir, "ticket.json.tmp"), "w") as f:
                json.dump(spec, f)
            os.replace(os.path.join(tdir, "ticket.json.tmp"),
                       os.path.join(tdir, "ticket.json"))
        except OSError as e:
            with self._lock:
                if not t.handle.done():  # a concurrent shed may have won
                    if t in self._queue:
                        self._queue.remove(t)
                    # deliberate in-lock terminal: the done-check,
                    # queue removal and terminal must be atomic
                    # against a concurrent shed, and this path only
                    # runs when the disk already failed
                    self.journal.write(  # sctlint: disable=SCT011
                        "run_failed", ticket=tid, tenant=tenant,
                        error=f"submit write failed: "
                              f"{type(e).__name__}: {e}",
                        trace_id=trace_id)
                    t.handle._finish(
                        "failed", error=FederatedRunError(
                            f"ticket {tid}: could not write the "
                            f"ticket payload ({type(e).__name__}: "
                            f"{e})"), reason="submit_io")
                    self._note_idle_locked()
            return handle
        with self._lock:
            t.ready = True
            self._dispatch_locked()
        return handle

    def _reject(self, tid: str, tenant: str, reason: str,
                trace_id: str = "") -> None:
        self.journal.write("rejected", ticket=tid, tenant=tenant,
                           reason=reason, trace_id=trace_id)
        self.metrics.counter("sched.rejected", tenant=tenant,
                             reason=reason).inc()
        raise RunRejected(
            f"ticket {tid} (tenant {tenant!r}) rejected at federated "
            f"admission: {reason}", reason=reason, tenant=tenant)

    def _pick_victim_locked(self, new_priority: int):
        """Same victim contract as ``RunScheduler._pick_victim_locked``:
        strictly-lower priority only, lowest priority first,
        tie-broken toward the tenant hogging the most queue, then the
        youngest arrival."""
        cands = [t for t in self._queue if t.priority < new_priority]
        if not cands:
            return None
        queued_by_tenant: dict[str, int] = {}
        for t in self._queue:
            queued_by_tenant[t.tenant] = \
                queued_by_tenant.get(t.tenant, 0) + 1
        return min(cands, key=lambda t: (
            t.priority, -queued_by_tenant[t.tenant], -t.seq))

    def _shed_locked(self, t: _Ticket, reason: str) -> None:
        if t.handle.done() or t.committing:
            # terminal exactly once: a concurrent path won (done), or
            # a pump thread accepted the commit under the lock and is
            # resolving it outside — the terminal is already claimed
            return
        if t in self._queue:
            self._queue.remove(t)
        self.journal.write("shed", ticket=t.id, tenant=t.tenant,
                           priority=t.priority, reason=reason,
                           queue_depth=len(self._queue),
                           trace_id=t.trace_id)
        self.metrics.counter("sched.shed", tenant=t.tenant,
                             reason=reason).inc()
        t.handle._finish("shed", error=RunShed(
            f"ticket {t.id} (tenant {t.tenant!r}) shed: {reason}",
            reason=reason, tenant=t.tenant), reason=reason)
        self._note_idle_locked()

    # -- dispatch -------------------------------------------------------
    def _dispatch_locked(self) -> None:
        # sctlint: io-under-lock — the assignment file write must be
        # atomic with the in_flight/queue bookkeeping: dropping the
        # lock between claiming the slot and landing the inbox file
        # would let a concurrent lose/requeue see a worker "running"
        # a ticket whose assignment does not exist yet (the write is
        # one small JSON rename per dispatched ticket)
        if self._closed and not self._queue:
            return
        progress = True
        while progress and self._queue:
            progress = False
            for t in list(self._queue):
                if not t.ready:
                    continue  # its submitter is still writing data
                in_flight = sum(
                    1 for w in self._workers.values()
                    for q in w.in_flight if q.tenant == t.tenant)
                if in_flight >= self.tenant_max_in_flight:
                    continue
                w = self._pick_worker_locked()
                if w is None:
                    return
                self._queue.remove(t)
                self.metrics.gauge("sched.queue_depth").set(
                    len(self._queue))
                t.worker = w
                w.in_flight.append(t)
                t.handle._status = "running"
                t.handle.worker = w.name
                apath = os.path.join(w.dir, "inbox",
                                     f"{t.id}-{t.epoch:03d}.json")
                try:
                    with open(apath + ".tmp", "w") as f:
                        json.dump({"ticket": t.id, "epoch": t.epoch,
                                   "dir": t.dir}, f)
                    os.replace(apath + ".tmp", apath)
                except OSError as e:
                    warnings.warn(
                        f"FederationSupervisor: assignment write for "
                        f"{t.id} on {w.name} failed "
                        f"({type(e).__name__}: {e}) — requeueing",
                        RuntimeWarning, stacklevel=2)
                    w.in_flight.remove(t)
                    t.worker = None
                    self._requeue_locked(t, from_worker=w)
                    continue
                self.journal.write("assigned", ticket=t.id,
                                   worker=w.name, epoch=t.epoch,
                                   trace_id=t.trace_id)
                progress = True

    def _pick_worker_locked(self):
        """Least-loaded live worker with a free slot; a wedged
        (partitioned) worker gets nothing new — the supervisor
        cannot reach it to assign, by definition."""
        best = None
        for w in self._workers.values():
            if w.lost or w.stopping or w.wedged or not w.alive():
                continue
            if len(w.in_flight) >= self.worker_capacity:
                continue
            if best is None or len(w.in_flight) < len(best.in_flight):
                best = w
        return best

    def _note_idle_locked(self) -> None:
        # a ticket mid-commit (accepted under the lock, being resolved
        # outside it by a pump thread) is still BUSY: drain() must not
        # release a caller before its handle goes terminal.  O(1) via
        # the counter — self._tickets is never pruned, so scanning it
        # here would put an O(all-tickets-ever) walk under the
        # dispatch lock
        busy = (self._queue
                or self._committing > 0
                or any(w.in_flight for w in self._workers.values()))
        if busy:
            self._all_idle.clear()
        else:
            self._all_idle.set()

    # -- introspection / shutdown ---------------------------------------
    def stats(self) -> dict:
        with self._lock:
            out = {
                "queue_depth": len(self._queue),
                "tickets": len(self._tickets),
                "workers": {
                    w.name: {"gen": w.gen, "alive": w.alive(),
                             "lost": w.lost, "wedged": w.wedged,
                             "beats": w.beats, "served": w.served,
                             "in_flight": [t.id for t in w.in_flight]}
                    for w in self._workers.values()},
            }
        # breaker snapshot OUTSIDE the dispatch lock: the federated
        # registry READS STATE FILES to cover breakers other
        # processes tripped — file IO under the lock would starve
        # heartbeat crediting and could rule a healthy worker
        # process_lost off a slow disk (SCT011)
        out["breakers"] = self.breakers.snapshot()
        if self.transport is not None:
            out["transport"] = self.transport.stats()
        return out

    def drain(self, timeout: float | None = None) -> bool:
        """Block until every admitted ticket is terminal (REAL
        event-wait on worker progress; returns False on timeout)."""
        return self._all_idle.wait(timeout)

    def shutdown(self, wait: bool = True, shed_queued: bool = False,
                 timeout: float | None = None) -> bool:
        """Stop admitting, stop the workers (graceful: each finishes
        its current assignment, then exits on the stop file), shed
        whatever never ran, write ``metrics.json``.  Idempotent."""
        with self._lock:
            self._closed = True
            if shed_queued:
                for t in list(self._queue):
                    self._shed_locked(t, "shutdown")
            stopping = []
            for w in self._workers.values():
                if w.lost:
                    continue
                w.stopping = True
                stopping.append(w)
        # stop-file writes OUTSIDE the lock (SCT011: no file IO under
        # the dispatch lock).  Safe unlocked: `stopping` was claimed
        # under the lock, and a worker that loses its lease in the
        # window simply ignores a stop file in a dir it no longer
        # scans
        for w in stopping:
            try:
                with open(os.path.join(w.dir, "stop"), "w") as f:
                    f.write("stop\n")
            except OSError as e:
                warnings.warn(
                    f"FederationSupervisor: stop file for "
                    f"{w.name} failed ({type(e).__name__}: {e}) "
                    "— will terminate instead", RuntimeWarning,
                    stacklevel=2)
        self._monitor_stop.set()
        if not wait:
            return False
        # REAL joins (cf. scheduler.shutdown): these are actual
        # subprocesses; a virtual clock would rule a healthy drain
        # timed out instantly
        deadline = (None if timeout is None
                    else SYSTEM_CLOCK.monotonic() + timeout)
        ok = True
        for w in list(self._workers.values()):
            if w.proc is None:
                continue
            left = (None if deadline is None
                    else max(0.0, deadline - SYSTEM_CLOCK.monotonic()))
            try:
                w.proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                ok = False
                with contextlib.suppress(OSError):
                    os.kill(w.pid, signal.SIGKILL)
                with contextlib.suppress(subprocess.TimeoutExpired):
                    w.proc.wait(timeout=10)
            if w.pump is not None:
                w.pump.join(timeout=10)
        with self._lock:
            # anything still non-terminal can never run now
            for t in list(self._queue):
                self._shed_locked(t, "shutdown")
            for t in self._tickets.values():
                if not t.handle.done():
                    self._shed_locked(t, "shutdown")
        if self._monitor is not None:
            self._monitor.join(timeout=10)
        if self.transport is not None:
            self.transport.close()
        mpath = os.path.join(self.fed_dir, "metrics.json")
        try:
            self.metrics.write(mpath)
        except OSError as e:
            warnings.warn(
                f"FederationSupervisor: could not write {mpath} "
                f"({type(e).__name__}: {e})", RuntimeWarning,
                stacklevel=2)
        # the fleet trail's FINAL flush and the merged Perfetto
        # timeline: both best-effort — observability must degrade,
        # never turn a clean shutdown into a failure
        self._flush_obs()
        try:
            self._export_fleet_trace()
        except (OSError, ValueError) as e:
            warnings.warn(
                f"FederationSupervisor: fleet trace export failed "
                f"({type(e).__name__}: {e})", RuntimeWarning,
                stacklevel=2)
        return ok

    def _export_fleet_trace(self) -> str | None:
        """Merge the span trees every terminal ticket's owning worker
        returned through the result-file handoff with the
        supervisor's own spans into ONE Perfetto timeline
        (``fed_dir/trace.json``, one pid per process) — the whole
        fleet on one ruler.  Returns the path, or ``None`` when no
        process recorded a span."""
        with self._lock:
            accepted = [(t.accepted[0], t.accepted[1], t.dir)
                        for t in self._tickets.values()
                        if t.accepted is not None]
        by_worker: dict[str, list] = {}
        for wname, epoch, tdir in accepted:
            rpath = os.path.join(tdir, f"result-{epoch:03d}.json")
            try:
                with open(rpath) as f:
                    spans = json.load(f).get("spans") or []
            except (OSError, ValueError):
                continue  # a lost result file costs its own spans only
            by_worker.setdefault(wname, []).extend(spans)
        processes = [("supervisor", trace.all_spans())]
        processes += [(f"worker:{name}", spans)
                      for name, spans in sorted(by_worker.items())]
        if not any(spans for _, spans in processes):
            return None
        return trace.export_fleet_trace(
            os.path.join(self.fed_dir, "trace.json"), processes)


# ---------------------------------------------------------------------------
# The worker-process entry point
# ---------------------------------------------------------------------------

def _build_runner_defaults(cfg: dict) -> dict:
    from .runner import RetryPolicy

    rcfg = dict(cfg.get("runner") or {})
    out: dict = {}
    if rcfg.get("policy"):
        out["policy"] = RetryPolicy(**rcfg["policy"])
    if rcfg.get("step_deadline_s") is not None:
        out["step_deadline_s"] = float(rcfg["step_deadline_s"])
    if "fallback_backend" in rcfg:
        out["fallback_backend"] = rcfg["fallback_backend"]
    if rcfg.get("fuse"):
        out["fuse"] = True
    if rcfg.get("assume_healthy"):
        # the federation tier already supervises this PROCESS; the
        # per-run subprocess device probe is redundant noise here
        out["probe"] = lambda: {"ok": True}
    return out


def worker_main(fed_dir: str, worker_id: str, gen: int = 0) -> int:
    """The supervised worker loop (subprocess entry point — the
    supervisor spawns ``python -c 'from sctools_tpu.federation import
    worker_main; ...'``).

    Protocol: heartbeat lines on stderr every ``heartbeat_s`` from a
    side thread (the lease stays fresh while a run executes); inbox
    scans every ``poll_s``; each assignment runs through ONE inner
    ``RunScheduler`` worker (shared federated breakers, worker
    journal at ``workers/<id>/journal.jsonl``, chaos re-armed from
    ``config.json`` specs for gen-0 incarnations); results commit by
    atomic rename AFTER a fence re-check, tagged with the assignment
    epoch — the supervisor accepts only the current epoch, so a
    fenced worker can never double-commit.  Exit codes: 0 (stop
    file), 3 (fenced)."""
    from .scheduler import RunScheduler

    wdir = os.path.join(fed_dir, "workers", worker_id)
    with open(os.path.join(fed_dir, "config.json")) as f:
        cfg = json.load(f)
    heartbeat_s = float(cfg.get("heartbeat_s", 1.0))
    poll_s = float(cfg.get("poll_s", 0.25))
    if cfg.get("init_module"):
        import importlib

        importlib.import_module(cfg["init_module"])
    chaos = None
    chaos_name = worker_id if gen == 0 else f"{worker_id}#{gen}"
    for pattern, spec in (cfg.get("chaos_specs") or {}).items():
        if fnmatch.fnmatchcase(chaos_name, pattern):
            from .utils.chaos import ChaosMonkey

            chaos = ChaosMonkey.from_spec(spec)
            break
    #: the worker's own journal: the inner scheduler appends run
    #: lifecycle here; in socket mode the transport's net_* records
    #: and the breakers' probe audit land in the same file
    #: (`_Journal` appends are line-atomic across instances)
    wjournal = _Journal(os.path.join(wdir, "journal.jsonl"))
    #: the worker's OWN registry (not the process default): the inner
    #: scheduler, transport and breakers all record here, and the
    #: heartbeat thread ships its ticks to the supervisor as lossy
    #: obs deltas — the worker side of the fleet trail
    wmetrics = telemetry.MetricsRegistry()
    tcfg = cfg.get("transport") or {}
    net = None
    if tcfg.get("kind") == "socket":
        net = SocketTransport(worker_id, chaos=chaos,
                              journal=wjournal, metrics=wmetrics,
                              seed=gen)
        net.connect("supervisor", tcfg["host"], int(tcfg["port"]))

    def say(kind: str, **fields) -> None:
        """The worker's message plane: stderr lines by default, the
        socket when config.json says so.  Socket messages carry this
        incarnation's ``gen`` (the supervisor refuses a stale gen's
        commit) and beats never retry — a lost beat is healed by the
        next one, while done/refused spend the full retry budget
        (and even a gave-up degrades to the result-file probe)."""
        if net is None:
            _say(kind, **fields)
            return
        if kind == "done" and os.environ.get("SCT_FED_TEST_MUTE_DONE"):
            return  # same lost-doorbell test hook as the file plane
        fields.setdefault("gen", gen)
        net.send("supervisor", kind,
                 retries=0 if kind in LOSSY_KINDS else None,
                 **fields)

    breakers = FederatedBreakerRegistry(
        os.path.join(fed_dir, "breakers"), owner=worker_id,
        journal=wjournal, transport=net,
        peers=("supervisor",) if net is not None else (),
        **(cfg.get("breaker") or {}))
    say("hello", pid=os.getpid(), gen=gen)
    stop_beats = threading.Event()
    seq = [0]

    def _beats():
        # the heartbeat cadence doubles as the obs-shipping cadence:
        # tick the local trail, export only what changed, and ship it
        # as a LOSSY frame (zero retries on the socket — a dropped
        # frame costs its own window of samples and nothing else).
        # Any obs failure degrades to noise: telemetry must never
        # stop the heartbeat that keeps this worker's lease alive.
        while not stop_beats.wait(heartbeat_s):
            seq[0] += 1
            say("beat", seq=seq[0])
            try:
                wmetrics.tick()
                delta = wmetrics.snapshot_delta()
                if (delta["counters"] or delta["gauges"]
                        or delta["histograms"]):
                    say("obs", seq=seq[0],
                        delta=json.dumps(delta,
                                         separators=(",", ":")))
            except Exception as e:  # noqa: BLE001 — obs is lossy by
                # contract: a telemetry fault must degrade to worker
                # noise, never kill the heartbeat thread
                say("noise", obs_error=type(e).__name__)

    hb = threading.Thread(target=_beats, daemon=True,
                          name="sct-fed-heartbeat")
    hb.start()

    def fenced() -> bool:
        return os.path.exists(os.path.join(wdir, "fence.json"))

    def stopped() -> bool:
        return os.path.exists(os.path.join(wdir, "stop"))

    inbox = os.path.join(wdir, "inbox")
    rc = 0
    sched = RunScheduler(
        max_concurrency=1, queue_high_water=1_000_000,
        tenant_max_in_flight=1_000_000, tenant_max_queued=1_000_000,
        journal_path=os.path.join(wdir, "journal.jsonl"),
        metrics=wmetrics, breakers=breakers, chaos=chaos,
        runner_defaults=_build_runner_defaults(cfg))
    try:
        while True:
            if fenced():
                rc = 3
                break
            names = []
            try:
                names = sorted(os.listdir(inbox))
            except OSError as e:
                say("noise", inbox_error=type(e).__name__)
            ran = False
            for fn in names:
                if not fn.endswith(".json"):
                    continue
                apath = os.path.join(inbox, fn)
                try:
                    with open(apath) as f:
                        assign = json.load(f)
                except (OSError, ValueError):
                    continue  # partial write: next scan reads it whole
                _run_assignment(sched, assign, wdir, fenced, say=say)
                with contextlib.suppress(OSError):
                    os.unlink(apath)
                ran = True
                if fenced():
                    break
            if ran:
                continue  # re-scan immediately: more may have landed
            if stopped():
                break
            SYSTEM_CLOCK.sleep(poll_s)
    finally:
        stop_beats.set()
        sched.shutdown(wait=True, timeout=60)
        hb.join(timeout=5)
        if net is not None:
            net.close()
    return rc


def _subst_ticket_dir(params: dict, tdir: str) -> dict:
    """Expand the ``{ticket_dir}`` placeholder in string-valued step
    params to the per-ticket directory.  The seam that makes
    REQUEUED TRAINING TICKETS resume from the training cursor: a
    ``model.scvi_stream`` step submitted with
    ``checkpoint="{ticket_dir}/train.npz"`` resolves to the SAME path
    on whichever worker owns the epoch (the ticket dir lives in the
    shared fed dir), so the respawned owner picks up the previous
    owner's mid-epoch cursor instead of restarting the epoch —
    exactly the runner-checkpoint at-most-once story, extended to
    sub-step (shard-boundary) granularity."""
    return {k: (v.replace("{ticket_dir}", tdir)
                if isinstance(v, str) and "{ticket_dir}" in v else v)
            for k, v in params.items()}


def _run_assignment(sched, assign: dict, wdir: str, fenced,
                    say=_say) -> None:
    """Run one assignment through the worker's inner scheduler and
    commit the result under the assignment epoch (fence re-checked at
    the commit boundary).  ``say`` is the worker's message plane
    (stderr lines or the socket transport)."""
    tid, epoch, tdir = assign["ticket"], assign["epoch"], assign["dir"]
    try:
        with open(os.path.join(tdir, "ticket.json")) as f:
            spec = json.load(f)
        data = load_celldata(os.path.join(tdir, "data.npz"))
    except (OSError, ValueError) as e:
        # an unreadable ticket must still reach a TERMINAL state —
        # going silent here would leave the handle blocked forever
        # (the worker keeps heartbeating, so no lease ever expires)
        say("done", ticket=tid, epoch=epoch, status="failed")
        say("noise", ticket=tid, load_error=type(e).__name__)
        return
    pipeline = Pipeline([Transform(name, backend=backend,
                                   **_subst_ticket_dir(params, tdir))
                         for name, backend, params in spec["steps"]])
    runner_kw = dict(spec.get("runner_kw") or {})
    # the SHARED per-ticket checkpoint home: a requeued epoch RESUMES
    # from the previous owner's fingerprinted checkpoints — at-most-
    # once execution for completed stages, never a replay
    runner_kw.setdefault("checkpoint_dir", os.path.join(tdir, "ckpt"))
    tr_id = str(spec.get("trace_id") or "")
    status, error = "completed", None
    out = None
    try:
        h = sched.submit(pipeline, data, tenant=spec["tenant"],
                         backend=spec.get("backend"),
                         runner_kw=runner_kw,
                         trace_id=tr_id or None)
        out = h.result()
    except BaseException as e:  # noqa: BLE001 — the worker loop must
        # survive anything a run raises; the verdict is committed as
        # a failed result and the inner journal has the classified
        # story
        status, error = "failed", f"{type(e).__name__}: {e}"
    if fenced():
        # the supervisor revoked this worker's lease while the run
        # executed (split-brain partition): DO NOT COMMIT — the
        # requeued epoch's owner is the one that counts
        say("refused", ticket=tid, epoch=epoch)
        return
    rbase = os.path.join(tdir, f"result-{epoch:03d}")
    # the span-tree handoff: this ticket's spans (keyed by the
    # admission trace_id the runner stamped into span meta) ride the
    # result file back to the supervisor, which merges every
    # process's trees into one Perfetto timeline at shutdown
    spans = []
    if tr_id:
        spans = [s for s in trace.serialize_spans(trace.all_spans())
                 if (s.get("meta") or {}).get("trace_id") == tr_id]
    try:
        if status == "completed":
            save_celldata(out, rbase + ".npz")
        with open(rbase + ".json.tmp", "w") as f:
            json.dump({"status": status, "error": error,
                       "epoch": epoch, "ts": round(time.time(), 3),
                       "trace_id": tr_id, "spans": spans}, f)
        os.replace(rbase + ".json.tmp", rbase + ".json")
    except OSError as e:
        # a failed COMMIT (disk full, result dir gone) is still a
        # terminal verdict for this epoch: report it failed so the
        # supervisor resolves the handle instead of waiting forever
        status = "failed"
        say("noise", ticket=tid, commit_error=type(e).__name__)
    say("done", ticket=tid, epoch=epoch, status=status)
