"""Preprocessing recipes — scanpy's ``pp.recipe_*`` one-call
pipelines, expressed as this framework's ``Pipeline`` objects.

Capability parity: scanpy ships canned preprocessing recipes
(``recipe_zheng17`` from the 10x 1.3M-cell paper, ``recipe_seurat``
from the original Seurat workflow); the reference source was
unavailable (/root/reference empty — SURVEY.md §0), so the public
scanpy step lists are the contract.  Each recipe here is BOTH a
registered one-call op (``sct.apply("recipe.zheng17", data,
backend="tpu")``) and a ``Pipeline`` factory (``zheng17_pipeline()``)
so users can inspect, edit, or checkpoint the steps.

The registered form snapshots raw counts into ``layers['counts']``
first (``util.snapshot_layer``) — the recipes normalise in place and
downstream DE usually wants the raw counts back.
"""

from __future__ import annotations

from .data.dataset import CellData
from .registry import Pipeline, register

# Pipeline-shaped recipes by short name — the index both
# ``recipe_pipeline()`` and ``run_recipe()`` dispatch through.
# ``weinreb17`` is deliberately absent: its gene filter needs host-side
# moment thresholding between device steps, so it exists only as the
# registered one-call op and cannot be checkpointed step-wise.
PIPELINES: dict = {}


def _pipeline_recipe(name: str):
    def deco(factory):
        PIPELINES[name] = factory
        return factory

    return deco


def recipe_pipeline(name: str, **kw) -> Pipeline:
    """Build the named recipe's :class:`Pipeline` (``"zheng17"``,
    ``"seurat"``, ``"pearson_residuals"``) with the factory's keyword
    arguments — the inspectable/editable/checkpointable form of the
    one-call ``recipe.*`` ops."""
    try:
        factory = PIPELINES[name]
    except KeyError:
        raise KeyError(
            f"no pipeline-shaped recipe named {name!r}; known: "
            f"{sorted(PIPELINES)} (weinreb17 is one-call only — its "
            f"gene filter thresholds host-side moments mid-recipe)"
        ) from None
    return factory(**kw)


def run_recipe(name: str, data: CellData, *, backend: str | None = None,
               checkpoint_dir: str | None = None, resume: bool = True,
               step_deadline_s: float | None = None,
               fuse: bool = False, mesh=None, bucketize: bool = False,
               runner_kw: dict | None = None, **recipe_kw) -> CellData:
    """Run a named recipe under the resilient execution layer.

    The one-call ``apply("recipe.seurat", ...)`` form dies on the
    first transient device error and restarts from scratch; this form
    builds the recipe's :class:`Pipeline` and hands it to
    ``runner.ResilientRunner`` — per-step retry with backoff, a
    circuit breaker over repeated transient failures, health-checked
    CPU fallback, optional per-step wall-clock deadlines
    (``step_deadline_s=``), and (with ``checkpoint_dir=``) digest-
    verified per-step checkpoints so a killed run resumes at the
    failed step.  Corrupt checkpoint files are quarantined (moved to
    ``checkpoint_dir/quarantine/``, never deleted) and resume falls
    back past them.  The input data's content digest is part of every
    checkpoint fingerprint: calling again with DIFFERENT data and the
    same ``checkpoint_dir`` recomputes instead of silently returning
    the previous run's result.  ``runner_kw`` forwards to the runner
    constructor (``policy=``, ``isolate=``, ``preflight=``,
    ``breaker=``, ``metrics=`` …); ``recipe_kw`` to the recipe
    factory (``n_top_genes=`` …).

    Observability rides along for free: every step is traced and
    auto-instrumented (per-op call/duration metrics, labelled
    cpu/tpu/degraded), every retry/degrade/breaker/quarantine ruling
    is journaled AND counted, and with ``checkpoint_dir=`` the run
    leaves ``journal.jsonl`` + ``metrics.json`` + a
    Perfetto-loadable ``trace.json`` behind —
    ``python -m tools.sctreport <checkpoint_dir>`` merges them into
    one run report (docs/GUIDE.md "Reading a run report").

    ``fuse=True`` compiles the recipe into fused execution stages
    first (``plan.fused_pipeline``): runs of consecutive
    jit-traceable device transforms become ONE cached compiled program
    and ONE retryable runner step — retries, deadlines, chaos faults
    and checkpoints all rule at stage granularity (fused and unfused
    checkpoints have different step fingerprints, so toggling ``fuse``
    across a resume recomputes rather than mixing layouts).  The
    one-call ``apply("recipe.*")`` forms fuse by default; here it is
    opt-in to keep existing checkpoint directories resumable.

    ``mesh=`` (with ``fuse=True``; a ``parallel.make_mesh`` cell
    mesh) compiles MESH-SHARDED stages — one program across the mesh
    per stage, shard the input first with ``parallel.shard_celldata``
    — and arms the runner's fewer-devices degrade rung
    (docs/GUIDE.md "Making a recipe fast", multi-device walkthrough).

    ``bucketize=True`` pads the input to the nearest shape bucket
    before running (``buckets.pad_to_bucket``) and trims the padding
    off the result: every differently-shaped upload that lands in the
    same bucket reuses the SAME compiled programs (with ``fuse=True``,
    the plan cache keys on the bucket shape and the validity mask rides
    along as a traced leaf).  Every step of the recipe must be
    registered ``mask_aware`` or this raises up front, naming the
    offending step — see docs/ARCHITECTURE.md "Shape bucketing".
    Checkpoints taken under ``bucketize=True`` fingerprint the PADDED
    data (mask included), so resuming with a different true shape in
    the same bucket recomputes rather than reusing a stale result.

    >>> out = run_recipe("seurat", data, backend="tpu",
    ...                  checkpoint_dir="ck/", step_deadline_s=900,
    ...                  n_top_genes=2000)
    """
    from .runner import ResilientRunner

    kw = dict(runner_kw or {})
    if step_deadline_s is not None:
        # the explicit parameter wins over a runner_kw duplicate — a
        # silently-discarded deadline budget is exactly the kind of
        # config drift the journal exists to rule out
        kw["step_deadline_s"] = step_deadline_s
    pipeline = recipe_pipeline(name, **recipe_kw)
    info = None
    if bucketize:
        from . import buckets

        buckets.validate_bucketizable(pipeline, backend or "tpu")
        data, info = buckets.pad_to_bucket(data)
    # mesh without fuse raises in the ResilientRunner constructor —
    # the guard lives on the mechanism, so direct runner users get it
    runner = ResilientRunner(pipeline,
                             checkpoint_dir=checkpoint_dir, fuse=fuse,
                             mesh=mesh, **kw)
    out = runner.run(data, backend=backend, resume=resume)
    if info is not None:
        from . import buckets

        out = buckets.trim_from_bucket(out, info)
    return out


def submit_recipe(scheduler, name: str, data: CellData, *,
                  tenant: str = "default", priority: int = 0,
                  deadline_s: float | None = None,
                  backend: str | None = None,
                  checkpoint_dir: str | None = None,
                  step_deadline_s: float | None = None,
                  fuse: bool = False, bucketize: bool = False,
                  runner_kw: dict | None = None,
                  **recipe_kw):
    """Submit a named recipe to a :class:`~sctools_tpu.scheduler.
    RunScheduler` — the multi-tenant form of :func:`run_recipe`.

    Where ``run_recipe`` executes inline (one island per call), this
    queues the recipe behind the scheduler's admission control:
    bounded concurrency, per-tenant quotas, queue deadlines and load
    shedding, with circuit-breaker state shared per backend across
    every run in the pool.  Returns the scheduler's ``RunHandle``
    immediately (``.result()`` blocks for the output); raises
    ``scheduler.RunRejected`` when admission refuses the submission.

    >>> with RunScheduler(max_concurrency=4) as sched:
    ...     h = submit_recipe(sched, "seurat", data, tenant="lab-a",
    ...                       priority=1, deadline_s=600,
    ...                       backend="tpu", n_top_genes=2000)
    ...     out = h.result()

    ``bucketize=True`` (see :func:`run_recipe`) pads to the shape
    bucket BEFORE admission — deliberately, so the scheduler's memory
    estimate charges the bucket shape the device will actually hold,
    not the smaller true shape — and returns a
    :class:`~sctools_tpu.buckets.TrimmingHandle` whose ``result()``
    trims the padding back off.
    """
    kw = dict(runner_kw or {})
    if checkpoint_dir is not None:
        kw["checkpoint_dir"] = checkpoint_dir
    if step_deadline_s is not None:
        kw["step_deadline_s"] = step_deadline_s
    if fuse:
        kw["fuse"] = True
    pipeline = recipe_pipeline(name, **recipe_kw)
    info = None
    if bucketize:
        from . import buckets

        buckets.validate_bucketizable(pipeline, backend or "tpu")
        data, info = buckets.pad_to_bucket(data)
    h = scheduler.submit(pipeline, data,
                         tenant=tenant, priority=priority,
                         deadline_s=deadline_s, backend=backend,
                         runner_kw=kw)
    if info is not None:
        from .buckets import TrimmingHandle

        return TrimmingHandle(h, info)
    return h


@_pipeline_recipe("zheng17")
def zheng17_pipeline(n_top_genes: int = 1000) -> Pipeline:
    """Zheng et al. 2017 (10x 1.3M-cell paper) steps: gene filter →
    count normalise → dispersion HVG subset → renormalise → log1p →
    scale (no clip)."""
    return Pipeline([
        ("util.snapshot_layer", {"layer": "counts"}),
        ("qc.filter_genes", {"min_cells": 1}),
        ("normalize.library_size", {"target_sum": None}),  # per-cell median
        # published recipe_zheng17 ranks genes with the cell_ranger
        # flavor (percentile-binned signed normalized dispersion)
        ("hvg.select", {"n_top": n_top_genes, "flavor": "cell_ranger",
                        "subset": True}),
        ("normalize.library_size", {"target_sum": None}),
        ("normalize.log1p", {}),
        ("normalize.scale", {"max_value": None}),
    ])


@_pipeline_recipe("seurat")
def seurat_pipeline(n_top_genes: int = 2000,
                    min_genes: int = 200, min_cells: int = 3,
                    target_sum: float = 1e4) -> Pipeline:
    """Classic Seurat workflow steps: cell filter → gene filter →
    library-size normalise → log1p → dispersion HVG subset → scale
    clipped at 10."""
    return Pipeline([
        ("util.snapshot_layer", {"layer": "counts"}),
        ("qc.per_cell_metrics", {}),  # filter_cells reads its columns
        ("qc.filter_cells", {"min_genes": min_genes}),
        ("qc.filter_genes", {"min_cells": min_cells}),
        ("normalize.library_size", {"target_sum": target_sum}),
        ("normalize.log1p", {}),
        ("hvg.select", {"n_top": n_top_genes, "flavor": "dispersion",
                        "subset": True}),
        ("normalize.scale", {"max_value": 10.0}),
    ])


@register("recipe.zheng17", backend="tpu")
def recipe_zheng17_tpu(data: CellData,
                       n_top_genes: int = 1000) -> CellData:
    """One-call Zheng et al. 2017 preprocessing (see
    ``zheng17_pipeline`` for the step list).  Runs FUSED: consecutive
    device steps execute as one cached compiled program, so repeated
    invocations on same-shaped data skip retrace entirely
    (docs/ARCHITECTURE.md "Execution plans & fusion")."""
    return zheng17_pipeline(n_top_genes).run(data, backend="tpu",
                                             fuse=True)


@register("recipe.zheng17", backend="cpu")
def recipe_zheng17_cpu(data: CellData,
                       n_top_genes: int = 1000) -> CellData:
    return zheng17_pipeline(n_top_genes).run(data, backend="cpu")


@register("recipe.seurat", backend="tpu")
def recipe_seurat_tpu(data: CellData, n_top_genes: int = 2000,
                      min_genes: int = 200, min_cells: int = 3,
                      target_sum: float = 1e4) -> CellData:
    """One-call classic-Seurat preprocessing (see ``seurat_pipeline``
    for the step list).  Runs FUSED like ``recipe.zheng17``."""
    return seurat_pipeline(n_top_genes, min_genes, min_cells,
                           target_sum).run(data, backend="tpu",
                                           fuse=True)


@register("recipe.seurat", backend="cpu")
def recipe_seurat_cpu(data: CellData, n_top_genes: int = 2000,
                      min_genes: int = 200, min_cells: int = 3,
                      target_sum: float = 1e4) -> CellData:
    return seurat_pipeline(n_top_genes, min_genes, min_cells,
                           target_sum).run(data, backend="cpu")


def _weinreb17(data: CellData, backend: str, log: bool,
               mean_threshold: float, cv_threshold: float,
               n_comps: int) -> CellData:
    """Shared Weinreb et al. 2017 (SPRING) preprocessing body.

    Step list (the public scanpy ``pp.recipe_weinreb17`` contract —
    reference source unavailable, SURVEY.md §0): per-cell count
    normalisation → gene filter by mean AND coefficient of variation
    thresholds → per-gene z-score → randomized PCA.  The CV filter is
    computed on the NORMALISED PRE-LOG counts (CV on log-counts would
    compress the threshold's meaning); ``log=True`` applies log1p
    between the filter and the z-score.
    """
    import numpy as np

    from .registry import apply

    d = apply("util.snapshot_layer", data, layer="counts",
              backend=backend)
    d = apply("normalize.library_size", d, target_sum=None,
              backend=backend)
    if backend == "tpu":
        # moments AND the mean/CV thresholding stay ON DEVICE — the
        # consumer (the gene subset below) is the next device stage.
        # The ONE host materialisation is the boolean keep-mask fetch:
        # the subset's output shape depends on it, so the sync is
        # inherent to the filter, not an implementation round-trip
        # (previously mu and var were both fetched and thresholded on
        # host — two array transfers plus host math on the hot path).
        import jax.numpy as jnp

        from .ops.hvg import _gene_moments_tpu

        mu_d, var_d, _ = _gene_moments_tpu(d.X)  # sparse AND dense X
        cv_d = (jnp.sqrt(jnp.maximum(var_d, 0.0))
                / jnp.maximum(mu_d, 1e-12))
        keep = np.asarray((mu_d >= mean_threshold)
                          & (cv_d >= cv_threshold))
    else:
        from .ops.hvg import _gene_moments_cpu

        mu, var = _gene_moments_cpu(d.X)
        cv = np.sqrt(np.maximum(var, 0.0)) / np.maximum(mu, 1e-12)
        keep = (mu >= mean_threshold) & (cv >= cv_threshold)
    if not keep.any():
        raise ValueError(
            f"recipe.weinreb17: no gene passes mean>={mean_threshold} "
            f"and cv>={cv_threshold}; loosen the thresholds")
    idx = np.flatnonzero(keep)
    if backend == "tpu":
        from .ops.hvg import select_genes_device

        d = select_genes_device(d, idx, compact=True)
    else:
        import scipy.sparse as sp

        X = d.X
        Xs = (X.tocsc()[:, idx].tocsr() if sp.issparse(X)
              else np.asarray(X)[:, idx])
        var_d = {k: np.asarray(v)[idx] for k, v in d.var.items()}
        varm = {k: np.asarray(v)[idx] for k, v in d.varm.items()}
        layers = {k: (v.tocsc()[:, idx].tocsr() if sp.issparse(v)
                      else np.asarray(v)[:, idx])
                  for k, v in d.layers.items()}
        d = d.replace(X=Xs, var=var_d, varm=varm, layers=layers)
    if log:
        d = apply("normalize.log1p", d, backend=backend)
    d = apply("normalize.scale", d, max_value=None, backend=backend)
    # z-scored genes flatten the spectrum's tail; as in the
    # pearson_residuals recipe, the default 2 power iterations
    # under-converge on whitened data — 4 is cheap insurance
    return apply("pca.randomized", d, n_components=n_comps, n_iter=4,
                 backend=backend)


@register("recipe.weinreb17", backend="tpu")
def recipe_weinreb17_tpu(data: CellData, log: bool = True,
                         mean_threshold: float = 0.01,
                         cv_threshold: float = 2.0,
                         n_comps: int = 50) -> CellData:
    """One-call Weinreb et al. 2017 (SPRING) preprocessing: count
    normalise → mean/CV gene filter → log1p → z-score → 50-PC
    randomized PCA (see ``_weinreb17`` for the exact order)."""
    return _weinreb17(data, "tpu", log, mean_threshold, cv_threshold,
                      n_comps)


@register("recipe.weinreb17", backend="cpu")
def recipe_weinreb17_cpu(data: CellData, log: bool = True,
                         mean_threshold: float = 0.01,
                         cv_threshold: float = 2.0,
                         n_comps: int = 50) -> CellData:
    return _weinreb17(data, "cpu", log, mean_threshold, cv_threshold,
                      n_comps)


@_pipeline_recipe("atlas_knn")
def atlas_knn_pipeline(n_top_genes: int = 2000, n_components: int = 50,
                       k: int = 15, metric: str = "cosine",
                       target_sum: float = 1e4,
                       knn_strategy: str = "ring") -> Pipeline:
    """The north-star atlas tail as ONE pipeline: count normalise →
    log1p → HVG scoring (moment flavor — no subset materialisation,
    so the whole preprocessing chain stays fusable) → scale → 50-PC
    randomized PCA → multi-chip kNN.  Under
    ``plan.fused_pipeline(mesh=...)`` this compiles to exactly two
    sharded stages: one GSPMD program for preprocess+PCA and the
    ppermute-ring kNN collective — the kNN+graph tail fused with
    preprocessing instead of a per-chip dispatch loop around it.
    Single-device (no mesh) it runs as one fused stage plus the
    multichip op on a 1-device mesh."""
    return Pipeline([
        ("normalize.library_size", {"target_sum": target_sum}),
        ("normalize.log1p", {}),
        ("hvg.select", {"n_top": n_top_genes, "flavor": "seurat_v3"}),
        ("normalize.scale", {"max_value": 10.0}),
        ("pca.randomized", {"n_components": n_components}),
        ("neighbors.knn_multichip", {"k": k, "metric": metric,
                                     "strategy": knn_strategy}),
    ])


@_pipeline_recipe("graph_tail")
def graph_tail_pipeline(t: int = 3, mode: str = "umap",
                        reorder: bool = True,
                        jaccard: bool = False) -> Pipeline:
    """The post-kNN graph tail as ONE pipeline: [locality reorder] →
    connectivities → [jaccard] → diffusion operator → MAGIC
    imputation → [restore order].  With ``reorder=True`` (default)
    the graph is RCM-permuted into dense tiles first — every
    iterative kernel downstream sweeps a narrow band instead of the
    whole table (the tiled family in ops/pallas_graph.py reads the
    recorded bandwidth) — and the INVERSE permutation is applied at
    the recipe boundary, so results leave in the caller's row order
    (the round-trip is bitwise, tests/test_graph_reorder.py).
    Requires neighbors.knn."""
    steps: list = []
    if reorder:
        steps.append(("graph.reorder", {}))
    steps.append(("graph.connectivities", {"mode": mode}))
    if jaccard:
        steps.append(("graph.jaccard", {}))
    steps.append(("graph.diffusion_operator", {}))
    steps.append(("impute.magic", {"t": t}))
    if reorder:
        steps.append(("graph.restore_order", {}))
    return Pipeline(steps)


@_pipeline_recipe("annotation_reference")
def annotation_reference_pipeline(n_components: int = 50,
                                  target_sum: float = 1e4) -> Pipeline:
    """Prepare a reference atlas for the online annotation service
    (``sctools_tpu/serving.py``): snapshot raw counts → library-size
    normalise → log1p → randomized PCA.  Deliberately NO hvg subset
    and no scale: the gene space must stay identical to what raw-count
    queries arrive in (``serving.build_reference_artifact`` freezes
    the loadings + mean + scores this produces, and the query kernel
    applies the same normalise/log1p before projecting), and per-gene
    z-scoring would need the reference's moments shipped to every
    query for no annotation-accuracy win at serving scale."""
    return Pipeline([
        ("util.snapshot_layer", {"layer": "counts"}),
        ("normalize.library_size", {"target_sum": target_sum}),
        ("normalize.log1p", {}),
        ("pca.randomized", {"n_components": n_components}),
    ])


@_pipeline_recipe("pearson_residuals")
def pearson_residuals_pipeline(n_top_genes: int = 2000,
                               theta: float = 100.0,
                               n_components: int = 50,
                               min_cells: int = 5) -> Pipeline:
    """scanpy ``experimental.pp.recipe_pearson_residuals`` steps:
    gene filter → pearson-residual HVG subset (raw counts) →
    analytic Pearson-residual normalisation → randomized PCA."""
    return Pipeline([
        ("util.snapshot_layer", {"layer": "counts"}),
        ("qc.filter_genes", {"min_cells": min_cells}),
        ("hvg.select", {"n_top": n_top_genes,
                        "flavor": "pearson_residuals",
                        "theta": theta, "subset": True}),
        ("normalize.pearson_residuals", {"theta": theta}),
        # residuals are per-gene standardised, so the spectrum's tail
        # is flat and the default 2 power iterations under-converge —
        # 4 is cheap insurance on whitened data
        ("pca.randomized", {"n_components": n_components, "n_iter": 4}),
    ])


@register("recipe.pearson_residuals", backend="tpu")
def recipe_pearson_tpu(data: CellData, n_top_genes: int = 2000,
                       theta: float = 100.0,
                       n_components: int = 50) -> CellData:
    """One-call Pearson-residuals workflow (Lause 2021 / scanpy
    experimental recipe; see ``pearson_residuals_pipeline``).  Runs
    FUSED like ``recipe.zheng17``."""
    return pearson_residuals_pipeline(
        n_top_genes, theta, n_components).run(data, backend="tpu",
                                              fuse=True)


@register("recipe.pearson_residuals", backend="cpu")
def recipe_pearson_cpu(data: CellData, n_top_genes: int = 2000,
                       theta: float = 100.0,
                       n_components: int = 50) -> CellData:
    return pearson_residuals_pipeline(
        n_top_genes, theta, n_components).run(data, backend="cpu")
