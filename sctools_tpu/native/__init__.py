"""Native (C++) runtime components with pure-numpy fallbacks.

The reference framework's IO/packing hot loops are native; here the
C++ library lives in ``csrc/`` and is loaded via ctypes.  Every entry
point has a numpy fallback so the package works before the library is
built (``make -C csrc``).
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

_LIB = None
_LIB_TRIED = False


def _load_lib():
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    for cand in (
        os.path.join(here, "csrc", "libscio.so"),
        os.path.join(os.path.dirname(__file__), "libscio.so"),
    ):
        if os.path.exists(cand):
            try:
                lib = ctypes.CDLL(cand)
                lib.scio_pack_ell_f32.restype = None
                lib.scio_pack_ell_f32.argtypes = [
                    ctypes.POINTER(ctypes.c_int64),  # indptr
                    ctypes.POINTER(ctypes.c_int32),  # col indices
                    ctypes.POINTER(ctypes.c_float),  # data
                    ctypes.c_int64,  # n_rows
                    ctypes.c_int64,  # rows_padded
                    ctypes.c_int64,  # capacity
                    ctypes.c_int32,  # sentinel
                    ctypes.POINTER(ctypes.c_int32),  # out indices
                    ctypes.POINTER(ctypes.c_float),  # out data
                ]
                _LIB = lib
                break
            except OSError:
                continue
    return _LIB


def have_native() -> bool:
    return _load_lib() is not None


def louvain_sweeps(idx, w, labels, resolution=1.0, n_sweeps=20):
    """Serial greedy Louvain local-move sweeps (native oracle) on a
    symmetric padded-ELL graph.  Mutates and returns ``labels``
    (int32); returns None when the native library is unavailable (the
    caller falls back to the Python sweep loop).

    The native path exists so cluster.leiden parity tests can assert
    against the serial oracle at 100k+ nodes — the pure-Python sweeps
    cap out around a few thousand (round-3 VERDICT Weak #5)."""
    lib = _load_lib()
    if lib is None or not hasattr(lib, "scio_louvain_sweeps"):
        return None
    idx = np.ascontiguousarray(idx, dtype=np.int32)
    w = np.ascontiguousarray(w, dtype=np.float32)
    labels = np.ascontiguousarray(labels, dtype=np.int32)
    n, k = idx.shape
    lib.scio_louvain_sweeps.restype = ctypes.c_int64
    lib.scio_louvain_sweeps.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64, ctypes.c_int64, ctypes.c_double, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32),
    ]
    moves = lib.scio_louvain_sweeps(
        idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        w.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n, k, float(resolution), int(n_sweeps),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    if moves < 0:  # invalid labels (negative id) — caller falls back
        return None
    return labels


def pack_ell(indptr, col_indices, data, rows_padded, capacity, sentinel):
    """CSR → padded-ELL.  Returns (indices, values) numpy arrays of
    shape (rows_padded, capacity)."""
    n_rows = len(indptr) - 1
    lib = _load_lib()
    if lib is not None and data.dtype == np.float32:
        out_idx = np.full((rows_padded, capacity), sentinel, dtype=np.int32)
        out_val = np.zeros((rows_padded, capacity), dtype=np.float32)
        indptr = np.ascontiguousarray(indptr, dtype=np.int64)
        col_indices = np.ascontiguousarray(col_indices, dtype=np.int32)
        data = np.ascontiguousarray(data, dtype=np.float32)
        lib.scio_pack_ell_f32(
            indptr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            col_indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n_rows,
            rows_padded,
            capacity,
            sentinel,
            out_idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            out_val.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        )
        return out_idx, out_val
    return _pack_ell_numpy(indptr, col_indices, data, rows_padded, capacity, sentinel)


def pack_ell_chunks(chunks, rows_padded, capacity, sentinel):
    """Decode several stored CSR chunks (disjoint row ranges of ONE
    shard) into a single padded-ELL buffer — the shard store's read
    path (``data/shardstore.py``).

    ``chunks`` is a list of ``(indptr, col_indices, data, row_offset)``
    tuples; chunk rows land at ``out[row_offset : row_offset + rows]``.
    Native path: ``scio_pack_ell_f32_chunks`` runs one decode thread
    per chunk (the memcpy loops never touch the same output bytes);
    numpy fallback decodes serially.  Returns ``(indices, values)`` of
    shape ``(rows_padded, capacity)``.
    """
    lib = _load_lib()
    if (lib is not None and hasattr(lib, "scio_pack_ell_f32_chunks")
            and all(np.asarray(d).dtype == np.float32
                    for _, _, d, _ in chunks)):
        out_idx = np.full((rows_padded, capacity), sentinel,
                          dtype=np.int32)
        out_val = np.zeros((rows_padded, capacity), dtype=np.float32)
        n = len(chunks)
        if n == 0:
            return out_idx, out_val
        # keep the contiguous per-chunk arrays alive for the call
        indptrs = [np.ascontiguousarray(c[0], np.int64) for c in chunks]
        colids = [np.ascontiguousarray(c[1], np.int32) for c in chunks]
        datas = [np.ascontiguousarray(c[2], np.float32) for c in chunks]
        rows = np.asarray([len(p) - 1 for p in indptrs], np.int64)
        offs = np.asarray([c[3] for c in chunks], np.int64)
        P64 = ctypes.POINTER(ctypes.c_int64)
        P32 = ctypes.POINTER(ctypes.c_int32)
        PF = ctypes.POINTER(ctypes.c_float)
        indptr_ptrs = (P64 * n)(*[a.ctypes.data_as(P64)
                                  for a in indptrs])
        colid_ptrs = (P32 * n)(*[a.ctypes.data_as(P32) for a in colids])
        data_ptrs = (PF * n)(*[a.ctypes.data_as(PF) for a in datas])
        lib.scio_pack_ell_f32_chunks.restype = None
        lib.scio_pack_ell_f32_chunks.argtypes = [
            ctypes.POINTER(P64), ctypes.POINTER(P32), ctypes.POINTER(PF),
            P64, P64, ctypes.c_int64, ctypes.c_int64, P32, PF,
        ]
        lib.scio_pack_ell_f32_chunks(
            indptr_ptrs, colid_ptrs, data_ptrs,
            rows.ctypes.data_as(P64), offs.ctypes.data_as(P64),
            n, capacity,
            out_idx.ctypes.data_as(P32), out_val.ctypes.data_as(PF),
        )
        return out_idx, out_val
    # numpy fallback: serial per-chunk vectorised scatter into slices
    dtype = (np.asarray(chunks[0][2]).dtype if chunks else np.float32)
    out_idx = np.full((rows_padded, capacity), sentinel, dtype=np.int32)
    out_val = np.zeros((rows_padded, capacity), dtype=dtype)
    for indptr, col_indices, data, row0 in chunks:
        rows = len(indptr) - 1
        idx, val = _pack_ell_numpy(
            np.asarray(indptr), np.asarray(col_indices),
            np.asarray(data), rows, capacity, sentinel)
        out_idx[row0: row0 + rows] = idx
        out_val[row0: row0 + rows] = val
    return out_idx, out_val


def _pack_ell_numpy(indptr, col_indices, data, rows_padded, capacity, sentinel):
    n_rows = len(indptr) - 1
    nnz = np.diff(indptr)
    out_idx = np.full((rows_padded, capacity), sentinel, dtype=np.int32)
    out_val = np.zeros((rows_padded, capacity), dtype=data.dtype)
    # Vectorised scatter: slot position of each nonzero within its row.
    rows = np.repeat(np.arange(n_rows), nnz)
    slots = np.arange(len(col_indices)) - np.repeat(indptr[:-1], nnz)
    out_idx[rows, slots] = col_indices
    out_val[rows, slots] = data
    return out_idx, out_val


def parse_mtx(path):
    """Parse a MatrixMarket .mtx file → (n_rows, n_cols, rows, cols, vals).

    Native fast path when built; numpy/scipy fallback otherwise.
    """
    lib = _load_lib()
    if lib is not None and hasattr(lib, "scio_parse_mtx"):
        return _parse_mtx_native(lib, path)
    import scipy.io

    m = scipy.io.mmread(path).tocoo()
    return m.shape[0], m.shape[1], m.row, m.col, m.data


def _parse_mtx_native(lib, path):
    lib.scio_parse_mtx.restype = ctypes.c_int64
    lib.scio_parse_mtx.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
    ]
    nr = ctypes.c_int64()
    nc = ctypes.c_int64()
    nnz = ctypes.c_int64()
    handle = lib.scio_parse_mtx(
        path.encode(), ctypes.byref(nr), ctypes.byref(nc), ctypes.byref(nnz)
    )
    if handle < 0:
        raise IOError(f"native mtx parse failed for {path}")
    n = nnz.value
    rows = np.empty(n, dtype=np.int32)
    cols = np.empty(n, dtype=np.int32)
    vals = np.empty(n, dtype=np.float32)
    lib.scio_fetch_mtx.restype = None
    lib.scio_fetch_mtx.argtypes = [
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_float),
    ]
    lib.scio_fetch_mtx(
        handle,
        rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        cols.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        vals.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
    )
    return nr.value, nc.value, rows, cols, vals
