"""SLO burn-rate rulings over the telemetry time-series trail.

The fleet observability plane's third leg (docs/ARCHITECTURE.md
"Observability"): :class:`SLOMonitor` evaluates DECLARED objectives —
serving p99 latency, admission availability, query error budget —
against the :class:`~sctools_tpu.utils.telemetry.MetricsRegistry`
ring-buffer trail, and journals ``slo_breach`` / ``slo_recovered`` as
first-class events.  An operator is TOLD the budget is burning while
the run is alive, instead of discovering it in a post-mortem report.

Burn rate is the SRE-workbook quantity: the fraction of events in a
window that violated the objective, divided by the objective's error
budget (``1 - target``).  A burn rate of 1.0 spends exactly the
budget over the objective's period; 10x spends it ten times too
fast.  Rulings use the standard TWO-WINDOW guard: a breach opens only
when the FAST window (sensitive, quick to recover) AND the SLOW
window (resistant to blips) both exceed ``burn_threshold`` — a
single slow query cannot page, and a real regression cannot hide
behind an old quiet hour.  The breach closes (``slo_recovered``)
when the fast window's burn drops below 1.0: the budget has stopped
burning faster than allotted.  Every breach pairs with exactly one
recovery — the window-close contract sctreport's fleet section joins
on.

Everything here runs on the INJECTABLE clock (the registry's own) —
zero real sleeps, so a VirtualClock drives a whole breach/recovery
cycle in a test without waiting out a window.  ``time.time()``
appears only as the journal-FACT wall stamp.  No device arrays are
ever touched: evaluation reads Python scalars out of tick records,
so the obs hot path cannot introduce a device sync.

>>> mon = SLOMonitor(metrics, journal=journal,
...                  objectives=serving_objectives())
>>> mon.maybe_evaluate()          # rate-limited; hot paths call this
[("slo_breach", "serving_p99_latency")]
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from .utils.telemetry import MetricsRegistry, split_series_key
from .utils.vclock import Clock

#: threshold alignment epsilon: a bucket whose upper bound equals the
#: objective threshold (within float noise) counts as GOOD
_EPS = 1e-12


@dataclass(frozen=True)
class SeriesSel:
    """Selects metric series by name plus a label subset: matches
    every series whose name equals ``name`` and whose labels contain
    all of ``labels`` (a ``(("k", "v"), ...)`` tuple)."""

    name: str
    labels: tuple = ()

    def matches(self, key: str) -> bool:
        n, lb = split_series_key(key)
        return n == self.name and all(lb.get(k) == v
                                      for k, v in self.labels)


@dataclass(frozen=True)
class Objective:
    """One declared service-level objective.

    ``kind="latency"``: over each window, the fraction of ``metric``
    histogram observations above ``threshold_s`` is the bad fraction
    (the histogram's fixed bucket ladder is the measurement — align
    ``threshold_s`` with a bucket bound or the nearest lower bound
    rules).  ``kind="ratio"``: the bad fraction is
    ``bad / (good + bad)`` over the selected counter deltas.

    ``target`` is the SLO fraction (0.99 → a 1% error budget);
    ``burn_threshold`` is the burn rate BOTH windows must exceed to
    open a breach."""

    name: str
    kind: str  # "latency" | "ratio"
    target: float = 0.99
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    burn_threshold: float = 2.0
    # kind="latency"
    metric: str = ""
    threshold_s: float = 0.0
    # kind="ratio"
    good: SeriesSel | None = None
    bad: tuple = ()

    def __post_init__(self):
        if self.kind not in ("latency", "ratio"):
            raise ValueError(f"unknown objective kind {self.kind!r}")
        if not 0.0 < self.target < 1.0:
            raise ValueError("target must be a fraction in (0, 1) — "
                             "the error budget is 1 - target")
        if self.kind == "latency" and not self.metric:
            raise ValueError("latency objective needs metric=")
        if self.kind == "ratio" and self.good is None:
            raise ValueError("ratio objective needs good=")


def serving_objectives(latency_slo_s: float = 0.05,
                       target: float = 0.99) -> tuple:
    """The serving tier's default objectives: p99-style latency (the
    fraction of completed queries over ``latency_slo_s`` must stay
    within the error budget) and the query error budget (failed/shed
    outcomes vs completed)."""
    return (
        Objective(name="serving_p99_latency", kind="latency",
                  metric="serve.latency_s",
                  threshold_s=latency_slo_s, target=target),
        Objective(name="serving_error_budget", kind="ratio",
                  good=SeriesSel("serve.queries",
                                 (("outcome", "completed"),)),
                  bad=(SeriesSel("serve.queries",
                                 (("outcome", "failed"),)),
                       SeriesSel("serve.queries",
                                 (("outcome", "shed"),))),
                  target=target),
    )


def scheduler_objectives(target: float = 0.99) -> tuple:
    """The admission funnel's default objective: availability —
    rejections (any reason) burn the budget against admissions."""
    return (
        Objective(name="admission_availability", kind="ratio",
                  good=SeriesSel("sched.admitted"),
                  bad=(SeriesSel("sched.rejected"),),
                  target=target),
    )


class SLOMonitor:
    """Evaluates objectives over the registry's time-series trail and
    journals breach/recovery rulings.

    The monitor owns no schedule: hot paths call
    :meth:`maybe_evaluate` (rate-limited on the injectable clock,
    default once per second), supervision loops may call
    :meth:`evaluate` directly.  Each evaluation first ticks the
    registry (rate-limited too), so the trail always reaches "now"
    before a window is read.  Journaling is optional — without a
    journal the rulings still land in ``slo.burn_rate`` /
    ``slo.breaches`` metrics and the returned list."""

    def __init__(self, metrics: MetricsRegistry, journal=None,
                 clock: Clock | None = None, objectives=(),
                 eval_interval_s: float = 1.0,
                 tick_interval_s: float | None = None):
        self.metrics = metrics
        self.journal = journal
        self.clock = clock if clock is not None else metrics.clock
        self.objectives = tuple(objectives)
        self.eval_interval_s = float(eval_interval_s)
        self.tick_interval_s = (float(tick_interval_s)
                                if tick_interval_s is not None
                                else self.eval_interval_s)
        self._lock = threading.Lock()
        self._last_eval: float | None = None
        # objective name -> {"breached": bool, "since": mono,
        #                    "since_wall": wall}
        self._state: dict = {}

    # -- public entry points ---------------------------------------------
    def maybe_evaluate(self) -> list:
        """:meth:`evaluate` if ``eval_interval_s`` has elapsed on the
        injectable clock since the last evaluation (else ``[]``) —
        cheap enough for admission/terminal hot paths."""
        now = self.clock.monotonic()
        with self._lock:
            if self._last_eval is not None and \
                    now - self._last_eval < self.eval_interval_s:
                return []
            self._last_eval = now
        return self.evaluate()

    def evaluate(self) -> list:
        """Tick the trail, measure every objective's fast/slow burn
        rates, rule breaches open/closed.  Returns
        ``[(ruling, objective_name), ...]`` for rulings made NOW."""
        self.metrics.maybe_tick(self.tick_interval_s)
        series = self.metrics.series()
        if not series:
            return []
        latest = series[-1]
        rulings = []
        # journal writes deferred past the lock (SCT011): one list
        # per event so each write site keeps its literal name (SCT009)
        pending_breach = []
        pending_recover = []
        with self._lock:
            for obj in self.objectives:
                fast = self._burn(obj, series, latest,
                                  obj.fast_window_s)
                slow = self._burn(obj, series, latest,
                                  obj.slow_window_s)
                self.metrics.gauge("slo.burn_rate",
                                   objective=obj.name,
                                   window="fast").set(fast)
                self.metrics.gauge("slo.burn_rate",
                                   objective=obj.name,
                                   window="slow").set(slow)
                st = self._state.setdefault(
                    obj.name, {"breached": False})
                if not st["breached"] \
                        and fast >= obj.burn_threshold \
                        and slow >= obj.burn_threshold:
                    st["breached"] = True
                    st["since"] = latest["t"]
                    st["since_wall"] = round(time.time(), 3)
                    self.metrics.counter(
                        "slo.breaches", objective=obj.name).inc()
                    pending_breach.append(
                        dict(objective=obj.name,
                             target=obj.target,
                             burn_fast=round(fast, 3),
                             burn_slow=round(slow, 3),
                             fast_window_s=obj.fast_window_s,
                             slow_window_s=obj.slow_window_s))
                    rulings.append(("slo_breach", obj.name))
                elif st["breached"] and fast < 1.0:
                    st["breached"] = False
                    window_s = latest["t"] - st.get("since",
                                                    latest["t"])
                    pending_recover.append(
                        dict(objective=obj.name,
                             target=obj.target,
                             burn_fast=round(fast, 3),
                             burn_slow=round(slow, 3),
                             breach_window_s=round(window_s, 6)))
                    rulings.append(("slo_recovered", obj.name))
        if self.journal is not None:
            for fields in pending_breach:
                self.journal.write("slo_breach", **fields)
            for fields in pending_recover:
                self.journal.write("slo_recovered", **fields)
        return rulings

    def breached(self, name: str) -> bool:
        with self._lock:
            st = self._state.get(name)
            return bool(st and st["breached"])

    # -- window math -----------------------------------------------------
    @staticmethod
    def _basis(series: list, latest: dict, window_s: float) -> dict:
        """The tick that anchors a window: the NEWEST tick at least
        ``window_s`` old (partial windows fall back to the oldest
        tick — a short trail measures what it has, it does not
        fabricate a quiet past)."""
        cutoff = latest["t"] - window_s
        basis = series[0]
        for rec in series:
            if rec["t"] <= cutoff:
                basis = rec
            else:
                break
        return basis

    def _burn(self, obj: Objective, series: list, latest: dict,
              window_s: float) -> float:
        basis = self._basis(series, latest, window_s)
        if obj.kind == "latency":
            good, bad = self._latency_counts(obj, basis, latest)
        else:
            good, bad = self._ratio_counts(obj, basis, latest)
        total = good + bad
        if total <= 0:
            return 0.0
        return (bad / total) / (1.0 - obj.target)

    @staticmethod
    def _latency_counts(obj: Objective, basis: dict,
                        latest: dict) -> tuple:
        good = bad = 0
        basis_h = basis.get("histograms", {})
        for key, h in latest.get("histograms", {}).items():
            name, _ = split_series_key(key)
            if name != obj.metric:
                continue
            prev = basis_h.get(key)
            counts = h["counts"]
            pcounts = (prev["counts"] if prev
                       else [0] * len(counts))
            delta = [a - b for a, b in zip(counts, pcounts)]
            bounds = h["buckets"]
            for bound, d in zip(bounds, delta):
                if bound <= obj.threshold_s + _EPS:
                    good += d
                else:
                    bad += d
            bad += delta[-1]  # the +inf bucket is always bad
        return good, bad

    @staticmethod
    def _ratio_counts(obj: Objective, basis: dict,
                      latest: dict) -> tuple:
        basis_c = basis.get("counters", {})
        latest_c = latest.get("counters", {})

        def total(sel: SeriesSel) -> float:
            return sum(v - basis_c.get(k, 0.0)
                       for k, v in latest_c.items()
                       if sel.matches(k))

        good = total(obj.good)
        bad = sum(total(sel) for sel in obj.bad)
        return good, bad
