"""sctools-tpu: a TPU-native single-cell analysis framework.

Built from scratch on JAX/XLA/Pallas with the capabilities of
dpeerlab/sctools (reference source unavailable — see SURVEY.md; the
capability contract is BASELINE.json's north star): a ``Transform``
operator registry with pluggable ``backend=`` execution, an
AnnData/CSR loader that materialises device-resident sparse blocks,
vmapped per-cell preprocessing, Seurat-v3 HVG selection, randomized
PCA, tiled distance/kNN kernels, and multi-chip neighbour-graph
construction over a ``jax.sharding.Mesh``.

Quick start::

    import sctools_tpu as sct

    ds = sct.data.synthetic.synthetic_counts(10_000, 2_000, n_clusters=5)
    dev = ds.device_put()
    out = sct.Pipeline([
        ("qc.per_cell_metrics", {}),
        ("normalize.library_size", {"target_sum": 1e4}),
        ("normalize.log1p", {}),
        ("hvg.select", {"n_top": 1000, "subset": True}),
        ("pca.randomized", {"n_components": 50}),
        ("neighbors.knn", {"k": 15, "metric": "cosine"}),
    ]).run(dev, backend="tpu")
"""

from . import (  # noqa: F401  (imports register transforms)
    data, models, ops, parallel, recipes,
)
from .config import config, configure
from .data import CellData, SparseCells
from .data.concat import concat
from .data.shardstore import (ShardReadScheduler, ShardStore,
                              StoreWriter, open_store, write_store)
from .data.io import (from_dense, from_scipy, read, read_10x_h5,
                      read_10x_mtx, read_csv, read_h5ad, read_loom,
                      read_mtx, read_text, write_h5ad, write_loom)
from . import buckets  # noqa: F401  (shape-bucket policy + masks)
from .buckets import pad_to_bucket, trim_from_bucket
from . import memory  # noqa: F401  (budget + estimate model)
from .memory import MemoryBudget
from .plan import describe_plan, fused_pipeline
from .recipes import recipe_pipeline, run_recipe, submit_recipe
from .registry import Pipeline, Transform, apply, backends, names, register
from .runner import ResilientRunner, RetryPolicy
from .scheduler import RunRejected, RunScheduler, RunShed, TenantQuota
from . import serving  # noqa: F401  (registers serve.* transforms)
from .serving import AnnotationService, build_reference_artifact
from . import factory  # noqa: F401  (registers data.append_store)
from .factory import AnnotationFactory
from .federation import (FederatedBreakerRegistry, FederatedRunError,
                         FederationSupervisor, TicketHandle)
from .compat import experimental, external, pp, tl  # scanpy-style namespaces
from . import pl  # scanpy-style plotting namespace (host-side)
from . import datasets  # offline sc.datasets subset
from . import queries  # offline sc.queries subset
from . import settings as logging  # print_header/print_versions/info/hint
from .settings import settings  # scanpy sc.settings analogue
from . import accessors as _accessors
from .registry import get as _registry_get


class _GetNamespace:
    """``sct.get`` serves two scanpy-shaped roles: CALLED, it is the
    registry lookup (``sct.get("normalize.log1p", backend="tpu")``);
    as a namespace it carries the ``sc.get``-style tabular accessors
    (``sct.get.rank_genes_groups_df`` / ``obs_df`` / ``var_df``)."""

    def __call__(self, name, backend=None):
        if backend is None:  # registry default, not a literal None
            return _registry_get(name)
        return _registry_get(name, backend)

    rank_genes_groups_df = staticmethod(_accessors.rank_genes_groups_df)
    obs_df = staticmethod(_accessors.obs_df)
    var_df = staticmethod(_accessors.var_df)


get = _GetNamespace()

__version__ = "0.1.0"

__all__ = [
    "CellData", "SparseCells", "Pipeline", "Transform", "apply", "register",
    "get", "names", "backends", "config", "configure",
    "read", "read_csv", "read_text", "read_mtx", "settings", "logging",
    "read_h5ad", "write_h5ad", "read_10x_mtx", "read_10x_h5", "read_loom",
    "write_loom",
    "from_scipy", "from_dense",
    "pp", "tl", "experimental", "external", "pl", "datasets", "queries",
    "ResilientRunner", "RetryPolicy", "recipe_pipeline", "run_recipe",
    "fused_pipeline", "describe_plan",
    "ShardStore", "ShardReadScheduler", "StoreWriter", "open_store",
    "write_store",
    "AnnotationService", "build_reference_artifact", "serving",
    "MemoryBudget", "memory",
]
