"""``sct.pl`` plotting namespace: every staple draws on a realistic
workflow result, returns live Axes with the expected marks, and
round-trips through savefig (Agg backend — no display needed)."""

import matplotlib

matplotlib.use("Agg")

import numpy as np
import pytest

import sctools_tpu as sct
from sctools_tpu.data.dataset import CellData
from sctools_tpu.data.synthetic import synthetic_counts


@pytest.fixture(scope="module")
def workflow():
    ds = synthetic_counts(600, 300, density=0.12, n_clusters=3, seed=3)
    ds = ds.with_var(gene_name=np.array([f"G{i}" for i in range(300)]))
    out = sct.Pipeline([
        ("qc.per_cell_metrics", {}),
        ("normalize.library_size", {"target_sum": 1e4}),
        ("normalize.log1p", {}),
        ("pca.randomized", {"n_components": 20}),
        ("neighbors.knn", {"k": 10, "metric": "cosine"}),
        ("graph.connectivities", {}),
        ("cluster.leiden", {"resolution": 1.0}),
        ("embed.umap", {"n_epochs": 30}),
        ("graph.paga", {"groups": "leiden"}),
        ("de.rank_genes_groups", {"groupby": "leiden"}),
        ("cluster.dendrogram", {"groupby": "leiden"}),
        ("embed.density", {"basis": "X_umap"}),
    ]).run(ds.device_put(), backend="tpu").to_host()
    return out


def _n_points(ax):
    return sum(len(c.get_offsets()) for c in ax.collections)


def test_embedding_categorical_and_gene(workflow, tmp_path):
    ax = sct.pl.umap(workflow, color="leiden",
                     save=tmp_path / "umap.png")
    assert _n_points(ax) == workflow.n_cells
    assert ax.get_legend() is not None
    assert (tmp_path / "umap.png").stat().st_size > 1000
    # gene-colored: continuous -> one collection + colorbar
    ax2 = sct.pl.umap(workflow, color="G5")
    assert _n_points(ax2) == workflow.n_cells
    assert ax2.get_legend() is None


def test_embedding_missing_basis_raises(workflow):
    with pytest.raises(KeyError, match="X_tsne"):
        sct.pl.tsne(workflow)


def test_scatter_and_violin(workflow, tmp_path):
    ax = sct.pl.scatter(workflow, "total_counts", "n_genes",
                        color="leiden")
    assert _n_points(ax) == workflow.n_cells
    ax2 = sct.pl.violin(workflow, ["total_counts", "n_genes"])
    assert len(ax2.collections) > 0
    ax3 = sct.pl.violin(workflow, ["total_counts"], groupby="leiden",
                        save=tmp_path / "violin.png")
    n_groups = len(np.unique(workflow.obs_vector("leiden")))
    assert len(ax3.get_xticklabels()) == n_groups
    with pytest.raises(ValueError, match="exactly one key"):
        sct.pl.violin(workflow, ["a", "b"], groupby="leiden")


def test_highest_expr_genes(workflow):
    ax = sct.pl.highest_expr_genes(workflow, n_top=10)
    assert len(ax.get_yticklabels()) == 10


def test_dotplot_matrixplot_heatmap(workflow, tmp_path):
    markers = [f"G{i}" for i in (1, 5, 9, 20)]
    ax = sct.pl.dotplot(workflow, markers, groupby="leiden",
                        save=tmp_path / "dot.png")
    n_groups = len(np.unique(workflow.obs_vector("leiden")))
    assert _n_points(ax) == n_groups * len(markers)
    ax2 = sct.pl.matrixplot(workflow, markers, groupby="leiden",
                            standard_scale="var")
    assert ax2.images[0].get_array().shape == (n_groups, len(markers))
    ax3 = sct.pl.heatmap(workflow, markers, groupby="leiden")
    assert ax3.images[0].get_array().shape == (workflow.n_cells,
                                               len(markers))


def test_rank_genes_groups_panels(workflow, tmp_path):
    axes = sct.pl.rank_genes_groups(workflow, n_genes=8,
                                    save=tmp_path / "rgg.png")
    groups = list(workflow.uns["rank_genes_groups"]["groups"])
    live = [a for row in axes for a in row if a.get_title()]
    assert len(live) == len(groups)
    # gene names rendered as text
    assert len(live[0].texts) == 8


def test_paga_and_dendrogram_and_density(workflow, tmp_path):
    ax = sct.pl.paga(workflow, save=tmp_path / "paga.png")
    n_groups = len(np.asarray(workflow.uns["paga_groups"]))
    assert _n_points(ax) == n_groups
    ax2 = sct.pl.dendrogram(workflow, "leiden")
    assert len(ax2.collections) > 0 or len(ax2.lines) > 0
    ax3 = sct.pl.embedding_density(workflow, "X_umap")
    assert _n_points(ax3) == workflow.n_cells


def test_velocity_embedding_requires_arrows(workflow):
    with pytest.raises(KeyError, match="velocity_umap"):
        sct.pl.velocity_embedding(workflow)


def test_standard_scale_group_and_validation(workflow):
    markers = ["G1", "G5", "G9", "G20"]
    ax = sct.pl.matrixplot(workflow, markers, groupby="leiden",
                           standard_scale="group")
    arr = np.asarray(ax.images[0].get_array())
    # per-row min-max: every non-degenerate row peaks at exactly 1
    rowmax = arr.max(axis=1)
    assert ((np.isclose(rowmax, 1.0)) | (np.isclose(rowmax, 0.0))).all()
    assert np.isclose(rowmax, 1.0).any()
    assert np.isclose(arr.min(axis=1), 0.0).all()
    with pytest.raises(ValueError, match="standard_scale"):
        sct.pl.dotplot(workflow, markers, groupby="leiden",
                       standard_scale="cells")


def test_paga_uses_stored_groups_key(workflow):
    # a second obs column with IDENTICAL levels must not hijack the
    # layout: graph.paga stores paga_groups_key and pl.paga reads it
    decoy = np.asarray(workflow.obs_vector("leiden")).copy()
    rng = np.random.default_rng(0)
    rng.shuffle(decoy)
    d2 = workflow.with_obs(aaa_decoy=decoy)  # sorts before "leiden"
    assert d2.uns["paga_groups_key"] == "leiden"
    ax = sct.pl.paga(d2)
    assert _n_points(ax) == len(np.asarray(d2.uns["paga_groups"]))


def test_save_closes_created_figures(workflow, tmp_path):
    import matplotlib.pyplot as plt

    before = plt.get_fignums()
    for i in range(3):
        sct.pl.umap(workflow, color="leiden",
                    save=tmp_path / f"u{i}.png")
    assert plt.get_fignums() == before  # no figure leak


def test_velocity_phase_portraits(tmp_path):
    rng = np.random.default_rng(0)
    n, g = 120, 4
    t = rng.uniform(0, 1, n).astype(np.float32)
    S = (np.abs(rng.normal(1, 0.2, (n, g))) * t[:, None]).astype(
        np.float32)
    U = (np.abs(rng.normal(1, 0.2, (n, g))) * (1 - t)[:, None]).astype(
        np.float32)
    d = CellData(S, var={"gene_name": np.array(
        [f"G{i}" for i in range(g)])})
    d = d.with_layers(Ms=S, Mu=U)
    d = d.with_obs(pt=t)
    d = sct.apply("velocity.estimate", d, backend="cpu", min_r2=-10)
    axes = sct.pl.velocity(d, ["G0", "G2"], color="pt",
                           save=tmp_path / "vel.png", show=False)
    assert axes.shape == (1, 2)
    assert (tmp_path / "vel.png").exists()
    # with the dynamical fit present, the trajectory overlay draws too
    d = sct.apply("velocity.recover_dynamics", d, backend="cpu",
                  n_outer=5, min_r2=-10)
    sct.pl.velocity(d, [0, 1, 2, 3], ncols=2,
                    save=tmp_path / "vel_fit.png", show=False)
    assert (tmp_path / "vel_fit.png").exists()

    with pytest.raises(KeyError, match="unknown gene"):
        sct.pl.velocity(d, ["NOPE"])


def test_velocity_portrait_categorical_color_and_legacy_fit(tmp_path):
    rng = np.random.default_rng(2)
    n, g = 80, 3
    S = np.abs(rng.normal(1, 0.3, (n, g))).astype(np.float32)
    U = np.abs(rng.normal(0.5, 0.2, (n, g))).astype(np.float32)
    d = CellData(S).with_layers(Ms=S, Mu=U)
    d = d.with_obs(grp=np.array(["a", "b"])[np.arange(n) % 2])
    # categorical color draws per-level palette without error
    sct.pl.velocity(d, [0, 1], color="grp",
                    save=tmp_path / "cat.png", show=False)
    assert (tmp_path / "cat.png").exists()
    # a legacy fit WITHOUT fit_t_switch_geo must fall back to the
    # steady-state-line-only portrait, not KeyError
    d2 = d.with_var(fit_alpha=np.ones(g, np.float32),
                    velocity_gamma=np.full(g, 0.5, np.float32))
    sct.pl.velocity(d2, [0], save=tmp_path / "legacy.png", show=False)
    assert (tmp_path / "legacy.png").exists()
