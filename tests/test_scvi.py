"""model.scvi: the NB-VAE model family."""

import os

import numpy as np
import pytest

import sctools_tpu as sct
from sctools_tpu.data.dataset import CellData
from sctools_tpu.data.synthetic import synthetic_counts


def _poisson_blocks(n=900, G=300, seed=0):
    """Three clusters with disjoint hot gene blocks + per-cell library
    variation — data an NB/Poisson decoder models exactly."""
    rng = np.random.default_rng(seed)
    truth = rng.integers(0, 3, n)
    base = rng.uniform(0.5, 2, G)
    prof = np.tile(base, (3, 1))
    for c in range(3):
        prof[c, c * 100:(c + 1) * 100] *= 8.0
    lib = rng.uniform(0.5, 2.0, n)
    X = rng.poisson(prof[truth] * lib[:, None] * 2).astype(np.float32)
    return CellData(X), truth


@pytest.fixture(scope="module")
def trained():
    d, truth = _poisson_blocks()
    out = sct.apply("model.scvi", d, backend="cpu", n_latent=8,
                    n_hidden=64, epochs=80, batch_size=128, seed=0)
    return d, truth, out


def test_scvi_elbo_decreases(trained):
    _, _, out = trained
    h = np.asarray(out.uns["scvi_elbo_history"])
    assert len(h) == 80
    assert h[-1] < 0.1 * h[0]  # orders-of-magnitude improvement
    assert h[-1] <= np.min(h[:20]) + 1e-6


def test_scvi_latent_separates_clusters(trained):
    _, truth, out = trained
    Z = np.asarray(out.obsm["X_scvi"])
    assert Z.shape == (900, 8)
    from sctools_tpu.ops.cluster import adjusted_rand_index

    zc = CellData(np.zeros((900, 1), np.float32),
                  obsm={"X_pca": Z.astype(np.float32)})
    km = sct.apply("cluster.kmeans", zc, backend="cpu", n_clusters=3,
                   seed=0)
    ari = adjusted_rand_index(np.asarray(km.obs["kmeans"]), truth)
    assert ari > 0.9  # measured 1.0


def test_scvi_library_size_not_dominating(trained):
    """The latent encodes state, not depth: no dim should be mostly a
    library-size readout (the decoder gets depth as an offset)."""
    d, _, out = trained
    Z = np.asarray(out.obsm["X_scvi"], np.float64)
    lib = np.log(np.asarray(d.X).sum(axis=1))
    corr = [abs(np.corrcoef(Z[:, j], lib)[0, 1])
            for j in range(Z.shape[1])]
    assert max(corr) < 0.9


def test_scvi_dispersion_positive(trained):
    _, _, out = trained
    th = np.asarray(out.var["scvi_dispersion"])
    assert th.shape == (300,)
    assert (th > 0).all()


def test_scvi_on_sparse_counts_runs():
    """Real entry point: sparse raw counts via synthetic_counts, with
    a batch covariate."""
    d = synthetic_counts(300, 120, density=0.2, n_clusters=2, seed=1)
    d = d.with_obs(sample=np.array(["a"] * 150 + ["b"] * 150))
    out = sct.apply("model.scvi", d, backend="cpu", n_latent=6,
                    n_hidden=48, epochs=10, batch_size=100,
                    batch_key="sample", seed=0)
    assert out.obsm["X_scvi"].shape == (300, 6)
    h = np.asarray(out.uns["scvi_elbo_history"])
    assert h[-1] < h[0]
    with pytest.raises(KeyError, match="nope"):
        sct.apply("model.scvi", d, backend="cpu", batch_key="nope",
                  epochs=1)


def test_scvi_deterministic():
    d, _ = _poisson_blocks(n=200, G=80, seed=2)
    a = sct.apply("model.scvi", d, backend="cpu", epochs=5,
                  batch_size=64, seed=7)
    b = sct.apply("model.scvi", d, backend="cpu", epochs=5,
                  batch_size=64, seed=7)
    np.testing.assert_array_equal(np.asarray(a.obsm["X_scvi"]),
                                  np.asarray(b.obsm["X_scvi"]))


def test_scvi_data_parallel_over_mesh():
    """8-virtual-device DP training: pmean'd grads keep replicated
    params in lockstep; the model still learns and separates."""
    d, truth = _poisson_blocks(n=600, G=200, seed=3)
    out = sct.apply("model.scvi", d, backend="tpu", n_latent=8,
                    n_hidden=64, epochs=175, batch_size=128, seed=0,
                    n_devices=8)
    h = np.asarray(out.uns["scvi_elbo_history"])
    assert h[-1] < 0.2 * h[0]
    from sctools_tpu.ops.cluster import adjusted_rand_index

    Z = np.asarray(out.obsm["X_scvi"])
    zc = CellData(np.zeros((600, 1), np.float32),
                  obsm={"X_pca": Z.astype(np.float32)})
    km = sct.apply("cluster.kmeans", zc, backend="cpu", n_clusters=3,
                   seed=0)
    assert adjusted_rand_index(np.asarray(km.obs["kmeans"]),
                               truth) > 0.9


def test_scvi_normalized_expression():
    """store_normalized: decoded rho recovers the generative profile
    ordering — hot-block genes dominate within their own cluster."""
    d, truth = _poisson_blocks(n=300, G=150, seed=4)
    out = sct.apply("model.scvi", d, backend="cpu", n_latent=6,
                    n_hidden=48, epochs=90, batch_size=100, seed=0,
                    store_normalized=True)
    rho = np.asarray(out.layers["scvi_normalized"])
    assert rho.shape == (300, 150)
    np.testing.assert_allclose(rho.sum(axis=1), 1.0, rtol=1e-4)
    # cluster-0 cells put more mass on genes 0:50 than cluster-1 cells
    m0 = rho[truth == 0][:, 0:50].sum(axis=1).mean()
    m1 = rho[truth == 1][:, 0:50].sum(axis=1).mean()
    assert m0 > 2 * m1


def test_scvi_sharded_x_lives_on_the_mesh():
    """The DP path must shard X across devices (the atlas shape), not
    replicate it — verify via the addressable shard sizes."""
    import jax as _jax

    from sctools_tpu.models import scvi as S
    from sctools_tpu.parallel.mesh import make_mesh

    d, _ = _poisson_blocks(n=160, G=40, seed=5)
    mesh = make_mesh(8)
    X = S._counts_dense(d)
    oh = _jax.numpy.zeros((160, 0), dtype="float32")
    fn = S._make_epoch_sharded(mesh, X, oh)
    shard_rows = {s.data.shape[0] for s in fn.x_sharded.addressable_shards}
    assert shard_rows == {160 // 8}  # each device holds 1/8 of cells


@pytest.fixture(scope="module")
def scanvi_trained():
    """ONE semi-supervised scanvi training shared by the label-recovery
    and decoder-conditioning tests (they trained the identical model
    twice; the duplicate cost bought no coverage)."""
    d, truth = _poisson_blocks(n=600, G=200, seed=6)
    rng = np.random.default_rng(0)
    labels = np.array([f"type_{c}" for c in truth], dtype=object)
    mask = rng.random(600) > 0.3
    labels[mask] = "Unknown"
    d = d.with_obs(cell_type=labels.astype(str))
    out = sct.apply("model.scanvi", d, backend="cpu", n_latent=8,
                    n_hidden=64, epochs=80, batch_size=128, seed=0)
    return truth, mask, out


def test_scanvi_semi_supervised_label_recovery(scanvi_trained):
    """30% of cells labelled; scanvi must predict the held-out 70%
    accurately on separable data."""
    truth, mask, out = scanvi_trained
    pred = np.asarray(out.obs["scanvi_prediction"])
    want = np.array([f"type_{c}" for c in truth])
    acc_unlabeled = (pred[mask] == want[mask]).mean()
    assert acc_unlabeled > 0.9
    conf = np.asarray(out.obs["scanvi_confidence"])
    assert conf.min() > 1.0 / 3.0 - 1e-6 and conf.max() <= 1.0 + 1e-6
    h = np.asarray(out.uns["scanvi_elbo_history"])
    assert h[-1] < h[0]
    assert out.obsm["X_scanvi"].shape == (600, 8)


def test_scanvi_decoder_conditions_on_label(scanvi_trained):
    """The published y-conditioned generative model (r4 documented
    simplification, now the default): uns['scanvi_class_profiles']
    decodes each class's learned latent anchor under its own label —
    class 0's archetype must be hot on class 0's gene block relative
    to class 1's archetype, and vice versa (measured ratios ~1.7/1.6).
    Class 2's hot block lies beyond G=200 in this fixture, so its
    archetype stays flat on both blocks — a built-in negative
    control."""
    truth, mask, out = scanvi_trained
    prof = np.asarray(out.uns["scanvi_class_profiles"])
    assert prof.shape == (3, 200)
    np.testing.assert_allclose(prof.sum(axis=1), 1.0, rtol=1e-4)
    b0 = prof[:, :100].mean(axis=1)
    b1 = prof[:, 100:200].mean(axis=1)
    assert b0[0] / b0[1] > 1.25   # class-0 archetype hot on block 0
    assert b1[1] / b1[0] > 1.25   # class-1 archetype hot on block 1
    # negative control: class 2 has no block in range — near-flat
    assert abs(b0[2] / b1[2] - 1.0) < 0.15
    # accuracy must not regress vs the classifier-only variant's gate
    pred = np.asarray(out.obs["scanvi_prediction"])
    want = np.array([f"type_{c}" for c in truth])
    assert (pred[mask] == want[mask]).mean() > 0.9


def test_scanvi_data_parallel_over_mesh():
    """The y-conditioned semi-supervised model trains data-parallel
    like scvi: X, labels, and the label mask all cells-axis sharded,
    pmean'd grads.  Held-out accuracy must match the single-device
    gate."""
    d, truth = _poisson_blocks(n=600, G=200, seed=6)
    rng = np.random.default_rng(0)
    labels = np.array([f"type_{c}" for c in truth], dtype=object)
    mask = rng.random(600) > 0.3
    labels[mask] = "Unknown"
    d = d.with_obs(cell_type=labels.astype(str))
    out = sct.apply("model.scanvi", d, backend="tpu", n_latent=8,
                    n_hidden=64, epochs=100, batch_size=128, seed=0,
                    n_devices=8)
    pred = np.asarray(out.obs["scanvi_prediction"])
    want = np.array([f"type_{c}" for c in truth])
    assert (pred[mask] == want[mask]).mean() > 0.9  # measured 0.95
    h = np.asarray(out.uns["scanvi_elbo_history"])
    assert h[-1] < h[0]
    # the y-conditioning survives the sharded path too
    prof = np.asarray(out.uns["scanvi_class_profiles"])
    assert prof[0, :100].mean() / prof[1, :100].mean() > 1.25


def test_scanvi_classifier_only_variant():
    """The r4 cheap variant stays available and emits no profiles."""
    d, truth = _poisson_blocks(n=400, G=200, seed=8)
    labels = np.array([f"type_{c}" for c in truth])
    d = d.with_obs(cell_type=labels)
    out = sct.apply("model.scanvi", d, backend="cpu", n_latent=8,
                    n_hidden=64, epochs=60, batch_size=128, seed=0,
                    classifier_only=True)
    assert "scanvi_class_profiles" not in out.uns
    assert (np.asarray(out.obs["scanvi_prediction"])
            == labels).mean() > 0.85  # measured 0.93


def test_scanvi_store_normalized():
    """Decoded expression under each cell's own (predicted where
    unlabelled) label — class-c cells put more mass on their hot
    block."""
    d, truth = _poisson_blocks(n=400, G=200, seed=9)
    rng = np.random.default_rng(1)
    labels = np.array([f"type_{c}" for c in truth], dtype=object)
    labels[rng.random(400) > 0.5] = "Unknown"
    d = d.with_obs(cell_type=labels.astype(str))
    out = sct.apply("model.scanvi", d, backend="cpu", n_latent=8,
                    n_hidden=64, epochs=60, batch_size=128, seed=0,
                    store_normalized=True)
    rho = np.asarray(out.layers["scanvi_normalized"])
    assert rho.shape == (400, 200)
    np.testing.assert_allclose(rho.sum(axis=1), 1.0, rtol=1e-4)
    m0 = rho[truth == 0][:, :100].sum(axis=1).mean()
    m1 = rho[truth == 1][:, :100].sum(axis=1).mean()
    assert m0 > 1.5 * m1


def test_scanvi_validates():
    d, _ = _poisson_blocks(n=100, G=50, seed=7)
    with pytest.raises(KeyError, match="cell_type"):
        sct.apply("model.scanvi", d, backend="cpu", epochs=1)
    one = d.with_obs(cell_type=np.array(["a"] * 100))
    with pytest.raises(ValueError, match=">=2"):
        sct.apply("model.scanvi", one, backend="cpu", epochs=1)


# ---------------------------------------------------------------------------
# the stable on-disk model convention (save_model / load_model)
# ---------------------------------------------------------------------------

def test_save_load_model_round_trip(tmp_path):
    """flatten/unflatten is a lossless bijection for scvi- AND
    scanvi-shaped parameter pytrees (nested dicts/lists of arrays),
    and the on-disk artifact verifies before it is trusted."""
    import jax

    from sctools_tpu.models.scvi import (init_params, load_model,
                                         save_model)

    params = init_params(jax.random.PRNGKey(3), 40, 2, n_latent=4,
                         n_hidden=8)
    # scanvi-shaped extras: a classifier head + class anchors
    params["clf"] = [{"w": np.ones((4, 3), np.float32),
                      "b": np.zeros((3,), np.float32)}]
    params["prior_mu"] = np.zeros((3, 4), np.float32)
    p = str(tmp_path / "model.npz")
    save_model(params, p, meta={"n_genes": 40, "n_latent": 4})
    got, meta = load_model(p)
    la = jax.tree_util.tree_leaves(params)
    lb = jax.tree_util.tree_leaves(got)
    assert len(la) == len(lb)
    assert all(np.array_equal(np.asarray(a), np.asarray(b))
               for a, b in zip(la, lb))
    assert int(meta["n_genes"]) == 40

    # generation rotation: a re-save rotates the old file to .prev
    save_model(params, p)
    assert os.path.exists(p + ".prev")

    # a foreign fingerprint is refused, never half-parsed
    from sctools_tpu.utils.checkpoint import (CheckpointCorruptError,
                                              save_npz_verified)

    foreign = str(tmp_path / "foreign.npz")
    save_npz_verified(foreign, fingerprint="other-v1",
                      x=np.zeros(3))
    with pytest.raises(CheckpointCorruptError):
        load_model(foreign)


def test_scvi_op_saves_model_artifact(tmp_path):
    """model.scvi(save_model_path=) leaves a verified reloadable
    artifact behind — the servable form of a trained reference."""
    from sctools_tpu.models.scvi import _encode, load_model

    d = synthetic_counts(200, 60, density=0.2, n_clusters=2, seed=0)
    p = str(tmp_path / "scvi.npz")
    out = sct.apply("model.scvi", d, backend="cpu", n_latent=4,
                    n_hidden=16, epochs=2, batch_size=64,
                    save_model_path=p)
    params, meta = load_model(p)
    assert int(meta["n_genes"]) == 60 and int(meta["n_latent"]) == 4
    # the reloaded params reproduce the op's own embedding
    import jax.numpy as jnp
    import scipy.sparse as sp

    X = jnp.asarray(np.asarray(d.X.todense() if sp.issparse(d.X)
                               else d.X), jnp.float32)
    oh = jnp.zeros((X.shape[0], 0), jnp.float32)
    z = np.asarray(_encode(params, X, oh))
    assert np.allclose(z, np.asarray(out.obsm["X_scvi"]), atol=1e-5)


def test_serving_artifact_embeds_scvi_params(tmp_path):
    """build_reference_artifact(scvi_model=) carries the trained
    params inside the serving artifact under the same flatten
    encoding, reloadable from the resident model."""
    import jax

    from sctools_tpu.models.scvi import init_params, save_model
    from sctools_tpu.serving import AnnotationService, \
        build_reference_artifact
    from sctools_tpu.utils.vclock import VirtualClock

    ref = synthetic_counts(200, 60, density=0.2, n_clusters=2, seed=0)
    ref = ref.with_obs(cell_type=np.array(
        ["a" if c == 0 else "b"
         for c in np.asarray(ref.obs["cluster_true"])]))
    fitted = sct.run_recipe("annotation_reference", ref,
                            backend="cpu", n_components=8)
    params = init_params(jax.random.PRNGKey(0), 60, 0, n_latent=4,
                         n_hidden=8)
    mp = str(tmp_path / "scvi.npz")
    save_model(params, mp)
    art = str(tmp_path / "serving.npz")
    build_reference_artifact(fitted, art, labels_key="cell_type",
                             scvi_model=mp, seed=0)
    svc = AnnotationService(art, name="scvi_embed",
                            clock=VirtualClock())
    try:
        got = svc.scvi_params()
        assert got is not None
        la = jax.tree_util.tree_leaves(params)
        lb = jax.tree_util.tree_leaves(got)
        assert len(la) == len(lb) and all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(la, lb))
    finally:
        svc.close()
