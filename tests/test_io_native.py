"""IO round-trips, native C++ packer/parser parity, regression tests."""

import os
import subprocess

import numpy as np
import pytest
import scipy.sparse as sp

import sctools_tpu as sct
from sctools_tpu.data.sparse import SparseCells
from sctools_tpu.data.synthetic import synthetic_counts

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_h5ad_roundtrip(tmp_path):
    ds = synthetic_counts(60, 80, seed=9)
    path = str(tmp_path / "x.h5ad")
    sct.write_h5ad(ds, path)
    back = sct.read_h5ad(path)
    assert back.shape == ds.shape
    assert (back.X != ds.X).nnz == 0
    np.testing.assert_array_equal(back.var["gene_name"], ds.var["gene_name"])
    np.testing.assert_array_equal(back.obs["cluster_true"],
                                  ds.obs["cluster_true"])


def test_h5ad_roundtrip_from_device(tmp_path):
    ds = synthetic_counts(40, 50, seed=10).device_put()
    ds = sct.apply("qc.per_cell_metrics", ds, backend="tpu")
    path = str(tmp_path / "dev.h5ad")
    sct.write_h5ad(ds, path)
    back = sct.read_h5ad(path)
    assert back.shape == ds.shape


def test_shard_iter(tmp_path):
    ds = synthetic_counts(100, 64, seed=11)
    path = str(tmp_path / "big.h5ad")
    sct.write_h5ad(ds, path)
    from sctools_tpu.data.io import shard_iter

    shards = list(shard_iter(path, shard_rows=32))
    assert sum(s.n_cells for s in shards) == 100
    # one global capacity across shards (single-compilation contract)
    assert len({s.capacity for s in shards}) == 1
    rebuilt = sp.vstack([s.to_scipy_csr() for s in shards])
    assert (rebuilt != ds.X).nnz == 0


def test_mtx_reader(tmp_path):
    rng = np.random.default_rng(3)
    m = sp.random(30, 20, density=0.2, random_state=rng).tocoo()
    d = tmp_path / "tenx"
    d.mkdir()
    from scipy.io import mmwrite

    mmwrite(str(d / "matrix.mtx"), m)  # genes x cells on disk
    with open(d / "genes.tsv", "w") as fh:
        for i in range(30):
            fh.write(f"ENSG{i}\tGENE{i}\n")
    with open(d / "barcodes.tsv", "w") as fh:
        for i in range(20):
            fh.write(f"BC{i}\n")
    ds = sct.read_10x_mtx(str(d))
    assert ds.shape == (20, 30)  # transposed to cells x genes
    np.testing.assert_allclose(ds.X.toarray(), m.toarray().T, rtol=1e-5)
    assert len(ds.var["gene_name"]) == 30
    assert len(ds.obs["barcode"]) == 20


@pytest.fixture(scope="module")
def native_lib():
    lib = os.path.join(REPO, "csrc", "libscio.so")
    if not os.path.exists(lib):
        r = subprocess.run(["make", "-C", os.path.join(REPO, "csrc")],
                           capture_output=True, text=True)
        if r.returncode != 0:
            pytest.skip(f"native build failed: {r.stderr[-500:]}")
    import sctools_tpu.native as native

    native._LIB_TRIED = False
    native._LIB = None
    if not native.have_native():
        pytest.skip("native lib not loadable")
    return native


def test_read_csv_text_mtx_and_dispatch(tmp_path):
    import scipy.io
    import scipy.sparse as sp

    import sctools_tpu as sct

    # csv with gene header + cell-name first column (auto-detected)
    csv = tmp_path / "t.csv"
    csv.write_text("g1,g2,g3\nc1,1,2,3\nc2,4,5,6\n")
    d = sct.read_csv(str(csv))
    assert d.n_cells == 2 and d.n_genes == 3
    assert list(d.var["gene_name"]) == ["g1", "g2", "g3"]
    assert list(d.obs["cell_name"]) == ["c1", "c2"]
    np.testing.assert_array_equal(np.asarray(d.X),
                                  [[1, 2, 3], [4, 5, 6]])

    # headerless numeric csv: no names, all rows are data
    raw = tmp_path / "r.csv"
    raw.write_text("1,2\n3,4\n")
    d2 = sct.read_csv(str(raw))
    assert d2.n_cells == 2 and "gene_name" not in d2.var

    # whitespace text via the dispatcher
    txt = tmp_path / "t.txt"
    txt.write_text("g1 g2\n1 2\n3 4\n")
    d3 = sct.read(str(txt))
    assert d3.n_genes == 2 and list(d3.var["gene_name"]) == ["g1", "g2"]

    # generic mtx: stored as-is, transpose= flips
    M = sp.random(5, 3, density=0.5, format="coo", random_state=0)
    mtx = tmp_path / "m.mtx"
    scipy.io.mmwrite(str(mtx), M)
    d4 = sct.read_mtx(str(mtx))
    assert (d4.n_cells, d4.n_genes) == (5, 3)
    d5 = sct.read(str(mtx), transpose=True)
    assert (d5.n_cells, d5.n_genes) == (3, 5)
    np.testing.assert_allclose(d4.X.toarray(), d5.X.toarray().T)

    with pytest.raises(ValueError, match="unknown extension"):
        sct.read("file.xyz")


def test_native_pack_matches_numpy(native_lib):
    rng = np.random.default_rng(4)
    csr = sp.random(50, 40, density=0.3, format="csr",
                    random_state=rng).astype(np.float32)
    csr.sort_indices()
    a_idx, a_val = native_lib.pack_ell(
        csr.indptr.astype(np.int64), csr.indices.astype(np.int32),
        csr.data, 56, 128, sentinel=40)
    b_idx, b_val = native_lib._pack_ell_numpy(
        csr.indptr.astype(np.int64), csr.indices.astype(np.int32),
        csr.data, 56, 128, sentinel=40)
    np.testing.assert_array_equal(a_idx, b_idx)
    np.testing.assert_array_equal(a_val, b_val)


def test_native_mtx_parse(native_lib, tmp_path):
    rng = np.random.default_rng(5)
    m = sp.random(25, 15, density=0.3, random_state=rng).tocoo()
    path = str(tmp_path / "m.mtx")
    from scipy.io import mmwrite

    mmwrite(path, m)
    nr, nc, rows, cols, vals = native_lib.parse_mtx(path)
    assert (nr, nc) == (25, 15)
    got = sp.coo_matrix((vals, (rows, cols)), shape=(25, 15))
    np.testing.assert_allclose(got.toarray(), m.toarray(), rtol=1e-5)


# ---------------------------------------------------------------------
# Regression tests from code review
# ---------------------------------------------------------------------


def test_filter_cells_with_string_obs():
    """filter_cells must keep non-numeric obs columns host-side."""
    ds = synthetic_counts(50, 40, seed=12)
    ds.obs["barcode"] = np.array([f"BC{i}" for i in range(50)])
    dev = ds.device_put()
    dev = sct.apply("qc.per_cell_metrics", dev, backend="tpu")
    out = sct.apply("qc.filter_cells", dev, backend="tpu", min_genes=1)
    host = out.to_host()
    assert len(host.obs["barcode"]) == host.n_cells
    assert host.obs["barcode"][0].startswith("BC")


def test_to_host_trims_knn_padding():
    """kNN outputs are padded to the row_block; to_host must trim."""
    ds = synthetic_counts(100, 60, n_clusters=2, seed=13)
    dev = ds.device_put()
    dev = sct.apply("pca.exact", dev, backend="tpu", n_components=5)
    dev = sct.apply("neighbors.knn", dev, backend="tpu", k=5,
                    metric="euclidean", query_block=256, cand_block=128)
    host = dev.to_host()
    assert host.obsp["knn_indices"].shape == (100, 5)
    assert host.obsp["knn_distances"].shape == (100, 5)
    assert (host.obsp["knn_indices"] >= 0).all()


def test_read_10x_h5_both_layouts(tmp_path):
    """CellRanger v3 ('matrix' group) and v2 (per-genome group)."""
    import h5py

    from sctools_tpu.data.io import read_10x_h5

    rng = np.random.default_rng(0)
    n_cells, n_genes = 30, 50
    dense = (rng.random((n_cells, n_genes)) < 0.2) * rng.integers(
        1, 9, (n_cells, n_genes))
    X = sp.csr_matrix(dense.astype(np.float32))

    def write_common(g):
        # 10x stores features x barcodes CSC == cells x genes CSR
        g.create_dataset("data", data=X.data)
        g.create_dataset("indices", data=X.indices.astype(np.int64))
        g.create_dataset("indptr", data=X.indptr.astype(np.int64))
        g.create_dataset("shape", data=np.array([n_genes, n_cells]))
        g.create_dataset("barcodes", data=np.array(
            [f"AAAC-{i}".encode() for i in range(n_cells)]))

    p3 = str(tmp_path / "v3.h5")
    with h5py.File(p3, "w") as f:
        g = f.create_group("matrix")
        write_common(g)
        feat = g.create_group("features")
        feat.create_dataset("id", data=np.array(
            [f"ENSG{i:04d}".encode() for i in range(n_genes)]))
        feat.create_dataset("name", data=np.array(
            [f"G{i}".encode() for i in range(n_genes)]))
        feat.create_dataset("feature_types", data=np.array(
            [b"Gene Expression"] * n_genes))
    d3 = read_10x_h5(p3)
    assert d3.shape == (n_cells, n_genes)
    np.testing.assert_array_equal(d3.X.toarray(), dense)
    assert d3.var["gene_name"][1] == "G1"
    assert d3.obs["barcode"][0] == "AAAC-0"

    p2 = str(tmp_path / "v2.h5")
    with h5py.File(p2, "w") as f:
        g = f.create_group("GRCh38")
        write_common(g)
        g.create_dataset("genes", data=np.array(
            [f"ENSG{i:04d}".encode() for i in range(n_genes)]))
        g.create_dataset("gene_names", data=np.array(
            [f"G{i}".encode() for i in range(n_genes)]))
    d2 = read_10x_h5(p2)
    np.testing.assert_array_equal(d2.X.toarray(), dense)
    d2b = read_10x_h5(p2, genome="GRCh38")
    np.testing.assert_array_equal(d2b.X.toarray(), dense)
    with pytest.raises(ValueError, match="genome"):
        read_10x_h5(p2, genome="mm10")

    # multi-genome v2 file with genome=None must raise, not silently
    # load the first (possibly half-empty) group
    with h5py.File(p2, "a") as f:
        g = f.create_group("mm10")
        write_common(g)
        g.create_dataset("genes", data=np.array(
            [f"ENSMUSG{i:04d}".encode() for i in range(n_genes)]))
        g.create_dataset("gene_names", data=np.array(
            [f"g{i}".encode() for i in range(n_genes)]))
    with pytest.raises(ValueError, match="multiple genome groups"):
        read_10x_h5(p2)
    np.testing.assert_array_equal(
        read_10x_h5(p2, genome="mm10").X.toarray(), dense)


def test_read_loom_with_velocity_layers(tmp_path):
    """Loom (genes x cells + layers) -> CellData feeding velocity.*"""
    import h5py

    from sctools_tpu.data.io import read_loom

    rng = np.random.default_rng(1)
    g, c = 40, 25
    spliced = (rng.random((g, c)) < 0.3) * rng.integers(1, 6, (g, c))
    unspliced = (rng.random((g, c)) < 0.2) * rng.integers(1, 4, (g, c))
    p = str(tmp_path / "v.loom")
    with h5py.File(p, "w") as f:
        f.create_dataset("matrix", data=spliced.astype(np.float32))
        lay = f.create_group("layers")
        lay.create_dataset("spliced", data=spliced.astype(np.float32))
        lay.create_dataset("unspliced",
                           data=unspliced.astype(np.float32))
        ca = f.create_group("col_attrs")
        ca.create_dataset("CellID", data=np.array(
            [f"cell{i}".encode() for i in range(c)]))
        ra = f.create_group("row_attrs")
        ra.create_dataset("Gene", data=np.array(
            [f"g{i}".encode() for i in range(g)]))

    d = read_loom(p)
    assert d.shape == (c, g)  # transposed to cells x genes
    np.testing.assert_array_equal(d.X.toarray(), spliced.T)
    np.testing.assert_array_equal(d.layers["unspliced"].toarray(),
                                  unspliced.T)
    assert d.obs["cell_id"][0] == "cell0"
    assert d.var["gene_name"][2] == "g2"
    # dense mode agrees
    dd = read_loom(p, sparse=False)
    np.testing.assert_array_equal(np.asarray(dd.X), spliced.T)
    # and the layers drive the velocity family end-to-end
    d = sct.apply("neighbors.knn",
                  d.with_obsm(X_pca=np.asarray(
                      d.X.toarray(), np.float32)),
                  backend="cpu", k=5, use_rep="X_pca")
    d = sct.apply("velocity.moments", d, backend="cpu")
    d = sct.apply("velocity.estimate", d, backend="cpu")
    assert d.layers["velocity"].shape == (c, g)


def test_loom_round_trip(tmp_path):
    from sctools_tpu.data.io import read_loom, write_loom

    rng = np.random.default_rng(2)
    dense = ((rng.random((15, 8)) < 0.4)
             * rng.integers(1, 5, (15, 8))).astype(np.float32)
    d = sct.CellData(sp.csr_matrix(dense),
                     obs={"cell_id": np.array(
                         [f"c{i}" for i in range(15)])},
                     var={"gene_name": np.array(
                         [f"g{i}" for i in range(8)])},
                     layers={"spliced": sp.csr_matrix(dense * 2)})
    p = str(tmp_path / "rt.loom")
    write_loom(d, p)
    back = read_loom(p)
    np.testing.assert_array_equal(back.X.toarray(), dense)
    np.testing.assert_array_equal(back.layers["spliced"].toarray(),
                                  dense * 2)
    assert list(back.obs["cell_id"]) == [f"c{i}" for i in range(15)]
    assert list(back.var["gene_name"]) == [f"g{i}" for i in range(8)]


def test_h5ad_roundtrip_nested_uns_and_obsp(tmp_path):
    """uns dicts (dendrogram-style) become subgroups and come back as
    dicts; obsp (the kNN graph) round-trips — losing the graph on save
    was a real pre-fix failure (write crashed on dict uns)."""
    from sctools_tpu.data.io import read_h5ad, write_h5ad
    from sctools_tpu.data.synthetic import synthetic_counts

    d = synthetic_counts(120, 80, density=0.2, n_clusters=3, seed=0)
    d = sct.Pipeline([
        ("normalize.library_size", {}), ("normalize.log1p", {}),
        ("pca.randomized", {"n_components": 8}),
        ("neighbors.knn", {"k": 8}),
    ]).run(d, backend="cpu")
    d = sct.apply("cluster.kmeans", d, backend="cpu", n_clusters=3)
    d = d.with_obs(label=np.asarray(d.obs["kmeans"]).astype(str))
    d = sct.apply("cluster.dendrogram", d, backend="cpu",
                  groupby="label")
    p = str(tmp_path / "nested.h5ad")
    write_h5ad(d, p)
    r = read_h5ad(p)
    dd = r.uns["dendrogram_label"]
    np.testing.assert_allclose(
        dd["linkage"], d.uns["dendrogram_label"]["linkage"])
    assert (list(dd["categories_ordered"])
            == list(d.uns["dendrogram_label"]["categories_ordered"]))
    np.testing.assert_array_equal(
        r.obsp["knn_indices"], np.asarray(d.obsp["knn_indices"]))
    np.testing.assert_allclose(
        r.obsp["knn_distances"], np.asarray(d.obsp["knn_distances"]),
        rtol=1e-6)

    # review findings: None inside uns (scanpy log1p idiom) must not
    # crash; varm round-trips; obsp is opt-out like layers
    d2 = d.with_uns(log1p={"base": None}).with_varm(
        PCs=np.arange(80 * 3, dtype=np.float32).reshape(80, 3))
    write_h5ad(d2, p)
    r2 = read_h5ad(p)
    assert r2.uns["log1p"]["base"] == ""
    np.testing.assert_allclose(r2.varm["PCs"], d2.varm["PCs"])
    lean = read_h5ad(p, load_obsp=False)
    assert lean.obsp == {}
