"""tools/check_registry_parity.py as a tier-1 gate: every registered
transform has both cpu and tpu backends (or an allowlist entry with a
reason) — the pairing the oracle tests AND the runner's degrade-to-cpu
fallback both depend on."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "tools"))

from check_registry_parity import ALLOWLIST, check  # noqa: E402


def test_registry_parity():
    problems = check()
    assert not problems, "\n".join(problems)


def test_allowlist_entries_have_reasons():
    for name, reason in ALLOWLIST.items():
        assert reason and reason.strip(), (
            f"allowlist entry {name!r} has no reason — state why the "
            f"parity exemption is intentional")
