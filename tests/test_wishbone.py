"""wishbone.run: bifurcating trajectory + branch assignment."""

import numpy as np
import pytest

import sctools_tpu as sct
from sctools_tpu.data.dataset import CellData


def _y_shape(n_trunk=150, n_arm=150, d=10, seed=0):
    """A Y: trunk from origin, then two arms diverging."""
    rng = np.random.default_rng(seed)
    t_trunk = np.linspace(0, 1, n_trunk)
    t_arm = np.linspace(0, 1, n_arm)
    dir_trunk = np.zeros(d)
    dir_trunk[0] = 1.0
    dir_a = np.zeros(d)
    dir_a[0], dir_a[1] = 0.7, 0.7
    dir_b = np.zeros(d)
    dir_b[0], dir_b[1] = 0.7, -0.7
    trunk = np.outer(t_trunk, dir_trunk)
    tip = dir_trunk  # branch point at (1, 0, ...)
    arm_a = tip + np.outer(t_arm, dir_a)
    arm_b = tip + np.outer(t_arm, dir_b)
    E = np.vstack([trunk, arm_a, arm_b])
    E = E + rng.normal(0, 0.02, E.shape)
    truth_t = np.concatenate([t_trunk, 1 + t_arm, 1 + t_arm])
    truth_b = np.concatenate([np.zeros(n_trunk), np.ones(n_arm),
                              np.full(n_arm, 2)]).astype(int)
    d_ = CellData(np.zeros((len(E), 1), np.float32),
                  obsm={"X_pca": E.astype(np.float32)})
    d_ = sct.apply("neighbors.knn", d_, backend="cpu", k=10,
                   metric="euclidean")
    return d_, truth_t, truth_b


@pytest.fixture(scope="module")
def ydata():
    return _y_shape()


def _spearman(a, b):
    ra = np.argsort(np.argsort(a)).astype(np.float64)
    rb = np.argsort(np.argsort(b)).astype(np.float64)
    ra -= ra.mean()
    rb -= rb.mean()
    return float((ra * rb).sum()
                 / np.sqrt((ra * ra).sum() * (rb * rb).sum()))


def test_wishbone_orders_cells(ydata):
    d, truth_t, _ = ydata
    out = sct.apply("wishbone.run", d, backend="cpu", start_cell=0,
                    n_waypoints=80)
    tau = np.asarray(out.obs["wishbone_trajectory"], np.float64)
    assert _spearman(tau, truth_t) > 0.95


def test_wishbone_finds_the_two_arms(ydata):
    d, truth_t, truth_b = ydata
    out = sct.apply("wishbone.run", d, backend="cpu", start_cell=0,
                    n_waypoints=80)
    br = np.asarray(out.obs["wishbone_branch"])
    # post-branch cells split into two arms that match the generative
    # arms (up to label swap)
    post = truth_b > 0
    a = br[post & (truth_b == 1)]
    b = br[post & (truth_b == 2)]
    # each true arm is dominated by one predicted label, and they differ
    la = np.bincount(a[a > 0], minlength=3).argmax()
    lb = np.bincount(b[b > 0], minlength=3).argmax()
    assert la != lb and la > 0 and lb > 0
    # cross-arm confusion only in the immediate branch vicinity
    # (measured 4/300 on this fixture)
    cross = ((a == lb).sum() + (b == la).sum()) / (len(a) + len(b))
    assert cross < 0.03
    acc = ((a == la).mean() + (b == lb).mean()) / 2
    assert acc > 0.9  # measured 0.973
    # trunk cells are labelled 0 (measured 0.987)
    assert (br[truth_b == 0] == 0).mean() > 0.9


def test_wishbone_tpu_distances_match_dijkstra(ydata):
    d, _, _ = ydata
    out_c = sct.apply("wishbone.run", d, backend="cpu", start_cell=0,
                      n_waypoints=40)
    out_t = sct.apply("wishbone.run", d, backend="tpu", start_cell=0,
                      n_waypoints=40)
    tc = np.asarray(out_c.obs["wishbone_trajectory"], np.float64)
    tt = np.asarray(out_t.obs["wishbone_trajectory"], np.float64)
    # min-plus relaxation (f32) vs dijkstra (f64): same shortest paths
    np.testing.assert_allclose(tt, tc, rtol=2e-3, atol=2e-3)
    assert _spearman(tt, tc) > 0.999


def test_wishbone_validates(ydata):
    d, _, _ = ydata
    with pytest.raises(ValueError, match="start_cell"):
        sct.apply("wishbone.run", d, backend="cpu", start_cell=10**6)
    bare = CellData(np.zeros((5, 2), np.float32))
    with pytest.raises(KeyError, match="neighbors.knn"):
        sct.apply("wishbone.run", bare, backend="cpu", start_cell=0)


def test_minplus_converges_past_the_round_cap():
    """A path graph's hop-diameter (n-1) far exceeds one relaxation
    round (128 sweeps); the host loop must keep relaxing until true
    convergence — regression for silently-unconverged distances."""
    from sctools_tpu.ops.wishbone import (_distances_cpu, _distances_tpu,
                                          _sym_edges)

    n = 500
    idx = np.full((n, 2), -1, np.int32)
    dist = np.zeros((n, 2), np.float32)
    idx[:-1, 0] = np.arange(1, n)     # i -> i+1
    dist[:-1, 0] = 1.0
    idx2, w2 = _sym_edges(idx, dist)
    sources = np.array([0, n - 1])
    D_dev = _distances_tpu(idx2, w2, sources)
    D_ora = _distances_cpu(idx2, w2, sources)
    np.testing.assert_allclose(D_dev, D_ora, rtol=1e-5)
    assert D_dev[n - 1, 0] == pytest.approx(n - 1)  # full chain length
