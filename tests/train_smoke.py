"""CI training smoke (tools/run_checks.sh stage 11).

Drives the preemption-tolerant out-of-core trainer's three headline
contracts on a temp-dir shard store:

1. **SIGKILL → bitwise resume**: a child process training with a
   cursor checkpoint is SIGKILLed at a RANDOMIZED shard read
   (mid-epoch, between arbitrary minibatches); the parent resumes
   from the cursor and finishes with params BITWISE IDENTICAL to an
   uninterrupted run, and the merged journal proves no shard was
   trained twice (unique ``train_shard`` (epoch, pos) pairs after a
   ``train_resume``);
2. **chaos preempt through the scheduler**: a ``preempt`` fault at
   the Nth shard-boundary poll (one VirtualClock, zero real sleeps)
   makes the training job checkpoint-then-yield, requeue, resume and
   complete — journal: ``preempted`` (non-terminal) then exactly one
   terminal, history identical to uninterrupted;
3. **corrupt cursor → quarantine, fall back a generation**: byte
   damage to the newest cursor checkpoint is caught by the digest
   verify, the file is QUARANTINED (never deleted, reason sidecar)
   and resume falls back to ``.prev`` — one shard of retraining,
   never a silent epoch restart — still finishing bitwise-identical.

Run directly: ``JAX_PLATFORMS=cpu python tests/train_smoke.py``
(exit 0 = all contracts hold).
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile

import numpy as np

# run as a plain script (CI stage 11): the script dir (tests/) is
# what lands on sys.path, not the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

HYPER = dict(n_latent=4, n_hidden=16, epochs=2, batch_size=128,
             seed=0)

_CHILD = """
import os, signal, sys
import sctools_tpu  # noqa: F401 - full package import, like a user
from sctools_tpu.data.shardstore import ShardStore
from sctools_tpu.models.train_stream import fit_scvi_stream

store_dir, ck, jp, kill_after = (sys.argv[1], sys.argv[2],
                                 sys.argv[3], int(sys.argv[4]))
store = ShardStore.open(store_dir)
orig = store.read_shard
calls = [0]


def killing(i, **kw):
    calls[0] += 1
    if calls[0] == kill_after:
        os.kill(os.getpid(), signal.SIGKILL)  # hard death mid-epoch
    return orig(i, **kw)


store.read_shard = killing
fit_scvi_stream(store, checkpoint=ck, journal=jp, n_latent=4,
                n_hidden=16, epochs=2, batch_size=128, seed=0)
"""


def _leaves_equal(a, b) -> bool:
    import jax

    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb))


def main() -> int:
    tmp = tempfile.mkdtemp(prefix="sctools_train_smoke_")
    try:
        return _run(tmp)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _run(tmp: str) -> int:
    import random as _random

    from sctools_tpu.data.shardstore import write_store
    from sctools_tpu.data.synthetic import synthetic_counts
    from sctools_tpu.models.train_stream import fit_scvi_stream
    from sctools_tpu.registry import Pipeline
    from sctools_tpu.scheduler import RunScheduler
    from sctools_tpu.utils.chaos import ChaosMonkey, Fault
    from sctools_tpu.utils.failsafe import (BreakerRegistry,
                                            JobPreempted, PreemptToken)
    from sctools_tpu.utils.telemetry import MetricsRegistry
    from sctools_tpu.utils.vclock import VirtualClock

    ds = synthetic_counts(1024, 64, density=0.2, n_clusters=3, seed=3)
    store = write_store(ds.X, os.path.join(tmp, "store"),
                        shard_rows=256, chunk_rows=64)
    ref = fit_scvi_stream(store, **HYPER)  # the uninterrupted oracle

    # -- 1. SIGKILL at a randomized shard read -> bitwise resume ------
    reads_per_run = store.n_shards * (HYPER["epochs"] + 0)
    kill_at = int(os.environ.get(
        "SCTOOLS_TEST_TRAIN_KILL",
        _random.SystemRandom().randint(2, reads_per_run - 1)))
    ck = os.path.join(tmp, "cursor.npz")
    jp = os.path.join(tmp, "train_journal.jsonl")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD, store.directory, ck, jp,
         str(kill_at)],
        env=env, capture_output=True, text=True, timeout=500)
    assert proc.returncode == -signal.SIGKILL, (kill_at, proc.stderr)
    assert os.path.exists(ck), (kill_at, "no cursor survived")
    got = fit_scvi_stream(store, checkpoint=ck, journal=jp, **HYPER)
    assert got["resumed_from"] is not None, kill_at
    assert _leaves_equal(ref["params"], got["params"]), (
        kill_at, "params diverged after SIGKILL resume")
    assert np.array_equal(ref["history"], got["history"]), kill_at
    assert not os.path.exists(ck), "cursor must self-delete"
    events = [json.loads(line) for line in open(jp)]
    kinds = [e["event"] for e in events]
    assert "train_resume" in kinds, kinds
    pairs = [(e["epoch"], e["pos"]) for e in events
             if e["event"] == "train_shard"]
    assert len(pairs) == len(set(pairs)), (
        "journal shows a REPLAYED shard", kill_at, pairs)
    resumed = got["resumed_from"]
    print(f"train_smoke: 1/3 SIGKILL at read {kill_at} -> resumed "
          f"from {resumed}, params bitwise-identical, "
          f"{len(pairs)} unique train_shard events")

    # -- 2. chaos preempt through the scheduler (VirtualClock) --------
    clock = VirtualClock()
    m = MetricsRegistry(clock=clock)
    monkey = ChaosMonkey([Fault("train-lab", "preempt", on_call=3)],
                         clock=clock)
    sj = os.path.join(tmp, "sched_journal.jsonl")
    ck2 = os.path.join(tmp, "cursor2.npz")
    pipe = Pipeline([("model.scvi_stream",
                      dict(store_dir=store.directory, checkpoint=ck2,
                           **HYPER))])
    placeholder = synthetic_counts(8, 8, density=0.3, seed=1)
    with RunScheduler(max_concurrency=1, clock=clock, metrics=m,
                      journal_path=sj,
                      breakers=BreakerRegistry(clock=clock),
                      chaos=monkey,
                      runner_defaults={"probe": lambda:
                                       {"ok": True}}) as sched:
        h = sched.submit(pipe, placeholder, tenant="train-lab",
                         backend="cpu", preemptible=True)
        out = h.result(timeout=600)
    hist = np.asarray(out.uns["scvi_stream_elbo_history"])
    assert np.array_equal(hist, ref["history"]), (
        "preempted+resumed history diverged")
    sev = [json.loads(line) for line in open(sj)]
    skinds = [e["event"] for e in sev]
    assert skinds.count("preempted") == 1, skinds
    from soak_smoke import check_journal_coherent

    check_journal_coherent(sj, 1)  # terminal exactly once
    assert [f["mode"] for f in monkey.injected] == ["preempt"]
    print("train_smoke: 2/3 chaos preempt OK (yield at boundary 3, "
          "requeued, resumed, terminal exactly once, zero real "
          "sleeps)")

    # -- 3. corrupt cursor -> quarantine + fall back a generation -----
    ck3 = os.path.join(tmp, "ck3", "cursor3.npz")
    os.makedirs(os.path.dirname(ck3))
    tok = PreemptToken()
    polls = [0]

    def probe():
        polls[0] += 1
        return "preempt" if polls[0] == 3 else None

    tok.probe = probe
    try:
        fit_scvi_stream(store, checkpoint=ck3, preempt=tok, **HYPER)
        raise AssertionError("expected JobPreempted")
    except JobPreempted:
        pass
    assert os.path.exists(ck3) and os.path.exists(ck3 + ".prev")
    with open(ck3, "r+b") as f:  # damage the NEWEST generation
        blob = bytearray(f.read())
        for i in range(0, min(len(blob), 2048), 7):
            blob[i] ^= 0xFF
        f.seek(0)
        f.write(blob)
    import warnings as _warnings

    with _warnings.catch_warnings(record=True) as wrec:
        _warnings.simplefilter("always")
        got3 = fit_scvi_stream(store, checkpoint=ck3, **HYPER)
    assert any("quarantined" in str(w.message) for w in wrec), (
        [str(w.message) for w in wrec])
    qdir = os.path.join(os.path.dirname(ck3), "quarantine")
    qfiles = os.listdir(qdir)
    assert any(f.endswith(".reason.json") for f in qfiles), qfiles
    assert any(not f.endswith(".json") for f in qfiles), qfiles
    # fell back ONE generation (pos 2, not a silent epoch restart),
    # and determinism still lands the identical params
    assert got3["resumed_from"] == {"epoch": 0, "pos": 2, "step": 4}, \
        got3["resumed_from"]
    assert _leaves_equal(ref["params"], got3["params"])
    print("train_smoke: 3/3 corrupt cursor OK (quarantined with "
          f"reason sidecar, resumed from .prev at pos 2, params "
          f"bitwise-identical)")
    print(f"train_smoke: ALL OK ({store.n_shards} shards, "
          f"{HYPER['epochs']} epochs)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
