"""Canned memory-fault-domain smoke — run_checks.sh gate.

A fast, deterministic, virtual-clock smoke of the memory fault domain
(``sctools_tpu/memory.py`` + the scheduler/runner wiring): a CAPPED
FAKE BUDGET (via the ``SCTOOLS_MEM_BUDGET_BYTES`` env cap — the same
knob CI uses to fake an HBM on a CPU box) admits a mixed-size
multi-tenant soak under chaos ``oom`` and ``mem_pressure`` faults.
Asserts:

* ZERO unhandled OOMs: every oom-faulted run completes through a
  containment-ladder rung (``mem.oom_events`` counts rungs, no ticket
  terminals ``run_failed`` on a RESOURCE error);
* the budget held: peak reserved bytes never exceed the cap, every
  reservation released, an infeasible arrival refused ``over_memory``
  at admission;
* the journal is COMPLETE and coherent (every ticket terminal exactly
  once — the shared ``soak_smoke.check_journal_coherent`` contract);
* zero real sleeps: everything timing-shaped moves on one
  VirtualClock.

Deliberately NOT named ``test_*`` — pytest skips it; the CI stage
runs ``python tests/mem_smoke.py`` (exit 0 = pass).  The full
acceptance soak (serving + preemptible training + per-rung audits)
lives in ``tests/test_memory.py``.
"""

import json
import os
import shutil
import sys
import tempfile
import warnings

# runnable as `python tests/mem_smoke.py` from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# the env cap must be set BEFORE the budget is constructed — this IS
# the detection path under test
CAP = 1_000_000
os.environ["SCTOOLS_MEM_BUDGET_BYTES"] = str(CAP)

from sctools_tpu.data.synthetic import synthetic_counts  # noqa: E402
from sctools_tpu.memory import MemoryBudget  # noqa: E402
from sctools_tpu.registry import Pipeline, register  # noqa: E402
from sctools_tpu.scheduler import (RunRejected,  # noqa: E402
                                   RunScheduler)
from sctools_tpu.utils.chaos import ChaosMonkey, Fault  # noqa: E402
from sctools_tpu.utils.failsafe import BreakerRegistry  # noqa: E402
from sctools_tpu.utils.telemetry import MetricsRegistry  # noqa: E402
from sctools_tpu.utils.vclock import VirtualClock  # noqa: E402

from soak_smoke import check_journal_coherent  # noqa: E402

N_SUBMISSIONS = 13  # 12 admitted + 1 refused over_memory


def _register_ops():
    """Smoke fixture ops (registered inside run() — importing this
    module must stay registry-clean)."""

    def _cost(params, input_bytes):
        return int(params.get("mem_bytes", input_bytes))

    def _passthrough(data, **kw):
        return data

    def _shrink(params):
        b = int(params.get("block", 256))
        if b <= 32:
            return None
        params["block"] = b // 2
        return params

    for backend in ("cpu", "tpu"):
        register("test.msmoke_sized", backend=backend,
                 mem_cost=_cost)(_passthrough)
        register("test.msmoke_fa", backend=backend,
                 fusable=True)(_passthrough)
        register("test.msmoke_fb", backend=backend,
                 fusable=True)(_passthrough)
        register("test.msmoke_shrink", backend=backend,
                 mem_shrink=_shrink)(_passthrough)
        register("test.msmoke_plain", backend=backend)(_passthrough)


def fail(msg: str) -> "NoReturn":  # noqa: F821
    print(f"mem_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def run() -> int:
    _register_ops()
    clock = VirtualClock()
    metrics = MetricsRegistry(clock=clock)
    budget = MemoryBudget(name="hbm0", metrics=metrics)
    if budget.capacity_bytes != CAP:
        fail(f"env cap not detected: {budget.capacity_bytes}")
    jdir = tempfile.mkdtemp(prefix="sct_mem_smoke_")
    jpath = os.path.join(jdir, "journal.jsonl")
    chaos = ChaosMonkey(
        [Fault("test.msmoke_fa", "oom", backend="tpu", times=1),
         Fault("test.msmoke_shrink", "oom", backend="tpu", times=1),
         Fault("test.msmoke_plain", "oom", backend="tpu", times=-1),
         Fault("hbm0", "mem_pressure", on_call=4, times=3)],
        clock=clock)
    sched = RunScheduler(
        max_concurrency=3, clock=clock, metrics=metrics,
        journal_path=jpath, breakers=BreakerRegistry(clock=clock),
        chaos=chaos, mem_budget=budget,
        runner_defaults={"sleep": lambda s: None,
                         "probe": lambda: {"ok": True}})
    data = synthetic_counts(48, 24, density=0.2, seed=0)

    handles = []
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            handles.append(sched.submit(
                Pipeline([("test.msmoke_fa", {}),
                          ("test.msmoke_fb", {})]), data,
                tenant="lab-a", backend="tpu",
                runner_kw={"fuse": True}))
            handles.append(sched.submit(
                Pipeline([("test.msmoke_shrink", {"block": 256})]),
                data, tenant="lab-b", backend="tpu"))
            handles.append(sched.submit(
                Pipeline([("test.msmoke_plain", {})]), data,
                tenant="lab-c", backend="tpu"))
            for i in range(9):
                handles.append(sched.submit(
                    Pipeline([("test.msmoke_sized",
                               {"mem_bytes": 250_000 + 20_000 * i})]),
                    data, tenant=f"t-{i % 3}", backend="cpu"))
            try:
                sched.submit(
                    Pipeline([("test.msmoke_sized",
                               {"mem_bytes": CAP * 5})]), data,
                    tenant="greedy", backend="cpu")
                fail("over-budget arrival was not rejected")
            except RunRejected as e:
                if e.reason != "over_memory":
                    fail(f"wrong rejection reason: {e.reason}")
            for h in handles:
                h.result(timeout=120)
        sched.shutdown(wait=True)

        # -- zero unhandled OOMs: every oom-faulted run completed
        # through a ladder rung, no ticket failed
        with open(jpath) as f:
            events = [json.loads(line) for line in f]
        failed = [e for e in events if e["event"] == "run_failed"]
        if failed:
            fail(f"{len(failed)} run(s) failed — unhandled OOMs? "
                 f"{failed}")
        snap = metrics.snapshot_compact()
        for rung in ("unfuse", "replan", "cpu"):
            if snap.get(f"mem.oom_events{{rung={rung}}}", 0) < 1:
                fail(f"ladder rung {rung!r} never fired")
        oom_fired = sum(1 for f in chaos.injected
                        if f["mode"] == "oom")
        if oom_fired < 3:
            fail(f"expected >=3 injected ooms, saw {oom_fired}")
        if not any(f["mode"] == "mem_pressure"
                   for f in chaos.injected):
            fail("mem_pressure never fired")

        # -- the budget held
        if budget.peak_reserved_bytes > CAP:
            fail(f"peak reserved {budget.peak_reserved_bytes} "
                 f"exceeded the {CAP} cap")
        if budget.reserved_bytes() != 0:
            fail(f"{budget.reserved_bytes()} bytes still reserved "
                 f"after drain")
        declared = sum(e.get("mem_bytes", 0) for e in events
                       if e["event"] == "admitted")
        if declared <= 2 * CAP:
            fail(f"soak under-subscribed the budget ({declared} "
                 f"bytes admitted vs {CAP} cap)")

        # -- journal coherent: every ticket terminal exactly once
        check_journal_coherent(jpath, N_SUBMISSIONS)

        # -- zero real sleeps: nothing moved the virtual clock but
        # chaos/backoff, and backoff sleeps were injected no-ops
        print(f"mem_smoke: OK — {len(handles)} run(s) + 1 refusal, "
              f"peak reserved {budget.peak_reserved_bytes}/{CAP} "
              f"bytes, rungs "
              + ", ".join(f"{r}={snap.get(f'mem.oom_events{{rung={r}}}', 0):g}"
                          for r in ("unfuse", "replan", "cpu"))
              + f", virtual clock at {clock.monotonic():.1f}s")
        return 0
    finally:
        shutil.rmtree(jdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(run())
