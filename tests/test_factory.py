"""The annotation factory (``factory.py``) — the closed
ingest → retrain → freeze → canary-swap loop — plus the durable
append path it stands on (``StoreWriter.append_to``).

Covers the cross-domain seams no single-module suite reaches:

* at-most-once ingest (manifest append ledger, torn-append redo);
* between-stage crash resume (``stage_crash`` chaos after the train
  commit and after the build commit) proven BITWISE from the merged
  journal — no replayed training shards, params/artifact untouched;
* incarnation fencing (``owner.json`` epoch);
* forced canary disagreement and corrupt-candidate rollback — the
  old epoch keeps serving;
* the full-stack soak: kill + wedge + mem-pressure + corrupt +
  preempt on ONE VirtualClock, zero dropped queries, both journals
  terminal-exactly-once.

The CI-stage variant lives in ``tests/factory_smoke.py``.
"""

import json
import os
import shutil
import threading
import warnings

import numpy as np
import pytest
import scipy.sparse as sp

import sctools_tpu as sct
from sctools_tpu.data.shardstore import (ShardCorruptError, ShardStore,
                                         StoreWriter, write_store)
from sctools_tpu.data.synthetic import synthetic_counts
from sctools_tpu.factory import (AnnotationFactory, FactoryFencedError,
                                 append_store)
from sctools_tpu.federation import FederationSupervisor
from sctools_tpu.memory import MemoryBudget
from sctools_tpu.serving import (AnnotationService,
                                 build_reference_artifact)
from sctools_tpu.utils.chaos import ChaosCrash, ChaosMonkey, Fault
from sctools_tpu.utils.telemetry import MetricsRegistry
from sctools_tpu.utils.vclock import VirtualClock

N_GENES = 64
HYPER = dict(n_latent=4, n_hidden=16, epochs=2, batch_size=128,
             seed=0)


def mk(n, seed):
    d = synthetic_counts(n, N_GENES, density=0.15, n_clusters=3,
                         seed=seed)
    return d.with_obs(cell_type=np.array(
        [f"type{c}" for c in np.asarray(d.obs["cluster_true"])]))


@pytest.fixture(scope="module")
def seed_bundle(tmp_path_factory):
    """Base store (256 cells) + a gen0 serving artifact (with a
    ``.prev`` generation), built once and COPIED per test — every
    test mutates its own store."""
    root = tmp_path_factory.mktemp("factory_seed")
    base = mk(256, 0)
    write_store(base.X.tocsr(), str(root / "store"), shard_rows=128,
                chunk_rows=64)
    labels = [str(v) for v in np.asarray(base.obs["cell_type"])]
    fitted = sct.run_recipe(
        "annotation_reference",
        sct.from_scipy(base.X.tocsr(),
                       obs={"cell_type": np.array(labels)}),
        backend="cpu", n_components=12)
    art = str(root / "model.npz")
    build_reference_artifact(fitted, art, labels_key="cell_type",
                             seed=0, version="gen0a")
    build_reference_artifact(fitted, art, labels_key="cell_type",
                             seed=0, version="gen0")
    return {"root": str(root), "labels": labels}


class Rig:
    """One test's live world: a private copy of the seed store +
    artifact, a service on a VirtualClock, and a factory builder
    whose ``ref_source`` tracks every ingested batch's labels."""

    def __init__(self, tmp, seed, *, chaos=None, mem_budget=None):
        self.tmp = str(tmp)
        self.store_dir = os.path.join(self.tmp, "store")
        shutil.copytree(os.path.join(seed["root"], "store"),
                        self.store_dir)
        self.art = os.path.join(self.tmp, "model.npz")
        shutil.copy(os.path.join(seed["root"], "model.npz"), self.art)
        shutil.copy(os.path.join(seed["root"], "model.npz") + ".prev",
                    self.art + ".prev")
        self.labels = list(seed["labels"])
        self.clock = VirtualClock()
        self.metrics = MetricsRegistry(clock=self.clock)
        self.journal_path = os.path.join(self.tmp, "journal.jsonl")
        self.svc = AnnotationService(
            self.art, name="fx", backend="tpu", clock=self.clock,
            metrics=self.metrics, journal_path=self.journal_path,
            chaos=chaos, mem_budget=mem_budget, max_concurrency=2,
            k=10, runner_defaults={"probe": lambda: {"ok": True}})

    def batch(self, n, seed):
        b = mk(n, seed)
        self.labels.extend(np.asarray(b.obs["cell_type"]).tolist())
        return b

    def ref_source(self, store):
        X = sp.vstack([sh.to_scipy_csr() for sh in
                       store.iter_shards()],
                      format="csr")[: store.n_cells]
        return sct.from_scipy(
            X, obs={"cell_type": np.array(self.labels)})

    def factory(self, **kw):
        kw.setdefault("n_components", 12)
        kw.setdefault("backend", "cpu")
        kw.setdefault("train_kw", HYPER)
        kw.setdefault("result_timeout_s", 600)
        return AnnotationFactory(
            os.path.join(self.tmp, "factory"),
            store_dir=self.store_dir, service=self.svc,
            ref_source=self.ref_source, name="fx", **kw)

    def events(self):
        return [json.loads(line) for line in open(self.journal_path)]

    def close(self):
        self.svc.drain()
        self.svc.close()


# ------------------------------------------------ StoreWriter.append_to

def _small_store(tmp_path, n=128):
    d = synthetic_counts(n, 16, density=0.3, seed=1)
    return write_store(d.X, str(tmp_path / "s"), shard_rows=64,
                       chunk_rows=32)


def test_append_to_extends_and_ledgers(tmp_path):
    store = _small_store(tmp_path)
    block = sp.csr_matrix(synthetic_counts(32, 16, density=0.3,
                                           seed=2).X.tocsr())
    w = StoreWriter.append_to(store, label="b1")
    w.append(block)
    out = w.close()
    assert out.n_cells == 160
    assert out.append_labels() == ["b1"]
    led = out.manifest["appends"][0]
    assert led["row_start"] == 128 and led["rows"] == 32
    # the appended rows read back bitwise, through the verified path
    got = sp.vstack([sh.to_scipy_csr() for sh in out.iter_shards()],
                    format="csr")[128:160]
    assert np.array_equal(got.toarray(), block.toarray())
    # digest chain stays extendable: a second append still verifies
    w2 = StoreWriter.append_to(out.directory, label="b2")
    w2.append(block)
    assert w2.close().append_labels() == ["b1", "b2"]


def test_append_to_refuses_geometry_mismatch(tmp_path):
    store = _small_store(tmp_path)
    with pytest.raises(ValueError, match="geometry is frozen"):
        StoreWriter.append_to(store, n_genes=17)
    with pytest.raises(ValueError, match="geometry is frozen"):
        StoreWriter.append_to(store, chunk_rows=64)


def test_append_to_refuses_tampered_manifest(tmp_path):
    store = _small_store(tmp_path)
    mpath = os.path.join(store.directory, "manifest.json")
    m = json.load(open(mpath))
    m["store_digest"] = "0" * 16
    json.dump(m, open(mpath, "w"))
    with pytest.raises(ShardCorruptError, match="tampered manifest"):
        StoreWriter.append_to(store.directory)


def test_append_to_refuses_partial_tail(tmp_path):
    store = _small_store(tmp_path)
    w = StoreWriter.append_to(store)
    w.append(sp.csr_matrix(np.ones((16, 16), np.float32)))
    out = w.close()  # legal write, but leaves a 16-row tail chunk
    assert out.n_cells == 144
    with pytest.raises(ValueError, match="ends mid-chunk"):
        StoreWriter.append_to(out.directory)


def test_torn_append_redo_is_byte_identical(tmp_path):
    """A crash between chunk flush and manifest commit leaves orphan
    chunk files; the redo overwrites them deterministically and the
    ledger records the batch ONCE."""
    store = _small_store(tmp_path)
    block = sp.csr_matrix(synthetic_counts(64, 16, density=0.3,
                                           seed=3).X.tocsr())
    w = StoreWriter.append_to(store, label="torn")
    w.append(block)  # full chunks flush eagerly ...
    orphan = os.path.join(store.directory, "chunks",
                          "chunk-00004.npz")
    assert os.path.exists(orphan)  # ... but the manifest is untouched
    orphan_bytes = open(orphan, "rb").read()
    assert ShardStore.open(store.directory).n_cells == 128
    del w  # simulated death before close()

    d = mk_cell(block)
    out = append_store(d, store_dir=store.directory, label="torn")
    assert int(out.uns["append_store_rows"]) == 64
    assert not bool(out.uns["append_store_skipped"])
    assert open(orphan, "rb").read() == orphan_bytes
    store2 = ShardStore.open(store.directory)
    assert store2.n_cells == 192
    assert store2.append_labels() == ["torn"]
    # the requeued ticket's SECOND redo dedups on the ledger
    out2 = append_store(d, store_dir=store.directory, label="torn")
    assert bool(out2.uns["append_store_skipped"])
    assert int(out2.uns["append_store_rows"]) == 0
    assert ShardStore.open(store.directory).n_cells == 192


def mk_cell(block):
    return sct.from_scipy(sp.csr_matrix(block))


# ------------------------------------------------------- the full cycle

def test_cycle_promotes_and_is_idempotent(seed_bundle, tmp_path):
    rig = Rig(tmp_path, seed_bundle)
    fac = rig.factory()
    b1 = rig.batch(64, 11)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        t = rig.svc.query(mk(5, 90), "label_transfer", tenant="lab")
        st = fac.run_cycle([("b1", b1)], cycle=0)
        assert t.result(timeout=600)["epoch"] == 0

    assert st["terminal"] == "promoted"
    assert rig.svc.epoch == 1
    assert rig.svc.model_version == "fx-c0000"
    assert st["swap"]["agreement"] >= 0.9
    assert ShardStore.open(rig.store_dir).n_cells == 320
    # the trained cursor was pinned to the POST-ingest store digest
    assert st["train"]["store_digest"] == st["ingest"]["store_digest"]
    kinds = [e["event"] for e in rig.events() if "cycle" in e]
    assert kinds == ["ingest_committed", "retrain_triggered",
                     "artifact_built", "swap_promoted"]
    assert all("ticket" not in e for e in rig.events()
               if "cycle" in e)
    # terminal cycles are inert; the next cycle id advances
    again = fac.run_cycle([("b1", b1)], cycle=0)
    assert again == st and rig.svc.epoch == 1
    assert fac.next_cycle() == 1
    rig.close()


def test_resume_between_stage_seams_and_fencing(seed_bundle,
                                                tmp_path):
    """Kill after the train commit (entering build), then after the
    build commit (entering swap); every incarnation resumes from the
    durable cursors — no replayed training shards, params and
    artifact byte-stable — and the fenced stale incarnation refuses
    to commit."""
    rig = Rig(tmp_path, seed_bundle)
    monkey = ChaosMonkey([Fault("fx/build", "stage_crash", on_call=1),
                          Fault("fx/swap", "stage_crash", on_call=1)],
                         clock=rig.clock)
    b1 = rig.batch(64, 11)
    batches = [("b1", b1)]
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        fac1 = rig.factory(chaos=monkey)
        with pytest.raises(ChaosCrash, match="entering stage 'build'"):
            fac1.run_cycle(batches, cycle=0)
        st = fac1.load_state(0)
        assert "train" in st and "build" not in st
        shards_before = [(e["epoch"], e["pos"]) for e in rig.events()
                         if e["event"] == "train_shard"]
        pmtime = os.path.getmtime(
            os.path.join(fac1.cycle_dir(0), "params.npz"))

        fac2 = rig.factory(chaos=monkey)
        with pytest.raises(ChaosCrash, match="entering stage 'swap'"):
            fac2.run_cycle(batches, cycle=0)
        st = fac2.load_state(0)
        assert "build" in st and "swap" not in st
        amtime = os.path.getmtime(
            os.path.join(fac2.cycle_dir(0), "artifact.npz"))

        # fac2's claim fenced fac1: its next commit must refuse
        with pytest.raises(FactoryFencedError):
            fac1.run_cycle(batches, cycle=0)

        fac3 = rig.factory(chaos=monkey)
        st = fac3.run_cycle(batches, cycle=0)

    assert st["terminal"] == "promoted"
    assert rig.svc.epoch == 1 and rig.svc.model_version == "fx-c0000"
    ev = rig.events()
    shards_after = [(e["epoch"], e["pos"]) for e in ev
                    if e["event"] == "train_shard"]
    assert shards_after == shards_before, "training shards replayed"
    assert len(shards_after) == len(set(shards_after))
    assert os.path.getmtime(
        os.path.join(fac3.cycle_dir(0), "params.npz")) == pmtime
    assert os.path.getmtime(
        os.path.join(fac3.cycle_dir(0), "artifact.npz")) == amtime
    kinds = [e["event"] for e in ev]
    for k in ("ingest_committed", "retrain_triggered",
              "artifact_built", "swap_promoted"):
        assert kinds.count(k) == 1, (k, kinds)
    assert [f["mode"] for f in monkey.injected] == \
        ["stage_crash", "stage_crash"]
    rig.close()


def test_canary_disagreement_rolls_back(seed_bundle, tmp_path,
                                        monkeypatch):
    """A candidate whose loadings no longer match its recorded
    reference scores fails its own canary; the swap rolls back and
    the OLD epoch keeps serving."""
    import sctools_tpu.factory as factory_mod

    real = factory_mod.build_reference_artifact_checked

    def poisoned(ref, path, **kw):
        pcs = np.asarray(ref.varm["PCs"])
        rng = np.random.default_rng(7)
        bad = rng.normal(size=pcs.shape).astype(pcs.dtype)
        return real(ref.with_varm(PCs=bad), path, **kw)

    monkeypatch.setattr(factory_mod,
                        "build_reference_artifact_checked", poisoned)
    rig = Rig(tmp_path, seed_bundle)
    fac = rig.factory()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        st = fac.run_cycle([("b1", rig.batch(64, 11))], cycle=0)
        t = rig.svc.query(mk(5, 90), "label_transfer", tenant="lab")
        assert t.result(timeout=600)["epoch"] == 0

    assert st["terminal"] == "rolled_back"
    assert st["swap"]["reason"] == "canary_disagreement"
    assert st["swap"]["agreement"] < 0.9
    assert rig.svc.epoch == 0 and rig.svc.model_version == "gen0"
    rb = [e for e in rig.events()
          if e["event"] == "swap_rolled_back" and "cycle" in e]
    assert len(rb) == 1 and rb[0]["reason"] == "canary_disagreement"
    # a rolled-back cycle is terminal: the loop moves on, it does
    # not retry the poisoned candidate forever
    assert fac.next_cycle() == 1
    rig.close()


def test_corrupt_candidate_rolls_back(seed_bundle, tmp_path):
    """Crash entering swap, damage the built candidate on disk (the
    torn-artifact window), resume: the digest check refuses the
    candidate and the cycle terminals ``rolled_back``."""
    rig = Rig(tmp_path, seed_bundle)
    monkey = ChaosMonkey([Fault("fx/swap", "stage_crash", on_call=1)],
                         clock=rig.clock)
    b1 = rig.batch(64, 11)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        fac = rig.factory(chaos=monkey)
        with pytest.raises(ChaosCrash):
            fac.run_cycle([("b1", b1)], cycle=0)
        artp = os.path.join(fac.cycle_dir(0), "artifact.npz")
        blob = bytearray(open(artp, "rb").read())
        for i in range(0, len(blob), max(1, len(blob) // 16)):
            blob[i] ^= 0xFF
        open(artp, "wb").write(bytes(blob))
        st = rig.factory(chaos=monkey).run_cycle([("b1", b1)],
                                                 cycle=0)
    assert st["terminal"] == "rolled_back"
    assert st["swap"]["reason"] == "artifact_corrupt"
    assert rig.svc.epoch == 0 and rig.svc.model_version == "gen0"
    rig.close()


# -------------------------------------------------- the full-stack soak

def test_factory_soak_full_stack(seed_bundle, tmp_path):
    """Kill + wedge + mem-pressure + corrupt + preempt on ONE
    VirtualClock: ingest rides federation tickets (worker killed,
    lease wedged), the retrain is preempted by the shared funnel,
    the live model is corrupted mid-traffic, and the memory budget
    comes under chaos pressure — the cycle still promotes, zero
    queries drop, and both journals are terminal-exactly-once."""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from soak_smoke import check_journal_coherent

    budget = MemoryBudget(500_000_000, name="hbm0")
    chaos = ChaosMonkey([
        Fault("w0", "kill_worker", on_call=2),
        Fault("w1", "lease_wedge", on_call=2),
        Fault("factory-train", "preempt", on_call=2),
        Fault("fx", "corrupt_model", on_call=2),
        Fault("hbm0", "mem_pressure", on_call=3, times=2),
    ])
    rig = Rig(tmp_path, seed_bundle, chaos=chaos, mem_budget=budget)
    b1, b2 = rig.batch(64, 11), rig.batch(64, 12)
    fed_dir = os.path.join(rig.tmp, "fed")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with FederationSupervisor(
                fed_dir, n_workers=2, heartbeat_s=0.1, poll_s=0.05,
                lease_timeout_s=30.0, clock=rig.clock,
                metrics=rig.metrics, chaos=chaos, max_respawns=1,
                tenant_max_queued=16,
                runner_config={"assume_healthy": True}) as sup:
            fac = rig.factory(supervisor=sup, result_timeout_s=240)
            th = threading.Thread(
                target=lambda: (sup.wedge_observed.wait(timeout=120)
                                and rig.clock.advance(31.0)),
                daemon=True)
            th.start()
            tickets = [rig.svc.query(mk(3 + i, 80 + i),
                                     "label_transfer",
                                     tenant=f"lab-{i % 2}")
                       for i in range(4)]
            st = fac.run_cycle([("b1", b1), ("b2", b2)], cycle=0)
            tickets.append(rig.svc.query(mk(6, 70), "label_transfer",
                                         tenant="lab-0"))
            results = [t.result(timeout=600) for t in tickets]
            th.join(timeout=10)

    assert st["terminal"] == "promoted"
    # every chaos leg actually fired
    modes = sorted({f["mode"] for f in chaos.injected})
    assert modes == ["corrupt_model", "kill_worker", "lease_wedge",
                     "mem_pressure", "preempt"], modes
    # zero dropped queries, each on its admitted epoch
    assert all(t.status == "completed" for t in tickets)
    for t, r in zip(tickets, results):
        assert r["epoch"] == t.epoch
    # the served epoch provably reflects the freshly-ingested data:
    # the promoted artifact's version is this cycle's, its training
    # ran on the post-ingest store digest, and the store grew
    assert rig.svc.epoch == 1
    assert rig.svc.model_version == "fx-c0000"
    store = ShardStore.open(rig.store_dir)
    assert store.n_cells == 256 + 128
    assert store.append_labels() == ["b1", "b2"]
    assert st["train"]["store_digest"] == \
        str(store.manifest["store_digest"])
    # both journals coherent: the federation funnel saw 2 tickets,
    # the service funnel saw the queries + the retrain submission
    check_journal_coherent(os.path.join(fed_dir, "journal.jsonl"), 2)
    rig.svc.drain()
    check_journal_coherent(rig.journal_path, len(tickets) + 1)
    fkinds = [json.loads(line)["event"]
              for line in open(os.path.join(fed_dir,
                                            "journal.jsonl"))]
    assert "worker_lost" in fkinds
    rig.close()


# ------------------------------------------- cycle vs operator races

def test_cycle_racing_manual_swap_never_double_promotes(seed_bundle,
                                                        tmp_path):
    """A running cycle races a manual operator ``service.swap()`` of
    the SAME candidate: the resumed cycle RECOGNISES the resident
    version instead of re-flipping (exactly one serving epoch
    burned, one ``model_swapped``, one ``swap_promoted``), and the
    stale incarnation — the race's loser — is fenced loudly, never a
    silent double promote."""
    rig = Rig(tmp_path, seed_bundle)
    monkey = ChaosMonkey([Fault("fx/swap", "stage_crash", on_call=1)])
    batches = [("b1", rig.batch(64, 21))]
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        fac1 = rig.factory(chaos=monkey)
        with pytest.raises(ChaosCrash, match="entering stage 'swap'"):
            fac1.run_cycle(batches, cycle=0)
        candidate = fac1.load_state(0)["build"]["artifact"]
        # the operator's manual swap wins the race to the flip
        assert rig.svc.swap(candidate)
        assert rig.svc.epoch == 1
        # a fresh incarnation resumes the torn cycle...
        fac2 = rig.factory()
        # ...which fences the crashed one: the loser cannot sneak a
        # second promote in
        with pytest.raises(FactoryFencedError):
            fac1.run_cycle(batches, cycle=0)
        st = fac2.run_cycle(batches, cycle=0)

    assert st["terminal"] == "promoted"
    assert st["swap"].get("resumed") is True  # recognised, not redone
    assert rig.svc.epoch == 1                 # ONE epoch, not two
    assert rig.svc.model_version == "fx-c0000"
    ev = rig.events()
    kinds = [e["event"] for e in ev]
    assert kinds.count("swap_promoted") == 1
    swaps = [e for e in ev if e["event"] == "model_swapped"
             and e.get("reason") != "init"]
    assert len(swaps) == 1                    # the manual flip only
    assert not [e for e in ev if e["event"] == "swap_rolled_back"]
    rig.close()


def test_overlapping_cycle_refused_while_predecessor_live(seed_bundle,
                                                          tmp_path):
    """Cycle N+1 refuses to start while cycle N is live
    (non-terminal): the overlap is refused at entry, the resume
    target stays N, and only N's terminal unlocks N+1."""
    rig = Rig(tmp_path, seed_bundle)
    monkey = ChaosMonkey([Fault("fx/build", "stage_crash",
                                on_call=1)])
    batches = [("b1", rig.batch(64, 31))]
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        fac1 = rig.factory(chaos=monkey)
        with pytest.raises(ChaosCrash,
                           match="entering stage 'build'"):
            fac1.run_cycle(batches, cycle=0)
        # cycle 0 is torn, not terminal: it IS the resume target
        fac2 = rig.factory()
        assert fac2.next_cycle() == 0
        with pytest.raises(ValueError, match="cycle 0 is live"):
            fac2.run_cycle([("b2", rig.batch(64, 32))], cycle=1)
        # no half-started cycle-1 residue survives the refusal
        assert not os.path.exists(fac2.cycle_dir(1))
        # finishing cycle 0 unlocks cycle 1
        st = fac2.run_cycle(batches, cycle=0)
        assert st["terminal"] == "promoted"
        assert fac2.next_cycle() == 1
        st1 = fac2.run_cycle([("b2", rig.batch(64, 32))], cycle=1)
        assert st1["terminal"] == "promoted"
    assert rig.svc.epoch == 2
    rig.close()
