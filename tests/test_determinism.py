"""utils.determinism — the race-detection analogue: key pipeline
stages must be bit-identical across repeat runs (thread timing in the
prefetcher/packer, PRNG handling, and shard-order reductions are the
hazards this guards)."""

import numpy as np
import pytest

from sctools_tpu.data.stream import ShardSource, stream_pipeline
from sctools_tpu.data.synthetic import synthetic_counts
from sctools_tpu.utils.determinism import check_deterministic


@pytest.fixture(scope="module")
def counts():
    return synthetic_counts(800, 300, density=0.1, n_clusters=3, seed=2)


def test_detects_nondeterminism():
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        return {"x": np.full(4, state["n"])}

    rep = check_deterministic(flaky)
    assert not rep.ok
    assert rep.mismatches


def test_detects_shape_drift():
    state = {"n": 0}

    def grows():
        state["n"] += 1
        return [np.zeros(state["n"])]

    rep = check_deterministic(grows)
    assert not rep.ok


def test_stream_pipeline_deterministic(counts):
    """The full streaming pipeline — including the PREFETCH THREAD
    (h5ad source) — must be bit-stable run to run."""
    import tempfile, os

    from sctools_tpu.data.io import write_h5ad

    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "c.h5ad")
        write_h5ad(counts, p)
        src = ShardSource.from_h5ad(p, shard_rows=256)
        assert src.prefetch  # the threaded path is what's under test

        def run():
            out = stream_pipeline(src, n_top=100, n_components=10, k=8,
                                  refine=16)
            return {"pca": np.asarray(out["X_pca"]),
                    "knn": np.asarray(out["knn_indices"]),
                    "hvg": np.asarray(out["hvg_genes"])}

        rep = check_deterministic(run)
        assert rep.ok, rep.mismatches[:5]


def test_tolerance_mode():
    state = {"n": 0}

    def jitter():
        state["n"] += 1
        return np.array([1.0 + 1e-9 * state["n"]])

    assert not check_deterministic(jitter).ok
    assert check_deterministic(jitter, exact=False, atol=1e-6).ok


def test_detects_structure_change():
    """Same leaf count, different keys — must NOT pass (run-to-run
    structural drift is exactly what a nondeterministic id produces)."""
    state = {"n": 0}

    def renames():
        state["n"] += 1
        return {f"k{state['n']}": np.zeros(3)}

    rep = check_deterministic(renames)
    assert not rep.ok
    assert "structure" in rep.mismatches[0][1]


def test_scipy_sparse_leaves_compared_fully():
    import scipy.sparse as sp

    state = {"n": 0}

    def shifting_pattern():
        state["n"] += 1
        # same data/indices arrays, different indptr -> different matrix
        if state["n"] == 1:
            return sp.csr_matrix(([1.0, 1.0], [0, 0], [0, 1, 2]),
                                 shape=(2, 2))
        return sp.csr_matrix(([1.0, 1.0], [0, 0], [0, 2, 2]),
                             shape=(2, 2))

    rep = check_deterministic(shifting_pattern)
    assert not rep.ok


def test_runs_validation():
    with pytest.raises(ValueError, match="asserts nothing"):
        check_deterministic(lambda: 1, runs=1)


def test_non_arrayable_leaf_compared_by_identity():
    """Leaves numpy can't convert (raising ``__array__``) fall back to
    identity/equality instead of crashing — and the swallowed
    conversion error is logged, not silent (sctlint SCT005).  A plain
    object WITHOUT ``__array__`` takes the 0-d-object-array path
    instead; both must come out ok for an identical leaf."""
    class NotArrayable:
        def __array__(self, *a, **kw):
            raise TypeError("refuses conversion")

    class Opaque:
        pass

    na, o = NotArrayable(), Opaque()
    rep = check_deterministic(
        lambda: {"x": np.arange(3), "na": na, "o": o})
    assert rep.ok, rep.mismatches


def test_not_arrayable_and_incomparable_reported():
    """Worst case — neither arrayable nor comparable: the check must
    report the failed equality as the mismatch reason, not raise."""
    class Nasty:
        def __array__(self, *a, **kw):
            raise TypeError("no array")

        def __eq__(self, other):
            raise TypeError("no eq")
        __hash__ = None

    outs = [Nasty(), Nasty()]
    rep = check_deterministic(lambda: outs.pop(0))
    assert not rep.ok
    assert "equality check failed" in str(rep.mismatches[0][1])


def test_raising_eq_reported_not_raised():
    """An object whose __eq__ raises must surface as a mismatch
    REASON; the determinism check itself never crashes the run it is
    checking."""
    class Hostile:
        def __eq__(self, other):
            raise TypeError("nope")
        __hash__ = None

    outs = [Hostile(), Hostile()]
    rep = check_deterministic(lambda: outs.pop(0))
    assert not rep.ok
    assert "raised" in str(rep.mismatches[0][1])
