"""embed.phate: potential-distance embedding."""

import numpy as np
import pytest

import sctools_tpu as sct
from sctools_tpu.data.dataset import CellData


def _spearman(a, b):
    ra = np.argsort(np.argsort(a)).astype(np.float64)
    rb = np.argsort(np.argsort(b)).astype(np.float64)
    ra -= ra.mean()
    rb -= rb.mean()
    return float((ra * rb).sum()
                 / np.sqrt((ra * ra).sum() * (rb * rb).sum()))


@pytest.fixture(scope="module")
def curve():
    """Cells along a noisy 1-D curve embedded in 10-D — PHATE must
    recover the ordering along its first component."""
    rng = np.random.default_rng(0)
    n = 400
    tt = np.sort(rng.random(n))
    base = np.stack([np.cos(2 * tt), np.sin(2 * tt)] + [tt * 2] * 2,
                    axis=1)
    E = np.concatenate([base, rng.normal(0, 0.03, (n, 6))], axis=1)
    d = CellData(np.zeros((n, 1), np.float32),
                 obsm={"X_pca": E.astype(np.float32)},
                 obs={"t": tt})
    d = sct.apply("neighbors.knn", d, backend="cpu", k=12,
                  metric="euclidean")
    return d, tt


def test_phate_orders_trajectory_cpu(curve):
    d, tt = curve
    # t=80: long diffusion resolves this curve's global ordering
    # (measured spearman 0.94; the auto-t knee is a heuristic users
    # override, same as with published PHATE)
    out = sct.apply("embed.phate", d, backend="cpu", n_components=2,
                    t=80)
    emb = np.asarray(out.obsm["X_phate"])
    assert emb.shape == (400, 2)
    assert abs(_spearman(emb[:, 0], tt)) > 0.9
    # auto-t runs and lands in a sane range; longer t only refines
    auto = sct.apply("embed.phate", d, backend="cpu", n_components=2)
    assert 2 <= auto.uns["phate_t"] <= 100
    assert abs(_spearman(
        np.asarray(auto.obsm["X_phate"])[:, 0], tt)) > 0.6


def test_phate_tpu_matches_cpu_geometry(curve):
    d, tt = curve
    t = 80
    out_c = sct.apply("embed.phate", d, backend="cpu", t=t)
    out_t = sct.apply("embed.phate", d, backend="tpu", t=t)
    ec = np.asarray(out_c.obsm["X_phate"], np.float64)
    et = np.asarray(out_t.obsm["X_phate"], np.float64)
    # eigenvectors are sign/rotation-ambiguous: compare the induced
    # pairwise geometry instead of coordinates
    rng = np.random.default_rng(0)
    ii = rng.integers(0, 400, 300)
    jj = rng.integers(0, 400, 300)
    dc = np.linalg.norm(ec[ii] - ec[jj], axis=1)
    dt = np.linalg.norm(et[ii] - et[jj], axis=1)
    assert _spearman(dc, dt) > 0.99
    # and both order the trajectory
    assert abs(_spearman(et[:, 0], tt)) > 0.9  # measured 0.944 (f32)


def test_phate_requires_graph():
    d = CellData(np.zeros((5, 2), np.float32))
    with pytest.raises(KeyError, match="neighbors.knn"):
        sct.apply("embed.phate", d, backend="cpu")
