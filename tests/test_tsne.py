"""embed.tsne — structure preservation and backend parity."""

import numpy as np
import pytest

import sctools_tpu as sct
from sctools_tpu.data.dataset import CellData
from sctools_tpu.data.synthetic import gaussian_blobs
from sctools_tpu.ops.cluster import adjusted_rand_index
from sctools_tpu.ops.knn import knn_numpy


@pytest.fixture(scope="module")
def blobs():
    n, blobs_n = 600, 5
    pts, truth = gaussian_blobs(n, 10, blobs_n, spread=0.2, seed=3)
    idx, dist = knn_numpy(pts, pts, k=15, metric="euclidean",
                          exclude_self=True)
    d = CellData(np.zeros((n, 4), np.float32),
                 obs={"truth": truth}).with_obsp(
        knn_indices=idx, knn_distances=dist).with_uns(
        knn_k=15, knn_metric="euclidean")
    return d, truth


def _purity(emb, truth, k=15):
    """Fraction of embedding-kNN sharing the query's true label —
    deterministic, unlike k-means whose one-shot init can split a
    blob and fail an otherwise perfect layout."""
    emb = np.asarray(emb, np.float64)
    idx, _ = knn_numpy(emb, emb, k=k, metric="euclidean",
                       exclude_self=True)
    return float((truth[idx] == truth[:, None]).mean())


def test_tsne_separates_blobs(blobs):
    d, truth = blobs
    out = sct.apply("embed.tsne", d, backend="tpu", n_iter=350)
    emb = np.asarray(out.obsm["X_tsne"])
    assert emb.shape == (600, 2)
    assert np.isfinite(emb).all()
    purity = _purity(emb, truth)
    assert purity > 0.95, purity


def test_tsne_backend_parity(blobs):
    """Same init, same math → both backends must separate the blobs
    and agree on the neighbourhood structure (not bit-identical:
    f32 scan vs f64 loop)."""
    d, truth = blobs
    t = sct.apply("embed.tsne", d, backend="tpu", n_iter=300)
    c = sct.apply("embed.tsne", d, backend="cpu", n_iter=300)
    pur_t = _purity(np.asarray(t.obsm["X_tsne"]), truth)
    pur_c = _purity(np.asarray(c.obsm["X_tsne"]), truth)
    assert pur_t > 0.95 and pur_c > 0.95, (pur_t, pur_c)
    # structural agreement: the embeddings' kNN graphs overlap
    it, _ = knn_numpy(np.asarray(t.obsm["X_tsne"], np.float64),
                      np.asarray(t.obsm["X_tsne"], np.float64), k=15,
                      metric="euclidean", exclude_self=True)
    ic, _ = knn_numpy(np.asarray(c.obsm["X_tsne"], np.float64),
                      np.asarray(c.obsm["X_tsne"], np.float64), k=15,
                      metric="euclidean", exclude_self=True)
    overlap = np.mean([
        len(np.intersect1d(it[i], ic[i])) / 15 for i in range(600)])
    # two different-precision optimisers of a non-convex layout agree
    # on which blob a point sits in (purity above), not on the
    # arbitrary ordering WITHIN a ~120-point blob — random ordering
    # inside the right blob would give 15/120 ≈ 0.13, so 0.35 is
    # strong structural agreement without asserting bit-stability
    assert overlap > 0.35, overlap


def test_tsne_requires_knn():
    d = CellData(np.zeros((10, 4), np.float32))
    with pytest.raises(ValueError, match="neighbors.knn"):
        sct.apply("embed.tsne", d, backend="tpu")
