"""ResilientRunner — retry classification, backoff schedule, health-
checked CPU fallback, checkpointed resume, run journal.  Everything
runs on the CPU backend with injected probes/sleepers: ZERO real
sleeps, no accelerator, faults injected deterministically by
utils.chaos (the whole point — recovery paths exercised in tier-1
instead of only on a live flaky tunnel)."""

import json
import os
import random

import numpy as np
import pytest

from sctools_tpu.data.synthetic import synthetic_counts
from sctools_tpu.recipes import run_recipe, seurat_pipeline
from sctools_tpu.registry import Pipeline, register
from sctools_tpu.runner import (ResilientRunError, ResilientRunner,
                                RetryPolicy)
from sctools_tpu.utils.chaos import ChaosCrash, ChaosMonkey, Fault
from sctools_tpu.utils.failsafe import (DETERMINISTIC, FATAL, TRANSIENT,
                                        CircuitBreaker,
                                        DeterministicChildError,
                                        StepDeadlineExceeded,
                                        TransientDeviceError,
                                        classify_error)
from sctools_tpu.utils.vclock import VirtualClock

OK_PROBE = {"ok": True, "device_kind": "test", "wall_s": 0.0}
DOWN_PROBE = {"ok": False, "reason": "test-ruled-down"}


@pytest.fixture
def boom_op():
    """A transform that always raises ValueError, registered under the
    reserved ``test.`` fixture prefix and removed on teardown so the
    registry-wide gates (docs coverage, cpu/tpu parity) never see it."""

    @register("test.boom", backend="tpu")
    @register("test.boom", backend="cpu")
    def _boom(data, **kw):
        raise ValueError("test.boom: deliberate shape mismatch")

    yield "test.boom"
    registry_mod = __import__("sctools_tpu.registry",
                              fromlist=["_REGISTRY", "_DOCS"])
    registry_mod._REGISTRY.pop("test.boom", None)
    registry_mod._DOCS.pop("test.boom", None)


def _data(n=300, g=120):
    return synthetic_counts(n, g, n_clusters=3)


def _pipe(**kw):
    kw.setdefault("n_top_genes", 50)
    kw.setdefault("min_genes", 1)
    kw.setdefault("min_cells", 1)
    return seurat_pipeline(**kw)


def _runner(pipe, **kw):
    kw.setdefault("probe", lambda: dict(OK_PROBE))
    kw.setdefault("sleep", lambda s: None)
    return ResilientRunner(pipe, **kw)


def _journal(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


def _dense(X):
    if hasattr(X, "todense"):
        return np.asarray(X.todense())
    return np.asarray(X)


# ---------------------------------------------------------------- taxonomy

def test_classify_error_taxonomy():
    assert classify_error(TransientDeviceError("x")) == TRANSIENT
    assert classify_error(TimeoutError()) == TRANSIENT
    assert classify_error(ConnectionResetError()) == TRANSIENT
    # jaxlib's XlaRuntimeError is one class for every gRPC status —
    # the status name in the message is the only signal
    assert classify_error(RuntimeError("UNAVAILABLE: socket closed")) \
        == TRANSIENT
    assert classify_error(RuntimeError("DEADLINE_EXCEEDED")) == TRANSIENT
    assert classify_error(ValueError("shape mismatch")) == DETERMINISTIC
    assert classify_error(TypeError()) == DETERMINISTIC
    # type beats message: a ValueError mentioning "aborted" is still
    # a program error
    assert classify_error(ValueError("user aborted the run")) \
        == DETERMINISTIC
    # unknown errors fail fast, not retry
    assert classify_error(RuntimeError("novel weirdness")) \
        == DETERMINISTIC
    # RESOURCE_EXHAUSTED recurs at the same shapes — never blindly
    # retried; since the memory fault domain landed it is its own
    # explicit class (the runner answers with the OOM containment
    # ladder, not retry-or-fail-fast — tests/test_memory.py pins the
    # message-shape corpus)
    from sctools_tpu.utils.failsafe import RESOURCE

    assert classify_error(RuntimeError("RESOURCE_EXHAUSTED: HBM OOM")) \
        == RESOURCE
    assert classify_error(KeyboardInterrupt()) == FATAL
    assert classify_error(SystemExit(1)) == FATAL
    assert classify_error(ChaosCrash("preempted")) == FATAL


def test_retry_policy_schedule_no_jitter():
    p = RetryPolicy(base_delay_s=0.5, multiplier=2.0, max_delay_s=3.0,
                    jitter=0.0)
    rng = random.Random(0)
    assert [p.delay_s(n, rng) for n in (1, 2, 3, 4, 5)] == \
        [0.5, 1.0, 2.0, 3.0, 3.0]  # capped at max_delay_s


def test_retry_policy_jitter_seeded_and_bounded():
    p = RetryPolicy(base_delay_s=1.0, multiplier=2.0, jitter=0.5, seed=7)
    a = [p.delay_s(n, random.Random(7)) for n in (1, 1, 1)]
    assert a[0] == a[1] == a[2]  # same rng state -> same delay
    rng = random.Random(7)
    for n in (1, 2, 3):
        d = p.delay_s(n, rng)
        base = 1.0 * 2.0 ** (n - 1)
        assert 0.5 * base <= d <= 1.5 * base


# ------------------------------------------------------------ retry paths

def test_transient_retries_then_succeeds(tmp_path):
    data, pipe = _data(), _pipe()
    base = pipe.run(data, backend="cpu")
    monkey = ChaosMonkey([Fault("hvg.select", "unavailable", times=1)])
    sleeps = []
    r = _runner(pipe, checkpoint_dir=str(tmp_path), sleep=sleeps.append)
    with monkey.activate():
        out = r.run(data, backend="cpu")
    hvg = next(s for s in r.report.steps if s.name == "hvg.select")
    assert [a.status for a in hvg.attempts] == ["error", "ok"]
    assert hvg.attempts[0].classified == TRANSIENT
    assert len(sleeps) == 1  # one backoff, via the injected sleeper
    np.testing.assert_allclose(np.asarray(base.X), np.asarray(out.X),
                               atol=1e-6)


def test_backoff_schedule_pinned_against_fake_clock():
    data, pipe = _data(), _pipe()
    policy = RetryPolicy(max_attempts=4, base_delay_s=0.5,
                         multiplier=2.0, jitter=0.5, seed=42)
    monkey = ChaosMonkey(
        [Fault("normalize.log1p", "unavailable", times=3)])
    sleeps = []
    r = _runner(pipe, policy=policy, sleep=sleeps.append,
                fallback_backend=None)
    with monkey.activate():
        r.run(data, backend="cpu")
    # deterministic seeded jitter: the exact schedule is reproducible
    rng = random.Random(42)
    assert sleeps == [policy.delay_s(n, rng) for n in (1, 2, 3)]
    for n, d in enumerate(sleeps, 1):
        base = 0.5 * 2.0 ** (n - 1)
        assert 0.5 * base <= d <= 1.5 * base


def test_deterministic_error_fails_fast_no_retry(boom_op):
    data = _data()
    pipe = Pipeline([("qc.per_cell_metrics", {}), (boom_op, {}),
                     ("normalize.log1p", {})])
    sleeps = []
    r = _runner(pipe, sleep=sleeps.append)
    with pytest.raises(ValueError, match="deliberate shape mismatch"):
        r.run(data, backend="cpu")
    boom = r.report.steps[1]
    assert len(boom.attempts) == 1  # NO retry on a deterministic raise
    assert boom.attempts[0].classified == DETERMINISTIC
    assert boom.status == "failed"
    assert sleeps == []  # and no backoff was scheduled
    assert r.report.steps[2].status == "pending"


def test_validate_hook_failure_is_the_attempts_failure():
    data, pipe = _data(), _pipe()

    def validate(i, name, out):
        if name == "normalize.scale":
            raise ValueError("validator: NaN in result")

    r = _runner(pipe, validate=validate)
    with pytest.raises(ValueError, match="validator"):
        r.run(data, backend="cpu")
    scale = next(s for s in r.report.steps
                 if s.name == "normalize.scale")
    assert len(scale.attempts) == 1  # ValueError -> fail fast


def test_exhausted_budget_raises_with_report():
    data, pipe = _data(), _pipe()
    monkey = ChaosMonkey(
        [Fault("normalize.log1p", "unavailable", times=-1)])
    r = _runner(pipe, policy=RetryPolicy(max_attempts=3),
                fallback_backend=None)
    with monkey.activate():
        with pytest.raises(ResilientRunError) as ei:
            r.run(data, backend="cpu")
    assert isinstance(ei.value.__cause__, TransientDeviceError)
    report = ei.value.report
    step = next(s for s in report.steps if s.name == "normalize.log1p")
    assert len(step.attempts) == 3
    assert report.status == "failed"


# ------------------------------------------------------------- fallback

def test_unhealthy_device_degrades_to_cpu_with_loud_warning():
    data, pipe = _data(), _pipe()
    # a TPU-only outage: the fault never fires on the cpu backend
    monkey = ChaosMonkey(
        [Fault("normalize.log1p", "unavailable", times=-1,
               backend="tpu")])
    r = _runner(pipe, probe=lambda: dict(DOWN_PROBE),
                policy=RetryPolicy(max_attempts=2),
                fallback_backend="cpu")
    with monkey.activate():
        with pytest.warns(RuntimeWarning, match="DEGRADING"):
            out = r.run(data, backend="tpu")
    assert r.report.degraded
    assert r.report.backend == "cpu"
    step = next(s for s in r.report.steps
                if s.name == "normalize.log1p")
    # 2 failed tpu attempts, then a fresh budget on cpu
    assert [a.backend for a in step.attempts] == ["tpu", "tpu", "cpu"]
    assert step.status == "completed"
    assert out.X.shape[1] == 50


def test_preflight_probe_degrades_before_first_step():
    data, pipe = _data(), _pipe()
    r = _runner(pipe, probe=lambda: dict(DOWN_PROBE), preflight=True,
                fallback_backend="cpu")
    with pytest.warns(RuntimeWarning, match="preflight"):
        r.run(data, backend="tpu")
    assert r.report.degraded
    assert all(a.backend == "cpu" for s in r.report.steps
               for a in s.attempts)


# -------------------------------------------------------------- resume

def test_crash_then_resume_from_step_checkpoint(tmp_path):
    data, pipe = _data(), _pipe()
    base = pipe.run(data, backend="cpu")
    monkey = ChaosMonkey([Fault("hvg.select", "crash", times=1)])
    r1 = _runner(pipe, checkpoint_dir=str(tmp_path))
    with monkey.activate():
        with pytest.raises(ChaosCrash):
            r1.run(data, backend="cpu")
    assert r1.report.status == "aborted"

    # a NEW runner (the killed process restarted) resumes mid-pipeline
    r2 = _runner(pipe, checkpoint_dir=str(tmp_path))
    out = r2.run(data, backend="cpu", resume=True)
    hvg_i = next(i for i, s in enumerate(r2.report.steps)
                 if s.name == "hvg.select")
    assert r2.report.resumed_from == hvg_i - 1
    assert all(s.status == "resumed"
               for s in r2.report.steps[:hvg_i])
    np.testing.assert_allclose(np.asarray(base.X), np.asarray(out.X),
                               atol=1e-6)


def test_resume_invalidates_only_downstream_of_an_edit(tmp_path):
    data = _data()
    _runner(_pipe(), checkpoint_dir=str(tmp_path)).run(
        data, backend="cpu")
    # editing the HVG step invalidates it and everything after it,
    # but the shared 6-step prefix stays resumable
    r = _runner(_pipe(n_top_genes=40), checkpoint_dir=str(tmp_path))
    out = r.run(data, backend="cpu", resume=True)
    hvg_i = next(i for i, s in enumerate(r.report.steps)
                 if s.name == "hvg.select")
    assert r.report.resumed_from == hvg_i - 1
    assert out.X.shape[1] == 40

    # editing an EARLY step invalidates all downstream checkpoints
    r2 = _runner(_pipe(target_sum=2e4), checkpoint_dir=str(tmp_path))
    r2.run(data, backend="cpu", resume=True)
    lib_i = next(i for i, s in enumerate(r2.report.steps)
                 if s.name == "normalize.library_size")
    assert r2.report.resumed_from == lib_i - 1


def test_chaos_param_activates_for_the_whole_run():
    """chaos= alone (no external activate()) must inject on ordinary
    in-process steps — the runner owns the activation."""
    data, pipe = _data(), _pipe()
    monkey = ChaosMonkey([Fault("hvg.select", "unavailable", times=1)])
    r = _runner(pipe, chaos=monkey)
    r.run(data, backend="cpu")
    assert monkey.injected and monkey.injected[0]["op"] == "hvg.select"
    hvg = next(s for s in r.report.steps if s.name == "hvg.select")
    assert [a.status for a in hvg.attempts] == ["error", "ok"]


def test_corrupt_checkpoint_falls_back_to_previous(tmp_path):
    data, pipe = _data(), _pipe()
    base = _runner(pipe, checkpoint_dir=str(tmp_path)).run(
        data, backend="cpu")
    # damage the newest checkpoint in place; the intact earlier ones
    # must still be used (not discarded for a from-scratch rerun)
    newest = max(tmp_path.glob("step*.npz"), key=lambda p: p.name)
    newest.write_bytes(b"not an npz")
    r = _runner(pipe, checkpoint_dir=str(tmp_path))
    with pytest.warns(RuntimeWarning, match="unreadable"):
        out = r.run(data, backend="cpu", resume=True)
    n = len(r.report.steps)
    assert r.report.resumed_from == n - 2  # next-newest checkpoint
    assert [s.status for s in r.report.steps] == \
        ["resumed"] * (n - 1) + ["completed"]
    np.testing.assert_allclose(_dense(base.X), _dense(out.X), atol=1e-6)


def test_all_checkpoints_corrupt_restarts_from_scratch(tmp_path):
    data, pipe = _data(), _pipe()
    _runner(pipe, checkpoint_dir=str(tmp_path)).run(data, backend="cpu")
    for p in tmp_path.glob("step*.npz"):
        p.write_bytes(b"not an npz")
    r = _runner(pipe, checkpoint_dir=str(tmp_path))
    with pytest.warns(RuntimeWarning, match="unreadable"):
        out = r.run(data, backend="cpu", resume=True)
    assert r.report.resumed_from is None  # full rerun, not a crash
    assert all(s.status == "completed" for s in r.report.steps)
    assert out.X.shape[1] == 50


def test_resume_false_reruns_from_scratch(tmp_path):
    data, pipe = _data(), _pipe()
    _runner(pipe, checkpoint_dir=str(tmp_path)).run(data, backend="cpu")
    r = _runner(pipe, checkpoint_dir=str(tmp_path))
    r.run(data, backend="cpu", resume=False)
    assert r.report.resumed_from is None
    assert all(s.status == "completed" for s in r.report.steps)


def test_fully_resumed_run_returns_final_result(tmp_path):
    data, pipe = _data(), _pipe()
    first = _runner(pipe, checkpoint_dir=str(tmp_path)).run(
        data, backend="cpu")
    r = _runner(pipe, checkpoint_dir=str(tmp_path))
    again = r.run(data, backend="cpu", resume=True)
    assert r.report.resumed_from == len(r.report.steps) - 1
    assert all(not s.attempts for s in r.report.steps)  # nothing re-ran
    np.testing.assert_allclose(_dense(first.X), _dense(again.X),
                               atol=1e-6)


# ----------------------------------------------------- acceptance e2e

def test_chaos_end_to_end_recovery_identical_to_fault_free(tmp_path):
    """The acceptance scenario: a seurat run with one transient
    UNAVAILABLE (retried in-run) plus a mid-pipeline process crash
    (aborts the run), resumed by a fresh runner, completing with
    results identical to a fault-free run — every attempt journaled
    with its classified error."""
    data, pipe = _data(), _pipe()
    base = pipe.run(data, backend="cpu")

    monkey = ChaosMonkey([
        Fault("normalize.log1p", "unavailable", times=1),
        Fault("hvg.select", "crash", times=1),
    ])
    ck = str(tmp_path)
    r1 = _runner(pipe, checkpoint_dir=ck)
    with monkey.activate():
        with pytest.raises(ChaosCrash):
            r1.run(data, backend="cpu")

    r2 = _runner(pipe, checkpoint_dir=ck)
    out = r2.run(data, backend="cpu", resume=True)
    assert r2.report.status == "completed"
    np.testing.assert_allclose(np.asarray(base.X), np.asarray(out.X),
                               atol=1e-6)
    assert list(out.var_names) == list(base.var_names)

    events = _journal(os.path.join(ck, "journal.jsonl"))
    attempts = [e for e in events if e["event"] == "attempt"]
    # every error attempt carries its classification
    errors = [e for e in attempts if e["status"] == "error"]
    assert {e["classified"] for e in errors} == {TRANSIENT, FATAL}
    log1p = [e for e in errors if e["name"] == "normalize.log1p"]
    assert log1p and log1p[0]["classified"] == TRANSIENT
    crash = [e for e in errors if e["name"] == "hvg.select"]
    assert crash and crash[0]["classified"] == FATAL
    # the resumed run is journaled as such, in the same file
    assert [e["event"] for e in events].count("run_start") == 2
    assert any(e["event"] == "resume" for e in events)
    assert events[-1]["event"] == "run_completed"
    # attempts link to trace spans
    assert all(e.get("span_id", 0) > 0 for e in attempts)


def test_run_recipe_resilient_wrapper(tmp_path):
    data = _data()
    base = _pipe().run(data, backend="cpu")
    out = run_recipe(
        "seurat", data, backend="cpu", checkpoint_dir=str(tmp_path),
        runner_kw={"probe": lambda: dict(OK_PROBE),
                   "sleep": lambda s: None},
        n_top_genes=50, min_genes=1, min_cells=1)
    np.testing.assert_allclose(np.asarray(base.X), np.asarray(out.X),
                               atol=1e-6)
    assert os.path.exists(os.path.join(str(tmp_path), "journal.jsonl"))


def test_run_recipe_unknown_name():
    with pytest.raises(KeyError, match="weinreb17 is one-call only"):
        run_recipe("weinreb17", _data())


# ----------------------------------------------------- step deadlines

def test_step_deadline_wedge_retried_like_any_transient(tmp_path):
    """A wedged step (chaos advances the shared virtual clock past the
    budget) overruns its deadline, is journaled and classified
    transient, and the retry completes — zero real sleeps."""
    data, pipe = _data(), _pipe()
    base = pipe.run(data, backend="cpu")
    clock = VirtualClock()
    monkey = ChaosMonkey([Fault("hvg.select", "wedge", times=1)],
                         clock=clock, wedge_s=120.0)
    sleeps = []
    r = ResilientRunner(pipe, checkpoint_dir=str(tmp_path),
                        chaos=monkey, clock=clock, sleep=sleeps.append,
                        probe=lambda: dict(OK_PROBE),
                        step_deadline_s=60.0)
    out = r.run(data, backend="cpu")
    hvg = next(s for s in r.report.steps if s.name == "hvg.select")
    assert [a.status for a in hvg.attempts] == ["error", "ok"]
    assert hvg.attempts[0].classified == TRANSIENT
    assert "StepDeadlineExceeded" in hvg.attempts[0].error
    assert sleeps and clock.monotonic() >= 120.0  # virtual time only
    events = _journal(os.path.join(str(tmp_path), "journal.jsonl"))
    dl = [e for e in events if e["event"] == "deadline"]
    assert dl and dl[0]["name"] == "hvg.select" \
        and dl[0]["budget_s"] == 60.0
    np.testing.assert_allclose(_dense(base.X), _dense(out.X), atol=1e-6)


def test_step_deadline_exhaustion_degrades_to_fallback():
    """A step that wedges on EVERY accelerator attempt burns its
    budget on deadline overruns, then degrades to the fallback like
    any other transient failure."""
    data, pipe = _data(), _pipe()
    clock = VirtualClock()
    monkey = ChaosMonkey(
        [Fault("normalize.log1p", "wedge", times=-1, backend="tpu")],
        clock=clock, wedge_s=999.0)
    r = ResilientRunner(pipe, chaos=monkey, clock=clock,
                        probe=lambda: dict(DOWN_PROBE),
                        policy=RetryPolicy(max_attempts=2),
                        breaker=CircuitBreaker(failure_threshold=99,
                                               clock=clock),
                        step_deadline_s=60.0, fallback_backend="cpu")
    with pytest.warns(RuntimeWarning, match="DEGRADING"):
        out = r.run(data, backend="tpu")
    step = next(s for s in r.report.steps
                if s.name == "normalize.log1p")
    assert [a.backend for a in step.attempts] == ["tpu", "tpu", "cpu"]
    assert all(a.classified == TRANSIENT
               for a in step.attempts if a.status == "error")
    assert r.report.degraded
    assert out.X.shape[1] == 50


def test_isolated_deadline_caps_child_watchdog(tmp_path):
    """An isolated step inherits the REMAINING deadline budget as its
    watchdog timeout (floored, never zero/negative)."""
    data = _data(120, 60)
    pipe = Pipeline([("qc.per_cell_metrics", {}),
                     ("normalize.log1p", {})])
    clock = VirtualClock()
    seen = {}
    import sctools_tpu.runner as runner_mod

    real = runner_mod.run_isolated

    def spy(fn, *a, **kw):
        seen["timeout_s"] = kw.get("timeout_s")
        return real(fn, *a, **kw)

    r = _runner(pipe, checkpoint_dir=str(tmp_path),
                isolate={"normalize.log1p"}, clock=clock,
                step_deadline_s=45.0, isolate_timeout_s=600.0)
    orig = runner_mod.run_isolated
    runner_mod.run_isolated = spy
    try:
        r.run(data, backend="cpu")
    finally:
        runner_mod.run_isolated = orig
    # deadline (45s) < isolate_timeout_s (600s): the tighter rules
    assert seen["timeout_s"] == pytest.approx(45.0, abs=1.0)


# ------------------------------------------------------ circuit breaker

def test_breaker_open_short_circuits_retries_and_probe():
    """K transient accelerator failures inside the window trip the
    breaker; further accelerator attempts skip the remaining retries
    AND the health probe, going straight to the degrade ruling."""
    data, pipe = _data(), _pipe()
    clock = VirtualClock()
    monkey = ChaosMonkey(
        [Fault("normalize.log1p", "unavailable", times=-1,
               backend="tpu")])
    probes = []

    def probe():
        probes.append(1)
        return dict(OK_PROBE)

    r = ResilientRunner(
        pipe, chaos=monkey, clock=clock, probe=probe,
        policy=RetryPolicy(max_attempts=5),  # budget NOT exhausted
        breaker=CircuitBreaker(failure_threshold=2, window_s=300.0,
                               cooldown_s=1e6, clock=clock),
        fallback_backend="cpu")
    with pytest.warns(RuntimeWarning, match="circuit breaker OPEN"):
        out = r.run(data, backend="tpu")
    step = next(s for s in r.report.steps
                if s.name == "normalize.log1p")
    # 2 tpu failures (not 5 — the breaker cut the retry storm), then cpu
    assert [a.backend for a in step.attempts] == ["tpu", "tpu", "cpu"]
    assert probes == []  # and NO probe storm either
    assert r.report.degraded and r.report.breaker["state"] == "open"
    assert out.X.shape[1] == 50


def test_breaker_open_journaled_with_fallback_reason(tmp_path):
    data, pipe = _data(), _pipe()
    clock = VirtualClock()
    monkey = ChaosMonkey(
        [Fault("normalize.log1p", "unavailable", times=-1,
               backend="tpu")])
    r = ResilientRunner(
        pipe, checkpoint_dir=str(tmp_path), chaos=monkey, clock=clock,
        probe=lambda: dict(OK_PROBE),
        breaker=CircuitBreaker(failure_threshold=2, cooldown_s=1e6,
                               clock=clock),
        fallback_backend="cpu")
    with pytest.warns(RuntimeWarning, match="circuit breaker OPEN"):
        r.run(data, backend="tpu")
    events = _journal(os.path.join(str(tmp_path), "journal.jsonl"))
    opens = [e for e in events if e["event"] == "breaker_open"]
    assert opens and opens[0]["state"] == "open" \
        and opens[0]["failure_threshold"] == 2
    fb = [e for e in events if e["event"] == "fallback"]
    assert fb and fb[0]["reason"] == "breaker_open"
    done = [e for e in events if e["event"] == "run_completed"]
    assert done and done[0]["breaker"]["state"] == "open"


def test_breaker_half_open_probe_closes_and_undegrades():
    """After the cooldown the breaker half-opens; ONE successful probe
    closes it and the run returns to the accelerator — the full
    open → half-open → closed cycle on a virtual clock."""
    data, pipe = _data(), _pipe()
    clock = VirtualClock()
    monkey = ChaosMonkey([
        # tpu-only outage on library_size trips the breaker...
        Fault("normalize.library_size", "unavailable", times=-1,
              backend="tpu"),
        # ...and a hang on the (post-degrade, cpu) log1p advances the
        # shared clock past the breaker cooldown
        Fault("normalize.log1p", "hang", times=1),
    ], clock=clock, hang_s=100.0)
    probes = []

    def probe():
        probes.append(1)
        return dict(OK_PROBE)

    r = ResilientRunner(
        pipe, chaos=monkey, clock=clock, probe=probe,
        policy=RetryPolicy(max_attempts=4, jitter=0.0),
        breaker=CircuitBreaker(failure_threshold=2, window_s=1000.0,
                               cooldown_s=50.0, clock=clock),
        fallback_backend="cpu")
    with pytest.warns(RuntimeWarning, match="circuit breaker OPEN"):
        out = r.run(data, backend="tpu")
    by_name = {s.name: s for s in r.report.steps}
    assert [a.backend for a in by_name["normalize.library_size"].attempts] \
        == ["tpu", "tpu", "cpu"]
    assert [a.backend for a in by_name["normalize.log1p"].attempts] \
        == ["cpu"]
    # cooldown elapsed during log1p's hang -> half-open -> probe ok ->
    # breaker closed, run un-degraded, back on the accelerator
    assert [a.backend for a in by_name["hvg.select"].attempts] == ["tpu"]
    assert probes == [1]  # exactly one half-open probe
    assert not r.report.degraded
    assert r.report.breaker["state"] == "closed"
    assert out.X.shape[1] == 50


def test_breaker_half_open_failed_probe_reopens():
    data, pipe = _data(), _pipe()
    clock = VirtualClock()
    monkey = ChaosMonkey([
        Fault("normalize.library_size", "unavailable", times=-1,
              backend="tpu"),
        Fault("normalize.log1p", "hang", times=1),
    ], clock=clock, hang_s=100.0)
    probes = []

    def probe():
        probes.append(1)
        return dict(DOWN_PROBE)

    r = ResilientRunner(
        pipe, chaos=monkey, clock=clock, probe=probe,
        policy=RetryPolicy(max_attempts=4, jitter=0.0),
        breaker=CircuitBreaker(failure_threshold=2, window_s=1000.0,
                               cooldown_s=50.0, clock=clock),
        fallback_backend="cpu")
    with pytest.warns(RuntimeWarning, match="circuit breaker OPEN"):
        r.run(data, backend="tpu")
    by_name = {s.name: s for s in r.report.steps}
    # the failed half-open probe re-opened the breaker: still degraded
    assert [a.backend for a in by_name["hvg.select"].attempts] == ["cpu"]
    assert r.report.degraded
    assert r.report.breaker["state"] in ("open", "half_open")
    assert r.report.breaker["opened_count"] == 2


# ------------------------------------------- checkpoint quarantine

def test_corrupt_checkpoint_quarantined_on_resume(tmp_path):
    """chaos corrupt_checkpoint damages the final step's file ON DISK
    after a good save; the next resume's digest verify catches it,
    quarantines the file (never deletes), journals the reason, and
    falls back to the previous intact checkpoint."""
    data, pipe = _data(), _pipe()
    base = pipe.run(data, backend="cpu")
    ck = str(tmp_path)
    monkey = ChaosMonkey(
        [Fault("normalize.scale", "corrupt_checkpoint", times=1)])
    r1 = _runner(pipe, checkpoint_dir=ck, chaos=monkey)
    r1.run(data, backend="cpu")
    assert r1.report.status == "completed"  # the WRITING run is fine
    assert any(f["mode"] == "corrupt_checkpoint"
               for f in monkey.injected)

    r2 = _runner(pipe, checkpoint_dir=ck)
    with pytest.warns(RuntimeWarning, match="QUARANTINED"):
        out = r2.run(data, backend="cpu", resume=True)
    n = len(r2.report.steps)
    assert r2.report.resumed_from == n - 2
    assert r2.report.steps[-1].status == "completed"  # re-ran
    np.testing.assert_allclose(_dense(base.X), _dense(out.X), atol=1e-6)
    qdir = tmp_path / "quarantine"
    qfiles = sorted(os.listdir(qdir))
    assert len([f for f in qfiles if f.endswith(".npz")]) == 1
    assert any(f.endswith(".reason.json") for f in qfiles)
    events = _journal(os.path.join(ck, "journal.jsonl"))
    quar = [e for e in events if e["event"] == "quarantine"]
    assert quar and quar[0]["step"] == n - 1
    assert "digest mismatch" in quar[0]["reason"] \
        or "unreadable" in quar[0]["reason"]
    # quarantine precedes the resume record, in the same journal
    names = [e["event"] for e in events]
    assert names.index("quarantine") < names.index("resume")


def test_resume_with_different_data_recomputes(tmp_path):
    """The PR-1 latent bug: resume=True with DIFFERENT data and the
    same checkpoint_dir silently returned the previous run's result.
    The input-content digest in the fingerprint makes the stale
    checkpoints unmatchable."""
    a = _data()
    b = synthetic_counts(300, 120, n_clusters=3, seed=7)
    pipe = _pipe()
    r1 = _runner(pipe, checkpoint_dir=str(tmp_path))
    out_a = r1.run(a, backend="cpu")
    r2 = _runner(pipe, checkpoint_dir=str(tmp_path))
    out_b = r2.run(b, backend="cpu", resume=True)
    assert r2.report.resumed_from is None  # nothing matched: recompute
    base_b = pipe.run(b, backend="cpu")
    np.testing.assert_allclose(_dense(base_b.X), _dense(out_b.X),
                               atol=1e-6)
    events = _journal(os.path.join(str(tmp_path), "journal.jsonl"))
    starts = [e for e in events if e["event"] == "run_start"]
    assert starts[0]["input_digest"] != starts[1]["input_digest"]
    # same data still resumes (and journals that the passed argument
    # is superseded by the checkpoint)
    r3 = _runner(pipe, checkpoint_dir=str(tmp_path))
    out_b2 = r3.run(b, backend="cpu", resume=True)
    assert r3.report.resumed_from == len(r3.report.steps) - 1
    np.testing.assert_allclose(_dense(out_b.X), _dense(out_b2.X),
                               atol=1e-6)
    events = _journal(os.path.join(str(tmp_path), "journal.jsonl"))
    res = [e for e in events if e["event"] == "resume"]
    assert res and "supersedes" in res[-1]["note"]


# ---------------------------------------------------------- containment

def test_isolated_step_contains_real_process_death(tmp_path):
    """chaos 'kill' (os._exit(9)) inside a contained child: the child
    dies for real, the runner's process survives, classifies the death
    transient, and the retry — with the chaos call-counter advanced
    across the process boundary — completes the step."""
    data, pipe = _data(150, 80), Pipeline([
        ("qc.per_cell_metrics", {}),
        ("normalize.library_size", {"target_sum": 1e4}),
        ("normalize.log1p", {}),
    ])
    base = pipe.run(data, backend="cpu")
    monkey = ChaosMonkey(
        [Fault("normalize.log1p", "kill", times=1)])
    r = _runner(pipe, checkpoint_dir=str(tmp_path),
                isolate={"normalize.log1p"}, chaos=monkey,
                isolate_timeout_s=240.0, isolate_stall_s=120.0)
    with monkey.activate():
        out = r.run(data, backend="cpu")
    step = next(s for s in r.report.steps
                if s.name == "normalize.log1p")
    assert step.isolated
    assert [a.status for a in step.attempts] == ["error", "ok"]
    assert step.attempts[0].classified == TRANSIENT
    np.testing.assert_allclose(_dense(base.X), _dense(out.X), atol=1e-6)


def test_isolated_deterministic_child_error_fails_fast(tmp_path):
    """The ROADMAP open item: a deterministic error inside an isolated
    child (here a TypeError from a bogus parameter) must FAIL FAST —
    classified from the stderr tail, one attempt, no retry burn, no
    probe, no degrade-to-cpu of a healthy device."""
    data = _data(150, 80)
    pipe = Pipeline([
        ("qc.per_cell_metrics", {}),
        ("normalize.log1p", {"bogus_param": 1}),
    ])
    probes = []

    def probe():
        probes.append(1)
        return dict(OK_PROBE)

    r = _runner(pipe, checkpoint_dir=str(tmp_path), probe=probe,
                isolate={"normalize.log1p"},
                isolate_timeout_s=240.0, isolate_stall_s=120.0)
    with pytest.raises(DeterministicChildError, match="TypeError"):
        r.run(data, backend="cpu")
    step = r.report.steps[1]
    assert step.isolated
    assert len(step.attempts) == 1  # NO retry on a deterministic raise
    assert step.attempts[0].classified == DETERMINISTIC
    assert step.status == "failed"
    assert probes == []  # and no probe storm
    assert not r.report.degraded


# ----------------------------------------- acceptance e2e (ISSUE 3)

def test_run_integrity_acceptance_wedge_breaker_corrupt_resume(tmp_path):
    """The ISSUE-3 acceptance scenario, all on a virtual clock with
    zero real sleeps: one step WEDGES past its per-step deadline
    (retried), repeated accelerator failures trip the circuit BREAKER
    open (short-circuit degrade, no probe), the latest checkpoint is
    CORRUPTED on disk, and a fresh resume still completes end-to-end —
    with the journal recording deadline → breaker-open → quarantine →
    resume, in order."""
    data, pipe = _data(), _pipe()
    ck = str(tmp_path)
    clock = VirtualClock()
    monkey = ChaosMonkey([
        Fault("qc.per_cell_metrics", "wedge", times=1),
        Fault("normalize.library_size", "unavailable", times=-1,
              backend="tpu"),
        Fault("normalize.scale", "corrupt_checkpoint", times=1),
    ], clock=clock, wedge_s=120.0)
    probes = []

    def probe():
        probes.append(1)
        return dict(OK_PROBE)

    r1 = ResilientRunner(
        pipe, checkpoint_dir=ck, chaos=monkey, clock=clock,
        probe=probe, step_deadline_s=60.0,
        policy=RetryPolicy(max_attempts=4, jitter=0.0),
        breaker=CircuitBreaker(failure_threshold=2, window_s=300.0,
                               cooldown_s=1e6, clock=clock),
        fallback_backend="cpu")
    with pytest.warns(RuntimeWarning, match="circuit breaker OPEN"):
        out1 = r1.run(data, backend="tpu")
    assert r1.report.status == "completed"
    assert r1.report.degraded  # breaker-driven, cooldown never elapsed
    assert probes == []        # straight to the ruling, no probe storm
    assert {f["mode"] for f in monkey.injected} == \
        {"wedge", "unavailable", "corrupt_checkpoint"}

    # a NEW runner (fresh process after a crash) resumes: the corrupt
    # final checkpoint is quarantined, the intact previous one is used
    probes2 = []

    def probe2():
        probes2.append(1)
        return dict(OK_PROBE)

    r2 = ResilientRunner(pipe, checkpoint_dir=ck, probe=probe2,
                         clock=VirtualClock())
    with pytest.warns(RuntimeWarning, match="QUARANTINED"):
        out2 = r2.run(data, backend="tpu", resume=True)
    assert r2.report.status == "completed"
    n = len(r2.report.steps)
    assert r2.report.resumed_from == n - 2
    assert probes2 == []
    assert out2.X.shape[1] == 50
    assert not np.isnan(np.asarray(_dense(out2.X))).any()
    assert os.path.isdir(os.path.join(ck, "quarantine"))

    events = _journal(os.path.join(ck, "journal.jsonl"))
    names = [e["event"] for e in events]
    # the acceptance ordering contract
    assert names.index("deadline") < names.index("breaker_open") \
        < names.index("quarantine") < names.index("resume")
    # and the journal ties each ruling to its step
    dl = next(e for e in events if e["event"] == "deadline")
    assert dl["name"] == "qc.per_cell_metrics"
    fb = next(e for e in events if e["event"] == "fallback")
    assert fb["reason"] == "breaker_open"
    assert names[-1] == "run_completed"


# --------------------------------------- telemetry artifacts (ISSUE 4)

def test_isolated_child_spans_grafted_into_parent_trace(tmp_path):
    """Regression for the lost-child-spans bug: isolated steps used to
    produce NO spans in the parent — the child's tree now rides the
    run_isolated handoff and is grafted under the parent's step span,
    with fresh parent-side ids."""
    from sctools_tpu.utils import trace

    data = _data(120, 60)
    pipe = Pipeline([("qc.per_cell_metrics", {}),
                     ("normalize.log1p", {})])
    r = _runner(pipe, checkpoint_dir=str(tmp_path),
                isolate={"normalize.log1p"},
                isolate_timeout_s=240.0, isolate_stall_s=120.0)
    r.run(data, backend="cpu")
    step_span = next(s for s in r._spans
                     if s.name == "runner:normalize.log1p")
    kids = [c.name for c in step_span.children]
    assert kids == ["isolated:normalize.log1p"]
    child_root = step_span.children[0]
    assert [c.name for c in child_root.children] == \
        ["load", "normalize.log1p", "save"]
    # fresh ids from THIS process's counter; the child's own id is
    # kept for cross-reference
    ids = [s.id for _, s in child_root.flat()]
    assert len(set(ids)) == len(ids) and all(i > 0 for i in ids)
    assert child_root.meta.get("child_span_id")
    assert child_root.meta.get("backend") == "cpu"
    # and the graft survives into the exported trace.json
    doc = json.load(open(os.path.join(str(tmp_path), "trace.json")))
    names = [e["name"] for e in doc["traceEvents"]
             if e.get("ph") == "X"]
    assert "isolated:normalize.log1p" in names
    assert "load" in names and "save" in names
    trace.reset()


def test_metrics_counters_mirror_journal_and_artifacts_written(tmp_path):
    """The runner's recovery counters agree with the journal, the
    snapshot lands in metrics.json, the spans in trace.json, and the
    journal's attempt span_ids all resolve in the trace — the
    join-key property PR 1 promised."""
    from sctools_tpu.utils.telemetry import MetricsRegistry
    from sctools_tpu.utils.vclock import VirtualClock

    data, pipe = _data(150, 80), _pipe()
    m = MetricsRegistry(clock=VirtualClock())
    monkey = ChaosMonkey([Fault("hvg.select", "unavailable", times=1)])
    r = _runner(pipe, checkpoint_dir=str(tmp_path), chaos=monkey,
                metrics=m)
    r.run(data, backend="cpu")

    snap = m.snapshot()
    c = snap["counters"]
    assert c["runner.retries"] == 1
    assert c["runner.attempts{backend=cpu,status=error}"] == 1
    assert c["runner.attempts{backend=cpu,status=ok}"] == len(pipe.steps)
    assert c["runner.checkpoint_writes"] == len(pipe.steps)
    assert c["runner.checkpoint_bytes"] > 0
    # auto-instrumented op metrics, installed by the runner itself
    assert c["op.calls{backend=cpu,op=hvg.select}"] == 2
    assert c["op.errors{backend=cpu,op=hvg.select}"] == 1
    assert snap["histograms"]["runner.step_wall_s{status=ok}"][
        "count"] == len(pipe.steps)

    mdoc = json.load(open(os.path.join(str(tmp_path), "metrics.json")))
    assert mdoc["metrics"]["counters"] == c
    tdoc = json.load(open(os.path.join(str(tmp_path), "trace.json")))
    trace_ids = {e["args"]["span_id"]
                 for e in tdoc["traceEvents"] if e.get("ph") == "X"}
    events = _journal(os.path.join(str(tmp_path), "journal.jsonl"))
    attempt_ids = {e["span_id"] for e in events
                   if e["event"] == "attempt"}
    assert attempt_ids and attempt_ids <= trace_ids
    # artifact events are journaled, and run_completed stays LAST
    names = [e["event"] for e in events]
    assert "metrics_written" in names and "trace_exported" in names
    assert names[-1] == "run_completed"


def test_degraded_runs_label_ops_degraded(tmp_path):
    from sctools_tpu.utils.telemetry import MetricsRegistry
    from sctools_tpu.utils.vclock import VirtualClock

    data, pipe = _data(150, 80), _pipe()
    m = MetricsRegistry(clock=VirtualClock())
    monkey = ChaosMonkey(
        [Fault("normalize.log1p", "unavailable", times=-1,
               backend="tpu")])
    r = _runner(pipe, probe=lambda: dict(DOWN_PROBE),
                policy=RetryPolicy(max_attempts=2),
                fallback_backend="cpu", metrics=m)
    with monkey.activate():
        with pytest.warns(RuntimeWarning, match="DEGRADING"):
            r.run(data, backend="tpu")
    c = m.snapshot()["counters"]
    assert c["runner.degrades{reason=probe}"] == 1
    # ops before the ruling are labelled tpu, after it degraded
    assert c["op.calls{backend=tpu,op=normalize.log1p}"] == 2
    assert c["op.calls{backend=degraded,op=normalize.log1p}"] == 1
    assert c["op.calls{backend=degraded,op=hvg.select}"] == 1
    # the override is scoped to this runner's instrumentor and
    # cleared at run end
    assert r._inst.backend_override is None


def test_failed_run_still_writes_artifacts(tmp_path):
    from sctools_tpu.utils.telemetry import MetricsRegistry
    from sctools_tpu.utils.vclock import VirtualClock

    data, pipe = _data(150, 80), _pipe()
    m = MetricsRegistry(clock=VirtualClock())
    monkey = ChaosMonkey(
        [Fault("normalize.log1p", "unavailable", times=-1)])
    r = _runner(pipe, checkpoint_dir=str(tmp_path), chaos=monkey,
                policy=RetryPolicy(max_attempts=2),
                fallback_backend=None, metrics=m)
    with pytest.raises(ResilientRunError):
        r.run(data, backend="cpu")
    assert os.path.exists(os.path.join(str(tmp_path), "metrics.json"))
    assert os.path.exists(os.path.join(str(tmp_path), "trace.json"))
    assert m.snapshot()["counters"]["runner.retries"] == 1
    # the journal's final line stays the run VERDICT — artifacts are
    # written for failed runs but never journaled after the verdict
    events = _journal(os.path.join(str(tmp_path), "journal.jsonl"))
    assert events[-1]["event"] == "run_failed"


# ------------------------------------------- shared breaker (registry)

def test_default_breaker_shared_across_sequential_runs():
    """PR-9 satellite regression: a runner constructed WITHOUT
    breaker= resolves the run's backend signature in the process-
    shared BreakerRegistry — two sequential runs share trip state
    (run 2's first failure trips the breaker run 1 fed), where the
    old per-run default would have made run 2 start from zero."""
    from sctools_tpu.utils.failsafe import default_breaker_registry
    from sctools_tpu.utils.vclock import VirtualClock

    clock = VirtualClock()
    data, pipe = _data(), _pipe()
    monkey = ChaosMonkey(
        [Fault("normalize.log1p", "unavailable", times=-1,
               backend="tpu")], clock=clock)
    # pre-seed the shared tpu breaker with this test's clock +
    # threshold (first-creation kwargs win; the conftest fixture
    # resets the registry after every test)
    shared = default_breaker_registry().get(
        "tpu", clock=clock, failure_threshold=3, window_s=1e6,
        cooldown_s=1e6)

    def run_once():
        r = _runner(pipe, probe=lambda: dict(DOWN_PROBE),
                    policy=RetryPolicy(max_attempts=2, jitter=0.0),
                    clock=clock)
        with monkey.activate():
            with pytest.warns(RuntimeWarning):
                r.run(data, backend="tpu")
        return r

    r1 = run_once()
    assert r1.breaker is shared          # resolved from the registry
    assert r1.breaker.signature == "tpu"
    # run 1: 2 transient tpu failures (budget spent), probe DOWN ->
    # degraded by the PROBE, breaker fed but not yet tripped
    assert r1.report.degraded
    assert shared.state == CircuitBreaker.CLOSED
    assert shared.snapshot()["failures_in_window"] == 2

    r2 = run_once()
    assert r2.breaker is shared          # SAME breaker, second runner
    # run 2's FIRST failure is the shared window's third: the breaker
    # trips and rules the degrade — no fresh retry storm
    assert shared.state == CircuitBreaker.OPEN
    assert shared.opened_count == 1
    log1p = next(s for s in r2.report.steps
                 if s.name == "normalize.log1p")
    assert len([a for a in log1p.attempts
                if a.backend == "tpu"]) == 1
    assert r2.report.breaker["signature"] == "tpu"

    # a runner with an EXPLICIT breaker keeps run-local isolation
    r3 = _runner(pipe, breaker=CircuitBreaker(clock=clock),
                 clock=clock)
    r3.run(data, backend="cpu")
    assert r3.breaker is not shared and r3.breaker.signature is None


def test_open_shared_breaker_short_circuits_fresh_run():
    """A run that STARTS with the shared breaker already open never
    attempts the accelerator: the pre-attempt gate rules the degrade
    (journalled fallback reason=breaker_open, short_circuit flag,
    registry signature) before the first attempt."""
    import json as _json
    import tempfile

    from sctools_tpu.utils.failsafe import default_breaker_registry
    from sctools_tpu.utils.vclock import VirtualClock

    clock = VirtualClock()
    data, pipe = _data(), _pipe()
    shared = default_breaker_registry().get(
        "tpu", clock=clock, failure_threshold=1, cooldown_s=1e6)
    shared.record_failure()              # trip it before any run
    assert shared.state == CircuitBreaker.OPEN

    jdir = tempfile.mkdtemp(prefix="sct_breaker_")
    r = _runner(pipe, checkpoint_dir=jdir, clock=clock)
    with pytest.warns(RuntimeWarning, match="circuit breaker OPEN"):
        out = r.run(data, backend="tpu")
    assert out is not None
    assert r.report.degraded
    # ZERO tpu attempts anywhere — every step short-circuited to cpu
    assert all(a.backend == "cpu" for s in r.report.steps
               for a in s.attempts)
    with open(os.path.join(jdir, "journal.jsonl")) as f:
        events = [_json.loads(line) for line in f]
    fb = [e for e in events if e["event"] == "fallback"]
    assert fb and fb[0]["reason"] == "breaker_open"
    assert fb[0]["short_circuit"] is True
    assert fb[0]["signature"] == "tpu"
    assert shared.opened_count == 1      # the run never re-tripped it


def test_degraded_run_rejoins_when_shared_breaker_closes_elsewhere():
    """Pool un-degrade contract: a run degraded by the shared breaker
    REJOINS the accelerator as soon as another sharer's probe closes
    it — it does not ride the cpu fallback to completion."""
    from sctools_tpu.utils.vclock import VirtualClock

    clock = VirtualClock()
    data, pipe = _data(), _pipe()
    breaker = CircuitBreaker(failure_threshold=1, window_s=1e6,
                             cooldown_s=1e6, clock=clock)
    monkey = ChaosMonkey(
        [Fault("normalize.library_size", "unavailable", times=1,
               backend="tpu")], clock=clock)
    n_steps = len(pipe.steps)
    lib_idx = next(i for i, t in enumerate(pipe.steps)
                   if t.name == "normalize.library_size")
    closed_at = lib_idx + 1
    assert closed_at < n_steps - 1   # steps remain to rejoin on

    def close_later(i, name, out):
        # stand-in for ANOTHER run's successful half-open probe
        if i == closed_at:
            breaker.record_success()

    r = _runner(pipe, breaker=breaker, clock=clock,
                validate=close_later)
    with monkey.activate():
        with pytest.warns(RuntimeWarning, match="circuit breaker OPEN"):
            out = r.run(data, backend="tpu")
    assert out is not None
    # degraded at log1p (threshold 1), back on tpu after closed_at
    assert not r.report.degraded     # rejoined before the run ended
    backends = [s.attempts[-1].backend for s in r.report.steps]
    assert backends[closed_at] == "cpu"       # still degraded there
    assert all(b == "tpu" for b in backends[closed_at + 1:])
    assert len(backends) == n_steps


def test_backend_signature_prefers_accelerator_in_mixed_pipeline():
    """A mixed cpu+tpu pipeline keys the shared breaker by the
    ACCELERATOR backend (the one whose failures feed it), not by
    whatever backend step 0 happens to bind."""
    from sctools_tpu.registry import Pipeline, Transform
    from sctools_tpu.runner import run_backend_signature

    mixed = Pipeline([Transform("normalize.log1p", backend="cpu"),
                      Transform("normalize.scale", backend="tpu")])
    assert run_backend_signature(mixed, None, "cpu") == "tpu"
    # run-level override always wins
    assert run_backend_signature(mixed, "tpu", "cpu") == "tpu"
    # an all-fallback pipeline falls back to step 0's backend
    all_cpu = Pipeline([Transform("normalize.log1p", backend="cpu")])
    assert run_backend_signature(all_cpu, None, "cpu") == "cpu"
    # no fallback configured: first step wins (legacy behavior)
    assert run_backend_signature(mixed, None, None) == "cpu"
