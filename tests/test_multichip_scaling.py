"""Multi-chip kNN scaling properties, asserted from the COMPILED
program rather than wall-clock (8 virtual devices share one host core,
so timings measure nothing about ICI — the collective structure and
per-device memory footprint are what distinguish the strategies).

Reference parity: BASELINE.json configs[4] — "multi-chip kNN …
ICI all-gather"; the ring strategy is the memory-scalable variant
(constant per-device working set vs all_gather's O(N·d))."""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sctools_tpu.config import config, round_up
from sctools_tpu.data.synthetic import gaussian_blobs
from sctools_tpu.parallel import make_mesh
from sctools_tpu.parallel.knn_multichip import _knn_multichip_jit
from sctools_tpu.parallel.mesh import CELL_AXIS
from jax.sharding import NamedSharding, PartitionSpec as P


def _lower(strategy, n=16384, d=32, k=15):
    mesh = make_mesh(8)
    block = round_up(n // 8, 8)
    pts, _ = gaussian_blobs(n, d, 4, seed=0)
    sharding = NamedSharding(mesh, P(CELL_AXIS, None))
    pts = jax.device_put(jnp.asarray(pts), sharding)
    return _knn_multichip_jit.lower(
        pts, k=k, metric="cosine", n_valid=n, block=block,
        exclude_self=False, strategy=strategy, mesh=mesh,
        mm_dtype="float32").compile()


@pytest.fixture(scope="module")
def compiled():
    return {s: _lower(s) for s in ("ring", "all_gather")}


def test_ring_uses_ppermute_not_allgather(compiled):
    hlo = compiled["ring"].as_text()
    assert "collective-permute" in hlo
    # the ring must never materialise the full gathered candidate set
    assert "all-gather" not in hlo


def test_allgather_uses_allgather(compiled):
    hlo = compiled["all_gather"].as_text()
    assert "all-gather" in hlo


def _largest_candidate_rows(hlo: str, d: int) -> int:
    """Largest row count of any f32 tensor of ANY rank whose minor dim
    is the embedding width ``d`` — i.e. the biggest candidate/point
    buffer the compiled program ever materialises.  (A rank-2-only
    regex misses the rank-3 ``(P, block, d)`` form all-gather lowers
    to on some jax versions — the round-2 advisor flagged exactly
    that brittleness.)"""
    best = 0
    for m in re.finditer(r"f32\[([0-9,]+)\]", hlo):
        dims = [int(x) for x in m.group(1).split(",")]
        if len(dims) >= 2 and dims[-1] == d:
            rows = 1
            for x in dims[:-1]:
                rows *= x
            best = max(best, rows)
    return best


def test_ring_working_set_stays_sharded(compiled):
    n, d = 16384, 32
    ring_rows = _largest_candidate_rows(compiled["ring"].as_text(), d)
    ag_rows = _largest_candidate_rows(compiled["all_gather"].as_text(), d)
    # all_gather materialises every point on every device; the ring
    # keeps at most a few blocks (shard + in-flight neighbour) resident
    assert ag_rows >= n
    assert 0 < ring_rows <= n // 8 * 3, (ring_rows, ag_rows)


# Note: compiled.memory_analysis() is NOT asserted here — on the
# virtual CPU mesh it reports whole-process totals (all 8 "devices"
# share one host executable), where the ring's unrolled scan state
# looks bigger than the all_gather buffer.  The per-device working-set
# claim is what the f32-shape scan above checks.
