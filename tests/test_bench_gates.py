"""Roofline plausibility gates in bench.py (VERDICT r4 Next #2).

The round-4 lying-barrier incident published dispatch-only timings as
real for three rounds.  These tests pin the defense: a wall-clock that
beats the chip's physical roofline must flag ``implausible``, and the
two concrete round-4 garbage numbers (config1's 1.2 ms, the kernel's
"MFU 20") must both trip the gate.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))

from bench import _HBM_BW, _PEAK_BF16, roofline_gate  # noqa: E402


def test_fake_fast_timing_flags():
    # a pure-dispatch wall (tens of µs) on the 68k QC working set
    # (ELL 68579 x 512, f32 values + i32 col ids) is under the HBM
    # bound (~0.3 ms on v5e) and must flag
    qc_bytes = 68579 * 512 * 8
    g = roofline_gate(50e-6, bytes_moved=qc_bytes, kind="TPU v5 lite")
    assert g["implausible"] is True
    assert g["roofline_s"] > 50e-6


def test_mfu_20x_kernel_timing_flags():
    # r4 artifact: exact kNN at 131072^2 x 50 timed at "MFU 20.25"
    flops = 2.0 * 131072 * 131072 * 50
    wall_at_mfu20 = flops / (20.25 * _PEAK_BF16["TPU v5 lite"])
    g = roofline_gate(wall_at_mfu20, flops=flops, kind="TPU v5 lite")
    assert g["implausible"] is True


def test_sane_timing_passes():
    qc_bytes = 68579 * 512 * 4
    bound = qc_bytes / _HBM_BW["TPU v5 lite"]
    g = roofline_gate(10 * bound, bytes_moved=qc_bytes,
                      kind="TPU v5 lite")
    assert "implausible" not in g
    assert g["roofline_s"] > 0
    # at exactly the bound: physically possible, must not flag
    g2 = roofline_gate(bound, bytes_moved=qc_bytes, kind="TPU v5 lite")
    assert "implausible" not in g2


def test_unknown_kind_gives_no_verdict():
    assert roofline_gate(1e-9, flops=1e15, kind="cpu") == {}
    assert roofline_gate(1e-9, flops=1e15, kind=None) == {}
    # no work model -> no verdict either
    assert roofline_gate(1e-9, kind="TPU v5 lite") == {}


def test_fusion_stage_speedup_and_cache_gate():
    """The plan-layer acceptance gate: bench's ``fusion`` stage must
    show fused execution >= 1.5x the unfused step-by-step wall on the
    synthetic configs[3]-shaped chain (CPU backend — measurable in
    CI), with zero retraces after the first compile and results equal
    to float tolerance.  One re-measure is allowed before failing:
    this box has 2 cores and CI neighbours."""
    import jax

    from bench import run_fusion

    det = run_fusion(jax)
    if det["speedup_vs_unfused"] < 1.5:  # pragma: no cover - noisy box
        det = run_fusion(jax)
    assert det["speedup_vs_unfused"] >= 1.5, det
    # scale's per-gene reductions may legally regroup by ulps inside
    # the fused program (same tolerance model as test_plan.py) — the
    # gate is "identical results", not "identical instruction order"
    assert det["fused_max_abs_err"] <= 1e-4, det
    # steady-state reps after the first compile are all cache hits
    assert det["plan_counters"]["plan.cache_misses"] == 1.0, det
    assert det["plan_counters"]["plan.cache_hits"] == float(det["reps"])
    # the double-buffered stream actually overlapped producer work
    assert det["stream_overlap_s"] > 0.0, det
    assert 0.0 <= det["overlap_efficiency"] <= 1.0


def test_mesh_stage_speedup_recall_and_cache_gate():
    """The sharded-plan acceptance gate: bench's ``mesh`` stage must
    show the mesh-sharded fused plan beating the per-chip dispatch
    loop on the same host mesh, with kNN recall vs a single-device
    exact search >= 0.999 (the MULTICHIP gate) and zero retraces
    after the first compile.  One re-measure before failing: this box
    has 2 cores and CI neighbours."""
    import jax

    from tools.bench_mesh import run_mesh_bench, v5e8_projection

    det = run_mesh_bench(jax, n_cells=1024, n_genes=256, reps=3)
    if det["speedup_vs_dispatch"] < 1.1:  # pragma: no cover - noisy box
        det = run_mesh_bench(jax, n_cells=1024, n_genes=256, reps=3)
    assert det["speedup_vs_dispatch"] > 1.0, det
    assert det["knn_recall_vs_single"] >= 0.999, det
    assert det["n_devices"] == 8
    # steady-state reps after the first compile are all cache hits,
    # and both sharded stage kinds ran every rep (warm + reps)
    assert det["plan_counters"]["plan.cache_misses"] == 1.0, det
    assert det["plan_counters"]["plan.cache_hits"] == float(det["reps"])
    assert det["plan_counters"]["plan.sharded_stages"] == \
        2.0 * (det["reps"] + 1)
    proj = det["v5e8_projection_10M"]
    assert proj["knn_compute_s_per_chip"] > 0
    # a measured MFU anchors the projection; garbage values don't
    assert v5e8_projection(0.55)["mfu_source"].startswith("measured")
    # an out-of-range "measured" value is neither used NOR claimed
    assert v5e8_projection(7.0)["mfu_anchor"] == 0.40
    assert v5e8_projection(7.0)["mfu_source"].startswith("assumed")


def test_flops_and_bytes_take_max():
    # compute-bound case: flops bound dominates the byte bound
    g = roofline_gate(1.0, flops=1e15, bytes_moved=1.0,
                      kind="TPU v5 lite")
    assert g["roofline_s"] > 1.0  # 1e15 / 197e12 ≈ 5.1 s
    assert g["implausible"] is True
