"""Roofline plausibility gates in bench.py (VERDICT r4 Next #2).

The round-4 lying-barrier incident published dispatch-only timings as
real for three rounds.  These tests pin the defense: a wall-clock that
beats the chip's physical roofline must flag ``implausible``, and the
two concrete round-4 garbage numbers (config1's 1.2 ms, the kernel's
"MFU 20") must both trip the gate.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))

from bench import _HBM_BW, _PEAK_BF16, roofline_gate  # noqa: E402


def test_fake_fast_timing_flags():
    # a pure-dispatch wall (tens of µs) on the 68k QC working set
    # (ELL 68579 x 512, f32 values + i32 col ids) is under the HBM
    # bound (~0.3 ms on v5e) and must flag
    qc_bytes = 68579 * 512 * 8
    g = roofline_gate(50e-6, bytes_moved=qc_bytes, kind="TPU v5 lite")
    assert g["implausible"] is True
    assert g["roofline_s"] > 50e-6


def test_mfu_20x_kernel_timing_flags():
    # r4 artifact: exact kNN at 131072^2 x 50 timed at "MFU 20.25"
    flops = 2.0 * 131072 * 131072 * 50
    wall_at_mfu20 = flops / (20.25 * _PEAK_BF16["TPU v5 lite"])
    g = roofline_gate(wall_at_mfu20, flops=flops, kind="TPU v5 lite")
    assert g["implausible"] is True


def test_sane_timing_passes():
    qc_bytes = 68579 * 512 * 4
    bound = qc_bytes / _HBM_BW["TPU v5 lite"]
    g = roofline_gate(10 * bound, bytes_moved=qc_bytes,
                      kind="TPU v5 lite")
    assert "implausible" not in g
    assert g["roofline_s"] > 0
    # at exactly the bound: physically possible, must not flag
    g2 = roofline_gate(bound, bytes_moved=qc_bytes, kind="TPU v5 lite")
    assert "implausible" not in g2


def test_unknown_kind_gives_no_verdict():
    assert roofline_gate(1e-9, flops=1e15, kind="cpu") == {}
    assert roofline_gate(1e-9, flops=1e15, kind=None) == {}
    # no work model -> no verdict either
    assert roofline_gate(1e-9, kind="TPU v5 lite") == {}


def test_flops_and_bytes_take_max():
    # compute-bound case: flops bound dominates the byte bound
    g = roofline_gate(1.0, flops=1e15, bytes_moved=1.0,
                      kind="TPU v5 lite")
    assert g["roofline_s"] > 1.0  # 1e15 / 197e12 ≈ 5.1 s
    assert g["implausible"] is True
