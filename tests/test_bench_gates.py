"""Roofline plausibility gates in bench.py (VERDICT r4 Next #2).

The round-4 lying-barrier incident published dispatch-only timings as
real for three rounds.  These tests pin the defense: a wall-clock that
beats the chip's physical roofline must flag ``implausible``, and the
two concrete round-4 garbage numbers (config1's 1.2 ms, the kernel's
"MFU 20") must both trip the gate.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))

from bench import _HBM_BW, _PEAK_BF16, roofline_gate  # noqa: E402


def test_fake_fast_timing_flags():
    # a pure-dispatch wall (tens of µs) on the 68k QC working set
    # (ELL 68579 x 512, f32 values + i32 col ids) is under the HBM
    # bound (~0.3 ms on v5e) and must flag
    qc_bytes = 68579 * 512 * 8
    g = roofline_gate(50e-6, bytes_moved=qc_bytes, kind="TPU v5 lite")
    assert g["implausible"] is True
    assert g["roofline_s"] > 50e-6


def test_mfu_20x_kernel_timing_flags():
    # r4 artifact: exact kNN at 131072^2 x 50 timed at "MFU 20.25"
    flops = 2.0 * 131072 * 131072 * 50
    wall_at_mfu20 = flops / (20.25 * _PEAK_BF16["TPU v5 lite"])
    g = roofline_gate(wall_at_mfu20, flops=flops, kind="TPU v5 lite")
    assert g["implausible"] is True


def test_sane_timing_passes():
    qc_bytes = 68579 * 512 * 4
    bound = qc_bytes / _HBM_BW["TPU v5 lite"]
    g = roofline_gate(10 * bound, bytes_moved=qc_bytes,
                      kind="TPU v5 lite")
    assert "implausible" not in g
    assert g["roofline_s"] > 0
    # at exactly the bound: physically possible, must not flag
    g2 = roofline_gate(bound, bytes_moved=qc_bytes, kind="TPU v5 lite")
    assert "implausible" not in g2


def test_unknown_kind_gives_no_verdict():
    assert roofline_gate(1e-9, flops=1e15, kind="cpu") == {}
    assert roofline_gate(1e-9, flops=1e15, kind=None) == {}
    # no work model -> no verdict either
    assert roofline_gate(1e-9, kind="TPU v5 lite") == {}


def test_fusion_stage_speedup_and_cache_gate():
    """The plan-layer acceptance gate: bench's ``fusion`` stage must
    show fused execution >= 1.5x the unfused step-by-step wall on the
    synthetic configs[3]-shaped chain (CPU backend — measurable in
    CI), with zero retraces after the first compile and results equal
    to float tolerance.  One re-measure is allowed before failing:
    this box has 2 cores and CI neighbours."""
    import jax

    from bench import run_fusion

    det = run_fusion(jax)
    if det["speedup_vs_unfused"] < 1.5:  # pragma: no cover - noisy box
        det = run_fusion(jax)
    assert det["speedup_vs_unfused"] >= 1.5, det
    # scale's per-gene reductions may legally regroup by ulps inside
    # the fused program (same tolerance model as test_plan.py) — the
    # gate is "identical results", not "identical instruction order"
    assert det["fused_max_abs_err"] <= 1e-4, det
    # steady-state reps after the first compile are all cache hits
    assert det["plan_counters"]["plan.cache_misses"] == 1.0, det
    assert det["plan_counters"]["plan.cache_hits"] == float(det["reps"])
    # the double-buffered stream actually overlapped producer work
    assert det["stream_overlap_s"] > 0.0, det
    assert 0.0 <= det["overlap_efficiency"] <= 1.0


def test_mesh_stage_speedup_recall_and_cache_gate():
    """The sharded-plan acceptance gate: bench's ``mesh`` stage must
    show the mesh-sharded fused plan beating the per-chip dispatch
    loop on the same host mesh, with kNN recall vs a single-device
    exact search >= 0.999 (the MULTICHIP gate) and zero retraces
    after the first compile.  One re-measure before failing: this box
    has 2 cores and CI neighbours."""
    import jax

    from tools.bench_mesh import run_mesh_bench, v5e8_projection

    det = run_mesh_bench(jax, n_cells=1024, n_genes=256, reps=3)
    if det["speedup_vs_dispatch"] < 1.1:  # pragma: no cover - noisy box
        det = run_mesh_bench(jax, n_cells=1024, n_genes=256, reps=3)
    assert det["speedup_vs_dispatch"] > 1.0, det
    assert det["knn_recall_vs_single"] >= 0.999, det
    assert det["n_devices"] == 8
    # steady-state reps after the first compile are all cache hits,
    # and both sharded stage kinds ran every rep (warm + reps)
    assert det["plan_counters"]["plan.cache_misses"] == 1.0, det
    assert det["plan_counters"]["plan.cache_hits"] == float(det["reps"])
    assert det["plan_counters"]["plan.sharded_stages"] == \
        2.0 * (det["reps"] + 1)
    proj = det["v5e8_projection_10M"]
    assert proj["knn_compute_s_per_chip"] > 0
    # a measured MFU anchors the projection; garbage values don't
    assert v5e8_projection(0.55)["mfu_source"].startswith("measured")
    # an out-of-range "measured" value is neither used NOR claimed
    assert v5e8_projection(7.0)["mfu_anchor"] == 0.40
    assert v5e8_projection(7.0)["mfu_source"].startswith("assumed")


def test_graph_stage_speedup_parity_and_locality_gate():
    """ISSUE 8's acceptance gate: bench's ``graph`` phase must show
    the tiled kernels on the RCM-reordered layout >= 1.3x the legacy
    gather path (phase-level wall, the one-shot reorder charged
    against the tiled arm), with parity pinned in the same run — on
    this CPU box the resolved impl is the blocked-XLA twin, which is
    BITWISE equal to the gather path, and jaccard exactly equal (the
    Pallas kernels' ulp tolerance lives in test_pallas_graph.py).
    One re-measure before failing: 2 cores, CI neighbours."""
    import jax

    from tools.bench_graph import run_graph_bench

    det = run_graph_bench(jax, sizes=(8192, 32768), reps=3)
    if det["speedup_tiled_reordered"] < 1.3:  # pragma: no cover - noisy box
        det = run_graph_bench(jax, sizes=(8192, 32768), reps=3)
    assert det["speedup_tiled_reordered"] >= 1.3, det
    assert det["impl"] == "xla"  # auto off-TPU = the bitwise twin
    assert det["matvec_max_abs_err"] == 0.0, det
    # reordered results, inverse-permuted, are the SAME numbers
    assert det["matvec_reordered_max_abs_err"] == 0.0, det
    assert det["jaccard_equal"] and det["jaccard_reordered_equal"], det
    # the locality pass must actually buy locality on the clustered
    # graph (that is what the banded kernels ride on TPU)
    assert (det["tile_density_reordered"]
            > 2.0 * det["tile_density_natural"]), det


def test_graph_stage_escape_hatch_restores_gather_path():
    """SCTOOLS_PALLAS_GRAPH=0 (config graph_impl='gather') must route
    every dispatcher back to the pre-ISSUE-8 path — same objects, not
    just same numbers."""
    import jax.numpy as jnp
    import numpy as np

    from sctools_tpu.config import _parse_graph_impl, configure
    from sctools_tpu.ops import graph as G

    assert _parse_graph_impl("0") == "gather"
    assert _parse_graph_impl("false") == "gather"
    assert _parse_graph_impl("1") == "pallas"
    assert _parse_graph_impl("xla") == "xla"
    rng = np.random.default_rng(0)
    idx = rng.integers(0, 256, (256, 8)).astype(np.int32)
    w = rng.random((256, 8)).astype(np.float32)
    x = rng.standard_normal((256, 5)).astype(np.float32)
    ref = np.asarray(G._knn_matvec_gather(
        jnp.asarray(idx), jnp.asarray(w), jnp.asarray(x)))
    with configure(graph_impl="gather"):
        assert G.knn_matvec.__module__ == "sctools_tpu.ops.graph"
        out = np.asarray(G.knn_matvec(
            jnp.asarray(idx), jnp.asarray(w), jnp.asarray(x)))
        jc = np.asarray(__import__(
            "sctools_tpu.ops.pallas_graph",
            fromlist=["jaccard"]).jaccard(jnp.asarray(idx)))
    assert np.array_equal(ref, out)
    assert np.array_equal(
        jc, np.asarray(G.jaccard_arrays(jnp.asarray(idx))))


def test_flops_and_bytes_take_max():
    # compute-bound case: flops bound dominates the byte bound
    g = roofline_gate(1.0, flops=1e15, bytes_moved=1.0,
                      kind="TPU v5 lite")
    assert g["roofline_s"] > 1.0  # 1e15 / 197e12 ≈ 5.1 s
    assert g["implausible"] is True


def test_ingest_overlap_efficiency_gate():
    """The out-of-core ingest acceptance gate: a shard store 10x the
    configured host-RAM budget must stream through the fused
    streaming recipe at >= 0.8 overlap efficiency (measured by the
    existing stream.overlap_s/stall_s counters in the sync-per-shard
    regime, where the double buffer is the only overlap mechanism).
    One re-measure is allowed before failing: this box has 2 cores
    and CI neighbours."""
    import jax

    from tools.bench_ingest import run_ingest_bench

    det = run_ingest_bench(jax)
    if det["overlap_efficiency"] < 0.8:  # pragma: no cover - noisy box
        det = run_ingest_bench(jax)
    # the out-of-core contract itself: the store really was 10x the
    # admitted in-flight budget, and every cell came out the far end
    assert det["store_to_budget_ratio"] >= 10.0, det
    assert det["cells_scored"] == det["n_cells"], det
    assert det["overlap_efficiency"] >= 0.8, det
    # the slow-disk chaos arm still completed the identical read plan
    # (the delta is informational: straggler headroom of the buffer)
    def total_reads(arm):
        return sum(v for k, v in det[arm]["ingest_counters"].items()
                   if k.startswith("ingest.reads"))

    assert total_reads("slow_disk") == total_reads("clean") > 0, det
    assert "slow_disk_efficiency_delta" in det


def test_train_overlap_and_parity_gate():
    """The out-of-core TRAINING acceptance gate (ISSUE 12): scvi
    trained on a shard store 10x the configured host-RAM budget must
    (a) keep the prefetched device feed >= 0.8 overlap-efficient
    (train.overlap_s/stall_s — decode + device_put of shard N+1
    hidden behind the compiled train scan on N) and (b) land its
    final loss within 5% of the in-RAM path on the same data, seed
    and hyperparameters (the per-shard program IS the in-RAM epoch
    scan, so only the permutation granularity differs).  One
    re-measure is allowed before failing: this box has 2 cores and
    CI neighbours."""
    import jax

    from tools.bench_train import run_train_bench

    det = run_train_bench(jax)
    if det["overlap_efficiency"] < 0.8:  # pragma: no cover - noisy box
        det = run_train_bench(jax)
    # the out-of-core contract itself: the store really was 10x the
    # admitted in-flight budget and training actually ran
    assert det["store_to_budget_ratio"] >= 10.0, det
    assert det["train_steps"] > 0, det
    assert det["overlap_efficiency"] >= 0.8, det
    # loss parity vs in-RAM, and both paths genuinely trained
    assert det["final_loss_rel_diff"] <= 0.05, det
    assert det["stream_loss_final"] < det["stream_loss_first"], det
    assert det["inram_loss_final"] < det["inram_loss_first"], det


def test_serve_latency_retrace_and_agreement_gate():
    """The resident-state serving acceptance gate (ISSUE 14): a
    sustained randomly-sized query stream against the resident
    reference model must (a) keep p99 admission->result latency
    under the bound (default 250 ms on this 2-core box — measured
    ~2.5 ms, the bound is headroom for CI neighbours; env
    SCTOOLS_BENCH_SERVE_P99_MS overrides), (b) add ZERO plan-cache
    retraces after warmup — INCLUDING across the mid-stream
    hot-swap, because the model arrays enter the compiled kernels as
    inputs, not baked constants — and (c) agree with the batch
    pipeline (integrate.ingest, cpu oracle) on >= 0.99 of a held-out
    query batch's labels.  One re-measure is allowed before failing:
    this box has 2 cores and CI neighbours."""
    import jax

    from tools.bench_serve import run_serve_bench

    p99_bound = float(os.environ.get("SCTOOLS_BENCH_SERVE_P99_MS",
                                     250.0))
    det = run_serve_bench(jax)
    if det["latency_p99_ms"] > p99_bound:  # pragma: no cover - noisy
        det = run_serve_bench(jax)
    # the stream really ran, every query completed, the swap flipped
    assert det["completed"] >= det["n_queries"], det
    assert det["swap_epoch"] == 1, det
    assert det["latency_p99_ms"] <= p99_bound, det
    assert det["retraces_after_warmup"] == 0.0, det
    assert det["plan_hits"] >= det["n_queries"], det
    assert det["batch_agreement"] >= 0.99, det


def test_buckets_stage_speedup_and_retrace_gate():
    """ISSUE 20's acceptance gate: bench's ``buckets`` phase must show
    N differently-shaped uploads through the bucketized fused recipe
    >= 1.3x faster than tracing per shape, with exactly ONE compile in
    the bucketized arm (every subsequent shape a plan-cache hit) and
    one compile PER SHAPE in the per-shape arm.  One re-measure before
    failing: 2 cores, CI neighbours."""
    import jax

    from tools.bench_buckets import run_bucket_bench

    det = run_bucket_bench(jax)
    # compile counts only hold on a process-fresh plan cache — pin
    # them from the FIRST measurement, before any re-measure
    assert det["compiles_pershape"] == det["n_shapes"], det
    assert det["compiles_bucketized"] == 1, det
    if det["speedup"] < 1.3:  # pragma: no cover - noisy box
        # fresh seed: same-process re-measure must draw new shapes or
        # the first call's cached plans zero the timing contrast
        det = run_bucket_bench(jax, seed=1)
    assert det["speedup"] >= 1.3, det
