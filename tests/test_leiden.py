"""cluster.leiden — modularity optimisation vs the serial greedy
Louvain oracle, on blob kNN graphs with known community structure."""

import numpy as np
import pytest

import sctools_tpu as sct
from sctools_tpu.data.dataset import CellData
from sctools_tpu.data.synthetic import gaussian_blobs
from sctools_tpu.ops.cluster import (adjusted_rand_index, _symmetrize_knn,
                                     modularity)
from sctools_tpu.ops.knn import knn_numpy


def _blob_data(n=600, blobs=5, k=12, seed=7):
    pts, truth = gaussian_blobs(n, 10, blobs, spread=0.25, seed=seed)
    idx, dist = knn_numpy(pts, pts, k=k, metric="euclidean",
                          exclude_self=True)
    d = CellData(np.zeros((n, 4), np.float32),
                 obs={"truth": truth}).with_obsp(
        knn_indices=idx, knn_distances=dist).with_uns(
        knn_k=k, knn_metric="euclidean")
    return sct.apply("graph.connectivities", d, backend="cpu"), truth


@pytest.fixture(scope="module")
def blobs():
    return _blob_data()


def test_leiden_modularity_vs_oracle(blobs):
    data, truth = blobs
    t = sct.apply("cluster.leiden", data, backend="tpu")
    c = sct.apply("cluster.leiden", data, backend="cpu")
    q_t = float(t.uns["leiden_modularity"])
    q_c = float(c.uns["leiden_modularity"])
    # device-parallel moves must reach within 5% of the serial oracle
    assert q_t >= q_c - 0.05 * abs(q_c), (q_t, q_c)
    # and both should be genuinely high on well-separated blobs
    assert q_c > 0.5
    # stored modularity matches the independent metric
    idx2, w2 = _symmetrize_knn(
        np.asarray(data.obsp["knn_indices"]),
        np.asarray(data.obsp["connectivities"]))
    q_check = modularity(idx2, w2, np.asarray(t.obs["leiden"]))
    assert abs(q_check - q_t) < 1e-4


def test_leiden_recovers_blobs(blobs):
    data, truth = blobs
    t = sct.apply("cluster.leiden", data, backend="tpu")
    ari = adjusted_rand_index(np.asarray(t.obs["leiden"]), truth)
    assert ari > 0.8, ari


def test_leiden_deterministic(blobs):
    data, _ = blobs
    a = sct.apply("cluster.leiden", data, backend="tpu")
    b = sct.apply("cluster.leiden", data, backend="tpu")
    assert (np.asarray(a.obs["leiden"]) == np.asarray(b.obs["leiden"])).all()


def test_leiden_resolution_monotone(blobs):
    data, _ = blobs
    lo = sct.apply("cluster.leiden", data, backend="tpu", resolution=0.25)
    hi = sct.apply("cluster.leiden", data, backend="tpu", resolution=4.0)
    n_lo = len(np.unique(np.asarray(lo.obs["leiden"])))
    n_hi = len(np.unique(np.asarray(hi.obs["leiden"])))
    assert n_hi >= n_lo, (n_lo, n_hi)


def test_leiden_requires_knn():
    d = CellData(np.zeros((10, 4), np.float32))
    with pytest.raises(ValueError, match="neighbors.knn"):
        sct.apply("cluster.leiden", d, backend="tpu")


# ----------------------------------------------------------------------
# Merge phase beyond the dense cap (ring of cliques, first level > 4096
# communities) — before round 4 the merge silently skipped above 4096.
# ----------------------------------------------------------------------


def _ring_of_cliques(n_cliques=5000, clique=4):
    """Symmetric ELL graph: n_cliques cliques of `clique` nodes, each
    clique internally complete (weight 1), consecutive cliques joined
    by one weak ring edge (weight 0.1)."""
    n = n_cliques * clique
    cap = clique  # clique-1 internal + at most 1 ring edge
    idx = np.full((n, cap), -1, np.int32)
    w = np.zeros((n, cap), np.float32)
    node = np.arange(n).reshape(n_cliques, clique)
    for j in range(clique):
        # internal edges: every clique-mate except self
        others = np.delete(node, j, axis=1)  # (n_cliques, clique-1)
        idx[node[:, j], : clique - 1] = others
        w[node[:, j], : clique - 1] = 1.0
    # ring: last node of clique c <-> first node of clique c+1
    a = node[:, -1]
    b = np.roll(node[:, 0], -1)
    idx[a, clique - 1] = b
    w[a, clique - 1] = 0.1
    idx[b, clique - 1] = a
    w[b, clique - 1] = 0.1
    return idx, w


def test_merge_active_beyond_dense_cap():
    from sctools_tpu.ops.cluster import (_modularity_merge,
                                         louvain_moves_arrays)
    import jax.numpy as jnp

    idx, w = _ring_of_cliques(5000, 4)
    n = idx.shape[0]
    first = np.asarray(louvain_moves_arrays(
        jnp.asarray(idx), jnp.asarray(w),
        jnp.arange(n, dtype=jnp.int32), n_rounds=8))
    m_first = len(np.unique(first))
    # local moves settle each clique into its own community — well
    # beyond the 4096 dense-merge cap that used to silently skip
    assert m_first > 4096, m_first
    merged = _modularity_merge(first, idx, w)
    m_merged = len(np.unique(merged))
    q_first = modularity(idx, w, first)
    q_merged = modularity(idx, w, merged)
    # the resolution limit makes merging adjacent cliques strictly
    # better than one-community-per-clique at 5000 cliques — an
    # active merge must find that improvement; a skipped merge can't
    assert m_merged < m_first, (m_merged, m_first)
    assert q_merged > q_first + 1e-4, (q_merged, q_first)
    # merged communities must be unions of cliques (never split one)
    cl = np.repeat(np.arange(5000), 4)
    for c in np.unique(cl[:64]):  # spot-check the first cliques
        assert len(np.unique(merged[cl == c])) == 1


def test_coarse_ell_preserves_self_loops():
    from sctools_tpu.ops.cluster import _coarse_ell

    idx, w = _ring_of_cliques(8, 3)
    labels = np.repeat(np.arange(8), 3).astype(np.int64)
    cidx, cw = _coarse_ell(labels, idx, w)
    # each clique of 3 has 6 directed internal entries of weight 1 ->
    # self-loop weight 6 on its supernode
    for c in range(8):
        row = cidx[c]
        self_slot = np.flatnonzero(row == c)
        assert len(self_slot) == 1
        assert np.isclose(cw[c, self_slot[0]], 6.0)


def test_native_sweeps_match_python():
    """The C++ oracle sweep (csrc scio_louvain_sweeps) must reproduce
    the pure-Python sweep loop exactly — same visit order, gain
    formula, and tie-breaks."""
    from sctools_tpu.native import have_native
    from sctools_tpu.ops.cluster import _serial_sweeps

    if not have_native():
        pytest.skip("native library not built")
    idx, w = _ring_of_cliques(40, 4)
    n = idx.shape[0]
    labels0 = np.arange(n, dtype=np.int64)
    py = _serial_sweeps(idx, w, labels0, 1.0, 10, force_python=True)
    nat = _serial_sweeps(idx, w, labels0, 1.0, 10)
    assert np.array_equal(py, nat)
    # and on an irregular weighted graph
    pts, _ = gaussian_blobs(400, 8, 6, spread=0.3, seed=11)
    kidx, kdist = knn_numpy(pts, pts, k=10, metric="euclidean",
                            exclude_self=True)
    idx2, w2 = _symmetrize_knn(kidx, 1.0 / (1.0 + kdist))
    labels0 = np.arange(idx2.shape[0], dtype=np.int64)
    py = _serial_sweeps(idx2, w2, labels0, 1.0, 20, force_python=True)
    nat = _serial_sweeps(idx2, w2, labels0, 1.0, 20)
    assert np.array_equal(py, nat)


def test_leiden_parity_at_scale():
    """Device-parallel moves vs the native serial oracle at a scale
    where parallel-move pathologies can actually appear (8k nodes —
    beyond the 4096 dense-merge cap, so the sparse merge path is
    active; the pure-Python oracle capped this assertion at ~600, and
    20k was measured to buy no extra coverage for ~4x the wall)."""
    from sctools_tpu.native import have_native

    if not have_native():
        pytest.skip("native library not built")
    n = 8192
    pts, truth = gaussian_blobs(n, 10, 12, spread=0.3, seed=13)
    idx, dist = knn_numpy(pts, pts, k=10, metric="euclidean",
                          exclude_self=True)
    d = CellData(np.zeros((n, 4), np.float32)).with_obsp(
        knn_indices=idx, knn_distances=dist).with_uns(
        knn_k=10, knn_metric="euclidean")
    d = sct.apply("graph.connectivities", d, backend="cpu")
    t = sct.apply("cluster.leiden", d, backend="tpu")
    c = sct.apply("cluster.leiden", d, backend="cpu")
    q_t = float(t.uns["leiden_modularity"])
    q_c = float(c.uns["leiden_modularity"])
    assert q_t >= q_c - 0.05 * abs(q_c), (q_t, q_c)
    ari = adjusted_rand_index(np.asarray(t.obs["leiden"]), truth)
    assert ari > 0.8, ari
