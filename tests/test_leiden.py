"""cluster.leiden — modularity optimisation vs the serial greedy
Louvain oracle, on blob kNN graphs with known community structure."""

import numpy as np
import pytest

import sctools_tpu as sct
from sctools_tpu.data.dataset import CellData
from sctools_tpu.data.synthetic import gaussian_blobs
from sctools_tpu.ops.cluster import (adjusted_rand_index, _symmetrize_knn,
                                     modularity)
from sctools_tpu.ops.knn import knn_numpy


def _blob_data(n=600, blobs=5, k=12, seed=7):
    pts, truth = gaussian_blobs(n, 10, blobs, spread=0.25, seed=seed)
    idx, dist = knn_numpy(pts, pts, k=k, metric="euclidean",
                          exclude_self=True)
    d = CellData(np.zeros((n, 4), np.float32),
                 obs={"truth": truth}).with_obsp(
        knn_indices=idx, knn_distances=dist).with_uns(
        knn_k=k, knn_metric="euclidean")
    return sct.apply("graph.connectivities", d, backend="cpu"), truth


@pytest.fixture(scope="module")
def blobs():
    return _blob_data()


def test_leiden_modularity_vs_oracle(blobs):
    data, truth = blobs
    t = sct.apply("cluster.leiden", data, backend="tpu")
    c = sct.apply("cluster.leiden", data, backend="cpu")
    q_t = float(t.uns["leiden_modularity"])
    q_c = float(c.uns["leiden_modularity"])
    # device-parallel moves must reach within 5% of the serial oracle
    assert q_t >= q_c - 0.05 * abs(q_c), (q_t, q_c)
    # and both should be genuinely high on well-separated blobs
    assert q_c > 0.5
    # stored modularity matches the independent metric
    idx2, w2 = _symmetrize_knn(
        np.asarray(data.obsp["knn_indices"]),
        np.asarray(data.obsp["connectivities"]))
    q_check = modularity(idx2, w2, np.asarray(t.obs["leiden"]))
    assert abs(q_check - q_t) < 1e-4


def test_leiden_recovers_blobs(blobs):
    data, truth = blobs
    t = sct.apply("cluster.leiden", data, backend="tpu")
    ari = adjusted_rand_index(np.asarray(t.obs["leiden"]), truth)
    assert ari > 0.8, ari


def test_leiden_deterministic(blobs):
    data, _ = blobs
    a = sct.apply("cluster.leiden", data, backend="tpu")
    b = sct.apply("cluster.leiden", data, backend="tpu")
    assert (np.asarray(a.obs["leiden"]) == np.asarray(b.obs["leiden"])).all()


def test_leiden_resolution_monotone(blobs):
    data, _ = blobs
    lo = sct.apply("cluster.leiden", data, backend="tpu", resolution=0.25)
    hi = sct.apply("cluster.leiden", data, backend="tpu", resolution=4.0)
    n_lo = len(np.unique(np.asarray(lo.obs["leiden"])))
    n_hi = len(np.unique(np.asarray(hi.obs["leiden"])))
    assert n_hi >= n_lo, (n_lo, n_hi)


def test_leiden_requires_knn():
    d = CellData(np.zeros((10, 4), np.float32))
    with pytest.raises(ValueError, match="neighbors.knn"):
        sct.apply("cluster.leiden", d, backend="tpu")
