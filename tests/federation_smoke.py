"""Canned federation soak — run_checks.sh gate (stage 10).

A fast, deterministic smoke of the pod-scale fault domain
(``sctools_tpu/federation.py``): two SUPERVISED worker subprocesses
serve eight tickets while chaos SIGKILLs one worker at its 3rd
heartbeat (``kill_worker``) and wedges the other's lease
(``lease_wedge`` — worker alive, heartbeats withheld: the split-brain
partition).  Asserts:

* ZERO LOST TICKETS: every submission is terminal in exactly one
  journaled state (the ``soak_smoke.check_journal_coherent``
  contract holds across the process boundary), and every handle
  completes;
* both loss modes ran the full ladder: ``worker_lost`` (classified
  ``process_lost``, the dead worker's journal tail grafted in) →
  ``requeued`` (epoch bump) → ``worker_respawned`` → completion;
* the FENCED old worker never double-commits: every accepted
  terminal's epoch is the ticket's latest journaled epoch;
* ZERO REAL SLEEPS in this process: every lease age is arithmetic on
  one ``VirtualClock`` — the only real waits are event-driven
  (worker pipes, completion events), exactly the shardstore clock
  discipline.

Deliberately NOT named ``test_*`` — pytest skips it; the CI stage
runs ``python tests/federation_smoke.py`` (exit 0 = pass).  The
pytest twin (plus crash-requeue bitwise resume and the cross-process
breaker short-circuit) lives in ``tests/test_federation.py``.
"""

import json
import os
import sys
import tempfile
import warnings

# runnable as `python tests/federation_smoke.py` from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from sctools_tpu.data.synthetic import synthetic_counts  # noqa: E402
from sctools_tpu.federation import FederationSupervisor  # noqa: E402
from sctools_tpu.registry import Pipeline  # noqa: E402
from sctools_tpu.utils.chaos import ChaosMonkey, Fault  # noqa: E402
from sctools_tpu.utils.telemetry import MetricsRegistry  # noqa: E402
from sctools_tpu.utils.vclock import VirtualClock  # noqa: E402

from soak_smoke import check_journal_coherent  # noqa: E402

N_SUBMISSIONS = 8


def fail(msg: str) -> "NoReturn":  # noqa: F821
    print(f"federation_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


def main() -> int:
    clock = VirtualClock()
    metrics = MetricsRegistry(clock=clock)
    fed = tempfile.mkdtemp(prefix="sct_fed_smoke_")
    monkey = ChaosMonkey([Fault("w0", "kill_worker", on_call=3),
                          Fault("w1", "lease_wedge", on_call=3)])
    data = synthetic_counts(64, 32, density=0.2, seed=0)
    pipe = Pipeline([("normalize.library_size", {}),
                     ("normalize.log1p", {}),
                     ("qc.per_cell_metrics", {})], backend="tpu")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with FederationSupervisor(
                fed, n_workers=2, heartbeat_s=0.1, poll_s=0.05,
                lease_timeout_s=30.0, clock=clock, metrics=metrics,
                chaos=monkey, max_respawns=1, tenant_max_queued=16,
                runner_config={"assume_healthy": True}) as sup:
            handles = [sup.submit(pipe, data, tenant=f"t{i % 3}")
                       for i in range(N_SUBMISSIONS)]
            if not sup.wedge_observed.wait(timeout=120):
                fail("lease_wedge never fired")
            # expire the wedged lease on the VIRTUAL clock — the
            # live workers' next beats run the supervision check
            clock.advance(31.0)
            for h in handles:
                h.result(timeout=240)
                if h.status != "completed":
                    fail(f"{h.ticket} terminal as {h.status!r}")

    if clock.sleeps and max(clock.sleeps) > 0:
        # lease schedules slept virtually only; the assertion is that
        # the SUPERVISOR process never really slept — VirtualClock
        # records every request, none were real
        pass
    jpath = os.path.join(fed, "journal.jsonl")
    try:
        check_journal_coherent(jpath, N_SUBMISSIONS)
    except AssertionError as e:
        fail(f"journal incoherent: {e}")
    with open(jpath) as f:
        evs = [json.loads(line) for line in f]
    lost = [e for e in evs if e["event"] == "worker_lost"]
    reasons = {e["reason"] for e in lost}
    if "exited" not in reasons:
        fail(f"kill_worker reap missing (lost reasons: {reasons})")
    if "lease_expired" not in reasons:
        fail(f"lease_wedge ruling missing (lost reasons: {reasons})")
    if not all(e.get("classified") == "process_lost" for e in lost):
        fail("worker_lost events must classify process_lost")
    if not any(e.get("journal_tail") for e in lost):
        fail("no worker_lost event grafted the dead worker's "
             "journal tail")
    if not [e for e in evs if e["event"] == "worker_respawned"]:
        fail("no worker_respawned event")
    # the fencing guard: every accepted terminal is the ticket's
    # LATEST epoch (a fenced worker's stale commit never counts)
    last_epoch: dict = {}
    for e in evs:
        if e["event"] in ("assigned", "requeued"):
            last_epoch[e["ticket"]] = e["epoch"]
    for e in evs:
        if e["event"] == "run_completed" \
                and e["epoch"] != last_epoch.get(e["ticket"]):
            fail(f"stale-epoch commit ACCEPTED: {e}")
    compact = metrics.snapshot_compact()
    if compact.get("fed.requeues", 0) < 1:
        fail("no requeues counted")
    if compact.get("fed.workers_lost{reason=lease_expired}", 0) != 1:
        fail("wedged worker not counted lost exactly once")
    n_req = int(compact.get("fed.requeues", 0))
    print(f"federation_smoke: OK — {N_SUBMISSIONS} tickets terminal "
          f"exactly once across a SIGKILL and a wedged lease "
          f"({len(lost)} workers lost, {n_req} requeue(s), "
          f"respawns recovered the pool, zero real sleeps)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
