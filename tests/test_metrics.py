"""metrics.morans_i / metrics.gearys_c vs a dense-formula oracle."""

import numpy as np
import pytest

import sctools_tpu as sct
from sctools_tpu.data.dataset import CellData


@pytest.fixture(scope="module")
def graphed():
    """Two spatial blobs; gene 0 separates them (high autocorrelation),
    gene 1 is pure noise (none)."""
    rng = np.random.default_rng(0)
    n = 300
    pos = np.vstack([rng.normal(0, 1, (150, 5)),
                     rng.normal(6, 1, (150, 5))]).astype(np.float32)
    X = np.zeros((n, 3), np.float32)
    X[:, 0] = np.concatenate([np.zeros(150), np.ones(150)]) \
        + rng.normal(0, 0.1, n)
    X[:, 1] = rng.normal(0, 1, n)
    X[:, 2] = pos[:, 0] * 0.5 + rng.normal(0, 0.2, n)
    d = CellData(X, obsm={"X_pca": pos})
    d = sct.apply("neighbors.knn", d, backend="cpu", k=10,
                  metric="euclidean")
    return sct.apply("graph.connectivities", d, backend="cpu")


def _dense_oracle(d):
    """Direct formulas on the densified weight matrix."""
    n = d.n_cells
    idx = np.asarray(d.obsp["knn_indices"])
    w = np.asarray(d.obsp["connectivities"], np.float64)
    W = np.zeros((n, n))
    for i in range(n):
        for j, wij in zip(idx[i], w[i]):
            if j >= 0:
                W[i, j] = wij
    X = np.asarray(d.X, np.float64)
    S0 = W.sum()
    I, C = [], []
    for g in range(X.shape[1]):
        x = X[:, g]
        z = x - x.mean()
        I.append((n / S0) * (z @ W @ z) / (z @ z))
        diff2 = (x[:, None] - x[None, :]) ** 2
        C.append(((n - 1) / (2 * S0)) * (W * diff2).sum() / (z @ z))
    return np.array(I), np.array(C)


def test_metrics_match_dense_oracle(graphed):
    want_i, want_c = _dense_oracle(graphed)
    out = sct.apply("metrics.morans_i", graphed, backend="cpu")
    out = sct.apply("metrics.gearys_c", out, backend="cpu")
    np.testing.assert_allclose(np.asarray(out.var["morans_i"],
                                          np.float64),
                               want_i, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(out.var["gearys_c"],
                                          np.float64),
                               want_c, rtol=1e-5, atol=1e-6)


def test_metrics_separate_signal_from_noise(graphed):
    out = sct.apply("metrics.morans_i", graphed, backend="cpu")
    out = sct.apply("metrics.gearys_c", out, backend="cpu")
    I = np.asarray(out.var["morans_i"])
    C = np.asarray(out.var["gearys_c"])
    assert I[0] > 0.8       # blob-separating gene: strong structure
    assert abs(I[1]) < 0.15  # noise gene
    assert C[0] < 0.3 and 0.7 < C[1] < 1.3


def test_metrics_tpu_matches_cpu(graphed):
    a = sct.apply("metrics.morans_i", graphed, backend="tpu")
    b = sct.apply("metrics.morans_i", graphed, backend="cpu")
    np.testing.assert_allclose(np.asarray(a.var["morans_i"]),
                               np.asarray(b.var["morans_i"]),
                               rtol=1e-4, atol=1e-5)
    a = sct.apply("metrics.gearys_c", graphed, backend="tpu")
    b = sct.apply("metrics.gearys_c", graphed, backend="cpu")
    np.testing.assert_allclose(np.asarray(a.var["gearys_c"]),
                               np.asarray(b.var["gearys_c"]),
                               rtol=1e-4, atol=1e-5)


def test_metrics_on_obsm_rep(graphed):
    out = sct.apply("metrics.morans_i", graphed, backend="cpu",
                    use_rep="X_pca")
    assert out.uns["morans_i_X_pca"].shape == (5,)
    # spatial coordinates are maximally autocorrelated over their own
    # kNN graph
    assert out.uns["morans_i_X_pca"][0] > 0.9


def test_metrics_require_graph():
    d = CellData(np.ones((5, 2), np.float32))
    with pytest.raises(KeyError, match="neighbors.knn"):
        sct.apply("metrics.morans_i", d, backend="cpu")
