"""TPU ops vs CPU oracle: normalize, QC, HVG, filters."""

import numpy as np
import pytest

import sctools_tpu as sct
from sctools_tpu.data.synthetic import synthetic_counts


@pytest.fixture(scope="module")
def ds():
    return synthetic_counts(200, 300, density=0.1, n_clusters=3,
                            mito_frac=0.03, seed=7)


def both(ds, name, **kw):
    cpu = sct.apply(name, ds, backend="cpu", **kw)
    tpu = sct.apply(name, ds.device_put(), backend="tpu", **kw).to_host()
    return cpu, tpu


def test_library_size(ds):
    cpu, tpu = both(ds, "normalize.library_size", target_sum=1e4)
    np.testing.assert_allclose(tpu.X.toarray(), cpu.X.toarray(),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(tpu.obs["library_size"],
                               cpu.obs["library_size"], rtol=1e-5)


def test_library_size_median(ds):
    cpu, tpu = both(ds, "normalize.library_size", target_sum=None)
    np.testing.assert_allclose(tpu.X.toarray(), cpu.X.toarray(),
                               rtol=1e-3, atol=1e-3)


def test_log1p(ds):
    cpu, tpu = both(ds, "normalize.log1p")
    np.testing.assert_allclose(tpu.X.toarray(), cpu.X.toarray(),
                               rtol=1e-4, atol=1e-5)


def test_scale(ds):
    cpu, tpu = both(ds, "normalize.scale", max_value=10.0)
    np.testing.assert_allclose(np.asarray(tpu.X)[: ds.n_cells],
                               cpu.X, rtol=2e-3, atol=2e-3)


def test_per_cell_metrics(ds):
    cpu, tpu = both(ds, "qc.per_cell_metrics")
    np.testing.assert_array_equal(tpu.obs["n_genes"], cpu.obs["n_genes"])
    np.testing.assert_allclose(tpu.obs["total_counts"],
                               cpu.obs["total_counts"], rtol=1e-5)
    np.testing.assert_allclose(tpu.obs["pct_counts_mt"],
                               cpu.obs["pct_counts_mt"], rtol=1e-4)
    assert np.asarray(cpu.obs["pct_counts_mt"]).max() > 0


def test_per_gene_metrics(ds):
    cpu, tpu = both(ds, "qc.per_gene_metrics")
    np.testing.assert_array_equal(tpu.var["n_cells"], cpu.var["n_cells"])
    np.testing.assert_allclose(tpu.var["total_counts"],
                               cpu.var["total_counts"], rtol=1e-5)


def test_filter_cells(ds):
    cpu = sct.apply("qc.per_cell_metrics", ds, backend="cpu")
    cpu = sct.apply("qc.filter_cells", cpu, backend="cpu",
                    min_genes=10, max_pct_mt=50.0)
    dev = sct.apply("qc.per_cell_metrics", ds.device_put(), backend="tpu")
    dev = sct.apply("qc.filter_cells", dev, backend="tpu",
                    min_genes=10, max_pct_mt=50.0)
    tpu = dev.to_host()
    assert tpu.n_cells == cpu.n_cells
    np.testing.assert_allclose(tpu.X.toarray(), cpu.X.toarray(), rtol=1e-5)
    np.testing.assert_array_equal(tpu.obs["n_genes"], cpu.obs["n_genes"])


def test_filter_genes(ds):
    cpu = sct.apply("qc.filter_genes", ds, backend="cpu", min_cells=5)
    dev = sct.apply("qc.filter_genes", ds.device_put(), backend="tpu",
                    min_cells=5)
    tpu = dev.to_host()
    assert tpu.n_genes == cpu.n_genes
    np.testing.assert_allclose(tpu.X.toarray(), cpu.X.toarray(), rtol=1e-5)


@pytest.mark.parametrize("flavor", ["seurat_v3", "dispersion"])
def test_hvg_parity(ds, flavor):
    base = ds
    if flavor == "dispersion":
        base = sct.apply("normalize.library_size", base, backend="cpu")
        base = sct.apply("normalize.log1p", base, backend="cpu")
    cpu = sct.apply("hvg.select", base, backend="cpu", n_top=50, flavor=flavor)
    tpu = sct.apply("hvg.select", base.device_put(), backend="tpu",
                    n_top=50, flavor=flavor).to_host()
    # scores agree
    np.testing.assert_allclose(tpu.var["hvg_score"], cpu.var["hvg_score"],
                               rtol=5e-3, atol=5e-3)
    # selected sets agree almost entirely (ties near cutoff may differ)
    a = set(np.nonzero(cpu.var["highly_variable"])[0].tolist())
    b = set(np.nonzero(tpu.var["highly_variable"])[0].tolist())
    assert len(a & b) >= 48


def test_hvg_subset(ds):
    cpu = sct.apply("hvg.select", ds, backend="cpu", n_top=40, subset=True)
    tpu = sct.apply("hvg.select", ds.device_put(), backend="tpu",
                    n_top=40, subset=True).to_host()
    assert cpu.n_genes == 40
    assert tpu.n_genes == cpu.n_genes
    np.testing.assert_allclose(tpu.X.toarray(), cpu.X.toarray(),
                               rtol=1e-4, atol=1e-4)


def test_subsample_parity_and_contracts():
    from sctools_tpu.data.synthetic import synthetic_counts

    d = synthetic_counts(500, 300, density=0.1, n_clusters=3, seed=4)
    dev = d.device_put()
    t = sct.apply("qc.subsample", dev, backend="tpu", n_obs=123, seed=7)
    c = sct.apply("qc.subsample", d, backend="cpu", n_obs=123, seed=7)
    assert t.n_cells == c.n_cells == 123
    # identical cells chosen (host RNG shared), matrices equal
    np.testing.assert_allclose(
        t.to_host().X.toarray(), c.X.toarray(), rtol=1e-6)
    np.testing.assert_array_equal(
        np.asarray(t.to_host().obs["cluster_true"]),
        np.asarray(c.obs["cluster_true"]))
    f = sct.apply("qc.subsample", d, backend="cpu", fraction=0.25, seed=1)
    assert f.n_cells == 125
    with pytest.raises(ValueError, match="exactly one"):
        sct.apply("qc.subsample", d, backend="cpu")
    with pytest.raises(ValueError, match="exactly one"):
        sct.apply("qc.subsample", d, backend="cpu", fraction=0.5, n_obs=10)


def test_subsample_fraction_floors_and_rejects_empty():
    from sctools_tpu.data.synthetic import synthetic_counts

    d = synthetic_counts(499, 100, density=0.1, seed=4)
    out = sct.apply("qc.subsample", d, backend="cpu", fraction=0.25)
    assert out.n_cells == 124  # floor(124.75), scanpy's convention
    for bad in (dict(n_obs=0), dict(n_obs=-5), dict(fraction=0.0001)):
        with pytest.raises(ValueError, match="out of range"):
            sct.apply("qc.subsample", d, backend="cpu", **bad)


def test_subset_ops_slice_layers_consistently():
    """filter_cells / subsample / hvg subset must slice layers with X
    (pre-fix they silently kept stale full-size layers)."""
    from sctools_tpu.data.synthetic import synthetic_counts

    d = synthetic_counts(200, 80, density=0.15, seed=9)
    counts = d.X.copy()
    d = d.with_layers(counts=counts)

    # cpu cell subset
    sub = sct.apply("qc.subsample", d, backend="cpu", n_obs=50, seed=1)
    assert sub.layers["counts"].shape == (50, 80)
    np.testing.assert_allclose(sub.layers["counts"].toarray(),
                               sub.X.toarray())
    # device cell subset
    dev = d.device_put()
    sub_t = sct.apply("qc.subsample", dev, backend="tpu", n_obs=50, seed=1)
    host = sub_t.to_host()
    np.testing.assert_allclose(host.layers["counts"].toarray(),
                               sub.layers["counts"].toarray(), rtol=1e-6)
    # gene subset keeps layers column-aligned (tpu + cpu)
    hv = sct.apply("hvg.select", dev, backend="tpu", n_top=30,
                   flavor="dispersion", subset=True)
    hh = hv.to_host()
    assert hh.layers["counts"].shape[1] == 30
    hvc = sct.apply("hvg.select", d, backend="cpu", n_top=30,
                    flavor="dispersion", subset=True)
    assert hvc.layers["counts"].shape == (200, 30)


def test_snapshot_layer_in_pipeline():
    from sctools_tpu.data.synthetic import synthetic_counts

    d = synthetic_counts(100, 50, density=0.2, seed=2)
    raw = d.X.toarray()
    out = sct.Pipeline([
        ("util.snapshot_layer", {"layer": "counts"}),
        ("normalize.library_size", {"target_sum": 100.0}),
        ("normalize.log1p", {}),
    ]).run(d.device_put(), backend="tpu").to_host()
    np.testing.assert_allclose(out.layers["counts"].toarray(), raw,
                               rtol=1e-6)
    assert not np.allclose(out.X.toarray(), raw)  # X did change


def test_filter_genes_slices_layers_both_backends():
    from sctools_tpu.data.synthetic import synthetic_counts

    d = synthetic_counts(120, 60, density=0.1, seed=5)
    d = d.with_layers(counts=d.X.copy())
    c = sct.apply("qc.filter_genes", d, backend="cpu", min_cells=3)
    assert c.layers["counts"].shape == c.X.shape
    t = sct.apply("qc.filter_genes", d.device_put(), backend="tpu",
                  min_cells=3).to_host()
    assert t.layers["counts"].shape[1] == t.X.shape[1]
    assert c.X.shape[1] == t.X.shape[1]


def test_hvg_seurat_alias_and_cell_ranger():
    from sctools_tpu.data.synthetic import synthetic_counts

    d = synthetic_counts(400, 800, density=0.1, n_clusters=3, seed=7)
    d = sct.apply("normalize.library_size", d, backend="cpu")
    d = sct.apply("normalize.log1p", d, backend="cpu")
    # "seurat" is an alias of "dispersion"
    a = sct.apply("hvg.select", d, backend="cpu", n_top=100,
                  flavor="seurat")
    b = sct.apply("hvg.select", d, backend="cpu", n_top=100,
                  flavor="dispersion")
    np.testing.assert_array_equal(np.asarray(a.var["hvg_rank"]),
                                  np.asarray(b.var["hvg_rank"]))
    # cell_ranger runs on both backends and agrees (host scorer on
    # device-computed moments)
    c_cpu = sct.apply("hvg.select", d, backend="cpu", n_top=100,
                      flavor="cell_ranger")
    c_tpu = sct.apply("hvg.select", d.device_put(), backend="tpu",
                      n_top=100, flavor="cell_ranger")
    hc = np.asarray(c_cpu.var["highly_variable"])
    ht = np.asarray(c_tpu.var["highly_variable"])
    assert hc.sum() == 100
    assert (hc == ht).mean() > 0.98  # f32 moment ties at the margin
    # a different ranking than the seurat flavor (median/MAD vs
    # mean/std in different bins)
    assert (hc != np.asarray(a.var["highly_variable"])).any()


def test_hvg_cell_ranger_score_is_signed():
    """scanpy's cell_ranger normalized dispersion is SIGNED: a gene
    with unusually LOW dispersion within its mean-bin must score below
    the bin median, never alias with a high-dispersion gene."""
    from sctools_tpu.ops.hvg import _cell_ranger_scores

    rng = np.random.default_rng(0)
    mean = np.full(60, 5.0) * rng.uniform(0.9, 1.1, 60)
    var = mean * 1.0  # dispersion ~1 baseline
    var[3] = mean[3] * 50.0   # unusually HIGH dispersion
    var[7] = mean[7] * 0.02   # unusually LOW dispersion
    s = _cell_ranger_scores(mean, var)
    assert s[3] > 0
    assert s[7] < 0
    assert s[7] < np.median(s)  # low-dispersion gene ranks last, not first


def test_qc_percent_top_genes():
    from sctools_tpu.data.synthetic import synthetic_counts

    d = synthetic_counts(200, 500, density=0.1, n_clusters=2, seed=3)
    cpu = sct.apply("qc.per_cell_metrics", d, backend="cpu",
                    percent_top=(10, 50))
    tpu = sct.apply("qc.per_cell_metrics", d.device_put(),
                    backend="tpu", percent_top=(10, 50))
    for N in (10, 50):
        col = f"pct_counts_in_top_{N}_genes"
        c = np.asarray(cpu.obs[col], np.float64)
        t = np.asarray(tpu.obs[col], np.float64)[:200]
        np.testing.assert_allclose(t, c, rtol=1e-4, atol=1e-3)
        assert (c > 0).all() and (c <= 100.0 + 1e-9).all()
    # top-10 captures less than top-50, never more
    c10 = np.asarray(cpu.obs["pct_counts_in_top_10_genes"])
    c50 = np.asarray(cpu.obs["pct_counts_in_top_50_genes"])
    assert (c10 <= c50 + 1e-6).all()
    # a cell with fewer than N genes reaches exactly 100%
    few = np.asarray(cpu.obs["n_genes"]) <= 10
    if few.any():
        np.testing.assert_allclose(c10[few], 100.0, rtol=1e-6)


def test_hvg_batch_key_combines_ranks():
    """batch_key: a gene variable only through a batch-specific shift
    must LOSE to genes variable within every batch."""
    import scipy.sparse as sp

    from sctools_tpu.data.synthetic import synthetic_counts

    rng = np.random.default_rng(0)
    d = synthetic_counts(600, 400, density=0.15, n_clusters=3, seed=9)
    X = np.asarray(d.X.todense())
    # gene 0: constant within each batch, big shift BETWEEN batches
    X[:, 0] = 1.0
    X[300:, 0] = 50.0
    batch = np.array(["a"] * 300 + ["b"] * 300)
    d = d.with_X(sp.csr_matrix(X.astype(np.float32))).with_obs(
        sample=batch)

    plain = sct.apply("hvg.select", d, backend="cpu", n_top=50,
                      flavor="seurat_v3")
    batched = sct.apply("hvg.select", d, backend="cpu", n_top=50,
                        flavor="seurat_v3", batch_key="sample")
    # without batch awareness the shifted gene looks hyper-variable
    assert bool(np.asarray(plain.var["highly_variable"])[0])
    # batch-aware ranking sends it down the list
    assert not bool(np.asarray(batched.var["highly_variable"])[0])
    nb = np.asarray(batched.var["highly_variable_nbatches"])
    assert nb.max() == 2 and nb.min() >= 0
    # tpu path agrees on the selection
    batched_t = sct.apply("hvg.select", d.device_put(), backend="tpu",
                          n_top=50, flavor="seurat_v3",
                          batch_key="sample")
    a = np.asarray(batched.var["highly_variable"])
    b = np.asarray(batched_t.var["highly_variable"])
    assert (a == b).mean() > 0.98
    # subset=True materialises the combined selection
    subd = sct.apply("hvg.select", d, backend="cpu", n_top=50,
                     flavor="seurat_v3", batch_key="sample",
                     subset=True)
    assert subd.n_genes == 50


def test_filter_max_bounds():
    """scanpy parity: max_genes/max_counts (cells) and
    max_cells/max_counts (genes) upper bounds."""
    from sctools_tpu.data.synthetic import synthetic_counts

    d = synthetic_counts(300, 200, density=0.2, n_clusters=2, seed=5)
    d = sct.apply("qc.per_cell_metrics", d, backend="cpu")
    ng = np.asarray(d.obs["n_genes"])
    hi = int(np.percentile(ng, 90))
    f = sct.apply("qc.filter_cells", d, backend="cpu", max_genes=hi)
    assert f.n_cells == int((ng <= hi).sum())
    f2 = sct.apply("qc.filter_cells", d.device_put(), backend="tpu",
                   max_genes=hi)
    assert f2.n_cells == f.n_cells
    nc = np.asarray(sct.apply("qc.per_gene_metrics", d,
                              backend="cpu").var["n_cells"])
    hic = int(np.percentile(nc, 80))
    g = sct.apply("qc.filter_genes", d, backend="cpu", min_cells=None,
                  max_cells=hic)
    assert g.n_genes == int((nc <= hic).sum())
    g2 = sct.apply("qc.filter_genes", d.device_put(), backend="tpu",
                   min_cells=None, max_cells=hic)
    assert g2.n_genes == g.n_genes


def test_hvg_pearson_residuals_flavor():
    """scanpy experimental flavor='pearson_residuals' (Lause 2021):
    clipped-residual variance on RAW counts.  The k-sparse ELL path
    (dense zero-baseline + stored-entry correction) must match the
    dense oracle, and biology must rank above depth: cluster-marker
    genes beat flat housekeeping genes whose counts only track cell
    depth."""
    from sctools_tpu.data.synthetic import synthetic_counts

    d = synthetic_counts(500, 800, density=0.1, n_clusters=4, seed=3)
    c = sct.apply("hvg.select", d, backend="cpu", n_top=100,
                  flavor="pearson_residuals")
    t = sct.apply("hvg.select", d.device_put(), backend="tpu",
                  n_top=100, flavor="pearson_residuals")
    sc_c = np.asarray(c.var["hvg_score"])
    sc_t = np.asarray(t.var["hvg_score"])
    np.testing.assert_allclose(sc_t, sc_c, rtol=1e-4, atol=1e-5)
    np.testing.assert_array_equal(
        np.asarray(c.var["highly_variable"]),
        np.asarray(t.var["highly_variable"]))

    # a hand-built contrast: marker gene (on in half the cells at 10x)
    # vs housekeeping gene (same expected depth share everywhere)
    rng = np.random.default_rng(0)
    n = 400
    depth = rng.uniform(0.5, 2.0, n)
    X = rng.poisson(np.outer(depth, np.full(50, 2.0))).astype(np.float32)
    marker = rng.poisson(depth * np.where(np.arange(n) < 200, 10.0, 0.3))
    X[:, 7] = marker
    from sctools_tpu.data.dataset import CellData

    dd = CellData(X)
    out = sct.apply("hvg.select", dd, backend="cpu", n_top=5,
                    flavor="pearson_residuals")
    rank = np.asarray(out.var["hvg_rank"])
    assert rank[7] == 0  # the marker dominates every flat gene
