"""Transform registry: registration, dispatch, pipelines."""

import numpy as np
import pytest

import sctools_tpu as sct
from sctools_tpu import registry


def test_known_transforms_present():
    names = sct.names()
    for expected in [
        "normalize.library_size", "normalize.log1p", "qc.per_cell_metrics",
        "hvg.select", "distance.pairwise", "neighbors.knn", "pca.randomized",
    ]:
        assert expected in names, f"{expected} missing from registry"
        assert set(sct.backends(expected)) >= {"cpu", "tpu"}


def test_unknown_name():
    with pytest.raises(registry.UnknownTransformError):
        sct.get("no.such.op")


def test_unknown_backend():
    with pytest.raises(registry.UnknownBackendError):
        sct.get("normalize.log1p", backend="cuda")


def test_transform_binding():
    t = sct.Transform("normalize.library_size", backend="cpu", target_sum=100.0)
    ds = sct.data.synthetic.synthetic_counts(30, 40, seed=1)
    out = t(ds)
    totals = np.asarray(out.X.sum(axis=1)).ravel()
    np.testing.assert_allclose(totals, 100.0, rtol=1e-5)


def test_custom_registration():
    @sct.register("test.double", backend="cpu")
    def _double(data):
        return data.with_X(data.X * 2)

    ds = sct.from_dense(np.ones((3, 4), np.float32))
    out = sct.apply("test.double", ds, backend="cpu")
    np.testing.assert_allclose(out.X, 2.0)


def test_pipeline_runs_both_backends():
    ds = sct.data.synthetic.synthetic_counts(64, 128, seed=2)
    pipe = sct.Pipeline([
        ("qc.per_cell_metrics", {}),
        ("normalize.library_size", {"target_sum": 1e4}),
        ("normalize.log1p", {}),
    ])
    cpu_out = pipe.run(ds, backend="cpu")
    dev = ds.device_put()
    tpu_out = pipe.run(dev, backend="tpu").to_host()
    np.testing.assert_allclose(
        tpu_out.obs["total_counts"], cpu_out.obs["total_counts"], rtol=1e-4
    )
    np.testing.assert_allclose(
        tpu_out.X.toarray(), cpu_out.X.toarray(), rtol=1e-4, atol=1e-5
    )


def test_every_registered_op_is_documented():
    """docs/GUIDE.md (+ README) must name every registered op — the
    operator map is the contract reference users navigate by, and a
    silent omission means a shipped op nobody can find."""
    import os

    from sctools_tpu import registry

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    docs = ""
    for p in ("docs/GUIDE.md", "README.md"):
        with open(os.path.join(root, p)) as f:
            docs += f.read()
    ops = sorted({k[0] if isinstance(k, tuple) else k
                  for k in registry._REGISTRY}
                 - {"test.double"})  # registered by the test above
    assert len(ops) > 50
    missing = [o for o in ops if o not in docs]
    assert not missing, f"ops missing from docs: {missing}"


def test_api_docs_are_fresh():
    """docs/API.md is generated from the registry; regenerate and
    compare so a new op cannot ship with a stale reference page."""
    import os
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(root, "tools"))
    import gen_api_docs

    want = gen_api_docs.generate()
    with open(os.path.join(root, "docs", "API.md")) as f:
        got = f.read()
    assert got == want, ("docs/API.md is stale — run "
                         "python tools/gen_api_docs.py")
