"""concat: AnnData-style cell-axis concatenation."""

import numpy as np
import pytest
import scipy.sparse as sp

import sctools_tpu as sct
from sctools_tpu.data.dataset import CellData


def _cd(dense, genes, **obs):
    return CellData(sp.csr_matrix(np.asarray(dense, np.float32)),
                    obs={k: np.asarray(v) for k, v in obs.items()},
                    var={"gene_name": np.asarray(genes)})


def test_concat_inner_aligns_by_gene_name():
    a = _cd([[1, 2, 3], [4, 5, 6]], ["g1", "g2", "g3"],
            depth=[1.0, 2.0])
    b = _cd([[7, 8], [9, 10]], ["g3", "g1"], depth=[3.0, 4.0])
    out = sct.concat([a, b], join="inner", label="batch",
                     keys=["s1", "s2"])
    assert list(out.var["gene_name"]) == ["g1", "g3"]  # first's order
    want = np.array([[1, 3], [4, 6], [8, 7], [10, 9]], np.float32)
    np.testing.assert_array_equal(out.X.toarray(), want)
    np.testing.assert_array_equal(out.obs["depth"], [1, 2, 3, 4])
    assert list(out.obs["batch"]) == ["s1", "s1", "s2", "s2"]


def test_concat_outer_fills_zero():
    a = _cd([[1, 2]], ["g1", "g2"])
    b = _cd([[5]], ["g3"])
    out = sct.concat([a, b], join="outer")
    assert list(out.var["gene_name"]) == ["g1", "g2", "g3"]
    want = np.array([[1, 2, 0], [0, 0, 5]], np.float32)
    np.testing.assert_array_equal(out.X.toarray(), want)


def test_concat_obs_union_and_obsm_intersection():
    a = _cd([[1, 2]], ["g1", "g2"], score=[0.5])
    a = a.with_obsm(X_pca=np.ones((1, 4)), only_a=np.ones((1, 2)))
    b = _cd([[3, 4]], ["g1", "g2"], other=["x"])
    b = b.with_obsm(X_pca=np.zeros((1, 4)))
    out = sct.concat([a, b])
    # union obs: numeric filled with NaN, string with ""
    assert np.isnan(out.obs["score"][1])
    assert out.obs["other"][0] == ""
    assert out.obs["other"][1] == "x"
    # intersection obsm
    assert set(out.obsm) == {"X_pca"}
    assert out.obsm["X_pca"].shape == (2, 4)


def test_concat_layers_reindexed_like_X():
    a = _cd([[1, 2]], ["g1", "g2"]).with_layers(
        counts=sp.csr_matrix(np.array([[10, 20]], np.float32)))
    b = _cd([[3, 4]], ["g2", "g1"]).with_layers(
        counts=sp.csr_matrix(np.array([[30, 40]], np.float32)))
    out = sct.concat([a, b], join="inner")
    np.testing.assert_array_equal(
        out.layers["counts"].toarray(), [[10, 20], [40, 30]])


def test_concat_positional_when_no_gene_names():
    a = CellData(sp.csr_matrix(np.eye(2, 3, dtype=np.float32)))
    b = CellData(sp.csr_matrix(np.ones((1, 3), np.float32)))
    out = sct.concat([a, b])
    assert out.shape == (3, 3)
    c = CellData(sp.csr_matrix(np.ones((1, 4), np.float32)))
    with pytest.raises(ValueError, match="differing gene counts"):
        sct.concat([a, c])


def test_concat_feeds_integration():
    """The label column drives integrate.harmony end-to-end."""
    from sctools_tpu.data.synthetic import synthetic_counts

    full = synthetic_counts(400, 300, density=0.1, n_clusters=3, seed=0)
    X = full.X.tocsr()
    a, b = full.with_X(X[:200]), full.with_X(X[200:])
    merged = sct.concat([a, b], label="sample", keys=["runA", "runB"])
    assert merged.n_cells == 400
    merged = sct.apply("normalize.library_size", merged, backend="cpu")
    merged = sct.apply("normalize.log1p", merged, backend="cpu")
    merged = sct.apply("pca.randomized", merged, backend="cpu",
                       n_components=10)
    out = sct.apply("integrate.harmony", merged, backend="cpu",
                    batch_key="sample", n_clusters=5)
    assert out.obsm["X_harmony"].shape == (400, 10)


def test_concat_rejects_duplicate_gene_names():
    a = _cd([[1, 2]], ["g1", "g1"])
    b = _cd([[3, 4]], ["g1", "g2"])
    with pytest.raises(ValueError, match="duplicate gene names"):
        sct.concat([a, b])


def test_concat_keys_require_label():
    a = _cd([[1, 2]], ["g1", "g2"])
    with pytest.raises(ValueError, match="label="):
        sct.concat([a, a], keys=["s1", "s2"])


def test_concat_preserves_first_var_columns():
    a = _cd([[1, 2]], ["g1", "g2"])
    a = a.with_var(highly_variable=np.array([True, False]),
                   feature_type=np.array(["gex", "gex"]))
    b = _cd([[3, 4, 5]], ["g2", "g3", "g1"])
    out = sct.concat([a, b], join="outer")
    assert list(out.var["gene_name"]) == ["g1", "g2", "g3"]
    hv = out.var["highly_variable"]
    assert hv[0] == 1.0 and hv[1] == 0.0 and np.isnan(hv[2])
    assert list(out.var["feature_type"]) == ["gex", "gex", ""]
