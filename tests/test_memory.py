"""Memory as a fault domain — the RESOURCE failure class, the learned
peak-estimate model, the budgeted admission ledger, the runner's OOM
containment ladder (unfuse → replan-smaller → cpu), standing resident
reservations, and the memory-adversarial acceptance soak.  Everything
timing-shaped runs on one VirtualClock — zero real sleeps."""

import json
import os
import sys
import threading
import warnings

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import sctools_tpu as sct  # noqa: E402
from sctools_tpu import memory  # noqa: E402
from sctools_tpu.data.shardstore import write_store  # noqa: E402
from sctools_tpu.data.synthetic import synthetic_counts  # noqa: E402
from sctools_tpu.memory import (MemoryBudget,  # noqa: E402
                                default_estimates, estimate_run_peak,
                                heuristic_estimate, step_estimate,
                                step_sig)
from sctools_tpu.plan import fused_pipeline  # noqa: E402
from sctools_tpu.registry import (Pipeline, Transform,  # noqa: E402
                                  register)
from sctools_tpu.runner import ResilientRunner  # noqa: E402
from sctools_tpu.scheduler import (RunRejected,  # noqa: E402
                                   RunScheduler)
from sctools_tpu.serving import (AnnotationService,  # noqa: E402
                                 build_reference_artifact)
from sctools_tpu.utils.chaos import ChaosMonkey, Fault  # noqa: E402
from sctools_tpu.utils.failsafe import (DETERMINISTIC,  # noqa: E402
                                        RESOURCE, TRANSIENT,
                                        BreakerRegistry,
                                        DeviceOOMError,
                                        classify_child_result,
                                        classify_error)
from sctools_tpu.utils.telemetry import MetricsRegistry  # noqa: E402
from sctools_tpu.utils.vclock import VirtualClock  # noqa: E402

from soak_smoke import check_journal_coherent  # noqa: E402

OK_PROBE = {"ok": True, "device_kind": "test", "wall_s": 0.0}


# ---------------------------------------------------------------------------
# fixtures: test ops with memory metadata
# ---------------------------------------------------------------------------

def _declared_cost(params, input_bytes):
    """mem_cost callable: the op declares its own peak outright."""
    return int(params.get("mem_bytes", input_bytes))


def _block_shrink(params):
    b = int(params.get("block", 256))
    if b <= 32:
        return None
    params["block"] = b // 2
    return params


@pytest.fixture(scope="module")
def mem_ops():
    """Memory-domain test transforms under the reserved ``test.``
    prefix, removed on module teardown so registry-wide gates never
    see them."""
    names = []

    def reg(name, fn, **meta):
        register(name, backend="cpu", **meta)(fn)
        register(name, backend="tpu", **meta)(fn)
        names.append(name)

    def _passthrough(data, **kw):
        return data

    # fusable pair — the unfuse rung's target
    reg("test.mem_fa", _passthrough, fusable=True, mem_cost=3.0)
    reg("test.mem_fb", _passthrough, fusable=True)
    # shrinkable op — the replan rung's target (fusable so the
    # full-walk test can drive unfuse → replan on one chain)
    reg("test.mem_shrinkable", _passthrough, fusable=True,
        mem_shrink=_block_shrink)
    # declared-cost op — deterministic admission estimates
    reg("test.mem_sized", _passthrough, mem_cost=_declared_cost)
    # plain op — the cpu rung's target
    reg("test.mem_plain", _passthrough)
    yield
    registry_mod = __import__("sctools_tpu.registry",
                              fromlist=["_REGISTRY"])
    for n in names:
        registry_mod._REGISTRY.pop(n, None)
        registry_mod._DOCS.pop(n, None)
        registry_mod._FUSABLE.pop(n, None)
        registry_mod._MEM_COST.pop(n, None)
        registry_mod._MEM_SHRINK.pop(n, None)


@pytest.fixture(autouse=True)
def _reset_estimates():
    """The estimate store is process-shared BY DESIGN (corrections
    must outlive pipelines); across tests that is a leak — an
    OOM-corrected estimate from one test would change another's
    admission rulings."""
    yield
    default_estimates().reset()


def _data(n=64, g=32):
    return synthetic_counts(n, g, density=0.2, seed=0)


def _journal(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


def _runner(pipe, clock, m, chaos=None, **kw):
    kw.setdefault("sleep", lambda s: None)
    kw.setdefault("probe", lambda: dict(OK_PROBE))
    return ResilientRunner(pipe, clock=clock, metrics=m, chaos=chaos,
                           **kw)


# ---------------------------------------------------------------------------
# RESOURCE classification — the XlaRuntimeError message-shape corpus
# ---------------------------------------------------------------------------

def test_classify_resource_message_shapes():
    """jaxlib raises ONE XlaRuntimeError class for every status; the
    message is the only signal.  This is the observed OOM corpus."""
    for msg in (
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "12884901888 bytes.",
        "RESOURCE_EXHAUSTED: Error allocating device buffer: "
        "Attempting to allocate 1.20G. That was not possible.",
        "Resource exhausted: Out of memory",
        "Ran out of memory in memory space hbm. Used 16.20G of "
        "15.48G hbm.",
        "XlaRuntimeError: RESOURCE_EXHAUSTED: Allocation failure",
    ):
        assert classify_error(RuntimeError(msg)) == RESOURCE, msg


def test_classify_resource_explicit_type_and_precedence():
    # the explicit assertion type
    assert classify_error(DeviceOOMError("chaos oom")) == RESOURCE
    # TYPE beats message: a ValueError mentioning OOM is still a
    # program error — retrying OR laddering it would be wrong
    assert classify_error(ValueError("config asked for out of memory "
                                     "stress")) == DETERMINISTIC
    # RESOURCE markers beat transient markers: an OOM whose message
    # also carries connection noise must not be blindly retried
    assert classify_error(RuntimeError(
        "RESOURCE_EXHAUSTED: out of memory; transfer aborted")) \
        == RESOURCE
    # the transient set is unchanged
    assert classify_error(RuntimeError("UNAVAILABLE: socket closed")) \
        == TRANSIENT


def test_classify_child_oom_tail():
    """An isolated child dying on an OOM classifies RESOURCE in the
    parent — the ladder, not blind retry, answers contained OOMs
    too."""
    res = {"status": "crashed", "rc": 1, "wall_s": 0.1,
           "stderr_tail": "Traceback (most recent call last):\n"
                          "  ...\njaxlib.xla_extension.XlaRuntimeError:"
                          " RESOURCE_EXHAUSTED: Out of memory while "
                          "trying to allocate 8589934592 bytes."}
    exc = classify_child_result(res, "hvg.select")
    assert isinstance(exc, DeviceOOMError)
    assert classify_error(exc) == RESOURCE
    # no traceback but an OOM signature (TPU runtime abort text)
    res2 = {"status": "crashed", "rc": -6, "wall_s": 0.1,
            "stderr_tail": "Ran out of memory in memory space hbm."}
    assert isinstance(classify_child_result(res2, "x"), DeviceOOMError)


# ---------------------------------------------------------------------------
# the estimate model
# ---------------------------------------------------------------------------

def test_heuristic_estimates_fused_vs_chain(mem_ops):
    nbytes = 10_000
    fa = Transform("test.mem_fa", backend="tpu")     # mem_cost 3.0
    fb = Transform("test.mem_fb", backend="tpu")     # default 2.0
    # eager: input × mem_cost
    assert heuristic_estimate(fa, nbytes) == 30_000
    assert heuristic_estimate(fb, nbytes) == 20_000
    fused = fused_pipeline(Pipeline([fa, fb])).steps[0]
    assert fused.name.startswith("fused:")
    # fused: 1 + Σ(m−1) = 1 + 2 + 1 = 4 → every intermediate live
    assert heuristic_estimate(fused, nbytes) == 40_000
    # unfused chain: max(m) — intermediates free between members
    assert heuristic_estimate(fused.unfuse(), nbytes) == 30_000


def test_step_sig_stable_across_rebuilt_objects(mem_ops):
    a = Transform("test.mem_fa", backend="tpu", k=3)
    b = Transform("test.mem_fa", backend="tpu", k=3)
    assert step_sig(a, 5000) == step_sig(b, 5000)
    # same power-of-two bucket → same key; different bucket → not
    assert step_sig(a, 5000) == step_sig(a, 8192)
    assert step_sig(a, 5000) != step_sig(a, 9000)
    # params separate keys
    assert step_sig(a, 5000) != step_sig(
        Transform("test.mem_fa", backend="tpu", k=4), 5000)


def test_registry_mem_metadata_accessors(mem_ops):
    from sctools_tpu.registry import mem_cost_of, mem_shrink_of

    # numeric metadata → tagged multiplier
    assert mem_cost_of("test.mem_fa", "tpu") == ("mult", 3.0)
    # callable metadata needs input bytes; without them the caller
    # falls back to the default multiplier
    assert mem_cost_of("test.mem_sized", "tpu",
                       {"mem_bytes": 777}, input_bytes=10) \
        == ("bytes", 777)
    assert mem_cost_of("test.mem_sized", "tpu",
                       {"mem_bytes": 777}) is None
    assert mem_cost_of("test.mem_plain", "tpu") is None
    # shrink halves toward the floor; AT the floor it returns None —
    # and so does a shrink that changes nothing (ladder must not loop)
    assert mem_shrink_of("test.mem_shrinkable", "tpu",
                         {"block": 256}) == {"block": 128}
    assert mem_shrink_of("test.mem_shrinkable", "tpu",
                         {"block": 32}) is None
    assert mem_shrink_of("test.mem_plain", "tpu", {}) is None


def test_estimate_run_peak_per_step(mem_ops):
    data = _data(8, 4)
    pipe = Pipeline([("test.mem_sized", {"mem_bytes": 9_000}),
                     ("test.mem_fa", {})])
    est = estimate_run_peak(pipe, data)
    assert [s["name"] for s in est["per_step"]] == \
        ["test.mem_sized", "test.mem_fa"]
    assert est["per_step"][0]["bytes"] == 9_000
    # the run peak is the max over steps (sequential execution),
    # floored at the input's own resident bytes
    assert est["bytes"] == max(s["bytes"] for s in est["per_step"])


def test_estimate_record_and_inflate(mem_ops):
    est = memory.MemoryEstimates()
    t = Transform("test.mem_plain", backend="tpu")
    sig = step_sig(t, 1000)
    est.record(sig, 5000, source="compiled")
    assert step_estimate(t, 1000, est) == {"bytes": 5000,
                                           "source": "compiled"}
    # inflate doubles and marks corrected
    assert est.inflate(sig, 5000) == 10000
    assert step_estimate(t, 1000, est)["source"] == "corrected"
    # a later compiled record must NOT deflate a correction — the
    # device's refusal outranks the compiler's declaration
    est.record(sig, 4000, source="compiled")
    assert step_estimate(t, 1000, est)["bytes"] == 10000


def test_compiled_estimate_recorded_and_within_factor(mem_ops):
    """The accuracy satellite: a canned fused plan's recorded
    estimate comes from compiled.memory_analysis(), and the mem_cost
    heuristic is within the documented HEURISTIC_ACCURACY_FACTOR of
    it."""
    from sctools_tpu.plan import cache_info, clear_plan_cache

    clear_plan_cache()
    data = _data(256, 64).device_put()
    pipe = Pipeline([("normalize.library_size", {}),
                     ("normalize.log1p", {})])
    fused = fused_pipeline(pipe)
    stage = fused.steps[0]
    input_bytes = memory.data_nbytes(data)
    heur = step_estimate(stage, input_bytes)
    assert heur["source"] == "heuristic"
    fused.run(data)
    # the plan-cache entry recorded the compiled peak...
    entries = [e for e in cache_info()["entries"]
               if e.get("peak_bytes")]
    assert entries, "no plan-cache entry recorded a peak estimate"
    # ...and the estimate store serves it for a REBUILT stage.  The
    # stage's traced input bytes differ from the CellData total by
    # the opaque leaves — accept either the compiled record (same
    # size bucket) or the heuristic (bucket moved), but the compiled
    # number must exist in the store under the stage's own sig
    rec = step_estimate(stage, input_bytes)
    actual = entries[0]["peak_bytes"]
    assert actual > 0
    f = memory.HEURISTIC_ACCURACY_FACTOR
    assert actual / f <= heur["bytes"] <= actual * f, (
        f"heuristic {heur['bytes']} vs compiled {actual} outside "
        f"the documented factor {f}")
    assert rec["bytes"] > 0


def test_oom_correction_persists_across_rebuilt_pipeline(mem_ops):
    """The self-correction satellite: an OOM observed at runtime
    inflates the stored estimate, and a REBUILT pipeline (fresh
    Transform objects) sees the inflated number."""
    clock = VirtualClock()
    m = MetricsRegistry(clock=clock)
    data = _data()
    before = estimate_run_peak(
        Pipeline([("test.mem_plain", {})]), data)["bytes"]
    chaos = ChaosMonkey([Fault("test.mem_plain", "oom",
                               backend="tpu", times=1)], clock=clock)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        _runner(Pipeline([("test.mem_plain", {})]), clock, m,
                chaos=chaos).run(data, backend="tpu")
    after = estimate_run_peak(
        Pipeline([("test.mem_plain", {})]), data)["bytes"]
    assert after >= 2 * before
    snap = m.snapshot_compact()
    assert snap.get("mem.estimate_corrections", 0) >= 1
    assert snap.get("mem.oom_events{rung=cpu}", 0) == 1


# ---------------------------------------------------------------------------
# the budget
# ---------------------------------------------------------------------------

def test_budget_ledger_and_pressure():
    m = MetricsRegistry()
    b = MemoryBudget(1000, name="dev", metrics=m)
    assert b.available_bytes() == 1000
    b.reserve("run:1", 400, tenant="a")
    b.reserve("resident", 300, standing=True)
    assert b.reserved_bytes() == 700
    assert b.standing_bytes() == 300
    # admission feasibility excludes dynamic holds AND pressure
    assert b.admissible_bytes() == 700
    assert b.fits(300) and not b.fits(301)
    b.set_pressure(0.5)  # apparent capacity 500 < held 700
    assert not b.fits(1)
    assert b.admissible_bytes() == 700  # pressure ignored on purpose
    b.clear_pressure()
    # re-reserving a name REPLACES the amount
    b.reserve("run:1", 100, tenant="a")
    assert b.reserved_bytes() == 400
    b.release("run:1")
    b.release("run:1")  # idempotent
    assert b.reserved_bytes() == 300
    assert b.peak_reserved_bytes == 700
    snap = b.snapshot()
    assert snap["holders"]["resident"]["standing"] is True
    assert m.snapshot()["gauges"]["mem.budget_bytes"] == 1000


def test_budget_env_cap_detection(monkeypatch):
    monkeypatch.setenv("SCTOOLS_MEM_BUDGET_BYTES", "4096")
    b = MemoryBudget()
    assert b.capacity_bytes == 4096
    monkeypatch.setenv("SCTOOLS_MEM_BUDGET_BYTES", "not-a-number")
    with pytest.raises(ValueError):
        MemoryBudget()
    # CPU devices report no bytes_limit → explicit capacity required
    monkeypatch.delenv("SCTOOLS_MEM_BUDGET_BYTES")
    with pytest.raises(ValueError):
        MemoryBudget()


def test_budget_scope_thread_local():
    b = MemoryBudget(100)
    assert memory.current_budget() is None
    with memory.budget_scope(b):
        assert memory.current_budget() is b
        seen = []
        th = threading.Thread(
            target=lambda: seen.append(memory.current_budget()))
        th.start()
        th.join()
        assert seen == [None]  # never leaks across threads
    assert memory.current_budget() is None


# ---------------------------------------------------------------------------
# the runner's OOM containment ladder
# ---------------------------------------------------------------------------

def test_oom_ladder_unfuse_rung(mem_ops, tmp_path):
    clock = VirtualClock()
    m = MetricsRegistry(clock=clock)
    chaos = ChaosMonkey([Fault("test.mem_fa", "oom", backend="tpu",
                               times=1)], clock=clock)
    r = _runner(Pipeline([("test.mem_fa", {}), ("test.mem_fb", {})]),
                clock, m, chaos=chaos, fuse=True,
                checkpoint_dir=str(tmp_path / "ck"))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        out = r.run(_data(), backend="tpu")
    assert out is not None and r.report.status == "completed"
    assert not r.report.degraded  # stayed on the accelerator
    degrades = [e for e in _journal(r.journal.path)
                if e["event"] == "degrade"]
    assert [e["rung"] for e in degrades] == ["unfuse"]
    assert degrades[0]["reason"] == "oom"
    assert degrades[0]["from_bytes"] > 0
    # unfused chain peak < fused peak — the rung's whole point
    assert degrades[0]["to_bytes"] < degrades[0]["from_bytes"]
    assert m.snapshot_compact()["mem.oom_events{rung=unfuse}"] == 1


def test_oom_ladder_replan_rung(mem_ops, tmp_path):
    clock = VirtualClock()
    m = MetricsRegistry(clock=clock)
    chaos = ChaosMonkey([Fault("test.mem_shrinkable", "oom",
                               backend="tpu", times=1)], clock=clock)
    r = _runner(Pipeline([("test.mem_shrinkable", {"block": 256})]),
                clock, m, chaos=chaos,
                checkpoint_dir=str(tmp_path / "ck"))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        r.run(_data(), backend="tpu")
    assert r.report.status == "completed" and not r.report.degraded
    degrades = [e for e in _journal(r.journal.path)
                if e["event"] == "degrade"]
    assert [e["rung"] for e in degrades] == ["replan"]
    # the shrunk params moved the step fingerprint (checkpoints from
    # the larger plan never mix)
    assert degrades[0]["fingerprint"] == r.report.steps[0].fingerprint


def test_oom_ladder_cpu_rung_and_bottom_fail(mem_ops, tmp_path):
    clock = VirtualClock()
    m = MetricsRegistry(clock=clock)
    # tpu-only persistent OOM → cpu rung completes
    chaos = ChaosMonkey([Fault("test.mem_plain", "oom",
                               backend="tpu", times=-1)], clock=clock)
    r = _runner(Pipeline([("test.mem_plain", {})]), clock, m,
                chaos=chaos, checkpoint_dir=str(tmp_path / "ck1"))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        r.run(_data(), backend="tpu")
    assert r.report.status == "completed"
    assert r.report.degraded and r.report.backend == "cpu"
    assert [e["rung"] for e in _journal(r.journal.path)
            if e["event"] == "degrade"] == ["cpu"]
    # the OOM never fed the breaker — a full device is not an outage
    assert r.report.breaker["state"] == "closed"
    assert r.report.breaker["failures_in_window"] == 0

    # both backends OOM → bottom-rung recurrence is deterministic
    chaos2 = ChaosMonkey([Fault("test.mem_plain", "oom", times=-1)],
                         clock=clock)
    r2 = _runner(Pipeline([("test.mem_plain", {})]), clock, m,
                 chaos=chaos2, checkpoint_dir=str(tmp_path / "ck2"))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(DeviceOOMError):
            r2.run(_data(), backend="tpu")
    assert r2.report.status == "failed"
    evs = _journal(r2.journal.path)
    assert evs[-1]["event"] == "run_failed"
    assert evs[-1]["classified"] == "resource"
    snap = m.snapshot_compact()
    assert snap["mem.oom_events{rung=fail}"] == 1


def test_oom_ladder_sharded_stage_never_unfuses(mem_ops, tmp_path):
    """A mesh-sharded fused stage must NOT take the unfuse rung: the
    unfused chain runs single-device, concentrating the whole sharded
    input onto one device — a guaranteed re-OOM.  Sharded stages go
    straight past unfuse (replan when shrinkable, else cpu)."""
    from sctools_tpu.parallel import make_mesh

    clock = VirtualClock()
    m = MetricsRegistry(clock=clock)
    chaos = ChaosMonkey([Fault("test.mem_fa", "oom", backend="tpu",
                               times=-1)], clock=clock)
    r = _runner(Pipeline([("test.mem_fa", {}), ("test.mem_fb", {})]),
                clock, m, chaos=chaos, fuse=True, mesh=make_mesh(2),
                checkpoint_dir=str(tmp_path / "ck"))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        r.run(_data(), backend="tpu")
    assert r.report.status == "completed"
    rungs = [e["rung"] for e in _journal(r.journal.path)
             if e["event"] == "degrade" and e.get("reason") == "oom"]
    assert "unfuse" not in rungs
    assert rungs[-1] == "cpu"


def test_oom_ladder_without_fallback_backend(mem_ops, tmp_path):
    """unfuse/replan are SAME-backend rungs: a runner that forbids
    the cpu degrade (fallback_backend=None) must still walk them —
    only the cpu rung needs a fallback, and the bottom rung is then
    fail instead."""
    clock = VirtualClock()
    m = MetricsRegistry(clock=clock)
    chaos = ChaosMonkey([Fault("test.mem_fa", "oom", backend="tpu",
                               times=1)], clock=clock)
    r = _runner(Pipeline([("test.mem_fa", {}), ("test.mem_fb", {})]),
                clock, m, chaos=chaos, fuse=True,
                fallback_backend=None,
                checkpoint_dir=str(tmp_path / "ck"))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        r.run(_data(), backend="tpu")
    assert r.report.status == "completed"
    assert [e["rung"] for e in _journal(r.journal.path)
            if e["event"] == "degrade"] == ["unfuse"]

    # persistent OOM with no fallback: unfuse fires, then fail — the
    # run never silently lands on a forbidden backend
    chaos2 = ChaosMonkey([Fault("test.mem_*", "oom", backend="tpu",
                                times=-1)], clock=clock)
    r2 = _runner(Pipeline([("test.mem_fa", {}), ("test.mem_fb", {})]),
                 clock, m, chaos=chaos2, fuse=True,
                 fallback_backend=None,
                 checkpoint_dir=str(tmp_path / "ck2"))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(DeviceOOMError):
            r2.run(_data(), backend="tpu")
    rungs = [e["rung"] for e in _journal(r2.journal.path)
             if e["event"] == "degrade"]
    assert rungs == ["unfuse"]
    assert r2.report.status == "failed"
    assert all(a.backend == "tpu" for s in r2.report.steps
               for a in s.attempts)


def test_oom_ladder_full_walk_one_step(mem_ops, tmp_path):
    """One fused step OOMing repeatedly walks EVERY rung in order:
    unfuse → replan (twice — block 256→128→64) → cpu."""
    clock = VirtualClock()
    m = MetricsRegistry(clock=clock)
    chaos = ChaosMonkey([Fault("test.mem_*", "oom", backend="tpu",
                               times=-1)], clock=clock)
    pipe = Pipeline([("test.mem_fa", {}),
                     ("test.mem_shrinkable", {"block": 128})])
    r = _runner(pipe, clock, m, chaos=chaos, fuse=True,
                checkpoint_dir=str(tmp_path / "ck"))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        r.run(_data(), backend="tpu")
    assert r.report.status == "completed"
    assert r.report.degraded and r.report.backend == "cpu"
    rungs = [e["rung"] for e in _journal(r.journal.path)
             if e["event"] == "degrade"]
    # fused stage unfuses, the shrinkable member replans 128→64→32,
    # then the step leaves the accelerator
    assert rungs[0] == "unfuse"
    assert rungs[-1] == "cpu"
    assert "replan" in rungs


# ---------------------------------------------------------------------------
# budgeted admission
# ---------------------------------------------------------------------------

def _sched(clock, m, budget, jpath, chaos=None, **kw):
    kw.setdefault("max_concurrency", 2)
    return RunScheduler(
        clock=clock, metrics=m, journal_path=jpath,
        breakers=BreakerRegistry(clock=clock), chaos=chaos,
        mem_budget=budget,
        runner_defaults={"sleep": lambda s: None,
                         "probe": lambda: dict(OK_PROBE)}, **kw)


def test_admission_rejects_infeasible_run(mem_ops, tmp_path):
    clock = VirtualClock()
    m = MetricsRegistry(clock=clock)
    budget = MemoryBudget(10_000, name="dev", metrics=m)
    jpath = str(tmp_path / "journal.jsonl")
    with _sched(clock, m, budget, jpath) as s:
        with pytest.raises(RunRejected) as ei:
            s.submit(Pipeline([("test.mem_sized",
                                {"mem_bytes": 50_000})]),
                     _data(8, 4), backend="cpu")
        assert ei.value.reason == "over_memory"
        # feasible work is untouched
        h = s.submit(Pipeline([("test.mem_sized",
                                {"mem_bytes": 5_000})]),
                     _data(8, 4), backend="cpu")
        h.result(timeout=60)
    evs = _journal(jpath)
    assert [e for e in evs if e["event"] == "rejected"][0]["reason"] \
        == "over_memory"
    assert m.snapshot_compact()[
        "sched.rejected{reason=over_memory,tenant=default}"] == 1


def test_over_budget_work_queues_not_co_schedules(mem_ops, tmp_path):
    """Two runs that each fit but cannot fit TOGETHER serialize: the
    second queues until the first releases — never an OOM-shaped
    co-schedule, proven by the reservation high-water."""
    clock = VirtualClock()
    m = MetricsRegistry(clock=clock)
    budget = MemoryBudget(10_000, name="dev", metrics=m)
    gate = threading.Event()
    started = threading.Event()

    def _block(data, **kw):
        started.set()
        gate.wait(60)
        return data

    register("test.mem_block", backend="cpu",
             mem_cost=_declared_cost)(_block)
    register("test.mem_block", backend="tpu",
             mem_cost=_declared_cost)(_block)
    try:
        jpath = str(tmp_path / "journal.jsonl")
        with _sched(clock, m, budget, jpath, max_concurrency=4) as s:
            h1 = s.submit(Pipeline([("test.mem_block",
                                     {"mem_bytes": 6_000})]),
                          _data(8, 4), tenant="a", backend="cpu")
            assert started.wait(30)
            h2 = s.submit(Pipeline([("test.mem_block",
                                     {"mem_bytes": 6_000})]),
                          _data(8, 4), tenant="b", backend="cpu")
            assert h2.status == "queued"  # fits alone, not beside h1
            gate.set()
            h1.result(timeout=60)
            h2.result(timeout=60)
        assert budget.peak_reserved_bytes <= 10_000
        assert budget.reserved_bytes() == 0
        reserved = [e for e in _journal(jpath)
                    if e["event"] == "mem_reserved"]
        assert len(reserved) == 2
        assert all(e["reserved_total"] <= 10_000 for e in reserved)
    finally:
        registry_mod = __import__("sctools_tpu.registry",
                                  fromlist=["_REGISTRY"])
        registry_mod._REGISTRY.pop("test.mem_block", None)
        registry_mod._MEM_COST.pop("test.mem_block", None)


def test_standing_growth_sheds_queued_over_memory(mem_ops, tmp_path):
    """Admission promised feasibility-at-zero-concurrency; a standing
    resident that lands AFTER admission can break the promise — the
    queued item is shed ``over_memory`` instead of wedging the queue
    (and any draining shutdown) forever."""
    clock = VirtualClock()
    m = MetricsRegistry(clock=clock)
    budget = MemoryBudget(10_000, name="dev", metrics=m)
    gate = threading.Event()
    started = threading.Event()

    def _block(data, **kw):
        started.set()
        gate.wait(60)
        return data

    register("test.mem_block2", backend="cpu",
             mem_cost=_declared_cost)(_block)
    register("test.mem_block2", backend="tpu",
             mem_cost=_declared_cost)(_block)
    try:
        jpath = str(tmp_path / "journal.jsonl")
        with _sched(clock, m, budget, jpath, max_concurrency=1) as s:
            h1 = s.submit(Pipeline([("test.mem_block2",
                                     {"mem_bytes": 2_000})]),
                          _data(8, 4), backend="cpu")
            assert started.wait(30)
            h2 = s.submit(Pipeline([("test.mem_sized",
                                     {"mem_bytes": 8_000})]),
                          _data(8, 4), backend="cpu")
            # a resident arrives while h2 queues: 8k no longer fits
            # beside 5k standing at ANY concurrency
            budget.reserve("resident", 5_000, standing=True)
            gate.set()
            h1.result(timeout=60)
            with pytest.raises(RunRejected) as ei:
                h2.result(timeout=60)
            assert ei.value.reason == "over_memory"
    finally:
        registry_mod = __import__("sctools_tpu.registry",
                                  fromlist=["_REGISTRY"])
        registry_mod._REGISTRY.pop("test.mem_block2", None)
        registry_mod._MEM_COST.pop("test.mem_block2", None)


def test_chaos_mem_pressure_channel(mem_ops, tmp_path):
    clock = VirtualClock()
    monkey = ChaosMonkey([Fault("dev", "mem_pressure", on_call=2,
                                times=1)], clock=clock,
                         pressure_frac=0.25)
    # channel disjointness: a memory-mode fault never fires on the
    # op-call channel
    assert monkey._firing("dev", None, 2, channel="call") is None
    assert monkey.on_memory("dev") is None          # call 1
    ruling = monkey.on_memory("dev")                # call 2: fires
    assert ruling == {"mode": "mem_pressure", "pressure_frac": 0.25}
    assert monkey.on_memory("dev") is None          # window passed
    # spec round-trip carries pressure_frac
    clone = ChaosMonkey.from_spec(monkey.spec())
    assert clone.pressure_frac == 0.25

    # end to end: the firing submit shrinks the apparent budget, the
    # next submit restores it
    m = MetricsRegistry(clock=clock)
    budget = MemoryBudget(10_000, name="dev2", metrics=m)
    chaos = ChaosMonkey([Fault("dev2", "mem_pressure", on_call=1,
                               times=1)], clock=clock,
                        pressure_frac=0.5)
    with _sched(clock, m, budget, str(tmp_path / "j.jsonl"),
                chaos=chaos) as s:
        h1 = s.submit(Pipeline([("test.mem_sized",
                                 {"mem_bytes": 100})]),
                      _data(8, 4), backend="cpu")
        assert budget.pressure == 0.5
        h2 = s.submit(Pipeline([("test.mem_sized",
                                 {"mem_bytes": 100})]),
                      _data(8, 4), backend="cpu")
        assert budget.pressure == 1.0
        h1.result(timeout=60)
        h2.result(timeout=60)
    assert [f["mode"] for f in chaos.injected] == ["mem_pressure"]


# ---------------------------------------------------------------------------
# standing resident reservations
# ---------------------------------------------------------------------------

N_REF, N_GENES = 256, 48


def _artifact(tmp_path):
    ref = synthetic_counts(N_REF, N_GENES, density=0.15, n_clusters=3,
                           seed=0)
    labels = np.array([f"type{c}"
                       for c in np.asarray(ref.obs["cluster_true"])])
    ref = ref.with_obs(cell_type=labels)
    fitted = sct.run_recipe("annotation_reference", ref,
                            backend="cpu", n_components=8)
    path = str(tmp_path / "model.npz")
    build_reference_artifact(fitted, path, labels_key="cell_type",
                             seed=0, version="v1")
    return path


def test_serving_model_holds_standing_reservation(mem_ops, tmp_path):
    clock = VirtualClock()
    m = MetricsRegistry(clock=clock)
    budget = MemoryBudget(50_000_000, name="dev", metrics=m)
    path = _artifact(tmp_path)
    svc = AnnotationService(
        path, name="memsvc", backend="tpu", clock=clock, metrics=m,
        journal_path=str(tmp_path / "journal.jsonl"),
        mem_budget=budget, k=5,
        runner_defaults={"probe": lambda: dict(OK_PROBE)})
    try:
        held = budget.holders()
        assert "serve:memsvc:model" in held
        assert held["serve:memsvc:model"]["standing"] is True
        assert held["serve:memsvc:model"]["bytes"] > 0
        # admission headroom shrank by exactly the resident
        assert budget.admissible_bytes() == \
            budget.capacity_bytes - held["serve:memsvc:model"]["bytes"]
        evs = _journal(str(tmp_path / "journal.jsonl"))
        assert any(e["event"] == "mem_reserved" and e.get("standing")
                   for e in evs)
    finally:
        svc.close()
    assert "serve:memsvc:model" not in budget.holders()
    evs = _journal(str(tmp_path / "journal.jsonl"))
    assert any(e["event"] == "mem_released" and e.get("standing")
               for e in evs)


def test_train_feed_holds_named_run_reservation(mem_ops, tmp_path):
    from sctools_tpu.models.train_stream import fit_scvi_stream

    counts = synthetic_counts(256, 32, density=0.2, seed=0)
    store = write_store(counts.X, str(tmp_path / "store"),
                        shard_rows=64, chunk_rows=32)
    budget = MemoryBudget(100_000_000, name="dev")
    seen = {}
    admissible_during = []

    class _SpyJournal:
        def write(self, event, **fields):
            if event == "mem_reserved":
                # run-scoped, so DYNAMIC: the hold tightens dispatch
                # fitting but must not shrink the admission floor —
                # a standing feed would permanently shed queued work
                # that fits the moment training ends
                admissible_during.append(budget.admissible_bytes())
            if event.startswith("mem_"):
                seen.setdefault(event, []).append(fields)

    fit_scvi_stream(store, n_latent=2, n_hidden=8, epochs=1,
                    batch_size=64, seed=0, mem_budget=budget,
                    journal=_SpyJournal())
    # reserved for the run's lifetime, released on completion
    assert len(seen["mem_reserved"]) == 1
    res = seen["mem_reserved"][0]
    assert res["name"].startswith("train:feed:")
    # (prefetch_depth + 1) dense shards
    assert res["bytes"] == 3 * store.shard_rows * store.n_genes * 4
    assert admissible_during == [budget.capacity_bytes]
    assert len(seen["mem_released"]) == 1
    assert budget.reserved_bytes() == 0
    assert budget.peak_reserved_bytes == res["bytes"]


# ---------------------------------------------------------------------------
# sctreport memory section
# ---------------------------------------------------------------------------

def test_sctreport_memory_section(tmp_path):
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    from tools.sctreport import memory_section

    events = [
        {"event": "mem_reserved", "ticket": 0, "tenant": "lab-a",
         "bytes": 600, "reserved_total": 600, "ts": 1.0},
        {"event": "mem_reserved", "standing": True,
         "service": "svc", "bytes": 300, "reserved_total": 900,
         "ts": 1.5},
        {"event": "mem_reserved", "name": "train:feed:1",
         "bytes": 120, "reserved_total": 1020, "ts": 1.7},
        {"event": "mem_released", "ticket": 0, "tenant": "lab-a",
         "bytes": 600, "reserved_total": 300, "ts": 2.0},
        {"event": "degrade", "step": 1, "reason": "oom",
         "rung": "unfuse", "from_bytes": 4000, "to_bytes": 3000,
         "corrected_bytes": 8000, "ts": 2.5},
    ]
    metrics = {"metrics": {
        "counters": {"mem.oom_events{rung=unfuse}": 1.0,
                     "mem.estimate_corrections": 1.0},
        "gauges": {"mem.budget_bytes": 1000.0,
                   "mem.reserved_bytes": 300.0},
        "histograms": {},
    }}
    L = memory_section(events, metrics)
    text = "\n".join(L)
    assert L[0] == "-- memory --"
    assert "budget 1000 bytes" in text
    assert "high-water 1020" in text
    assert "lab-a" in text
    assert "svc" in text and "(standing)" in text
    assert "train:feed:1" in text
    assert "rung=unfuse" in text and "4000 -> 3000" in text
    assert "corrected to 8000" in text
    assert "estimate corrections (inflate-on-OOM): 1" in text
    # absence contract: no mem series → no section
    assert memory_section([], {"metrics": {"counters": {},
                                           "gauges": {},
                                           "histograms": {}}}) == []


# ---------------------------------------------------------------------------
# THE ACCEPTANCE SOAK — memory-adversarial multi-tenant traffic
# ---------------------------------------------------------------------------

def test_memory_adversarial_acceptance_soak(mem_ops, tmp_path):
    """The PR's acceptance criteria, end to end on ONE VirtualClock
    with zero real sleeps:

    * >= 20 concurrent mixed-size submissions — serving queries from
      three tenants through an AnnotationService sharing the pool,
      one PREEMPTIBLE out-of-core training job, and ladder-driving
      pipeline runs — under a budget that cannot hold half of their
      summed estimates at once;
    * chaos ``oom`` (tpu-only, several ops) and ``mem_pressure``
      faults mid-soak;
    * every ticket terminal exactly once with a journaled reason;
    * peak reserved bytes never exceed the budget;
    * at least one run COMPLETES through each containment-ladder
      rung (unfuse, replan-smaller, cpu);
    * an over-budget arrival is refused ``over_memory`` at admission.
    """
    clock = VirtualClock()
    m = MetricsRegistry(clock=clock)
    CAP = 40_000_000
    budget = MemoryBudget(CAP, name="hbm0", metrics=m)
    jpath = str(tmp_path / "journal.jsonl")
    chaos = ChaosMonkey(
        [Fault("test.mem_fa", "oom", backend="tpu", times=1),
         Fault("test.mem_shrinkable", "oom", backend="tpu", times=1),
         Fault("test.mem_plain", "oom", backend="tpu", times=-1),
         Fault("hbm0", "mem_pressure", on_call=8, times=4)],
        clock=clock, pressure_frac=0.6)
    sched = RunScheduler(
        max_concurrency=4, clock=clock, metrics=m,
        journal_path=jpath, breakers=BreakerRegistry(clock=clock),
        chaos=chaos, mem_budget=budget,
        runner_defaults={"sleep": lambda s: None,
                         "probe": lambda: dict(OK_PROBE)})

    # the resident reference model (standing reservation)
    svc = AnnotationService(_artifact(tmp_path), name="soaksvc",
                            backend="tpu", scheduler=sched, k=5)

    # the training store (tiny; the job is about the CONTRACT)
    counts = synthetic_counts(256, 32, density=0.2, seed=1)
    store_dir = str(tmp_path / "store")
    write_store(counts.X, store_dir, shard_rows=64, chunk_rows=32)

    handles, tickets, rejected = [], [], []
    ladder_dirs = {}
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")

        # 1 preemptible training job (low priority)
        handles.append(sched.submit(
            Pipeline([("model.scvi_stream",
                       {"store_dir": store_dir, "n_latent": 2,
                        "n_hidden": 8, "epochs": 1, "batch_size": 64,
                        "seed": 0,
                        "checkpoint": str(tmp_path / "cursor.npz")})]),
            _data(8, 4), tenant="train-lab", priority=0,
            backend="cpu", preemptible=True))

        # 3 ladder-driving runs, one per rung, each with its own
        # journal so the rung ruling is auditable
        for nick, pipe, kw in [
            ("unfuse", Pipeline([("test.mem_fa", {}),
                                 ("test.mem_fb", {})]),
             {"fuse": True}),
            ("replan", Pipeline([("test.mem_shrinkable",
                                  {"block": 256})]), {}),
            ("cpu", Pipeline([("test.mem_plain", {})]), {}),
        ]:
            d = str(tmp_path / f"ladder_{nick}")
            ladder_dirs[nick] = d
            handles.append(sched.submit(
                pipe, _data(), tenant=f"lab-{nick}", priority=1,
                backend="tpu",
                runner_kw={"checkpoint_dir": d, **kw}))

        # 8 bulk analyses with DECLARED peaks — the runs whose summed
        # estimates over-subscribe the budget 2×+, so dispatch must
        # serialize them (at most ~3 × 12M fit in 40M at once)
        for i in range(8):
            handles.append(sched.submit(
                Pipeline([("test.mem_sized",
                           {"mem_bytes": 12_000_000})]),
                _data(8, 4), tenant=f"bulk-{i % 2}", priority=1,
                backend="cpu"))

        # 16 serving queries, mixed sizes, three tenants, higher
        # priority than the training job (it must yield, not block)
        rng = np.random.default_rng(7)
        for i in range(16):
            n = int(rng.integers(3, 40))
            q = synthetic_counts(n, N_GENES, density=0.15, seed=100 + i)
            handles.append(svc.query(
                q, "label_transfer", tenant=f"lab-{i % 3}",
                priority=2))

        # the over-budget arrival: refused at the door
        with pytest.raises(RunRejected) as ei:
            sched.submit(Pipeline([("test.mem_sized",
                                    {"mem_bytes": CAP * 10})]),
                         _data(8, 4), tenant="greedy", backend="cpu")
        assert ei.value.reason == "over_memory"
        rejected.append(ei.value)

        # drain: every handle terminal
        for h in handles:
            obj = getattr(h, "handle", h)   # ServeTicket or RunHandle
            assert obj.wait(timeout=300), obj
            tickets.append(obj)
        results = []
        for h in handles:
            results.append(h.result(timeout=10))
        svc.close()
        sched.shutdown(wait=True)

    # --- every ticket terminal exactly once with a journaled reason
    n_tickets = len(handles) + 1    # + the rejected arrival
    assert n_tickets >= 21          # >= 20 submissions + rejection
    by_ticket = check_journal_coherent(jpath, n_tickets)
    assert len(by_ticket) == n_tickets

    # --- the budget held: peak reserved never exceeded capacity
    assert 0 < budget.peak_reserved_bytes <= CAP
    assert budget.reserved_bytes() == 0   # everything released
    evs = _journal(jpath)
    for e in evs:
        if e["event"] == "mem_reserved":
            assert e["reserved_total"] <= CAP

    # --- mixed sizes genuinely over-subscribed the budget: the
    # summed admitted estimates (+ standing residents) could not have
    # co-scheduled — the budget fits at most half of them at once
    admitted_bytes = sum(e.get("mem_bytes", 0) for e in evs
                         if e["event"] == "admitted")
    assert admitted_bytes > 2 * CAP

    # --- chaos fired on both memory channels
    modes = {f["mode"] for f in chaos.injected}
    assert "oom" in modes and "mem_pressure" in modes
    assert budget.pressure == 1.0   # episode over by shutdown

    # --- at least one run completed through EACH ladder rung
    for nick, rung in [("unfuse", "unfuse"), ("replan", "replan"),
                       ("cpu", "cpu")]:
        run_evs = _journal(os.path.join(ladder_dirs[nick],
                                        "journal.jsonl"))
        rungs = [e["rung"] for e in run_evs if e["event"] == "degrade"
                 and e.get("reason") == "oom"]
        assert rung in rungs, (nick, rungs)
        assert run_evs[-1]["event"] == "run_completed", nick
    snap = m.snapshot_compact()
    for rung in ("unfuse", "replan", "cpu"):
        assert snap.get(f"mem.oom_events{{rung={rung}}}", 0) >= 1

    # --- the training job terminal-completed (possibly after
    # preemption yields) and its feed reservation is gone
    train_result = results[0]
    assert train_result.uns["scvi_stream_epochs"] == 1
    assert not any(k.startswith("train:feed")
                   for k in budget.holders())

    # --- serving queries all completed on the resident model
    for res in results[12:]:
        assert res["labels"].shape[0] >= 1
    assert snap.get("serve.queries{outcome=completed}", 0) == 16
