"""Pod-scale fault domains (``sctools_tpu/federation.py``): the
cross-process breaker transport, the supervised worker pool, and the
lost-worker ladder (fence → requeue → respawn → resume).

Subprocess tests spawn REAL worker processes (each imports jax, so a
few seconds of startup each) and are kept few and combined; every
lease/age schedule runs on the injectable clock — the test process
itself waits only on event-driven handles, never a poll sleep.
"""

import json
import os
import warnings

import numpy as np
import pytest

from sctools_tpu.data.synthetic import synthetic_counts
from sctools_tpu.federation import (FederatedBreakerRegistry,
                                    FederatedRunError,
                                    FederationSupervisor, TicketHandle,
                                    worker_main, _Ticket, _Worker)
from sctools_tpu.registry import Pipeline
from sctools_tpu.scheduler import RunRejected, RunShed
from sctools_tpu.utils.chaos import ChaosMonkey, Fault
from sctools_tpu.utils.telemetry import MetricsRegistry
from sctools_tpu.utils.vclock import VirtualClock

from soak_smoke import check_journal_coherent


def _data(n=64, g=32, seed=0):
    return synthetic_counts(n, g, density=0.2, seed=seed)


def _pipe():
    return Pipeline([("normalize.library_size", {}),
                     ("normalize.log1p", {}),
                     ("qc.per_cell_metrics", {})], backend="tpu")


def _events(fed_dir):
    with open(os.path.join(fed_dir, "journal.jsonl")) as f:
        return [json.loads(line) for line in f]


# ------------------------------------------------------- breaker transport

def test_federated_breaker_trip_and_close_propagate(tmp_path):
    """One registry's trip forces every sharer open; one probe close
    returns the whole pool — the PR-8 contract across processes."""
    clk = VirtualClock()
    A = FederatedBreakerRegistry(str(tmp_path), clock=clk, owner="wA",
                                 failure_threshold=2, cooldown_s=30.0)
    B = FederatedBreakerRegistry(str(tmp_path), clock=clk, owner="wB",
                                 failure_threshold=2, cooldown_s=30.0)
    a, b = A.get("tpu"), B.get("tpu")
    assert a.allow() and b.allow()
    a.record_failure()
    assert b.allow()  # one failure: below threshold, nothing published
    a.record_failure()
    assert a.state == "open"
    assert b.state == "open" and not b.allow()  # the trip crossed over
    clk.advance(31.0)
    assert b.state == "half_open"
    assert b.try_acquire_probe()
    # A is also half-open now, but B holds the CROSS-PROCESS claim
    assert a.state == "half_open"
    assert a.try_acquire_probe() is False
    b.record_success()
    assert b.state == "closed"
    assert a.state == "closed" and a.allow()  # the close crossed back
    assert a.snapshot()["fed_epoch"] == 2  # open + close transitions


def test_federated_breaker_reopen_restarts_remote_cooldown(tmp_path):
    clk = VirtualClock()
    A = FederatedBreakerRegistry(str(tmp_path), clock=clk, owner="wA",
                                 failure_threshold=1, cooldown_s=10.0)
    B = FederatedBreakerRegistry(str(tmp_path), clock=clk, owner="wB",
                                 failure_threshold=1, cooldown_s=10.0)
    a, b = A.get("tpu"), B.get("tpu")
    a.record_failure()
    assert b.state == "open"
    clk.advance(11.0)
    assert a.state == "half_open"
    assert a.try_acquire_probe()
    a.record_failure()  # the probe lied: re-open, epoch bumps
    # B saw the re-publication: open again with a FRESH local cooldown
    assert b.state == "open" and not b.allow()
    clk.advance(11.0)
    assert b.state == "half_open"


def test_clear_probe_claims_frees_a_dead_workers_claim(tmp_path):
    clk = VirtualClock()
    A = FederatedBreakerRegistry(str(tmp_path), clock=clk, owner="wA",
                                 failure_threshold=1, cooldown_s=5.0)
    B = FederatedBreakerRegistry(str(tmp_path), clock=clk, owner="wB",
                                 failure_threshold=1, cooldown_s=5.0)
    a, b = A.get("tpu"), B.get("tpu")
    a.record_failure()
    assert b.state == "open"  # B observes NOW: its cooldown starts
    clk.advance(6.0)
    assert a.try_acquire_probe()      # wA holds the claim file...
    assert b.try_acquire_probe() is False
    assert A.clear_probe_claims("wA") == 1  # ...then wA dies: fenced
    assert b.try_acquire_probe()      # the pool recovers the slot


def test_registry_snapshot_covers_remote_signatures(tmp_path):
    clk = VirtualClock()
    A = FederatedBreakerRegistry(str(tmp_path), clock=clk, owner="wA",
                                 failure_threshold=1)
    B = FederatedBreakerRegistry(str(tmp_path), clock=clk, owner="wB",
                                 failure_threshold=1)
    A.get("tpu").record_failure()
    snap = B.snapshot()  # B never called get("tpu") itself
    assert snap["tpu"]["state"] == "open"


# ------------------------------------------------- fencing (no subprocess)

def _fake_supervisor(tmp_path):
    sup = FederationSupervisor(str(tmp_path), n_workers=1)
    w = _Worker("w0", 0, os.path.join(str(tmp_path), "workers", "w0"))
    os.makedirs(os.path.join(w.dir, "inbox"), exist_ok=True)
    h = TicketHandle("t000000", "default", 0)
    t = _Ticket(0, "default", 0, "tpu", [], {},
                os.path.join(str(tmp_path), "tickets", "t000000"),
                h, 0.0)
    os.makedirs(t.dir, exist_ok=True)
    sup._tickets[t.id] = t
    sup._workers["w0"] = w
    t.worker = w
    w.in_flight.append(t)
    return sup, w, t


def test_stale_epoch_commit_is_refused(tmp_path):
    """The fencing guard: a result tagged with a superseded epoch is
    journaled ``commit_refused`` and does NOT terminate the ticket —
    the current epoch's owner is the one that counts."""
    sup, w, t = _fake_supervisor(tmp_path)
    t.epoch = 1  # the supervisor already requeued past epoch 0
    sup._on_done(w, {"ticket": t.id, "epoch": "0",
                     "status": "completed"})
    assert not t.handle.done()
    evs = _events(str(tmp_path))
    assert [e["event"] for e in evs] == ["commit_refused"]
    assert evs[0]["by"] == "supervisor"
    # the CURRENT epoch's commit is accepted exactly once
    sup._on_done(w, {"ticket": t.id, "epoch": "1",
                     "status": "completed"})
    assert t.handle.done() and t.handle.status == "completed"


def test_duplicate_delivery_of_accepted_commit_dedupes_silently(
        tmp_path):
    """The result-file probe and the real ``done`` line race each
    other by design; the LOSER is a duplicate delivery of an
    ALREADY-ACCEPTED commit and must dedupe silently — journalling
    it ``commit_refused`` would pollute the at-most-once fencing
    evidence (and inflate ``fed.fenced_commits``) on every recovered
    commit."""
    m = MetricsRegistry()
    sup, w, t = _fake_supervisor(tmp_path)
    sup.metrics = m
    sup._on_done(w, {"ticket": t.id, "epoch": "0",
                     "status": "completed"})
    assert t.handle.done() and t.handle.status == "completed"
    # same commit delivered again (the doorbell arrived after the
    # probe): silent — not a fencing event
    sup._on_done(w, {"ticket": t.id, "epoch": "0",
                     "status": "completed"})
    evs = [e["event"] for e in _events(str(tmp_path))]
    assert evs == ["run_completed"]
    assert m.snapshot_compact().get("fed.fenced_commits", 0) == 0
    # a genuinely foreign commit still refuses on the record
    w2 = _Worker("w9", 0, os.path.join(str(tmp_path), "workers", "w9"))
    sup._workers["w9"] = w2
    sup._on_done(w2, {"ticket": t.id, "epoch": "0",
                      "status": "completed"})
    evs = [e["event"] for e in _events(str(tmp_path))]
    assert evs == ["run_completed", "commit_refused"]
    assert m.snapshot_compact().get("fed.fenced_commits", 0) == 1


def test_worker_refuses_commit_after_fence(tmp_path, capsys):
    """Worker-side half of the fence: ``_run_assignment`` re-checks
    the fence at the commit boundary and declines — no result files,
    a ``refused`` protocol line instead."""
    from sctools_tpu.federation import _run_assignment

    tdir = tmp_path / "t"
    tdir.mkdir()
    from sctools_tpu.utils.checkpoint import save_celldata

    save_celldata(_data(), str(tdir / "data.npz"))
    (tdir / "ticket.json").write_text(json.dumps(
        {"ticket": "t000000", "tenant": "x", "backend": "tpu",
         "steps": [["normalize.log1p", "tpu", {}]], "runner_kw": {}}))

    class _Handle:
        def result(self):
            return _data()

    class _Sched:
        def submit(self, *a, **kw):
            return _Handle()

    _run_assignment(_Sched(), {"ticket": "t000000", "epoch": 0,
                               "dir": str(tdir)},
                    str(tmp_path), fenced=lambda: True)
    err = capsys.readouterr().err
    assert "[fed] refused ticket=t000000 epoch=0" in err
    assert not os.path.exists(str(tdir / "result-000.json"))
    assert not os.path.exists(str(tdir / "result-000.npz"))


def test_submit_admission_funnel(tmp_path):
    """Federation-tier admission: tenant queue quota and reject_storm
    refuse at the door with the journal trail of the in-process
    scheduler."""
    monkey = ChaosMonkey([Fault("stormy", "reject_storm", times=1)])
    sup = FederationSupervisor(str(tmp_path), n_workers=1,
                               tenant_max_queued=2, chaos=monkey)
    sup._started = True  # admission only: never spawn real workers
    d = _data()
    with pytest.raises(RunRejected, match="reject_storm"):
        sup.submit(_pipe(), d, tenant="stormy")
    sup.submit(_pipe(), d, tenant="lab")
    sup.submit(_pipe(), d, tenant="lab")
    with pytest.raises(RunRejected, match="tenant_queue_quota"):
        sup.submit(_pipe(), d, tenant="lab")
    evs = [e["event"] for e in _events(str(tmp_path))]
    assert evs.count("rejected") == 2
    assert evs.count("admitted") == 2


def test_high_water_sheds_lowest_priority(tmp_path):
    sup = FederationSupervisor(str(tmp_path), n_workers=1,
                               queue_high_water=2,
                               tenant_max_queued=10)
    sup._started = True
    d = _data()
    h_low = sup.submit(_pipe(), d, tenant="a", priority=0)
    sup.submit(_pipe(), d, tenant="b", priority=1)
    sup.submit(_pipe(), d, tenant="c", priority=2)  # sheds h_low
    assert h_low.status == "shed"
    with pytest.raises(RunShed):
        h_low.result(timeout=0)
    with pytest.raises(RunRejected, match="queue_full"):
        sup.submit(_pipe(), d, tenant="d", priority=0)


# --------------------------------------------------- subprocess acceptance

def test_federation_chaos_soak_kill_and_wedge(tmp_path):
    """THE acceptance soak: two supervised workers, one SIGKILLed by
    chaos at its 3rd heartbeat, the other wedged (heartbeats
    withheld — the split-brain partition).  Every submission is
    terminal in exactly one journaled state, the killed/wedged
    workers' in-flight runs are requeued and complete, the fenced
    worker never double-commits, and every lease schedule ran on the
    VirtualClock (the test never sleeps; it waits on event-driven
    handles)."""
    clk = VirtualClock()
    m = MetricsRegistry(clock=clk)
    monkey = ChaosMonkey([Fault("w0", "kill_worker", on_call=3),
                          Fault("w1", "lease_wedge", on_call=3)])
    d = _data()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with FederationSupervisor(
                str(tmp_path), n_workers=2, heartbeat_s=0.1,
                poll_s=0.05, lease_timeout_s=30.0, clock=clk,
                metrics=m, chaos=monkey, max_respawns=1,
                tenant_max_queued=16,
                runner_config={"assume_healthy": True}) as sup:
            handles = [sup.submit(_pipe(), d, tenant=f"t{i % 3}")
                       for i in range(8)]
            # the wedge fires on a real heartbeat; wait for THAT
            # event, then expire the wedged lease on the virtual
            # clock — the live workers' next beats re-stamp
            # themselves and run the supervision check
            assert sup.wedge_observed.wait(timeout=90), \
                "lease_wedge never fired"
            clk.advance(31.0)
            for h in handles:
                out = h.result(timeout=180)
                assert out.X is not None
                assert h.status == "completed"
    evs = _events(str(tmp_path))
    names = [e["event"] for e in evs]
    # both loss modes ran the full ladder
    lost = [e for e in evs if e["event"] == "worker_lost"]
    reasons = {e["reason"] for e in lost}
    assert "exited" in reasons, names  # the SIGKILL reap
    assert "lease_expired" in reasons, names  # the wedge ruling
    assert all(e["classified"] == "process_lost" for e in lost)
    assert any(e.get("journal_tail") for e in lost), \
        "worker_lost must graft the dead worker's journal tail"
    assert "worker_respawned" in names
    # zero lost tickets: every submission terminal exactly once
    check_journal_coherent(os.path.join(str(tmp_path),
                                        "journal.jsonl"), 8)
    # requeues happened and were charged to the metric
    compact = m.snapshot_compact()
    assert compact.get("fed.requeues", 0) >= 1
    assert compact.get(
        "fed.workers_lost{reason=lease_expired}", 0) == 1
    # the fenced (wedged) worker never had a commit ACCEPTED after
    # its fence: every accepted terminal is the ticket's current
    # epoch (commit_refused events are allowed, acceptance is not)
    done = [e for e in evs if e["event"] == "run_completed"]
    assert len(done) == 8
    # acceptance is epoch-guarded: every accepted terminal's epoch is
    # the LAST epoch the supervisor journaled for that ticket (a
    # fenced worker's stale-epoch commit can never be the accepted
    # one).  NB the fence FILE is cleared again when the incarnation
    # respawns — the journal, not the file, is the durable evidence.
    last_epoch: dict = {}
    for e in evs:
        if e["event"] in ("assigned", "requeued"):
            last_epoch[e["ticket"]] = e["epoch"]
    for e in done:
        assert e["epoch"] == last_epoch[e["ticket"]], e


def test_lost_done_line_recovers_from_result_file(tmp_path):
    """The lost-doorbell regression (caught by the chaos soak): a
    worker commits its result by atomic rename but the stderr
    ``done`` line never reaches the supervisor — previously the
    ticket sat in_flight forever on a HEALTHY worker (no lease ever
    expires, nothing requeues).  The supervision tick now probes the
    result file of every in-flight ticket's current epoch: the
    rename is the durable record, the line only the doorbell.  The
    ``SCT_FED_TEST_MUTE_DONE`` hook drops every done line
    worker-side while the worker keeps beating and committing."""
    m = MetricsRegistry()
    env = dict(os.environ, SCT_FED_TEST_MUTE_DONE="1")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with FederationSupervisor(
                str(tmp_path), n_workers=1, heartbeat_s=0.1,
                poll_s=0.05, lease_timeout_s=120.0, metrics=m,
                env=env,
                runner_config={"assume_healthy": True}) as sup:
            handles = [sup.submit(_pipe(), _data(), tenant="lab")
                       for _ in range(2)]
            for h in handles:
                out = h.result(timeout=180)
                assert out.X is not None
                assert h.status == "completed"
    evs = _events(str(tmp_path))
    done = [e for e in evs if e["event"] == "run_completed"]
    assert len(done) == 2
    # every acceptance came through the recovery path, on the record
    assert all(e.get("recovered") for e in done), done
    assert m.snapshot_compact().get("fed.recovered_commits", 0) == 2
    # no worker was lost and nothing requeued: the worker stayed
    # healthy the whole time — recovery is not the lost-worker ladder
    names = [e["event"] for e in evs]
    assert "worker_lost" not in names and "requeued" not in names
    check_journal_coherent(os.path.join(str(tmp_path),
                                        "journal.jsonl"), 2)


def test_crash_requeue_resumes_bitwise_identical(tmp_path):
    """The at-most-once contract: a ticket SIGKILLed mid-fused-stage
    (in-worker chaos ``kill`` inside the second fused stage) is
    requeued onto the respawned worker, RESUMES from the checkpoint
    fingerprint (journal proves resume, not replay) and produces
    bitwise-identical results to an uninterrupted run."""
    d = _data(96, 48, seed=3)
    pipe = Pipeline([
        ("normalize.library_size", {}),
        ("normalize.log1p", {}),
        ("qc.per_cell_metrics", {}),
        ("qc.filter_cells", {"min_counts": 1.0}),  # fusion break
        ("hvg.select", {"n_top": 16, "flavor": "dispersion"}),
        ("normalize.scale", {"max_value": 10.0}),
    ], backend="tpu")
    kill_spec = ChaosMonkey(
        [Fault("hvg.select", "kill", on_call=1)]).spec()

    def run(fed, specs):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            with FederationSupervisor(
                    str(fed), n_workers=1, heartbeat_s=0.1,
                    poll_s=0.05, lease_timeout_s=120.0,
                    max_respawns=1, chaos_specs=specs,
                    runner_config={"assume_healthy": True,
                                   "fuse": True}) as sup:
                h = sup.submit(pipe, d, tenant="lab")
                return h.result(timeout=240), h

    out_kill, h_kill = run(tmp_path / "a", {"w0": kill_spec})
    out_clean, _ = run(tmp_path / "b", {})

    evs = _events(str(tmp_path / "a"))
    names = [e["event"] for e in evs]
    assert "worker_lost" in names and "requeued" in names
    assert h_kill.epoch == 1  # completed by the requeued epoch
    # RESUME, not replay: the respawned worker's runner resumed from
    # the fingerprinted checkpoint the dead worker left behind
    ckpt_journal = os.path.join(str(tmp_path / "a"), "tickets",
                                "t000000", "ckpt", "journal.jsonl")
    with open(ckpt_journal) as f:
        run_evs = [json.loads(line) for line in f]
    resumes = [e for e in run_evs if e["event"] == "resume"]
    assert resumes, "the requeued run must resume from checkpoints"
    assert resumes[-1]["from_step"] >= 0
    # bitwise-identical to the uninterrupted run
    assert np.array_equal(np.asarray(out_kill.X),
                          np.asarray(out_clean.X))


def test_ticket_dir_placeholder_substitution():
    from sctools_tpu.federation import _subst_ticket_dir

    params = {"checkpoint": "{ticket_dir}/cursor.npz",
              "journal": "{ticket_dir}/tj.jsonl",
              "store_dir": "/data/store", "epochs": 3,
              "note": "no placeholder here"}
    out = _subst_ticket_dir(params, "/fed/tickets/t000001")
    assert out["checkpoint"] == "/fed/tickets/t000001/cursor.npz"
    assert out["journal"] == "/fed/tickets/t000001/tj.jsonl"
    assert out["store_dir"] == "/data/store"      # untouched
    assert out["epochs"] == 3                     # non-strings too
    assert out["note"] == "no placeholder here"


def test_training_ticket_resumes_from_cursor_via_ticket_dir(tmp_path):
    """The requeued-training-ticket contract, end to end through a
    REAL worker: a training cursor left mid-epoch in the ticket dir
    (here by a preempted direct run — a requeue reuses the SAME dir,
    so the seeding path is identical to what a lost worker leaves
    behind) is found by the worker through the ``{ticket_dir}``
    placeholder, resumed (journaled ``train_resume`` at the exact
    cursor), and finished to the uninterrupted run's loss history
    bitwise."""
    from sctools_tpu.data.shardstore import write_store
    from sctools_tpu.models.train_stream import fit_scvi_stream
    from sctools_tpu.utils.failsafe import JobPreempted, PreemptToken

    hyper = dict(n_latent=4, n_hidden=16, epochs=2, batch_size=128,
                 seed=0)
    ds = synthetic_counts(1024, 64, density=0.2, n_clusters=3, seed=0)
    store = write_store(ds.X, str(tmp_path / "store"),
                        shard_rows=256, chunk_rows=64)
    ref = fit_scvi_stream(store, **hyper)

    # phase A: yield a mid-epoch cursor into the (deterministic)
    # first ticket's directory — exactly what a worker lost at pos 2
    # would leave behind for the requeued epoch
    fed = tmp_path / "fed"
    tdir = fed / "tickets" / "t000000"
    os.makedirs(tdir)
    polls = [0]

    def probe():
        polls[0] += 1
        return "preempt" if polls[0] == 2 else None

    with pytest.raises(JobPreempted):
        fit_scvi_stream(store, checkpoint=str(tdir / "cursor.npz"),
                        preempt=PreemptToken(probe=probe), **hyper)
    assert os.path.exists(tdir / "cursor.npz")

    # phase B: a REAL worker picks the ticket up, substitutes the
    # placeholder, and RESUMES instead of restarting the epoch
    pipe = Pipeline([("model.scvi_stream",
                      dict(store_dir=store.directory,
                           checkpoint="{ticket_dir}/cursor.npz",
                           journal="{ticket_dir}/tj.jsonl",
                           **hyper))], backend="cpu")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with FederationSupervisor(
                str(fed), n_workers=1, heartbeat_s=0.1, poll_s=0.05,
                lease_timeout_s=240.0,
                runner_config={"assume_healthy": True}) as sup:
            h = sup.submit(pipe, _data(8, 8, seed=1), tenant="lab")
            out = h.result(timeout=300)
    hist = np.asarray(out.uns["scvi_stream_elbo_history"])
    assert np.array_equal(hist, np.asarray(ref["history"]))
    tj = [json.loads(line) for line in open(tdir / "tj.jsonl")]
    kinds = [e["event"] for e in tj]
    assert "train_resume" in kinds, kinds
    res = next(e for e in tj if e["event"] == "train_resume")
    assert (res["epoch"], res["pos"]) == (0, 2)
    pairs = [(e["epoch"], e["pos"]) for e in tj
             if e["event"] == "train_shard"]
    assert len(pairs) == len(set(pairs))  # no replayed shards


def test_breaker_trip_on_worker_a_short_circuits_worker_b(tmp_path):
    """Federated admission to the accelerator: worker A's chaos trips
    the shared tpu breaker; worker B — a DIFFERENT PROCESS — starts
    its next run already degraded (journal ``fallback
    reason=breaker_open short_circuit=true``, zero fresh tpu
    attempts).  The cross-process transport is what carries it."""
    d = _data()
    storm = ChaosMonkey(
        [Fault("normalize.log1p", "unavailable", times=-1,
               backend="tpu")]).spec()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with FederationSupervisor(
                str(tmp_path), n_workers=2, heartbeat_s=0.1,
                poll_s=0.05, lease_timeout_s=120.0,
                chaos_specs={"w0": storm},
                breaker_defaults={"failure_threshold": 2,
                                  "cooldown_s": 600.0},
                runner_config={
                    "assume_healthy": True,
                    "policy": {"max_attempts": 2,
                               "base_delay_s": 0.01,
                               "max_delay_s": 0.02}}) as sup:
            # phase 1: one ticket on w0 trips the breaker (2 failing
            # attempts reach the threshold), completes degraded
            h0 = sup.submit(_pipe(), d, tenant="lab")
            h0.result(timeout=180)
            bpath = os.path.join(str(tmp_path), "breakers",
                                 "tpu.json")
            with open(bpath) as f:
                assert json.load(f)["state"] == "open"
            # phase 2: more tickets — both workers' runs now start
            # under the remotely-opened breaker
            hs = [sup.submit(_pipe(), d, tenant="lab")
                  for _ in range(4)]
            for h in hs:
                h.result(timeout=180)
            servers = {h.worker for h in hs}
            assert "w1" in servers, servers  # B really served some
            b_tickets = [h.ticket for h in hs if h.worker == "w1"]
    # a w1-served run's OWN journal (the ticket's checkpoint dir)
    # proves the pre-attempt short circuit in worker B's process
    for tid in b_tickets:
        with open(os.path.join(str(tmp_path), "tickets", tid,
                               "ckpt", "journal.jsonl")) as f:
            run_evs = [json.loads(line) for line in f]
        sc = [e for e in run_evs if e["event"] == "fallback"
              and e.get("reason") == "breaker_open"
              and e.get("short_circuit")]
        assert sc, (tid, [e["event"] for e in run_evs])
        assert sc[0].get("signature") == "tpu"
        # zero fresh accelerator attempts: the remote trip ruled the
        # run degraded BEFORE it touched the backend
        tpu_attempts = [e for e in run_evs if e["event"] == "attempt"
                        and e.get("backend") == "tpu"]
        assert not tpu_attempts, (tid, tpu_attempts)


def test_worker_main_exits_fenced(tmp_path):
    """A worker that starts under an existing fence stands down
    immediately (exit code 3) without serving anything."""
    fed = tmp_path / "fed"
    wdir = fed / "workers" / "w9"
    (wdir / "inbox").mkdir(parents=True)
    (fed / "config.json").write_text(json.dumps(
        {"heartbeat_s": 0.1, "poll_s": 0.05}))
    (wdir / "fence.json").write_text(json.dumps({"reason": "test"}))
    assert worker_main(str(fed), "w9", gen=0) == 3


def test_shutdown_sheds_undispatched(tmp_path):
    sup = FederationSupervisor(str(tmp_path), n_workers=1,
                               tenant_max_queued=10)
    sup._started = True  # no workers: nothing can dispatch
    h = sup.submit(_pipe(), _data(), tenant="lab")
    sup.shutdown(wait=True, timeout=5)
    assert h.status == "shed"
    assert h.reason == "shutdown"
    with pytest.raises(RunShed):
        h.result(timeout=0)
